"""SLO engine + time-series ring + trace replay + capacity model.

Tier-1 coverage of the observability-derived capacity layer (ISSUE 11):

- the seeded arrival process produces a BIT-IDENTICAL request schedule
  for a fixed seed (the determinism contract the committed
  ``CAPACITY_rNN.json`` artifacts rest on);
- burn-rate window math is exact: a synthetic ring with hand-placed
  timestamps yields the analytically-known burn rates, and multi-window
  status requires BOTH the long and the short window to burn hot;
- the time-series ring is bounded (eviction counted), reset-aware, and
  its windowed percentile sees ONLY the window's observations;
- a gate-deterministic SLO-violation path: typed deadline expiries
  against a real ModelServer drive availability below target ->
  BREACH status, published on the ``mxtpu_slo_status`` gauge;
- tenant attribution reaches the per-tenant series from both submit
  and outcome paths;
- the capacity model's chips-per-M-users algebra is exact on synthetic
  rates, and ``perf_capture.emit_capacity_snapshot`` honors the
  stale/skip refusal contract (an unhealthy replay commits an artifact
  with ``value: null`` + a ``skipped`` marker, never a headline).
"""
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mxnet_tpu.observability.registry import MetricsRegistry  # noqa: E402
from mxnet_tpu.observability.timeseries import (  # noqa: E402
    TimeSeriesRing, diff_cum_counts, percentile_from_counts)
from mxnet_tpu.observability.slo import (  # noqa: E402
    SLO, SLOEngine, STATUS_OK, STATUS_WARN, STATUS_PAGE, STATUS_BREACH)
from mxnet_tpu.observability import capacity as cap_mod  # noqa: E402


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------- seeded schedule --

def test_trace_bit_identical_for_fixed_seed():
    lr = _load_tool("load_replay")
    spec_kw = dict(seed=11, duration_s=6.0, base_rps=25.0,
                   burst_rate=0.3, burst_mult=4.0, tenants=5,
                   tenant_skew=1.4)
    t1 = lr.generate_trace(lr.TraceSpec(**spec_kw))
    t2 = lr.generate_trace(lr.TraceSpec(**spec_kw))
    assert t1 == t2                       # bit-identical, field by field
    assert lr.schedule_digest(t1) == lr.schedule_digest(t2)
    t3 = lr.generate_trace(lr.TraceSpec(**dict(spec_kw, seed=12)))
    assert lr.schedule_digest(t1) != lr.schedule_digest(t3)
    assert len(t1) > 50                   # ~150 expected at 25rps x 6s


def test_trace_shape_and_skew():
    lr = _load_tool("load_replay")
    trace = lr.generate_trace(lr.TraceSpec(
        seed=2, duration_s=8.0, base_rps=40.0, tenants=4,
        tenant_skew=1.5, prompt_min=2, prompt_max=64, out_min=1,
        out_max=32))
    ats = [r["at_us"] for r in trace]
    assert ats == sorted(ats)             # arrivals are a time series
    assert all(0 <= a < 8_000_000 for a in ats)
    assert all(2 <= r["prompt_len"] <= 64 for r in trace)
    assert all(1 <= r["new_tokens"] <= 32 for r in trace)
    by_tenant = {}
    for r in trace:
        by_tenant[r["tenant"]] = by_tenant.get(r["tenant"], 0) + 1
    # Zipf skew: the head tenant dominates every other tenant
    head = by_tenant.get("t00", 0)
    assert head == max(by_tenant.values())
    assert head > len(trace) / 4          # > uniform share (1/4)
    # heavy tail: medians sit well below the max (most requests short)
    lens = sorted(r["prompt_len"] for r in trace)
    assert lens[len(lens) // 2] <= 16


def test_prompt_tokens_deterministic_and_in_vocab():
    lr = _load_tool("load_replay")
    spec = lr.TraceSpec(seed=5, duration_s=2.0, base_rps=20.0)
    trace = lr.generate_trace(spec)
    req = trace[0]
    a = lr.prompt_tokens(spec, req, vocab=32)
    b = lr.prompt_tokens(spec, req, vocab=32)
    assert a == b and len(a) == req["prompt_len"]
    assert all(0 <= t < 32 for t in a)


# --------------------------------------------------- ring bounds ----

def _mini_registry():
    reg = MetricsRegistry()
    served = reg.counter("mxtpu_serving_requests_completed_total", "",
                         ("server",)).labels(server="u")
    shed = reg.counter("mxtpu_serving_shed_total", "",
                       ("server", "reason")).labels(server="u",
                                                    reason="queue_full")
    reg.counter("mxtpu_serving_deadline_expired_total", "",
                ("server",)).labels(server="u")
    hist = reg.histogram("mxtpu_serving_latency_seconds", "",
                         ("server",)).labels(server="u")
    return reg, served, shed, hist


def test_ring_bounded_and_eviction_counted():
    reg, served, _, _ = _mini_registry()
    ring = TimeSeriesRing(reg, capacity=8)
    for i in range(20):
        served.inc()
        ring.record(now=float(i))
    assert len(ring) == 8
    recs = ring.records()
    assert recs[0]["ts"] == 12.0 and recs[-1]["ts"] == 19.0
    assert reg.get("mxtpu_ts_snapshots_total").value == 20
    assert reg.get("mxtpu_ts_snapshots_dropped_total").value == 12
    assert reg.get("mxtpu_ts_ring_size").value == 8


def test_ring_rate_window_and_reset():
    reg, served, _, _ = _mini_registry()
    ring = TimeSeriesRing(reg, capacity=32)
    lbl = {"server": "u"}
    name = "mxtpu_serving_requests_completed_total"
    for i in range(10):
        served.inc(5)                      # 5/s at 1s cadence
        ring.record(now=100.0 + i)
    assert ring.rate(name, lbl) == pytest.approx(5.0)
    assert ring.rate(name, lbl, window_s=3.0) == pytest.approx(5.0)
    assert ring.delta(name, lbl, window_s=3.0) == pytest.approx(15.0)
    # reset-awareness: a restarted process restarts the counter
    served.reset()
    served.inc(2)
    ring.record(now=111.0)
    assert ring.delta(name, lbl, window_s=2.0) == pytest.approx(2.0)
    # too-narrow window (single snapshot) -> no answer, not garbage
    assert ring.rate(name, lbl, window_s=0.1) is None


def test_ring_windowed_percentile_sees_only_window():
    reg, _, _, hist = _mini_registry()
    ring = TimeSeriesRing(reg, capacity=16)
    name = "mxtpu_serving_latency_seconds"
    lbl = {"server": "u"}
    for _ in range(1000):
        hist.observe(0.001)                # ancient fast history
    ring.record(now=0.0)
    for _ in range(10):
        hist.observe(0.5)                  # fresh regression
    ring.record(now=10.0)
    # cumulative view drowns the regression; the window sees it
    assert hist.percentile(50) < 0.01
    win_p50 = ring.percentile_over(name, 50, lbl, window_s=60.0)
    assert win_p50 > 0.25
    # empty window -> None
    hist_only = ring.percentile_over(name, 50, lbl, window_s=0.0)
    assert hist_only is None


def test_counts_helpers_exact():
    assert diff_cum_counts([1, 3, 5], [2, 6, 9]) == [1, 3, 4]
    # reset: now < then -> take now wholesale
    assert diff_cum_counts([5, 9, 12], [1, 2, 3]) == [1, 2, 3]
    edges = (0.1, 0.2, 0.4)
    # 10 obs in (0.1, 0.2]: p50 interpolates to the bucket midpoint
    assert percentile_from_counts(edges, [0, 10, 10, 10], 50) == \
        pytest.approx(0.15)
    assert percentile_from_counts(edges, [0, 0, 0, 0], 50) is None
    # overflow bucket clamps to the top edge
    assert percentile_from_counts(edges, [0, 0, 0, 10], 99) == \
        pytest.approx(0.4)


# ----------------------------------------------- burn-rate math ----

def _burn_fixture(target=0.99):
    """10 snapshots at 1s cadence: 9 clean seconds of 10 good/s, then
    one second with 10 good + 10 shed -> last-1s error rate 0.5."""
    reg, served, shed, _ = _mini_registry()
    ring = TimeSeriesRing(reg, capacity=32)
    t = 0.0
    ring.record(now=t)
    for i in range(9):
        t += 1.0
        served.inc(10)
        ring.record(now=t)
    t += 1.0
    served.inc(10)
    shed.inc(10)
    ring.record(now=t)
    slo = SLO.serving_availability("avail_u", "u", target=target)
    return reg, ring, slo


def test_burn_rate_window_math_exact():
    reg, ring, slo = _burn_fixture(target=0.99)
    # last 1s: 10 good, 10 bad -> err 0.5 -> burn 0.5/0.01 = 50
    assert slo.burn(ring, 1.0) == pytest.approx(50.0)
    # last 5s: 50 good, 10 bad -> err 1/6 -> burn 100/6
    assert slo.burn(ring, 5.0) == pytest.approx((10 / 60) / 0.01)
    # full span: 100 good, 10 bad -> err 1/11
    assert slo.burn(ring, 10.0) == pytest.approx((10 / 110) / 0.01)
    # an idle window burns nothing (None, not zero-division garbage)
    reg2, served2, _, _ = _mini_registry()
    ring2 = TimeSeriesRing(reg2, capacity=8)
    ring2.record(now=0.0)
    ring2.record(now=1.0)
    slo2 = SLO.serving_availability("avail_idle", "u")
    assert slo2.burn(ring2, 1.0) is None


def test_multiwindow_status_requires_both_windows():
    # long window hot + short window hot -> PAGE
    reg, ring, slo = _burn_fixture(target=0.99)
    eng = SLOEngine([slo], ring, registry=reg,
                    windows=[(5.0, 1.0, 14.4, STATUS_PAGE)])
    rep = eng.evaluate()["avail_u"]
    # attainment 100/110 = 0.909 < 0.99: BREACH outranks PAGE
    assert rep["status"] == STATUS_BREACH
    # same burn shape but a lenient target that is still attained:
    # burn windows decide alone
    reg2, served2, shed2, _ = _mini_registry()
    ring2 = TimeSeriesRing(reg2, capacity=32)
    t = 0.0
    ring2.record(now=t)
    for i in range(9):
        t += 1.0
        served2.inc(100)
        ring2.record(now=t)
    t += 1.0
    served2.inc(100)
    shed2.inc(10)                       # lifetime err 10/1010 < 0.05
    ring2.record(now=t)
    slo2 = SLO.serving_availability("avail_w", "u", target=0.95)
    # short window err 10/110 -> burn ~1.8; long 5s err 10/510 -> ~0.39
    eng2 = SLOEngine([slo2], ring2, registry=reg2,
                     windows=[(5.0, 1.0, 1.0, STATUS_PAGE)])
    rep2 = eng2.evaluate()["avail_w"]
    # long window under threshold -> NOT paging even though the short
    # window burns hot (the multi-window AND)
    assert rep2["status"] == STATUS_OK
    eng3 = SLOEngine([slo2], ring2, registry=reg2,
                     windows=[(1.5, 1.0, 1.0, STATUS_PAGE)])
    rep3 = eng3.evaluate()["avail_w"]
    assert rep3["status"] == STATUS_PAGE
    assert rep3["burn_rates"]["1s"] == pytest.approx(
        (10 / 110) / 0.05)


def test_latency_slo_threshold_above_top_edge_counts_overflow_good():
    """A bound at/above the histogram's top finite edge includes the
    +Inf overflow bucket — slow-but-within-bound requests must not
    read as violations (spurious breach)."""
    reg, _, _, hist = _mini_registry()
    ring = TimeSeriesRing(reg, capacity=8)
    for _ in range(5):
        hist.observe(40.0)        # beyond the 30s top _LATENCY edge
    ring.record(now=0.0)
    slo = SLO.latency("lat_top", threshold_ms=60_000.0, target=0.9,
                      labels={"server": "u"})
    good, total = slo.good_total(ring.latest()["metrics"])
    assert (good, total) == (5.0, 5.0)
    eng = SLOEngine([slo], ring, registry=reg, windows=[])
    assert eng.evaluate()["lat_top"]["status"] == STATUS_OK


def test_burn_gauge_clears_when_window_goes_idle():
    """A hot burn gauge must return to 0 once the window empties —
    otherwise dashboards read a live page condition forever."""
    reg, ring, slo = _burn_fixture(target=0.99)
    eng = SLOEngine([slo], ring, registry=reg,
                    windows=[(5.0, 1.0, 14.4, STATUS_PAGE)])
    eng.evaluate()
    gauge = reg.get("mxtpu_slo_burn_rate")
    assert gauge.labels(slo="avail_u", window="1s").value > 10
    # traffic stops: two idle snapshots beyond every window
    ring.record(now=100.0)
    ring.record(now=101.0)
    rep = eng.evaluate()["avail_u"]
    assert rep["burn_rates"]["1s"] is None          # honest None
    assert gauge.labels(slo="avail_u", window="1s").value == 0.0


def test_metrics_dump_delta_survives_bucket_relayout():
    md = _load_tool("metrics_dump")
    ra = MetricsRegistry()
    ra.histogram("mxtpu_serving_latency_seconds", "", ("server",),
                 buckets=(0.1, 0.2)).labels(server="u").observe(0.15)
    snap_a = {"ts": 0.0, "metrics": ra.snapshot()}
    rb = MetricsRegistry()
    rb.histogram("mxtpu_serving_latency_seconds", "", ("server",),
                 buckets=(0.1, 0.2, 0.4)).labels(server="u").observe(0.3)
    snap_b = {"ts": 1.0, "metrics": rb.snapshot()}
    out = md.render_delta(snap_a, snap_b)   # must not raise
    assert "bucket layout changed" in out


def test_latency_slo_good_total_and_threshold_snap():
    reg, _, _, hist = _mini_registry()
    ring = TimeSeriesRing(reg, capacity=8)
    for _ in range(90):
        hist.observe(0.004)
    for _ in range(10):
        hist.observe(0.2)
    ring.record(now=0.0)
    slo = SLO.latency("lat_u", threshold_ms=5.0, target=0.95,
                      labels={"server": "u"})
    good, total = slo.good_total(ring.latest()["metrics"])
    assert (good, total) == (90.0, 100.0)
    # 5ms is a real edge of DEFAULT_TIME_BUCKETS -> snaps to itself
    assert slo.effective_threshold_s == pytest.approx(0.005)
    eng = SLOEngine([slo], ring, registry=reg, windows=[])
    rep = eng.evaluate()["lat_u"]
    assert rep["attainment"] == pytest.approx(0.9)
    assert rep["status"] == STATUS_BREACH


# ------------------------------- deterministic breach, end to end ----

def test_slo_breach_path_from_typed_deadline_sheds():
    """Gate-deterministic: expired-at-submit deadlines (deadline_ms=0
    fails fast, no timing race) drive availability below target; the
    engine reports BREACH and publishes it on mxtpu_slo_status."""
    from mxnet_tpu import serving
    from mxnet_tpu.observability import get_registry
    from mxnet_tpu.serving import DeadlineExceededError

    srv = serving.ModelServer(lambda b: b * 2.0, buckets=[1, 2],
                              max_delay_ms=0.5, item_shape=(3,),
                              dtype="float32",
                              name="slo_breach_t").start()
    srv.warmup()
    served = [srv.submit(np.zeros(3, np.float32)) for _ in range(2)]
    for f in served:
        f.result(timeout=60)
    expired = 0
    for _ in range(8):
        with pytest.raises(DeadlineExceededError):
            srv.submit(np.zeros(3, np.float32), deadline_ms=0,
                       tenant="bad_tenant")
        expired += 1
    srv.shutdown()

    label = srv._stats.server_label
    reg = get_registry()
    ring = TimeSeriesRing(reg, capacity=8)
    ring.record(now=0.0)
    slo = SLO.serving_availability("breach_avail", label, target=0.99)
    eng = SLOEngine([slo], ring, registry=reg, windows=[])
    rep = eng.evaluate()["breach_avail"]
    assert rep["good"] == 2 and rep["total"] == 2 + expired
    assert rep["attainment"] == pytest.approx(2 / (2 + expired))
    assert rep["status"] == STATUS_BREACH
    assert rep["status_name"] == "breach"
    gauge = reg.get("mxtpu_slo_status")
    assert gauge.labels(slo="breach_avail").value == STATUS_BREACH
    # the typed sheds are tenant-attributed too (expired at submit)
    tcounter = reg.get("mxtpu_serving_tenant_requests_total")
    assert tcounter.labels(server=label, tenant="bad_tenant",
                           outcome="expired").value == expired


def test_tenant_attribution_served_path():
    from mxnet_tpu import serving
    from mxnet_tpu.observability import get_registry
    srv = serving.ModelServer(lambda b: b + 1.0, buckets=[1, 2, 4],
                              max_delay_ms=0.5, item_shape=(2,),
                              dtype="float32",
                              name="tenant_t").start()
    srv.warmup()
    futs = [srv.submit(np.zeros(2, np.float32),
                       tenant=f"t{i % 2}") for i in range(6)]
    for f in futs:
        f.result(timeout=60)
    snap = srv._stats.snapshot()
    srv.shutdown()
    assert snap["tenants"]["t0"] == {"submitted": 3, "served": 3}
    assert snap["tenants"]["t1"] == {"submitted": 3, "served": 3}
    # untagged submits create no series: exactly the two tenants above
    label = srv._stats.server_label
    reg = get_registry()
    tcounter = reg.get("mxtpu_serving_tenant_requests_total")
    tenants = {c.labels_dict["tenant"] for c in tcounter.children()
               if c.labels_dict.get("server") == label}
    assert tenants == {"t0", "t1"}


# --------------------------------------------------- capacity model --

def _capacity_fixture():
    reg, served, shed, hist = _mini_registry()
    ring = TimeSeriesRing(reg, capacity=16)
    ring.record(now=0.0)
    served.inc(200)                        # 20 qps over 10s
    for _ in range(200):
        hist.observe(0.004)
    ring.record(now=10.0)
    slo = SLO.latency("cap_lat", threshold_ms=25.0, target=0.99,
                      labels={"server": "u"})
    avail = SLO.serving_availability("cap_avail", "u", target=0.99)
    eng = SLOEngine([avail, slo], ring, registry=reg, windows=[])
    return reg, ring, slo, eng.evaluate()


def test_capacity_algebra_exact():
    reg, ring, slo, reports = _capacity_fixture()
    rec = cap_mod.build_report(
        ring, reports, [("serving", "u", slo)], chips=2,
        user_model={"requests_per_user_per_s": 0.01})
    assert rec["slo_attained"] is True
    blk = rec["frontends"][0]
    assert blk["served_qps"] == pytest.approx(20.0)
    assert blk["good_qps"] == pytest.approx(20.0)
    assert blk["qps_per_chip"] == pytest.approx(10.0)
    # 1e6 users x 0.01 rps / 10 qps-per-chip = 1000 chips
    assert blk["chips_per_m_users"] == pytest.approx(1000.0)
    assert rec["value"] == pytest.approx(1000.0)
    assert "skipped" not in rec


def test_capacity_empty_window_refuses_headline():
    reg, *_ = _mini_registry()
    ring = TimeSeriesRing(reg, capacity=8)    # no snapshots at all
    rec = cap_mod.build_report(ring, {}, [("serving", "u", None)])
    assert rec["value"] is None
    assert "skipped" in rec


def test_emit_capacity_snapshot_refusal_contract(tmp_path):
    pc = _load_tool("perf_capture")
    good = {
        "metric": "chips_per_m_users", "unit": "chips / 1M users",
        "value": 12.5, "slo_attained": True, "slo": {}, "chips": 1,
        "frontends": [], "user_model": {}, "window_s": 10.0,
        "snapshots": 4, "compiles_during_replay": 0,
        "_capture": {"tag": "t", "metrics_log": "",
                     "captured_at": "now"},
    }
    p1 = pc.emit_capacity_snapshot(good, out_dir=str(tmp_path))
    assert os.path.basename(p1) == "CAPACITY_r01.json"
    with open(p1) as f:
        rec1 = json.load(f)
    assert rec1["value"] == 12.5 and "skipped" not in rec1
    assert rec1["metric"] == "chips_per_m_users"
    # an unhealthy run commits the attempt but never a headline
    bad = dict(good, skipped="3 XLA recompiles during the measured "
                             "window")
    p2 = pc.emit_capacity_snapshot(bad, out_dir=str(tmp_path))
    assert os.path.basename(p2) == "CAPACITY_r02.json"   # numbering
    with open(p2) as f:
        rec2 = json.load(f)
    assert rec2["value"] is None
    assert "recompiles" in rec2["skipped"]


# ----------------------------------------------- delta render tool --

def test_metrics_dump_delta_math():
    md = _load_tool("metrics_dump")
    reg, served, _, hist = _mini_registry()
    snap_a = {"ts": 0.0, "metrics": reg.snapshot()}
    served.inc(30)
    for _ in range(10):
        hist.observe(0.08)
    snap_b = {"ts": 10.0, "metrics": reg.snapshot()}
    out = md.render_delta(snap_a, snap_b)
    assert "mxtpu_serving_requests_completed_total{server=u}" in out
    assert "(+30)" in out and "(+3/s)" in out
    assert "n+10" in out and "(1/s)" in out
    # unchanged series are omitted from a delta view
    assert "mxtpu_serving_deadline_expired_total" not in out
