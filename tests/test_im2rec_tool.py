"""tools/im2rec.py end-to-end (reference: tools/im2rec.py list+pack)."""
import os
import subprocess
import sys

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_im2rec_list_and_pack(tmp_path):
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = tmp_path / "imgs" / cls
        d.mkdir(parents=True)
        for i in range(3):
            img = (rng.rand(40, 40, 3) * 255).astype(np.uint8)
            cv2.imwrite(str(d / f"{i}.jpg"), img)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    prefix = str(tmp_path / "data")
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "im2rec.py"),
         "--list", "--recursive", prefix, str(tmp_path / "imgs")],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr
    lines = open(prefix + ".lst").read().strip().splitlines()
    assert len(lines) == 6
    labels = {line.split("\t")[1] for line in lines}
    assert labels == {"0", "1"}          # two classes -> two labels

    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "im2rec.py"),
         prefix, str(tmp_path / "imgs"), "--resize", "32"],
        capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr

    from mxnet_tpu import recordio
    rd = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    assert len(rd.keys) == 6
    hdr, img = recordio.unpack_img(rd.read_idx(rd.keys[0]))
    assert min(img.shape[:2]) == 32      # shorter edge resized
    rd.close()
