"""Top-level compatibility modules: viz, engine, attribute, name,
error (reference: python/mxnet/{visualization,engine,attribute,name,
error}.py).
"""
import contextlib
import io

import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    d = mx.sym.var("data")
    n = mx.sym.FullyConnected(d, name="fc1", num_hidden=16)
    n = mx.sym.Activation(n, act_type="relu", name="act1")
    return mx.sym.FullyConnected(n, name="fc2", num_hidden=4)


def test_print_summary_counts_params():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        total = mx.viz.print_summary(_mlp(), shape={"data": (1, 10)})
    assert total == 10 * 16 + 16 + 16 * 4 + 4
    text = buf.getvalue()
    assert "fc1" in text and "FullyConnected" in text
    assert f"Total params: {total}" in text


def test_plot_network_requires_graphviz():
    try:
        import graphviz  # noqa: F401
        has = True
    except ImportError:
        has = False
    if has:
        dot = mx.viz.plot_network(_mlp())
        assert "fc1" in dot.source
    else:
        with pytest.raises(ImportError):
            mx.viz.plot_network(_mlp())


def test_engine_bulk_scope():
    prev = mx.engine.set_bulk_size(10)
    with mx.engine.bulk(64):
        pass
    mx.engine.set_bulk_size(prev)


def test_attr_scope_and_name_manager():
    with mx.attribute.AttrScope(__lr_mult__="2.0"):
        with mx.attribute.AttrScope(ctx_group="dev1"):
            attrs = mx.attribute.get_current_attrs()
    assert attrs == {"__lr_mult__": "2.0", "ctx_group": "dev1"}
    with pytest.raises(ValueError):
        mx.attribute.AttrScope(bad=3)
    with mx.name.Prefix("s1_"):
        nm = mx.name.current()
        assert nm.get(None, "conv") == "s1_conv0"
        assert nm.get(None, "conv") == "s1_conv1"
        assert nm.get("explicit", "conv") == "s1_explicit"


def test_error_hierarchy():
    assert issubclass(mx.error.ValueError, mx.error.MXNetError)
    assert issubclass(mx.error.ValueError, ValueError)
    with pytest.raises(ValueError):
        raise mx.error.ValueError("boom")

    @mx.error.register_error("CustomErr")
    class CustomErr(mx.error.MXNetError):
        pass


def test_attr_scope_applies_to_symbols():
    """AttrScope attributes land on symbols created inside the scope
    (reference: attribute.py AttrScope consulted at symbol creation)."""
    d = mx.sym.var("data")
    with mx.attribute.AttrScope(__lr_mult__="2.0", ctx_group="dev1"):
        fc = mx.sym.FullyConnected(d, name="fca", num_hidden=4)
    assert fc.attr("__lr_mult__") == "2.0"
    assert fc.attr("ctx_group") == "dev1"
    # explicit attr= merges over the scope
    with mx.attribute.AttrScope(ctx_group="dev1"):
        fc2 = mx.sym.FullyConnected(d, name="fcb", num_hidden=4,
                                    attr={"ctx_group": "dev2"})
    assert fc2.attr("ctx_group") == "dev2"
    # outside any scope: untouched
    fc3 = mx.sym.FullyConnected(d, name="fcc", num_hidden=4)
    assert fc3.attr("ctx_group") is None
