"""Eager vs hybridized equality across the gluon layer zoo.

The CachedOp jit path (gluon/block.py) must be numerically transparent
for every layer — the property the reference pins per-layer in
tests/python/unittest/test_gluon.py; here swept uniformly.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
import mxnet_tpu.autograd as ag


def _mk(layer_fn, shape, seed=0):
    mx.random.seed(seed)
    net = layer_fn()
    net.initialize()
    x = nd.array(np.random.RandomState(seed).randn(*shape)
                 .astype(np.float32))
    return net, x


CASES = [
    ("dense", lambda: nn.Dense(8, activation="relu"), (4, 6)),
    ("dense_nobias", lambda: nn.Dense(5, use_bias=False), (3, 7)),
    ("conv2d", lambda: nn.Conv2D(6, 3, padding=1), (2, 3, 8, 8)),
    ("conv2d_nhwc", lambda: nn.Conv2D(6, 3, padding=1, layout="NHWC"),
     (2, 8, 8, 3)),
    ("conv1d", lambda: nn.Conv1D(4, 3, padding=1), (2, 3, 9)),
    ("conv2dT", lambda: nn.Conv2DTranspose(4, 2, strides=2),
     (2, 3, 5, 5)),
    ("maxpool", lambda: nn.MaxPool2D(2), (2, 3, 8, 8)),
    ("avgpool", lambda: nn.AvgPool2D(2), (2, 3, 8, 8)),
    ("gap", lambda: nn.GlobalAvgPool2D(), (2, 3, 6, 6)),
    ("batchnorm", lambda: nn.BatchNorm(), (4, 3, 5)),
    ("layernorm", lambda: nn.LayerNorm(), (4, 6)),
    ("instancenorm", lambda: nn.InstanceNorm(), (3, 4, 6)),
    ("dropout_eval", lambda: nn.Dropout(0.5), (4, 6)),
    ("embedding", lambda: nn.Embedding(20, 5), (3, 4)),
    ("leakyrelu", lambda: nn.LeakyReLU(0.1), (3, 5)),
    ("prelu", lambda: nn.PReLU(), (3, 5)),
    ("elu", lambda: nn.ELU(), (3, 5)),
    ("swish", lambda: nn.Swish(), (3, 5)),
    ("flatten", lambda: nn.Flatten(), (2, 3, 4)),
]


@pytest.mark.parametrize("name,layer_fn,shape",
                         CASES, ids=[c[0] for c in CASES])
def test_hybridize_matches_eager(name, layer_fn, shape):
    net, x = _mk(layer_fn, shape)
    with ag.pause():
        eager = net(x).asnumpy()
    net.hybridize()
    with ag.pause():
        hybrid1 = net(x).asnumpy()
        hybrid2 = net(x).asnumpy()      # second call: cached program
    np.testing.assert_allclose(hybrid1, eager, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(hybrid2, eager, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("name,layer_fn,shape",
                         [c for c in CASES if c[0] not in
                          ("dropout_eval", "embedding")],
                         ids=[c[0] for c in CASES
                              if c[0] not in ("dropout_eval",
                                              "embedding")])
def test_hybridize_gradients_match_eager(name, layer_fn, shape):
    net, x = _mk(layer_fn, shape, seed=1)
    x.attach_grad()
    with ag.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    g_eager = x.grad.asnumpy()
    net.hybridize()
    x2 = nd.array(x.asnumpy())
    x2.attach_grad()
    with ag.record():
        loss2 = (net(x2) ** 2).sum()
    loss2.backward()
    np.testing.assert_allclose(x2.grad.asnumpy(), g_eager, rtol=1e-4,
                               atol=1e-5)
