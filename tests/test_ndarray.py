"""Core NDArray tests (reference: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == np.float32
    assert np.allclose(a.asnumpy(), [[1, 2], [3, 4]])
    z = nd.zeros((3, 4), dtype="float16")
    assert z.dtype == np.float16
    o = nd.ones((2,))
    assert o.asnumpy().tolist() == [1.0, 1.0]
    f = nd.full((2, 2), 7)
    assert f.asnumpy().tolist() == [[7, 7], [7, 7]]
    r = nd.arange(0, 10, 2)
    assert r.asnumpy().tolist() == [0, 2, 4, 6, 8]


def test_float64_input_downcast():
    a = nd.array(np.random.rand(3, 3))  # float64 numpy
    assert a.dtype == np.float32


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    assert np.allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    assert np.allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    assert np.allclose((a * 2).asnumpy(), [[2, 4], [6, 8]])
    assert np.allclose((1 / a).asnumpy(), 1.0 / a.asnumpy())
    assert np.allclose((a ** 2).asnumpy(), a.asnumpy() ** 2)
    assert np.allclose((-a).asnumpy(), -a.asnumpy())
    assert np.allclose((a > 2).asnumpy(), a.asnumpy() > 2)


def test_broadcast():
    a = nd.ones((2, 1, 3))
    b = nd.ones((1, 4, 3))
    assert (a + b).shape == (2, 4, 3)


def test_inplace_ops():
    a = nd.ones((2, 2))
    a += 1
    assert np.allclose(a.asnumpy(), 2)
    a *= 3
    assert np.allclose(a.asnumpy(), 6)


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    assert a[0].shape == (3, 4)
    assert a[:, 1].shape == (2, 4)
    assert a[0, 1, 2].asscalar() == 6
    a[0] = 0
    assert np.allclose(a.asnumpy()[0], 0)
    a[:] = 5
    assert np.allclose(a.asnumpy(), 5)


def test_setitem_slice():
    a = nd.zeros((4, 4))
    a[1:3] = 1
    expected = np.zeros((4, 4))
    expected[1:3] = 1
    assert np.allclose(a.asnumpy(), expected)


def test_reshape_transpose():
    a = nd.array(np.arange(12).reshape(3, 4))
    assert a.reshape(4, 3).shape == (4, 3)
    assert a.reshape((2, 6)).shape == (2, 6)
    assert a.reshape(-1).shape == (12,)
    assert a.T.shape == (4, 3)
    assert a.transpose().shape == (4, 3)
    b = nd.ones((2, 3, 4))
    assert b.transpose((2, 0, 1)).shape == (4, 2, 3)
    assert b.swapaxes(0, 2).shape == (4, 3, 2)
    assert b.flatten().shape == (2, 12)
    assert b.expand_dims(0).shape == (1, 2, 3, 4)


def test_reductions():
    a = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert a.sum().asscalar() == 15
    assert a.sum(axis=0).asnumpy().tolist() == [3, 5, 7]
    assert a.mean().asscalar() == 2.5
    assert a.max().asscalar() == 5
    assert a.min().asscalar() == 0
    assert a.argmax(axis=1).asnumpy().tolist() == [2, 2]
    assert abs(a.norm().asscalar() - np.linalg.norm(a.asnumpy())) < 1e-5


def test_dot():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 5).astype(np.float32))
    c = nd.dot(a, b)
    assert c.shape == (3, 5)
    assert np.allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)


def test_concat_split():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrs.nd")
    d = {"w": nd.array([[1, 2]]), "b": nd.array([3.0])}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert np.allclose(loaded["w"].asnumpy(), [[1, 2]])
    # list form
    nd.save(fname, [nd.ones((2,)), nd.zeros((3,))])
    ll = nd.load(fname)
    assert isinstance(ll, list) and len(ll) == 2


def test_astype_copy():
    a = nd.ones((2, 2))
    b = a.astype("float16")
    assert b.dtype == np.float16
    c = a.copy()
    c[:] = 5
    assert np.allclose(a.asnumpy(), 1)


def test_context():
    a = nd.ones((2,), ctx=mx.cpu())
    assert a.context.device_type in ("cpu", "tpu")
    b = a.as_in_context(mx.cpu(0))
    assert np.allclose(b.asnumpy(), 1)


def test_wait_and_scalar():
    a = nd.ones((1,))
    a.wait_to_read()
    assert a.asscalar() == 1.0
    mx.waitall()


def test_generated_ops_exist():
    # codegen parity: a sample of reference op names must exist on nd
    for name in ["relu", "sigmoid", "softmax", "exp", "log", "sqrt",
                 "abs", "dot", "transpose", "sum", "mean", "topk",
                 "argsort", "one_hot", "take", "where", "clip",
                 "broadcast_add", "FullyConnected", "Convolution",
                 "Pooling", "BatchNorm", "Activation"]:
        assert hasattr(nd, name), f"nd.{name} missing"


def test_advanced_indexing():
    a = nd.array(np.arange(10, dtype=np.float32))
    idx = nd.array([1, 3, 5], dtype="int32")
    assert a[idx].asnumpy().tolist() == [1, 3, 5]
    mask = a > 6
    picked = a[mask.astype("bool")] if hasattr(mask, "astype") else None


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0]])
    assert a.argsort().asnumpy().tolist() == [[1, 2, 0]]
    assert a.sort().asnumpy().tolist() == [[1, 2, 3]]
    t = a.topk(k=2)
    assert t.asnumpy().tolist() == [[0, 2]]


def test_positional_param_mapping():
    """Positional config args must map correctly for plain, *args-based and
    variadic impl signatures (regression test for the codegen tail rule)."""
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[5.0, 6.0], [7.0, 8.0]])
    # variadic: 3rd positional is dim
    c = nd.concat(a, b, 0)
    assert c.shape == (4, 2)
    c1 = nd.concat(a, b, 1)
    assert c1.shape == (2, 4)
    # *args impl: nd.FullyConnected(x, w, b, num_hidden)
    x = nd.ones((2, 3))
    w = nd.ones((4, 3))
    bias = nd.zeros((4,))
    out = nd.FullyConnected(x, w, bias, 4)
    assert out.shape == (2, 4)
    # *args impl with string param: LeakyReLU act_type
    e = nd.LeakyReLU(nd.array([-1.0, 1.0]), "elu")
    assert abs(e.asnumpy()[0] - (np.exp(-1) - 1) * 0.25) < 1e-5
    # plain impl with required non-array param: one_hot depth
    oh = nd.one_hot(nd.array([1], dtype="int32"), 4)
    assert oh.shape == (1, 4)
    # plain impl: dot transpose flags positionally
    d = nd.dot(a, b, True)
    assert np.allclose(d.asnumpy(), a.asnumpy().T @ b.asnumpy())
