"""Fused single-dispatch trainer update: bit-exactness vs the per-param
loop, no-recompile lr scheduling, buffer donation, dispatch-count
regression, fold-the-allreduce, and the persistent compile cache."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon import Trainer
from mxnet_tpu.gluon.parameter import Parameter
from mxnet_tpu.observability import get_registry, \
    install_jax_monitoring_bridge


def _make_params(n=7, seed=0, ctx=None):
    rng = np.random.RandomState(seed)
    params = []
    for i in range(n):
        shape = (3 + (i % 5), 4)
        p = Parameter(f"p{i}", shape=shape)
        p.initialize(init=mx.initializer.Constant(0), ctx=ctx)
        p.set_data(mx.nd.NDArray(rng.randn(*shape).astype(np.float32)))
        params.append(p)
    return params


def _set_grads(params, seed):
    rng = np.random.RandomState(seed)
    for p in params:
        for g in p.list_grad():
            g[:] = mx.nd.NDArray(rng.randn(*p.shape).astype(np.float32))


def _run(monkeypatch, opt, opt_args, fused, steps=5, lr_seq=None,
         batch_seq=None, scaler=None):
    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "1" if fused else "0")
    params = _make_params()
    trainer = Trainer(params, opt, dict(opt_args))
    if scaler is not None:
        from mxnet_tpu import amp
        amp.init_trainer(trainer, loss_scaler=scaler())
    for s in range(steps):
        if lr_seq:
            trainer.set_learning_rate(lr_seq[s % len(lr_seq)])
        _set_grads(params, 100 + s)
        trainer.step(batch_seq[s % len(batch_seq)] if batch_seq else 32)
    return [p.data().asnumpy().copy() for p in params], trainer


@pytest.mark.parametrize("opt,args", [
    ("sgd", {"learning_rate": 0.05}),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 1e-3, "wd": 1e-3}),
    ("adamw", {"learning_rate": 1e-3}),
])
def test_fused_bitexact(monkeypatch, opt, args):
    """The fused dispatch must produce bit-identical weights AND
    optimizer slot state vs the per-param loop, across lr changes and
    batch-size (rescale_grad) changes — Adam's bias-correction step
    counter included."""
    lr_seq = [0.05, 0.02, 0.05, 0.01]
    batch_seq = [32, 16, 64]
    a, tr_a = _run(monkeypatch, opt, args, True, lr_seq=lr_seq,
                   batch_seq=batch_seq)
    b, tr_b = _run(monkeypatch, opt, args, False, lr_seq=lr_seq,
                   batch_seq=batch_seq)
    for i, (wa, wb) in enumerate(zip(a, b)):
        assert (wa == wb).all(), f"param {i} differs (not bit-exact)"
    assert tr_a._optimizer._index_update_count == \
        tr_b._optimizer._index_update_count
    assert tr_a._optimizer.num_update == tr_b._optimizer.num_update
    # optimizer slots (momentum / mean / var) must match bitwise too
    sa, sb = tr_a._updaters[0].states, tr_b._updaters[0].states
    assert sorted(sa) == sorted(sb)
    import jax
    for k in sa:
        for la, lb in zip(jax.tree_util.tree_leaves(sa[k]),
                          jax.tree_util.tree_leaves(sb[k])):
            assert (la.asnumpy() == lb.asnumpy()).all(), \
                f"state {k} differs"


def test_fused_bitexact_with_loss_scaler(monkeypatch):
    """LossScaler rescale enters the compiled step as a traced scalar;
    scaled runs stay bit-exact with the loop."""
    from mxnet_tpu.amp import LossScaler
    mk = lambda: LossScaler(init_scale=64.0, target_dtype="float16")  # noqa: E731
    a, _ = _run(monkeypatch, "sgd",
                {"learning_rate": 0.05, "momentum": 0.9}, True, scaler=mk)
    b, _ = _run(monkeypatch, "sgd",
                {"learning_rate": 0.05, "momentum": 0.9}, False, scaler=mk)
    for wa, wb in zip(a, b):
        assert (wa == wb).all()


def test_lr_change_does_not_recompile(monkeypatch):
    """After the first step compiles the fused program, lr / batch-size
    changes must reuse it (asserted via the jax.monitoring compile
    counter)."""
    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "1")
    install_jax_monitoring_bridge()
    reg = get_registry()
    params = _make_params(n=5, seed=3)
    trainer = Trainer(params, "adam", {"learning_rate": 1e-3})
    _set_grads(params, 0)
    trainer.step(8)  # warm-up: compiles the fused program
    compiles = reg.counter("mxtpu_xla_compile_total")
    fused = reg.counter("mxtpu_trainer_update_fused_total")
    c0, f0 = compiles.value, fused.value
    for s in range(4):
        trainer.set_learning_rate(1e-3 * (s + 1))
        _set_grads(params, s + 1)
        trainer.step(8 + 4 * s)
    assert fused.value - f0 == 4, "steps did not stay on the fused path"
    assert compiles.value - c0 == 0, \
        "lr/batch change recompiled the fused update"


def test_single_dispatch_regardless_of_param_count(monkeypatch):
    """Dispatch-count regression guard: a >=50-parameter model must
    execute exactly ONE compiled update launch per Trainer.step; the
    same model on the loop path shows one per parameter (proving the
    counter measures real launches)."""
    reg = get_registry()
    dispatch = reg.counter("mxtpu_trainer_update_dispatch_total")

    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "1")
    params = _make_params(n=55, seed=1)
    trainer = Trainer(params, "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    _set_grads(params, 0)
    trainer.step(8)  # compile
    d0 = dispatch.value
    _set_grads(params, 1)
    trainer.step(8)
    assert dispatch.value - d0 == 1, \
        f"fused step took {dispatch.value - d0} dispatches, not 1"

    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "0")
    d1 = dispatch.value
    _set_grads(params, 2)
    trainer.step(8)
    assert dispatch.value - d1 == 55


def test_donation_frees_old_buffers(monkeypatch):
    """donate_argnums on the fused step must invalidate the pre-step
    weight and slot buffers (in-place HBM update, no 2x residency)."""
    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "1")
    params = _make_params(n=6, seed=2)
    trainer = Trainer(params, "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9})
    _set_grads(params, 0)
    trainer.step(4)  # creates slots, compiles
    old_w = [p.data()._data for p in params]
    old_s = [trainer._updaters[0].states[i]._data
             for i in range(len(params))]
    _set_grads(params, 1)
    trainer.step(4)
    assert all(b.is_deleted() for b in old_w), "weight buffers not donated"
    assert all(b.is_deleted() for b in old_s), "slot buffers not donated"
    # the live buffers are the new ones and stay readable
    assert all(np.isfinite(p.data().asnumpy()).all() for p in params)


def test_fallback_paths(monkeypatch):
    """ignore_stale_grad, unfusable optimizers, and the env kill-switch
    run the per-param loop — and produce the same numbers."""
    reg = get_registry()
    fallback = reg.counter("mxtpu_trainer_update_fallback_total",
                           labelnames=("reason",))

    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "1")
    params = _make_params(n=3, seed=4)
    trainer = Trainer(params, "sgd", {"learning_rate": 0.1})
    _set_grads(params, 0)
    before = fallback.labels(reason="ignore_stale_grad").value
    trainer.step(4, ignore_stale_grad=True)
    assert fallback.labels(reason="ignore_stale_grad").value == before + 1

    # unfusable optimizer (host-state per call)
    params2 = _make_params(n=3, seed=5)
    trainer2 = Trainer(params2, "nadam", {"learning_rate": 1e-3})
    before = fallback.labels(reason="optimizer").value
    _set_grads(params2, 0)
    trainer2.step(4)
    assert fallback.labels(reason="optimizer").value == before + 1

    # kill-switch
    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "0")
    params3 = _make_params(n=3, seed=6)
    trainer3 = Trainer(params3, "sgd", {"learning_rate": 0.1})
    before = fallback.labels(reason="env_disabled").value
    _set_grads(params3, 0)
    trainer3.step(4)
    assert fallback.labels(reason="env_disabled").value == before + 1


def test_fused_fallback_sparse_grad(monkeypatch):
    """A row-sparse gradient anywhere in the set must route the whole
    step through the loop (the lazy row update is eager-only)."""
    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "1")
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    import jax.numpy as jnp
    params = _make_params(n=2, seed=7)
    trainer = Trainer(params, "sgd", {"learning_rate": 0.1})
    _set_grads(params, 0)
    w = params[0].data()
    rows = jnp.asarray([0, 2], jnp.int32)
    params[0].data()._grad = RowSparseNDArray(
        jnp.ones((2,) + w.shape[1:], jnp.float32), rows, w.shape)
    reg = get_registry()
    fallback = reg.counter("mxtpu_trainer_update_fallback_total",
                           labelnames=("reason",))
    before = fallback.labels(reason="sparse_grad").value
    trainer.step(1)
    assert fallback.labels(reason="sparse_grad").value == before + 1


def test_fold_allreduce_multictx(monkeypatch):
    """kvstore=None with per-context replicas: reduce + update must run
    as ONE dispatch, replicas end identical, and the math matches the
    reduced-gradient momentum update."""
    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "1")
    ctxs = [mx.cpu(0), mx.cpu(1)]
    params = _make_params(n=3, seed=8, ctx=ctxs)
    vals = [p.data().asnumpy().copy() for p in params]
    trainer = Trainer(params, "sgd",
                      {"learning_rate": 0.1, "momentum": 0.9},
                      kvstore=None)
    g_by_ctx = []
    for i, p in enumerate(params):
        gs = [np.random.RandomState(40 + 10 * j + i)
              .randn(*p.shape).astype(np.float32) for j in range(2)]
        for g_nd, g in zip(p.list_grad(), gs):
            g_nd[:] = mx.nd.NDArray(g)
        g_by_ctx.append(gs)
    reg = get_registry()
    dispatch = reg.counter("mxtpu_trainer_update_dispatch_total")
    trainer.step(1)  # compile step
    d0 = dispatch.value
    for i, p in enumerate(params):
        for g_nd, g in zip(p.list_grad(), g_by_ctx[i]):
            g_nd[:] = mx.nd.NDArray(g)
    trainer.step(1)
    assert dispatch.value - d0 == 1
    for i, p in enumerate(params):
        total = g_by_ctx[i][0] + g_by_ctx[i][1]
        # both steps saw the same per-ctx grads: two momentum updates
        # on the reduced gradient
        mom1 = -0.1 * total
        mom2 = 0.9 * mom1 - 0.1 * total
        want = vals[i] + mom1 + mom2
        replicas = [d.asnumpy() for d in p.list_data()]
        assert (replicas[0] == replicas[1]).all()
        np.testing.assert_allclose(replicas[0], want, rtol=2e-5,
                                   atol=2e-6)


def test_tree_allreduce_matches_sum(monkeypatch):
    """_allreduce_grads with no kvstore: every replica must hold the
    cross-context sum after the single tree-level reduce."""
    ctxs = [mx.cpu(0), mx.cpu(1), mx.cpu(2)]
    params = _make_params(n=4, seed=9, ctx=ctxs)
    trainer = Trainer(params, "sgd", {"learning_rate": 0.1},
                      kvstore=None, update_on_kvstore=False)
    grads = []
    for i, p in enumerate(params):
        gs = [np.random.RandomState(60 + 10 * j + i)
              .randn(*p.shape).astype(np.float32) for j in range(3)]
        for g_nd, g in zip(p.list_grad(), gs):
            g_nd[:] = mx.nd.NDArray(g)
        grads.append(gs)
    trainer.allreduce_grads()
    for i, p in enumerate(params):
        total = grads[i][0] + grads[i][1] + grads[i][2]
        for g_nd in p.list_grad():
            np.testing.assert_allclose(g_nd.asnumpy(), total, rtol=1e-6,
                                       atol=1e-6)


def test_fused_state_checkpoint_roundtrip(monkeypatch, tmp_path):
    """Slots written by the fused path restore bit-exactly through the
    resilience checkpoint, and training resumes on the fused path."""
    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "1")
    params = _make_params(n=4, seed=10)
    trainer = Trainer(params, "adam", {"learning_rate": 1e-3})
    for s in range(3):
        _set_grads(params, s)
        trainer.step(8)
    trainer.save_state(str(tmp_path))
    after3 = [p.data().asnumpy().copy() for p in params]
    _set_grads(params, 3)
    trainer.step(8)
    after4 = [p.data().asnumpy().copy() for p in params]

    params2 = _make_params(n=4, seed=11)
    trainer2 = Trainer(params2, "adam", {"learning_rate": 1e-3})
    trainer2.restore_state(str(tmp_path))
    for wa, p in zip(after3, params2):
        assert (wa == p.data().asnumpy()).all()
    _set_grads(params2, 3)
    trainer2.step(8)
    for wa, p in zip(after4, params2):
        assert (wa == p.data().asnumpy()).all(), \
            "resumed step diverged from the uninterrupted run"


def test_donation_does_not_break_param_copies(monkeypatch):
    """Target-network pattern: a second parameter set_data'd from a
    trained one must keep a private buffer — the donated update of the
    source must not delete the copy's storage (DQN/EMA regression)."""
    monkeypatch.setenv("MXNET_TPU_FUSED_UPDATE", "1")
    params = _make_params(n=3, seed=12)
    targets = _make_params(n=3, seed=13)
    for p, t in zip(params, targets):
        t.set_data(p.data())
    trainer = Trainer(params, "sgd", {"learning_rate": 0.1})
    snap = [t.data().asnumpy().copy() for t in targets]
    for s in range(2):
        _set_grads(params, s)
        trainer.step(4)
    for t, before in zip(targets, snap):
        assert (t.data().asnumpy() == before).all()  # alive AND unchanged


def test_enable_compile_cache(tmp_path):
    """enable_compile_cache points JAX's persistent cache at the dir and
    fresh compiles land there as cache entries."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import runtime
    prev = {f: getattr(jax.config, f)
            for f in ("jax_compilation_cache_dir",
                      "jax_persistent_cache_min_compile_time_secs",
                      "jax_persistent_cache_min_entry_size_bytes")}
    try:
        resolved = runtime.enable_compile_cache(str(tmp_path))
        assert resolved == str(tmp_path)
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
        # a shape this suite never uses elsewhere -> fresh compile
        x = jnp.ones((13, 17, 3))
        jax.jit(lambda a: (a * 2.5 + 1.0).sum(axis=1))(x).block_until_ready()
        entries = [f for f in os.listdir(str(tmp_path)) if "cache" in f]
        assert entries, "no persistent cache entries written"
    finally:
        for f, v in prev.items():
            jax.config.update(f, v)
        from jax.experimental.compilation_cache import compilation_cache
        compilation_cache.reset_cache()  # drop the tmp_path-backed cache
