"""int8 KV quantization (ISSUE 13): the explicit tolerance contract.

Quantized KV is NOT bit-exact against fp32 — so instead of silent
drift this suite pins an explicit contract:

- mechanics are exact where they can be: the quantized flat reference
  equals the fp32 reference evaluated on the dequantized pages
  bit-for-bit (dequantization is the only difference), and the Pallas
  quantized kernel (interpret mode off-TPU) tracks the quantized
  reference to float-accumulation tolerance;
- per-element quantization error is bounded by half a scale step
  (symmetric round-to-nearest, scale = max|x|/127);
- per-layer model tolerance: ``decode_flat`` logits with int8 KV stay
  within ``LOGIT_TOL`` of the fp32 run on the same inputs;
- end-to-end greedy decoding with int8 KV agrees top-1, token for
  token, with the fp32 eager oracle for the pinned seed/config;
- int8 composes with the prefix cache bit-exactly (a cached quantized
  block IS the bytes a recomputing sequence would write), and the
  whole path stays zero-recompile with clean block accounting.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import jax.numpy as jnp  # noqa: E402
from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.ops.ragged_attention import (  # noqa: E402
    ragged_flat_attention, ragged_flat_attention_reference)
from mxnet_tpu.serving.llm import (  # noqa: E402
    TinyDecoder, DecoderConfig, LLMEngine, Sequence,
    greedy_decode_reference)

VOCAB = 17
BS = 8
# small context: both int8 engines in this module share one set of
# page/program shapes, so the quantized programs compile once
CTX = 32

# the per-layer contract: max |logits_int8 - logits_fp32| for one
# decode_flat dispatch of this reference config (measured ~9e-3; the
# bound leaves ~5x headroom without ever letting real drift hide)
LOGIT_TOL = 0.05
# kernel-vs-reference tolerance: both dequantize identically, the
# only difference is online-softmax float accumulation order
KERNEL_TOL = 2e-6


@pytest.fixture(scope="module")
def model():
    return TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=16, num_layers=2, num_heads=2,
        d_ff=32, max_context=CTX))


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(seed=0)


def _quantize_pages(rng, n, bs, h, d):
    kf = rng.randn(n, bs, h, d).astype(np.float32)
    sc = np.maximum(np.abs(kf).max(-1) / 127.0, 1e-8).astype(np.float32)
    kq = np.clip(np.round(kf / sc[..., None]), -127, 127).astype(np.int8)
    return kf, kq, sc


def test_quantization_error_bounded_by_half_scale_step():
    rng = np.random.RandomState(0)
    kf, kq, sc = _quantize_pages(rng, 6, BS, 2, 8)
    deq = kq.astype(np.float32) * sc[..., None]
    err = np.abs(deq - kf)
    assert (err <= sc[..., None] * 0.5 + 1e-7).all()


def test_quant_reference_equals_dequant_oracle_bitwise():
    """The quantized reference path differs from fp32 ONLY by the
    dequantize step: feeding the fp32 reference the dequantized pages
    must reproduce it exactly."""
    rng = np.random.RandomState(1)
    _, kq, ks = _quantize_pages(rng, 9, BS, 2, 8)
    _, vq, vs = _quantize_pages(rng, 9, BS, 2, 8)
    q = rng.randn(6, 2, 8).astype(np.float32)
    bt = np.array([[3, 1, 7, 0], [2, 5, 0, 0], [4, 6, 8, 1]], np.int32)
    sid = np.array([0, 0, 1, 2, 2, 1], np.int32)
    pos = np.array([3, 9, 14, 5, 30, 2], np.int32)
    ref_q = ragged_flat_attention_reference(
        jnp.asarray(q), jnp.asarray(kq), jnp.asarray(vq),
        jnp.asarray(bt), jnp.asarray(sid), jnp.asarray(pos),
        k_scales=jnp.asarray(ks), v_scales=jnp.asarray(vs))
    kd = kq.astype(np.float32) * ks[..., None]
    vd = vq.astype(np.float32) * vs[..., None]
    ref_f = ragged_flat_attention_reference(
        jnp.asarray(q), jnp.asarray(kd), jnp.asarray(vd),
        jnp.asarray(bt), jnp.asarray(sid), jnp.asarray(pos))
    assert np.array_equal(np.asarray(ref_q), np.asarray(ref_f))


def test_quant_pallas_kernel_matches_reference():
    """The Pallas quantized-page kernel (interpret mode off-TPU) —
    same scalar-prefetched block-table indexing, dequant fused at the
    page tile — tracks the quantized gather reference within float
    accumulation tolerance, over fragmented tables."""
    rng = np.random.RandomState(2)
    _, kq, ks = _quantize_pages(rng, 11, BS, 2, 8)
    _, vq, vs = _quantize_pages(rng, 11, BS, 2, 8)
    q = rng.randn(8, 2, 8).astype(np.float32)
    bt = np.array([[9, 2, 5, 1], [7, 10, 0, 0], [3, 8, 6, 4]], np.int32)
    sid = np.array([0, 0, 0, 1, 1, 2, 2, 2], np.int32)
    pos = np.array([0, 7, 25, 8, 15, 3, 17, 31], np.int32)
    ref = ragged_flat_attention_reference(
        jnp.asarray(q), jnp.asarray(kq), jnp.asarray(vq),
        jnp.asarray(bt), jnp.asarray(sid), jnp.asarray(pos),
        k_scales=jnp.asarray(ks), v_scales=jnp.asarray(vs))
    pal = ragged_flat_attention(q, kq, vq, bt, sid, pos,
                                use_pallas=True, interpret=True,
                                k_scales=ks, v_scales=vs)
    assert float(jnp.max(jnp.abs(pal - ref))) < KERNEL_TOL


def test_quant_requires_both_scales():
    q = np.zeros((1, 2, 8), np.float32)
    kp = np.zeros((2, BS, 2, 8), np.int8)
    with pytest.raises(ValueError, match="both"):
        ragged_flat_attention(q, kp, kp, np.zeros((1, 1), np.int32),
                              np.zeros(1, np.int32),
                              np.zeros(1, np.int32),
                              k_scales=np.ones((2, BS, 2), np.float32))


def test_decode_flat_per_layer_logit_tolerance(model, params):
    """The per-layer contract: one mixed flat dispatch, fp32 pools vs
    int8 pools, same tokens — logits within LOGIT_TOL and identical
    argmax at every position."""
    rng = np.random.RandomState(3)
    L, H, D = model.num_layers, model.num_heads, model.head_dim
    N = 9
    kp = jnp.zeros((L, N, BS, H, D), jnp.float32)
    vp = jnp.zeros((L, N, BS, H, D), jnp.float32)
    kq = jnp.zeros((L, N, BS, H, D), jnp.int8)
    vq = jnp.zeros((L, N, BS, H, D), jnp.int8)
    ks = jnp.ones((L, N, BS, H), jnp.float32)
    vs = jnp.ones((L, N, BS, H), jnp.float32)
    T = 16
    toks = rng.randint(0, VOCAB, T).astype(np.int32)
    pos = np.arange(T, dtype=np.int32)
    sid = np.zeros(T, np.int32)
    valid = np.ones(T, np.int32)
    bt = np.zeros((4, 8), np.int32)
    bt[0, :2] = [3, 5]
    lf = model.decode_flat(params, toks, pos, sid, valid, kp, vp, bt)[0]
    lq = model.decode_flat(params, toks, pos, sid, valid, kq, vq, bt,
                           k_scales=ks, v_scales=vs)[0]
    diff = float(jnp.max(jnp.abs(lf - lq)))
    assert diff < LOGIT_TOL, f"int8 logit drift {diff} > {LOGIT_TOL}"
    assert np.array_equal(np.asarray(jnp.argmax(lf, -1)),
                          np.asarray(jnp.argmax(lq, -1)))


@pytest.mark.slow   # the int8 engine compiles its own quantized
# program set (~18s); the tolerance CONTRACT stays tier-1 via the
# op-level and decode_flat tests above — this pins it end to end
def test_engine_int8_greedy_top1_agreement(model, params):
    """End to end: continuous-batched greedy decoding on int8 KV
    agrees token for token with the fp32 eager oracle (pinned seed —
    any disagreement is drift past the contract, not noise)."""
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, VOCAB, size=n).tolist()
               for n in (3, 5, 8, 13, 16, 21)]
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefill_chunk=8,
                    kv_dtype="int8")
    assert eng.quantized and eng.cache.dtype.name == "int8"
    eng.warmup()
    seqs = [Sequence(p, 6) for p in prompts]
    with serving.CompileCounter() as cc:
        for s in seqs:
            eng.add(s)
        steps = 0
        while eng.has_work():
            eng.step()
            steps += 1
            assert steps < 1000
    assert cc.count == 0, f"{cc.count} recompiles on the int8 path"
    for p, s in zip(prompts, seqs):
        ref = greedy_decode_reference(model, params, p, 6)
        assert s.output_tokens() == ref, \
            f"int8 greedy diverged from fp32 oracle on prompt {p}"
    assert eng.cache.allocator.num_used == 0
    eng.cache.check(live_block_ids=[])


@pytest.mark.slow   # shares the int8 program set above
def test_int8_prefix_cache_hit_equals_miss_bitexact(model, params):
    """Quantization is a pure function of the written value, so a
    shared quantized block holds exactly the bytes a recomputing
    sequence would produce: cache-hit int8 decoding == cache-miss
    int8 decoding, bit for bit."""
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, VOCAB, size=2 * BS + 3).tolist()
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefill_chunk=8,
                    kv_dtype="int8")
    eng.warmup()
    first = Sequence(prompt, 8)       # miss: computes + registers
    for s in (first,):
        eng.add(s)
    while eng.has_work():
        eng.step()
    second = Sequence(prompt, 8)      # hit: shares the int8 blocks
    eng.add(second)
    while eng.has_work():
        eng.step()
    assert second.cache_hit_tokens >= 2 * BS
    assert first.output_tokens() == second.output_tokens()
    eng.cache.check(live_block_ids=[])


def test_kv_dtype_env_knob(model, params, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_LLM_KV_DTYPE", "int8")
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefill_chunk=8)
    assert eng.quantized
    assert eng.cache.k_scales is not None
    assert eng.cache.stats()["kv_dtype"] == "int8"
    monkeypatch.setenv("MXNET_TPU_LLM_KV_DTYPE", "float32")
    eng2 = LLMEngine(model, params, max_seqs=4, block_size=BS,
                     max_context=CTX, prefill_chunk=8)
    assert not eng2.quantized and eng2.cache.k_scales is None


# ------------------------------------------------ fp8 KV (ISSUE 20) --
FP8_KV_LOGIT_TOL = 0.15     # e4m3 pages: coarser mantissa than int8's
# 255-step grid at small |x|, finer near zero; measured ~0.05


def test_decode_flat_fp8_kv_logit_tolerance(model, params):
    """fp8-e4m3 KV pages: one mixed flat dispatch stays within
    FP8_KV_LOGIT_TOL of the fp32 run — the in-trace write path clips
    into the finite +-448 range before the cast (which would NaN
    out-of-range values, not saturate)."""
    from mxnet_tpu.serving.llm import fp8_supported
    if not fp8_supported():
        pytest.skip("no fp8-e4m3 dtype on this backend")
    rng = np.random.RandomState(7)
    L, H, D = model.num_layers, model.num_heads, model.head_dim
    N = 9
    fp8 = jnp.dtype("float8_e4m3fn")
    kp = jnp.zeros((L, N, BS, H, D), jnp.float32)
    vp = jnp.zeros((L, N, BS, H, D), jnp.float32)
    kq = jnp.zeros((L, N, BS, H, D), fp8)
    vq = jnp.zeros((L, N, BS, H, D), fp8)
    ks = jnp.ones((L, N, BS, H), jnp.float32)
    vs = jnp.ones((L, N, BS, H), jnp.float32)
    T = 16
    toks = rng.randint(0, VOCAB, T).astype(np.int32)
    pos = np.arange(T, dtype=np.int32)
    sid = np.zeros(T, np.int32)
    valid = np.ones(T, np.int32)
    bt = np.zeros((4, 8), np.int32)
    bt[0, :2] = [3, 5]
    lf = model.decode_flat(params, toks, pos, sid, valid, kp, vp, bt)[0]
    lq = model.decode_flat(params, toks, pos, sid, valid, kq, vq, bt,
                           k_scales=ks, v_scales=vs)[0]
    assert not np.isnan(np.asarray(lq)).any()
    diff = float(jnp.max(jnp.abs(lf - lq)))
    assert diff < FP8_KV_LOGIT_TOL, \
        f"fp8 KV logit drift {diff} > {FP8_KV_LOGIT_TOL}"


def test_kv_dtype_env_knob_fp8(model, params, monkeypatch):
    """MXNET_TPU_LLM_KV_DTYPE=fp8 builds float8_e4m3fn pools riding
    the SAME scale-pool plumbing as int8 (PR 13)."""
    from mxnet_tpu.serving.llm import fp8_supported
    if not fp8_supported():
        pytest.skip("no fp8-e4m3 dtype on this backend")
    monkeypatch.setenv("MXNET_TPU_LLM_KV_DTYPE", "fp8")
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefill_chunk=8)
    assert eng.quantized
    assert eng.cache.dtype.name == "float8_e4m3fn"
    assert eng.cache.k_scales is not None
    assert eng.kv_dtype_fallbacks == 0
    assert eng.cache.stats()["kv_dtype"] == "float8_e4m3fn"


@pytest.mark.slow   # compiles its own fp8-KV program set
def test_engine_fp8_kv_serves_zero_recompiles(model, params):
    """End to end: fp8-KV continuous batching serves greedy traffic
    with zero steady-state recompiles and clean block accounting.
    Token parity vs fp32 is NOT pinned for fp8 (near-tie positions
    may flip within FP8_KV_LOGIT_TOL) — the per-dispatch tolerance
    above is the contract."""
    from mxnet_tpu.serving.llm import fp8_supported
    if not fp8_supported():
        pytest.skip("no fp8-e4m3 dtype on this backend")
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, VOCAB, size=n).tolist()
               for n in (3, 8, 13)]
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefill_chunk=8, kv_dtype="fp8")
    eng.warmup()
    seqs = [Sequence(p, 6) for p in prompts]
    with serving.CompileCounter() as cc:
        for s in seqs:
            eng.add(s)
        steps = 0
        while eng.has_work():
            eng.step()
            steps += 1
            assert steps < 1000
    assert cc.count == 0, f"{cc.count} recompiles on the fp8 KV path"
    for s in seqs:
        assert len(s.output_tokens()) == 6
        assert all(0 <= t < VOCAB for t in s.output_tokens())
    assert eng.cache.allocator.num_used == 0
    eng.cache.check(live_block_ids=[])
