"""gluon.contrib.rnn cells (reference:
python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py, rnn_cell.py).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon.contrib import rnn as crnn
from mxnet_tpu.gluon import rnn as grnn
import mxnet_tpu.autograd as ag


def test_conv_lstm_cell_step_and_unroll():
    mx.random.seed(0)
    cell = crnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=5,
                               i2h_kernel=3, h2h_kernel=3)
    cell.initialize()
    x = nd.array(np.random.RandomState(0).randn(2, 3, 8, 8)
                 .astype(np.float32))
    states = cell.begin_state(batch_size=2)
    out, nstates = cell(x, states)
    assert out.shape == (2, 5, 8, 8)
    assert len(nstates) == 2 and nstates[1].shape == (2, 5, 8, 8)
    # unroll over time keeps shapes and is finite
    seq = nd.array(np.random.RandomState(1).randn(2, 4, 3, 8, 8)
                   .astype(np.float32))
    outs, final = cell.unroll(4, seq, layout="TNC"
                              if False else "NTC", merge_outputs=False)
    assert len(outs) == 4
    assert np.isfinite(outs[-1].asnumpy()).all()


def test_conv_gru_and_rnn_cells():
    for cls, states in [(crnn.Conv1DGRUCell, 1),
                        (crnn.Conv1DRNNCell, 1)]:
        mx.random.seed(1)
        cell = cls(input_shape=(2, 6), hidden_channels=4)
        cell.initialize()
        x = nd.array(np.random.RandomState(2).randn(3, 2, 6)
                     .astype(np.float32))
        out, ns = cell(x, cell.begin_state(batch_size=3))
        assert out.shape == (3, 4, 6)
        assert len(ns) == states


def test_lstmp_cell_projects():
    mx.random.seed(2)
    cell = crnn.LSTMPCell(hidden_size=16, projection_size=6)
    cell.initialize()
    x = nd.array(np.random.RandomState(3).randn(4, 10).astype(np.float32))
    out, states = cell(x, cell.begin_state(batch_size=4))
    assert out.shape == (4, 6)                 # projected
    assert states[0].shape == (4, 6)
    assert states[1].shape == (4, 16)          # memory cell unprojected
    # trains
    tr = gluon.Trainer(cell.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    with ag.record():
        o, _ = cell(x, cell.begin_state(batch_size=4))
        loss = (o ** 2).sum()
    loss.backward()
    tr.step(4)


def test_variational_dropout_mask_is_fixed_per_unroll():
    mx.random.seed(3)
    base = grnn.LSTMCell(8)
    cell = crnn.VariationalDropoutCell(base, drop_outputs=0.5)
    cell.initialize()
    x = nd.array(np.ones((2, 4), np.float32))
    states = cell.begin_state(batch_size=2)
    with ag.record():     # masks only apply in training mode
        out1, states = cell(x, states)
        out2, _ = cell(x, states)
    m1 = np.asarray(out1.asnumpy() == 0)
    m2 = np.asarray(out2.asnumpy() == 0)
    # the same output units are dropped at both steps
    np.testing.assert_array_equal(m1, m2)
    assert m1.any()
    # eval mode: no dropout
    out3, _ = cell(x, cell.begin_state(batch_size=2))
    assert not (out3.asnumpy() == 0).all()
