"""check_symbolic_forward / check_symbolic_backward oracles.

Reference: python/mxnet/test_utils.py:1130 (check_symbolic_forward) and
:1187 (check_symbolic_backward) — used pervasively by the reference op
tests to pin a symbol's executor outputs/input-grads against numpy.
These tests exercise the helpers themselves: correct values pass,
wrong values raise, grad_req routing is honored.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu.test_utils import (check_symbolic_forward,
                                  check_symbolic_backward)


def test_forward_elemwise():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    s = a * b + 2.0
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    y = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    check_symbolic_forward(s, {"a": x, "b": y}, [x * y + 2.0])
    # positional location form
    check_symbolic_forward(s, [x, y], [x * y + 2.0])


def test_forward_fc_detects_wrong_expectation():
    d = mx.sym.var("data")
    s = mx.sym.FullyConnected(d, num_hidden=3, name="fc")
    rng = np.random.RandomState(2)
    x = rng.randn(2, 5).astype(np.float32)
    w = rng.randn(3, 5).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    expected = x @ w.T + b
    check_symbolic_forward(s, {"data": x, "fc_weight": w, "fc_bias": b},
                           [expected], rtol=1e-4, atol=1e-5)
    with pytest.raises(AssertionError):
        check_symbolic_forward(
            s, {"data": x, "fc_weight": w, "fc_bias": b},
            [expected + 0.1], rtol=1e-4, atol=1e-5)


def test_backward_product_rule():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    s = a * b
    rng = np.random.RandomState(3)
    x = rng.randn(4,).astype(np.float32)
    y = rng.randn(4,).astype(np.float32)
    og = rng.randn(4,).astype(np.float32)
    grads = check_symbolic_backward(
        s, {"a": x, "b": y}, [og], {"a": og * y, "b": og * x})
    assert set(grads) == {"a", "b"}


def test_backward_grad_req_null_skips():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    s = a * b
    x = np.ones((2, 2), np.float32)
    y = np.full((2, 2), 3.0, np.float32)
    og = np.ones((2, 2), np.float32)
    grads = check_symbolic_backward(
        s, {"a": x, "b": y}, [og], {"a": og * y},
        grad_req={"a": "write", "b": "null"})
    assert "b" not in grads
    # an expectation for a null-req arg is ignored, not compared
    check_symbolic_backward(
        s, {"a": x, "b": y}, [og],
        {"a": og * y, "b": np.full((2, 2), 123.0, np.float32)},
        grad_req={"a": "write", "b": "null"})


def test_backward_wrong_grad_detected():
    a = mx.sym.var("a")
    s = mx.sym.exp(a)
    x = np.random.RandomState(4).randn(5,).astype(np.float32)
    og = np.ones((5,), np.float32)
    check_symbolic_backward(s, {"a": x}, [og], {"a": np.exp(x)},
                            rtol=1e-4, atol=1e-5)
    with pytest.raises(AssertionError):
        check_symbolic_backward(s, {"a": x}, [og], {"a": np.exp(x) * 1.1},
                                rtol=1e-4, atol=1e-5)


def test_location_validation():
    a = mx.sym.var("a")
    s = a + 1.0
    with pytest.raises(ValueError):
        check_symbolic_forward(s, {"nope": np.ones(2, np.float32)},
                               [np.ones(2, np.float32)])
    with pytest.raises(ValueError):
        check_symbolic_forward(s, [np.ones(2), np.ones(2)],
                               [np.ones(2, np.float32)])


def test_expected_grad_validation():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    s = a * b
    x = np.ones((2,), np.float32)
    og = np.ones((2,), np.float32)
    # a typo'd expected name must raise, not pass vacuously
    with pytest.raises(ValueError):
        check_symbolic_backward(s, {"a": x, "b": x}, [og],
                                {"a_typo": og})
    # a positional expected list must cover every argument
    with pytest.raises(ValueError):
        check_symbolic_backward(s, {"a": x, "b": x}, [og], [og])
