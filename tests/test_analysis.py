"""mxlint (mxnet_tpu.analysis) — the static-analysis gate.

Per-rule fixtures prove one true positive AND one near-miss
non-finding each, the suppression/baseline machinery round-trips, the
JSON reporter schema is pinned, and the full-tree smoke asserts the
repo itself lints clean (findings ⊆ committed baseline) fast — this
test IS the tier-1 wiring of ``tools/mxlint.py --check``, run
in-process (one engine pass, no subprocess-per-file).
"""
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu import analysis
from mxnet_tpu.analysis import baseline as baseline_mod
from mxnet_tpu.analysis import reporters
from mxnet_tpu.analysis.rules import RULES_BY_ID

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CATALOG_RULES = ("metric-catalog", "envvar-catalog", "fault-catalog")


def rules_of(src, rule_id):
    return [f for f in analysis.lint_source(src) if f.rule == rule_id]


# ------------------------------------------------------------------ host-sync

HOST_SYNC_TP = '''
import jax
import numpy as np
def step(params, x):
    loss = (params * x).sum()
    v = loss.item()
    s = float(loss)
    a = np.asarray(loss)
    return loss
f = jax.jit(step)
'''

# near miss: shape-derived values are static under trace, and eager
# code may sync freely
HOST_SYNC_OK = '''
import jax
import numpy as np
def step(params, x):
    n = float(x.shape[0])
    k = int(len(params))
    return params * x / n * k
f = jax.jit(step)
def eager_loop(x):
    return float(x.sum())
'''


def test_host_sync_true_positive():
    lines = {f.line for f in rules_of(HOST_SYNC_TP, "host-sync")}
    assert lines == {6, 7, 8}, lines


def test_host_sync_near_miss():
    assert rules_of(HOST_SYNC_OK, "host-sync") == []


def test_host_sync_reaches_transitive_callees():
    src = '''
import jax
def inner(x):
    return float(x)
def outer(x):
    return inner(x) + 1
f = jax.jit(outer)
'''
    assert len(rules_of(src, "host-sync")) == 1


def test_host_sync_ignores_same_name_method():
    # a class method named like a jitted local must not be conflated
    src = '''
import jax
class Trainer:
    def step(self, x):
        return float(x)
def make():
    def step(x):
        return x * 2
    return jax.jit(step)
'''
    assert rules_of(src, "host-sync") == []


# -------------------------------------------------------------- donated-reuse

DONATED_TP = '''
import jax
def train(params, grads):
    f = jax.jit(apply, donate_argnums=(0,))
    out = f(params, grads)
    return params.copy()
'''

DONATED_OK = '''
import jax
def train(params, grads):
    f = jax.jit(apply, donate_argnums=(0,))
    params = f(params, grads)
    return params
'''


def test_donated_reuse_true_positive():
    fs = rules_of(DONATED_TP, "donated-reuse")
    assert len(fs) == 1 and fs[0].line == 6


def test_donated_reuse_near_miss_rebind():
    assert rules_of(DONATED_OK, "donated-reuse") == []


def test_donated_reuse_nested_statement_single_finding():
    # the donating call sits under an `if` — every statement level
    # sees it, but exactly ONE finding (and one baseline entry) must
    # come out
    src = '''
import jax
def train(params, grads, flag):
    f = jax.jit(apply, donate_argnums=(0,))
    if flag:
        out = f(params, grads)
    return params.copy()
'''
    assert len(rules_of(src, "donated-reuse")) == 1


# ----------------------------------------------------------- recompile-hazard

RECOMPILE_TP = '''
import jax
def make():
    lr = 0.1
    def step(x):
        return x * lr
    j = jax.jit(step)
    lr = 0.2
    return j
'''

# near misses: a closure assigned once before the compile is static
# config; a fresh def + fresh jit per loop iteration is the
# bucket-ladder idiom (one trace each), not a recompile
RECOMPILE_OK = '''
import jax
def make(cfg):
    scale = cfg["scale"]
    def step(x):
        return x * scale
    return jax.jit(step)
def ladder(widths):
    jits = {}
    for w in widths:
        def stepw(x):
            return x[:w]
        jits[w] = jax.jit(stepw)
    return jits
'''


def test_recompile_hazard_true_positive():
    fs = rules_of(RECOMPILE_TP, "recompile-hazard")
    assert len(fs) == 1 and "lr" in fs[0].message


def test_recompile_hazard_near_miss():
    assert rules_of(RECOMPILE_OK, "recompile-hazard") == []


# ------------------------------------------------------------------- kv-leak

KV_TP = '''
class Engine:
    def grow(self, n):
        blocks = self.cache.allocator.alloc(n)
        self.dispatch(blocks)
        self.table.extend(blocks)
'''

KV_OK = '''
class Engine:
    def grow(self, seq, n):
        seq.block_ids.extend(self.cache.allocator.alloc(n))
    def cow(self, n):
        new = None
        try:
            new = self.cache.allocator.alloc(1)[0]
            self.dispatch(new)
        except BaseException:
            if new is not None:
                self.cache.allocator.free([new])
            raise
'''

KV_EXCEPT_TP = '''
class Engine:
    def run(self, seq):
        try:
            self.dispatch(seq)
        except Exception:
            self.cache.allocator.free(seq.block_ids)
            raise
'''

KV_EXCEPT_OK = '''
class Engine:
    def run(self, seq):
        try:
            self.dispatch(seq)
        except BaseException:
            self.cache.allocator.free(seq.block_ids)
            raise
'''


def test_kv_leak_true_positive():
    fs = rules_of(KV_TP, "kv-leak")
    assert len(fs) == 1 and fs[0].line == 4


def test_kv_leak_near_miss_safe_patterns():
    assert rules_of(KV_OK, "kv-leak") == []


def test_kv_leak_flags_block_freeing_except_exception():
    fs = rules_of(KV_EXCEPT_TP, "kv-leak")
    assert len(fs) == 1 and "BaseException" in fs[0].message


def test_kv_leak_base_exception_handler_clean():
    assert rules_of(KV_EXCEPT_OK, "kv-leak") == []


# ---------------------------------------------------------------- guarded-by

GUARDED_TP = '''
import threading
class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []        # guarded-by: _lock
    def depth(self):
        return len(self._q)
'''

GUARDED_OK = '''
import threading
class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []        # guarded-by: _lock
        self._q.append(0)   # __init__ is pre-publication
    def depth(self):
        with self._lock:
            return len(self._q)
    def _pop_locked(self):  # guarded-by: caller
        return self._q.pop()
'''


def test_guarded_by_true_positive():
    fs = rules_of(GUARDED_TP, "guarded-by")
    assert len(fs) == 1 and fs[0].line == 8


def test_guarded_by_near_miss_locked_waived_init():
    assert rules_of(GUARDED_OK, "guarded-by") == []


def test_guarded_by_wrong_lock_still_flagged():
    src = '''
import threading
class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._other = threading.Lock()
        self._q = []        # guarded-by: _lock
    def depth(self):
        with self._other:
            return len(self._q)
'''
    assert len(rules_of(src, "guarded-by")) == 1


# ------------------------------------------------------------- catalog rules

def _mini_project(tmp_path, code, obs="", env="", res=""):
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "mod.py").write_text(code)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "OBS.md").write_text(obs)
    (tmp_path / "docs" / "ENV.md").write_text(env)
    (tmp_path / "docs" / "RES.md").write_text(res)
    config = dict(analysis.DEFAULT_CONFIG)
    config.update(paths=["pkg"], catalog_paths=["pkg"],
                  metric_docs="docs/OBS.md", env_docs="docs/ENV.md",
                  fault_docs="docs/RES.md")
    return analysis.run(str(tmp_path), config=config)


CATALOG_CODE = '''
import os
from x import faults
def setup(r):
    c = r.counter("mxtpu_widget_spins_total", "help")
    lim = os.environ.get("MXNET_TPU_WIDGET_LIMIT", "4")
    faults.check("widget.spin")
    return c, lim
'''


def test_catalog_rules_flag_drift(tmp_path):
    result = _mini_project(tmp_path, CATALOG_CODE)
    by_rule = result.by_rule()
    assert by_rule.get("metric-catalog") == 1
    assert by_rule.get("envvar-catalog") == 1
    assert by_rule.get("fault-catalog") == 1
    assert all(f.path == "pkg/mod.py" for f in result.findings)


def test_catalog_rules_documented_clean(tmp_path):
    result = _mini_project(
        tmp_path, CATALOG_CODE,
        obs="| `mxtpu_widget_spins_total` | counter | spins |\n",
        env="| `MXNET_TPU_WIDGET_LIMIT` | 4 | widget cap |\n",
        res="| `widget.spin` | check | the spin dispatch |\n")
    assert result.findings == []


def test_catalog_ignores_docstrings_and_non_catalog_paths(tmp_path):
    # env names in docstrings and metric strings outside declaration
    # calls are mentions, not declarations
    code = '''
"""Reads MXNET_TPU_WIDGET_LIMIT someday."""
NAMES = ["mxtpu_not_a_declaration"]
'''
    result = _mini_project(tmp_path, code)
    assert result.findings == []


# ---------------------------------------------------- suppressions + baseline

def test_suppression_inline_and_wrong_rule():
    suppressed = KV_TP.replace(
        "alloc(n)",
        "alloc(n)   # mxlint: disable=kv-leak  scratch, caller frees")
    assert analysis.lint_source(suppressed) == []
    wrong = KV_TP.replace(
        "alloc(n)", "alloc(n)   # mxlint: disable=host-sync  nope")
    assert len([f for f in analysis.lint_source(wrong)
                if f.rule == "kv-leak"]) == 1


def test_suppression_standalone_line_covers_next_line():
    src = '''
class Engine:
    def grow(self, n):
        # mxlint: disable=kv-leak  handed to the caller-owned pool
        blocks = self.cache.allocator.alloc(n)
        self.dispatch(blocks)
'''
    assert analysis.lint_source(src) == []


def test_suppression_file_level():
    src = "# mxlint: disable-file=kv-leak  fixture corpus\n" + KV_TP
    assert analysis.lint_source(src) == []


def test_baseline_roundtrip(tmp_path):
    findings = analysis.lint_source(KV_TP, path="pkg/mod.py")
    assert findings
    path = tmp_path / "baseline.json"
    baseline_mod.write_baseline(str(path), findings)
    keys, entries = baseline_mod.load_baseline(str(path))
    assert all(set(e) >= {"rule", "path", "line", "message"}
               for e in entries)
    new, known, stale = baseline_mod.diff(findings, keys)
    assert new == [] and len(known) == len(findings) and stale == []
    # the baseline matches exact lines: a moved finding comes back new
    moved = [analysis.Finding(f.rule, f.path, f.line + 5, f.col,
                              f.message) for f in findings]
    new, _, stale = baseline_mod.diff(moved, keys)
    assert len(new) == len(findings) and len(stale) == len(findings)


def test_baseline_missing_file_is_empty(tmp_path):
    keys, entries = baseline_mod.load_baseline(
        str(tmp_path / "nope.json"))
    assert keys == set() and entries == []


def test_minimal_toml_parser_handles_comments():
    # on Python 3.10 (the repo floor, no tomllib) this parser IS the
    # production config path — trailing comments after quoted values
    # and per-line comments inside multi-line arrays must not corrupt
    # values (a corrupted `paths` silently lints zero files)
    from mxnet_tpu.analysis.core import _parse_toml_minimal
    data = _parse_toml_minimal('''
[tool.mxlint]
baseline = "tools/b.json"   # the gate ledger
paths = [
  "mxnet_tpu",   # core
  "tools#x",
]   # end
limit = 3  # int
strict = true
''')
    t = data["tool"]["mxlint"]
    assert t["baseline"] == "tools/b.json"
    assert t["paths"] == ["mxnet_tpu", "tools#x"]
    assert t["limit"] == 3 and t["strict"] is True


def test_collect_files_excludes_segments_not_substrings(tmp_path):
    # "dist"/"build" excludes must not swallow distill.py / build_x.py
    pkg = tmp_path / "pkg"
    (pkg / "dist").mkdir(parents=True)
    (pkg / "native" / "_build").mkdir(parents=True)
    for rel in ("mod.py", "distill.py", "build_utils.py",
                "dist/skip.py", "native/_build/gen.py"):
        (pkg / rel).write_text("x = 1\n")
    files = analysis.collect_files(
        str(tmp_path), ["pkg"], ["dist", "native/_build"])
    assert files == ["pkg/build_utils.py", "pkg/distill.py",
                     "pkg/mod.py"]


# ------------------------------------------------------------- JSON reporter

def test_json_reporter_schema_stable(tmp_path):
    result = _mini_project(tmp_path, CATALOG_CODE)
    doc = reporters.to_json(result, new=result.findings, stale=[])
    assert set(doc) == {"version", "tool", "findings", "summary",
                        "new_findings", "stale_baseline"}
    assert doc["version"] == reporters.JSON_SCHEMA_VERSION
    assert doc["tool"] == "mxlint"
    for f in doc["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message"}
    assert set(doc["summary"]) == {
        "files", "findings", "suppressed", "by_rule", "elapsed_s",
        "new", "stale_baseline"}
    json.dumps(doc)   # serializable


# --------------------------------------------------------- full-tree smoke --

@pytest.fixture(scope="module")
def tree_result():
    return analysis.run(REPO_ROOT)


def test_full_tree_clean_against_baseline(tree_result):
    config = analysis.load_config(REPO_ROOT)
    keys, entries = baseline_mod.load_baseline(
        os.path.join(REPO_ROOT, config["baseline"]))
    new, known, stale = baseline_mod.diff(tree_result.findings, keys)
    assert new == [], (
        "mxlint found new violations — fix them, suppress with a "
        "justified '# mxlint: disable=RULE reason', or re-baseline "
        "deliberately:\n" + "\n".join(
            f"{f.path}:{f.line}: {f.rule}: {f.message}"
            for f in new))
    assert stale == [], (
        f"stale baseline entries (fixed code): {stale} — delete them "
        f"from {config['baseline']}")
    # the catalog-drift rules carry NO grandfathered findings: docs
    # drift is always fixable in the PR that causes it
    bad = [e for e in entries if e["rule"] in CATALOG_RULES]
    assert bad == [], f"catalog drift must be fixed, not baselined: {bad}"


def test_full_tree_is_fast(tree_result):
    # pure-ast full-tree pass; the CLI promises seconds, the gate <10s
    assert tree_result.elapsed_s < 10.0, tree_result.elapsed_s
    assert len(tree_result.files) > 150   # actually scanned the tree


def test_full_tree_parses_everything(tree_result):
    assert tree_result.parse_errors == []


def test_rule_registry_complete(tree_result):
    # every shipped rule has an id, a scope, and a description
    for rule_id, cls in RULES_BY_ID.items():
        assert rule_id and cls.scope in ("file", "project")
        assert cls.description


def test_cli_check_standalone(tmp_path):
    # the CLI loads mxnet_tpu/analysis WITHOUT importing mxnet_tpu
    # (no jax) — pin that property for real: poisoned jax/mxnet_tpu
    # modules shadow the installed ones via PYTHONPATH, so ANY import
    # of either crashes the subprocess instead of silently passing
    for name in ("jax", "mxnet_tpu", "numpy"):
        (tmp_path / f"{name}.py").write_text(
            f"raise RuntimeError('mxlint must not import {name}')\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "mxlint.py"),
         "--check"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "PYTHONPATH": str(tmp_path)})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "mxlint:" in proc.stdout
