"""SSD detection path: ops, model, loss, mAP metric.

Oracles are hand-computed box math (reference semantics:
src/operator/contrib/multibox_prior.cc:28, multibox_target.cc:32,
multibox_detection.cc:46, roi_align.cc:144).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.autograd as ag


def test_multibox_prior_matches_reference_math():
    x = nd.array(np.zeros((1, 3, 2, 3), np.float32))
    out = nd._contrib_MultiBoxPrior(x, sizes=(0.5, 0.3), ratios=(1.0, 2.0))
    a = out.asnumpy()
    h, w = 2, 3
    assert a.shape == (1, h * w * 3, 4)
    cy, cx = 0.5 / h, 0.5 / w
    # anchor 0: size .5, ratio 1 -> w = s*(h/w)/2, h = s/2
    w0, h0 = 0.5 * (h / w) / 2, 0.5 / 2
    np.testing.assert_allclose(a[0, 0], [cx - w0, cy - h0, cx + w0,
                                         cy + h0], rtol=1e-5)
    # anchor 1: size .3, ratio 1 (all sizes use ratios[0])
    w1, h1 = 0.3 * (h / w) / 2, 0.3 / 2
    np.testing.assert_allclose(a[0, 1], [cx - w1, cy - h1, cx + w1,
                                         cy + h1], rtol=1e-5)
    # anchor 2: size .5, ratio 2
    w2, h2 = 0.5 * (h / w) * np.sqrt(2) / 2, 0.5 / np.sqrt(2) / 2
    np.testing.assert_allclose(a[0, 2], [cx - w2, cy - h2, cx + w2,
                                         cy + h2], rtol=1e-5)
    # clip
    c = nd._contrib_MultiBoxPrior(x, sizes=(0.9,), clip=True).asnumpy()
    assert c.min() >= 0 and c.max() <= 1


def test_box_iou():
    a = nd.array(np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32))
    b = nd.array(np.array([[0, 0, 2, 2], [2, 2, 4, 4]], np.float32))
    iou = nd._contrib_box_iou(a, b).asnumpy()
    np.testing.assert_allclose(iou, [[1.0, 0.0], [1 / 7, 1 / 7]],
                               rtol=1e-5)


def _toy_setup():
    anchors = nd._contrib_MultiBoxPrior(
        nd.array(np.zeros((1, 3, 4, 4), np.float32)),
        sizes=(0.4,), ratios=(1.0, 2.0))
    A = anchors.shape[1]
    label = np.full((2, 3, 6), -1.0, np.float32)
    label[0, 0] = [1, 0.1, 0.1, 0.4, 0.4, 0]
    label[0, 1] = [0, 0.6, 0.6, 0.9, 0.95, 0]
    label[1, 0] = [2, 0.3, 0.2, 0.8, 0.7, 0]
    cls_pred = np.random.RandomState(0).randn(2, 4, A).astype(np.float32)
    return anchors, A, label, cls_pred


def test_multibox_target_assignment():
    anchors, A, label, cls_pred = _toy_setup()
    lt, lm, ct = nd._contrib_MultiBoxTarget(
        anchors, nd.array(label), nd.array(cls_pred),
        overlap_threshold=0.5, negative_mining_ratio=3.0)
    assert lt.shape == (2, A * 4) and lm.shape == (2, A * 4)
    assert ct.shape == (2, A)
    ctn = ct.asnumpy()
    # every valid gt gets at least one positive anchor (bipartite stage)
    assert (ctn[0] > 0).sum() >= 2
    assert (ctn[1] > 0).sum() >= 1
    # class ids offset by 1 (0 = background)
    assert set(np.unique(ctn[0][ctn[0] > 0])) <= {1.0, 2.0}
    # negative mining keeps ~3x positives as background, rest ignored
    npos, nneg = (ctn[0] > 0).sum(), (ctn[0] == 0).sum()
    assert nneg <= 3 * npos
    assert (ctn[0] == -1).sum() == A - npos - nneg
    # loc_mask nonzero exactly on positives
    lmn = lm.asnumpy()[0].reshape(A, 4)
    np.testing.assert_array_equal(lmn.any(axis=1), ctn[0] > 0)


def test_multibox_target_no_mining_all_negatives():
    anchors, A, label, cls_pred = _toy_setup()
    _, _, ct = nd._contrib_MultiBoxTarget(
        anchors, nd.array(label), nd.array(cls_pred),
        negative_mining_ratio=-1.0)
    ctn = ct.asnumpy()
    assert ((ctn == 0) | (ctn > 0)).all()   # nothing ignored


def test_multibox_encode_decode_roundtrip():
    """Targets encoded by MultiBoxTarget, fed to MultiBoxDetection as
    perfect predictions, must decode back to the ground-truth boxes."""
    anchors, A, label, cls_pred = _toy_setup()
    lt, lm, ct = nd._contrib_MultiBoxTarget(
        anchors, nd.array(label), nd.array(cls_pred),
        overlap_threshold=0.5, negative_mining_ratio=3.0)
    ctn = ct.asnumpy()[0]
    probs = np.zeros((1, 4, A), np.float32)
    probs[0, 0, :] = 1.0
    for i in np.where(ctn > 0)[0]:
        probs[0, int(ctn[i]), i] = 1.0
        probs[0, 0, i] = 0.0
    det = nd._contrib_MultiBoxDetection(
        nd.array(probs), nd.array(lt.asnumpy()[0:1]), anchors,
        nms_threshold=0.45, threshold=0.2)
    d = det.asnumpy()[0]
    kept = d[d[:, 0] >= 0]
    assert len(kept) >= 2
    for row in kept:
        cls, score, x1, y1, x2, y2 = row
        gt = label[0][label[0][:, 0] == cls][:, 1:5]
        ious = []
        for g in gt:
            iw = min(x2, g[2]) - max(x1, g[0])
            ih = min(y2, g[3]) - max(y1, g[1])
            inter = max(iw, 0) * max(ih, 0)
            union = ((x2 - x1) * (y2 - y1) +
                     (g[2] - g[0]) * (g[3] - g[1]) - inter)
            ious.append(inter / union)
        assert max(ious) > 0.95, row
    # rows are score-sorted
    scores = kept[:, 1]
    assert (np.diff(scores) <= 1e-6).all()


def test_box_nms_suppresses_overlaps():
    data = np.array([[
        [0, 0.9, 0.1, 0.1, 0.5, 0.5],
        [0, 0.8, 0.12, 0.12, 0.52, 0.52],   # overlaps row 0 -> suppressed
        [0, 0.7, 0.6, 0.6, 0.9, 0.9],
        [1, 0.6, 0.11, 0.11, 0.51, 0.51],   # other class -> kept
    ]], np.float32)
    out = nd._contrib_box_nms(nd.array(data), overlap_thresh=0.5,
                              coord_start=2, score_index=1, id_index=0)
    o = out.asnumpy()[0]
    kept_ids = o[o[:, 0] >= 0][:, 0]
    assert len(kept_ids) == 3
    # force_suppress kills the cross-class overlap too
    out2 = nd._contrib_box_nms(nd.array(data), overlap_thresh=0.5,
                               coord_start=2, score_index=1, id_index=0,
                               force_suppress=True)
    o2 = out2.asnumpy()[0]
    assert (o2[:, 0] >= 0).sum() == 2


def test_roi_align_values_and_grad():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import _REGISTRY

    # linear ramp image: bilinear sampling of a linear function is exact
    H = W = 8
    ramp = np.arange(W, dtype=np.float32)[None, :].repeat(H, 0)
    img = np.stack([ramp, ramp.T])[None]          # (1, 2, H, W)
    rois = np.array([[0, 1, 1, 5, 5]], np.float32)
    out = nd._contrib_ROIAlign(nd.array(img), nd.array(rois),
                               pooled_size=(2, 2), spatial_scale=1.0,
                               sample_ratio=2).asnumpy()
    # channel 0 varies along x only: bin centers at x = 2, 4
    np.testing.assert_allclose(out[0, 0], [[2.0, 4.0], [2.0, 4.0]],
                               atol=1e-5)
    np.testing.assert_allclose(out[0, 1], [[2.0, 2.0], [4.0, 4.0]],
                               atol=1e-5)

    g = jax.grad(lambda d: _REGISTRY["_contrib_ROIAlign"].impl(
        d, jnp.asarray(rois), pooled_size=(2, 2),
        sample_ratio=2).sum())(jnp.asarray(img))
    assert float(g.sum()) == pytest.approx(8.0, rel=1e-5)


@pytest.mark.slow   # ~17s of full-net compile on 1 CPU (tier-1
# budget); the multibox/roi/nms op tests above keep the detection
# math in the fast gate
def test_ssd_300_forward_shapes():
    from mxnet_tpu.gluon.model_zoo import ssd_300_vgg16_reduced

    mx.random.seed(0)
    net = ssd_300_vgg16_reduced(classes=20)
    net.initialize()
    x = nd.array(np.random.RandomState(0).randn(1, 3, 300, 300) * 0.1)
    with ag.pause():
        cls_preds, loc_preds, anchors = net(x)
    # SSD-300 anchor ledger: 38^2*4 + 19^2*6 + 10^2*6 + 5^2*6 + 3^2*4
    # + 1^2*4 = 8732
    assert anchors.shape == (1, 8732, 4)
    assert cls_preds.shape == (1, 21, 8732)
    assert loc_preds.shape == (1, 8732 * 4)
    assert np.isfinite(cls_preds.asnumpy()).all()


@pytest.mark.slow   # ~32s convergence loop (tier-1 budget);
# SSD forward/anchor/NMS correctness stays in the fast tests above
def test_ssd_toy_convergence():
    """A small SSD must learn to localize a synthetic box task: loss
    drops and mAP on the train set becomes high."""
    from mxnet_tpu.gluon.model_zoo.ssd import SSD, MultiBoxLoss
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    stage1 = nn.HybridSequential(prefix="")
    stage1.add(nn.Conv2D(16, 3, strides=2, padding=1, activation="relu"))
    stage1.add(nn.Conv2D(16, 3, strides=2, padding=1, activation="relu"))
    stage2 = nn.HybridSequential(prefix="")
    stage2.add(nn.Conv2D(16, 3, strides=2, padding=1, activation="relu"))
    net = SSD([stage1, stage2], sizes=[(0.3,), (0.6,)],
              ratios=[(1.0, 2.0), (1.0, 2.0)], steps=[-1.0, -1.0],
              classes=2)
    net.initialize()
    loss_fn = MultiBoxLoss(negative_mining_ratio=3.0)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})

    # synthetic task: one bright square per image; class = quadrant row
    rng = np.random.RandomState(0)
    N = 16
    imgs = rng.randn(N, 3, 32, 32).astype(np.float32) * 0.05
    labels = np.full((N, 2, 6), -1.0, np.float32)
    for i in range(N):
        cx, cy = rng.uniform(0.25, 0.75, 2)
        s = 0.3
        x1, y1, x2, y2 = cx - s / 2, cy - s / 2, cx + s / 2, cy + s / 2
        c = 0 if cx < 0.5 else 1
        imgs[i, c, int(y1 * 32):int(y2 * 32),
             int(x1 * 32):int(x2 * 32)] += 1.0
        labels[i, 0] = [c, x1, y1, x2, y2, 0]
    x, y = nd.array(imgs), nd.array(labels)

    losses = []
    for _ in range(60):
        with ag.record():
            cls_preds, loc_preds, anchors = net(x)
            loss = loss_fn(cls_preds, loc_preds, y, anchors).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])

    metric = mx.metric.create("vocmapmetric")
    with ag.pause():
        det = net.detect(x, nms_threshold=0.45, threshold=0.3)
    metric.update([y], [det])
    name, value = metric.get()
    assert value > 0.5, (name, value)


def test_voc_map_metric_known_values():
    m = mx.metric.create("voc07mapmetric")
    gt = np.full((1, 2, 6), -1.0, np.float32)
    gt[0, 0] = [0, 0.1, 0.1, 0.5, 0.5, 0]
    det = np.full((1, 3, 6), -1.0, np.float32)
    det[0, 0] = [0, 0.9, 0.1, 0.1, 0.5, 0.5]       # perfect hit
    m.update([nd.array(gt)], [nd.array(det)])
    assert m.get()[1] == pytest.approx(1.0)
    m.reset()
    det[0, 0] = [0, 0.9, 0.6, 0.6, 0.9, 0.9]       # miss
    m.update([nd.array(gt)], [nd.array(det)])
    assert m.get()[1] == pytest.approx(0.0)
