"""CTC loss + gradient vs torch's reference implementation.

Reference: src/operator/nn/ctc_loss.cc is validated in the reference
repo against warp-ctc; torch.nn.functional.ctc_loss implements the
same Graves CTC and ships in this image, so it serves as the
independent oracle here — both the forward loss and the full input
gradient must agree, including variable label lengths and variable
data lengths.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from mxnet_tpu.ops.nn import _ctc_loss  # noqa: E402


def _torch_ctc(logits, labels, lab_len, dat_len, blank):
    tl = torch.tensor(logits, requires_grad=True)
    logp = torch.nn.functional.log_softmax(tl, dim=-1)
    B = logits.shape[1]
    tgt = torch.tensor(np.concatenate(
        [labels[b, :lab_len[b]] for b in range(B)]).astype(np.int64))
    loss = torch.nn.functional.ctc_loss(
        logp, tgt, torch.tensor(dat_len, dtype=torch.long),
        torch.tensor(lab_len, dtype=torch.long), blank=blank,
        reduction="none")
    loss.sum().backward()
    return loss.detach().numpy(), tl.grad.numpy()


@pytest.mark.parametrize("seed", [0, 1])
def test_blank_last_matches_torch(seed):
    rng = np.random.RandomState(seed)
    T, B, A, L = 12, 4, 11, 4
    logits = rng.randn(T, B, A).astype(np.float32)
    labels = rng.randint(0, A - 1, size=(B, L)).astype(np.int32)
    lab_len = rng.randint(1, L + 1, size=B).astype(np.int32)
    padded = labels.copy()
    for b in range(B):
        padded[b, lab_len[b]:] = A - 1

    ours = _ctc_loss(jnp.asarray(logits), jnp.asarray(padded),
                     label_lengths=jnp.asarray(lab_len),
                     use_label_lengths=True, blank_label="last")
    g = jax.grad(lambda lg: _ctc_loss(
        lg, jnp.asarray(padded), label_lengths=jnp.asarray(lab_len),
        use_label_lengths=True, blank_label="last").sum())(
        jnp.asarray(logits))

    want, gwant = _torch_ctc(logits, labels, lab_len,
                             np.full(B, T, np.int64), blank=A - 1)
    np.testing.assert_allclose(np.asarray(ours), want, rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(g), gwant, rtol=1e-4,
                               atol=1e-5)


def test_blank_first_with_data_lengths_matches_torch():
    rng = np.random.RandomState(2)
    T, B, A, L = 10, 3, 7, 3
    logits = rng.randn(T, B, A).astype(np.float32)
    labels = rng.randint(1, A, size=(B, L)).astype(np.int32)  # blank=0
    lab_len = np.array([3, 2, 1], np.int32)
    dat_len = np.array([10, 8, 6], np.int32)
    padded = labels.copy()
    for b in range(B):
        padded[b, lab_len[b]:] = -1

    ours = _ctc_loss(jnp.asarray(logits), jnp.asarray(padded),
                     data_lengths=jnp.asarray(dat_len),
                     label_lengths=jnp.asarray(lab_len),
                     use_data_lengths=True, use_label_lengths=True,
                     blank_label="first")
    g = jax.grad(lambda lg: _ctc_loss(
        lg, jnp.asarray(padded), data_lengths=jnp.asarray(dat_len),
        label_lengths=jnp.asarray(lab_len), use_data_lengths=True,
        use_label_lengths=True, blank_label="first").sum())(
        jnp.asarray(logits))

    want, gwant = _torch_ctc(logits, labels, lab_len, dat_len, blank=0)
    np.testing.assert_allclose(np.asarray(ours), want, rtol=1e-5,
                               atol=1e-5)
    # grads beyond each sequence's data length are zero on both sides
    np.testing.assert_allclose(np.asarray(g), gwant, rtol=1e-4,
                               atol=1e-5)
