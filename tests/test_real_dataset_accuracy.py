"""Real-dataset accuracy floor (BASELINE.json top-1-parity stand-in).

The reference's protocol trains ResNet on CIFAR/ImageNet and checks
top-1 (example/image-classification/train_cifar10.py); those datasets
need network egress, so the floor is pinned on the one REAL image
dataset available offline — scikit-learn's handwritten digits (1797
genuine 8x8 grayscale scans, Alpaydin & Kaynak 1995). The full stack is
the same as the CIFAR run: JPEG-packed .rec -> native C++ decode/augment
pool -> model-zoo ResNet-18 (CIFAR stem) -> gluon Trainer, deterministic
seeds, held-out split, hard accuracy assert.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, recordio
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.image import ImageRecordIterNative, native_pipeline_available


def _digits_rec(prefix, images, labels, quality=3):  # PNG: lossless
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "w")
    for i, (img, lab) in enumerate(zip(images, labels)):
        # 8x8 [0,16] -> 32x32 RGB uint8 (nearest: keep strokes crisp)
        u8 = np.clip(img * 255.0 / 16.0, 0, 255).astype(np.uint8)
        big = np.repeat(np.repeat(u8, 4, axis=0), 4, axis=1)
        rgb = np.stack([big] * 3, axis=-1)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(lab), i, 0), rgb,
            quality=quality, img_fmt=".png"))
    rec.close()


@pytest.mark.slow   # ~390s: the single largest tier-1 cost (ISSUE 12
# budget fix); MLP/LeNet convergence floors in test_train.py keep the
# fast gate's accuracy coverage
@pytest.mark.skipif(not native_pipeline_available(),
                    reason="native decode pipeline unavailable")
def test_resnet18_digits_accuracy_floor(tmp_path):
    from sklearn.datasets import load_digits
    digits = load_digits()
    n = len(digits.images)
    rng = np.random.RandomState(0)
    order = rng.permutation(n)
    split = int(0.85 * n)
    tr_idx, te_idx = order[:split], order[split:]
    _digits_rec(str(tmp_path / "train"), digits.images[tr_idx],
                digits.target[tr_idx])
    _digits_rec(str(tmp_path / "test"), digits.images[te_idx],
                digits.target[te_idx])

    mx.random.seed(0)
    net = vision.resnet18_v1(classes=10, thumbnail=True)
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    train_it = ImageRecordIterNative(
        path_imgrec=str(tmp_path / "train.rec"), data_shape=(3, 32, 32),
        batch_size=64, shuffle=True, seed=0,
        mean=(127.5, 127.5, 127.5), std=(127.5, 127.5, 127.5))
    for epoch in range(3):
        for batch in train_it:
            data, label = batch.data[0], batch.label[0]
            with mx.autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0] - batch.pad)
        if epoch < 2:
            train_it.reset()
    train_it.close()

    metric = mx.metric.Accuracy()
    test_it = ImageRecordIterNative(
        path_imgrec=str(tmp_path / "test.rec"), data_shape=(3, 32, 32),
        batch_size=128, mean=(127.5, 127.5, 127.5),
        std=(127.5, 127.5, 127.5))
    for batch in test_it:
        out = net(batch.data[0])
        keep = batch.data[0].shape[0] - batch.pad
        metric.update([batch.label[0][:keep]], [out[:keep]])
    test_it.close()
    acc = metric.get()[1]
    # 270 held-out real images; deterministic seeds. Observed ~0.97;
    # the floor leaves headroom for platform fp differences only.
    assert acc >= 0.90, f"real-data top-1 {acc:.3f} below floor 0.90"
