"""gluon.contrib.nn layers (reference:
python/mxnet/gluon/contrib/nn/basic_layers.py).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import nn as cnn
import mxnet_tpu.autograd as ag


def test_concurrent_concats_branches():
    mx.random.seed(0)
    net = cnn.HybridConcurrent(axis=1)
    net.add(nn.Dense(4), cnn.Identity())
    net.initialize()
    x = nd.array(np.ones((2, 3), np.float32))
    out = net(x)
    assert out.shape == (2, 7)
    np.testing.assert_allclose(out.asnumpy()[:, 4:], 1.0)
    net.hybridize()
    out2 = net(x)
    np.testing.assert_allclose(out2.asnumpy(), out.asnumpy(), rtol=1e-6)


def test_pixelshuffle_oracles():
    x1 = np.arange(6, dtype=np.float32).reshape(1, 2, 3)
    y1 = cnn.PixelShuffle1D(2)(nd.array(x1)).asnumpy()
    np.testing.assert_allclose(y1, [[[0, 3, 1, 4, 2, 5]]])
    x2 = np.arange(16, dtype=np.float32).reshape(1, 4, 2, 2)
    y2 = cnn.PixelShuffle2D((2, 2))(nd.array(x2)).asnumpy()
    assert y2.shape == (1, 1, 4, 4)
    # torch pixel_shuffle oracle for the same layout convention
    np.testing.assert_allclose(y2[0, 0, 0], [0, 4, 1, 5])
    np.testing.assert_allclose(y2[0, 0, 1], [8, 12, 9, 13])
    x3 = np.arange(8, dtype=np.float32).reshape(1, 8, 1, 1, 1)
    y3 = cnn.PixelShuffle3D((2, 2, 2))(nd.array(x3)).asnumpy()
    assert y3.shape == (1, 1, 2, 2, 2)


def test_sparse_embedding_layer():
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    emb = cnn.SparseEmbedding(50, 4)
    emb.initialize()
    with ag.record():
        loss = (emb(nd.array(np.array([1, 9]))) ** 2).sum()
    loss.backward()
    assert isinstance(emb.weight.grad(), RowSparseNDArray)


def test_sync_batch_norm_layer_trains_and_syncs():
    import jax
    import jax.numpy as jnp
    sbn = cnn.SyncBatchNorm(in_channels=3)
    sbn.initialize()
    x = nd.array(np.random.RandomState(0).randn(8, 3, 6)
                 .astype(np.float32))
    with ag.record():
        out = sbn(x)
    out.backward()
    assert np.isfinite(out.asnumpy()).all()
    # running stats moved off their init
    assert np.abs(sbn.running_mean.data().asnumpy()).sum() > 0
