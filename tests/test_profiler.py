"""Profiler bridge tests (reference surface: python/mxnet/profiler.py).

A trace of real work must produce a loadable capture directory and an
aggregate-stats table naming device ops — the workflow that diagnosed
the round-3 MFU issues.
"""
import glob
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.autograd as ag


@pytest.mark.slow   # ~13s on 1 CPU (tier-1 budget); the lane-
# classification and pause/resume tests keep fast coverage
def test_profiler_capture_and_dumps(tmp_path):
    from mxnet_tpu import profiler
    from mxnet_tpu.gluon import nn

    out = str(tmp_path / "prof")
    profiler.set_config(filename=out, aggregate_stats=True)
    assert profiler.state() == "stop"

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.RandomState(0).randn(16, 12))
    with ag.pause():
        net(x)  # warm up outside the capture

    profiler.set_state("run")
    assert profiler.state() == "run"
    assert profiler.scopes_enabled()
    with profiler.scope("bench_region"):
        with ag.pause():
            for _ in range(3):
                y = net(x)
        float(y.sum().asnumpy())
    profiler.set_state("stop")
    assert not profiler.scopes_enabled()

    files = glob.glob(os.path.join(out, "plugins", "profile", "**", "*"),
                      recursive=True)
    assert any(f.endswith(".trace.json.gz") for f in files), files

    table = profiler.dumps()
    assert "Total(us)" in table
    stats = profiler.dumps(format_="dict")
    assert isinstance(stats, dict) and len(stats) > 0
    # every record is (total_us, count) with positive counts
    for name, (total, count) in stats.items():
        assert count > 0 and total >= 0


def test_dumps_lane_classification(tmp_path, monkeypatch):
    """Lane heuristic regression: process lanes whose name matches
    neither the device nor the host hints are 'unknown' — they must not
    be silently counted as device time (the old substring test did
    exactly that) — and lane='both' exposes totals for every class."""
    from mxnet_tpu import profiler

    out = str(tmp_path / "prof_lanes")
    trace = os.path.join(out, "plugins", "profile", "run")
    os.makedirs(trace, exist_ok=True)
    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "name": "process_name", "pid": 3,
         "args": {"name": "plugin-worker"}},     # neither hint set
        {"ph": "X", "pid": 1, "name": "fusion.1", "dur": 100.0},
        {"ph": "X", "pid": 2, "name": "memcpy", "dur": 30.0},
        {"ph": "X", "pid": 3, "name": "mystery_op", "dur": 7.0},
    ]
    import gzip as _gzip
    import json as _json
    with _gzip.open(os.path.join(trace, "x.trace.json.gz"), "wt") as f:
        _json.dump({"traceEvents": events}, f)
    profiler.set_config(filename=out)

    # device table holds ONLY the device lane (the old heuristic let
    # the unknown lane's 7us leak in)
    dev = profiler.dumps(format_="dict")
    assert dev == {"fusion": (100.0, 1)}
    both = profiler.dumps(format_="dict", lane="both")
    assert both["device"]["total_us"] == 100.0
    assert both["host"]["ops"] == {"memcpy": (30.0, 1)}
    assert both["unknown"]["ops"] == {"mystery_op": (7.0, 1)}
    assert both["unknown"]["count"] == 1
    assert profiler.dumps(format_="dict", lane="host") == \
        {"memcpy": (30.0, 1)}
    with pytest.raises(ValueError):
        profiler.dumps(lane="both")          # needs format_='dict'
    with pytest.raises(ValueError):
        profiler.dumps(format_="dict", lane="bogus")
    # a capture with no device lane falls back to host+unknown
    with _gzip.open(os.path.join(trace, "x.trace.json.gz"), "wt") as f:
        _json.dump({"traceEvents": [e for e in events
                                    if e.get("pid") != 1]}, f)
    cpu_only = profiler.dumps(format_="dict")
    assert cpu_only == {"memcpy": (30.0, 1), "mystery_op": (7.0, 1)}


def test_profiler_config_validation():
    from mxnet_tpu import profiler

    with pytest.raises(ValueError):
        profiler.set_config(not_an_option=True)
    with pytest.raises(ValueError):
        profiler.set_state("bogus")


@pytest.mark.slow   # ~19s on 1 CPU (tier-1 budget): a real capture
# window; dump/lane coverage stays fast via
# test_dumps_lane_classification, validation via the test above
def test_profiler_pause_resume_and_config_validation(tmp_path):
    from mxnet_tpu import profiler

    out = str(tmp_path / "prof2")
    profiler.set_config(filename=out)
    profiler.set_state("run")
    profiler.pause()
    assert profiler.state() == "stop"
    profiler.resume()
    assert profiler.state() == "run"
    profiler.dump(finished=True)
    assert profiler.state() == "stop"

    with pytest.raises(ValueError):
        profiler.set_state("bogus")
