"""In-program sampling + speculative decoding (ISSUE 12).

The contracts pinned here:

- the fused temperature / top-k / top-p transform matches an
  INDEPENDENT numpy reimplementation token-for-token when both see
  the same position-keyed Gumbel noise (each knob exercised alone and
  combined);
- greedy is the temperature->0 limit and recovers the BIT-EXACT raw
  argmax (no sampling arithmetic leaks into greedy decoding);
- the Gumbel-max draw actually samples the adjusted distribution
  (empirical frequencies over thousands of keyed draws);
- speculative decoding with a greedy target is BIT-IDENTICAL to
  target-only greedy decoding (the accept rule degenerates to
  argmax-agreement), and under sampling the accepted stream's
  marginal matches target-only sampling (the standard accept-rule
  guarantee, Monte-Carlo-checked at the library level);
- sampled decoding is restart-deterministic: KV-pressure preemption
  and resume reproduce the exact sampled stream (PR 8's determinism
  contract extended beyond greedy — the PRNG is a pure function of
  (seed, position));
- rejected draft KV rolls back through the strict BlockAllocator:
  accounting stays exact under sustained speculation.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.serving.llm import (  # noqa: E402
    TinyDecoder, DecoderConfig, LLMEngine, LLMServer, Sequence,
    SamplingParams, greedy_decode_reference)
from mxnet_tpu.serving.llm.sampling import (  # noqa: E402
    TAG_SAMPLE, TAG_DRAFT, row_keys, adjusted_log_probs,
    sample_tokens, sample_and_probs, spec_accept)

VOCAB = 17
BS = 8
CTX = 64


@pytest.fixture(scope="module")
def model():
    return TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=16, num_layers=2, num_heads=2,
        d_ff=32, max_context=CTX))


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(seed=0)


@pytest.fixture(scope="module")
def draft():
    return TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=8, num_layers=1, num_heads=1,
        d_ff=16, max_context=CTX))


@pytest.fixture(scope="module")
def draft_params(draft):
    return draft.init_params(seed=1)


# ------------------------------------------- numpy reference (indep) --
def _np_softmax(x):
    x = x - np.max(x)
    e = np.exp(x)
    return e / e.sum()


def _np_adjusted_log_probs(logits, temperature, top_k, top_p):
    """Independent numpy reimplementation of the transform."""
    V = len(logits)
    scaled = logits.astype(np.float64) / max(temperature, 1e-6)
    if top_k > 0:
        kth = np.sort(scaled)[::-1][min(top_k, V) - 1]
        scaled = np.where(scaled >= kth, scaled, -np.inf)
    probs = _np_softmax(np.where(np.isinf(scaled), -1e30, scaled))
    probs = np.where(np.isinf(scaled), 0.0, probs)
    sp = np.sort(probs)[::-1]
    csum = np.cumsum(sp)
    keep = (csum - sp) < top_p
    thresh = sp[keep.sum() - 1]
    scaled = np.where(probs >= thresh, scaled, -np.inf)
    finite = np.where(np.isinf(scaled), -1e30, scaled)
    lse = finite.max() + np.log(
        np.exp(finite - finite.max()).sum()) if np.any(
            ~np.isinf(scaled)) else 0.0
    out = scaled - lse
    return out


def _host_gumbel(seed, counter, tag, shape):
    kd = np.asarray(row_keys(jnp.asarray([seed], jnp.int32),
                             jnp.asarray([counter], jnp.int32), tag))[0]
    return np.asarray(jax.random.gumbel(
        jax.random.wrap_key_data(jnp.asarray(kd)), shape))


@pytest.mark.parametrize("knobs", [
    dict(temperature=0.7, top_k=0, top_p=1.0),     # temperature only
    dict(temperature=1.0, top_k=4, top_p=1.0),     # top-k only
    dict(temperature=1.0, top_k=0, top_p=0.6),     # top-p only
    dict(temperature=0.85, top_k=6, top_p=0.8),    # combined
], ids=["temp", "topk", "topp", "combined"])
def test_sample_tokens_matches_numpy_reference(knobs):
    """Fixed seed, 64 rows: the fused in-program transform + Gumbel
    argmax picks the same token as the numpy reimplementation fed the
    same noise."""
    rng = np.random.RandomState(3)
    N = 64
    logits = rng.randn(N, VOCAB).astype(np.float32) * 2.0
    seeds = np.arange(N, dtype=np.int32)
    counters = (np.arange(N, dtype=np.int32) * 7) % 23
    keys = row_keys(jnp.asarray(seeds), jnp.asarray(counters),
                    TAG_SAMPLE)
    got = np.asarray(sample_tokens(
        jnp.asarray(logits),
        jnp.full(N, knobs["temperature"], jnp.float32),
        jnp.full(N, knobs["top_k"], jnp.int32),
        jnp.full(N, knobs["top_p"], jnp.float32), keys))
    for i in range(N):
        lp = _np_adjusted_log_probs(logits[i], **knobs)
        g = _host_gumbel(int(seeds[i]), int(counters[i]), TAG_SAMPLE,
                         (VOCAB,))
        want = int(np.argmax(np.where(np.isinf(lp), -np.inf, lp) + g))
        assert int(got[i]) == want, f"row {i}: {got[i]} != {want}"


def test_greedy_is_bit_exact_argmax():
    """temperature <= 0 recovers argmax(logits) exactly, no matter
    what the other knobs say."""
    rng = np.random.RandomState(5)
    logits = rng.randn(32, VOCAB).astype(np.float32)
    keys = row_keys(jnp.zeros(32, jnp.int32),
                    jnp.arange(32, dtype=jnp.int32), TAG_SAMPLE)
    got = np.asarray(sample_tokens(
        jnp.asarray(logits), jnp.zeros(32, jnp.float32),
        jnp.full(32, 3, jnp.int32), jnp.full(32, 0.5, jnp.float32),
        keys))
    np.testing.assert_array_equal(got, np.argmax(logits, axis=-1))


def test_gumbel_draws_sample_the_adjusted_distribution():
    """Monte Carlo over 4000 keyed draws of ONE distribution: the
    empirical token frequencies match the adjusted probabilities."""
    rng = np.random.RandomState(11)
    logits = rng.randn(VOCAB).astype(np.float32) * 1.5
    N = 4000
    t, k, p = 0.9, 8, 0.9
    keys = row_keys(jnp.arange(N, dtype=jnp.int32),
                    jnp.zeros(N, jnp.int32), TAG_SAMPLE)
    toks = np.asarray(sample_tokens(
        jnp.broadcast_to(jnp.asarray(logits), (N, VOCAB)),
        jnp.full(N, t, jnp.float32), jnp.full(N, k, jnp.int32),
        jnp.full(N, p, jnp.float32), keys))
    want = np.exp(_np_adjusted_log_probs(logits, t, k, p))
    want = np.where(np.isfinite(want), want, 0.0)
    emp = np.bincount(toks, minlength=VOCAB) / N
    np.testing.assert_allclose(emp, want, atol=0.035)
    # masked tokens are never drawn
    assert set(np.flatnonzero(emp)) <= set(np.flatnonzero(want > 0))


def test_spec_accept_first_token_marginal_matches_target():
    """The accept-rule guarantee, Monte-Carlo-checked: draft proposals
    drawn from q, accept/residual per the standard rule — the FIRST
    committed token's marginal equals target-only sampling from p
    (the accepted stream is distributionally identical to target-only
    decoding, position by position)."""
    rng = np.random.RandomState(23)
    N, K = 4000, 2
    t = 1.0
    target = rng.randn(VOCAB).astype(np.float32)
    draft_logits = (target * 0.6
                    + rng.randn(VOCAB).astype(np.float32) * 0.8)
    cl = 7                          # arbitrary stream position anchor
    temp = jnp.full(N, t, jnp.float32)
    tk = jnp.zeros(N, jnp.int32)
    tp = jnp.ones(N, jnp.float32)
    seeds = jnp.arange(N, dtype=jnp.int32)
    # draft proposals: sampled from the draft's ADJUSTED dist with the
    # engine's key discipline (TAG_DRAFT at the proposal's position)
    d_toks, d_probs = [], []
    for j in range(K):
        keys_j = row_keys(seeds, jnp.full(N, cl + j, jnp.int32),
                          TAG_DRAFT)
        tj, pj = sample_and_probs(
            jnp.broadcast_to(jnp.asarray(draft_logits), (N, VOCAB)),
            temp, tk, tp, keys_j)
        d_toks.append(np.asarray(tj))
        d_probs.append(np.asarray(pj))
    d_toks = jnp.asarray(np.stack(d_toks, axis=1))
    d_probs = jnp.asarray(np.stack(d_probs, axis=1))
    ctr = jnp.full(N, cl, jnp.int32)[:, None] + jnp.arange(
        K + 1, dtype=jnp.int32)
    seeds2 = jnp.broadcast_to(seeds[:, None], (N, K + 1))
    from mxnet_tpu.serving.llm.sampling import TAG_ACCEPT
    a_keys = row_keys(seeds2[:, :K], ctr[:, :K], TAG_ACCEPT)
    s_keys = row_keys(seeds2, ctr, TAG_SAMPLE)
    toks, n_acc = spec_accept(
        jnp.broadcast_to(jnp.asarray(target), (N, K + 1, VOCAB)),
        d_toks, d_probs, jnp.full(N, K, jnp.int32), temp, tk, tp,
        a_keys, s_keys)
    first = np.asarray(toks)[:, 0]
    want = np.exp(_np_adjusted_log_probs(target, t, 0, 1.0))
    emp = np.bincount(first, minlength=VOCAB) / N
    np.testing.assert_allclose(emp, want, atol=0.035)
    # speculation actually speculated: some drafts accepted, some not
    n_acc = np.asarray(n_acc)
    assert n_acc.max() >= 1 and (n_acc < K).any()


# --------------------------------------------------- engine streams --
@pytest.mark.slow   # ~30s on 1 CPU (tier-1 budget); the
# deterministic-spec-sampled-streams and spec-accounting tests in
# this file keep fast speculative coverage
def test_spec_greedy_bit_identical_to_target_only(model, params,
                                                  draft, draft_params):
    """Greedy + speculation == greedy without speculation == the eager
    oracle, token for token, across a ragged mixed batch — and zero
    recompiles after warmup."""
    from mxnet_tpu.serving.llm.metrics import LLMStats
    stats = LLMStats(server="spec_greedy_t")
    # same (max_seqs, spec_k) as the other spec tests in this
    # module: the compiled target-step and draft programs are shared,
    # so only the first spec test pays the XLA warmup
    eng = LLMEngine(model, params, max_seqs=2, block_size=BS,
                    max_context=CTX, draft_model=draft,
                    draft_params=draft_params, spec_k=2, stats=stats)
    warm = eng.warmup()
    assert any(k.startswith("draft_t") for k in warm)
    assert any(k.startswith("step_t") for k in warm)
    rng = np.random.RandomState(9)
    cases = [(rng.randint(0, VOCAB,
                          size=int(rng.randint(1, 25))).tolist(),
              int(rng.randint(1, 14))) for _ in range(6)]
    seqs = []
    with serving.CompileCounter() as cc:
        for prompt, n in cases:
            s = Sequence(prompt, n)
            seqs.append(s)
            eng.add(s)
        steps = 0
        while eng.has_work():
            eng.step()
            steps += 1
            assert steps < 2000
    assert cc.count == 0, f"{cc.count} recompiles under speculation"
    for (prompt, n), s in zip(cases, seqs):
        ref = greedy_decode_reference(model, params, prompt, n)
        assert s.output_tokens() == ref, f"seq {s.seq_id} diverged"
    assert eng.cache.allocator.num_used == 0
    eng.cache.check(live_block_ids=[])
    # speculation actually accelerated commits: accepted drafts mean
    # multi-token steps, so dispatches < tokens generated
    snap = stats.snapshot()
    assert snap["spec_accepted"] > 0
    assert snap["decode_steps"] < snap["tokens_generated"]


@pytest.mark.slow   # ~32s on 1 CPU (tier-1 budget): two full spec
# warmups; spec-sampled coverage stays fast via the accept-rate pin
# above, spec_rollback below, and test_llm_spmd's mixed spec traffic
def test_spec_sampled_stream_is_deterministic(model, params, draft,
                                              draft_params):
    """Same seeds, two independent spec engines: identical sampled
    streams (the PRNG is a pure function of (seed, position) on both
    the draft and target sides)."""
    def run():
        eng = LLMEngine(model, params, max_seqs=2, block_size=BS,
                        max_context=CTX, draft_model=draft,
                        draft_params=draft_params, spec_k=2)
        eng.warmup()
        out = []
        for i, temp in enumerate((0.8, 1.2)):
            s = Sequence([3, 1, 4, 1], 12,
                         sampling=SamplingParams(temperature=temp,
                                                 top_k=6, seed=100 + i))
            out.append(s)
            eng.add(s)
        while eng.has_work():
            eng.step()
        assert eng.cache.allocator.num_used == 0
        return [s.output_tokens() for s in out]

    a, b = run(), run()
    assert a == b
    assert all(len(t) == 12 for t in a)


@pytest.mark.slow   # ~19s on 1 CPU (tier-1 budget); kv-pressure
# preemption-resume in test_llm_serving keeps fast coverage
def test_sampled_preemption_resumes_exact_stream(model, params):
    """Restart determinism EXTENDED TO SAMPLING (the PR 8 contract):
    a pool too small for every sequence forces restart-based
    preemption; the position-keyed PRNG must resume each sampled
    stream bit-identically to an unpressured run."""
    def make_seqs():
        rng = np.random.RandomState(5)
        seqs = []
        for i in range(3):
            prompt = rng.randint(0, VOCAB,
                                 size=int(rng.randint(4, 12))).tolist()
            seqs.append(Sequence(prompt, 25, sampling=SamplingParams(
                temperature=1.0, top_k=0, top_p=0.9, seed=7 * i + 1)))
        return seqs

    def run(one_at_a_time):
        # the SAME pool both ways (one compiled program set): batched
        # admission overflows it and preempts; one-at-a-time never
        # feels pressure — the unpressured reference stream
        eng = LLMEngine(model, params, max_seqs=3, block_size=BS,
                        max_context=CTX, num_blocks=11)  # 10 usable
        eng.warmup()
        seqs = make_seqs()
        preempts = steps = 0
        waves = ([[s] for s in seqs] if one_at_a_time else [seqs])
        for wave in waves:
            for s in wave:
                eng.add(s)
            while eng.has_work():
                preempts += sum(1 for k, _ in eng.step()
                                if k == "preempted")
                steps += 1
                assert steps < 3000
        assert eng.cache.allocator.num_used == 0
        eng.cache.check(live_block_ids=[])
        return [s.output_tokens() for s in seqs], preempts

    pressured, preempts = run(one_at_a_time=False)
    free_run, _ = run(one_at_a_time=True)
    assert preempts >= 1, "pool was sized to force preemption"
    assert pressured == free_run


def test_spec_rollback_keeps_block_accounting_exact(model, params):
    """An adversarial draft (random params — most proposals rejected)
    under sustained speculation: rejected draft KV must roll back
    through the strict allocator every step; the pool ends exactly
    empty and the accept telemetry shows real rejections."""
    bad_draft = TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=8, num_layers=1, num_heads=1,
        d_ff=16, max_context=CTX))
    from mxnet_tpu.serving.llm.metrics import LLMStats
    stats = LLMStats(server="spec_acct_t")
    # spec_k matches the module's other spec engines so the target
    # step programs are shared; the adversarial draft still drives
    # sustained rejections at K=2
    eng = LLMEngine(model, params, max_seqs=2, block_size=BS,
                    max_context=CTX, draft_model=bad_draft,
                    draft_params=bad_draft.init_params(seed=99),
                    spec_k=2, stats=stats)
    eng.warmup()
    seqs = []
    for i in range(4):
        s = Sequence([1 + i, 2, 3], 20)
        seqs.append(s)
        eng.add(s)
    steps = 0
    while eng.has_work():
        eng.step()
        steps += 1
        assert steps < 2000
        eng.cache.check(live_block_ids=[
            s.block_ids for s in eng.scheduler.running()])
    snap = stats.snapshot()
    assert snap["spec_proposed"] > 0
    assert snap["spec_accepted"] < snap["spec_proposed"]
    assert eng.cache.allocator.num_used == 0
    # the streams still match the oracle exactly (greedy target)
    for i, s in enumerate(seqs):
        ref = greedy_decode_reference(model, params, [1 + i, 2, 3], 20)
        assert s.output_tokens() == ref


def test_sampling_params_validate():
    """The knobs validate at construction (server-independent)."""
    with pytest.raises(ValueError):
        SamplingParams(temperature=-0.1)
    with pytest.raises(ValueError):
        SamplingParams(top_k=-1)
    with pytest.raises(ValueError):
        SamplingParams(top_p=0.0)


@pytest.mark.slow   # ~15s on 1 CPU (tier-1 budget): its own server
# warmup; sampled-stream determinism stays fast at the engine level
# (test_llm_spmd tp=1 bit-exact greedy AND sampled)
def test_sampling_through_server_and_validation(model, params):
    """SamplingParams ride submit()/generate() (dict form too)."""
    srv = LLMServer(model, params, name="sampling_t", max_seqs=2,
                    block_size=BS, max_context=CTX)
    srv.warmup()
    srv.start()
    ref = greedy_decode_reference(model, params, [2, 7, 1], 6)
    greedy = srv.generate([2, 7, 1], 6, timeout=30)
    assert greedy.tokens == ref          # default stays bit-exact greedy
    a = srv.generate([2, 7, 1], 6, timeout=30,
                     sampling=dict(temperature=1.1, seed=3))
    b = srv.generate([2, 7, 1], 6, timeout=30,
                     sampling=SamplingParams(temperature=1.1, seed=3))
    srv.shutdown()
    assert a.tokens == b.tokens          # same seed -> same stream
    assert len(a.tokens) == 6
