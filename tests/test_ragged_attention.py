"""Ragged/paged attention parity + KV block allocator accounting.

The kernel contract (ISSUE 8): attention over block-table-indirected
paged KV for a batch of different-length sequences must match the
dense oracle ``ops/flash_attention.py:attention_reference`` on every
ragged length mix — including block-boundary edges (len = block_size
- 1, block_size, block_size + 1) and fragmented (non-contiguous,
shuffled) block tables — on BOTH paths (gather-based jnp reference and
the Pallas kernel in interpret mode). The block allocator must never
leak or double-free across randomized admit/evict schedules.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu.ops.flash_attention import attention_reference  # noqa: E402
from mxnet_tpu.ops.ragged_attention import (  # noqa: E402
    ragged_attention_reference, ragged_paged_attention)
from mxnet_tpu.serving.llm.kv_cache import (  # noqa: E402
    BlockAllocator, PagedKVCache, NoFreeBlocksError,
    BlockAccountingError, NULL_BLOCK)
from mxnet_tpu.serving.bucketing import (  # noqa: E402
    BucketSpec, pad_to_bucket)

BS = 8          # block size
H, D = 2, 16    # heads, head dim


def _paged_case(lens, num_blocks=64, seed=0, fragment=True):
    """Build a paged cache holding one ragged batch: returns
    (q, k_pages, v_pages, block_tables, kv_lens, per-seq dense k/v)."""
    rng = np.random.RandomState(seed)
    S = len(lens)
    MB = max(-(-int(t) // BS) for t in lens)
    k_pages = np.zeros((num_blocks, BS, H, D), np.float32)
    v_pages = np.zeros((num_blocks, BS, H, D), np.float32)
    tables = np.full((S, MB), NULL_BLOCK, np.int32)
    # fragmented, non-contiguous allocation: shuffle the pool so no
    # sequence's blocks are adjacent or ordered (dedicated RNG so the
    # q/k/v draws below are identical for fragment=True/False)
    pool = list(range(1, num_blocks))
    if fragment:
        np.random.RandomState(seed + 1000).shuffle(pool)
    it = iter(pool)
    dense = []
    q = rng.randn(S, H, D).astype(np.float32)
    for i, t in enumerate(lens):
        t = int(t)
        k_seq = rng.randn(t, H, D).astype(np.float32)
        v_seq = rng.randn(t, H, D).astype(np.float32)
        dense.append((k_seq, v_seq))
        nb = -(-t // BS)
        for j in range(nb):
            b = next(it)
            tables[i, j] = b
            chunk = k_seq[j * BS:(j + 1) * BS]
            k_pages[b, :len(chunk)] = chunk
            chunk = v_seq[j * BS:(j + 1) * BS]
            v_pages[b, :len(chunk)] = chunk
    return (q, k_pages, v_pages, tables,
            np.asarray(lens, np.int32), dense)


def _oracle(q, dense):
    """Per-sequence dense attention via the flash oracle."""
    outs = []
    for i, (k_seq, v_seq) in enumerate(dense):
        o = attention_reference(
            jnp.asarray(q[i][None, :, None, :]),          # (1, H, 1, D)
            jnp.asarray(k_seq.transpose(1, 0, 2)[None]),  # (1, H, t, D)
            jnp.asarray(v_seq.transpose(1, 0, 2)[None]))
        outs.append(np.asarray(o)[0, :, 0, :])
    return np.stack(outs)


# block-boundary edges around BS plus interior/multi-block lengths
EDGE_MIXES = [
    [BS - 1, BS, BS + 1],
    [1, BS - 1, 2 * BS, 2 * BS + 1, 3 * BS - 1],
    [5, 11, 17, 24],
]


@pytest.mark.parametrize("lens", EDGE_MIXES, ids=["edges", "multi", "mix"])
@pytest.mark.parametrize("path", ["reference", "pallas"])
def test_parity_vs_dense_oracle(lens, path):
    q, kp, vp, bt, kl, dense = _paged_case(lens)
    want = _oracle(q, dense)
    got = ragged_paged_attention(q, kp, vp, bt, kl,
                                 use_pallas=(path == "pallas"),
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(got), want,
                               rtol=2e-5, atol=2e-6)


def test_fragmented_table_equals_contiguous():
    """A shuffled block table must read identically to a contiguous
    one — the kernel sees only the table, never block adjacency."""
    lens = [BS + 3, 2 * BS, 3]
    q, kp, vp, bt, kl, dense = _paged_case(lens, fragment=True, seed=3)
    q2, kp2, vp2, bt2, kl2, dense2 = _paged_case(lens, fragment=False,
                                                 seed=3)
    a = np.asarray(ragged_paged_attention(q, kp, vp, bt, kl))
    b = np.asarray(ragged_paged_attention(q2, kp2, vp2, bt2, kl2))
    np.testing.assert_array_equal(a, b)


def test_pallas_matches_reference_path_bitwise_inputs():
    """Both paths over the SAME buffers: allclose at f32 ulp level."""
    lens = [2, BS, 19]
    q, kp, vp, bt, kl, _ = _paged_case(lens, seed=7)
    ref = np.asarray(ragged_attention_reference(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(bt), jnp.asarray(kl)))
    pal = np.asarray(ragged_paged_attention(
        q, kp, vp, bt, kl, use_pallas=True, interpret=True))
    np.testing.assert_allclose(ref, pal, rtol=1e-5, atol=1e-6)


def test_garbage_in_unreferenced_blocks_is_invisible():
    """Stale KV beyond kv_len and in never-referenced blocks must not
    leak into any output — the masking contract preemption relies on."""
    lens = [5, 9]
    q, kp, vp, bt, kl, dense = _paged_case(lens, seed=11)
    base = np.asarray(ragged_paged_attention(q, kp, vp, bt, kl))
    kp2, vp2 = kp.copy(), vp.copy()
    # poison the null block, every free block, and the tail slots of
    # each sequence's last block
    used = set(bt.ravel().tolist()) - {NULL_BLOCK}
    for b in range(kp.shape[0]):
        if b not in used:
            kp2[b] = 1e6
            vp2[b] = -1e6
    for i, t in enumerate(lens):
        last = bt[i, (t - 1) // BS]
        kp2[last, t % BS or BS:] = 1e6
        vp2[last, t % BS or BS:] = -1e6
    got = np.asarray(ragged_paged_attention(q, kp2, vp2, bt, kl))
    np.testing.assert_array_equal(base, got)


# ------------------------------------------ multi-token chunk shape --


def _chunk_case(kv_lens, q_lens, num_blocks=64, seed=0):
    """A paged cache + a [S, Q, H, D] query chunk: queries are the
    LAST q_lens[i] positions of each sequence (the chunked-prefill /
    verify layout)."""
    rng = np.random.RandomState(seed)
    S = len(kv_lens)
    Q = max(q_lens)
    MB = max(-(-int(t) // BS) for t in kv_lens)
    k_pages = np.zeros((num_blocks, BS, H, D), np.float32)
    v_pages = np.zeros((num_blocks, BS, H, D), np.float32)
    tables = np.full((S, MB), NULL_BLOCK, np.int32)
    pool = list(range(1, num_blocks))
    np.random.RandomState(seed + 1000).shuffle(pool)
    it = iter(pool)
    dense = []
    q = rng.randn(S, Q, H, D).astype(np.float32)
    for i, t in enumerate(kv_lens):
        t = int(t)
        k_seq = rng.randn(t, H, D).astype(np.float32)
        v_seq = rng.randn(t, H, D).astype(np.float32)
        dense.append((k_seq, v_seq))
        for j in range(-(-t // BS)):
            b = next(it)
            tables[i, j] = b
            chunk = k_seq[j * BS:(j + 1) * BS]
            k_pages[b, :len(chunk)] = chunk
            chunk = v_seq[j * BS:(j + 1) * BS]
            v_pages[b, :len(chunk)] = chunk
    return (q, k_pages, v_pages, tables,
            np.asarray(kv_lens, np.int32),
            np.asarray(q_lens, np.int32), dense)


@pytest.mark.parametrize("path", ["reference", "pallas"])
def test_chunk_parity_vs_dense_causal_oracle(path):
    """Multi-token queries: token t of row i (absolute position
    kv_len - q_len + t) must equal single-query dense attention over
    exactly its causal prefix — across block-boundary kv lengths,
    chunk sizes from 1 (decode) to full-prefill, fragmented tables."""
    kv_lens = [13, 5, 2 * BS, BS + 1]
    q_lens = [5, 2, 1, BS + 1]       # verify-, decode- and prefill-like
    q, kp, vp, bt, kl, ql, dense = _chunk_case(kv_lens, q_lens)
    got = np.asarray(ragged_paged_attention(
        q, kp, vp, bt, kl, q_lens=ql,
        use_pallas=(path == "pallas"), interpret=True))
    for i, (k_seq, v_seq) in enumerate(dense):
        t, qn = int(kl[i]), int(ql[i])
        for tt in range(qn):
            pos = t - qn + tt
            o = attention_reference(
                jnp.asarray(q[i, tt][None, :, None, :]),
                jnp.asarray(k_seq[:pos + 1].transpose(1, 0, 2)[None]),
                jnp.asarray(v_seq[:pos + 1].transpose(1, 0, 2)[None]))
            want = np.asarray(o)[0, :, 0, :]
            np.testing.assert_allclose(got[i, tt], want,
                                       rtol=2e-5, atol=2e-6)


def test_chunk_q_len_one_matches_decode_kernel():
    """The decode shape is the Q=1 slice of the chunk shape: both
    kernels over the same buffers agree at f32 tolerance."""
    lens = [5, 11, 24]
    q, kp, vp, bt, kl, _ = _paged_case(lens, seed=13)
    dec = np.asarray(ragged_paged_attention(
        q, kp, vp, bt, kl, use_pallas=True, interpret=True))
    chk = np.asarray(ragged_paged_attention(
        q[:, None], kp, vp, bt, kl,
        q_lens=np.ones(len(lens), np.int32),
        use_pallas=True, interpret=True))[:, 0]
    np.testing.assert_allclose(dec, chk, rtol=1e-5, atol=1e-6)


def test_chunk_padded_tail_and_garbage_invisible():
    """Padded query tokens (t >= q_len) and KV garbage beyond kv_len
    must not perturb any VALID output row."""
    kv_lens = [9, 17]
    q_lens = [3, 5]
    q, kp, vp, bt, kl, ql, _ = _chunk_case(kv_lens, q_lens, seed=5)
    base = np.asarray(ragged_paged_attention(q, kp, vp, bt, kl,
                                             q_lens=ql))
    # poison everything the mask must hide: free blocks, null block,
    # tail slots past kv_len, and the padded q rows themselves
    used = set(bt.ravel().tolist()) - {NULL_BLOCK}
    kp2, vp2, q2 = kp.copy(), vp.copy(), q.copy()
    for b in range(kp.shape[0]):
        if b not in used:
            kp2[b] = 1e6
            vp2[b] = -1e6
    for i, t in enumerate(kv_lens):
        last = bt[i, (t - 1) // BS]
        kp2[last, t % BS or BS:] = 1e6
        vp2[last, t % BS or BS:] = -1e6
        q2[i, q_lens[i]:] = 1e6
    got = np.asarray(ragged_paged_attention(q2, kp2, vp2, bt, kl,
                                            q_lens=ql))
    for i, qn in enumerate(q_lens):
        np.testing.assert_array_equal(base[i, :qn], got[i, :qn])


@pytest.mark.parametrize("path", ["reference", "pallas"])
def test_flat_parity_vs_chunk_shape(path):
    """The FLAT packed layout (the engine's hot path) must agree with
    the per-row chunk shape over the same buffers: packing the valid
    tokens of every row into one [T] batch with per-token
    seq_ids/positions changes the layout, never the math."""
    from mxnet_tpu.ops.ragged_attention import ragged_flat_attention
    kv_lens = [13, 5, 2 * BS]
    q_lens = [5, 2, 1]
    q, kp, vp, bt, kl, ql, _ = _chunk_case(kv_lens, q_lens, seed=21)
    chunk = np.asarray(ragged_paged_attention(
        q, kp, vp, bt, kl, q_lens=ql,
        use_pallas=(path == "pallas"), interpret=True))
    # pack the valid tokens flat
    flat_q, sids, poss, want = [], [], [], []
    for i, qn in enumerate(q_lens):
        for t in range(qn):
            flat_q.append(q[i, t])
            sids.append(i)
            poss.append(kv_lens[i] - qn + t)
            want.append(chunk[i, t])
    got = np.asarray(ragged_flat_attention(
        np.stack(flat_q), kp, vp, bt,
        np.asarray(sids, np.int32), np.asarray(poss, np.int32),
        use_pallas=(path == "pallas"), interpret=True))
    np.testing.assert_allclose(got, np.stack(want),
                               rtol=1e-5, atol=1e-6)


def test_chunk_requires_q_lens():
    q, kp, vp, bt, kl, _ = _paged_case([5], seed=1)
    with pytest.raises(ValueError, match="q_lens"):
        ragged_paged_attention(q[:, None], kp, vp, bt, kl)


# ------------------------------------------------------- allocator --


def test_allocator_alloc_free_roundtrip():
    a = BlockAllocator(9)           # 8 usable
    assert a.num_usable == 8 and a.num_free == 8
    blocks = a.alloc(3)
    assert len(blocks) == 3 and NULL_BLOCK not in blocks
    assert a.num_used == 3 and a.occupancy() == pytest.approx(3 / 8)
    a.free(blocks)
    assert a.num_used == 0 and a.num_free == 8
    a.check()


def test_allocator_oom_is_all_or_nothing():
    a = BlockAllocator(5)           # 4 usable
    a.alloc(3)
    with pytest.raises(NoFreeBlocksError):
        a.alloc(2)
    assert a.num_free == 1          # failed alloc touched nothing
    a.check()


def test_allocator_double_free_and_null_are_errors():
    a = BlockAllocator(5)
    b = a.alloc(2)
    a.free(b)
    with pytest.raises(BlockAccountingError):
        a.free(b)                   # double free
    with pytest.raises(BlockAccountingError):
        a.free([NULL_BLOCK])        # the reserved block
    with pytest.raises(BlockAccountingError):
        a.free([99])                # out of range
    c = a.alloc(1)
    with pytest.raises(BlockAccountingError):
        a.free(c + c)               # duplicates within one call
    a.check()


def test_allocator_fuzz_1k_schedules_never_leaks():
    """Property test: across 1k random admit/evict schedules the
    allocator's {free} ∪ {used} partition stays exact — no leaked, no
    double-counted, no vanished blocks."""
    rng = np.random.RandomState(0)
    a = BlockAllocator(33)          # 32 usable
    live = []                       # list of allocated block-id lists
    for step in range(1000):
        if live and (rng.rand() < 0.45 or a.num_free == 0):
            seq_blocks = live.pop(rng.randint(len(live)))
            a.free(seq_blocks)
        else:
            want = int(rng.randint(1, 6))
            if a.can_alloc(want):
                live.append(a.alloc(want))
            else:
                with pytest.raises(NoFreeBlocksError):
                    a.alloc(want)
        a.check()
        held = sum(len(b) for b in live)
        assert a.num_used == held
        assert a.num_free == a.num_usable - held
    for seq_blocks in live:
        a.free(seq_blocks)
    a.check()
    assert a.num_free == a.num_usable


def test_allocator_fuzz_1k_refcount_cow_lru_churn():
    """The ISSUE-13 extension of the schedule fuzz: 1k random steps of
    alloc / share (ref) / free / register-cacheable / LRU reclaim,
    with a shadow refcount model checked against ``check()`` and the
    partition counters every step. Sharing and caching must never
    break the exact {free, refcounted, cached} partition."""
    rng = np.random.RandomState(1)
    reclaimed = []
    a = BlockAllocator(33, reclaim_cb=reclaimed.append)
    live = []                       # per-"sequence" block-id lists
    shadow = {}                     # block -> expected refcount
    for step in range(1000):
        r = rng.rand()
        if live and r < 0.30:
            # retire one sequence: decref every block it owns
            seq_blocks = live.pop(rng.randint(len(live)))
            a.free(seq_blocks)
            for b in seq_blocks:
                shadow[b] -= 1
                if shadow[b] == 0:
                    del shadow[b]
        elif live and r < 0.45:
            # a "prefix hit": a new sequence refs an existing block
            donor = live[rng.randint(len(live))]
            b = donor[rng.randint(len(donor))]
            a.ref(b)
            shadow[b] += 1
            live.append([b])
        elif live and r < 0.55:
            # register a random live block in the "prefix index"
            donor = live[rng.randint(len(live))]
            a.mark_cacheable(donor[rng.randint(len(donor))])
        else:
            want = int(rng.randint(1, 6))
            if a.can_alloc(want):
                got = a.alloc(want)     # may reclaim LRU cached blocks
                live.append(got)
                for b in got:
                    assert b not in shadow      # reclaim gave it back
                    shadow[b] = 1
            else:
                with pytest.raises(NoFreeBlocksError):
                    a.alloc(want)
        a.check()
        assert a._ref == shadow
        assert a.num_used == len(shadow)
        assert (a.num_used + a.num_cached
                + (a.num_free - a.num_cached)) == a.num_usable
    # a reclaimed block must have been handed out again, never leaked
    for seq_blocks in live:
        a.free(seq_blocks)
    a.check()
    assert a.num_used == 0
    assert a.num_free == a.num_usable


def test_allocator_ref_and_cache_lifecycle():
    """Directed coverage of the sharing lifecycle: ref of free blocks
    is an error, cached blocks revive through ref(), reclaim fires the
    callback and drops LRU-oldest first."""
    dropped = []
    a = BlockAllocator(5, reclaim_cb=dropped.append)   # 4 usable
    b1, b2 = a.alloc(2)
    with pytest.raises(BlockAccountingError):
        a.ref(99)
    a.ref(b1)                       # shared
    assert a.refcount(b1) == 2 and a.num_shared == 1
    a.free([b1])
    assert a.refcount(b1) == 1 and a.num_shared == 0
    with pytest.raises(BlockAccountingError):
        a.mark_cacheable(77)        # not allocated
    a.mark_cacheable(b1)
    a.mark_cacheable(b2)
    a.free([b1])                    # -> cached LRU (oldest)
    a.free([b2])                    # -> cached LRU (newest)
    assert a.num_cached == 2 and a.num_used == 0
    assert a.num_free == a.num_usable    # cached = reclaimable
    a.ref(b2)                       # hit revives from the LRU
    assert a.refcount(b2) == 1 and a.num_cached == 1
    got = a.alloc(3)                # must reclaim b1 (LRU) + 2 free
    assert dropped == [b1]
    assert b1 in got
    a.check()
    with pytest.raises(BlockAccountingError):
        a.free([b1, b1])            # duplicate in one call


def test_paged_cache_check_refcount_aware():
    """check(live_block_ids) validates the refcounted ownership
    multiset exactly: legal sharing passes, drifted refcounts and
    leaks raise."""
    c = PagedKVCache(num_layers=1, num_heads=2, head_dim=4,
                     block_size=8, num_blocks=9, max_context=32,
                     prefix_cache=True)
    b1, b2 = c.allocator.alloc(2)
    c.allocator.ref(b1)
    assert c.check(live_block_ids=[[b1, b2], [b1]])
    with pytest.raises(BlockAccountingError):
        c.check(live_block_ids=[[b1, b2]])      # refcount drift
    with pytest.raises(BlockAccountingError):
        c.check(live_block_ids=[[b1, b1], [b1], [b2]])  # dup in one seq
    # registered + fully released blocks are CACHED capacity, not leaks
    c.register("h1", b1)
    c.allocator.free([b1])          # one reference per call: a
    c.allocator.free([b1])          # sequence never owns a block twice
    with pytest.raises(BlockAccountingError):
        c.allocator.free([b1])      # double free past zero
    c.allocator.free([b2])
    assert c.check(live_block_ids=[])
    assert c.stats()["blocks_cached"] == 1
    assert c.prefix_get("h1") == b1


def test_paged_cache_table_row_and_sizing():
    c = PagedKVCache(num_layers=2, num_heads=2, head_dim=4,
                     block_size=8, num_blocks=9, max_context=32)
    assert c.max_blocks_per_seq == 4
    assert c.blocks_for(1) == 1 and c.blocks_for(8) == 1
    assert c.blocks_for(9) == 2
    row = c.table_row([5, 3])
    assert row.tolist() == [5, 3, NULL_BLOCK, NULL_BLOCK]
    assert c.k_pages.shape == (2, 9, 8, 2, 4)
    st = c.stats()
    assert st["blocks_free"] == 8 and st["occupancy"] == 0.0


# ------------------------------------------- shared bucketing spec --


def test_bucket_spec_shared_pow2_discipline():
    """The refactored BucketSpec is the one bucket implementation both
    serving paths use: pow2 sizes, smallest-fit pick, zero-pad."""
    spec = BucketSpec.pow2(8)
    assert spec.buckets == [1, 2, 4, 8]
    assert spec.pick(3) == 4
    rows = np.ones((3, 5), np.float32)
    padded, bucket = spec.pad(rows)
    assert bucket == 4 and padded.shape == (4, 5)
    np.testing.assert_array_equal(padded[3:], 0)
    assert spec.waste(3) == pytest.approx(0.25)
    assert [b for b, _ in spec.warmup_shapes((5,))] == [1, 2, 4, 8]


def test_bucket_spec_page_aligned_length_axis():
    """The LLM prefill variant: pow2 buckets rounded up to block
    multiples, padding along the LENGTH axis."""
    spec = BucketSpec.pow2(64, multiple_of=16)
    assert spec.buckets == [16, 32, 64]
    toks = np.arange(21, dtype=np.int32)
    padded, bucket = spec.pad(toks)
    assert bucket == 32 and padded.shape == (32,)
    np.testing.assert_array_equal(padded[:21], toks)
    np.testing.assert_array_equal(padded[21:], 0)
    # axis-general padding (prefill pads axis 0 of a 1-D prompt; a
    # batched caller pads axis 1)
    x = np.ones((2, 3), np.float32)
    assert pad_to_bucket(x, 4, axis=1).shape == (2, 4)
    with pytest.raises(ValueError):
        pad_to_bucket(x, 2, axis=1)
