"""2-bit gradient compression with error feedback.

Reference: src/kvstore/gradient_compression.h:52 (threshold quantizer to
{-t, 0, +t}, 16 values per word, per-worker residual), kvstore.py
set_gradient_compression. The multi-process packed-payload reduce is
exercised in tests/test_distributed.py; here: wire format, quantizer
semantics, error feedback, the kvstore push path, and convergence.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.kvstore import compression as gc


def test_pack_unpack_roundtrip():
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    comp = gc.TwoBitCompression(0.5)
    codes = jnp.asarray(rng.randint(0, 3, 1003), jnp.uint8)
    packed = comp.pack(codes)
    assert packed.dtype == jnp.int32
    assert packed.shape[0] == -(-1003 // 16)      # 16 values per word
    out = comp.unpack(packed, 1003)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(codes))


def test_quantizer_semantics_and_residual():
    import jax.numpy as jnp
    comp = gc.TwoBitCompression(1.0)
    g = jnp.asarray(np.array([2.5, 0.3, -0.9, -1.0, 1.0, 0.0], np.float32))
    res = jnp.zeros(6, jnp.float32)
    deq, new_res = comp.roundtrip(g, res)
    np.testing.assert_allclose(np.asarray(deq), [1, 0, 0, -1, 1, 0])
    # residual keeps exactly what quantization dropped
    np.testing.assert_allclose(np.asarray(new_res),
                               np.asarray(g) - np.asarray(deq), rtol=1e-6)


def test_error_feedback_is_unbiased_over_time():
    """Pushing the same gradient repeatedly must transmit its full mass:
    sum of dequantized outputs -> N * g as N grows (the whole point of
    the residual, gradient_compression.h docstring)."""
    import jax.numpy as jnp
    comp = gc.TwoBitCompression(0.5)
    g = jnp.asarray(np.array([0.2, -0.07, 0.45, -0.3], np.float32))
    res = jnp.zeros(4, jnp.float32)
    total = np.zeros(4, np.float32)
    n = 200
    for _ in range(n):
        deq, res = comp.roundtrip(g, res)
        total += np.asarray(deq)
    np.testing.assert_allclose(total / n, np.asarray(g), atol=0.51 / n)


def test_create_validates_params():
    assert gc.create(None) is None
    comp = gc.create({"type": "2bit", "threshold": 0.25})
    assert comp.threshold == 0.25
    with pytest.raises(ValueError):
        gc.create({"type": "1bit"})
    with pytest.raises(ValueError):
        gc.create({"type": "2bit", "bogus": 1})


def test_kvstore_push_applies_compression_per_worker():
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    assert kv.gradient_compression is not None
    kv.init(0, nd.zeros((4,)))
    v1 = nd.array(np.array([2.0, 0.4, -1.5, 0.0], np.float32))
    v2 = nd.array(np.array([0.9, 1.1, -0.2, -3.0], np.float32))
    kv.push(0, [v1, v2])
    out = nd.zeros((4,))
    kv.pull(0, out=out)
    # oracle: each worker quantized independently (zero residuals), then sum
    expect = np.array([1, 0, -1, 0], np.float32) + \
        np.array([0, 1, 0, -1], np.float32)
    np.testing.assert_allclose(out.asnumpy(), expect)
    # second push consumes the residuals kept per worker slot
    kv.push(0, [v1, v2])
    out2 = nd.zeros((4,))
    kv.pull(0, out=out2)
    # worker1 residual [1, .4, -.5, 0] + v1 = [3,.8,-2,0] -> [1,0,-1,0](x?)
    # compute oracle explicitly
    comp = gc.TwoBitCompression(1.0)
    import jax.numpy as jnp
    r1 = jnp.asarray(v1.asnumpy()) - jnp.asarray([1, 0, -1, 0.])
    r2 = jnp.asarray(v2.asnumpy()) - jnp.asarray([0, 1, 0, -1.])
    d1, _ = comp.roundtrip(jnp.asarray(v1.asnumpy()) + r1, jnp.zeros(4))
    d2, _ = comp.roundtrip(jnp.asarray(v2.asnumpy()) + r2, jnp.zeros(4))
    np.testing.assert_allclose(out2.asnumpy(),
                               np.asarray(d1) + np.asarray(d2))


def test_compressed_training_converges():
    """SGD through compressed grads + error feedback still drives a
    quadratic to its optimum (the reference's acceptance property)."""
    import jax.numpy as jnp
    comp = gc.TwoBitCompression(0.5)
    target = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
    w = jnp.zeros(4)
    res = jnp.zeros(4)
    for _ in range(300):
        g = w - jnp.asarray(target)          # grad of 0.5*|w - target|^2
        deq, res = comp.roundtrip(g, res)
        w = w - 0.2 * deq
    np.testing.assert_allclose(np.asarray(w), target, atol=0.05)
