"""Saved-model backward compatibility + large-tensor guarantees.

Reference analogues:
- model_backwards_compatibility_check/ — old checkpoints must keep
  loading in newer builds. tests/assets/golden_r4_*.{params,json} were
  written by round 4's serializers and are committed; every later build
  must load them bit-exactly and reproduce the recorded outputs.
- tests/nightly/test_large_array.py — int64/large-extent correctness.
  17 GB arrays don't fit this box, so the assertions here cover the
  parts that need no materialization (shape arithmetic via eval_shape)
  plus >2^31 index VALUES under the x64 context.
"""
import os

import jax
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn

ASSETS = os.path.join(os.path.dirname(__file__), "assets")


def test_golden_nd_params_load():
    d = nd.load(os.path.join(ASSETS, "golden_r4_nd.params"))
    assert set(d) == {"weight", "bias", "step"}
    assert d["weight"].shape == (4, 3)
    assert d["step"].asnumpy().tolist() == [7]
    rng = np.random.RandomState(42)
    np.testing.assert_allclose(d["weight"].asnumpy(),
                               rng.randn(4, 3).astype(np.float32))


def test_golden_gluon_params_load_and_reproduce():
    net = nn.HybridSequential(prefix="g_")
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.load_parameters(os.path.join(ASSETS, "golden_r4_gluon.params"))
    x = nd.array(np.load(os.path.join(ASSETS, "golden_r4_gluon_in.npy")))
    want = np.load(os.path.join(ASSETS, "golden_r4_gluon_out.npy"))
    import mxnet_tpu.autograd as ag
    with ag.pause():
        got = net(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)


def test_golden_module_checkpoint_load_and_reproduce():
    sym, args, auxs = mx.model.load_checkpoint(
        os.path.join(ASSETS, "golden_r4_module"), 0)
    mod = mx.mod.Module(sym, context=mx.context.current_context())
    x = nd.array(np.load(os.path.join(ASSETS, "golden_r4_gluon_in.npy")))
    mod.bind(data_shapes=[("data", (2, 5))], for_training=False)
    mod.set_params(args, auxs)
    from mxnet_tpu.io.io import DataBatch
    mod.forward(DataBatch(data=[x]), is_train=False)
    want = np.load(os.path.join(ASSETS, "golden_r4_module_out.npy"))
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(), want,
                               rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# large tensors
# ---------------------------------------------------------------------------

def test_large_shape_arithmetic_no_overflow():
    """Shape plumbing must survive >2^32-element logical shapes; XLA's
    eval_shape does the math without allocating."""
    big = 2**32 + 6
    out = jax.eval_shape(lambda x: x.sum(axis=0),
                         jax.ShapeDtypeStruct((big, 2), np.float32))
    assert out.shape == (2,)
    out2 = jax.eval_shape(
        lambda x: x.reshape(2**16, -1)[:4, :4],
        jax.ShapeDtypeStruct((big - 6,), np.float32))
    assert out2.shape == (4, 4)
    # symbol-level inference over a big batch dim
    s = mx.sym.var("data")
    f = mx.sym.FullyConnected(s, num_hidden=4, name="fc")
    _, outs, _ = f.infer_shape(data=(big, 3))
    assert outs == [(big, 4)]


def test_int64_index_values_beyond_2_31():
    """>2^31 index VALUES round-trip exactly under the x64 context
    (reference large-array support is the int64 build; TPU-native code
    keeps int32 on-device and goes x64 only where values demand it)."""
    try:
        enable_x64 = jax.enable_x64
    except AttributeError:  # pre-0.6 jax: experimental namespace
        from jax.experimental import enable_x64
    with enable_x64(True):
        big = np.int64(2**31 + 123)
        a = nd.array(np.asarray([big, big + 1], np.int64))
        assert a.asnumpy().dtype == np.int64
        assert a.asnumpy().tolist() == [2**31 + 123, 2**31 + 124]
        assert int((a + 1).asnumpy()[1]) == 2**31 + 125
