"""End-to-end smoke of the north-star protocol driver:
examples/train_imagenet.py — symbolic ResNet-50 + Module.fit +
MXDataIter("ImageRecordIter") over .rec files + kvstore.

Reference protocol: example/image-classification/train_imagenet.py:1
(+ common/fit.py:150). The reference's CI equivalent trains a small
net on synthetic rec files; here we pack a tiny JPEG dataset and run
the actual driver main().
"""
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "examples"))


def _make_rec(path_prefix, n, num_classes, rng):
    rec = recordio.MXIndexedRecordIO(path_prefix + ".idx",
                                     path_prefix + ".rec", "w")
    for i in range(n):
        img = rng.randint(0, 255, (24, 24, 3), dtype=np.uint8)
        header = recordio.IRHeader(0, float(i % num_classes), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=90,
                                           img_fmt=".jpg"))
    rec.close()


@pytest.fixture(scope="module")
def rec_dataset(tmp_path_factory):
    d = tmp_path_factory.mktemp("imagenet_rec")
    rng = np.random.RandomState(0)
    _make_rec(str(d / "train"), 32, 4, rng)
    _make_rec(str(d / "val"), 16, 4, rng)
    return d


@pytest.mark.slow   # ~42s; the synthetic-benchmark twin below keeps
# the driver path in the fast gate (tier-1 budget, ISSUE 12)
def test_train_imagenet_resnet50_rec(rec_dataset, tmp_path):
    import train_imagenet
    prefix = str(tmp_path / "r50")
    mod = train_imagenet.main([
        "--data-train", str(rec_dataset / "train.rec"),
        "--data-val", str(rec_dataset / "val.rec"),
        "--network", "resnet", "--num-layers", "50",
        "--num-classes", "4", "--image-shape", "3,24,24",
        "--batch-size", "8", "--num-examples", "32",
        "--num-epochs", "1", "--lr", "0.01", "--lr-step-epochs", "",
        "--kv-store", "local", "--disp-batches", "2",
        "--model-prefix", prefix, "--top-k", "2",
    ])
    # checkpoint written through the user-facing callback path
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0001.params")
    # reload and score: the saved model must be usable standalone
    sym, args, auxs = mx.model.load_checkpoint(prefix, 1)
    scored = mx.mod.Module(symbol=sym, context=mx.context.current_context())
    val = mx.io.MXDataIter("ImageRecordIter",
                           path_imgrec=str(rec_dataset / "val.rec"),
                           data_shape=(3, 24, 24), batch_size=8)
    scored.bind(data_shapes=val.provide_data,
                label_shapes=val.provide_label, for_training=False)
    scored.set_params(args, auxs)
    res = scored.score(val, mx.metric.create("accuracy"))
    acc = dict(res)["accuracy"]
    assert 0.0 <= acc <= 1.0 and np.isfinite(acc)
    del mod


@pytest.mark.slow   # ~24s on 1 CPU (tier-1 budget); the
# train_imagenet.py example run in test_examples_smoke keeps the
# north-star protocol in the fast gate
def test_train_imagenet_synthetic_benchmark():
    import train_imagenet
    mod = train_imagenet.main([
        "--benchmark", "1", "--num-layers", "18", "--num-classes", "4",
        "--image-shape", "3,16,16", "--batch-size", "4",
        "--num-examples", "8", "--num-epochs", "1",
        "--lr", "0.01", "--lr-step-epochs", "", "--kv-store", "local",
    ])
    assert mod.binded and mod.params_initialized


def test_lr_scheduler_resume_offsets():
    """Resuming at epoch 60 of lr-step-epochs 30,60 must start at
    lr*factor^2 with no stale steps (reference: common/fit.py:29)."""
    import argparse
    import train_imagenet
    args = argparse.Namespace(lr=0.1, lr_factor=0.1,
                              lr_step_epochs="30,60,")  # trailing comma ok
    lr, sched = train_imagenet._lr_scheduler(args, epoch_size=100,
                                             begin_epoch=60)
    assert abs(lr - 0.001) < 1e-12
    assert sched is None  # 30 and 60 both passed, no steps remain
    lr, sched = train_imagenet._lr_scheduler(args, epoch_size=100,
                                             begin_epoch=30)
    assert abs(lr - 0.01) < 1e-12
    assert sched.step == [100 * 30]  # epoch 60 is 30 epochs away
    lr, sched = train_imagenet._lr_scheduler(args, epoch_size=100,
                                             begin_epoch=0)
    assert lr == 0.1 and sched.step == [3000, 6000]


def test_resnet_symbol_shapes():
    import train_imagenet
    for layers, img in ((18, (3, 32, 32)), (50, (3, 224, 224))):
        sym = train_imagenet.get_resnet_symbol(1000, layers, img)
        _, out_shapes, _ = sym.infer_shape(data=(2,) + img,
                                           softmax_label=(2,))
        assert out_shapes == [(2, 1000)]
