"""External operator libraries (mx.library.load).

Reference: python/mxnet/library.py MXLoadLib + example/extensions/
lib_custom_op (a user-compiled .so registering ops at runtime). Here a
real C++ plugin is compiled with g++ in the test, loaded through the
TPU-build ABI (mxnet_tpu/library.py), and its ops run from nd.* — the
row-17 "external op library" capability end to end.
"""
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")

_PLUGIN_SRC = r"""
// Minimal mxnet_tpu op library: 'plugin_scale2' (x*2) and
// 'plugin_madd' (a + b) over float32 buffers.
#include <cstring>

extern "C" {

int mxtpu_num_ops(void) { return 2; }

const char* mxtpu_op_name(int i) {
  return i == 0 ? "plugin_scale2" : "plugin_madd";
}

int mxtpu_op_infer_shape(int i, int n_in, const int* in_ndim,
                         const long* const* in_shape, long* out_shape,
                         int* out_ndim) {
  // both ops: output shape == first input's shape
  if (n_in < 1) return 1;
  *out_ndim = in_ndim[0];
  for (int d = 0; d < in_ndim[0]; ++d) out_shape[d] = in_shape[0][d];
  return 0;
}

static long numel(const long* shape, int ndim) {
  long n = 1;
  for (int d = 0; d < ndim; ++d) n *= shape[d];
  return n;
}

int mxtpu_op_compute(int i, int n_in, const float** in,
                     const int* in_ndim, const long* const* in_shape,
                     float* out, const long* out_shape, int out_ndim) {
  long n = numel(out_shape, out_ndim);
  if (i == 0) {
    for (long j = 0; j < n; ++j) out[j] = in[0][j] * 2.0f;
    return 0;
  }
  if (i == 1) {
    if (n_in != 2) return 1;
    for (long j = 0; j < n; ++j) out[j] = in[0][j] + in[1][j];
    return 0;
  }
  return 2;
}

}  // extern "C"
"""


@pytest.fixture(scope="module")
def plugin(tmp_path_factory):
    d = tmp_path_factory.mktemp("plugin")
    src = d / "plugin.cpp"
    so = d / "libplugin.so"
    src.write_text(_PLUGIN_SRC)
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", str(src), "-o",
                    str(so)], check=True, capture_output=True)
    return str(so)


def test_load_and_run_plugin_ops(plugin):
    names = mx.library.load(plugin, verbose=False)
    assert names == ["plugin_scale2", "plugin_madd"]
    from mxnet_tpu import nd
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = nd.plugin_scale2(x)
    np.testing.assert_allclose(out.asnumpy(),
                               np.arange(6).reshape(2, 3) * 2.0)
    y = nd.array(np.ones((2, 3), np.float32))
    np.testing.assert_allclose(
        nd.plugin_madd(x, y).asnumpy(),
        np.arange(6).reshape(2, 3) + 1.0)
    assert plugin in mx.library.loaded_libraries()


def test_plugin_op_composes_with_framework_ops(plugin):
    mx.library.load(plugin, verbose=False)
    from mxnet_tpu import nd
    x = nd.array(np.full((3,), 2.0, np.float32))
    out = nd.relu(nd.plugin_scale2(x) - 3.0)
    np.testing.assert_allclose(out.asnumpy(), [1.0, 1.0, 1.0])


def test_load_rejects_non_plugin(tmp_path):
    bogus = tmp_path / "not_a_plugin.so"
    bogus.write_bytes(b"\x7fELF garbage")
    with pytest.raises(OSError):
        mx.library.load(str(bogus), verbose=False)
