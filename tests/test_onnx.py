"""ONNX export/import roundtrip.

Reference: python/mxnet/contrib/onnx/ (mx2onnx export_model:33,
onnx2mx import_model:32). The serializer is the repo's own protobuf
wire codec, so these tests pin (a) structural validity of the emitted
ModelProto and (b) numeric equality through a full export->import
roundtrip — the same acceptance the reference's onnx backend tests use.
"""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.onnx import _proto as P


def _mlp():
    d = mx.sym.var("data")
    f1 = mx.sym.FullyConnected(d, name="fc1", num_hidden=16)
    a = mx.sym.Activation(f1, act_type="relu", name="r1")
    f2 = mx.sym.FullyConnected(a, name="fc2", num_hidden=4)
    return mx.sym.softmax(f2, name="sm")


def _convnet():
    d = mx.sym.var("data")
    c = mx.sym.Convolution(d, name="c1", kernel=(3, 3), num_filter=8,
                           pad=(1, 1))
    b = mx.sym.BatchNorm(c, name="bn1")
    a = mx.sym.Activation(b, act_type="relu")
    p = mx.sym.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max")
    fl = mx.sym.Flatten(p)
    return mx.sym.FullyConnected(fl, name="fc", num_hidden=5)


def _init_params(sym, **shapes):
    arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
    rng = np.random.RandomState(0)
    params = {}
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        if name in shapes or name.endswith("_label"):
            continue
        params[name] = mx.nd.array(
            rng.randn(*shape).astype(np.float32) * 0.3)
    for name, shape in zip(sym.list_auxiliary_states(), aux_shapes):
        params[name] = mx.nd.array(
            np.abs(rng.randn(*shape).astype(np.float32)) + 0.5)
    return params


def _run(sym, params, x):
    feed = {"data": mx.nd.array(x)}
    feed.update(params)
    out = sym.eval_dict(feed)
    if isinstance(out, list):
        out = out[0]
    return out.asnumpy()


def test_export_structure_decodes():
    sym = _mlp()
    params = _init_params(sym, data=(2, 10))
    blob = mx.onnx.export_model(sym, params, {"data": (2, 10)})
    model = P.decode(blob)
    assert model[1][0] == 8                       # ir_version
    assert model[2][0] == b"mxnet_tpu"            # producer
    graph = P.decode(model[7][0])
    ops = [P.decode(n)[4][0].decode() for n in graph[1]]
    assert "Gemm" in ops and "Relu" in ops and "Softmax" in ops
    # every initializer names a param
    inits = {P.decode(t)[8][0].decode() for t in graph[5]}
    assert set(params) <= inits
    opset = P.decode(model[8][0])
    assert opset[2][0] == 17  # LayerNormalization floor


def test_roundtrip_mlp(tmp_path):
    sym = _mlp()
    params = _init_params(sym, data=(2, 10))
    x = np.random.RandomState(1).randn(2, 10).astype(np.float32)
    want = _run(sym, params, x)

    path = str(tmp_path / "mlp.onnx")
    mx.onnx.export_model(sym, params, {"data": (2, 10)},
                         onnx_file_path=path)
    sym2, args2, aux2 = mx.onnx.import_model(path)
    got = _run(sym2, {**args2, **aux2}, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_roundtrip_convnet():
    sym = _convnet()
    params = _init_params(sym, data=(2, 3, 12, 12))
    x = np.random.RandomState(2).randn(2, 3, 12, 12).astype(np.float32)
    want = _run(sym, params, x)

    blob = mx.onnx.export_model(sym, params, {"data": (2, 3, 12, 12)})
    sym2, args2, aux2 = mx.onnx.import_model(blob)
    got = _run(sym2, {**args2, **aux2}, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_import_external_fixture():
    """Import a hand-authored, exporter-independent .onnx blob and pin
    its outputs (VERDICT r4 weak #5: import was previously validated
    only against this repo's own exporter). The fixture bytes are
    encoded straight from the ONNX protobuf spec by
    tests/assets/gen_external_onnx.py — torch-style value names, Gemm
    with transB/alpha/beta attributes, raw_data AND float_data tensor
    encodings."""
    here = os.path.join(os.path.dirname(__file__), "assets")
    path = os.path.join(here, "external_mlp.onnx")
    io = np.load(os.path.join(here, "external_mlp_io.npz"))

    sym, args, aux = mx.onnx.import_model(path)
    assert not aux
    assert sorted(args) == ["fc1.bias", "fc1.weight", "fc2.bias",
                            "fc2.weight"]
    feed = {"data": mx.nd.array(io["x"])}
    feed.update(args)
    out = sym.eval_dict(feed)
    if isinstance(out, list):
        out = out[0]
    np.testing.assert_allclose(out.asnumpy(), io["expected"],
                               rtol=1e-5, atol=1e-5)
