"""Optimizer/lr_scheduler/initializer/metric tests.

Modeled on the reference's tests/python/unittest/test_optimizer.py: each
optimizer step is checked against a pure-numpy reimplementation of the
update rule.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _setup(shape=(4, 7), dtype="float32", seed_w=1.0):
    w_np = np.random.uniform(-1, 1, shape).astype(dtype) * seed_w
    g_np = np.random.uniform(-1, 1, shape).astype(dtype)
    return w_np, g_np


def _run_steps(optimizer, w_np, g_np, nsteps=3):
    w = mx.nd.array(w_np)
    state = optimizer.create_state(0, w)
    for _ in range(nsteps):
        optimizer.update(0, w, mx.nd.array(g_np), state)
    return w.asnumpy()


def test_sgd_matches_numpy():
    w_np, g_np = _setup()
    lr, wd, mom = 0.1, 0.01, 0.9
    got = _run_steps(opt.SGD(learning_rate=lr, wd=wd, momentum=mom),
                     w_np, g_np)
    w, m = w_np.copy(), np.zeros_like(w_np)
    for _ in range(3):
        m = mom * m - lr * (g_np + wd * w)
        w = w + m
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_sgd_no_momentum():
    w_np, g_np = _setup()
    got = _run_steps(opt.SGD(learning_rate=0.1, wd=0.0), w_np, g_np, 1)
    np.testing.assert_allclose(got, w_np - 0.1 * g_np, rtol=1e-5, atol=1e-6)


def test_adam_matches_numpy():
    w_np, g_np = _setup()
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    got = _run_steps(opt.Adam(learning_rate=lr, beta1=b1, beta2=b2,
                              epsilon=eps), w_np, g_np)
    w = w_np.copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t in range(1, 4):
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g_np
        v = b2 * v + (1 - b2) * g_np ** 2
        w = w - lr_t * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_rmsprop():
    w_np, g_np = _setup()
    lr, rho, eps = 0.01, 0.9, 1e-8
    got = _run_steps(opt.RMSProp(learning_rate=lr, rho=rho, epsilon=eps),
                     w_np, g_np, 2)
    w = w_np.copy()
    n = np.zeros_like(w)
    for _ in range(2):
        n = rho * n + (1 - rho) * g_np ** 2
        w = w - lr * g_np / np.sqrt(n + eps)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_adagrad():
    w_np, g_np = _setup()
    got = _run_steps(opt.AdaGrad(learning_rate=0.1, eps=1e-7), w_np, g_np, 2)
    w = w_np.copy()
    h = np.zeros_like(w)
    for _ in range(2):
        h += g_np ** 2
        w = w - 0.1 * g_np / (np.sqrt(h) + 1e-7)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("name", ["sgd", "nag", "adam", "adamw", "adagrad",
                                  "adadelta", "adamax", "nadam", "rmsprop",
                                  "ftml", "ftrl", "lamb", "lars", "dcasgd",
                                  "sgld", "signum", "signsgd", "lbsgd",
                                  "groupadagrad", "test"])
def test_all_optimizers_step(name):
    """Every registered optimizer makes a finite update step."""
    w_np, g_np = _setup()
    kwargs = {"wd": 0.0} if name == "groupadagrad" else {}
    o = opt.create(name, **kwargs)
    w = mx.nd.array(w_np)
    state = o.create_state(0, w)
    o.update(0, w, mx.nd.array(g_np), state)
    out = w.asnumpy()
    assert np.all(np.isfinite(out))
    assert not np.allclose(out, w_np)  # something changed


def test_multi_precision_sgd():
    w_np, g_np = _setup(dtype="float16")
    o = opt.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    w = mx.nd.array(w_np, dtype="float16")
    state = o.create_state_multi_precision(0, w)
    o.update_multi_precision(0, w, mx.nd.array(g_np, dtype="float16"), state)
    assert w.dtype == np.float16
    assert state[1].dtype == np.float32  # master weights


def test_updater_state_roundtrip():
    w_np, g_np = _setup()
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(o)
    w = mx.nd.array(w_np)
    upd(0, mx.nd.array(g_np), w)
    blob = upd.get_states()
    upd2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    upd2.set_states(blob)
    assert 0 in upd2.states


def test_lr_scheduler_factor():
    from mxnet_tpu.lr_scheduler import FactorScheduler
    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25


def test_lr_scheduler_warmup():
    from mxnet_tpu.lr_scheduler import PolyScheduler
    s = PolyScheduler(max_update=100, base_lr=1.0, pwr=1, warmup_steps=10)
    assert s(0) == 0.0
    assert abs(s(5) - 0.5) < 1e-9
    v50 = s(50)
    assert 0 < v50 < 1.0


def test_lr_scheduler_in_optimizer():
    from mxnet_tpu.lr_scheduler import MultiFactorScheduler
    sched = MultiFactorScheduler(step=[5, 10], factor=0.1, base_lr=1.0)
    o = opt.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = mx.nd.array(np.ones((2, 2), np.float32))
    g = mx.nd.array(np.zeros((2, 2), np.float32))
    for _ in range(3):
        o.update(0, w, g, None)
    assert o._get_lr(0) == 1.0


def test_initializers():
    import mxnet_tpu.initializer as init
    for name, cls in [("xavier", init.Xavier), ("normal", init.Normal),
                      ("uniform", init.Uniform), ("zeros", init.Zero),
                      ("ones", init.One), ("orthogonal", init.Orthogonal),
                      ("msraprelu", init.MSRAPrelu)]:
        arr = np.empty((8, 4), np.float32)
        i = init.create(name)
        assert isinstance(i, cls)
        i("fc1_weight", arr)
        assert np.all(np.isfinite(arr))
    arr = np.empty((8,), np.float32)
    init.Xavier()("fc1_bias", arr)  # bias branch → zeros
    np.testing.assert_allclose(arr, 0)


def test_initializer_orthogonal_is_orthogonal():
    import mxnet_tpu.initializer as init
    arr = np.empty((16, 16), np.float32)
    init.Orthogonal(scale=1.0)("q_weight", arr)
    np.testing.assert_allclose(arr @ arr.T, np.eye(16), atol=1e-5)


def test_metric_accuracy():
    m = mx.metric.Accuracy()
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    name, acc = m.get()
    assert name == "accuracy"
    assert abs(acc - 2.0 / 3) < 1e-9


def test_metric_topk():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.2, 0.7], [0.5, 0.4, 0.1]])
    label = mx.nd.array([1, 1])
    m.update([label], [pred])
    _, acc = m.get()
    assert abs(acc - 1.0) < 1e-9


def test_metric_mse_f1_composite():
    comp = mx.metric.create(["mse", "mae"])
    pred = mx.nd.array([[1.0], [2.0]])
    label = mx.nd.array([[1.5], [2.5]])
    comp.update([label], [pred])
    names, values = comp.get()
    assert "mse" in names and "mae" in names
    assert abs(values[names.index("mse")] - 0.25) < 1e-6
    assert abs(values[names.index("mae")] - 0.5) < 1e-6


def test_metric_perplexity():
    m = mx.metric.Perplexity(ignore_label=None)
    pred = mx.nd.array([[0.25, 0.75], [0.5, 0.5]])
    label = mx.nd.array([1, 0])
    m.update([label], [pred])
    _, ppl = m.get()
    expected = np.exp(-(np.log(0.75) + np.log(0.5)) / 2)
    assert abs(ppl - expected) < 1e-5


def test_metric_custom():
    @mx.metric.np
    def zero_one(label, pred):
        return float((label == pred.argmax(axis=1)).mean())

    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1]])
    label = mx.nd.array([1, 1])
    zero_one.update([label], [pred])
    _, v = zero_one.get()
    assert abs(v - 0.5) < 1e-9
