"""Unified runtime observability (mxnet_tpu.observability).

Pins the contracts of the metrics substrate every subsequent perf PR
reports through:

- registry correctness: concurrent increments, fixed-edge histogram
  bucket math, valid Prometheus text exposition;
- StepTimer on a real 2-step gluon.Trainer loop (step wall time,
  data-wait vs compute split, examples counters);
- the jax.monitoring bridge (XLA compile count/duration as metrics,
  serving.compile_count parity);
- serving telemetry after the registry migration: same snapshot
  schema, counters exact, and BOUNDED memory — percentiles come from
  fixed-edge histograms, not ever-growing sample lists;
- the acceptance criterion: ONE expose() call carrying training,
  serving, resilience-checkpoint and XLA-compile metrics produced by a
  single in-process run.
"""
import json
import os
import re
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.autograd as ag
from mxnet_tpu.observability import (MetricsRegistry, StepTimer,
                                     get_registry,
                                     install_jax_monitoring_bridge)
from mxnet_tpu.observability.registry import DEFAULT_TIME_BUCKETS


# ------------------------------------------------------ registry core --

def test_counter_concurrent_increments():
    reg = MetricsRegistry()
    c = reg.counter("t_hits_total", "hits")
    def worker():
        for _ in range(1000):
            c.inc()
    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    with pytest.raises(ValueError):
        c.inc(-1)


def test_histogram_concurrent_observe():
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_seconds", "lat", buckets=(0.1, 1.0))
    def worker():
        for i in range(500):
            h.observe(0.05 if i % 2 else 0.5)
    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == 2000
    # le=0.1 bucket holds exactly the 0.05 observations
    assert h._need_default().bucket_counts()[0] == 1000


def test_histogram_bucket_math():
    reg = MetricsRegistry()
    h = reg.histogram("t_h_seconds", "h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 3.0, 9.0):
        h.observe(v)
    child = h._need_default()
    # le semantics are inclusive: 1.0 lands in the first bucket
    assert child._counts == [2, 1, 1, 1]
    assert child.bucket_counts() == [2, 3, 4, 5]   # cumulative + (+Inf)
    assert h.count == 5
    assert h.sum == pytest.approx(15.0)
    # percentiles are monotone and clamped to the observed range
    ps = [h.percentile(p) for p in (1, 25, 50, 75, 95, 99.9)]
    assert all(a <= b + 1e-12 for a, b in zip(ps, ps[1:]))
    assert ps[0] >= 0.5 - 1e-12
    assert ps[-1] <= 9.0 + 1e-12
    # empty histogram percentile is defined
    assert reg.histogram("t_empty_seconds", buckets=(1.0,)) \
        .percentile(99) == 0.0
    # interpolation never overshoots the observed range: samples
    # clustered just past a wide bucket's lower edge must not report
    # a tail half-way up the bucket
    hc = reg.histogram("t_clamp_seconds", buckets=(1.0, 100.0))
    for _ in range(100):
        hc.observe(1.5)
    assert hc.percentile(50) == pytest.approx(1.5)
    assert hc.percentile(99) == pytest.approx(1.5)


def test_histogram_memory_is_bounded():
    """The whole point of fixed-edge histograms: state size never grows
    with the number of observations."""
    reg = MetricsRegistry()
    h = reg.histogram("t_flat_seconds", "flat")
    child = h._need_default()
    h.observe(0.01)
    size_before = len(child._counts)
    for i in range(10000):
        h.observe((i % 100) / 1000.0)
    assert len(child._counts) == size_before
    assert h.count == 10001
    # no per-sample storage anywhere on the child
    for v in vars(child).values():
        assert not isinstance(v, (list, tuple)) or \
            len(v) <= len(DEFAULT_TIME_BUCKETS) + 1


def test_registry_idempotent_and_type_conflicts():
    reg = MetricsRegistry()
    a = reg.counter("t_x_total", "x")
    assert reg.counter("t_x_total") is a
    with pytest.raises(ValueError):
        reg.gauge("t_x_total")
    h = reg.histogram("t_y_seconds", buckets=(1.0, 2.0))
    assert reg.histogram("t_y_seconds") is h
    with pytest.raises(ValueError):
        reg.histogram("t_y_seconds", buckets=(1.0, 3.0))
    c = reg.counter("t_l_total", "l", ("op",))
    with pytest.raises(ValueError):
        reg.counter("t_l_total", labelnames=("other",))
    with pytest.raises(ValueError):
        c.inc()            # labeled metric needs .labels(...)
    c.labels(op="a").inc(2)
    c.labels(op="b").inc(3)
    assert c.labels(op="a").value == 2


def _parse_exposition(text):
    """Minimal independent validator of Prometheus text format."""
    name_re = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
    sample_re = re.compile(
        r"^(%s)(\{%s=\"(?:[^\"\\]|\\.)*\"(?:,%s=\"(?:[^\"\\]|\\.)*\")*\})?"
        r" ([+-]?(?:\d+\.?\d*(?:e[+-]?\d+)?|inf|nan))$"
        % (name_re, name_re, name_re), re.IGNORECASE)
    samples = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            assert re.match(r"^# (HELP|TYPE) %s .+$" % name_re, line), line
            continue
        m = sample_re.match(line)
        assert m, f"malformed exposition line: {line!r}"
        samples[(m.group(1), m.group(2) or "")] = float(m.group(3))
    return samples


def test_expose_prometheus_format():
    reg = MetricsRegistry()
    reg.counter("t_req_total", "requests\nserved", ("server",)) \
        .labels(server='a"b\\c').inc(3)
    reg.gauge("t_depth", "queue depth").set(2.5)
    h = reg.histogram("t_ms_seconds", "lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.expose()
    samples = _parse_exposition(text)
    assert samples[("t_depth", "")] == 2.5
    # escaped label survives the round trip
    assert any(n == "t_req_total" and 'a\\"b\\\\c' in l
               for n, l in samples)
    # histogram invariants: cumulative buckets, +Inf == count
    b1 = samples[("t_ms_seconds_bucket", '{le="0.1"}')]
    b2 = samples[("t_ms_seconds_bucket", '{le="1"}')]
    binf = samples[("t_ms_seconds_bucket", '{le="+Inf"}')]
    assert (b1, b2, binf) == (1, 2, 3)
    assert samples[("t_ms_seconds_count", "")] == 3
    assert samples[("t_ms_seconds_sum", "")] == pytest.approx(5.55)
    # HELP newline is escaped, not emitted raw
    assert "requests\\nserved" in text


def test_non_finite_values_do_not_break_exporters(tmp_path):
    """A diverged run (grad_norm = inf/nan) must not kill the scrape:
    expose() emits the Prometheus +Inf/NaN tokens and write_snapshot
    stays strict JSON."""
    reg = MetricsRegistry()
    reg.gauge("t_diverged").set(float("inf"))
    reg.gauge("t_nan").set(float("nan"))
    reg.counter("t_ok_total").inc(3)
    text = reg.expose()
    assert "t_diverged +Inf" in text
    assert "t_nan NaN" in text
    samples = _parse_exposition(text)
    assert samples[("t_ok_total", "")] == 3
    path = str(tmp_path / "m.jsonl")
    reg.write_snapshot(path)
    rec = json.loads(open(path).read())      # strict JSON parses
    assert rec["metrics"]["t_diverged"]["series"][0]["value"] \
        == "Infinity"
    assert float(rec["metrics"]["t_nan"]["series"][0]["value"]) != \
        float(rec["metrics"]["t_nan"]["series"][0]["value"])   # NaN


def test_snapshot_jsonl_roundtrip(tmp_path):
    reg = MetricsRegistry()
    reg.counter("t_a_total").inc(7)
    reg.histogram("t_b_seconds", buckets=(1.0,)).observe(0.5)
    path = str(tmp_path / "metrics.jsonl")
    assert reg.write_snapshot(path) == path
    reg.counter("t_a_total").inc(1)
    reg.write_snapshot(path)
    lines = [json.loads(s) for s in
             open(path).read().strip().splitlines()]
    assert len(lines) == 2
    assert lines[0]["metrics"]["t_a_total"]["series"][0]["value"] == 7
    assert lines[1]["metrics"]["t_a_total"]["series"][0]["value"] == 8
    hist = lines[1]["metrics"]["t_b_seconds"]["series"][0]
    assert hist["counts"] == [1, 1] and hist["count"] == 1
    # env-gated default: no path, no env -> no-op
    assert MetricsRegistry().write_snapshot() in (
        None, os.environ.get("MXNET_TPU_METRICS_LOG"))


# ------------------------------------------------------- step timer --

def _train_two_steps(timer):
    from mxnet_tpu.gluon import nn, Trainer
    from mxnet_tpu.gluon.loss import L2Loss
    mx.random.seed(11)
    net = nn.Dense(4)
    net.initialize()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.1})
    loss_fn = L2Loss()
    rs = np.random.RandomState(3)
    for _ in range(2):
        x = nd.array(rs.randn(8, 3).astype(np.float32))
        y = nd.array(rs.randn(8, 4).astype(np.float32))
        with timer.step(batch_size=8):
            with ag.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(8)
    return trainer


def test_steptimer_two_step_trainer_loop():
    reg = get_registry()
    steps0 = reg.counter("mxtpu_training_steps_total").value
    opt0 = reg.counter("mxtpu_training_optimizer_steps_total").value
    ex0 = reg.counter("mxtpu_training_examples_total").value
    n_step0 = reg.histogram("mxtpu_training_step_seconds").count
    n_wait0 = reg.histogram("mxtpu_training_data_wait_seconds").count
    n_comp0 = reg.histogram("mxtpu_training_compute_seconds").count

    timer = StepTimer()
    _train_two_steps(timer)

    assert reg.counter("mxtpu_training_steps_total").value - steps0 == 2
    assert reg.counter(
        "mxtpu_training_optimizer_steps_total").value - opt0 == 2
    assert reg.counter(
        "mxtpu_training_examples_total").value - ex0 == 16
    assert reg.histogram(
        "mxtpu_training_step_seconds").count - n_step0 == 2
    assert reg.histogram(
        "mxtpu_training_data_wait_seconds").count - n_wait0 == 2
    assert reg.histogram(
        "mxtpu_training_compute_seconds").count - n_comp0 == 2
    # compute + wait == step (within float tolerance), compute dominates
    # a tight loop, and the split gauges are in range
    assert reg.gauge("mxtpu_training_examples_per_sec").value > 0
    frac = reg.gauge("mxtpu_training_data_fraction").value
    assert 0.0 <= frac <= 1.0
    assert reg.histogram(
        "mxtpu_training_optimizer_step_seconds").count >= 2


def test_steptimer_failed_step_not_recorded():
    reg = get_registry()
    timer = StepTimer()
    n0 = reg.histogram("mxtpu_training_step_seconds").count
    with pytest.raises(RuntimeError):
        with timer.step(batch_size=4):
            raise RuntimeError("boom")
    assert reg.histogram("mxtpu_training_step_seconds").count == n0
    with timer.step(batch_size=4):
        pass
    assert reg.histogram("mxtpu_training_step_seconds").count == n0 + 1


def test_grad_norm_gauge_opt_in(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_METRICS_GRAD_NORM", "1")
    reg = get_registry()
    _train_two_steps(StepTimer())
    assert reg.gauge("mxtpu_training_grad_norm").value > 0


def test_estimator_default_step_timer_handler():
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.gluon.contrib.estimator.event_handler import \
        StepTimerHandler
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    mx.random.seed(5)
    net = nn.Dense(3)
    net.initialize()
    est = Estimator(net, SoftmaxCrossEntropyLoss())
    handlers = est._prepare_handlers(None, 1, None, None)
    assert any(isinstance(h, StepTimerHandler) for h in handlers)
    reg = get_registry()
    steps0 = reg.counter("mxtpu_training_steps_total").value
    rs = np.random.RandomState(0)
    data = [(rs.randn(4, 6).astype(np.float32),
             rs.randint(0, 3, (4,)).astype(np.float32))
            for _ in range(2)]
    est.fit(data, epochs=1)
    assert reg.counter("mxtpu_training_steps_total").value - steps0 == 2


# --------------------------------------------------- jax.monitoring --

def test_jax_monitoring_compile_bridge():
    import jax
    import jax.numpy as jnp
    reg = install_jax_monitoring_bridge()
    assert reg is get_registry()
    c0 = reg.counter("mxtpu_xla_compile_total").value
    d0 = reg.histogram("mxtpu_xla_compile_seconds").count

    @jax.jit
    def fresh(x):
        return x * 3.14159 + 42.0          # unique program

    fresh(jnp.ones((3, 3))).block_until_ready()
    c1 = reg.counter("mxtpu_xla_compile_total").value
    assert c1 - c0 >= 1
    assert reg.histogram("mxtpu_xla_compile_seconds").count - d0 >= 1
    assert reg.histogram("mxtpu_xla_compile_seconds").sum > 0
    # cached second call must not count
    fresh(jnp.ones((3, 3))).block_until_ready()
    assert reg.counter("mxtpu_xla_compile_total").value == c1


def test_serving_compile_count_is_bridge_view():
    from mxnet_tpu import serving
    import jax
    import jax.numpy as jnp
    reg = get_registry()
    assert serving.compile_count() == int(
        reg.counter("mxtpu_xla_compile_total").value)
    with serving.CompileCounter() as cc:
        jax.jit(lambda x: x - 7.125)(jnp.ones(4)).block_until_ready()
    assert cc.count >= 1


# ------------------------------------------------ serving telemetry --

def test_serving_stats_parity_after_migration():
    """Same snapshot schema and exact counter values as the
    pre-registry ServingStats."""
    from mxnet_tpu.serving.telemetry import ServingStats
    st = ServingStats(server="parity")
    st.record_submit()
    st.record_submit()
    st.record_submit()
    st.record_queue_depth(2)
    st.record_batch(2, 4, [0.001, 0.003], 0.002)
    st.record_batch(1, 1, [0.010], 0.004)
    st.record_failure(1)
    snap = st.snapshot()
    assert snap["requests_submitted"] == 3
    assert snap["requests_completed"] == 3
    assert snap["requests_failed"] == 1
    assert snap["batches"] == 2
    assert snap["queue_depth"] == 2
    assert snap["avg_batch_size"] == pytest.approx(1.5)
    assert snap["padded_waste"] == pytest.approx(2 / 5)
    assert snap["bucket_hits"] == {4: 1, 1: 1}
    assert snap["throughput_rps"] > 0
    for key in ("wait_ms", "latency_ms", "service_ms"):
        p = snap[key]
        assert set(p) == {"p50", "p95", "p99"}
        assert 0 <= p["p50"] <= p["p95"] <= p["p99"]
    # the same numbers are visible in the shared exposition
    text = get_registry().expose()
    assert 'mxtpu_serving_requests_submitted_total{server="parity"} 3' \
        in text
    st.reset()
    assert st.snapshot()["requests_submitted"] == 0
    assert st.snapshot()["bucket_hits"] == {}


def test_serving_stats_memory_flat_over_10k_requests():
    """Regression for the unbounded-reservoir bug: percentile state must
    not grow with sustained load."""
    from mxnet_tpu.serving.telemetry import ServingStats
    st = ServingStats(server="flood")
    st.record_batch(1, 1, [0.001], 0.001)
    hist_sizes = [len(st._wait._counts), len(st._latency._counts),
                  len(st._service._counts)]
    for i in range(10000):
        st.record_submit()
        st.record_batch(1, 1, [(i % 97) / 10000.0], 0.0005)
    assert [len(st._wait._counts), len(st._latency._counts),
            len(st._service._counts)] == hist_sizes
    # and nothing sample-shaped accumulated on the instance
    for v in vars(st).values():
        assert not isinstance(v, (list, tuple)) or len(v) < 64
    snap = st.snapshot()
    assert snap["requests_completed"] == 10001
    assert snap["latency_ms"]["p50"] <= snap["latency_ms"]["p99"]


def test_serving_stats_label_lifecycle():
    """Concurrent same-named servers are isolated behind #N suffixes;
    a RESTARTED server (previous instance collected) re-claims its
    label with fresh children, so dashboards keyed on the name follow
    the restart instead of a frozen series."""
    import gc
    from mxnet_tpu.serving.telemetry import ServingStats
    a = ServingStats(server="lifecycle")
    a.record_batch(2, 2, [0.001, 0.001], 0.001)
    b = ServingStats(server="lifecycle")      # a still alive -> suffix
    assert a._server == "lifecycle" and b._server == "lifecycle#2"
    b.record_batch(1, 1, [0.001], 0.001)
    assert a.snapshot()["requests_completed"] == 2   # untouched by b
    del a
    gc.collect()
    c = ServingStats(server="lifecycle")      # holder gone -> re-claim
    assert c._server == "lifecycle"
    snap = c.snapshot()                       # fresh, not frozen at 2
    assert snap["requests_completed"] == 0
    assert snap["bucket_hits"] == {}


# ------------------------------------------------------ acceptance --

def test_single_exposition_covers_four_subsystems(tmp_path):
    """One in-process run -> one expose() carrying training, serving,
    resilience-checkpoint and XLA-compile series (the PR's acceptance
    criterion), all in valid Prometheus text format."""
    from mxnet_tpu import serving
    install_jax_monitoring_bridge()
    trainer = _train_two_steps(StepTimer())
    trainer.save_state(str(tmp_path / "run"))
    trainer.restore_state(str(tmp_path / "run"))
    srv = serving.ModelServer(lambda b: b * 2.0, buckets=[1, 2],
                              max_delay_ms=1.0, item_shape=(3,),
                              dtype="float32").start()
    srv.warmup()
    futs = [srv.submit(np.full(3, i, np.float32)) for i in range(4)]
    for f in futs:
        f.result(timeout=60)
    srv.shutdown()

    text = get_registry().expose()
    samples = _parse_exposition(text)        # valid exposition
    for prefix in ("mxtpu_training_", "mxtpu_serving_",
                   "mxtpu_resilience_checkpoint_", "mxtpu_xla_compile_"):
        assert any(name.startswith(prefix) for name, _ in samples), \
            f"no {prefix}* series in exposition"
    # and the checkpoint write/restore instrumentation saw real IO
    reg = get_registry()
    assert reg.counter(
        "mxtpu_resilience_checkpoint_writes_total").value >= 1
    assert reg.counter(
        "mxtpu_resilience_checkpoint_restores_total").value >= 1
    assert reg.counter(
        "mxtpu_resilience_checkpoint_bytes_written_total").value > 0
    assert reg.histogram(
        "mxtpu_resilience_checkpoint_write_seconds").count >= 1


def test_kvstore_allreduce_metrics():
    from mxnet_tpu import kvstore as kvs
    reg = get_registry()
    kv = kvs.create("local")
    v = nd.array(np.ones((4, 5), np.float32))
    kv.init(0, v)
    c = reg.counter("mxtpu_kvstore_allreduce_total", labelnames=("store",))
    b = reg.counter("mxtpu_kvstore_allreduce_bytes_total",
                    labelnames=("store",))
    c0 = c.labels(store="device").value
    b0 = b.labels(store="device").value
    kv.push(0, [nd.array(np.ones((4, 5), np.float32)),
                nd.array(np.ones((4, 5), np.float32))])
    assert c.labels(store="device").value - c0 == 1
    assert b.labels(store="device").value - b0 == 2 * 4 * 5 * 4
    assert reg.histogram("mxtpu_kvstore_allreduce_seconds",
                         labelnames=("store",)) \
        .labels(store="device").count >= 1


def test_retry_metrics():
    from mxnet_tpu.resilience.retry import call_with_retry, RetryError
    reg = get_registry()
    retries = reg.counter("mxtpu_resilience_retry_total",
                          labelnames=("op",))
    exhausted = reg.counter("mxtpu_resilience_retry_exhausted_total",
                            labelnames=("op",))
    r0 = retries.labels(op="obs.test").value
    e0 = exhausted.labels(op="obs.test").value
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("blip")
        return "ok"

    assert call_with_retry(flaky, op="obs.test", max_attempts=4,
                           sleep=lambda s: None) == "ok"
    assert retries.labels(op="obs.test").value - r0 == 2
    with pytest.raises(RetryError):
        call_with_retry(lambda: (_ for _ in ()).throw(OSError("x")),
                        op="obs.test", max_attempts=2,
                        sleep=lambda s: None)
    assert exhausted.labels(op="obs.test").value - e0 == 1
