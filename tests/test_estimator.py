"""Estimator + event handlers.

Reference: python/mxnet/gluon/contrib/estimator/ (Estimator.fit:326,
evaluate:272, StoppingHandler, MetricHandler, ValidationHandler,
CheckpointHandler, EarlyStoppingHandler, GradientUpdateHandler).
"""
import os

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib.estimator import (
    Estimator, EpochEnd, BatchEnd, CheckpointHandler, EarlyStoppingHandler,
    LoggingHandler, StoppingHandler)


def _toy_data(n=64, seed=0):
    """Linearly separable 2-class problem."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float32)
    return x, y


def _loader(x, y, batch=16):
    return [(nd.array(x[i:i + batch]), nd.array(y[i:i + batch]))
            for i in range(0, len(x), batch)]


def _make_est(lr=0.1, seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize()
    est = Estimator(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        trainer=gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": lr, "momentum": 0.9}))
    return est


def test_fit_converges_and_runs_handlers_in_order():
    x, y = _toy_data(128)
    data = _loader(x, y)
    est = _make_est()

    events = []

    class Recorder(EpochEnd, BatchEnd):
        def batch_end(self, estimator, *a, **kw):
            events.append("batch")

        def epoch_end(self, estimator, *a, **kw):
            events.append("epoch")

    est.fit(data, epochs=5, event_handlers=[Recorder()])
    # 8 batches per epoch, 5 epochs
    assert events.count("epoch") == 5
    assert events.count("batch") == 40
    res = est.evaluate(_loader(x, y))
    assert res["accuracy"] > 0.9, res


def test_fit_batches_limit():
    x, y = _toy_data(64)
    est = _make_est()
    counted = []

    class Count(BatchEnd):
        def batch_end(self, estimator, *a, **kw):
            counted.append(1)

    est.fit(_loader(x, y), batches=3, event_handlers=[Count()])
    assert len(counted) == 3


def test_validation_handler_runs_each_epoch():
    x, y = _toy_data(64)
    xv, yv = _toy_data(32, seed=1)
    est = _make_est()
    est.fit(_loader(x, y), val_data=_loader(xv, yv), epochs=3)
    # val metrics were refreshed by the per-epoch validation run
    assert est.val_loss_metric.get()[1] > 0


def test_checkpoint_handler(tmp_path):
    x, y = _toy_data(64)
    est = _make_est()
    ck = CheckpointHandler(str(tmp_path), model_prefix="toy",
                           epoch_period=1, max_checkpoints=2)
    est.fit(_loader(x, y), epochs=3, event_handlers=[ck])
    files = sorted(f for f in os.listdir(tmp_path)
                   if f.endswith(".params"))
    assert len(files) == 2, files            # rotation keeps newest 2
    # checkpoint loads back into a fresh net
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net2.load_parameters(os.path.join(tmp_path, files[-1]))


def test_early_stopping_handler():
    x, y = _toy_data(64)
    est = _make_est(lr=0.0)     # lr=0: loss can never improve
    early = EarlyStoppingHandler(monitor=est.train_loss_metric,
                                 patience=1)
    est.fit(_loader(x, y), epochs=50, event_handlers=[early])
    assert early.stopped_epoch is not None
    assert early.stopped_epoch < 10      # stopped long before 50
