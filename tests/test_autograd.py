"""Autograd tests (reference: tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x)
        z = (y * 2).sum()
    z.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * np.exp(x.asnumpy()), atol=1e-5)


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_multiple_uses():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x * 3
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [2 * 2 + 3])


def test_pause():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = x * 100  # not recorded
        w = y + z.detach()
    w.backward()
    assert np.allclose(x.grad.asnumpy(), [2.0])


def test_grad_fn():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
    g = autograd.grad(y, [x])
    assert np.allclose(g[0].asnumpy(), 3 * x.asnumpy() ** 2)


def test_training_modes():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training()
        assert autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()
    with autograd.predict_mode():
        assert not autograd.is_training()


def test_retain_graph():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = x.grad.asnumpy().copy()
    y.backward()
    assert np.allclose(g1, [6.0])


def test_grad_add_req():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    with autograd.record():
        y = x * 2
    y.backward()
    with autograd.record():
        y = x * 3
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [5.0])


def test_mark_variables():
    x = nd.array([2.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * 5
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [5.0])
