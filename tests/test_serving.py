"""mxnet_tpu.serving: dynamic batching + recompile-free bucketing.

The serving contract pinned here (ISSUE 2 acceptance criteria):

- batching/padding is NUMERICALLY INVISIBLE: outputs of requests served
  through the batcher are bit-identical to unbatched Predictor calls
  run through the same bucket program (rows of a batched forward are
  independent; the pad rows change nothing);
- after ``warmup()`` the jit cache holds every bucket, so ragged
  concurrent traffic causes ZERO XLA recompiles (asserted with the
  jax.monitoring-backed compile counter);
- graceful drain — explicit shutdown or ``PreemptionGuard`` signal —
  loses no in-flight request: every submitted Future resolves;
- stats counters are consistent with the submitted load.
"""
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import nd, serving
from mxnet_tpu.gluon import nn
import mxnet_tpu.autograd as ag
from mxnet_tpu.resilience import PreemptionGuard

ITEM = (8,)


def _net():
    mx.random.seed(7)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="tanh"), nn.Dense(4))
    net.initialize()
    return net


@pytest.fixture(scope="module")
def predictor():
    net = _net()
    x = np.zeros((1,) + ITEM, np.float32)
    with ag.pause():
        net(nd.array(x))
    blob = mx.deploy.export_predictor(net, x, poly_batch=True)
    return mx.deploy.load_predictor(blob)


# ------------------------------------------------------- bucket math --
def test_bucket_sizes_powers_of_two():
    assert serving.bucket_sizes(8) == [1, 2, 4, 8]
    assert serving.bucket_sizes(1) == [1]
    # non-power-of-two max batch becomes the top bucket
    assert serving.bucket_sizes(6) == [1, 2, 4, 6]
    assert serving.bucket_sizes(8, min_bucket=4) == [4, 8]
    with pytest.raises(ValueError):
        serving.bucket_sizes(0)


def test_pick_bucket_and_padding():
    buckets = [1, 2, 4, 8]
    assert serving.pick_bucket(1, buckets) == 1
    assert serving.pick_bucket(3, buckets) == 4
    assert serving.pick_bucket(8, buckets) == 8
    with pytest.raises(ValueError):
        serving.pick_bucket(9, buckets)
    rows = np.ones((3, 5), np.float32)
    padded = serving.pad_batch(rows, 4)
    assert padded.shape == (4, 5)
    np.testing.assert_array_equal(padded[:3], rows)
    np.testing.assert_array_equal(padded[3:], 0)
    assert serving.pad_batch(rows, 3) is rows      # full bucket: no copy
    assert serving.waste_fraction(3, 4) == pytest.approx(0.25)


# ------------------------------------------------- batching queue ----
def test_queue_coalesces_up_to_max_batch():
    q = serving.MicroBatchQueue()
    for i in range(5):
        q.submit(i)
    batch = q.get_batch(max_batch=4, max_delay_s=0.001)
    assert [r.x for r in batch] == [0, 1, 2, 3]
    batch = q.get_batch(max_batch=4, max_delay_s=0.001)
    assert [r.x for r in batch] == [4]


def test_queue_waits_at_most_max_delay():
    q = serving.MicroBatchQueue()
    q.submit("only")
    t0 = time.monotonic()
    batch = q.get_batch(max_batch=8, max_delay_s=0.05)
    took = time.monotonic() - t0
    assert len(batch) == 1 and took < 2.0


def test_queue_close_rejects_and_signals_empty():
    q = serving.MicroBatchQueue()
    q.close()
    with pytest.raises(serving.ServerClosed):
        q.submit(1)
    assert q.get_batch(4, 0.001) == []


# ------------------------------------------------ (a) exactness ------
def test_batched_bit_identical_to_unbatched_predictor(predictor):
    """Requests coalesced into micro-batches must be bit-identical to
    unbatched Predictor calls through the same bucket program."""
    B = 4
    srv = serving.ModelServer(predictor, buckets=[B], max_delay_ms=5.0)
    srv.start()
    srv.warmup()
    X = np.random.RandomState(0).randn(10, *ITEM).astype(np.float32)
    futs = [srv.submit(r) for r in X]
    got = [f.result(timeout=60) for f in futs]
    srv.shutdown()
    for r, g in zip(X, got):
        ref = np.asarray(
            predictor.predict(serving.pad_batch(r[None], B)))[0]
        np.testing.assert_array_equal(g, ref)


def test_same_inputs_same_outputs_any_batching(predictor):
    """One input submitted many times must yield one answer no matter
    how the batcher groups it. Bit-exactness holds per bucket program
    (pinned above); ACROSS buckets XLA may vectorize differently, so
    cross-bucket agreement is ulp-level, not bitwise."""
    srv = serving.ModelServer(predictor, buckets=[1, 2, 4],
                              max_delay_ms=2.0).start()
    srv.warmup()
    x = np.random.RandomState(1).randn(*ITEM).astype(np.float32)
    futs = [srv.submit(x) for _ in range(17)]
    outs = [f.result(timeout=60) for f in futs]
    srv.shutdown()
    st = srv.stats()
    # batching actually happened (one batch would mean no coalescing
    # under 17 concurrent submits — delay/batch knobs broken)
    assert st["batches"] >= 1
    for o in outs[1:]:
        np.testing.assert_allclose(outs[0], o, rtol=1e-5, atol=1e-7)


# ---------------------------------------- (b) zero recompiles --------
def test_zero_recompiles_after_warmup_ragged_load(predictor):
    srv = serving.ModelServer(predictor, buckets=[1, 2, 4],
                              max_delay_ms=1.0).start()
    srv.warmup()
    X = np.random.RandomState(2).randn(40, *ITEM).astype(np.float32)
    with serving.CompileCounter() as cc:
        # ragged arrival: alternating bursts of 1..6 concurrent
        # requests so every bucket gets exercised
        i = 0
        while i < len(X):
            burst = (i % 6) + 1
            rows = X[i:i + burst]
            futs = [srv.submit(r) for r in rows]
            for f in futs:
                f.result(timeout=60)
            i += burst
    srv.shutdown()
    assert cc.count == 0, \
        f"{cc.count} XLA recompiles after warmup (buckets leak shapes)"
    hits = srv.stats()["bucket_hits"]
    assert sum(hits.values()) == srv.stats()["batches"]


def test_warmup_compiles_each_bucket_once(predictor):
    # fresh server over the SAME predictor: programs already cached, so
    # even warmup must not compile again — the cache is per jitted
    # callable, which lives on the Predictor, not the server
    srv = serving.ModelServer(predictor, buckets=[1, 2, 4],
                              max_delay_ms=1.0).start()
    srv.warmup()
    with serving.CompileCounter() as cc:
        srv.warmup()
    srv.shutdown()
    assert cc.count == 0
    assert predictor.jit_cache_size() >= 3


# ------------------------------------------------- (c) drain ---------
def test_shutdown_drains_every_inflight_request(predictor):
    srv = serving.ModelServer(predictor, buckets=[1, 2, 4],
                              max_delay_ms=200.0).start()
    srv.warmup()
    X = np.random.RandomState(3).randn(9, *ITEM).astype(np.float32)
    futs = [srv.submit(r) for r in X]
    # long max_delay: without the drain flush these would sit waiting
    srv.shutdown(drain=True)
    outs = [f.result(timeout=60) for f in futs]
    assert len(outs) == len(X)
    for r, g in zip(X, outs):
        assert np.isfinite(g).all()
    with pytest.raises(serving.ServerClosed):
        srv.submit(X[0])


def test_preemption_guard_drain(predictor):
    """SIGUSR1 through PreemptionGuard: admission closes, queued work
    completes, nothing is lost."""
    guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        srv = serving.ModelServer(predictor, buckets=[1, 2, 4],
                                  max_delay_ms=200.0).start()
        srv.warmup()
        srv.attach_preemption_guard(guard, poll_s=0.01)
        X = np.random.RandomState(4).randn(7, *ITEM).astype(np.float32)
        futs = [srv.submit(r) for r in X]
        os.kill(os.getpid(), signal.SIGUSR1)
        outs = [f.result(timeout=60) for f in futs]
        assert len(outs) == len(X)
        deadline = time.monotonic() + 10
        while srv.running and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(serving.ServerClosed):
            srv.submit(X[0])
    finally:
        guard.uninstall()


def test_shutdown_without_drain_fails_queued(predictor):
    srv = serving.ModelServer(predictor, buckets=[4],
                              max_delay_ms=500.0).start()
    srv.warmup()
    # a single queued request (delay keeps it waiting) then abort
    fut = srv.submit(np.zeros(ITEM, np.float32))
    srv.shutdown(drain=False)
    with pytest.raises(serving.ServerClosed):
        fut.result(timeout=60)


# ------------------------------------------------- (d) stats ---------
def test_stats_consistent_with_load(predictor, tmp_path):
    log_path = str(tmp_path / "events.jsonl")
    srv = serving.ModelServer(predictor, buckets=[1, 2, 4],
                              max_delay_ms=1.0, event_log=log_path)
    srv.start()
    srv.warmup()
    N = 30
    X = np.random.RandomState(5).randn(N, *ITEM).astype(np.float32)
    errs = []

    def client(rows):
        try:
            for r in rows:
                srv.predict(r, timeout=60)
        except Exception as exc:             # pragma: no cover
            errs.append(exc)

    threads = [threading.Thread(target=client, args=(X[i::3],))
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    srv.shutdown()
    st = srv.stats()
    assert st["requests_submitted"] == N
    assert st["requests_completed"] == N
    assert st["requests_failed"] == 0
    assert sum(st["bucket_hits"].values()) == st["batches"]
    assert 1 <= st["batches"] <= N
    assert 0.0 <= st["padded_waste"] < 1.0
    assert st["latency_ms"]["p50"] <= st["latency_ms"]["p95"] \
        <= st["latency_ms"]["p99"]
    assert st["throughput_rps"] > 0
    # per-batch rows in the event log reconcile with the counters
    import json
    with open(log_path) as f:
        events = [json.loads(line) for line in f]
    kinds = {e["event"] for e in events}
    assert {"start", "warmup", "batch", "stop"} <= kinds
    batch_rows = sum(e["n"] for e in events if e["event"] == "batch")
    assert batch_rows == N


def test_stats_report_compile_count(predictor):
    srv = serving.ModelServer(predictor, buckets=[1]).start()
    st = srv.stats()
    srv.shutdown()
    assert "compiles" in st and st["compiles"] >= 0
    assert st["buckets"] == [1]


# ------------------------------------------------- backends ----------
def test_serve_directly_from_hybrid_block():
    net = _net()
    x = np.random.RandomState(6).randn(*ITEM).astype(np.float32)
    with net.serve(example_input=x, buckets=[1, 2, 4],
                   max_delay_ms=1.0) as srv:
        srv.warmup()
        with ag.pause():
            want = net(nd.array(
                serving.pad_batch(x[None], 1))).asnumpy()[0]
        got = srv.predict(x, timeout=60)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_fixed_shape_artifact_guard():
    net = _net()
    x = np.zeros((2,) + ITEM, np.float32)
    with ag.pause():
        net(nd.array(x))
    blob = mx.deploy.export_predictor(net, x)      # poly_batch=False
    pred = mx.deploy.load_predictor(blob)
    assert not pred.poly_batch
    with pytest.raises(ValueError):
        serving.ModelServer(pred, buckets=[1, 2, 4])
    # matching single bucket is allowed
    srv = serving.ModelServer(pred, buckets=[2], max_delay_ms=1.0)
    srv.start()
    srv.warmup()
    out = srv.predict(x[0], timeout=60)
    assert out.shape == (4,)
    srv.shutdown()


def test_request_shape_validation(predictor):
    srv = serving.ModelServer(predictor, buckets=[1]).start()
    with pytest.raises(ValueError):
        srv.submit(np.zeros((2,) + ITEM, np.float32))  # batch dim
    srv.shutdown()


def test_env_var_config(predictor, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_SERVE_MAX_BATCH", "16")
    monkeypatch.setenv("MXNET_TPU_SERVE_MAX_DELAY_MS", "7.5")
    srv = serving.ModelServer(predictor)
    assert srv.max_batch_size == 16
    assert srv.buckets == [1, 2, 4, 8, 16]
    assert srv.max_delay_s == pytest.approx(0.0075)
    monkeypatch.setenv("MXNET_TPU_SERVE_BUCKETS", "2,8")
    srv2 = serving.ModelServer(predictor)
    assert srv2.buckets == [2, 8]
    assert srv2.max_batch_size == 8


def test_overload_env_var_config(predictor, monkeypatch):
    """The overload knobs resolve constructor arg > env var > default
    like every other serving knob."""
    srv = serving.ModelServer(predictor, buckets=[1])
    assert srv.max_queue is None            # default: unbounded
    assert srv.default_deadline_ms is None  # default: no deadline
    monkeypatch.setenv("MXNET_TPU_SERVE_MAX_QUEUE", "32")
    monkeypatch.setenv("MXNET_TPU_SERVE_DEADLINE_MS", "250")
    srv2 = serving.ModelServer(predictor, buckets=[1])
    assert srv2.max_queue == 32
    assert srv2._queue.max_depth == 32
    assert srv2.default_deadline_ms == 250.0
    srv3 = serving.ModelServer(predictor, buckets=[1], max_queue=4,
                               deadline_ms=50)
    assert srv3.max_queue == 4 and srv3.default_deadline_ms == 50.0


def test_typed_errors_exported_under_one_base(predictor):
    """Satellite: serving-side errors share the exported ServingError
    base (and stay RuntimeError-compatible for old callers)."""
    srv = serving.ModelServer(predictor, buckets=[1]).start()
    srv.shutdown()
    with pytest.raises(serving.ServingError):
        srv.submit(np.zeros(ITEM, np.float32))
    with pytest.raises(RuntimeError):       # legacy contract
        srv.submit(np.zeros(ITEM, np.float32))
