"""Bounded-shape execution of dynamic-output ops under jit
(SURVEY §7: the TPU answer to the reference's in-executor runtime shape
re-inference, src/executor/graph_executor.cc:1497-1530)."""
import jax
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np as mnp, npx


def test_unique_nonzero_traceable_under_bound():
    x = mnp.array([3.0, 1.0, 3.0, 0.0, 2.0, 1.0])

    # drive through the mx.np surface inside jit
    def g(a):
        with npx.dynamic_shape_bound(8):
            u = mnp.unique(mnp.ndarray(a))
            (nz,) = mnp.nonzero(mnp.ndarray(a))
        return u._data, nz._data

    u, nz = jax.jit(g)(np.asarray(x.asnumpy()))
    assert u.shape == (8,) and nz.shape == (8,)
    # padded with the repeated max/fill; leading entries are the truth
    np.testing.assert_array_equal(np.asarray(u)[:4], [0.0, 1.0, 2.0, 3.0])
    np.testing.assert_array_equal(sorted(np.asarray(nz)[:4]),
                                  [0, 1, 2, 4])


def test_unique_without_bound_stays_eager_only():
    x = mnp.array([1.0, 2.0, 2.0])
    u = mnp.unique(x)            # eager: exact dynamic shape
    assert u.shape == (2,)

    def f(a):
        return mnp.unique(mnp.ndarray(a))._data

    with pytest.raises(Exception):   # concretization error: honest fail
        jax.jit(f)(np.asarray([1.0, 2.0, 2.0]))


def test_boolean_mask_bounded_matches_eager():
    data = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    index = mx.nd.array(np.array([1.0, 0.0, 1.0, 0.0]))
    exact = mx.nd.contrib.boolean_mask(data, index).asnumpy()
    assert exact.shape == (2, 3)

    with npx.dynamic_shape_bound(4):
        padded = mx.nd.contrib.boolean_mask(data, index).asnumpy()
    assert padded.shape == (4, 3)
    np.testing.assert_array_equal(padded[:2], exact)
    np.testing.assert_array_equal(padded[2:], 0.0)

    # and it traces
    def f(d, i):
        with npx.dynamic_shape_bound(4):
            from mxnet_tpu.ops.registry import get
            return get("_contrib_boolean_mask").impl(d, i)

    out = jax.jit(f)(data._data, index._data)
    np.testing.assert_array_equal(np.asarray(out), padded)


def test_shape_bucket_bounds_recompiles():
    assert npx.shape_bucket(1) == 8
    assert npx.shape_bucket(8) == 8
    assert npx.shape_bucket(9) == 16
    assert npx.shape_bucket(1000) == 1024
    # a varying workload compiles one program per bucket, not per size
    traces = {"n": 0}

    def f(a, size):
        traces["n"] += 1
        return mnp.unique(mnp.ndarray(a), size=size)._data

    jf = jax.jit(f, static_argnums=1)
    for n in (3, 5, 7, 9, 12, 15):
        a = np.arange(n, dtype=np.float32)
        out = jf(np.pad(a, (0, 16 - n)), npx.shape_bucket(n))
        assert out.shape[0] in (8, 16)
    assert traces["n"] == 2   # two buckets -> two traces


def test_nested_bounds_innermost_wins():
    with npx.dynamic_shape_bound(16):
        with npx.dynamic_shape_bound(4):
            assert npx.current_shape_bound() == 4
            u = mnp.unique(mnp.array([5.0, 5.0, 1.0]))
            assert u.shape == (4,)
        assert npx.current_shape_bound() == 16
    assert npx.current_shape_bound() is None


def test_ndarray_nonzero_method_honors_bound():
    def g(a):
        with npx.dynamic_shape_bound(6):
            return mnp.ndarray(a).nonzero()[0]._data

    out = jax.jit(g)(np.array([0.0, 3.0, 0.0, 5.0]))
    assert out.shape == (6,)
    assert sorted(np.asarray(out)[:2].tolist()) == [1, 3]


def test_boolean_mask_bounded_no_nan_from_inf():
    """Padding must SELECT zeros, not multiply by zero (0*inf = nan)."""
    from mxnet_tpu.ops.registry import get
    impl = get("_contrib_boolean_mask").impl
    data = np.array([[np.inf, 1.0]], np.float32)
    out = np.asarray(impl(data, np.array([1.0]), size=3))
    assert out.shape == (3, 2)
    assert np.isinf(out[0, 0]) and np.all(out[1:] == 0.0)
    assert not np.isnan(out).any()
