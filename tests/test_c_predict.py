"""Embed-from-C inference: a plain C program (no Python source) loads a
deploy artifact through libmxtpu_predict.so and must reproduce the
Python-side prediction exactly.

Reference analogue: include/mxnet/c_predict_api.h +
tests/cpp/ (the reference's C predict API is exercised from C++ image
classification predictors). The C host below is compiled by the test
with g++, links ONLY the shim, and exchanges raw float32 files — if it
runs, the artifact is servable from a C/C++ application with no
user-written Python.
"""
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

C_HOST = r"""
#include "mxtpu_predict.h"
#include <stdio.h>
#include <stdlib.h>
#include <math.h>

static float *read_f32(const char *path, long *n_out) {
  FILE *f = fopen(path, "rb");
  if (!f) { fprintf(stderr, "open %s failed\n", path); exit(2); }
  fseek(f, 0, SEEK_END);
  long bytes = ftell(f);
  fseek(f, 0, SEEK_SET);
  float *buf = (float *)malloc(bytes);
  if (fread(buf, 1, bytes, f) != (size_t)bytes) exit(2);
  fclose(f);
  *n_out = bytes / (long)sizeof(float);
  return buf;
}

int main(int argc, char **argv) {
  if (argc != 4) { fprintf(stderr, "usage: host art in exp\n"); return 2; }
  MXTpuPredictorHandle h;
  if (MXTpuPredCreate(argv[1], &h) != 0) {
    fprintf(stderr, "create: %s\n", MXTpuPredGetLastError());
    return 3;
  }
  const int64_t *ishape; int indim;
  if (MXTpuPredGetInputShape(h, &ishape, &indim) != 0) return 3;
  long want = 1;
  for (int i = 0; i < indim; ++i) want *= ishape[i];
  long n_in, n_exp;
  float *in = read_f32(argv[2], &n_in);
  float *exp_out = read_f32(argv[3], &n_exp);
  if (n_in != want) { fprintf(stderr, "input count\n"); return 4; }
  if (MXTpuPredForward(h, in, (size_t)n_in) != 0) {
    fprintf(stderr, "forward: %s\n", MXTpuPredGetLastError());
    return 5;
  }
  int num;
  if (MXTpuPredGetNumOutputs(h, &num) != 0 || num < 1) return 6;
  const int64_t *oshape; int ondim;
  if (MXTpuPredGetOutputShape(h, 0, &oshape, &ondim) != 0) return 6;
  long n_out = 1;
  for (int i = 0; i < ondim; ++i) n_out *= oshape[i];
  if (n_out != n_exp) { fprintf(stderr, "output count\n"); return 6; }
  float *out = (float *)malloc(n_out * sizeof(float));
  if (MXTpuPredGetOutput(h, 0, out, (size_t)n_out) != 0) {
    fprintf(stderr, "get: %s\n", MXTpuPredGetLastError());
    return 7;
  }
  double max_diff = 0;
  for (long i = 0; i < n_out; ++i) {
    double d = fabs((double)out[i] - (double)exp_out[i]);
    if (d > max_diff) max_diff = d;
  }
  printf("max_abs_diff %g\n", max_diff);
  /* second Forward on the same handle must also work (serving loop) */
  if (MXTpuPredForward(h, in, (size_t)n_in) != 0) return 8;
  MXTpuPredFree(h);
  return max_diff < 1e-5 ? 0 : 9;
}
"""


@pytest.fixture(scope="module")
def artifact_and_host(tmp_path_factory):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu import native

    tmp = tmp_path_factory.mktemp("cpredict")

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(init=mx.initializer.Xavier())
    rng = np.random.RandomState(3)
    x = rng.randn(2, 8).astype(np.float32)
    art_path = str(tmp / "model.mxtpu")
    # export on the CPU backend regardless of this process's default
    # platform: the C host runs with JAX_PLATFORMS=cpu, and jax.export
    # artifacts are platform-specific
    import jax
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        mx.deploy.export_predictor(net, nd.array(x), art_path)
        expected = net(nd.array(x)).asnumpy()

    (tmp / "input.bin").write_bytes(x.tobytes())
    (tmp / "expected.bin").write_bytes(
        np.ascontiguousarray(expected, np.float32).tobytes())

    lib = native.predict_lib_path()
    host_src = tmp / "host.c"
    host_src.write_text(C_HOST)
    host_bin = tmp / "host"
    build_dir = os.path.dirname(lib)
    subprocess.run(
        ["g++", str(host_src), "-o", str(host_bin),
         "-I", os.path.dirname(native.predict_header_path()),
         "-L", build_dir, "-lmxtpu_predict",
         "-Wl,-rpath," + build_dir],
        check=True, capture_output=True)
    return tmp, host_bin, art_path


def test_c_host_matches_python(artifact_and_host):
    tmp, host_bin, art_path = artifact_and_host
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [str(host_bin), art_path, str(tmp / "input.bin"),
         str(tmp / "expected.bin")],
        capture_output=True, text=True, timeout=900, env=env)
    assert p.returncode == 0, \
        f"C host rc={p.returncode}\n{p.stdout}\n{p.stderr}"
    assert "max_abs_diff" in p.stdout


def test_c_host_reports_bad_artifact(artifact_and_host, tmp_path):
    tmp, host_bin, _ = artifact_and_host
    bogus = tmp_path / "bogus.mxtpu"
    bogus.write_bytes(b"definitely not an artifact")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [str(host_bin), str(bogus), str(tmp / "input.bin"),
         str(tmp / "expected.bin")],
        capture_output=True, text=True, timeout=900, env=env)
    assert p.returncode == 3
    assert "not an mxnet_tpu predictor artifact" in p.stderr


def test_artifact_header_is_parseable(artifact_and_host):
    # the C shim parses this header in-Python; pin the binary layout the
    # loader snippet in predict_c.cpp relies on (MAGIC + u32 + json)
    _, _, art_path = artifact_and_host
    blob = open(art_path, "rb").read()
    assert blob.startswith(b"MXTPUPRED1")
    (hlen,) = struct.unpack_from("<I", blob, 10)
    import json
    meta = json.loads(blob[14:14 + hlen].decode())
    assert meta["input_shape"] == [2, 8]
