"""Every example script must run end to end at a tiny configuration.

The reference CI runs its example/ scripts the same way
(tests/nightly/test_tutorial etc.); a broken example is a broken
user-facing surface. Each case is a real subprocess — fresh
interpreter, argparse, import path — not an in-process import.
"""
import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")

CASES = [
    ("recommender_mf.py", ["--steps", "4", "--batch-size", "32",
                           "--users", "20", "--items", "15"]),
    pytest.param("dcgan.py", ["--steps", "2", "--batch-size", "4"],
                 marks=pytest.mark.slow),   # ~8s (tier-1 budget);
    # GAN/conv-training coverage stays fast via recommender/vae/mnist
    pytest.param("bert_pretrain_mlm.py",
                 ["--steps", "2", "--batch-size", "4",
                  "--seq-len", "8", "--vocab", "16"],
                 marks=pytest.mark.slow),   # ~11s (tier-1 budget)
    pytest.param("train_cifar_gluon.py",
                 ["--steps", "2", "--batch-size", "4",
                  "--model", "resnet18_v1"],
                 marks=pytest.mark.slow),   # ~11s (tier-1 budget);
    # gluon-training coverage stays fast via mnist/multi_task/lenet
    ("train_mnist_mlp.py", ["--epochs", "1", "--batch-size", "32"]),
    ("char_lstm.py", ["--epochs", "1", "--seq-len", "8",
                      "--batch-size", "4"]),
    ("lstm_ocr.py", ["--epochs", "1", "--num-samples", "32",
                     "--batch-size", "16", "--width", "24"]),
    ("dqn_cartpole.py", ["--episodes", "6", "--batch-size", "32"]),
    ("multi_task.py", ["--epochs", "1", "--num-samples", "128",
                       "--batch-size", "32"]),
    ("bucketing_lm.py", ["--epochs", "1", "--batch-size", "4",
                         "--buckets", "6,9"]),
    pytest.param("bi_lstm_sort.py",
                 ["--epochs", "1", "--num-samples", "64",
                  "--batch-size", "16", "--seq-len", "4",
                  "--vocab", "8"],
                 marks=pytest.mark.slow),   # ~6s (tier-1 budget);
    # seq2seq/bucketing coverage stays fast via char_lstm/bucketing_lm
    ("sparse_linear_classification.py",
     ["--epochs", "2", "--num-samples", "256", "--num-features", "100",
      "--batch-size", "64", "--min-acc", "0.6"]),
    ("vae_mnist.py", ["--epochs", "1", "--num-samples", "128",
                      "--batch-size", "32", "--max-loss", "110"]),
    ("adversary_fgsm.py", ["--epochs", "2", "--num-samples", "256",
                           "--batch-size", "64", "--min-drop", "0.02"]),
    pytest.param("ssd_detect.py", ["--steps", "2", "--batch-size", "2"],
                 marks=pytest.mark.slow),   # ~49s (tier-1 budget)
    ("svm_digits.py", ["--epochs", "3", "--num-samples", "256",
                       "--batch-size", "64", "--min-acc", "0.12"]),
    # the L1-hinge branch is the other half of SVMOutput; pytest
    # disambiguates the duplicate id with a numeric suffix
    ("svm_digits.py", ["--epochs", "3", "--num-samples", "256",
                       "--batch-size", "64", "--min-acc", "0.12",
                       "--hinge", "l1"]),
    pytest.param("multi_threaded_inference.py",
                 ["--threads", "4", "--requests", "2",
                  "--batch-size", "2", "--image-size", "32"],
                 marks=pytest.mark.slow),   # ~7s (tier-1 budget);
    # threaded-inference coverage stays fast via serve_predictor +
    # test_threadsafe
    ("serve_predictor.py", ["--threads", "4", "--requests", "8",
                            "--max-batch", "4", "--feature-dim", "16"]),
    pytest.param("llm_serve_decode.py",
                 ["--threads", "4", "--requests", "4",
                  "--max-context", "32", "--max-new-tokens", "6"],
                 marks=pytest.mark.slow),   # ~18s (tier-1 budget);
    # test_llm_serving's decoder-artifact roundtrip keeps fast coverage
    pytest.param("nce_lm.py", ["--epochs", "3", "--max-ppl", "120"],
                 marks=pytest.mark.slow),   # ~22s (tier-1 budget)
    ("rbm_digits.py", ["--epochs", "3", "--num-samples", "256",
                       "--max-recon-err", "0.12"]),
    # --check-uncertainty needs a longer trajectory than CI affords;
    # the 0.6 RMSE gate beats the constant-zero baseline (0.64 on this
    # eval set), so a non-learning regression cannot pass it
    pytest.param("bayesian_sgld.py",
                 ["--epochs", "100", "--burn-in", "70",
                  "--lr", "2e-4", "--max-rmse", "0.6"],
                 marks=pytest.mark.slow),   # ~18s (tier-1 budget)
    pytest.param("stochastic_depth.py",
                 ["--epochs", "5", "--num-samples", "1024",
                  "--min-acc", "0.5"],
                 marks=pytest.mark.slow),   # ~36s (tier-1 budget)
    pytest.param("train_imagenet.py",
                 ["--benchmark", "1", "--num-layers", "18",
                  "--num-classes", "4", "--image-shape", "3,16,16",
                  "--batch-size", "4", "--num-examples", "8",
                  "--num-epochs", "1", "--lr", "0.01",
                  "--lr-step-epochs", "", "--kv-store", "local"],
                 marks=pytest.mark.slow),   # ~22s (tier-1 budget);
    # symbolic fit/kvstore coverage stays fast via svm/rbm_digits +
    # test_distributed launcher tests
]


@pytest.mark.parametrize(
    "script,args", CASES,
    ids=[getattr(c, "values", c)[0] for c in CASES])
def test_example_runs(script, args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script)] + list(args),
        capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, \
        f"{script} failed:\n{p.stdout[-2000:]}\n{p.stderr[-2000:]}"


def test_serve_bench_smoke():
    """tools/serve_bench.py --smoke: the closed-loop load generator
    must complete losslessly with zero recompiles during load (it
    exits 1 otherwise)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    tools = os.path.join(os.path.dirname(EXAMPLES), "tools")
    p = subprocess.run(
        [sys.executable, os.path.join(tools, "serve_bench.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, \
        f"serve_bench --smoke failed:\n{p.stdout[-2000:]}\n" \
        f"{p.stderr[-2000:]}"
    assert "SMOKE PASS" in p.stdout


@pytest.mark.slow   # ~34s on 1 CPU (tier-1 budget); the llm serving
# bit-exactness/zero-recompile gates in tests/test_llm_serving.py and
# test_metrics_dump_smoke keep fast coverage of the same invariants
def test_llm_bench_smoke():
    """tools/llm_bench.py --smoke: the continuous-batching decode load
    generator must complete losslessly with zero recompiles during
    load AND emit a BENCH json carrying tokens/sec, TTFT p50/p99 and
    KV occupancy (it exits 1 otherwise)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    tools = os.path.join(os.path.dirname(EXAMPLES), "tools")
    p = subprocess.run(
        [sys.executable, os.path.join(tools, "llm_bench.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, \
        f"llm_bench --smoke failed:\n{p.stdout[-2000:]}\n" \
        f"{p.stderr[-2000:]}"
    assert "SMOKE PASS" in p.stdout


@pytest.mark.slow   # ~32s on 1 CPU (tier-1 budget); the exposition
# path keeps fast coverage via test_metrics_dump_smoke and the fleet
# replay variant stays pinned in test_fleet's slow tier
def test_load_replay_smoke():
    """tools/load_replay.py --smoke: a tiny seeded trace replayed
    against BOTH serving front ends must be deterministic (bit-
    identical schedule), recompile-free, exactly accounted (typed
    served/shed/expired partition sums to submitted), and must emit a
    well-formed CAPACITY json plus a clean exposition carrying the
    mxtpu_slo_*/mxtpu_ts_*/tenant series (it exits 1 otherwise)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    tools = os.path.join(os.path.dirname(EXAMPLES), "tools")
    p = subprocess.run(
        [sys.executable, os.path.join(tools, "load_replay.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, \
        f"load_replay --smoke failed:\n{p.stdout[-2000:]}\n" \
        f"{p.stderr[-2000:]}"
    assert "SMOKE PASS" in p.stdout


def test_metrics_dump_smoke():
    """tools/metrics_dump.py --smoke: the observability exposition path
    (registry -> 4-subsystem instrumentation -> Prometheus text ->
    JSONL round-trip) must hold end to end (it exits 1 otherwise)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    tools = os.path.join(os.path.dirname(EXAMPLES), "tools")
    p = subprocess.run(
        [sys.executable, os.path.join(tools, "metrics_dump.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600, env=env)
    assert p.returncode == 0, \
        f"metrics_dump --smoke failed:\n{p.stdout[-2000:]}\n" \
        f"{p.stderr[-2000:]}"
    assert "SMOKE PASS" in p.stdout


@pytest.mark.slow   # ~160s of XLA CPU compile for the 4-stage ResNet
def test_pipeline_parallel_example_runs():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    p = subprocess.run(
        [sys.executable,
         os.path.join(EXAMPLES, "pipeline_parallel_resnet.py"),
         "--steps", "1"],
        capture_output=True, text=True, timeout=700, env=env)
    assert p.returncode == 0, \
        f"pipeline example failed:\n{p.stdout[-2000:]}\n" \
        f"{p.stderr[-2000:]}"
