"""Direct oracle tests for mx.metric (reference:
tests/python/unittest/test_metric.py).

Round 5 rewrote the F1/MCC confusion bookkeeping and the Pearson
micro-average streaming state in this repo's idiom; these pin every
rewritten path against closed-form numpy oracles, plus the zoo basics.
"""
import math

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import metric as M


def _nd(a):
    return mx.nd.array(np.asarray(a, np.float32))


def _two_col(pos_prob):
    """binary 'probabilities' with argmax == (p > .5)"""
    p = np.asarray(pos_prob, np.float32)
    return np.stack([1 - p, p], axis=1)


LABELS = np.array([1, 0, 1, 1, 0, 0, 1, 0])
PREDS = np.array([0.9, 0.8, 0.7, 0.2, 0.1, 0.6, 0.55, 0.3])
# argmax>.5: pred_pos = [1,1,1,0,0,1,1,0] -> tp=3 fp=2 fn=1 tn=2


def _f1(tp, fp, fn):
    prec = tp / (tp + fp)
    rec = tp / (tp + fn)
    return 2 * prec * rec / (prec + rec)


def _mcc(tp, fp, fn, tn):
    denom = math.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
    return (tp * tn - fp * fn) / denom


def test_f1_micro_oracle():
    m = M.F1(average="micro")
    m.update([_nd(LABELS[:4])], [_nd(_two_col(PREDS[:4]))])
    m.update([_nd(LABELS[4:])], [_nd(_two_col(PREDS[4:]))])
    name, val = m.get()
    assert name == "f1"
    np.testing.assert_allclose(val, _f1(3, 2, 1), rtol=1e-6)


def test_f1_macro_averages_per_update():
    m = M.F1(average="macro")
    m.update([_nd([1, 0])], [_nd(_two_col([0.9, 0.1]))])  # perfect: f1=1
    m.update([_nd([1, 1])], [_nd(_two_col([0.9, 0.1]))])  # tp=1 fn=1: f1=2/3
    np.testing.assert_allclose(m.get()[1], (1.0 + 2 / 3) / 2, rtol=1e-6)


def test_f1_rejects_multiclass_labels():
    m = M.F1()
    with pytest.raises(ValueError, match="binary"):
        m.update([_nd([0, 1, 2])], [_nd(_two_col([0.9, 0.1, 0.5]))])


def test_mcc_micro_oracle():
    m = M.MCC(average="micro")
    m.update([_nd(LABELS[:5])], [_nd(_two_col(PREDS[:5]))])
    m.update([_nd(LABELS[5:])], [_nd(_two_col(PREDS[5:]))])
    np.testing.assert_allclose(m.get()[1], _mcc(3, 2, 1, 2), rtol=1e-6)


def test_mcc_macro_and_global():
    m = M.MCC(average="macro")
    m.update([_nd(LABELS)], [_nd(_two_col(PREDS))])
    want = _mcc(3, 2, 1, 2)
    np.testing.assert_allclose(m.get()[1], want, rtol=1e-6)
    # global tally survives reset_local
    m.reset_local()
    assert np.isnan(m.get()[1]) or m.get()[1] == 0.0 or m.num_inst == 0
    np.testing.assert_allclose(m.get_global()[1], want, rtol=1e-6)


def test_mcc_degenerate_all_one_class():
    m = M.MCC()
    m.update([_nd([1, 1, 1])], [_nd(_two_col([0.9, 0.8, 0.7]))])
    # tp=3, everything else 0: empty marginals drop out of the product
    # (reference convention), giving 3/sqrt(3*3) = 1? no: terms tp+fp=3,
    # tp+fn=3, tn+fp=0(drop), tn+fn=0(drop) -> 3*0-0 / sqrt(9) = 1... tp*tn=0
    # numerator tp*tn - fp*fn = 0 -> mcc 0
    np.testing.assert_allclose(m.get()[1], 0.0, atol=1e-12)


def test_pearson_macro_matches_corrcoef():
    rng = np.random.RandomState(0)
    lab, prd = rng.randn(20), rng.randn(20)
    m = M.PearsonCorrelation()
    m.update([_nd(lab)], [_nd(prd)])
    np.testing.assert_allclose(m.get()[1], np.corrcoef(prd, lab)[0, 1],
                               rtol=1e-6)


def test_pearson_micro_streams_across_batches():
    rng = np.random.RandomState(1)
    lab = rng.randn(30)
    prd = 0.6 * lab + 0.4 * rng.randn(30)
    m = M.PearsonCorrelation(average="micro")
    for i in range(0, 30, 7):  # uneven batch sizes
        m.update([_nd(lab[i:i + 7])], [_nd(prd[i:i + 7])])
    np.testing.assert_allclose(m.get()[1], np.corrcoef(prd, lab)[0, 1],
                               rtol=1e-6)
    m.reset()
    assert np.isnan(m.get()[1])


def test_pearson_micro_large_mean_stable():
    """Raw-moment accumulation must not cancel away the signal when the
    data's mean dwarfs its variance (code-review r5)."""
    rng = np.random.RandomState(2)
    lab = 1e8 + rng.randn(40)
    prd = 1e8 + 0.5 * (lab - 1e8) + 0.5 * rng.randn(40)
    m = M.PearsonCorrelation(average="micro")
    for i in range(0, 40, 9):  # float64 numpy straight in: float32 NDArray
        m.update([lab[i:i + 9]], [prd[i:i + 9]])  # would quantize 1e8 away
    np.testing.assert_allclose(m.get()[1], np.corrcoef(prd, lab)[0, 1],
                               rtol=1e-6)


def test_custom_metric_scalar_and_tuple():
    scalar = M.CustomMetric(lambda l, p: float(np.abs(l - p).mean()),
                            name="mad")
    scalar.update([_nd([1.0, 2.0])], [_nd([1.5, 1.0])])
    np.testing.assert_allclose(scalar.get()[1], 0.75)
    assert scalar.num_inst == 1

    pair = M.CustomMetric(lambda l, p: (float(np.abs(l - p).sum()),
                                        l.size), name="sad")
    pair.update([_nd([1.0, 2.0])], [_nd([1.5, 1.0])])
    pair.update([_nd([0.0])], [_nd([4.0])])
    np.testing.assert_allclose(pair.get()[1], (1.5 + 4.0) / 3)
    assert pair.num_inst == 3


def test_composite_update_dict_filters_names():
    acc = M.Accuracy(output_names=["out"], label_names=["lab"])
    comp = M.CompositeEvalMetric([acc])
    comp.update_dict(
        {"lab": _nd([1, 0]), "other_lab": _nd([0, 0])},
        {"out": _nd(_two_col([0.9, 0.1])), "junk": _nd(_two_col([0., 0.]))})
    np.testing.assert_allclose(comp.get()[1][0], 1.0)


def test_accuracy_and_topk():
    a = M.Accuracy()
    a.update([_nd([1, 0, 2])],
             [_nd([[0.1, 0.8, 0.1], [0.9, 0.05, 0.05], [0.3, 0.4, 0.3]])])
    np.testing.assert_allclose(a.get()[1], 2 / 3)
    t = M.TopKAccuracy(top_k=2)
    t.update([_nd([2])], [_nd([[0.3, 0.1, 0.25]])])  # 2nd-best hit
    np.testing.assert_allclose(t.get()[1], 1.0)


def test_perplexity_ignore_label():
    p = M.Perplexity(ignore_label=0)
    probs = np.array([[0.2, 0.8], [0.5, 0.5], [0.9, 0.1]], np.float32)
    p.update([_nd([1, 0, 1])], [_nd(probs)])
    want = math.exp(-(math.log(0.8) + math.log(0.1)) / 2)
    np.testing.assert_allclose(p.get()[1], want, rtol=1e-6)


def test_create_by_name_and_config():
    m = M.create("mcc", average="micro")
    assert isinstance(m, M.MCC)
    cfg = M.create("accuracy").get_config()
    assert cfg["metric"] == "Accuracy"
    assert isinstance(M.create(["accuracy", "mae"]), M.CompositeEvalMetric)
