"""AMP (automatic mixed precision) end-to-end tests.

Covers the chokepoint casting (ops/invoke.py), LossScaler overflow-skip,
and a bf16 LeNet convergence run — the pieces VERDICT round 2 flagged as
untested. Reference behavior: python/mxnet/contrib/amp/.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, gluon, nd
from mxnet_tpu.gluon import nn
import mxnet_tpu.autograd as ag


@pytest.fixture(autouse=True)
def _amp_cleanup():
    yield
    amp.uninit()


def test_amp_casts_lp_ops_to_bf16():
    amp.init()
    a = nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    w = nd.array(np.random.RandomState(1).randn(16, 8).astype(np.float32))
    out = nd.FullyConnected(a, w, no_bias=True, num_hidden=16)
    assert out.dtype == np.dtype("bfloat16"), out.dtype
    # f32-forced ops stay f32 even on bf16 inputs
    s = nd.softmax(out)
    assert s.dtype == np.dtype("float32"), s.dtype


def test_amp_inactive_after_uninit():
    amp.init()
    amp.uninit()
    a = nd.array(np.ones((2, 4), np.float32))
    w = nd.array(np.ones((3, 4), np.float32))
    out = nd.FullyConnected(a, w, no_bias=True, num_hidden=3)
    assert out.dtype == np.dtype("float32")


def test_loss_scaler_overflow_skips_update_and_halves_scale():
    amp.init(target_dtype="float16")
    net = nn.Dense(4, in_units=4)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    scaler = trainer._amp_loss_scaler
    scale0 = scaler.loss_scale
    assert scale0 > 1.0  # float16 engages real scaling

    x = nd.array(np.ones((2, 4), np.float32))
    w_before = net.weight.data().asnumpy().copy()

    # poison the gradient with inf -> step must be skipped, scale halved
    with ag.record():
        loss = net(x).sum()
    loss.backward()
    net.weight.grad()._data = (net.weight.grad()._data * np.inf)
    with pytest.warns(UserWarning, match="overflow"):
        trainer.step(2)
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w_before)
    assert scaler.loss_scale == scale0 / 2

    # clean step updates params and counts toward the growth window
    with ag.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(2)
    assert not np.array_equal(net.weight.data().asnumpy(), w_before)


def test_loss_scaler_recovery_doubles_after_scale_window():
    """Full overflow→recovery cycle through the trainer: overflow halves
    the scale and skips the update; after ``scale_window`` clean steps
    the scale doubles back."""
    amp.init(target_dtype="float16")
    net = nn.Dense(4, in_units=4)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    from mxnet_tpu.amp import LossScaler
    amp.init_trainer(trainer, loss_scaler=LossScaler(
        init_scale=2.0 ** 8, scale_window=3, target_dtype="float16"))
    scaler = trainer._amp_loss_scaler
    x = nd.array(np.ones((2, 4), np.float32))

    def one_step(poison=False):
        with ag.record():
            loss = net(x).sum()
        loss.backward()
        if poison:
            net.weight.grad()._data = net.weight.grad()._data * np.inf
        trainer.step(2)

    with pytest.warns(UserWarning, match="overflow"):
        one_step(poison=True)
    assert scaler.loss_scale == 2.0 ** 7          # halved
    steps_before = trainer._step_count
    for _ in range(3):                            # scale_window clean steps
        one_step()
    assert scaler.loss_scale == 2.0 ** 8          # doubled back
    assert trainer._step_count == steps_before + 3  # none skipped


def test_has_overflow_fused_single_reduction():
    """has_overflow folds ALL grads into one jitted reduction: it must
    flag a non-finite value in any parameter, and pass on clean grads."""
    from mxnet_tpu.amp import LossScaler
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.Dense(2))
    net.initialize()
    x = nd.array(np.ones((2, 4), np.float32))
    with ag.record():
        loss = net(x).sum()
    loss.backward()
    scaler = LossScaler(target_dtype="float16")
    params = list(net.collect_params().values())
    assert not scaler.has_overflow(params)
    # poison ONE grad among many — still caught by the fused check
    last = params[-1]
    last.grad()._data = last.grad()._data * np.nan
    assert scaler.has_overflow(params)


def test_scale_loss_context_multiplies_by_scale():
    amp.init(target_dtype="float16")
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.0})
    amp.init_trainer(trainer)
    scale = trainer._amp_loss_scaler.loss_scale
    loss = nd.array(np.array([1.5]))
    with amp.scale_loss(loss, trainer) as scaled:
        np.testing.assert_allclose(scaled.asnumpy(), [1.5 * scale])


def test_bf16_lenet_convergence():
    """LeNet under amp.init() must train on a toy problem: the AMP
    chokepoint casts conv/dense to bf16 while softmax/loss stay f32."""
    amp.init()
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"))
    net.add(nn.MaxPool2D(2))
    net.add(nn.Conv2D(16, 3, padding=1, activation="relu"))
    net.add(nn.GlobalAvgPool2D())
    net.add(nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.3, "momentum": 0.9})
    amp.init_trainer(trainer)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(0)
    # 4 classes distinguished by which quadrant carries signal
    x_np = rng.randn(32, 1, 8, 8).astype(np.float32) * 0.1
    y_np = np.arange(32) % 4
    for i, c in enumerate(y_np):
        x_np[i, 0, (c // 2) * 4:(c // 2) * 4 + 4,
             (c % 2) * 4:(c % 2) * 4 + 4] += 1.0
    x, y = nd.array(x_np), nd.array(y_np.astype(np.float32))

    losses = []
    for _ in range(40):
        with ag.record():
            out = net(x)
            loss = loss_fn(out, y).mean()
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < 0.3 * losses[0], (losses[0], losses[-1])
    # the conv compute really ran in bf16 under AMP
    with ag.pause():
        feat = net[0](x)
    assert feat.dtype == np.dtype("bfloat16")
