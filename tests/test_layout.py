"""Layout selection (NCHW vs NHWC) must be numerically transparent.

The reference supports layout selection on conv/pool
(src/operator/nn/convolution.cc:395-507); here layout='NHWC' keeps
activations channels-last end-to-end (the fast path on TPU) with weights
in OHWI. These tests pin NHWC == NCHW up to dtype round-off, at the op
level and through the full ResNet zoo models.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops.registry import _REGISTRY
import mxnet_tpu.autograd as ag


def _op(name, *args, **kw):
    import jax.numpy as jnp
    arrays = [jnp.asarray(a) for a in args]
    return np.asarray(_REGISTRY[name].impl(*arrays, **kw))


def test_convolution_nhwc_matches_nchw():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 8, 10, 12).astype(np.float32)   # NCHW
    w = rng.randn(16, 8, 3, 3).astype(np.float32)    # OIHW
    b = rng.randn(16).astype(np.float32)
    ref = _op("Convolution", x, w, b, kernel=(3, 3), stride=(2, 2),
              pad=(1, 1), num_filter=16)
    out = _op("Convolution", x.transpose(0, 2, 3, 1),
              w.transpose(0, 2, 3, 1), b, kernel=(3, 3), stride=(2, 2),
              pad=(1, 1), num_filter=16, layout="NHWC")
    np.testing.assert_allclose(out.transpose(0, 3, 1, 2), ref,
                               rtol=1e-5, atol=1e-5)


def test_grouped_convolution_nhwc():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 8, 9, 9).astype(np.float32)
    w = rng.randn(8, 2, 3, 3).astype(np.float32)
    ref = _op("Convolution", x, w, kernel=(3, 3), num_filter=8,
              num_group=4, no_bias=True)
    out = _op("Convolution", x.transpose(0, 2, 3, 1),
              w.transpose(0, 2, 3, 1), kernel=(3, 3), num_filter=8,
              num_group=4, no_bias=True, layout="NHWC")
    np.testing.assert_allclose(out.transpose(0, 3, 1, 2), ref,
                               rtol=1e-5, atol=1e-5)


def test_deconvolution_nhwc_matches_nchw():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 6, 7, 7).astype(np.float32)
    w = rng.randn(6, 4, 4, 4).astype(np.float32)     # IOHW
    ref = _op("Deconvolution", x, w, kernel=(4, 4), stride=(2, 2),
              pad=(1, 1), num_filter=4)
    out = _op("Deconvolution", x.transpose(0, 2, 3, 1),
              w.transpose(0, 2, 3, 1), kernel=(4, 4), stride=(2, 2),
              pad=(1, 1), num_filter=4, layout="NHWC")
    np.testing.assert_allclose(out.transpose(0, 3, 1, 2), ref,
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("pool_type", ["max", "avg"])
@pytest.mark.parametrize("convention", ["valid", "full"])
def test_pooling_nhwc_matches_nchw(pool_type, convention):
    rng = np.random.RandomState(3)
    x = rng.randn(2, 5, 11, 13).astype(np.float32)
    kw = dict(kernel=(3, 3), stride=(2, 2), pad=(1, 1),
              pool_type=pool_type, pooling_convention=convention)
    ref = _op("Pooling", x, **kw)
    out = _op("Pooling", x.transpose(0, 2, 3, 1), layout="NHWC", **kw)
    np.testing.assert_allclose(out.transpose(0, 3, 1, 2), ref,
                               rtol=1e-6, atol=1e-6)


def test_global_pooling_nhwc():
    rng = np.random.RandomState(4)
    x = rng.randn(2, 5, 6, 7).astype(np.float32)
    ref = _op("Pooling", x, pool_type="avg", global_pool=True)
    out = _op("Pooling", x.transpose(0, 2, 3, 1), pool_type="avg",
              global_pool=True, layout="NHWC")
    np.testing.assert_allclose(out.transpose(0, 3, 1, 2), ref,
                               rtol=1e-6, atol=1e-6)


def test_conv1d_nwc():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 4, 9).astype(np.float32)        # NCW
    w = rng.randn(6, 4, 3).astype(np.float32)        # OIW
    ref = _op("Convolution", x, w, kernel=(3,), num_filter=6, no_bias=True)
    out = _op("Convolution", x.transpose(0, 2, 1), w.transpose(0, 2, 1),
              kernel=(3,), num_filter=6, no_bias=True, layout="NWC")
    np.testing.assert_allclose(out.transpose(0, 2, 1), ref,
                               rtol=1e-5, atol=1e-5)


def _copy_params_permuted(src_net, dst_net):
    p1, p2 = src_net.collect_params(), dst_net.collect_params()
    for ka, kb in zip(sorted(p1), sorted(p2)):
        v = p1[ka].data().asnumpy()
        if v.ndim == 4:  # OIHW -> OHWI
            v = v.transpose(0, 2, 3, 1)
        p2[kb].set_data(mx.nd.array(v))


@pytest.mark.parametrize("factory,version", [("resnet18_v1", 1),
                                             ("resnet18_v2", 2)])
def test_resnet_nhwc_matches_nchw(factory, version):
    from mxnet_tpu.gluon.model_zoo import vision
    import jax.numpy as jnp

    mx.random.seed(0)
    x = np.random.RandomState(0).randn(2, 3, 32, 32).astype(np.float32)
    n1 = getattr(vision, factory)(thumbnail=True)
    n1.initialize(init=mx.initializer.Xavier())
    with ag.pause():
        o1 = n1(nd.NDArray(jnp.asarray(x)))

    n2 = getattr(vision, factory)(thumbnail=True, layout="NHWC")
    n2.initialize(init=mx.initializer.Xavier())
    xt = x.transpose(0, 2, 3, 1)
    with ag.pause():
        n2(nd.NDArray(jnp.asarray(xt)))  # shape warmup
    _copy_params_permuted(n1, n2)
    with ag.pause():
        o2 = n2(nd.NDArray(jnp.asarray(xt)))
    np.testing.assert_allclose(o2.asnumpy(), o1.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_nhwc_training_step_grads():
    """Gradients must flow through the NHWC path (conv+pool+BN train mode)."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, layout="NHWC"))
    net.add(nn.BatchNorm(axis=3))
    net.add(nn.Activation("relu"))
    net.add(nn.MaxPool2D(2, layout="NHWC"))
    net.add(nn.GlobalAvgPool2D(layout="NHWC"))
    net.add(nn.Dense(4))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 8, 8, 3))
    y = mx.nd.array(np.array([0, 1, 2, 3]))
    with ag.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(4)
    g = net[0].weight.grad().asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_s2d_stem_conv_matches_convolution():
    """The space-to-depth stem rewrite (ops/nn.py _s2d_stem_conv) must be
    numerically identical to the plain 7x7/s2/p3 Convolution it replaces
    (MLPerf-ResNet stem optimisation)."""
    rng = np.random.RandomState(0)
    x = rng.randn(2, 224, 224, 3).astype(np.float32)
    w = rng.randn(64, 7, 7, 3).astype(np.float32)  # OHWI
    ref = _op("Convolution", x, w, kernel=(7, 7), stride=(2, 2),
              pad=(3, 3), num_filter=64, no_bias=True, layout="NHWC")
    out = _op("_s2d_stem_conv", x, w)
    assert out.shape == ref.shape == (2, 112, 112, 64)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_s2d_stem_resnet_matches_plain_stem():
    """resnet18_v1(stem_s2d=True) must produce the same logits as the
    plain-stem model given identical parameters (the stem weight is the
    same OHWI (O,7,7,3) tensor, so checkpoints interchange)."""
    from mxnet_tpu.gluon.model_zoo import vision
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    x = rng.randn(2, 64, 64, 3).astype(np.float32)
    mx.random.seed(0)
    net = vision.resnet18_v1(layout="NHWC")
    net.initialize()
    with ag.pause():
        o1 = net(nd.NDArray(jnp.asarray(x)))
    mx.random.seed(0)
    net2 = vision.resnet18_v1(layout="NHWC", stem_s2d=True)
    net2.initialize()
    with ag.pause():
        net2(nd.NDArray(jnp.asarray(x)))  # shape inference
    # copy params across (the stem weight name differs only by block name)
    strip = lambda k: k.split("_", 1)[1]  # drop the 'resnetv1N' prefix
    src = {strip(k): v for k, v in net.collect_params().items()}
    for name, p in net2.collect_params().items():
        key = strip(name)
        if "_s2dstemconv0_" in key:
            key = key.replace("_s2dstemconv0_", "conv2d0_")
        p.set_data(src[key].data())
    with ag.pause():
        o2 = net2(nd.NDArray(jnp.asarray(x)))
    np.testing.assert_allclose(o2.asnumpy(), o1.asnumpy(),
                               rtol=2e-3, atol=2e-3)
