"""RecordIO / image pipeline tests (reference: tests/python/unittest/
test_recordio.py, test_image.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio


def test_recordio_roundtrip(tmp_path):
    f = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(f, "w")
    for i in range(5):
        w.write(f"record{i}".encode())
    w.close()
    r = recordio.MXRecordIO(f, "r")
    for i in range(5):
        assert r.read() == f"record{i}".encode()
    assert r.read() is None
    r.close()


def test_indexed_recordio(tmp_path):
    f = str(tmp_path / "test.rec")
    idx = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx, f, "w")
    for i in range(5):
        w.write_idx(i, f"rec{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx, f, "r")
    assert r.read_idx(3) == b"rec3"
    assert r.read_idx(0) == b"rec0"
    assert r.keys == [0, 1, 2, 3, 4]
    r.close()


def test_pack_unpack_header():
    h = recordio.IRHeader(0, 7.0, 123, 0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload"
    assert h2.label == 7.0
    assert h2.id == 123
    # multi-label
    h = recordio.IRHeader(0, np.array([1.0, 2.0, 3.0]), 5, 0)
    s = recordio.pack(h, b"x")
    h2, payload = recordio.unpack(s)
    np.testing.assert_array_equal(h2.label, [1, 2, 3])


def test_pack_unpack_img(tmp_path):
    img = (np.random.RandomState(0).rand(32, 32, 3) * 255).astype(np.uint8)
    h = recordio.IRHeader(0, 2.0, 1, 0)
    s = recordio.pack_img(h, img, quality=100, img_fmt=".png")
    h2, img2 = recordio.unpack_img(s)
    assert h2.label == 2.0
    np.testing.assert_array_equal(img, img2)  # png is lossless


def test_image_imdecode_resize():
    import cv2
    img = (np.random.RandomState(1).rand(40, 60, 3) * 255).astype(np.uint8)
    ok, buf = cv2.imencode(".png", cv2.cvtColor(img, cv2.COLOR_RGB2BGR))
    out = mx.image.imdecode(buf.tobytes())
    np.testing.assert_array_equal(out.asnumpy(), img)
    r = mx.image.imresize(out, 30, 20)
    assert r.shape == (20, 30, 3)
    rs = mx.image.resize_short(out, 20)
    assert min(rs.shape[:2]) == 20


def test_image_iter_from_rec(tmp_path):
    f = str(tmp_path / "data.rec")
    idx = str(tmp_path / "data.idx")
    w = recordio.MXIndexedRecordIO(idx, f, "w")
    rng = np.random.RandomState(0)
    for i in range(10):
        img = (rng.rand(36, 36, 3) * 255).astype(np.uint8)
        h = recordio.IRHeader(0, float(i % 3), i, 0)
        w.write_idx(i, recordio.pack_img(h, img, img_fmt=".png"))
    w.close()

    it = mx.image.ImageIter(batch_size=4, data_shape=(3, 32, 32),
                            path_imgrec=f)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape == (4,)


def test_record_file_dataset(tmp_path):
    f = str(tmp_path / "ds.rec")
    idx = str(tmp_path / "ds.idx")
    w = recordio.MXIndexedRecordIO(idx, f, "w")
    for i in range(6):
        w.write_idx(i, f"sample{i}".encode())
    w.close()
    ds = mx.gluon.data.RecordFileDataset(f)
    assert len(ds) == 6
    assert ds[2] == b"sample2"


def test_recordio_payload_containing_magic(tmp_path):
    # dmlc-core multipart framing: payloads containing the magic word are
    # split on write and rejoined on read
    import struct
    magic = struct.pack("<I", 0xced7230a)
    payloads = [b"head" + magic + b"tail",
                magic + b"x", b"y" + magic, magic * 3, b"plain"]
    f = str(tmp_path / "magic.rec")
    w = recordio.MXRecordIO(f, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(f, "r")
    for p in payloads:
        assert r.read() == p
    r.close()


def test_ndarray_iter_discard():
    x = np.arange(20).reshape(10, 2).astype(np.float32)
    it = mx.io.NDArrayIter(x, np.arange(10), batch_size=4,
                           last_batch_handle="discard")
    batches = list(it)
    assert len(batches) == 2
    for b in batches:
        assert b.data[0].shape == (4, 2)


def test_mxdataiter_dispatch(tmp_path):
    """MXDataIter maps the reference's C++ iterator names onto the
    TPU-build pipelines (reference: io.py MXDataIter)."""
    import numpy as np
    import mxnet_tpu as mx
    cv2 = pytest.importorskip("cv2")
    from mxnet_tpu import recordio

    # build a tiny .rec
    rng = np.random.RandomState(0)
    rec = str(tmp_path / "d.rec")
    idx = str(tmp_path / "d.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(8):
        img = (rng.rand(24, 24, 3) * 255).astype(np.uint8)
        ok, buf = cv2.imencode(".jpg", img)
        header = recordio.IRHeader(0, float(i % 2), i, 0)
        w.write_idx(i, recordio.pack(header, buf.tobytes()))
    w.close()

    it = mx.io.MXDataIter("ImageRecordIter", batch_size=4,
                          data_shape=(3, 24, 24), path_imgrec=rec)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 24, 24)
    assert batch.label[0].shape[0] == 4

    # CSV dispatch
    csv = tmp_path / "x.csv"
    np.savetxt(csv, rng.rand(6, 5), delimiter=",")
    it2 = mx.io.MXDataIter("CSVIter", data_csv=str(csv),
                           data_shape=(5,), batch_size=3)
    b2 = next(iter(it2))
    assert b2.data[0].shape == (3, 5)
