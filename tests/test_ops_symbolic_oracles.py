"""Reference-style op tests: symbolic forward/backward vs numpy oracles.

Reference: tests/python/unittest/test_operator.py uses
check_symbolic_forward/check_symbolic_backward pervasively (e.g.
test_fullyconnected, test_convolution_grouping, test_softmax). This
file ports that testing style onto the new oracles in
mxnet_tpu.test_utils — every case states the expected value/gradient in
closed numpy form, independent of the op implementation.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu.test_utils import (check_symbolic_forward,
                                  check_symbolic_backward)

RNG = np.random.RandomState(42)


def test_fullyconnected_forward_backward():
    B, I, H = 4, 7, 3
    x = RNG.randn(B, I).astype(np.float32)
    w = RNG.randn(H, I).astype(np.float32)
    b = RNG.randn(H).astype(np.float32)
    og = RNG.randn(B, H).astype(np.float32)

    s = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=H, name="fc")
    loc = {"data": x, "fc_weight": w, "fc_bias": b}
    check_symbolic_forward(s, loc, [x @ w.T + b], rtol=1e-4, atol=1e-5)
    check_symbolic_backward(
        s, loc, [og],
        {"data": og @ w, "fc_weight": og.T @ x, "fc_bias": og.sum(0)},
        rtol=1e-4, atol=1e-4)


def test_activation_backward():
    x = RNG.randn(3, 5).astype(np.float32)
    og = RNG.randn(3, 5).astype(np.float32)
    s = mx.sym.Activation(mx.sym.var("data"), act_type="relu")
    check_symbolic_forward(s, {"data": x}, [np.maximum(x, 0)])
    check_symbolic_backward(s, {"data": x}, [og], {"data": og * (x > 0)})

    s = mx.sym.Activation(mx.sym.var("data"), act_type="sigmoid")
    sig = 1 / (1 + np.exp(-x))
    check_symbolic_forward(s, {"data": x}, [sig], rtol=1e-5, atol=1e-6)
    check_symbolic_backward(s, {"data": x}, [og],
                            {"data": og * sig * (1 - sig)},
                            rtol=1e-4, atol=1e-5)


def test_elemwise_binary_backward():
    a = RNG.randn(2, 3).astype(np.float32)
    b = RNG.randn(2, 3).astype(np.float32) + 2.5
    og = RNG.randn(2, 3).astype(np.float32)
    va, vb = mx.sym.var("a"), mx.sym.var("b")
    check_symbolic_backward(va / vb, {"a": a, "b": b}, [og],
                            {"a": og / b, "b": -og * a / b ** 2},
                            rtol=1e-4, atol=1e-5)
    check_symbolic_backward(va ** 2.0 + vb, {"a": a, "b": b}, [og],
                            {"a": og * 2 * a, "b": og}, rtol=1e-4,
                            atol=1e-5)


def test_convolution_1x1_as_matmul():
    # 1x1 conv == per-pixel matmul: closed-form oracle
    B, C, H, W, F = 2, 3, 4, 4, 5
    x = RNG.randn(B, C, H, W).astype(np.float32)
    w = RNG.randn(F, C, 1, 1).astype(np.float32)
    b = np.zeros(F, np.float32)
    s = mx.sym.Convolution(mx.sym.var("data"), kernel=(1, 1), num_filter=F,
                           name="conv")
    want = np.einsum("bchw,fc->bfhw", x, w[:, :, 0, 0]).astype(np.float32)
    check_symbolic_forward(
        s, {"data": x, "conv_weight": w, "conv_bias": b}, [want],
        rtol=1e-4, atol=1e-4)
    og = RNG.randn(B, F, H, W).astype(np.float32)
    check_symbolic_backward(
        s, {"data": x, "conv_weight": w, "conv_bias": b}, [og],
        {"data": np.einsum("bfhw,fc->bchw", og, w[:, :, 0, 0]),
         "conv_weight": np.einsum("bfhw,bchw->fc", og, x)[..., None, None],
         "conv_bias": og.sum((0, 2, 3))},
        rtol=1e-3, atol=1e-3)


def test_pooling_forward():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    s = mx.sym.Pooling(mx.sym.var("data"), kernel=(2, 2), stride=(2, 2),
                       pool_type="max")
    want = np.array([[[[5, 7], [13, 15]]]], np.float32)
    check_symbolic_forward(s, {"data": x}, [want])
    s = mx.sym.Pooling(mx.sym.var("data"), kernel=(2, 2), stride=(2, 2),
                       pool_type="avg")
    want = np.array([[[[2.5, 4.5], [10.5, 12.5]]]], np.float32)
    check_symbolic_forward(s, {"data": x}, [want])


def test_softmax_and_logsoftmax():
    x = RNG.randn(3, 6).astype(np.float32)
    e = np.exp(x - x.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    check_symbolic_forward(mx.sym.softmax(mx.sym.var("data")),
                           {"data": x}, [p], rtol=1e-5, atol=1e-6)
    check_symbolic_forward(mx.sym.log_softmax(mx.sym.var("data")),
                           {"data": x}, [np.log(p)], rtol=1e-4, atol=1e-5)
    # softmax jacobian: dL/dx = p*(og - sum(og*p))
    og = RNG.randn(3, 6).astype(np.float32)
    want = p * (og - (og * p).sum(-1, keepdims=True))
    check_symbolic_backward(mx.sym.softmax(mx.sym.var("data")),
                            {"data": x}, [og], {"data": want},
                            rtol=1e-4, atol=1e-5)


def test_batchnorm_inference_forward():
    B, C = 4, 3
    x = RNG.randn(B, C, 2, 2).astype(np.float32)
    gamma = RNG.rand(C).astype(np.float32) + 0.5
    beta = RNG.randn(C).astype(np.float32)
    mean = RNG.randn(C).astype(np.float32)
    var = RNG.rand(C).astype(np.float32) + 0.5
    s = mx.sym.BatchNorm(mx.sym.var("data"), fix_gamma=False, name="bn",
                         use_global_stats=True, eps=1e-5)
    want = (x - mean[:, None, None]) / np.sqrt(var[:, None, None] + 1e-5) \
        * gamma[:, None, None] + beta[:, None, None]
    check_symbolic_forward(
        s, {"data": x, "bn_gamma": gamma, "bn_beta": beta},
        [want.astype(np.float32)],
        aux_states={"bn_moving_mean": mean, "bn_moving_var": var},
        rtol=1e-4, atol=1e-4)


def test_embedding_backward_scatter():
    V, D = 6, 4
    W = RNG.randn(V, D).astype(np.float32)
    ids = np.array([[1, 3], [3, 5]], np.float32)
    og = RNG.randn(2, 2, D).astype(np.float32)
    s = mx.sym.Embedding(mx.sym.var("data"), input_dim=V, output_dim=D,
                         name="emb")
    check_symbolic_forward(s, {"data": ids, "emb_weight": W},
                           [W[ids.astype(int)]])
    want = np.zeros_like(W)
    for b in range(2):
        for t in range(2):
            want[int(ids[b, t])] += og[b, t]
    check_symbolic_backward(
        s, {"data": ids, "emb_weight": W}, [og],
        {"emb_weight": want}, grad_req={"data": "null",
                                        "emb_weight": "write"},
        rtol=1e-5, atol=1e-6)


def test_reduce_ops_backward():
    x = RNG.randn(3, 4).astype(np.float32)
    og = np.float32(RNG.randn())
    check_symbolic_backward(mx.sym.sum(mx.sym.var("a")), {"a": x},
                            [np.asarray(og)], {"a": np.full_like(x, og)})
    # mean spreads the cotangent
    check_symbolic_backward(mx.sym.mean(mx.sym.var("a")), {"a": x},
                            [np.asarray(og)],
                            {"a": np.full_like(x, og / x.size)},
                            rtol=1e-5, atol=1e-6)


def test_transpose_reshape_roundtrip_backward():
    x = RNG.randn(2, 3, 4).astype(np.float32)
    og = RNG.randn(4, 3, 2).astype(np.float32)
    s = mx.sym.transpose(mx.sym.var("a"), axes=(2, 1, 0))
    check_symbolic_forward(s, {"a": x}, [x.transpose(2, 1, 0)])
    check_symbolic_backward(s, {"a": x}, [og],
                            {"a": og.transpose(2, 1, 0)})


def test_concat_split_backward():
    a = RNG.randn(2, 3).astype(np.float32)
    b = RNG.randn(2, 5).astype(np.float32)
    og = RNG.randn(2, 8).astype(np.float32)
    s = mx.sym.concat(mx.sym.var("a"), mx.sym.var("b"), dim=1)
    check_symbolic_forward(s, {"a": a, "b": b},
                           [np.concatenate([a, b], 1)])
    check_symbolic_backward(s, {"a": a, "b": b}, [og],
                            {"a": og[:, :3], "b": og[:, 3:]})


def test_where_and_clip_backward():
    x = RNG.randn(4, 4).astype(np.float32)
    og = RNG.randn(4, 4).astype(np.float32)
    s = mx.sym.clip(mx.sym.var("a"), a_min=-0.5, a_max=0.5)
    inside = ((x > -0.5) & (x < 0.5)).astype(np.float32)
    check_symbolic_forward(s, {"a": x}, [np.clip(x, -0.5, 0.5)])
    check_symbolic_backward(s, {"a": x}, [og], {"a": og * inside})


def test_dot_backward():
    a = RNG.randn(3, 4).astype(np.float32)
    b = RNG.randn(4, 5).astype(np.float32)
    og = RNG.randn(3, 5).astype(np.float32)
    s = mx.sym.dot(mx.sym.var("a"), mx.sym.var("b"))
    check_symbolic_forward(s, {"a": a, "b": b}, [a @ b], rtol=1e-4,
                           atol=1e-5)
    check_symbolic_backward(s, {"a": a, "b": b}, [og],
                            {"a": og @ b.T, "b": a.T @ og},
                            rtol=1e-4, atol=1e-5)


def test_layernorm_forward():
    x = RNG.randn(4, 6).astype(np.float32)
    gamma = RNG.rand(6).astype(np.float32) + 0.5
    beta = RNG.randn(6).astype(np.float32)
    mu = x.mean(-1, keepdims=True)
    sd = np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    want = (x - mu) / sd * gamma + beta
    s = mx.sym.LayerNorm(mx.sym.var("data"), name="ln", eps=1e-5)
    check_symbolic_forward(
        s, {"data": x, "ln_gamma": gamma, "ln_beta": beta},
        [want.astype(np.float32)], rtol=1e-4, atol=1e-4)
