"""Operator correctness tests (reference: tests/python/unittest/test_operator.py).

Oracle is numpy (SURVEY.md §4: CPU/numpy is the reference implementation
the accelerator backend is checked against).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def _rand(*shape):
    return np.random.uniform(-1, 1, shape).astype(np.float32)


def test_activation_ops():
    x = nd.array(_rand(3, 4))
    xn = x.asnumpy()
    assert np.allclose(nd.relu(x).asnumpy(), np.maximum(xn, 0))
    assert np.allclose(nd.sigmoid(x).asnumpy(), 1 / (1 + np.exp(-xn)), atol=1e-6)
    assert np.allclose(nd.tanh(x).asnumpy(), np.tanh(xn), atol=1e-6)
    assert np.allclose(nd.Activation(x, act_type="relu").asnumpy(),
                       np.maximum(xn, 0))


def test_softmax():
    x = nd.array(_rand(2, 5))
    y = nd.softmax(x).asnumpy()
    assert np.allclose(y.sum(axis=-1), 1.0, atol=1e-5)
    ref = np.exp(x.asnumpy()) / np.exp(x.asnumpy()).sum(-1, keepdims=True)
    assert np.allclose(y, ref, atol=1e-5)
    ls = nd.log_softmax(x).asnumpy()
    assert np.allclose(ls, np.log(ref), atol=1e-5)


def test_fully_connected():
    x = nd.array(_rand(4, 10))
    w = nd.array(_rand(6, 10))
    b = nd.array(_rand(6))
    out = nd.FullyConnected(x, w, b, num_hidden=6)
    assert out.shape == (4, 6)
    assert np.allclose(out.asnumpy(),
                       x.asnumpy() @ w.asnumpy().T + b.asnumpy(), atol=1e-5)


def test_convolution():
    # NCHW, reference layout (src/operator/nn/convolution.cc)
    x = nd.array(_rand(2, 3, 8, 8))
    w = nd.array(_rand(4, 3, 3, 3))
    b = nd.array(_rand(4))
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    assert out.shape == (2, 4, 6, 6)
    out2 = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4,
                          pad=(1, 1), stride=(2, 2))
    assert out2.shape == (2, 4, 4, 4)


def test_conv_grad():
    x = nd.array(_rand(1, 1, 5, 5))
    w = nd.array(_rand(1, 1, 3, 3))
    x.attach_grad(); w.attach_grad()
    with autograd.record():
        y = nd.Convolution(x, w, kernel=(3, 3), num_filter=1, no_bias=True)
        loss = y.sum()
    loss.backward()
    assert x.grad is not None and w.grad is not None
    assert x.grad.shape == x.shape and w.grad.shape == w.shape
    # numeric check on w
    eps = 1e-2
    wn = w.asnumpy()
    num = np.zeros_like(wn)
    import jax.numpy as jnp
    for i in range(3):
        for j in range(3):
            wp, wm = wn.copy(), wn.copy()
            wp[0, 0, i, j] += eps
            wm[0, 0, i, j] -= eps
            fp = nd.Convolution(x, nd.array(wp), kernel=(3, 3), num_filter=1,
                                no_bias=True).sum().asscalar()
            fm = nd.Convolution(x, nd.array(wm), kernel=(3, 3), num_filter=1,
                                no_bias=True).sum().asscalar()
            num[0, 0, i, j] = (fp - fm) / (2 * eps)
    assert np.allclose(w.grad.asnumpy(), num, atol=1e-2)


def test_pooling():
    x = nd.array(_rand(1, 2, 4, 4))
    y = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert y.shape == (1, 2, 2, 2)
    ref = x.asnumpy().reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
    assert np.allclose(y.asnumpy(), ref, atol=1e-6)
    ya = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    refa = x.asnumpy().reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
    assert np.allclose(ya.asnumpy(), refa, atol=1e-6)
    yg = nd.Pooling(x, kernel=(1, 1), global_pool=True, pool_type="avg")
    assert yg.shape == (1, 2, 1, 1)


def test_batchnorm_inference_and_training():
    x = nd.array(_rand(4, 3, 5, 5))
    gamma, beta = nd.ones((3,)), nd.zeros((3,))
    mean, var = nd.zeros((3,)), nd.ones((3,))
    y = nd.BatchNorm(x, gamma, beta, mean, var, fix_gamma=False)
    assert y.shape == x.shape


def test_dropout_modes():
    x = nd.ones((100, 100))
    # predict mode: identity
    y = nd.Dropout(x, p=0.5)
    assert np.allclose(y.asnumpy(), 1.0)
    with autograd.record():
        yt = nd.Dropout(x, p=0.5)
    m = yt.asnumpy()
    frac = (m == 0).mean()
    assert 0.3 < frac < 0.7  # ~half dropped
    kept = m[m != 0]
    assert np.allclose(kept, 2.0, atol=1e-5)  # inverted scaling


def test_elemwise_binary():
    a, b = nd.array(_rand(3, 4)), nd.array(_rand(3, 4))
    an, bn = a.asnumpy(), b.asnumpy()
    assert np.allclose(nd.maximum(a, b).asnumpy(), np.maximum(an, bn))
    assert np.allclose(nd.minimum(a, b).asnumpy(), np.minimum(an, bn))
    assert np.allclose(nd.hypot(a, b).asnumpy(), np.hypot(an, bn), atol=1e-5)


def test_where():
    cond = nd.array([1.0, 0.0, 1.0])
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([10.0, 20.0, 30.0])
    assert nd.where(cond, a, b).asnumpy().tolist() == [1.0, 20.0, 3.0]


def test_take_gather():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    idx = nd.array([0, 2], dtype="int32")
    t = nd.take(a, idx)
    assert t.shape == (2, 4)
    assert np.allclose(t.asnumpy(), a.asnumpy()[[0, 2]])


def test_embedding():
    data = nd.array([1, 0, 2], dtype="int32")
    weight = nd.array(_rand(5, 8))
    out = nd.Embedding(data, weight, input_dim=5, output_dim=8)
    assert out.shape == (3, 8)
    assert np.allclose(out.asnumpy(), weight.asnumpy()[[1, 0, 2]])


def test_layernorm():
    x = nd.array(_rand(2, 10))
    g, b = nd.ones((10,)), nd.zeros((10,))
    y = nd.LayerNorm(x, g, b)
    yn = y.asnumpy()
    assert np.allclose(yn.mean(-1), 0, atol=1e-5)
    assert np.allclose(yn.std(-1), 1, atol=1e-2)


def test_one_hot():
    x = nd.array([0, 2], dtype="int32")
    y = nd.one_hot(x, 3)
    assert np.allclose(y.asnumpy(), [[1, 0, 0], [0, 0, 1]])


def test_random_ops():
    u = nd.random.uniform(0, 1, shape=(1000,))
    un = u.asnumpy()
    assert 0 <= un.min() and un.max() <= 1
    assert 0.4 < un.mean() < 0.6
    n = nd.random.normal(0, 1, shape=(1000,))
    assert abs(n.asnumpy().mean()) < 0.2


def test_random_seed_determinism():
    mx.ndarray.random.seed(42)
    a = nd.random.uniform(shape=(5,)).asnumpy()
    mx.ndarray.random.seed(42)
    b = nd.random.uniform(shape=(5,)).asnumpy()
    assert np.allclose(a, b)


def test_linalg_ops():
    a_np = _rand(3, 3)
    spd = a_np @ a_np.T + 3 * np.eye(3, dtype=np.float32)
    chol = nd.linalg.potrf(nd.array(spd))
    assert np.allclose(chol.asnumpy() @ chol.asnumpy().T, spd, atol=1e-4)
    g = nd.linalg.gemm2(nd.array(a_np), nd.array(a_np))
    assert np.allclose(g.asnumpy(), a_np @ a_np, atol=1e-5)


def test_optimizer_update_ops():
    w = nd.array([1.0, 2.0])
    g = nd.array([0.1, 0.1])
    out = nd.sgd_update(w, g, lr=1.0, wd=0.0)
    assert np.allclose(w.asnumpy(), [0.9, 1.9], atol=1e-6)


def test_numeric_gradient_generic():
    """check_numeric_gradient analogue for a composite expression."""
    x = nd.array(_rand(4))
    x.attach_grad()
    with autograd.record():
        y = (nd.tanh(x) * nd.sigmoid(x)).sum()
    y.backward()
    eps = 1e-3
    xn = x.asnumpy()
    num = np.zeros_like(xn)
    for i in range(4):
        xp, xm = xn.copy(), xn.copy()
        xp[i] += eps
        xm[i] -= eps
        f = lambda v: (np.tanh(v) * (1 / (1 + np.exp(-v)))).sum()
        num[i] = (f(xp) - f(xm)) / (2 * eps)
    assert np.allclose(x.grad.asnumpy(), num, atol=1e-3)
