"""Gluon Block/HybridBlock/Trainer tests.

Modeled on the reference's tests/python/unittest/test_gluon.py: parameter
handling, layer correctness, hybridize consistency, trainer updates,
save/load round trips.
"""
import os
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier", ctx=mx.cpu())
    assert p.name == "weight"
    assert p.shape == (10, 10)
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()


def test_paramdict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.save("/tmp/test_paramdict.params")
    params.load("/tmp/test_paramdict.params", mx.cpu())


def test_dense():
    model = nn.Dense(128, activation="tanh", in_units=10,
                     flatten=False, prefix="test_")
    inputs = mx.nd.zeros((2, 3, 10))
    model.initialize()
    outputs = model(inputs)
    assert {p.name for p in model.collect_params().values()} == \
        {"test_weight", "test_bias"}
    assert outputs.shape == (2, 3, 128)

    model = nn.Dense(128, activation="relu", in_units=30, flatten=True,
                     prefix="test2_")
    inputs = mx.nd.zeros((17, 2, 5, 3))
    model.initialize()
    outputs = model(inputs)
    assert outputs.shape == (17, 128)


def test_dense_deferred_shape():
    model = nn.Dense(8)
    model.initialize()
    out = model(mx.nd.ones((4, 6)))
    assert model.weight.shape == (8, 6)
    assert out.shape == (4, 8)


@pytest.mark.parametrize("hybridize", [False, True])
def test_sequential_training_decreases_loss(hybridize):
    np.random.seed(42)
    mx.random.seed(42)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize()
    if hybridize:
        net.hybridize()
    x = mx.nd.array(np.random.randn(16, 8).astype(np.float32))
    y = mx.nd.array((np.random.randn(16) > 0).astype(np.float32))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(10):
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(16)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0]


def test_hybrid_matches_eager():
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.MaxPool2D(2), nn.Flatten(),
                nn.Dense(6))
    net.initialize()
    x = mx.nd.array(np.random.randn(2, 3, 8, 8).astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-5)


def test_batchnorm_running_stats_update_hybrid():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.BatchNorm(in_channels=3))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.randn(4, 3, 5, 5).astype(np.float32) + 2.0)
    before = net[0].running_mean.data().asnumpy().copy()
    with mx.autograd.record():
        net(x)
    after = net[0].running_mean.data().asnumpy()
    assert not np.allclose(before, after)
    # eval mode must use (not update) running stats
    before = after.copy()
    net(x)
    after = net[0].running_mean.data().asnumpy()
    np.testing.assert_allclose(before, after)


def test_dropout_active_only_in_training():
    net = nn.Dropout(0.5)
    net.initialize()
    x = mx.nd.ones((100, 100))
    out = net(x)  # predict mode: identity
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    with mx.autograd.record():
        out = net(x)
    assert (out.asnumpy() == 0).mean() > 0.3


def test_conv_layers_shapes():
    x1 = mx.nd.ones((2, 3, 16))
    x2 = mx.nd.ones((2, 3, 16, 16))
    x3 = mx.nd.ones((2, 3, 8, 8, 8))
    cases = [
        (nn.Conv1D(4, 3, padding=1), x1, (2, 4, 16)),
        (nn.Conv2D(4, 3, strides=2, padding=1), x2, (2, 4, 8, 8)),
        (nn.Conv3D(4, 3, padding=1), x3, (2, 4, 8, 8, 8)),
        (nn.Conv2DTranspose(4, 2, strides=2), x2, (2, 4, 32, 32)),
        (nn.MaxPool2D(2), x2, (2, 3, 8, 8)),
        (nn.AvgPool2D(2), x2, (2, 3, 8, 8)),
        (nn.GlobalAvgPool2D(), x2, (2, 3, 1, 1)),
        (nn.GlobalMaxPool2D(), x2, (2, 3, 1, 1)),
    ]
    for layer, x, want in cases:
        layer.initialize()
        got = layer(x).shape
        assert got == want, f"{layer}: {got} != {want}"


def test_norm_layers():
    x = mx.nd.array(np.random.randn(2, 6, 4, 4).astype(np.float32))
    for layer in (nn.LayerNorm(), nn.InstanceNorm(), nn.GroupNorm(2),
                  nn.BatchNorm()):
        layer.initialize()
        out = layer(x)
        assert out.shape == x.shape


def test_activations_layers():
    x = mx.nd.array(np.random.randn(3, 4).astype(np.float32))
    for layer in (nn.LeakyReLU(0.1), nn.ELU(), nn.SELU(), nn.Swish(),
                  nn.GELU(), nn.PReLU()):
        layer.initialize()
        assert layer(x).shape == x.shape


def test_embedding():
    layer = nn.Embedding(10, 4)
    layer.initialize()
    x = mx.nd.array([0, 2, 5])
    out = layer(x)
    assert out.shape == (3, 4)
    with mx.autograd.record():
        loss = layer(x).sum()
    loss.backward()
    g = layer.weight.grad().asnumpy()
    assert g[0].sum() != 0 and g[1].sum() == 0


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, in_units=8), nn.Dense(4, in_units=16))
    net.initialize()
    x = mx.nd.ones((2, 8))
    out1 = net(x).asnumpy()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(16, in_units=8), nn.Dense(4, in_units=16))
    net2.load_parameters(f)
    out2 = net2(x).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-6)


def test_losses():
    pred = mx.nd.array(np.random.randn(8, 4).astype(np.float32))
    label_sparse = mx.nd.array(np.random.randint(0, 4, (8,)))
    label_dense = mx.nd.array(np.abs(np.random.randn(8, 4)).astype(np.float32))
    sign = mx.nd.array(np.sign(np.random.randn(8, 4)).astype(np.float32))
    cases = [
        (gluon.loss.L2Loss(), (pred, label_dense)),
        (gluon.loss.L1Loss(), (pred, label_dense)),
        (gluon.loss.SoftmaxCrossEntropyLoss(), (pred, label_sparse)),
        (gluon.loss.SoftmaxCrossEntropyLoss(sparse_label=False),
         (pred, label_dense)),
        (gluon.loss.SigmoidBinaryCrossEntropyLoss(),
         (pred, (sign + 1) / 2)),
        (gluon.loss.KLDivLoss(), (mx.nd.log_softmax(pred, axis=-1),
                                  mx.nd.softmax(label_dense, axis=-1))),
        (gluon.loss.HuberLoss(), (pred, label_dense)),
        (gluon.loss.HingeLoss(), (pred, sign)),
        (gluon.loss.SquaredHingeLoss(), (pred, sign)),
        (gluon.loss.LogisticLoss(), (pred[:, 0], sign[:, 0])),
        (gluon.loss.PoissonNLLLoss(), (pred, label_dense)),
        (gluon.loss.TripletLoss(), (pred, label_dense, label_dense + 1)),
    ]
    for loss_fn, args in cases:
        out = loss_fn(*args)
        v = out.asnumpy()
        assert np.isfinite(v).all(), f"{loss_fn} produced non-finite loss"


def test_softmax_ce_loss_value():
    # uniform logits -> loss == log(C)
    pred = mx.nd.zeros((4, 10))
    label = mx.nd.array([1, 3, 5, 7])
    loss = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    np.testing.assert_allclose(loss.asnumpy(),
                               np.full(4, np.log(10)), rtol=1e-5)


def test_trainer_lr():
    p = gluon.Parameter("w", shape=(2,))
    p.initialize(ctx=mx.cpu())
    tr = gluon.Trainer({"w": p}, "sgd", {"learning_rate": 0.5})
    assert tr.learning_rate == 0.5
    tr.set_learning_rate(0.1)
    assert tr.learning_rate == 0.1


def test_trainer_sgd_step_math():
    p = gluon.Parameter("w", shape=(3,), init="zeros")
    p.initialize(ctx=mx.cpu())
    tr = gluon.Trainer({"w": p}, "sgd",
                       {"learning_rate": 1.0, "wd": 0.0})
    with mx.autograd.record():
        loss = (p.data() * mx.nd.array([1.0, 2.0, 3.0])).sum()
    loss.backward()
    tr.step(1)
    np.testing.assert_allclose(p.data().asnumpy(),
                               [-1.0, -2.0, -3.0], rtol=1e-6)


def test_trainer_save_load_states(tmp_path):
    p = gluon.Parameter("w", shape=(3,), init="ones")
    p.initialize(ctx=mx.cpu())
    tr = gluon.Trainer({"w": p}, "adam", {"learning_rate": 0.1})
    with mx.autograd.record():
        loss = (p.data() ** 2).sum()
    loss.backward()
    tr.step(1)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    tr2 = gluon.Trainer({"w": p}, "adam", {"learning_rate": 0.1})
    tr2.load_states(f)
    assert tr2._updaters[0].states


def test_block_naming():
    d1 = nn.Dense(4)
    d2 = nn.Dense(4)
    assert d1.prefix != d2.prefix
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(4), nn.Dense(4))
    names = list(net.collect_params().keys())
    assert all(n.startswith("model_dense") for n in names)


def test_collect_params_select():
    net = nn.HybridSequential(prefix="m_")
    with net.name_scope():
        net.add(nn.Dense(4), nn.BatchNorm())
    net.initialize()
    net(mx.nd.ones((2, 3)))
    w = net.collect_params(".*weight")
    assert all("weight" in k for k in w.keys())
    assert len(list(w.keys())) == 1


def test_hybrid_rng_varies_per_call():
    # dropout mask must differ call-to-call under jit (rng is a traced
    # input, not a baked constant)
    net = nn.Dropout(0.5)
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((64, 64))
    with mx.autograd.record():
        a = net(x).asnumpy()
        b = net(x).asnumpy()
    assert not np.allclose(a, b)


def test_lambda_blocks():
    add3 = nn.Lambda(lambda x: x + 3)
    assert float(add3(mx.nd.zeros((1,))).asnumpy()[0]) == 3.0
    hl = nn.HybridLambda("relu")
    assert float(hl(mx.nd.array([-1.0])).asnumpy()[0]) == 0.0
