"""Sharded, async, elastic checkpointing (resilience.sharded /
resilience.async_writer): parallel per-shard manifest checkpoints with
crash injection at every phase, background saves provably off the
training critical path, and resume that reshards to a different
mesh/replica count. All tier-1: fast, CPU-only, deterministic (gates
and counters, no wall-clock sleeps)."""
import json
import os
import shutil
import sys
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
import mxnet_tpu.autograd as ag
from mxnet_tpu import error, nd, resilience as rz
from mxnet_tpu.gluon import nn
from mxnet_tpu.observability import get_registry
from mxnet_tpu.resilience import async_writer as aw
from mxnet_tpu.resilience import checkpoint as ckpt_mod
from mxnet_tpu.resilience import faults
from mxnet_tpu.resilience import sharded as sh


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in ("MXNET_TPU_CKPT_ASYNC", "MXNET_TPU_CKPT_SHARDED",
                "MXNET_TPU_CKPT_WRITERS"):
        monkeypatch.delenv(var, raising=False)
    faults.reset()
    yield
    faults.reset()           # releases any armed gates first …
    aw._reset_for_tests()    # … so joining the writers cannot hang


def _arrays(rows=8):
    rs = np.random.RandomState(3)
    return {
        "w": nd.array(rs.randn(rows, 3).astype(np.float32)),
        "b": nd.array(rs.randn(2).astype(np.float32)),
        "s": nd.array(np.float32(4.25)),
    }


def _host(arrays):
    return {k: v.asnumpy() for k, v in arrays.items()}


def _mlp(seed=7):
    mx.nd.random.seed(seed)
    net = nn.Dense(2, in_units=4)
    net.initialize()
    return net


def _train(net, trainer, n):
    rs = np.random.RandomState(0)
    x = rs.randn(8, 4).astype(np.float32)
    y = rs.randn(8, 2).astype(np.float32)
    for _ in range(n):
        with ag.record():
            loss = ((net(nd.array(x)) - nd.array(y)) ** 2).sum()
        loss.backward()
        trainer.step(8)


# --------------------------------------------------------------- layout ----

def test_plan_layout_covers_and_balances():
    meta = {"w": ((8, 3), "float32"), "b": ((2,), "float32"),
            "s": ((), "float32"), "big": ((100, 4), "float32")}
    plan = sh.plan_layout(meta, 4)
    assert plan == sh.plan_layout(meta, 4)   # pure function of inputs
    for name in ("w", "big"):                # rows >= shards: row-split
        parts = plan[name]["parts"]
        assert [p["shard"] for p in parts] == [0, 1, 2, 3]
        assert parts[0]["start"] == 0
        assert parts[-1]["stop"] == meta[name][0][0]
        for a, b in zip(parts, parts[1:]):
            assert a["stop"] == b["start"]   # contiguous, no overlap
    # small arrays are whole-assigned within the shard count
    assert 0 <= plan["b"]["shard"] < 4 and 0 <= plan["s"]["shard"] < 4


def test_sharded_roundtrip_and_manifest(tmp_path):
    run = str(tmp_path / "run")
    arrays = _arrays()
    path = rz.write_checkpoint(run, arrays, step=5, num_shards=3)
    manifest = rz.validate_checkpoint(path)
    assert manifest["format"] == "mxtpu-ckpt-v2"
    assert manifest["layout"]["num_shards"] == 3
    shard_files = [f for f in manifest["files"]
                   if sh.parse_shard_filename(f)]
    assert len(shard_files) == 3
    for f in shard_files:
        assert os.path.isfile(os.path.join(path, f))
    assert sh.check_layout(path, manifest) == []
    back = rz.read_arrays(path, manifest)
    for k, v in _host(arrays).items():
        assert np.array_equal(back[k].asnumpy(), v), k


def test_v1_unsharded_remains_default(tmp_path):
    run = str(tmp_path / "run")
    path = rz.write_checkpoint(run, _arrays(), step=1)
    manifest = rz.validate_checkpoint(path)
    assert manifest["format"] == "mxtpu-ckpt-v1"
    assert ckpt_mod.DATA_FILE in manifest["files"]
    assert "layout" not in manifest


@pytest.mark.parametrize("new_world", [1, 2, 3, 5, 8])
def test_reshard_reader_assembles_any_world_size(tmp_path, new_world):
    run = str(tmp_path / "run")
    arrays = _arrays(rows=11)
    path = rz.write_checkpoint(run, arrays, step=1, num_shards=4)
    manifest = rz.validate_checkpoint(path)
    got = {}
    for shard_id in range(new_world):
        piece = sh.read_for_shard(path, manifest, shard_id, new_world)
        for name, v in piece.items():
            got.setdefault(name, []).append(v)
    for name, want in _host(arrays).items():
        have = got[name]
        v = np.concatenate(have, 0) if want.ndim and len(have) > 1 \
            else have[0]
        assert np.array_equal(v, want), name
    # the dry-run agrees with what the real reader just did
    plan = sh.reshard_check(path, manifest, new_world)
    assert plan["num_shards"] == new_world


# --------------------------------------------------------- fault matrix ----

# every phase of a sharded save, killed: the resumed run must always
# land on the newest COMMITTED checkpoint (step 1 if the crash preceded
# the step-2 manifest commit, step 2 after it)
_PHASES = [
    ("shard_first_bytes", lambda: faults.kill_write_at("shard-00000", 10),
     1),
    ("after_2_of_4_shards",
     lambda: faults.crash_at_point("ckpt.shard:2"), 1),
    ("shard_last_bytes", lambda: faults.kill_write_at("shard-00003", 40),
     1),
    ("manifest_body", lambda: faults.kill_write_at("MANIFEST.json", 5),
     1),
    ("manifest_rename",
     lambda: faults.crash_at_point("atomic.replace:MANIFEST.json"), 1),
    ("latest_pointer", lambda: faults.crash_at_point("ckpt.latest"), 2),
    ("prune", lambda: faults.crash_at_point("ckpt.prune"), 2),
]


@pytest.mark.parametrize("phase,arm,expect_step",
                         _PHASES, ids=[p[0] for p in _PHASES])
def test_crash_matrix_resumes_newest_committed(tmp_path, monkeypatch,
                                               phase, arm, expect_step):
    monkeypatch.setenv("MXNET_TPU_CKPT_WRITERS", "1")  # deterministic
    run = str(tmp_path / "run")
    vals = {1: _arrays(), 2: {k: nd.array(v.asnumpy() + 100.0)
                              for k, v in _arrays().items()}}
    assert rz.write_checkpoint(run, vals[1], step=1, num_shards=4)
    arm()
    with pytest.raises(rz.InjectedCrash):
        rz.write_checkpoint(run, vals[2], step=2, num_shards=4, keep=5)
    faults.reset()
    path, manifest = rz.latest_checkpoint(run)
    assert manifest["step"] == expect_step, phase
    back = rz.read_arrays(path, manifest)
    assert np.array_equal(back["w"].asnumpy(),
                          vals[expect_step]["w"].asnumpy())
    if expect_step == 1:
        # the partial step-2 directory exists but never validates: no
        # partial state is ever loadable
        partial = os.path.join(run, ckpt_mod.checkpoint_dirname(2))
        assert os.path.isdir(partial)
        with pytest.raises(error.CheckpointCorruptError):
            rz.validate_checkpoint(partial)
        # and pruning clears the unreadable stray
        rz.prune_checkpoints(run, keep=5)
        assert not os.path.isdir(partial)


def test_crashed_shard_write_then_clean_retry_commits(tmp_path,
                                                      monkeypatch):
    """After a crash left partial shard files behind, a restarted writer
    at the same step overwrites them atomically and commits."""
    monkeypatch.setenv("MXNET_TPU_CKPT_WRITERS", "1")
    run = str(tmp_path / "run")
    faults.crash_at_point("ckpt.shard:1")
    with pytest.raises(rz.InjectedCrash):
        rz.write_checkpoint(run, _arrays(), step=3, num_shards=2)
    faults.reset()
    path = rz.write_checkpoint(run, _arrays(), step=3, num_shards=2)
    manifest = rz.validate_checkpoint(path)
    assert manifest["step"] == 3
    assert sh.check_layout(path, manifest) == []


# ----------------------------------------------------- prune protection ----

def test_prune_never_removes_inflight_dir(tmp_path):
    run = str(tmp_path / "run")
    mgr = rz.CheckpointManager(run, keep=1, async_=True, num_shards=2)
    assert mgr.save(_arrays(), step=1).result(30)   # committed baseline
    gate = faults.block_at("checkpoint.write")
    handle = mgr.save(_arrays(), step=2)
    assert gate.wait_reached(), "writer never reached the write site"
    # while step-2 is mid-write: its dir is partial on disk, an
    # unprotected prune would delete it as 'invalid' AND would prune
    # step-1 (keep=1) — the checkpoint this save is superseding
    reg = get_registry()
    skipped = reg.counter("mxtpu_ckpt_prune_skipped_total",
                          labelnames=("reason",))
    before = skipped.labels(reason="in_flight").value
    rz.prune_checkpoints(run, keep=1)
    assert os.path.isdir(os.path.join(run,
                                      ckpt_mod.checkpoint_dirname(2)))
    assert os.path.isdir(os.path.join(run,
                                      ckpt_mod.checkpoint_dirname(1)))
    assert skipped.labels(reason="in_flight").value == before + 1
    gate.release()
    handle.result(30)
    faults.reset()
    # after the commit the manager's keep=1 retention already ran in the
    # writer (prune only after commit): step 1 is gone, step 2 stays
    path, manifest = mgr.latest()
    assert manifest["step"] == 2
    assert not os.path.isdir(os.path.join(
        run, ckpt_mod.checkpoint_dirname(1)))


def test_prune_counts_deletions(tmp_path):
    run = str(tmp_path / "run")
    for s in (1, 2, 3):
        rz.write_checkpoint(run, _arrays(), step=s)
    reg = get_registry()
    pruned = reg.counter("mxtpu_ckpt_pruned_total",
                         labelnames=("reason",))
    before = pruned.labels(reason="retention").value
    rz.prune_checkpoints(run, keep=1)
    assert pruned.labels(reason="retention").value == before + 2


# ------------------------------------------------------------ async path ----

def test_async_save_off_critical_path_and_overlap_counted(tmp_path,
                                                          monkeypatch):
    """THE overlap proof, no wall clock: the writer thread is parked on
    a gate mid-save while the training thread completes real optimizer
    steps; the overlap counter records them; release → commit."""
    monkeypatch.setenv("MXNET_TPU_CKPT_ASYNC", "1")
    run = str(tmp_path / "run")
    net = _mlp()
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1})
    _train(net, tr, 1)
    gate = faults.block_at("checkpoint.write")
    handle = tr.save_state(run)
    assert isinstance(handle, rz.AsyncSaveHandle) and not handle.done()
    assert gate.wait_reached()
    reg = get_registry()
    overlap = reg.counter("mxtpu_ckpt_async_overlap_steps_total")
    in_flight = reg.gauge("mxtpu_ckpt_async_in_flight")
    before = overlap.value
    assert in_flight.value == 1
    _train(net, tr, 3)                 # steps land while the save hangs
    assert overlap.value == before + 3
    gate.release()
    path = handle.result(30)
    faults.reset()
    assert rz.validate_checkpoint(path)["step"] == 1
    tr.ckpt_wait()
    assert in_flight.value == 0


def test_async_snapshot_is_immune_to_later_mutation(tmp_path,
                                                    monkeypatch):
    """Snapshot-then-write consistency: parameter updates issued AFTER
    submit must not leak into the bytes on disk."""
    monkeypatch.setenv("MXNET_TPU_CKPT_ASYNC", "1")
    run = str(tmp_path / "run")
    net = _mlp()
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1})
    _train(net, tr, 1)
    saved_w = net.weight.data().asnumpy().copy()
    gate = faults.block_at("checkpoint.write")
    handle = tr.save_state(run)
    assert gate.wait_reached()
    _train(net, tr, 4)   # mutates the live params while the save hangs
    assert not np.array_equal(net.weight.data().asnumpy(), saved_w)
    gate.release()
    handle.result(30)
    faults.reset()
    net2 = _mlp(seed=99)
    tr2 = mx.gluon.Trainer(net2.collect_params(), "sgd",
                           {"learning_rate": 0.1})
    tr2.restore_state(run)
    assert np.array_equal(net2.weight.data().asnumpy(), saved_w)


def test_async_write_error_typed_on_next_save(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_CKPT_ASYNC", "1")
    from mxnet_tpu.resilience import retry as retry_mod
    monkeypatch.setattr(retry_mod.time, "sleep", lambda s: None)
    run = str(tmp_path / "run")
    mgr = rz.CheckpointManager(run, keep=5)
    faults.script("checkpoint.write", [OSError("disk gone")] * 4)
    handle = mgr.save(_arrays(), step=1)
    with pytest.raises(rz.RetryError):
        handle.result(30)          # the handle carries the raw failure
    reg = get_registry()
    errors = reg.counter("mxtpu_ckpt_async_errors_total")
    assert errors.value >= 1
    # …and the NEXT save surfaces it typed instead of losing it
    with pytest.raises(error.CheckpointWriteError) as ei:
        mgr.save(_arrays(), step=2)
    assert isinstance(ei.value.__cause__, rz.RetryError)
    faults.reset()
    # the writer recovers: a clean save commits
    assert mgr.save(_arrays(), step=3).result(30)
    _, manifest = mgr.latest()
    assert manifest["step"] == 3


def test_async_backpressure_at_most_one_in_flight(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_CKPT_ASYNC", "1")
    run = str(tmp_path / "run")
    mgr = rz.CheckpointManager(run, keep=5)
    gate = faults.block_at("checkpoint.write")
    h1 = mgr.save(_arrays(), step=1)
    assert gate.wait_reached()
    # a second submit must JOIN save-1 first; release from a watcher
    # thread once save-2's submit begins waiting
    releaser = threading.Thread(target=gate.release)
    releaser.start()
    h2 = mgr.save(_arrays(), step=2)
    releaser.join()
    assert h1.result(30) and h2.result(30)
    faults.reset()
    _, manifest = mgr.latest()
    assert manifest["step"] == 2
    hist = get_registry().histogram(
        "mxtpu_ckpt_async_backpressure_seconds")
    assert hist.count >= 2        # every submit metered its join


def test_latest_checkpoint_joins_own_inflight_save(tmp_path,
                                                   monkeypatch):
    """A reader in the same process never races the background commit:
    latest_checkpoint joins the run dir's writer first."""
    monkeypatch.setenv("MXNET_TPU_CKPT_ASYNC", "1")
    run = str(tmp_path / "run")
    mgr = rz.CheckpointManager(run, keep=5)
    mgr.save(_arrays(), step=7)
    path, manifest = rz.latest_checkpoint(run)   # no explicit wait()
    assert manifest is not None and manifest["step"] == 7


# --------------------------------------------- trainer-level round-trips ----

def test_gluon_trainer_sharded_async_bit_exact(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_CKPT_ASYNC", "1")
    monkeypatch.setenv("MXNET_TPU_CKPT_SHARDED", "3")
    run = str(tmp_path / "run")
    netA = _mlp()
    trA = mx.gluon.Trainer(netA.collect_params(), "adam",
                           {"learning_rate": 0.05})
    _train(netA, trA, 3)
    handle = trA.save_state(run)
    trA.ckpt_wait()
    _train(netA, trA, 4)
    wA = [p._get_primary().asnumpy() for p in trA._params]

    netB = _mlp(seed=123)
    trB = mx.gluon.Trainer(netB.collect_params(), "adam",
                           {"learning_rate": 0.05})
    manifest = trB.restore_state(run)
    assert manifest["format"] == "mxtpu-ckpt-v2"
    assert manifest["layout"]["num_shards"] == 3
    assert manifest["step"] == 3 and trB._step_count == 3
    _train(netB, trB, 4)
    wB = [p._get_primary().asnumpy() for p in trB._params]
    for a, b in zip(wA, wB):
        assert np.array_equal(a, b)


def test_sharded_trainer_elastic_mesh_resume(tmp_path, monkeypatch):
    """Checkpoint saved under a dp=2 mesh restores under dp=4 and
    continues within the documented ~1 ULP reduction-order envelope
    (values cross mesh sizes, placement does not)."""
    from mxnet_tpu.parallel import ShardedTrainer, local_mesh
    monkeypatch.setenv("MXNET_TPU_CKPT_SHARDED", "2")
    run = str(tmp_path / "run")
    rs = np.random.RandomState(0)
    x = rs.randn(8, 4).astype(np.float32)
    y = rs.randn(8, 2).astype(np.float32)

    def make(seed, mesh_n):
        mx.nd.random.seed(seed)
        net = nn.Dense(2, in_units=4)
        net.initialize()
        return ShardedTrainer(net, lambda p, l: (p - l) ** 2, "adam",
                              {"learning_rate": 0.05},
                              mesh=local_mesh(mesh_n))

    stA = make(9, 2)
    for _ in range(3):
        stA.step(x, y)
    assert stA.save_state(run) is not None
    for _ in range(4):
        stA.step(x, y)
    pA = [np.asarray(stA.params[k]) for k in sorted(stA.params)]

    stB = make(31, 4)                      # DIFFERENT mesh size
    manifest = stB.restore_state(run)      # deferred to first step
    assert manifest["format"] == "mxtpu-ckpt-v2"
    assert manifest["extra"]["mesh"]["axes"] == {"dp": 2}
    for _ in range(4):
        stB.step(x, y)
    assert stB._step_count == 7
    pB = [np.asarray(stB.params[k]) for k in sorted(stB.params)]
    for a, b in zip(pA, pB):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=1e-7)


def test_compiled_step_kill_and_resume_elastic_replicas(tmp_path,
                                                        monkeypatch):
    """The full drill on the compiled hot path: SIGTERM lands mid-epoch,
    the loop saves a sharded+async checkpoint and exits; a 'restarted
    process' at a DIFFERENT replica count (1 ctx → 2 ctx) restores and
    finishes with final params bit-exact to the uninterrupted run and
    the loss trajectory within 1 ULP (the compiled step computes on the
    primary context and broadcasts, so replica count never changes the
    update math; the 2-ctx program's loss OUTPUT head may fuse
    differently — the documented ~1 ULP envelope). SGD on purpose:
    Adam-family optimizers advance their update count once per replica
    in the reference-compatible eager loop, so their trajectory is a
    function of replica count by SEMANTICS, not a checkpoint defect
    (docs/RESILIENCE.md)."""
    monkeypatch.setenv("MXNET_TPU_CKPT_ASYNC", "1")
    monkeypatch.setenv("MXNET_TPU_CKPT_SHARDED", "2")
    run = str(tmp_path / "run")
    total, k = 6, 3
    rs = np.random.RandomState(11)
    X = rs.randn(total, 8, 4).astype(np.float32)
    Y = rs.randn(total, 8, 2).astype(np.float32)
    sizes = [8] * (total - 1) + [5]        # ragged tail exercises buckets

    def build(seed, ctx=None):
        mx.nd.random.seed(seed)
        net = nn.Dense(2, in_units=4)
        net.initialize(ctx=ctx)
        tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.05})
        step = tr.compile_step(
            lambda a, b: ((net(a) - b) ** 2).sum(axis=1))
        return net, tr, step

    def run_steps(step, start, stop, guard=None, tr=None):
        losses = []
        for s in range(start, stop):
            n = sizes[s]
            losses.append(step(nd.array(X[s][:n]),
                               nd.array(Y[s][:n])).asnumpy())
            if guard is not None and guard.requested:
                tr.save_state(run)
                tr.ckpt_wait()
                break
        return losses

    # uninterrupted reference, single context
    net_r, tr_r, step_r = build(42)
    ref_losses = run_steps(step_r, 0, total)
    ref_params = [p._get_primary().asnumpy() for p in tr_r._params]

    # preempted run: SIGTERM at step k, checkpoint, clean exit
    net_a, tr_a, step_a = build(42)
    faults.sigterm_at_step(k)
    with rz.PreemptionGuard() as guard:
        losses_a = run_steps(step_a, 0, total, guard=guard, tr=tr_a)
    faults.reset()
    assert len(losses_a) == k
    _, manifest = rz.latest_checkpoint(run)
    assert manifest["step"] == k
    assert manifest["format"] == "mxtpu-ckpt-v2"

    # 'restarted process' at 2 replicas resumes
    ctxs = [mx.cpu(0), mx.cpu(1)]
    net_b, tr_b, step_b = build(77, ctx=ctxs)
    tr_b.restore_state(run)
    assert tr_b._step_count == k
    # bucket warmth travelled with the checkpoint: the resumed step pads
    # the ragged tail to the same bucket the saved run would have
    assert step_b._max_batch == 8
    losses_b = run_steps(step_b, k, total)

    full = losses_a + losses_b
    assert len(full) == total
    for s, (got, want) in enumerate(zip(full, ref_losses)):
        if s < k:
            assert np.array_equal(got, want), \
                "pre-preemption trajectory diverged"
        else:
            np.testing.assert_array_max_ulp(got, want, maxulp=1)
    # the STATE is bit-exact on every replica — the checkpoint round-
    # trip and the update math are exact across the replica change
    for p_b, want in zip(tr_b._params, ref_params):
        for ctx in p_b.list_ctx():
            assert np.array_equal(p_b.data(ctx).asnumpy(), want)


# ------------------------------------------------------------- verifier ----

def test_verify_checkpoint_sharded_exit_codes(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import verify_checkpoint as vc
    finally:
        sys.path.pop(0)
    run = str(tmp_path / "run")
    rz.write_checkpoint(run, _arrays(rows=8), step=1, num_shards=4)
    assert vc.main([run, "--quiet"]) == 0
    assert vc.main([run, "--quiet", "--reshard-check", "3"]) == 0
    assert vc.main([run, "--quiet", "--reshard-check", "16"]) == 0
    ck = os.path.join(run, ckpt_mod.checkpoint_dirname(1))
    # orphan shard file (stray of a crashed different-world save) → 2
    shutil.copy(os.path.join(ck, sh.shard_filename(0, 4)),
                os.path.join(ck, sh.shard_filename(9, 4)))
    assert vc.main([run, "--quiet"]) == 2
    os.remove(os.path.join(ck, sh.shard_filename(9, 4)))
    assert vc.main([run, "--quiet"]) == 0
    # layout coverage gap → 2, and the reshard dry-run refuses → 3
    mpath = os.path.join(ck, ckpt_mod.MANIFEST_NAME)
    with open(mpath) as f:
        manifest = json.load(f)
    manifest["layout"]["arrays"]["w"]["parts"] = \
        manifest["layout"]["arrays"]["w"]["parts"][:-1]
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    assert vc.main([run, "--quiet"]) == 2
    with pytest.raises(error.CheckpointCorruptError):
        sh.reshard_check(ck, manifest, 3)
    # a missing shard file fails CRC validation → nothing restorable
    os.remove(os.path.join(ck, sh.shard_filename(1, 4)))
    assert vc.main([run, "--quiet"]) == 1


def test_nd_save_accepts_host_numpy(tmp_path):
    p = str(tmp_path / "h.params")
    meta = nd.save(p, {"w": np.arange(6, dtype=np.float32).reshape(2, 3)})
    back = nd.load(p, manifest=meta["arrays"])
    assert np.array_equal(back["w"].asnumpy(),
                          np.arange(6, dtype=np.float32).reshape(2, 3))


def test_snapshot_arrays_copies():
    src = {"w": np.ones((2, 2), np.float32)}
    snap = rz.snapshot_arrays(src)
    src["w"][:] = 7.0
    assert np.array_equal(snap["w"], np.ones((2, 2), np.float32))
