"""Detection op tail: proposals, PS/deformable/rotated ROI ops,
Mask R-CNN targets, Hawkes LL (reference files cited in
mxnet_tpu/ops/contrib_det2.py docstrings).
"""
import numpy as np
import pytest

from mxnet_tpu.ops.registry import _REGISTRY


def _op(name, *args, **kw):
    import jax.numpy as jnp
    arrays = [jnp.asarray(a) for a in args]
    return _REGISTRY[name].impl(*arrays, **kw)


def test_proposal_basic():
    """A strong-scoring anchor at a known location must surface as the
    top proposal with (near) zero deltas."""
    rng = np.random.RandomState(0)
    H = W = 8
    A = 3                                  # 1 scale x 3 ratios
    cls = rng.rand(1, 2 * A, H, W).astype(np.float32) * 0.1
    cls[0, A + 1, 3, 5] = 0.99             # fg score of anchor 1 @ (3,5)
    bbox = np.zeros((1, 4 * A, H, W), np.float32)
    im_info = np.array([[128.0, 128.0, 1.0]], np.float32)
    rois = _op("_contrib_Proposal", cls, bbox, im_info,
               scales=(8,), ratios=(0.5, 1, 2), feature_stride=16,
               rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10,
               threshold=0.7, rpn_min_size=4)
    rois = np.asarray(rois)
    assert rois.shape == (10, 5)
    assert (rois[:, 0] == 0).all()
    # top roi must be inside the image and near the hot position
    x1, y1, x2, y2 = rois[0, 1:]
    assert 0 <= x1 < x2 <= 127 and 0 <= y1 < y2 <= 127
    cx, cy = (x1 + x2) / 2, (y1 + y2) / 2
    assert abs(cx - 5 * 16) < 24 and abs(cy - 3 * 16) < 24, (cx, cy)


def test_multi_proposal_batched():
    rng = np.random.RandomState(1)
    A, H, W = 3, 4, 4
    cls = rng.rand(2, 2 * A, H, W).astype(np.float32)
    bbox = rng.randn(2, 4 * A, H, W).astype(np.float32) * 0.1
    im_info = np.array([[64.0, 64.0, 1.0]] * 2, np.float32)
    rois, scores = _op("_contrib_MultiProposal", cls, bbox, im_info,
                       scales=(8,), ratios=(0.5, 1, 2),
                       feature_stride=16, rpn_pre_nms_top_n=20,
                       rpn_post_nms_top_n=5, output_score=True)
    rois = np.asarray(rois)
    assert rois.shape == (10, 5)
    assert (rois[:5, 0] == 0).all() and (rois[5:, 0] == 1).all()
    assert np.isfinite(np.asarray(scores)).all()


def test_psroi_pooling_uniform_plane():
    """On a channel-constant input, each output channel's bins must
    equal the constant of the mapped input channel."""
    p, g, od = 2, 2, 3
    C = od * g * g
    data = np.zeros((1, C, 8, 8), np.float32)
    for c in range(C):
        data[0, c] = c
    rois = np.array([[0, 0, 0, 7, 7]], np.float32)
    out = np.asarray(_op("_contrib_PSROIPooling", data, rois,
                         spatial_scale=1.0, output_dim=od,
                         pooled_size=p, group_size=g))
    assert out.shape == (1, od, p, p)
    for o in range(od):
        for i in range(p):
            for j in range(p):
                want = o * g * g + (i * g // p) * g + (j * g // p)
                assert out[0, o, i, j] == want, (o, i, j)


def test_deformable_conv_zero_offsets_match_conv():
    """With zero offsets the deformable conv must equal a plain conv
    (the defining property, reference deformable_convolution.cc)."""
    import jax.numpy as jnp
    from jax import lax
    rng = np.random.RandomState(2)
    x = rng.randn(2, 4, 7, 7).astype(np.float32)
    w = rng.randn(6, 4, 3, 3).astype(np.float32)
    off = np.zeros((2, 2 * 9, 7, 7), np.float32)
    out = np.asarray(_op("_contrib_DeformableConvolution", x, off, w,
                         kernel=(3, 3), pad=(1, 1), num_filter=6,
                         no_bias=True))
    want = np.asarray(lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)


def test_modulated_deformable_conv_mask_scales():
    """Unit mask == DCNv1; half mask halves the output (linearity in
    the mask, reference modulated_deformable_convolution.cc)."""
    rng = np.random.RandomState(3)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    off = np.zeros((1, 2 * 9, 5, 5), np.float32)
    ones = np.ones((1, 9, 5, 5), np.float32)
    out1 = np.asarray(_op("_contrib_ModulatedDeformableConvolution",
                          x, off, ones, w, kernel=(3, 3), pad=(1, 1),
                          num_filter=3, no_bias=True))
    out_h = np.asarray(_op("_contrib_ModulatedDeformableConvolution",
                           x, off, ones * 0.5, w, kernel=(3, 3),
                           pad=(1, 1), num_filter=3, no_bias=True))
    np.testing.assert_allclose(out_h, out1 * 0.5, rtol=1e-4, atol=1e-5)


def test_deformable_psroi_no_trans_matches_psroi_constant():
    p, g, od = 2, 2, 2
    C = od * g * g
    data = np.zeros((1, C, 8, 8), np.float32)
    for c in range(C):
        data[0, c] = c
    rois = np.array([[0, 1, 1, 6, 6]], np.float32)
    out = np.asarray(_op("_contrib_DeformablePSROIPooling", data, rois,
                         spatial_scale=1.0, output_dim=od,
                         group_size=g, pooled_size=p, no_trans=True,
                         sample_per_part=2))
    assert out.shape == (1, od, p, p)
    for o in range(od):
        for i in range(p):
            for j in range(p):
                want = o * g * g + i * g + j
                np.testing.assert_allclose(out[0, o, i, j], want,
                                           atol=1e-4)


def test_rroi_align_zero_angle_matches_axis_aligned():
    """theta=0 must reduce to ordinary bilinear ROI pooling of the
    axis-aligned box."""
    rng = np.random.RandomState(4)
    data = np.tile(np.arange(8, dtype=np.float32)[None, None, None, :],
                   (1, 1, 8, 1))          # value == x coordinate
    rois = np.array([[0, 3.5, 3.5, 4.0, 4.0, 0.0]], np.float32)
    out = np.asarray(_op("_contrib_RROIAlign", data, rois,
                         pooled_size=(2, 2), spatial_scale=1.0))
    assert out.shape == (1, 1, 2, 2)
    # columns sample around x = 2.5 and x = 4.5
    np.testing.assert_allclose(out[0, 0, :, 0], [2.5, 2.5], atol=0.01)
    np.testing.assert_allclose(out[0, 0, :, 1], [4.5, 4.5], atol=0.01)
    # rotating 90 degrees swaps the sampling axes of this symmetric roi:
    # the sampled x becomes cx - ly, so rows are constant across cols
    rois90 = np.array([[0, 3.5, 3.5, 4.0, 4.0, 90.0]], np.float32)
    out90 = np.asarray(_op("_contrib_RROIAlign", data, rois90,
                           pooled_size=(2, 2), spatial_scale=1.0))
    np.testing.assert_allclose(out90[0, 0, 0, :], [4.5, 4.5], atol=0.01)
    np.testing.assert_allclose(out90[0, 0, 1, :], [2.5, 2.5], atol=0.01)


def test_mrcnn_mask_target_shapes_and_onehot():
    rng = np.random.RandomState(5)
    B, R, M, H, W = 1, 3, 2, 16, 16
    NC, MS = 4, 8
    rois = np.array([[[0, 0, 15, 15], [4, 4, 11, 11],
                      [0, 0, 7, 7]]], np.float32)
    masks = (rng.rand(B, M, H, W) > 0.5).astype(np.float32)
    matches = np.array([[0, 1, 0]], np.int32)
    cls_t = np.array([[1, 3, 0]], np.int32)
    t, c = _op("_contrib_mrcnn_mask_target", rois, masks, matches,
               cls_t, num_rois=R, num_classes=NC, mask_size=(MS, MS))
    t, c = np.asarray(t), np.asarray(c)
    assert t.shape == (B, R, NC, MS, MS)
    assert c.shape == (B, R, NC, MS, MS)
    assert c[0, 0, 1].all() and not c[0, 0, 2].any()
    assert c[0, 1, 3].all()
    assert not c[0, 2].any()               # background roi: no class
    assert ((t >= 0) & (t <= 1)).all()


def test_hawkesll_oracle():
    """Numpy transcription of the reference kernel
    (hawkes_ll-inl.h:113) as the oracle."""
    rng = np.random.RandomState(6)
    N, T, K = 2, 5, 3
    mu = rng.rand(N, K).astype(np.float32) * 0.5 + 0.1
    alpha = rng.rand(K).astype(np.float32) * 0.5
    beta = rng.rand(K).astype(np.float32) + 0.5
    state = rng.rand(N, K).astype(np.float32)
    lags = rng.rand(N, T).astype(np.float32)
    marks = rng.randint(0, K, (N, T)).astype(np.int32)
    vl = np.array([5, 3], np.float32)
    mt = np.array([10.0, 8.0], np.float32)

    ll, out_state = _op("_contrib_hawkesll", mu, alpha, beta, state,
                        lags, marks, vl, mt)

    def oracle(i):
        st = state[i].copy()
        last = np.zeros(K)
        t = 0.0
        llv = 0.0
        for j in range(int(vl[i])):
            ci = marks[i, j]
            t += lags[i, j]
            d = t - last[ci]
            ed = np.exp(-beta[ci] * d)
            lam = mu[i, ci] + alpha[ci] * beta[ci] * st[ci] * ed
            comp = mu[i, ci] * d + alpha[ci] * st[ci] * (1 - ed)
            llv += np.log(lam) - comp
            st[ci] = 1 + st[ci] * ed
            last[ci] = t
        d = mt[i] - last
        ed = np.exp(-beta * d)
        llv -= (mu[i] * d + alpha * st * (1 - ed)).sum()
        return llv, st * ed

    for i in range(N):
        want_ll, want_st = oracle(i)
        np.testing.assert_allclose(float(np.asarray(ll)[i]), want_ll,
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(out_state)[i], want_st,
                                   rtol=1e-4)
