"""mxnet_tpu.serving.llm: continuous-batching decode engine.

The decode-serving contract pinned here (ISSUE 8 acceptance criteria):

- greedy continuous-batched decoding is BIT-IDENTICAL (token for
  token) to per-sequence eager decoding for a mixed batch of >= 8
  sequences with different prompt lengths and different stop steps,
  with sequences admitted and evicted mid-run;
- after ``warmup()`` a mixed prefill/decode workload (varying prompt
  lengths, staggered arrivals) triggers ZERO XLA recompiles (asserted
  via the jax.monitoring backend_compile counter);
- KV pressure preempts and resumes sequences without changing their
  token streams (restart-based recompute preemption);
- drain on shutdown/preemption runs in-flight sequences to completion
  within the deadline or rejects them with a typed
  ``SequenceEvictedError`` carrying the tokens generated so far —
  never a silent drop;
- ``mxtpu_llm_tokens_per_sec``, ``mxtpu_llm_ttft_seconds`` and
  ``mxtpu_llm_kv_blocks_in_use`` land in one Prometheus exposition.
"""
import os
import signal
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.serving import ServerClosed  # noqa: E402
from mxnet_tpu.serving.llm import (  # noqa: E402
    TinyDecoder, DecoderConfig, LLMEngine, LLMServer, Sequence,
    SequenceEvictedError, greedy_decode_reference)
from mxnet_tpu.resilience import PreemptionGuard  # noqa: E402

VOCAB = 17
BS = 8          # KV block size
CTX = 64


@pytest.fixture(scope="module")
def model():
    return TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=16, num_layers=2, num_heads=2,
        d_ff=32, max_context=CTX))


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(seed=0)


def _prompts(rng, n, lo=1, hi=25):
    return [rng.randint(0, VOCAB, size=int(rng.randint(lo, hi))).tolist()
            for _ in range(n)]


# ------------------------------------------ (a) bit-identical decode --
def test_continuous_batching_bit_identical_mixed_batch(model, params):
    """>= 8 sequences, ragged prompt lengths (incl. block-boundary
    edges), different stop steps, fewer slots than sequences so
    admission/eviction churns mid-run: every token stream must equal
    per-sequence eager greedy decoding exactly."""
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX)
    eng.warmup()
    rng = np.random.RandomState(2)
    cases = []
    # block-boundary prompt lengths first, then a ragged mix
    for plen in (BS - 1, BS, BS + 1):
        cases.append((rng.randint(0, VOCAB, size=plen).tolist(),
                      int(rng.randint(1, 12))))
    for prompt in _prompts(rng, 6):
        cases.append((prompt, int(rng.randint(1, 12))))
    assert len(cases) >= 8
    seqs = []
    # staggered admission: half now, half injected mid-run
    for prompt, n in cases[:5]:
        s = Sequence(prompt, n)
        seqs.append(s)
        eng.add(s)
    steps = 0
    injected = 5
    while eng.has_work() or injected < len(cases):
        if injected < len(cases) and (steps % 2 == 0
                                      or not eng.has_work()):
            prompt, n = cases[injected]
            s = Sequence(prompt, n)
            seqs.append(s)
            eng.add(s)
            injected += 1
        eng.step()
        steps += 1
        assert steps < 1000
    for (prompt, n), s in zip(cases, seqs):
        assert s.state == "finished"
        ref = greedy_decode_reference(model, params, prompt, n)
        assert s.output_tokens() == ref, \
            f"seq {s.seq_id} (prompt {len(prompt)}, n={n}) diverged"
    assert eng.cache.allocator.num_used == 0
    eng.cache.allocator.check()


def test_stop_token_ends_generation_early(model, params):
    eng = LLMEngine(model, params, max_seqs=2, block_size=BS,
                    max_context=CTX)
    eng.warmup()
    prompt = [3, 1, 4, 1, 5]
    free_run = greedy_decode_reference(model, params, prompt, 20)
    stop = free_run[4]               # stop at the 5th generated token
    s = Sequence(prompt, 20, stop_token=stop)
    eng.add(s)
    while eng.has_work():
        eng.step()
    ref = greedy_decode_reference(model, params, prompt, 20,
                                  stop_token=stop)
    assert s.output_tokens() == ref
    assert s.output_tokens()[-1] == stop
    assert len(s.output_tokens()) < 20
    assert s.finish_reason == "stop_token"


# --------------------------------------------- (b) zero recompiles ---
def test_zero_recompiles_mixed_prefill_decode_staggered(model, params):
    """After warmup, staggered arrivals with varying prompt lengths mix
    prefill and decode launches every which way — and compile
    NOTHING (the backend_compile counter must not move)."""
    # same max_seqs as the bit-identical test above: the compiled
    # program set is shared, so this test's warmup compiles nothing
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX)
    eng.warmup()
    rng = np.random.RandomState(4)
    with serving.CompileCounter() as cc:
        pending = _prompts(rng, 9)
        live = []
        for prompt in pending[:3]:
            s = Sequence(prompt, int(rng.randint(1, 10)))
            live.append(s)
            eng.add(s)
        injected = 3
        steps = 0
        while eng.has_work() or injected < len(pending):
            if steps % 3 == 0 and injected < len(pending):
                s = Sequence(pending[injected],
                             int(rng.randint(1, 10)))
                live.append(s)
                eng.add(s)
                injected += 1
            eng.step()
            steps += 1
            assert steps < 1000
    assert cc.count == 0, \
        f"{cc.count} XLA recompiles after warmup (shape leak)"
    assert all(s.state == "finished" for s in live)


def test_warmup_covers_every_bucket_once(model, params):
    """A second warmup over the same engine compiles nothing: the ONE
    chunked-step program is everything steady state can reach."""
    eng = LLMEngine(model, params, max_seqs=2, block_size=BS,
                    max_context=CTX)
    first = eng.warmup()
    # every (packed length x table width) rung of the ONE flat
    # program, in its greedy and sampled variants, plus the prefix
    # cache's fixed-shape copy-on-write program — nothing else is
    # reachable in steady state
    expect = {f"step_t{t}mb{mb}_{v}" for t in eng._t_buckets
              for mb in eng._mb_widths for v in ("greedy", "sampled")}
    if eng.prefix_enabled:
        expect.add("cow_copy")
    assert set(first) == expect
    assert max(eng._t_buckets) == eng.max_seqs * eng.q_tokens
    assert eng.cache.max_blocks_per_seq in eng._mb_widths
    with serving.CompileCounter() as cc:
        eng.warmup()
    assert cc.count == 0


# ------------------------------------------------- (c) preemption ----
def test_kv_pressure_preempts_and_resumes_exact_stream(model, params):
    """A pool too small for all sequences at full length forces
    restart-based preemption; deterministic greedy decoding must
    resume the exact token stream."""
    eng = LLMEngine(model, params, max_seqs=3, block_size=BS,
                    max_context=CTX, num_blocks=11)   # 10 usable, 8/seq
    eng.warmup()
    rng = np.random.RandomState(5)
    seqs, orig = [], {}
    for prompt in _prompts(rng, 3, lo=4, hi=12):
        s = Sequence(prompt, 25)
        orig[s.seq_id] = list(prompt)
        seqs.append(s)
        eng.add(s)
    preempts = 0
    steps = 0
    while eng.has_work():
        preempts += sum(1 for k, _ in eng.step() if k == "preempted")
        steps += 1
        assert steps < 3000
    assert preempts >= 1, "pool was sized to force preemption"
    for s in seqs:
        ref = greedy_decode_reference(model, params, orig[s.seq_id],
                                      s.max_new_tokens)
        assert s.output_tokens() == ref
    assert eng.cache.allocator.num_used == 0
    eng.cache.allocator.check()


# ------------------------------------------------------ (d) drain ----
def test_drain_deadline_evicts_with_partial_tokens(model, params):
    """Shutdown under a deadline: sequences that cannot finish resolve
    with SequenceEvictedError CARRYING their tokens so far.

    Deterministic (no wall-clock race): generations are sized near the
    context cap (~56 tokens each), we POLL until real decode progress
    exists, then shut down with an explicit ``deadline_ms=0`` — the
    worker's next loop iteration is already past the deadline, so no
    amount of CPU speed can run the remaining ~50 steps per sequence
    to completion first."""
    srv = LLMServer(model, params, name="drain_t", max_seqs=2,
                    block_size=BS, max_context=CTX)
    srv.warmup()
    srv.start()
    want = CTX - 8                       # far more than can ever finish
    futs = [srv.submit([1, 2, 3], want) for _ in range(4)]
    deadline = time.monotonic() + 30
    while (srv.stats()["tokens_generated"] < 4
           and time.monotonic() < deadline):
        time.sleep(0.005)                # wait for partial progress
    assert srv.stats()["tokens_generated"] >= 4
    srv.shutdown(drain=True, deadline_ms=0.0)   # evict now, typed
    done = evicted = partial = 0
    for f in futs:
        try:
            r = f.result(timeout=10)
            done += 1
            assert len(r.tokens) == want
        except SequenceEvictedError as e:
            evicted += 1
            assert e.reason == "drain_deadline"
            assert isinstance(e.tokens, list)
            if e.tokens:
                partial += 1
    assert done + evicted == 4          # nothing silently dropped
    assert evicted >= 1                 # deadline actually bound
    assert partial >= 1                 # tokens-so-far really carried
    with pytest.raises(ServerClosed):
        srv.submit([1], 1)


def test_drain_without_deadline_completes_everything(model, params):
    srv = LLMServer(model, params, name="drain_full", max_seqs=2,
                    block_size=BS, max_context=CTX)
    srv.warmup()
    srv.start()
    futs = [srv.submit([i + 1, 2], 6) for i in range(5)]
    srv.shutdown(drain=True)            # unbounded: run all to the end
    for f in futs:
        assert len(f.result(timeout=10).tokens) == 6


def test_shutdown_without_drain_rejects_live_sequences(model, params):
    srv = LLMServer(model, params, name="nodrain", max_seqs=2,
                    block_size=BS, max_context=CTX)
    srv.warmup()
    srv.start()
    futs = [srv.submit([1, 2], 40) for _ in range(3)]
    srv.shutdown(drain=False)
    for f in futs:
        with pytest.raises(SequenceEvictedError) as ei:
            f.result(timeout=10)
        assert ei.value.reason == "shutdown"


def test_preemption_guard_drains_decode_sequences(model, params):
    """SIGUSR1 through PreemptionGuard: admission closes and every
    in-flight decode sequence either completes within the deadline or
    resolves with a typed eviction — never lost."""
    guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        srv = LLMServer(model, params, name="guard_t", max_seqs=2,
                        block_size=BS, max_context=CTX)
        srv.warmup()
        srv.start()
        srv.attach_preemption_guard(guard, poll_s=0.01,
                                    deadline_ms=2000.0)
        futs = [srv.submit([1, 2, 3], 5) for _ in range(4)]
        os.kill(os.getpid(), signal.SIGUSR1)
        resolved = 0
        for f in futs:
            try:
                r = f.result(timeout=30)
                assert len(r.tokens) == 5
            except SequenceEvictedError:
                pass
            resolved += 1
        assert resolved == 4
        deadline = time.monotonic() + 10
        while srv.running and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(ServerClosed):
            srv.submit([1], 1)
    finally:
        guard.uninstall()


def test_model_server_drain_deadline_env(monkeypatch):
    """Satellite: the single-shot ModelServer honors
    MXNET_TPU_SERVE_DRAIN_DEADLINE_MS — a drain that cannot finish in
    time fails the remaining queue with ServerClosed instead of
    serving it; every Future still resolves."""
    monkeypatch.setenv("MXNET_TPU_SERVE_DRAIN_DEADLINE_MS", "250")

    def slow(batch):
        time.sleep(0.2)
        return batch * 2.0

    srv = serving.ModelServer(slow, buckets=[1], max_delay_ms=0.1,
                              item_shape=(2,), dtype="float32",
                              name="slow_t").start()
    futs = [srv.submit(np.full(2, i, np.float32)) for i in range(8)]
    t0 = time.monotonic()
    srv.shutdown(drain=True)            # env deadline binds
    assert time.monotonic() - t0 < 5.0  # not 8 * 0.2s + slack
    served = failed = 0
    for f in futs:
        try:
            f.result(timeout=10)
            served += 1
        except ServerClosed:
            failed += 1
    assert served + failed == 8         # nothing silently dropped
    assert failed >= 1                  # the deadline actually cut in


def test_engine_error_closes_admission_and_resolves_futures(model,
                                                            params):
    """A dying engine loop must not leave the server half-alive: every
    live Future resolves with the error and later submits raise
    ServerClosed instead of enqueueing onto a dead worker."""
    srv = LLMServer(model, params, name="err_t", max_seqs=2,
                    block_size=BS, max_context=CTX)
    srv.warmup()
    srv.start()
    boom = RuntimeError("injected engine failure")

    def bad_step():
        raise boom

    srv.engine.step = bad_step
    fut = srv.submit([1, 2, 3], 5)
    with pytest.raises(RuntimeError, match="injected engine failure"):
        fut.result(timeout=10)
    deadline = time.monotonic() + 10
    while srv.running and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(ServerClosed):
        srv.submit([1], 1)


# ---------------------------------------------------- (e) metrics ----
def test_llm_metrics_in_one_exposition(model, params):
    from mxnet_tpu.observability import get_registry
    srv = LLMServer(model, params, name="metrics_t", max_seqs=2,
                    block_size=BS, max_context=CTX)
    srv.warmup()
    srv.start()
    futs = [srv.submit([1 + i, 2], 4) for i in range(3)]
    for f in futs:
        f.result(timeout=30)
    st = srv.stats()
    srv.shutdown()
    assert st["requests_completed"] == 3
    assert st["tokens_generated"] == 12
    assert st["tokens_per_sec"] > 0
    assert st["ttft_ms"]["p50"] <= st["ttft_ms"]["p99"]
    text = get_registry().expose()
    for needed in ("mxtpu_llm_tokens_per_sec", "mxtpu_llm_ttft_seconds",
                   "mxtpu_llm_kv_blocks_in_use",
                   "mxtpu_llm_requests_completed_total",
                   "mxtpu_llm_decode_steps_total"):
        assert needed in text, f"{needed} missing from exposition"
    # the tools-side checker must accept the exposition wholesale
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    try:
        from metrics_dump import parse_exposition
    finally:
        sys.path.pop(0)
    samples = parse_exposition(text)
    key = ("mxtpu_llm_requests_completed_total",
           (("server", "metrics_t"),))
    assert samples[key] == 3


# ----------------------------------------------- (f) deploy/export ---
def test_decoder_artifact_round_trips_through_server(model, params,
                                                     tmp_path):
    path = str(tmp_path / "decoder.mxtpu")
    mx.deploy.export_decoder(model, params, path)
    m2, p2 = mx.deploy.load_decoder(path)
    assert m2.config.to_dict() == model.config.to_dict()
    prompt = [2, 7, 1]
    ref = greedy_decode_reference(model, params, prompt, 6)
    srv = LLMServer(m2, p2, name="artifact_t", max_seqs=2,
                    block_size=BS, max_context=CTX)
    srv.warmup()
    srv.start()
    res = srv.generate(prompt, 6, timeout=30)
    srv.shutdown()
    assert res.tokens == ref


def test_bad_artifact_rejected(tmp_path):
    with pytest.raises(ValueError):
        mx.deploy.load_decoder(b"NOTANARTIFACT")


# ------------------------------------------------- (g) validation ----
def test_submit_validation(model, params):
    srv = LLMServer(model, params, name="valid_t", max_seqs=2,
                    block_size=BS, max_context=CTX)
    srv.warmup()
    srv.start()
    with pytest.raises(ValueError):
        srv.submit(list(range(1, CTX + 2))[:CTX], 1)   # no room left
    with pytest.raises(ValueError):
        srv.submit([VOCAB + 5], 1)                     # out of vocab
    with pytest.raises(ValueError):
        srv.submit([1], 0)                             # nothing to gen
    with pytest.raises(ValueError):
        srv.submit([], 1)                              # empty prompt
    srv.shutdown()


def test_submit_deadline_and_queue_knobs(model, params, monkeypatch):
    """Overload knobs on the decode path: env-var resolution plus the
    fail-fast submit behaviors (expired budget, bounded queue)."""
    monkeypatch.setenv("MXNET_TPU_SERVE_MAX_QUEUE", "7")
    monkeypatch.setenv("MXNET_TPU_SERVE_DEADLINE_MS", "500")
    srv = LLMServer(model, params, name="knobs_t", max_seqs=2,
                    block_size=BS, max_context=CTX)
    assert srv.max_queue == 7
    assert srv.default_deadline_ms == 500.0
    srv.warmup()
    srv.start()
    with pytest.raises(serving.DeadlineExceededError):
        srv.submit([1, 2], 4, deadline_ms=0)    # budget already gone
    # a deadline generous enough never to bind: serves normally
    res = srv.submit([1, 2], 3, deadline_ms=60000).result(timeout=30)
    assert len(res.tokens) == 3
    srv.shutdown()
    assert srv.stats()["deadline_expired"] == 1
    # typed-hierarchy satellite: eviction/deadline errors share the
    # exported base
    assert issubclass(SequenceEvictedError, serving.ServingError)
    assert issubclass(serving.DeadlineExceededError,
                      serving.ServingError)


def test_engine_sizing_guards(model, params):
    with pytest.raises(ValueError):
        LLMEngine(model, params, max_seqs=2, block_size=BS,
                  max_context=CTX - 1)                 # not page-aligned
    with pytest.raises(ValueError):
        LLMEngine(model, params, max_seqs=2, block_size=BS,
                  max_context=CTX, num_blocks=4)       # < 1 full seq
    with pytest.raises(ValueError):
        LLMEngine(model, params, max_seqs=2, block_size=BS,
                  max_context=CTX, prefill_chunk=0)    # no chunk
    with pytest.raises(ValueError):
        LLMEngine(model, params, max_seqs=2, block_size=BS,
                  max_context=CTX, spec_k=-1,
                  draft_model=model, draft_params=params)
