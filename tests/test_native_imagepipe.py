"""Native C++ decode/augment pipeline (image.ImageRecordIterNative).

Reference behavior being matched: src/io/iter_image_recordio_2.cc:887
(ImageRecordIter worker threads: JPEG decode, resize/crop/mirror,
normalize, batch). Plus one property the reference lacks and we pin:
bit-determinism independent of thread count.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.image import ImageRecordIterNative, native_pipeline_available

pytestmark = pytest.mark.skipif(
    not native_pipeline_available(),
    reason="native image pipeline unavailable (no toolchain/OpenCV)")


def _make_rec(prefix, n, hw=(32, 24), num_classes=5, seed=0):
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    imgs = []
    for i in range(n):
        img = rng.randint(0, 255, hw + (3,), dtype=np.uint8)
        imgs.append(img)
        header = recordio.IRHeader(0, float(i % num_classes), i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=95,
                                           img_fmt=".jpg"))
    rec.close()
    return imgs


@pytest.fixture(scope="module")
def rec20(tmp_path_factory):
    d = tmp_path_factory.mktemp("native_pipe")
    prefix = str(d / "data")
    imgs = _make_rec(prefix, 20)
    return prefix, imgs


def test_labels_and_order(rec20):
    prefix, _ = rec20
    it = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                               data_shape=(3, 24, 24), batch_size=5)
    labels = np.concatenate([b.label[0].asnumpy() for b in it])
    assert labels.tolist() == [float(i % 5) for i in range(20)]
    it.close()


def test_decode_matches_python_decoder(rec20):
    """Center-crop-free case: native decode == cv2 decode of the same
    JPEG bytes (both are libjpeg; allow tiny IDCT wiggle)."""
    prefix, _ = rec20
    it = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                               data_shape=(3, 32, 24), batch_size=4)
    batch = next(it)
    arr = batch.data[0].asnumpy()  # NCHW float32
    reader = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                        "r")
    for i in range(4):
        header, img_bytes = recordio.unpack(reader.read_idx(i))
        ref = mx.image.imdecode(img_bytes).asnumpy()  # HWC RGB uint8
        got = arr[i].transpose(1, 2, 0)
        assert np.abs(got - ref.astype(np.float32)).max() <= 2.0
    it.close()


def test_nhwc_layout_and_normalize(rec20):
    prefix, _ = rec20
    mean, std = (100.0, 110.0, 120.0), (50.0, 55.0, 60.0)
    raw = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                                data_shape=(3, 32, 24), batch_size=4)
    norm = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                                 data_shape=(32, 24, 3), batch_size=4,
                                 layout="NHWC", mean=mean, std=std)
    a = next(raw).data[0].asnumpy().transpose(0, 2, 3, 1)
    b = next(norm).data[0].asnumpy()
    expect = (a - np.asarray(mean)) / np.asarray(std)
    assert np.allclose(b, expect, atol=1e-5)
    raw.close()
    norm.close()


def test_deterministic_across_thread_counts(rec20):
    prefix, _ = rec20
    outs = []
    for nthreads in (1, 8):
        it = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                                   data_shape=(3, 16, 16), batch_size=4,
                                   shuffle=True, rand_crop=True,
                                   rand_mirror=True, seed=7,
                                   preprocess_threads=nthreads)
        outs.append(np.stack([b.data[0].asnumpy() for b in it]))
        it.close()
    np.testing.assert_array_equal(outs[0], outs[1])


def test_epochs_reshuffle_and_pad(rec20):
    prefix, _ = rec20
    it = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=8,
                               shuffle=True, seed=3)
    ep0 = [next(it) for _ in range(3)]
    assert [b.pad for b in ep0] == [0, 0, 4]  # 20 = 2*8 + 4
    with pytest.raises(StopIteration):
        next(it)
    labels0 = np.concatenate([b.label[0].asnumpy() for b in ep0])
    it.reset()
    labels1 = np.concatenate([b.label[0].asnumpy()
                              for b in [next(it) for _ in range(3)]])
    assert labels0.shape == labels1.shape == (24,)
    assert not np.array_equal(labels0, labels1)  # epoch reshuffled
    it.close()


def test_sharding_disjoint(rec20):
    prefix, _ = rec20
    seen = []
    for part in (0, 1):
        it = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                                   data_shape=(3, 16, 16), batch_size=10,
                                   num_parts=2, part_index=part)
        seen.append(set(next(it).label[0].asnumpy().tolist()))
        it.close()
    # each shard holds 10 of the 20 samples; labels cycle mod 5 so both
    # shards see every class but from disjoint records
    assert len(seen[0]) == len(seen[1]) == 5


def test_mirror_flips_pixels(rec20):
    prefix, _ = rec20
    base = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                                 data_shape=(3, 32, 24), batch_size=1)
    a = next(base).data[0].asnumpy()[0]
    found_flip = False
    for seed in range(6):
        mir = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                                    data_shape=(3, 32, 24), batch_size=1,
                                    rand_mirror=True, seed=seed)
        m = next(mir).data[0].asnumpy()[0]
        mir.close()
        if np.array_equal(m, a[:, :, ::-1]):
            found_flip = True
            break
    base.close()
    assert found_flip, "rand_mirror never produced a horizontal flip"


def _write_bad_rec(tmp_path):
    prefix = str(tmp_path / "bad")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    header = recordio.IRHeader(0, 1.0, 0, 0)
    rec.write_idx(0, recordio.pack(header, b"not a jpeg at all"))
    img = np.full((16, 16, 3), 200, dtype=np.uint8)
    rec.write_idx(1, recordio.pack_img(recordio.IRHeader(0, 2.0, 1, 0),
                                       img, quality=95, img_fmt=".jpg"))
    rec.close()
    return prefix


def test_corrupt_record_zero_filled_and_warned(tmp_path, caplog):
    prefix = _write_bad_rec(tmp_path)
    it = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=2)
    with caplog.at_level("WARNING", logger="mxnet_tpu"):
        batch = next(it)
    data = batch.data[0].asnumpy()
    assert np.all(data[0] == 0.0)          # corrupt -> zero-filled
    assert data[1].mean() > 100.0          # good record decoded
    assert it.error_count == 1
    # silent zero-fill is not silent anymore (advisor r4): the first bad
    # record must produce a visible warning carrying the native message
    assert any("failed to decode" in r.message for r in caplog.records)
    assert it.last_error != ""
    it.close()


def test_corrupt_record_strict_raises(tmp_path):
    from mxnet_tpu.base import MXNetError
    prefix = _write_bad_rec(tmp_path)
    it = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=2,
                               strict=True)
    with pytest.raises(MXNetError, match="failed to decode"):
        next(it)
    it.close()


def test_std_only_normalizes(rec20):
    """std without mean must still divide (regression: silently raw)."""
    prefix, _ = rec20
    raw = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                                data_shape=(3, 32, 24), batch_size=4)
    scaled = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                                   data_shape=(3, 32, 24), batch_size=4,
                                   std=(2.0, 4.0, 8.0))
    a = next(raw).data[0].asnumpy()
    b = next(scaled).data[0].asnumpy()
    assert np.allclose(b, a / np.asarray([2.0, 4.0, 8.0])[:, None, None],
                       atol=1e-5)
    raw.close()
    scaled.close()


def test_discard_last_batch(rec20):
    prefix, _ = rec20
    it = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                               data_shape=(3, 16, 16), batch_size=8,
                               last_batch_handle="discard")
    batches = list(it)
    assert len(batches) == 2 and all(b.pad == 0 for b in batches)
    it.close()


def test_mxdataiter_prefers_native(rec20):
    prefix, _ = rec20
    it = mx.io.MXDataIter("ImageRecordIter", path_imgrec=prefix + ".rec",
                          data_shape=(3, 16, 16), batch_size=4,
                          preprocess_threads=2, mean_r=1.0, mean_g=2.0,
                          mean_b=3.0)
    assert isinstance(it, ImageRecordIterNative)
    it.close()
    # out-of-scope option falls back to the Python augmenter pipeline
    it2 = mx.io.MXDataIter("ImageRecordIter", path_imgrec=prefix + ".rec",
                           data_shape=(3, 16, 16), batch_size=4,
                           brightness=0.5)
    from mxnet_tpu.image import ImageIter
    assert isinstance(it2, ImageIter)


def test_multi_float_labels(tmp_path):
    """label_width > 1: the reference packs extra label floats after the
    IRHeader (flag = count); the native pipe must surface all of them."""
    prefix = str(tmp_path / "ml")
    rng = np.random.RandomState(0)
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "w")
    labels = rng.randn(6, 3).astype(np.float32)
    for i in range(6):
        img = rng.randint(0, 255, (16, 16, 3), dtype=np.uint8)
        header = recordio.IRHeader(0, labels[i], i, 0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=95,
                                           img_fmt=".jpg"))
    rec.close()
    it = ImageRecordIterNative(path_imgrec=prefix + ".rec",
                              data_shape=(3, 16, 16), batch_size=3,
                              label_width=3)
    got = np.concatenate([b.label[0].asnumpy() for b in it])
    np.testing.assert_allclose(got, labels, rtol=1e-6)
    it.close()
