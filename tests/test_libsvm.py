"""LibSVMIter: sparse libsvm text -> CSR batches.

Reference: src/io/iter_libsvm.cc:200 (MXNET_REGISTER_IO_ITER(LibSVMIter));
the first test is the reference docstring example, pinned exactly.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray.sparse import CSRNDArray

DOC_EXAMPLE = """1.0 0:0.5 2:1.2
-2.0
-3.0 0:0.6 1:2.4 2:1.2
4 2:-1.2
"""


@pytest.fixture
def doc_file(tmp_path):
    p = tmp_path / "data.t"
    p.write_text(DOC_EXAMPLE)
    return str(p)


def test_reference_docstring_example(doc_file):
    it = mx.io.LibSVMIter(data_libsvm=doc_file, data_shape=(3,),
                          batch_size=3)
    b = next(it)
    assert isinstance(b.data[0], CSRNDArray)
    np.testing.assert_array_equal(
        b.data[0].asnumpy(),
        np.array([[0.5, 0.0, 1.2], [0.0, 0.0, 0.0], [0.6, 2.4, 1.2]],
                 np.float32))
    np.testing.assert_array_equal(b.label[0].asnumpy(), [1.0, -2.0, -3.0])
    b2 = next(it)
    # round_batch: wraps to the beginning, pad reports wrapped rows
    np.testing.assert_array_equal(
        b2.data[0].asnumpy(),
        np.array([[0.0, 0.0, -1.2], [0.5, 0.0, 1.2], [0.0, 0.0, 0.0]],
                 np.float32))
    np.testing.assert_array_equal(b2.label[0].asnumpy(), [4.0, 1.0, -2.0])
    assert b2.pad == 2
    with pytest.raises(StopIteration):
        next(it)
    it.reset()
    again = next(it)
    np.testing.assert_array_equal(again.label[0].asnumpy(),
                                  [1.0, -2.0, -3.0])


def test_separate_label_file(tmp_path):
    d = tmp_path / "d.t"
    d.write_text("0 1:2.0\n0 0:1.0\n")
    lf = tmp_path / "l.t"
    lf.write_text("0:1.0 2:3.0\n1.5\n")
    it = mx.io.LibSVMIter(data_libsvm=str(d), data_shape=(2,),
                          label_libsvm=str(lf), label_shape=(3,),
                          batch_size=2)
    b = next(it)
    # sparse cols populate the dense label row; a bare value fills col 0
    np.testing.assert_array_equal(
        b.label[0].asnumpy(), [[1.0, 0.0, 3.0], [1.5, 0.0, 0.0]])
    np.testing.assert_array_equal(
        b.data[0].asnumpy(), [[0.0, 2.0], [1.0, 0.0]])


def test_num_parts_partition(doc_file):
    seen = []
    for part in range(2):
        it = mx.io.LibSVMIter(data_libsvm=doc_file, data_shape=(3,),
                              batch_size=2, num_parts=2, part_index=part)
        seen.extend(next(it).label[0].asnumpy().tolist())
    assert sorted(seen) == [-3.0, -2.0, 1.0, 4.0]


def test_directory_input(tmp_path):
    (tmp_path / "a.t").write_text("1.0 0:1.0\n")
    (tmp_path / "b.t").write_text("2.0 1:2.0\n")
    it = mx.io.LibSVMIter(data_libsvm=str(tmp_path), data_shape=(2,),
                          batch_size=2)
    b = next(it)
    np.testing.assert_array_equal(b.data[0].asnumpy(),
                                  [[1.0, 0.0], [0.0, 2.0]])
    np.testing.assert_array_equal(b.label[0].asnumpy(), [1.0, 2.0])


def test_provide_data_and_mxdataiter_dispatch(doc_file):
    it = mx.io.MXDataIter("LibSVMIter", data_libsvm=doc_file,
                          data_shape=(3,), batch_size=2)
    assert it.provide_data[0].shape == (2, 3)
    assert it.provide_label[0].shape == (2,)
    assert isinstance(next(it).data[0], CSRNDArray)


def test_malformed_input_rejected(tmp_path):
    bad = tmp_path / "bad.t"
    bad.write_text("1.0 2:1.0 1:2.0\n")  # non-ascending indices
    with pytest.raises(ValueError, match="ascending"):
        mx.io.LibSVMIter(data_libsvm=str(bad), data_shape=(3,),
                         batch_size=1)
    oob = tmp_path / "oob.t"
    oob.write_text("1.0 5:1.0\n")
    with pytest.raises(ValueError, match="feature index"):
        mx.io.LibSVMIter(data_libsvm=str(oob), data_shape=(3,),
                         batch_size=1)
    with pytest.raises(ValueError, match="round_batch"):
        mx.io.LibSVMIter(data_libsvm=str(tmp_path / "bad.t"),
                         data_shape=(3,), batch_size=1,
                         round_batch=False)


def test_scalar_labels_in_sparse_form(tmp_path):
    d = tmp_path / "d.t"
    d.write_text("0:1.0\n1:2.0\n")
    lf = tmp_path / "l.t"
    lf.write_text("0:1.5\n0:2.5\n")  # labels as sparse 0:v entries
    it = mx.io.LibSVMIter(data_libsvm=str(d), data_shape=(2,),
                          label_libsvm=str(lf), batch_size=2)
    np.testing.assert_array_equal(next(it).label[0].asnumpy(),
                                  [1.5, 2.5])


def test_num_parts_no_empty_part(tmp_path):
    f = tmp_path / "d.t"
    f.write_text("".join(f"{i}.0 0:1.0\n" for i in range(5)))
    got = []
    for part in range(4):  # 5 rows over 4 parts: every part non-empty
        it = mx.io.LibSVMIter(data_libsvm=str(f), data_shape=(1,),
                              batch_size=1, num_parts=4, part_index=part)
        it_labels = []
        while True:
            try:
                b = next(it)
            except StopIteration:
                break
            if b.pad == 0:
                it_labels.extend(b.label[0].asnumpy().tolist())
        got.extend(it_labels)
    assert sorted(got) == [0.0, 1.0, 2.0, 3.0, 4.0]
