"""One donated SPMD program per training step (ISSUE 14).

``Trainer.compile_step(mesh=...)`` / ``parallel.ShardedTrainer`` lower
the whole step — forward + loss + backward + IN-PROGRAM gradient reduce
+ fused optimizer apply — onto one buffer-donating SPMD executable over
a device mesh. These tests pin the acceptance contract on the
conftest's 8 virtual CPU devices:

- bit-exact parity vs the replica-loop semantics (per-shard gradients
  summed in device order, applied through the same user-facing
  ``Trainer.step``) for sgd+momentum and adam,
- exactly 1 device dispatch per steady-state step and ZERO recompiles
  across lr / loss-scale / batch-tail changes (backend_compile-counter
  pinned via the jax.monitoring bridge),
- AMP rescale parity and the in-program overflow skip under sharding,
- elastic resume: a run killed mid-checkpoint on a 4-device mesh
  (``faults.crash_at_point`` on the PR 7 ``ckpt.*`` sites) resumes on a
  2- AND an 8-device mesh bit-exactly with the uninterrupted run.

Tier-1 budget guard: the module shares ONE warmed dp=2 mesh/program set
(module-scoped fixture) for the fast gates; the full device-count x
optimizer parity sweep is ``slow`` with the dp=2 fast case retained.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, parallel
from mxnet_tpu.gluon import nn, Trainer
import mxnet_tpu.autograd as ag
from mxnet_tpu.observability import get_registry, \
    install_jax_monitoring_bridge

LOSS = gluon.loss.SoftmaxCrossEntropyLoss()


def _mesh(n):
    return parallel.local_mesh(n)


def _build(seed=0):
    """Tiny MLP with deferred init resolved (same-seed builds draw
    identical host-rng streams)."""
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", in_units=6),
                nn.Dense(4, in_units=16))
    net.initialize(init=mx.initializer.Xavier())
    with ag.pause(train_mode=False):
        net(nd.array(np.zeros((1, 6), np.float32)))
    return net


def _data(steps=8, n=32):
    rng = np.random.RandomState(7)
    X = rng.randn(steps, n, 6).astype(np.float32)
    Y = (np.arange(steps * n).reshape(steps, n) % 4).astype(np.float32)
    return X, Y


def _replica_loop_run(net, opt, opt_args, sizes, dp, lrs=None,
                      scaler=None):
    """The replica-loop semantics the SPMD program replaces: per-shard
    eager gradients summed in device order, applied through the same
    user-facing ``Trainer.step``. This is the bit-exactness oracle —
    XLA's dp-psum reduces partial per-shard sums in exactly this
    association."""
    tr = Trainer(net.collect_params(), opt, dict(opt_args))
    if scaler is not None:
        from mxnet_tpu import amp
        amp.init_trainer(tr, loss_scaler=scaler)
    X, Y = _data(len(sizes))
    losses = []
    for s, n in enumerate(sizes):
        if lrs:
            tr.set_learning_rate(lrs[s % len(lrs)])
        per = n // dp
        assert per * dp == n, "oracle shards must tile the batch"
        shard_grads, shard_losses = [], []
        for c in range(dp):
            lo, hi = c * per, (c + 1) * per
            with ag.record():
                l = LOSS(net(nd.array(X[s][lo:hi])),
                         nd.array(Y[s][lo:hi]))
                if scaler is not None:
                    from mxnet_tpu import amp
                    with amp.scale_loss(l, tr) as scaled:
                        pass
            (scaled if scaler is not None else l).backward()
            shard_grads.append({k: p.list_grad()[0]._data.copy()
                                for k, p in
                                net.collect_params().items()
                                if p.grad_req != "null"})
            shard_losses.append(l.asnumpy())
        for k, p in net.collect_params().items():
            if p.grad_req == "null":
                continue
            tot = shard_grads[0][k]
            for g in shard_grads[1:]:
                tot = tot + g[k]
            p.list_grad()[0]._data = tot
        tr.step(n)
        losses.append(np.concatenate(shard_losses))
    return tr, losses


def _spmd_run(net, opt, opt_args, sizes, mesh, lrs=None, scaler=None,
              **step_kw):
    tr = Trainer(net.collect_params(), opt, dict(opt_args))
    if scaler is not None:
        from mxnet_tpu import amp
        amp.init_trainer(tr, loss_scaler=scaler)
    step = tr.compile_step(lambda x, y: LOSS(net(x), y), mesh=mesh,
                           **step_kw)
    X, Y = _data(len(sizes))
    losses = []
    for s, n in enumerate(sizes):
        if lrs:
            tr.set_learning_rate(lrs[s % len(lrs)])
        losses.append(step(nd.array(X[s][:n]), nd.array(Y[s][:n]))
                      .asnumpy())
    return tr, step, losses


def _params_of(net):
    return [p.data().asnumpy().copy()
            for _, p in sorted(net.collect_params().items())]


def _assert_bitexact(net_a, net_b, what=""):
    for (ka, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                 sorted(net_b.collect_params().items())):
        assert (pa.data().asnumpy() == pb.data().asnumpy()).all(), \
            f"{what} parameter {ka} differs (not bit-exact)"


# --------------------------------------------------------- fast gates --
# One warmed dp=2 mesh/program set shared by the parity, dispatch-count
# and recompile gates (tier-1 budget: the programs compile ONCE per
# module, every fast test below reads this run).

SIZES = [32, 32, 20, 32, 20, 32, 32]      # 20-row ragged tails pad to 32
LRS = [0.05, 0.02, 0.05, 0.01]


@pytest.fixture(scope="module")
def warmed_dp2():
    install_jax_monitoring_bridge()
    reg = get_registry()
    compiles = reg.counter("mxtpu_xla_compile_total")
    sdispatch = reg.counter("mxtpu_spmd_step_dispatch_total")

    net = _build(0)
    tr = Trainer(net.collect_params(), "sgd",
                 {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4})
    step = tr.compile_step(lambda x, y: LOSS(net(x), y), mesh=_mesh(2))
    X, Y = _data(len(SIZES))
    losses = []
    marks = []                      # (compiles, spmd_dispatches) per step
    for s, n in enumerate(SIZES):
        tr.set_learning_rate(LRS[s % len(LRS)])
        losses.append(step(nd.array(X[s][:n]), nd.array(Y[s][:n]))
                      .asnumpy())
        marks.append((compiles.value, sdispatch.value))

    net_o = _build(0)
    _, oracle_losses = _replica_loop_run(
        net_o, "sgd",
        {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4},
        SIZES, dp=2, lrs=LRS)
    return {"net": net, "tr": tr, "step": step, "losses": losses,
            "marks": marks, "net_o": net_o,
            "oracle_losses": oracle_losses}


def test_spmd_parity_dp2(warmed_dp2):
    """Fast gate: sgd+momentum+wd over full buckets AND padded ragged
    tails with per-step lr changes. Losses are bit-exact through the
    first tail STEP inclusive (pad rows cannot touch real rows'
    forward, and the full-bucket updates before it were bitwise —
    otherwise the tail step's losses would already differ). A padded
    tail's UPDATE carries the replica path's documented
    reduction-reassociation tolerance (the batch-summed gradient sees
    the +0 pad rows — test_bucket_tail_semantics), so the weights after
    the tail-bearing run match the oracle to that tolerance; the
    all-full-bucket runs (adam below, the slow sweep) stay bitwise end
    to end."""
    w = warmed_dp2
    assert w["step"].last_reason is None, w["step"].last_reason
    for s in range(3):          # 32, 32, 20-row tail
        assert (w["losses"][s] == w["oracle_losses"][s]).all(), \
            f"step {s} (n={SIZES[s]}) losses not bit-exact"
        assert w["losses"][s].shape == (SIZES[s],), \
            "pad rows leaked into the returned per-sample losses"
    for (ka, pa), (_, pb) in zip(
            sorted(w["net"].collect_params().items()),
            sorted(w["net_o"].collect_params().items())):
        np.testing.assert_allclose(
            pa.data().asnumpy(), pb.data().asnumpy(),
            rtol=1e-6, atol=1e-7, err_msg=f"dp=2 sgd {ka}")


def test_spmd_single_dispatch_steady_state(warmed_dp2):
    """Fast gate: after the warmup step, every step is EXACTLY one SPMD
    program launch — no per-context loop, no host-side allreduce
    dispatches."""
    marks = warmed_dp2["marks"]
    for s in range(1, len(marks)):
        d = marks[s][1] - marks[s - 1][1]
        assert d == 1, f"step {s} took {d} SPMD dispatches, not 1"


def test_spmd_zero_recompile_lr_and_tails(warmed_dp2):
    """Fast gate: lr changes and ragged tails mapped onto the warm
    bucket never recompile the SPMD program (backend_compile counter
    pinned). Steps 0-1 warm the bucket-32 program + tail glue; steps
    2.. must be compile-free — including the first 20-row tail, which
    reuses the padded bucket."""
    marks = warmed_dp2["marks"]
    assert marks[-1][0] - marks[2][0] == 0, \
        "an lr change or warmed batch tail recompiled the SPMD step"


def test_spmd_adam_parity_bitexact_dp2():
    """Adam (bias-correction counters under the traced step) stays
    bit-exact with the replica-loop oracle on the dp=2 mesh."""
    sizes = [32, 32, 32, 32]
    net_s = _build(1)
    _, step, sl = _spmd_run(net_s, "adam",
                            {"learning_rate": 1e-3, "wd": 1e-3},
                            sizes, _mesh(2))
    assert step.last_reason is None, step.last_reason
    net_o = _build(1)
    _, ol = _replica_loop_run(net_o, "adam",
                              {"learning_rate": 1e-3, "wd": 1e-3},
                              sizes, dp=2)
    for s in range(len(sizes)):
        assert (sl[s] == ol[s]).all(), f"step {s} losses not bit-exact"
    _assert_bitexact(net_s, net_o, "dp=2 adam")


def test_spmd_amp_rescale_and_overflow_skip_dp2():
    """AMP under sharding: the LossScaler rescale rides as a traced
    scalar (bit-exact with the replica-loop AMP oracle), and a forced
    overflow skips the update IN-PROGRAM on every shard — weights
    unchanged, scale halves, no step tick, and the post-overflow scale
    change does NOT recompile the SPMD program."""
    from mxnet_tpu import amp
    install_jax_monitoring_bridge()
    reg = get_registry()
    compiles = reg.counter("mxtpu_xla_compile_total")
    sizes = [16, 16, 16]

    net_s = _build(3)
    tr_s, step, _ = _spmd_run(
        net_s, "sgd", {"learning_rate": 0.05}, sizes, _mesh(2),
        scaler=amp.LossScaler(init_scale=64.0, target_dtype="float16"))
    assert step.last_reason is None, step.last_reason
    assert tr_s._amp_loss_scaler.loss_scale == 64.0
    net_o = _build(3)
    _replica_loop_run(
        net_o, "sgd", {"learning_rate": 0.05}, sizes, dp=2,
        scaler=amp.LossScaler(init_scale=64.0, target_dtype="float16"))
    _assert_bitexact(net_s, net_o, "dp=2 amp")

    # overflow: a loss scale beyond float32 range makes every shard's
    # gradients non-finite; the in-program where() keeps the weights
    X, Y = _data(2, 16)
    net_v = _build(4)
    tr_v = Trainer(net_v.collect_params(), "sgd",
                   {"learning_rate": 0.05})
    amp.init_trainer(tr_v, loss_scaler=amp.LossScaler(
        init_scale=1e39, target_dtype="float16"))
    stepv = tr_v.compile_step(lambda x, y: LOSS(net_v(x), y),
                              mesh=_mesh(2))
    before = _params_of(net_v)
    with pytest.warns(UserWarning, match="overflow"):
        stepv(nd.array(X[0]), nd.array(Y[0]))
    assert stepv.last_reason is None, stepv.last_reason
    assert tr_v._amp_loss_scaler.loss_scale == 5e38
    assert tr_v._step_count == 0
    for b, a in zip(before, _params_of(net_v)):
        assert (a == b).all(), "weights changed despite overflow skip"
    # the scale is a traced scalar: recovery steps keep halving it
    # (5e38, 2.5e38, ... are each still-overflowing DISTINCT values)
    # until an update lands — with zero recompiles across all of them
    import warnings
    c0 = compiles.value
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for _ in range(8):
            stepv(nd.array(X[1]), nd.array(Y[1]))
            if tr_v._step_count:
                break
    assert tr_v._step_count == 1, \
        "loss scale never recovered below the overflow threshold"
    assert compiles.value - c0 == 0, \
        "a loss-scale change recompiled the SPMD step"


# ------------------------------------------------- device-count sweep --

@pytest.mark.slow   # multi-mesh parity sweep: one program per (mesh,opt)
@pytest.mark.parametrize("n_dev", [1, 8])
@pytest.mark.parametrize("opt,args", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 1e-3, "wd": 1e-3}),
])
def test_spmd_parity_sweep(n_dev, opt, args):
    """Full acceptance sweep: 1- and 8-device meshes (the dp=2 case is
    the retained fast gate above), sgd + adam, full buckets and padded
    tails, bit-exact vs the replica-loop oracle."""
    sizes = [32, 32, 16, 32]
    net_s = _build(5)
    _, step, sl = _spmd_run(net_s, opt, args, sizes, _mesh(n_dev))
    assert step.last_reason is None, step.last_reason
    net_o = _build(5)
    _, ol = _replica_loop_run(net_o, opt, args, sizes, dp=n_dev)
    for s in range(len(sizes)):
        assert (sl[s] == ol[s]).all(), \
            f"{n_dev}-device step {s} losses not bit-exact"
    _assert_bitexact(net_s, net_o, f"{n_dev}-device {opt}")


# --------------------------------------------------- elastic resume --

def _sharded_data(steps=6, n=32):
    rng = np.random.RandomState(11)
    X = rng.randn(steps, n, 6).astype(np.float32)
    Y = (np.arange(steps * n).reshape(steps, n) % 4).astype(np.float32)
    return X, Y


def _run_sharded(tr, X, Y, lo, hi):
    for s in range(lo, hi):
        tr.step(X[s], Y[s])


def test_spmd_elastic_resume_kill_mid_ckpt_4_to_2_and_8(tmp_path):
    """The PR 7 elastic-resume contract under the SPMD step: a 4-device
    adam run is killed MID-CHECKPOINT (faults crash point on the
    sharded manifest commit), and the newest COMMITTED checkpoint
    restores onto 2-, 4- and 8-device meshes. The contract, precisely:

    - the restored state (params + every adam slot + step counter +
      RNG) is BIT-EXACT with the saving run's state at the commit —
      sharding is a placement property, the manifest carries exact
      host values, any mesh size can read them;
    - resumed on the SAME mesh shape, the continuation is bit-exact
      with the uninterrupted run end to end;
    - resumed on a DIFFERENT dp extent, the continuation equals the
      target mesh's own deterministic trajectory; vs the source mesh
      it carries the documented reduction-reassociation tolerance
      (a dp-psum over 2/8 shards re-associates the very gradient sum
      a 4-shard psum computed — bitwise cross-extent equality is a
      no-reassociation property, same as the bucket-tail contract)."""
    import jax
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.faults import InjectedCrash
    X, Y = _sharded_data()
    run = str(tmp_path / "run")
    opt_args = {"learning_rate": 1e-3}

    tr_a = parallel.ShardedTrainer(_build(8), LOSS, "adam", opt_args,
                                   mesh=_mesh(4))
    _run_sharded(tr_a, X, Y, 0, 3)
    tr_a.save_state(run, num_shards=2)        # committed @ step 3
    saved = [np.asarray(parallel.mesh.to_host(tr_a._params[n]))
             for n in tr_a._names]
    saved_slots = [np.asarray(parallel.mesh.to_host(leaf))
                   for n in tr_a._trainable
                   for leaf in jax.tree_util.tree_leaves(
                       tr_a._opt_states[n])]
    _run_sharded(tr_a, X, Y, 3, 4)
    faults.crash_at_point("ckpt.manifest")    # die publishing the manifest
    try:
        with pytest.raises(InjectedCrash):
            tr_a.save_state(run, num_shards=2)
    finally:
        faults.reset()
    _run_sharded(tr_a, X, Y, 4, 6)            # uninterrupted to step 6
    final = [np.asarray(parallel.mesh.to_host(tr_a._params[n]))
             for n in tr_a._names]

    for n_dev in (2, 4, 8):
        tr_b = parallel.ShardedTrainer(_build(9), LOSS, "adam",
                                       dict(opt_args), mesh=_mesh(n_dev))
        manifest = tr_b.restore_state(run)
        assert manifest["extra"]["step_count"] == 3, \
            "resume did not fall back to the newest COMMITTED checkpoint"
        assert manifest["extra"]["mesh"]["axes"]["dp"] == 4, \
            "manifest lost the saving mesh's shape"
        tr_b._ensure_init(X[3])               # applies the restore
        for i, (a, b) in enumerate(zip(
                saved, (np.asarray(parallel.mesh.to_host(tr_b._params[n]))
                        for n in tr_b._names))):
            assert (a == b).all(), \
                f"restored param #{i} not bit-exact on {n_dev} devices"
        restored_slots = [np.asarray(parallel.mesh.to_host(leaf))
                          for n in tr_b._trainable
                          for leaf in jax.tree_util.tree_leaves(
                              tr_b._opt_states[n])]
        for i, (a, b) in enumerate(zip(saved_slots, restored_slots)):
            assert (a == b).all(), \
                f"restored adam slot #{i} not bit-exact on {n_dev} devices"
        _run_sharded(tr_b, X, Y, 3, 6)
        assert tr_b._step_count == 6
        resumed = [np.asarray(parallel.mesh.to_host(tr_b._params[n]))
                   for n in tr_b._names]
        if n_dev == 4:
            for i, (a, b) in enumerate(zip(final, resumed)):
                assert (a == b).all(), \
                    f"param #{i} diverged resuming on the same mesh"
        else:
            for i, (a, b) in enumerate(zip(final, resumed)):
                np.testing.assert_allclose(
                    a, b, rtol=1e-6, atol=1e-7,
                    err_msg=f"param #{i} resuming 4->{n_dev} devices")


def test_sharded_trainer_lr_scheduler_no_tracer_leak():
    """A scheduler rides OUTSIDE the trace: the traced step seeds
    num_update/_index_update_count with the traced step counter for
    Adam-family bias correction, and must restore them — a leaked
    tracer killed the second step's host-side schedule sync
    (UnexpectedTracerError) before the counters joined the saved/
    restored hyper state. Pins: steps keep running, the schedule
    actually decays lr, and the optimizer's counters stay host ints."""
    from mxnet_tpu import lr_scheduler
    sched = lr_scheduler.FactorScheduler(step=1, factor=0.5)
    tr = parallel.ShardedTrainer(
        _build(12), LOSS, "sgd",
        {"learning_rate": 0.1, "lr_scheduler": sched},
        mesh=_mesh(2))
    X, Y = _sharded_data(4)
    _run_sharded(tr, X, Y, 0, 4)
    opt = tr._optimizer
    assert isinstance(opt.num_update, int), type(opt.num_update)
    assert all(isinstance(c, int)
               for c in opt._index_update_count.values())
    assert float(opt.learning_rate) < 0.1, \
        "schedule never advanced under the SPMD step"
