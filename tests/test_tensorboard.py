"""TensorBoard event-file writer (contrib/tensorboard.py — mxboard
analogue; TFRecord framing + masked crc32c + Event protos)."""
import glob
import os
import struct

import numpy as np

from mxnet_tpu.contrib.tensorboard import (SummaryWriter, read_events,
                                           _crc32c, _masked_crc)


def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8A9136AA
    assert _crc32c(b"\x00" * 32) == 0x8A9136AA
    assert _crc32c(b"123456789") == 0xE3069283


def test_writer_roundtrip(tmp_path):
    with SummaryWriter(str(tmp_path)) as w:
        path = w.path
        w.add_scalar("loss", 2.5, global_step=1)
        w.add_scalar("loss", 1.25, global_step=2)
        w.add_histogram("weights", np.random.RandomState(0).randn(100),
                        global_step=2)
    events = read_events(path)
    # first record is the file_version header event
    assert len(events) == 4
    assert events[1]["scalars"] == {"loss": 2.5}
    assert events[2]["step"] == 2
    assert events[3]["scalars"]["weights"] == "<histogram>"


def test_estimator_can_log_through_writer(tmp_path):
    """The writer slots into the estimator's handler protocol."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.estimator import Estimator, EpochEnd

    class TBHandler(EpochEnd):
        def __init__(self, writer, est):
            self.w = writer
            self.est = est
            self.epoch = 0

        def epoch_end(self, estimator, *a, **kw):
            self.w.add_scalar("train_loss",
                              self.est.train_loss_metric.get()[1],
                              global_step=self.epoch)
            self.epoch += 1

    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    est = Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                    trainer=gluon.Trainer(net.collect_params(), "sgd",
                                          {"learning_rate": 0.1}))
    rng = np.random.RandomState(0)
    x = rng.randn(32, 4).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    data = [(nd.array(x), nd.array(y))]
    with SummaryWriter(str(tmp_path)) as w:
        path = w.path
        est.fit(data, epochs=3, event_handlers=[TBHandler(w, est)])
    events = read_events(path)
    losses = [e["scalars"]["train_loss"] for e in events
              if "train_loss" in e["scalars"]]
    assert len(losses) == 3
    assert all(np.isfinite(losses))
