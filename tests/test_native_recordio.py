"""Native (C++) RecordIO reader vs the pure-Python implementation.

Reference analogue: dmlc-core recordio.h + src/io/ prefetching iterator
threads — the C++ half of the reference's data pipeline. Tests pin:
byte-exact agreement between both readers on the same file (including
multipart records containing the magic word), indexed access, and the
threaded prefetch reader's completeness/ordering.
"""
import os

import numpy as np
import pytest

from mxnet_tpu import recordio
from mxnet_tpu.native import (NativePrefetchReader, NativeRecordReader,
                              recordio_lib)

pytestmark = pytest.mark.skipif(
    recordio_lib() is None, reason="no C++ toolchain / native disabled")


def _write_rec(path, records):
    w = recordio.MXRecordIO(str(path), "w")
    for r in records:
        w.write(r)
    w.close()


def _records(rng, n=50):
    recs = []
    for i in range(n):
        if i % 7 == 3:
            # payload containing the magic word at an aligned offset ->
            # multipart framing on disk
            recs.append(b"abcd" + (0xced7230a).to_bytes(4, "little")
                        + bytes(rng.randint(0, 256, rng.randint(0, 64))
                                .astype(np.uint8)))
        else:
            recs.append(bytes(rng.randint(0, 256, rng.randint(1, 200))
                              .astype(np.uint8)))
    return recs


def test_native_reader_matches_python(tmp_path):
    rng = np.random.RandomState(0)
    recs = _records(rng)
    path = tmp_path / "a.rec"
    _write_rec(path, recs)

    native = NativeRecordReader(str(path))
    got = []
    while True:
        r = native.read()
        if r is None:
            break
        got.append(r)
    native.close()
    assert got == recs

    # the MXRecordIO fast path reads through the same native core
    rd = recordio.MXRecordIO(str(path), "r")
    assert rd._native is not None
    got2 = []
    while True:
        r = rd.read()
        if r is None:
            break
        got2.append(r)
    rd.close()
    assert got2 == recs

    # pure-Python fallback agrees byte for byte
    os.environ["MXNET_TPU_NATIVE"] = "0"
    try:
        rd = recordio.MXRecordIO(str(path), "r")
        assert rd._native is None
        got3 = []
        while True:
            r = rd.read()
            if r is None:
                break
            got3.append(r)
        rd.close()
    finally:
        del os.environ["MXNET_TPU_NATIVE"]
    assert got3 == recs


def test_native_indexed_read(tmp_path):
    rng = np.random.RandomState(1)
    recs = _records(rng, 20)
    w = recordio.MXIndexedRecordIO(str(tmp_path / "b.idx"),
                                   str(tmp_path / "b.rec"), "w")
    for i, r in enumerate(recs):
        w.write_idx(i, r)
    w.close()

    rd = recordio.MXIndexedRecordIO(str(tmp_path / "b.idx"),
                                    str(tmp_path / "b.rec"), "r")
    assert rd._native is not None
    order = rng.permutation(20)
    for i in order:
        assert rd.read_idx(int(i)) == recs[i]
    rd.close()


def test_prefetch_reader_complete_and_ordered(tmp_path):
    rng = np.random.RandomState(2)
    recs = _records(rng, 200)
    path = tmp_path / "c.rec"
    _write_rec(path, recs)
    pf = NativePrefetchReader(str(path), queue_size=8)
    got = list(pf)
    pf.close()
    assert got == recs


def test_prefetch_reader_early_close(tmp_path):
    """Closing with records still queued must not deadlock the producer
    thread."""
    rng = np.random.RandomState(3)
    recs = _records(rng, 500)
    path = tmp_path / "d.rec"
    _write_rec(path, recs)
    pf = NativePrefetchReader(str(path), queue_size=4)
    assert pf.read() == recs[0]
    pf.close()       # producer blocked on a full queue; must exit cleanly
