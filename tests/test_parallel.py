"""Sharded-training / collective tests on the virtual 8-device CPU mesh.

The TPU-build analogue of the reference's fake-cluster distributed tests
(tests/nightly/dist_sync_kvstore.py run with --launcher local,
SURVEY.md §4): all collectives execute for real, over 8 virtual devices.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon import nn


def _require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def test_make_mesh_axes():
    _require_devices(8)
    mesh = parallel.make_mesh(tp=2)
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == len(jax.devices()) // 2


def test_shard_batch():
    _require_devices(8)
    mesh = parallel.local_mesh()
    x = mx.nd.array(np.arange(64.0).reshape(8, 8))
    xs = parallel.shard_batch(x, mesh)
    assert len(xs._data.devices()) == 8
    np.testing.assert_array_equal(xs.asnumpy(), x.asnumpy())


def test_functional_call_matches_eager():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.randn(4, 8).astype(np.float32))
    eager = net(x).asnumpy()
    params = parallel.extract_params(net)
    out, aux = parallel.functional_call(net, params, x._data)
    np.testing.assert_allclose(eager, np.asarray(out), rtol=1e-6)
    assert aux == {}


def test_sharded_trainer_dp_convergence():
    _require_devices(8)
    mx.random.seed(1)
    np.random.seed(1)
    mesh = parallel.local_mesh()
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize()
    x = np.random.randn(64, 10).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    tr = parallel.ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.5}, mesh=mesh)
    losses = [float(tr.step(x, y).asscalar()) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.7, losses
    # sync back to the block: eager forward agrees with sharded params
    tr.sync_block()
    out_eager = net(mx.nd.array(x)).asnumpy()
    out_sharded = tr.forward(x).asnumpy()
    np.testing.assert_allclose(out_eager, out_sharded, rtol=1e-4,
                               atol=1e-5)


def test_sharded_trainer_matches_single_device_sgd():
    # dp allreduce-mean must equal single-device full-batch SGD
    _require_devices(8)
    np.random.seed(2)
    x = np.random.randn(16, 6).astype(np.float32)
    y = np.random.randint(0, 3, 16).astype(np.float32)

    def make_net(seed):
        mx.random.seed(seed)
        np.random.seed(seed)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="tanh", in_units=6),
                    nn.Dense(3, in_units=8))
        net.initialize()
        return net

    netA = make_net(5)
    netB = make_net(5)
    pA = {k.split("_", 1)[1]: v.data().asnumpy()
          for k, v in netA.collect_params().items()}
    pB = {k.split("_", 1)[1]: v.data().asnumpy()
          for k, v in netB.collect_params().items()}
    for k in pA:
        np.testing.assert_array_equal(pA[k], pB[k])

    # single device eager
    trainer = gluon.Trainer(netA.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(3):
        with mx.autograd.record():
            loss = L(netA(mx.nd.array(x)), mx.nd.array(y))
        loss.backward()
        trainer.step(x.shape[0])

    # sharded: loss.mean() grad == rescale 1/batch
    mesh = parallel.local_mesh()
    tr = parallel.ShardedTrainer(netB, L, "sgd", {"learning_rate": 0.1},
                                 mesh=mesh)
    for _ in range(3):
        tr.step(x, y)
    tr.sync_block()
    for (ka, va), (kb, vb) in zip(sorted(netA.collect_params().items()),
                                  sorted(netB.collect_params().items())):
        np.testing.assert_allclose(va.data().asnumpy(),
                                   vb.data().asnumpy(), rtol=1e-4,
                                   atol=1e-5)


def test_ring_attention_matches_full():
    _require_devices(8)
    mesh = parallel.make_mesh(dp=1, sp=8)
    B, H, T, D = 2, 2, 64, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)

    def full_attention(q, k, v, causal):
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.tril(np.ones((T, T), bool))
            s = np.where(mask, s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, v)

    for causal in (False, True):
        out = parallel.ring_attention(q, k, v, mesh, causal=causal)
        want = full_attention(np.asarray(q), np.asarray(k), np.asarray(v),
                              causal)
        np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4,
                                   atol=2e-5)


def test_pipeline_stage_loop():
    _require_devices(8)
    mesh = parallel.make_mesh(dp=1, pp=4)
    n_stages, n_micro, mb, dim = 4, 8, 2, 16
    rng = np.random.RandomState(1)
    # each stage: x -> tanh(x @ W_i)
    W = jnp.asarray(rng.randn(n_stages, dim, dim) * 0.3, jnp.float32)
    mbs = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    f = parallel.pipeline_stage_loop(stage_fn, n_micro, mesh)
    out = np.asarray(f(W, mbs))

    want = np.asarray(mbs)
    for i in range(n_stages):
        want = np.tanh(want @ np.asarray(W[i]))
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_kvstore_local_pushpull():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones((2, 3)))
    kv.push(3, mx.nd.ones((2, 3)) * 8)
    out = mx.nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 8.0))
    # multi-value push reduces
    kv.push(3, [mx.nd.ones((2, 3))] * 4)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 4.0))


def test_kvstore_updater():
    kv = mx.kv.create("device")
    kv.init("w", mx.nd.zeros((4,)))

    def upd(key, grad, weight):
        weight -= 0.1 * grad

    kv.set_updater(upd)
    kv.push("w", mx.nd.ones((4,)))
    out = mx.nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(4, -0.1), rtol=1e-6)


def test_sharded_trainer_adam_matches_eager():
    # Adam bias correction must track the true step count under jit
    # (regression: t was baked at 1 into the compiled step)
    _require_devices(8)
    np.random.seed(3)
    x = np.random.randn(16, 5).astype(np.float32)
    y = np.random.randint(0, 2, 16).astype(np.float32)

    def make_net(seed):
        mx.random.seed(seed)
        np.random.seed(seed)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(6, activation="tanh", in_units=5),
                    nn.Dense(2, in_units=6))
        net.initialize()
        return net

    netA, netB = make_net(11), make_net(11)
    L = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(netA.collect_params(), "adam",
                               {"learning_rate": 0.05})
    for _ in range(5):
        with mx.autograd.record():
            loss = L(netA(mx.nd.array(x)), mx.nd.array(y))
        loss.backward()
        trainer.step(x.shape[0])

    tr = parallel.ShardedTrainer(netB, L, "adam",
                                 {"learning_rate": 0.05},
                                 mesh=parallel.local_mesh())
    for _ in range(5):
        tr.step(x, y)
    tr.sync_block()
    for (ka, va), (kb, vb) in zip(sorted(netA.collect_params().items()),
                                  sorted(netB.collect_params().items())):
        np.testing.assert_allclose(va.data().asnumpy(),
                                   vb.data().asnumpy(), rtol=2e-3,
                                   atol=1e-5), ka


def test_pipeline_training_matches_sequential_oracle():
    """jax.grad through the scanned GPipe schedule must equal the grads
    of the equivalent unpipelined stacked model, and a few SGD steps
    through the pipe must reduce the loss."""
    _require_devices(8)
    mesh = parallel.make_mesh(dp=1, pp=4)
    n_stages, n_micro, mb, dim = 4, 8, 2, 12
    rng = np.random.RandomState(2)
    W = jnp.asarray(rng.randn(n_stages, dim, dim) * 0.4, jnp.float32)
    mbs = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)
    ys = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    step = parallel.pipeline_value_and_grad(stage_fn, loss_fn, n_micro,
                                            mesh)
    loss, grads = jax.jit(step)(W, mbs, ys)

    # sequential oracle
    def oracle(Wf):
        h = mbs
        for i in range(n_stages):
            h = jnp.tanh(h @ Wf[i])
        return jax.vmap(loss_fn)(h, ys).mean()

    want_loss, want_grads = jax.value_and_grad(oracle)(W)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(want_grads),
                               rtol=1e-4, atol=1e-5)

    # a few pipeline-parallel SGD steps reduce the loss
    jstep = jax.jit(step)
    Wt = W
    losses = []
    for _ in range(5):
        l, g = jstep(Wt, mbs, ys)
        losses.append(float(l))
        Wt = Wt - 0.5 * g
    assert losses[-1] < losses[0] * 0.8, losses


# ---------------------------------------------------------------------------
# Heterogeneous pipeline (real models: per-stage params + changing shapes)
# ---------------------------------------------------------------------------

def _pp_mesh(n):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices")
    from jax.sharding import Mesh
    return Mesh(np.asarray(devs[:n]).reshape(n), ("pp",))


def test_hetero_pipeline_matches_sequential_oracle():
    """4 stages with different widths AND different pytree structures;
    loss + grads must match the sequential chain exactly (no BN, so fp32
    agreement is tight)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _pp_mesh(4)
    rng = np.random.RandomState(0)
    dims = [8, 16, 12, 6, 3]
    params, fns = [], []
    for i in range(4):
        w = jnp.asarray(rng.randn(dims[i], dims[i + 1]) * 0.3, jnp.float32)
        b = jnp.asarray(rng.randn(dims[i + 1]) * 0.1, jnp.float32)
        if i % 2 == 0:
            params.append({"w": w, "b": b})
            fns.append(lambda p, x: jnp.tanh(x @ p["w"] + p["b"]))
        else:
            params.append((w,))   # different structure on purpose
            fns.append(lambda p, x: jnp.tanh(x @ p[0]))
    mb, n_mb = 4, 8
    pipe = parallel.hetero_pipeline(fns, params, [(d,) for d in dims],
                                    mb, n_mb, mesh)
    packed = jax.device_put(pipe.packed, NamedSharding(mesh, P("pp")))
    xs = jnp.asarray(rng.randn(n_mb, mb, 8), jnp.float32)
    ys = jnp.asarray(rng.randn(n_mb, mb, 3), jnp.float32)
    loss_fn = lambda out, lab: ((out - lab) ** 2).mean()  # noqa: E731
    step = jax.jit(pipe.value_and_grad(loss_fn))
    loss, g = step(packed, xs, ys)

    def seq_loss(plist, xs, ys):
        def apply(x):
            for f, p in zip(fns, plist):
                x = f(p, x)
            return x
        outs = jax.vmap(apply)(xs)
        return jax.vmap(loss_fn)(outs, ys).mean()

    oloss, og = jax.value_and_grad(seq_loss)(pipe.unpack_params(packed),
                                             xs, ys)
    assert abs(float(loss) - float(oloss)) < 1e-5
    for a, b in zip(jax.tree.leaves(pipe.unpack_params(g)),
                    jax.tree.leaves(og)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=1e-5)
    # pack/unpack roundtrip
    rt = pipe.pack_params(pipe.unpack_params(packed))
    np.testing.assert_array_equal(np.asarray(rt), np.asarray(packed))
    # training decreases loss on the packed representation directly
    losses = [float(loss)]
    for _ in range(5):
        packed = packed - 0.2 * g
        loss, g = step(packed, xs, ys)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


@pytest.mark.slow   # ~200s of XLA CPU compile for the staged ResNet-18
def test_hetero_pipeline_resnet18_stages():
    """A REAL model through the pipe: ResNet-18 split into 4 stages via
    gluon_pipeline_stages. Forward loss matches the sequential oracle to
    fp32 exactness; gradients match stage-wise within fp32 amplification
    bounds (BN computes batch stats in fp32 along an 18-layer backward
    chain — in float64 the worst leaf agrees to ~6e-6, the same level as
    a jit-vs-eager control of the oracle itself, so the schedule's math
    is exact and the fp32 spread is precision, not logic; measured
    2026-07 on the 8-device CPU mesh)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.gluon.model_zoo import vision
    import mxnet_tpu.autograd as ag
    mesh = _pp_mesh(4)
    mx.random.seed(0)
    net = vision.resnet18_v1(classes=8, thumbnail=True)
    net.initialize(init=mx.initializer.Xavier())
    with ag.pause():
        net(mx.nd.NDArray(jnp.ones((1, 3, 32, 32), jnp.float32)))
    mb, n_mb = 2, 4
    fns, params, shapes = parallel.gluon_pipeline_stages(
        net, [2, 3, 4], (mb, 3, 32, 32))
    assert shapes[0] == (3, 32, 32) and shapes[-1] == (8,)
    pipe = parallel.hetero_pipeline(fns, params, shapes, mb, n_mb, mesh)
    packed = jax.device_put(pipe.packed, NamedSharding(mesh, P("pp")))
    rng = np.random.RandomState(1)
    xs = jnp.asarray(rng.randn(n_mb, mb, 3, 32, 32), jnp.float32)
    ys = jnp.asarray(rng.randint(0, 8, (n_mb, mb)), jnp.int32)

    def loss_fn(logits, lab):
        lp = jax.nn.log_softmax(logits, -1)
        return -jnp.take_along_axis(lp, lab[:, None], 1).mean()

    step = jax.jit(pipe.value_and_grad(loss_fn))
    loss, g = step(packed, xs, ys)

    def seq_loss(plist, xs, ys):
        def apply_batch(x):  # per-microbatch chain == pipeline BN stats
            for f, p in zip(fns, plist):
                x = f(p, x)
            return x
        outs = jax.vmap(apply_batch)(xs)
        return jax.vmap(loss_fn)(outs, ys).mean()

    oloss, og = jax.value_and_grad(seq_loss)(pipe.unpack_params(packed),
                                             xs, ys)
    assert abs(float(loss) - float(oloss)) < 1e-4
    rels = []
    for sp, so in zip(pipe.unpack_params(g), og):
        for (k, a), (_, b) in zip(sorted(sp.items()), sorted(so.items())):
            a, b = np.asarray(a), np.asarray(b)
            rels.append(np.max(np.abs(a - b)) /
                        (np.max(np.abs(b)) + 1e-12))
    rels = np.asarray(rels)
    assert rels.max() < 5e-2, rels.max()       # fp32 amplification bound
    assert np.median(rels) < 1e-3              # bulk of leaves are tight
    # the pipe trains: 5 SGD steps on the packed params
    losses = [float(loss)]
    for _ in range(5):
        packed = packed - 0.05 * g
        loss, g = step(packed, xs, ys)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses


def test_gluon_pipeline_stages_validation():
    from mxnet_tpu.gluon.model_zoo import vision
    import mxnet_tpu.autograd as ag
    net = vision.resnet18_v1(classes=4, thumbnail=True)
    net.initialize()
    with ag.pause():
        net(mx.nd.NDArray(jnp.ones((1, 3, 32, 32), jnp.float32)))
    with pytest.raises(ValueError):
        parallel.gluon_pipeline_stages(net, [3, 3], (2, 3, 32, 32))
    fns, params, shapes = parallel.gluon_pipeline_stages(
        net, [2, 4], (2, 3, 32, 32))
    assert len(fns) == len(params) == 3 and len(shapes) == 4
    keys = [set(p) for p in params]
    assert not (keys[0] & keys[1]) and not (keys[1] & keys[2])


def test_auto_spec_derives_megatron_layout():
    """auto_spec must derive column-parallel q/k/v + ffn1 and
    row-parallel out + ffn2 from the block STRUCTURE (no name matching
    by the caller), skip non-divisible dims, and leave the rest
    replicated."""
    from jax.sharding import Mesh, PartitionSpec as P
    import mxnet_tpu.autograd as ag
    from mxnet_tpu.gluon.model_zoo.bert import BERTEncoderLayer
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs 2 devices")
    mesh = Mesh(np.asarray(devs[:2]).reshape(1, 2), ("dp", "tp"))
    layer = BERTEncoderLayer(units=32, hidden_size=64, num_heads=4,
                             dropout=0.0)
    layer.initialize()
    with ag.pause():
        layer(mx.nd.NDArray(jnp.ones((1, 4, 32), jnp.float32)))
    fn = parallel.auto_spec(layer, mesh)
    s = fn.specs
    col, row = P("tp", None), P(None, "tp")
    by_suffix = {}
    for name, spec in s.items():
        for suf in ("query_weight", "key_weight", "value_weight",
                    "out_weight", "ffn1_weight", "ffn2_weight",
                    "query_bias", "ffn1_bias", "out_bias", "ffn2_bias"):
            if name.endswith(suf):
                by_suffix[suf] = spec
    assert by_suffix["query_weight"] == col
    assert by_suffix["key_weight"] == col
    assert by_suffix["value_weight"] == col
    assert by_suffix["ffn1_weight"] == col
    assert by_suffix["out_weight"] == row
    assert by_suffix["ffn2_weight"] == row
    assert by_suffix["query_bias"] == P("tp")
    assert by_suffix["ffn1_bias"] == P("tp")
    # row-parallel biases are post-reduce terms: replicated (absent)
    assert "out_bias" not in by_suffix and "ffn2_bias" not in by_suffix
    # LayerNorm params replicated
    assert fn("whatever_ln_gamma", (32,)) == P()
    # a 30-unit dense on a size-4 tp axis is not divisible -> replicated
    from jax.sharding import Mesh as M2
    if len(devs) >= 4:
        mesh4 = M2(np.asarray(devs[:4]).reshape(1, 4), ("dp", "tp"))
        layer2 = BERTEncoderLayer(units=30, hidden_size=60, num_heads=2,
                                  dropout=0.0)
        layer2.initialize()
        with ag.pause():
            layer2(mx.nd.NDArray(jnp.ones((1, 4, 30), jnp.float32)))
        fn4 = parallel.auto_spec(layer2, mesh4)
        assert all(not any(ax == "tp" for ax in (sp or ()))
                   for name, sp in fn4.specs.items()
                   if name.endswith("query_weight"))
