"""Sharded-training / collective tests on the virtual 8-device CPU mesh.

The TPU-build analogue of the reference's fake-cluster distributed tests
(tests/nightly/dist_sync_kvstore.py run with --launcher local,
SURVEY.md §4): all collectives execute for real, over 8 virtual devices.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon import nn


def _require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def test_make_mesh_axes():
    _require_devices(8)
    mesh = parallel.make_mesh(tp=2)
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == len(jax.devices()) // 2


def test_shard_batch():
    _require_devices(8)
    mesh = parallel.local_mesh()
    x = mx.nd.array(np.arange(64.0).reshape(8, 8))
    xs = parallel.shard_batch(x, mesh)
    assert len(xs._data.devices()) == 8
    np.testing.assert_array_equal(xs.asnumpy(), x.asnumpy())


def test_functional_call_matches_eager():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.randn(4, 8).astype(np.float32))
    eager = net(x).asnumpy()
    params = parallel.extract_params(net)
    out, aux = parallel.functional_call(net, params, x._data)
    np.testing.assert_allclose(eager, np.asarray(out), rtol=1e-6)
    assert aux == {}


def test_sharded_trainer_dp_convergence():
    _require_devices(8)
    mx.random.seed(1)
    np.random.seed(1)
    mesh = parallel.local_mesh()
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize()
    x = np.random.randn(64, 10).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    tr = parallel.ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                 "sgd", {"learning_rate": 0.5}, mesh=mesh)
    losses = [float(tr.step(x, y).asscalar()) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.7, losses
    # sync back to the block: eager forward agrees with sharded params
    tr.sync_block()
    out_eager = net(mx.nd.array(x)).asnumpy()
    out_sharded = tr.forward(x).asnumpy()
    np.testing.assert_allclose(out_eager, out_sharded, rtol=1e-4,
                               atol=1e-5)


def test_sharded_trainer_matches_single_device_sgd():
    # dp allreduce-mean must equal single-device full-batch SGD
    _require_devices(8)
    np.random.seed(2)
    x = np.random.randn(16, 6).astype(np.float32)
    y = np.random.randint(0, 3, 16).astype(np.float32)

    def make_net(seed):
        mx.random.seed(seed)
        np.random.seed(seed)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(8, activation="tanh", in_units=6),
                    nn.Dense(3, in_units=8))
        net.initialize()
        return net

    netA = make_net(5)
    netB = make_net(5)
    pA = {k.split("_", 1)[1]: v.data().asnumpy()
          for k, v in netA.collect_params().items()}
    pB = {k.split("_", 1)[1]: v.data().asnumpy()
          for k, v in netB.collect_params().items()}
    for k in pA:
        np.testing.assert_array_equal(pA[k], pB[k])

    # single device eager
    trainer = gluon.Trainer(netA.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(3):
        with mx.autograd.record():
            loss = L(netA(mx.nd.array(x)), mx.nd.array(y))
        loss.backward()
        trainer.step(x.shape[0])

    # sharded: loss.mean() grad == rescale 1/batch
    mesh = parallel.local_mesh()
    tr = parallel.ShardedTrainer(netB, L, "sgd", {"learning_rate": 0.1},
                                 mesh=mesh)
    for _ in range(3):
        tr.step(x, y)
    tr.sync_block()
    for (ka, va), (kb, vb) in zip(sorted(netA.collect_params().items()),
                                  sorted(netB.collect_params().items())):
        np.testing.assert_allclose(va.data().asnumpy(),
                                   vb.data().asnumpy(), rtol=1e-4,
                                   atol=1e-5)


def test_ring_attention_matches_full():
    _require_devices(8)
    mesh = parallel.make_mesh(dp=1, sp=8)
    B, H, T, D = 2, 2, 64, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, T, D), jnp.float32)

    def full_attention(q, k, v, causal):
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.tril(np.ones((T, T), bool))
            s = np.where(mask, s, -np.inf)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, v)

    for causal in (False, True):
        out = parallel.ring_attention(q, k, v, mesh, causal=causal)
        want = full_attention(np.asarray(q), np.asarray(k), np.asarray(v),
                              causal)
        np.testing.assert_allclose(np.asarray(out), want, rtol=2e-4,
                                   atol=2e-5)


def test_pipeline_stage_loop():
    _require_devices(8)
    mesh = parallel.make_mesh(dp=1, pp=4)
    n_stages, n_micro, mb, dim = 4, 8, 2, 16
    rng = np.random.RandomState(1)
    # each stage: x -> tanh(x @ W_i)
    W = jnp.asarray(rng.randn(n_stages, dim, dim) * 0.3, jnp.float32)
    mbs = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    f = parallel.pipeline_stage_loop(stage_fn, n_micro, mesh)
    out = np.asarray(f(W, mbs))

    want = np.asarray(mbs)
    for i in range(n_stages):
        want = np.tanh(want @ np.asarray(W[i]))
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_kvstore_local_pushpull():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones((2, 3)))
    kv.push(3, mx.nd.ones((2, 3)) * 8)
    out = mx.nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 8.0))
    # multi-value push reduces
    kv.push(3, [mx.nd.ones((2, 3))] * 4)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 4.0))


def test_kvstore_updater():
    kv = mx.kv.create("device")
    kv.init("w", mx.nd.zeros((4,)))

    def upd(key, grad, weight):
        weight -= 0.1 * grad

    kv.set_updater(upd)
    kv.push("w", mx.nd.ones((4,)))
    out = mx.nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(4, -0.1), rtol=1e-6)


def test_sharded_trainer_adam_matches_eager():
    # Adam bias correction must track the true step count under jit
    # (regression: t was baked at 1 into the compiled step)
    _require_devices(8)
    np.random.seed(3)
    x = np.random.randn(16, 5).astype(np.float32)
    y = np.random.randint(0, 2, 16).astype(np.float32)

    def make_net(seed):
        mx.random.seed(seed)
        np.random.seed(seed)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(6, activation="tanh", in_units=5),
                    nn.Dense(2, in_units=6))
        net.initialize()
        return net

    netA, netB = make_net(11), make_net(11)
    L = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(netA.collect_params(), "adam",
                               {"learning_rate": 0.05})
    for _ in range(5):
        with mx.autograd.record():
            loss = L(netA(mx.nd.array(x)), mx.nd.array(y))
        loss.backward()
        trainer.step(x.shape[0])

    tr = parallel.ShardedTrainer(netB, L, "adam",
                                 {"learning_rate": 0.05},
                                 mesh=parallel.local_mesh())
    for _ in range(5):
        tr.step(x, y)
    tr.sync_block()
    for (ka, va), (kb, vb) in zip(sorted(netA.collect_params().items()),
                                  sorted(netB.collect_params().items())):
        np.testing.assert_allclose(va.data().asnumpy(),
                                   vb.data().asnumpy(), rtol=2e-3,
                                   atol=1e-5), ka


def test_pipeline_training_matches_sequential_oracle():
    """jax.grad through the scanned GPipe schedule must equal the grads
    of the equivalent unpipelined stacked model, and a few SGD steps
    through the pipe must reduce the loss."""
    _require_devices(8)
    mesh = parallel.make_mesh(dp=1, pp=4)
    n_stages, n_micro, mb, dim = 4, 8, 2, 12
    rng = np.random.RandomState(2)
    W = jnp.asarray(rng.randn(n_stages, dim, dim) * 0.4, jnp.float32)
    mbs = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)
    ys = jnp.asarray(rng.randn(n_micro, mb, dim), jnp.float32)

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    step = parallel.pipeline_value_and_grad(stage_fn, loss_fn, n_micro,
                                            mesh)
    loss, grads = jax.jit(step)(W, mbs, ys)

    # sequential oracle
    def oracle(Wf):
        h = mbs
        for i in range(n_stages):
            h = jnp.tanh(h @ Wf[i])
        return jax.vmap(loss_fn)(h, ys).mean()

    want_loss, want_grads = jax.value_and_grad(oracle)(W)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(want_grads),
                               rtol=1e-4, atol=1e-5)

    # a few pipeline-parallel SGD steps reduce the loss
    jstep = jax.jit(step)
    Wt = W
    losses = []
    for _ in range(5):
        l, g = jstep(Wt, mbs, ys)
        losses.append(float(l))
        Wt = Wt - 0.5 * g
    assert losses[-1] < losses[0] * 0.8, losses
