"""DataLoader worker-pool behavior (reference: gluon/data/dataloader.py
_MultiWorkerIter; the round-3 review flagged the spawn main-guard
footgun, silent thread fallback, and __del__ shutdown noise)."""
import warnings

import numpy as np
import pytest

from mxnet_tpu import gluon


class _SquareDataset(gluon.data.Dataset):
    def __init__(self, n=32):
        self._n = n

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        return np.full((3,), float(i * i), np.float32)


def test_worker_pool_matches_serial():
    ds = _SquareDataset()
    serial = [b.asnumpy() for b in gluon.data.DataLoader(
        ds, batch_size=8, shuffle=False)]
    workers = [b.asnumpy() for b in gluon.data.DataLoader(
        ds, batch_size=8, shuffle=False, num_workers=2)]
    assert len(serial) == len(workers) == 4
    for a, b in zip(serial, workers):
        np.testing.assert_array_equal(a, b)


def test_unpicklable_dataset_warns_and_falls_back():
    class Unpicklable(gluon.data.Dataset):
        def __init__(self):
            self._fn = lambda i: np.float32(i)  # lambdas don't pickle

        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.full((2,), self._fn(i), np.float32)

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        dl = gluon.data.DataLoader(Unpicklable(), batch_size=4,
                                   num_workers=2)
        batches = [b.asnumpy() for b in dl]
    assert len(batches) == 2
    assert any("thread pool" in str(x.message) for x in w)


def test_del_on_partial_instance_is_silent():
    dl = gluon.data.DataLoader.__new__(gluon.data.DataLoader)
    dl.__del__()  # must not raise (no _pool attribute yet)
    with pytest.raises(ValueError):
        gluon.data.DataLoader(_SquareDataset(), batch_size=4,
                              shuffle=True, batch_sampler=object())
