#!/usr/bin/env python
"""Generate an externally-authored ONNX fixture for the import tests.

This deliberately does NOT use mxnet_tpu.onnx (or any of its proto
helpers): the bytes are hand-encoded straight from the ONNX protobuf
spec (onnx/onnx.proto field numbers), the way a third-party exporter
would produce them — so importer bugs cannot cancel against exporter
bugs (VERDICT r4 weak #5). Node/value names follow torch.onnx's
"/layer/Op_output_0" idiom; one initializer uses raw_data and another
float_data to cover both tensor encodings.

Model: data(2,4) -> Gemm(transB=1, alpha=1, beta=1) -> Relu ->
Gemm(transB=1) -> out(2,3). Weights are a fixed-seed draw; expected
outputs are computed here with numpy and stored alongside.

Run from the repo root to (re)generate:
    python tests/assets/gen_external_onnx.py
"""
import os
import struct

import numpy as np


def vint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def key(field, wire):
    return vint((field << 3) | wire)


def ld(field, payload):
    if isinstance(payload, str):
        payload = payload.encode()
    return key(field, 2) + vint(len(payload)) + payload


def iv(field, n):
    return key(field, 0) + vint(n)


def f32(field, x):
    return key(field, 5) + struct.pack("<f", x)


def tensor_raw(name, arr):
    """TensorProto with raw_data (field 9)."""
    msg = b"".join(iv(1, d) for d in arr.shape)       # dims
    msg += iv(2, 1)                                    # data_type FLOAT
    msg += ld(8, name)                                 # name
    msg += ld(9, arr.astype("<f4").tobytes())          # raw_data
    return msg


def tensor_floats(name, arr):
    """TensorProto with packed float_data (field 4)."""
    msg = b"".join(iv(1, d) for d in arr.shape)
    msg += iv(2, 1)
    packed = struct.pack(f"<{arr.size}f", *arr.reshape(-1).tolist())
    msg += ld(4, packed)                               # float_data packed
    msg += ld(8, name)
    return msg


def value_info(name, shape):
    dims = b"".join(ld(1, iv(1, d)) for d in shape)    # Dimension.dim_value
    tshape = ld(2, dims)                               # TensorShapeProto
    ttype = iv(1, 1) + tshape                          # elem_type + shape
    return ld(1, name) + ld(2, ld(1, ttype))           # name + tensor_type


def attr_int(name, v):
    return ld(1, name) + iv(3, v) + iv(20, 2)          # i + type=INT


def attr_float(name, v):
    return ld(1, name) + f32(2, v) + iv(20, 1)         # f + type=FLOAT


def node(op, ins, outs, name, attrs=()):
    msg = b"".join(ld(1, i) for i in ins)
    msg += b"".join(ld(2, o) for o in outs)
    msg += ld(3, name) + ld(4, op)
    msg += b"".join(ld(5, a) for a in attrs)
    return msg


def main():
    rng = np.random.RandomState(42)
    w1 = rng.randn(8, 4).astype(np.float32)
    b1 = rng.randn(8).astype(np.float32)
    w2 = rng.randn(3, 8).astype(np.float32)
    b2 = rng.randn(3).astype(np.float32)
    x = rng.randn(2, 4).astype(np.float32)
    hidden = np.maximum(x @ w1.T + b1, 0.0)
    expected = hidden @ w2.T + b2

    g = b""
    g += ld(1, node("Gemm", ["data", "fc1.weight", "fc1.bias"],
                    ["/fc1/Gemm_output_0"], "/fc1/Gemm",
                    [attr_float("alpha", 1.0), attr_float("beta", 1.0),
                     attr_int("transB", 1)]))
    g += ld(1, node("Relu", ["/fc1/Gemm_output_0"],
                    ["/act/Relu_output_0"], "/act/Relu"))
    g += ld(1, node("Gemm", ["/act/Relu_output_0", "fc2.weight",
                             "fc2.bias"], ["out"], "/fc2/Gemm",
                    [attr_int("transB", 1)]))
    g += ld(2, "torch_style_mlp")                      # graph name
    g += ld(5, tensor_raw("fc1.weight", w1))           # initializers
    g += ld(5, tensor_floats("fc1.bias", b1))
    g += ld(5, tensor_raw("fc2.weight", w2))
    g += ld(5, tensor_floats("fc2.bias", b2))
    g += ld(11, value_info("data", (2, 4)))            # graph input
    g += ld(12, value_info("out", (2, 3)))             # graph output

    m = iv(1, 8)                                       # ir_version
    m += ld(2, "external-handwritten")                 # producer_name
    m += ld(7, g)                                      # graph
    m += ld(8, iv(2, 13))                              # opset_import v13

    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "external_mlp.onnx"), "wb") as f:
        f.write(m)
    np.savez(os.path.join(here, "external_mlp_io.npz"),
             x=x, expected=expected)
    print(f"wrote external_mlp.onnx ({len(m)} bytes) + external_mlp_io.npz")


if __name__ == "__main__":
    main()
