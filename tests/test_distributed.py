"""Real multi-process distributed training.

The reference exercises its wire protocol with local multi-process
launches (tests/nightly/dist_sync_kvstore.py via tools/launch.py
--launcher local); this is the TPU-build analogue, and it goes through
the SAME user-facing door: ``mxnet_tpu.launch`` spawns N OS processes
(each a jax process with one virtual CPU device) that join via the
MXNET_TPU_* env plumbing + jax.distributed.initialize. Covers the
KVStoreTPU('dist_sync') compiled psum reduce and a ShardedTrainer dp
step over the process-spanning mesh, asserting byte-identical results
on every rank.
"""
import os
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os, sys, hashlib
    import numpy as np

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, "__REPO__")
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    # rank/world/coordinator arrive via the launcher's env plumbing
    nproc = int(os.environ["MXNET_TPU_NUM_WORKERS"])
    rank = int(os.environ["MXNET_TPU_RANK"])
    from mxnet_tpu.kvstore.tpu import init_process_group
    init_process_group()
    assert jax.process_count() == nproc, jax.process_count()

    # ---- kvstore dist_sync: compiled psum reduce --------------------
    kv = mx.kv.create("dist_sync")
    assert kv.type == "dist_sync"
    assert kv.rank == rank and kv.num_workers == nproc
    base = np.arange(12, dtype=np.float32).reshape(3, 4)
    kv.init("w", nd.array(np.zeros((3, 4), np.float32)))
    # each rank pushes a rank-dependent gradient
    kv.push("w", nd.array(base * (rank + 1)))
    out = nd.array(np.zeros((3, 4), np.float32))
    kv.pull("w", out=out)
    expect = base * sum(r + 1 for r in range(nproc))
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)
    kv.barrier()

    # ---- batched multi-key push: one flattened cross-process launch --
    kv.init("m1", nd.array(np.zeros((2, 2), np.float32)))
    kv.init("m2", nd.array(np.zeros(3, np.float32)))
    kv.push(["m1", "m2"],
            [nd.array(np.full((2, 2), float(rank + 1), np.float32)),
             nd.array(np.arange(3, dtype=np.float32) * (rank + 1))])
    o1 = nd.array(np.zeros((2, 2), np.float32))
    o2 = nd.array(np.zeros(3, np.float32))
    kv.pull("m1", out=o1)
    kv.pull("m2", out=o2)
    tot = sum(r + 1 for r in range(nproc))
    np.testing.assert_allclose(o1.asnumpy(), np.full((2, 2), float(tot)))
    np.testing.assert_allclose(o2.asnumpy(),
                               np.arange(3, dtype=np.float32) * tot)
    kv.barrier()

    # ---- compressed push: packed int32 payload over the process mesh --
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    kvc.init("g", nd.array(np.zeros(5, np.float32)))
    v = np.array([2.0, -0.5, 1.0, -3.0, 0.0], np.float32) * (rank + 1)
    kvc.push("g", nd.array(v))
    outc = nd.array(np.zeros(5, np.float32))
    kvc.pull("g", out=outc)
    # oracle: each rank quantizes its own v to {-1,0,1}, then sum
    q = lambda a: np.clip(np.where(a >= 1, 1, np.where(a <= -1, -1, 0)),
                          -1, 1).astype(np.float32)
    expect_c = sum(q(np.array([2.0, -0.5, 1.0, -3.0, 0.0]) * (r + 1))
                   for r in range(nproc))
    np.testing.assert_allclose(outc.asnumpy(), expect_c)
    kvc.barrier()

    # ---- ShardedTrainer dp step over the process-spanning mesh ------
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    mesh = parallel.make_mesh(dp=nproc)
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh)
    rng = np.random.RandomState(0)      # same data on every rank
    x = rng.randn(8, 6).astype(np.float32)
    y = (np.arange(8) % 4).astype(np.float32)
    losses = [float(tr.step(x, y).asscalar()) for _ in range(3)]
    assert losses[-1] < losses[0], losses

    # byte-identical trained params on every rank (params are replicated:
    # read this process's shard)
    h = hashlib.sha256()
    for n in sorted(tr.params):
        local = np.asarray(tr.params[n].addressable_data(0))
        h.update(np.ascontiguousarray(local).tobytes())
    with open(os.path.join("__OUT__", f"result_{rank}.txt"), "w") as f:
        f.write(f"RESULT rank={rank} losses={losses[-1]:.6f} "
                f"hash={h.hexdigest()}\\n")
""")


@pytest.mark.parametrize(
    "nproc", [pytest.param(2, marks=pytest.mark.slow)])
# ~9s on 1 CPU (tier-1 budget): two fresh jax processes; launcher
# lifecycle stays fast via the teardown + multihost-emulation tests
def test_multiprocess_dist_sync(tmp_path, nproc, monkeypatch):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.replace("__REPO__", repo)
                      .replace("__OUT__", str(tmp_path)))
    # launch(cpu=True) overrides the runner's device-count/platform env
    # per worker; monkeypatch keeps this module's own env untouched for
    # later tests
    monkeypatch.syspath_prepend(repo)
    from mxnet_tpu.launch import launch
    rc = launch(nproc, [sys.executable, str(script)], cpu=True,
                timeout=420)
    assert rc == 0, f"launcher reported failure rc={rc}"
    results = []
    for r in range(nproc):
        f = tmp_path / f"result_{r}.txt"
        assert f.exists(), f"rank {r} wrote no result"
        results.append(f.read_text().strip())
    hashes = {line.split("hash=")[1] for line in results}
    assert len(hashes) == 1, f"ranks diverged: {results}"


def test_launcher_tears_down_group_on_rank_failure(tmp_path):
    """Failure detection (§5.3): one rank dies before the distributed
    join; the launcher must detect it, kill the surviving rank (which
    would otherwise block in the join forever), and report nonzero —
    within the timeout, not at it."""
    import time as _time
    from mxnet_tpu.launch import launch
    script = tmp_path / "dying_worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        rank = int(os.environ["MXNET_TPU_RANK"])
        if rank == 1:
            sys.exit(3)          # dies before joining the group
        # rank 0 would block in jax.distributed.initialize forever;
        # simulate the blocking join without paying jax import time
        time.sleep(600)
    """))
    t0 = _time.monotonic()
    rc = launch(2, [sys.executable, str(script)], cpu=True, timeout=120,
                quiet=True)
    elapsed = _time.monotonic() - t0
    assert rc == 3, f"expected the dead rank's code, got {rc}"
    assert elapsed < 60, f"teardown took {elapsed:.0f}s (no fail-fast)"


_MH_WORKER = textwrap.dedent("""
    import os, sys, hashlib
    import numpy as np

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, "__REPO__")
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    world = int(os.environ["MXNET_TPU_NUM_WORKERS"])
    rank = int(os.environ["MXNET_TPU_RANK"])
    host_rank = int(os.environ["TEST_HOST_RANK"])
    per_host = int(os.environ["TEST_PER_HOST"])
    # host-major rank assignment (reference: tools/launch.py:29 dmlc
    # tracker hands worker ids out per host)
    assert rank // per_host == host_rank, (rank, host_rank)
    assert world == 2 * per_host, world

    from mxnet_tpu.kvstore.tpu import init_process_group
    init_process_group()
    assert jax.process_count() == world, jax.process_count()

    kv = mx.kv.create("dist_sync")
    base = np.arange(8, dtype=np.float32)
    kv.init("w", nd.array(np.zeros(8, np.float32)))
    kv.push("w", nd.array(base * (rank + 1)))
    out = nd.array(np.zeros(8, np.float32))
    kv.pull("w", out=out)
    got = out.asnumpy()
    expect = base * sum(r + 1 for r in range(world))
    np.testing.assert_array_equal(got, expect)
    kv.barrier()
    h = hashlib.sha256(np.ascontiguousarray(got).tobytes()).hexdigest()
    with open(os.path.join("__OUT__", f"mh_result_{rank}.txt"), "w") as f:
        f.write(f"rank={rank} hash={h}\\n")
""")


def test_multihost_launcher_emulation(tmp_path, monkeypatch):
    """Two launcher invocations on one box emulate a 2-host x 2-proc
    cluster sharing a coordinator (reference: tools/launch.py:29 ssh
    bring-up, one `launch.py -n 2` per host): host-major rank
    assignment and a byte-exact 4-way reduce across both "hosts"."""
    import threading as _threading

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "mh_worker.py"
    script.write_text(_MH_WORKER.replace("__REPO__", repo)
                      .replace("__OUT__", str(tmp_path)))
    monkeypatch.syspath_prepend(repo)
    from mxnet_tpu.launch import launch, _free_port

    per_host = 2
    coordinator = f"127.0.0.1:{_free_port()}"
    rcs = {}

    def one_host(host_rank):
        rcs[host_rank] = launch(
            per_host, [sys.executable, str(script)],
            coordinator=coordinator, num_hosts=2, host_rank=host_rank,
            cpu=True, timeout=420,
            env_extra={"TEST_HOST_RANK": str(host_rank),
                       "TEST_PER_HOST": str(per_host)})

    threads = [_threading.Thread(target=one_host, args=(k,))
               for k in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert rcs == {0: 0, 1: 0}, f"launcher rcs: {rcs}"

    hashes = set()
    for r in range(2 * per_host):
        f = tmp_path / f"mh_result_{r}.txt"
        assert f.exists(), f"rank {r} wrote no result"
        hashes.add(f.read_text().split("hash=")[1].strip())
    assert len(hashes) == 1, "ranks diverged across hosts"
