"""Real multi-process distributed training.

The reference exercises its wire protocol with local multi-process
launches (tests/nightly/dist_sync_kvstore.py via tools/launch.py
--launcher local); this is the TPU-build analogue: N OS processes, each
a jax process with one virtual CPU device, joined by
jax.distributed.initialize. Covers the KVStoreTPU('dist_sync') compiled
psum reduce and a ShardedTrainer dp step over the process-spanning mesh,
asserting byte-identical results on every rank.
"""
import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os, sys, hashlib
    import numpy as np

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    coord, nproc, rank = (sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
    sys.path.insert(0, "__REPO__")
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.kvstore.tpu import init_process_group
    init_process_group(coord, nproc, rank)
    assert jax.process_count() == nproc, jax.process_count()

    # ---- kvstore dist_sync: compiled psum reduce --------------------
    kv = mx.kv.create("dist_sync")
    assert kv.type == "dist_sync"
    assert kv.rank == rank and kv.num_workers == nproc
    base = np.arange(12, dtype=np.float32).reshape(3, 4)
    kv.init("w", nd.array(np.zeros((3, 4), np.float32)))
    # each rank pushes a rank-dependent gradient
    kv.push("w", nd.array(base * (rank + 1)))
    out = nd.array(np.zeros((3, 4), np.float32))
    kv.pull("w", out=out)
    expect = base * sum(r + 1 for r in range(nproc))
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)
    kv.barrier()

    # ---- compressed push: packed int32 payload over the process mesh --
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    kvc.init("g", nd.array(np.zeros(5, np.float32)))
    v = np.array([2.0, -0.5, 1.0, -3.0, 0.0], np.float32) * (rank + 1)
    kvc.push("g", nd.array(v))
    outc = nd.array(np.zeros(5, np.float32))
    kvc.pull("g", out=outc)
    # oracle: each rank quantizes its own v to {-1,0,1}, then sum
    q = lambda a: np.clip(np.where(a >= 1, 1, np.where(a <= -1, -1, 0)),
                          -1, 1).astype(np.float32)
    expect_c = sum(q(np.array([2.0, -0.5, 1.0, -3.0, 0.0]) * (r + 1))
                   for r in range(nproc))
    np.testing.assert_allclose(outc.asnumpy(), expect_c)
    kvc.barrier()

    # ---- ShardedTrainer dp step over the process-spanning mesh ------
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon import nn
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    mesh = parallel.make_mesh(dp=nproc)
    tr = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9}, mesh=mesh)
    rng = np.random.RandomState(0)      # same data on every rank
    x = rng.randn(8, 6).astype(np.float32)
    y = (np.arange(8) % 4).astype(np.float32)
    losses = [float(tr.step(x, y).asscalar()) for _ in range(3)]
    assert losses[-1] < losses[0], losses

    # byte-identical trained params on every rank (params are replicated:
    # read this process's shard)
    h = hashlib.sha256()
    for n in sorted(tr.params):
        local = np.asarray(tr.params[n].addressable_data(0))
        h.update(np.ascontiguousarray(local).tobytes())
    print(f"RESULT rank={rank} losses={losses[-1]:.6f} "
          f"hash={h.hexdigest()}", flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("nproc", [2])
def test_multiprocess_dist_sync(tmp_path, nproc):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.replace("__REPO__", repo))
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coord, str(nproc), str(r)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for r in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed worker timed out")
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-4000:]}"
    results = [line for out in outs for line in out.splitlines()
               if line.startswith("RESULT")]
    assert len(results) == nproc, outs
    hashes = {line.split("hash=")[1] for line in results}
    assert len(hashes) == 1, f"ranks diverged: {results}"
