"""Cross-request prefix caching (ISSUE 13): the acceptance contract.

- BIT-EXACT parity: greedy decode with the prefix cache ON (fp32 KV)
  is identical to cache OFF across mixed shared/unshared batches —
  including under KV-pressure preemption and speculative decoding —
  because a cached block holds exactly the bytes the sequence would
  have computed itself (per-token K/V is a deterministic function of
  the shared prefix);
- refcounted sharing rides the STRICT BlockAllocator accounting:
  ``check()`` stays clean through hit/ref/free/COW/LRU churn, cached
  blocks are reclaimable capacity (never leaks), and copy-on-write
  gives a sequence a private block before its first divergent write
  into a shared one;
- zero steady-state recompiles under mixed hit/miss + sampled +
  speculative traffic (cache hit vs miss never changes a program
  shape; the COW copy is a warmed fixed-shape program).
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.serving.llm import (  # noqa: E402
    TinyDecoder, DecoderConfig, LLMEngine, LLMServer, Sequence,
    greedy_decode_reference)
from mxnet_tpu.serving.llm.kv_cache import (  # noqa: E402
    prefix_block_hashes)
from mxnet_tpu.serving.llm.sampling import SamplingParams  # noqa: E402

VOCAB = 17
BS = 8
# CTX deliberately small: every engine in this module shares the
# same page/program shapes (max_seqs=4, 8-token blocks, 32 context or
# the one small pressure pool), so XLA compiles each program ONCE for
# the whole module
CTX = 32


@pytest.fixture(scope="module")
def model():
    return TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=16, num_layers=2, num_heads=2,
        d_ff=32, max_context=CTX))


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(seed=0)


@pytest.fixture(scope="module")
def draft(model):
    """One layer-truncated draft shared by every speculative test in
    this module (a fresh draft model per test would recompile its
    programs)."""
    return TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=16, num_layers=1, num_heads=2,
        d_ff=32, max_context=CTX))


@pytest.fixture(scope="module")
def draft_params(params):
    return {k: (v if k != "layers" else list(v[:1]))
            for k, v in params.items()}


def _run_all(eng, seqs):
    for s in seqs:
        eng.add(s)
    steps = 0
    while eng.has_work():
        eng.step()
        steps += 1
        assert steps < 2000
    return steps


def _shared_mix(rng, shared_prefix, n_shared=4, n_unshared=3):
    """Mixed batch: n_unshared fully distinct prompts FIRST (so the
    initial admission wave holds at most one copy of the shared
    prefix — later shared admissions find it registered), then
    n_shared prompts extending one shared prefix with distinct
    tails."""
    cases = []
    for i in range(n_unshared):
        cases.append((rng.randint(0, VOCAB,
                                  size=int(rng.randint(2, 20))).tolist(),
                      3 + i))
    for i in range(n_shared):
        tail = rng.randint(0, VOCAB, size=1 + i).tolist()
        cases.append((shared_prefix + tail, 4 + (i % 3)))
    return cases


def test_chained_block_hashes_bind_whole_prefix():
    a = prefix_block_hashes(list(range(16)), 8)
    b = prefix_block_hashes(list(range(16)), 8)
    assert a == b and len(a) == 2
    # same second block content, different FIRST block -> different
    # chained hash (equal hash k must imply equal whole prefix)
    c = prefix_block_hashes([9] * 8 + list(range(8, 16)), 8)
    assert c[1] != b[1]
    # partial tail block never hashes
    assert len(prefix_block_hashes(list(range(15)), 8)) == 1


@pytest.mark.slow   # ~24s on 1 CPU (tier-1 budget): a second
# cache-OFF engine warmup; hit-path bit-exactness stays fast via
# test_block_aligned_full_hit_cows_on_first_divergence below and
# test_llm_spmd's prefix/COW pins
def test_cache_on_equals_cache_off_mixed_shared_batches(model, params):
    """The headline parity pin: same mixed shared/unshared batch, same
    admission order, cache ON vs OFF — every token stream identical,
    and both equal the per-sequence eager oracle. The ON run must
    actually hit (saved tokens > 0) for the comparison to mean
    anything."""
    rng = np.random.RandomState(7)
    prefix = rng.randint(0, VOCAB, size=2 * BS).tolist()
    cases = _shared_mix(rng, prefix)
    outs = {}
    for on in (False, True):
        eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                        max_context=CTX, prefill_chunk=8,
                        prefix_cache=on)
        eng.warmup()
        seqs = [Sequence(p, n) for p, n in cases]
        _run_all(eng, seqs)
        outs[on] = [s.output_tokens() for s in seqs]
        assert eng.cache.allocator.num_used == 0
        eng.cache.check(live_block_ids=[])
        if on:
            assert eng.prefix_lookups == len(cases)
            assert eng.prefix_hits >= 3          # the shared tails hit
            assert eng.prefill_tokens_saved >= 3 * 2 * BS - 1
        else:
            assert eng.prefix_lookups == 0
    assert outs[True] == outs[False]
    for (p, n), toks in zip(cases, outs[True]):
        assert toks == greedy_decode_reference(model, params, p, n)


def test_block_aligned_full_hit_cows_on_first_divergence(model, params):
    """A prompt that is EXACTLY its cached blocks: the hit serves all
    but the last token, whose recompute-chunk writes into the final
    SHARED block — copy-on-write must give the new sequence a private
    copy first (the original owner is still alive and attending over
    that block). Streams stay bit-exact for both."""
    rng = np.random.RandomState(11)
    prompt = rng.randint(0, VOCAB, size=2 * BS).tolist()   # aligned
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefill_chunk=8)
    eng.warmup()
    a = Sequence(prompt, 8)                # long-lived first owner
    eng.add(a)
    # step until A's prompt blocks are registered (prefill complete)
    steps = 0
    while not a.generated:
        eng.step()
        steps += 1
        assert steps < 50
    b = Sequence(prompt, 4)
    eng.add(b)
    while eng.has_work():
        eng.step()
        live = [s.block_ids for s in eng.scheduler.running()]
        eng.cache.check(live_block_ids=live)
    assert b.cache_hit_tokens == 2 * BS - 1
    assert eng.cache.cow_count >= 1
    ref_a = greedy_decode_reference(model, params, prompt, 8)
    ref_b = greedy_decode_reference(model, params, prompt, 4)
    assert a.output_tokens() == ref_a
    assert b.output_tokens() == ref_b
    eng.cache.check(live_block_ids=[])


@pytest.mark.slow   # distinct small-pool page shape = its own full
# XLA program set (~14s); tier-1 keeps preemption parity
# (test_llm_serving), shared-refcount chaos (test_serving_chaos) and
# the allocator-level LRU fuzz (test_ragged_attention)
def test_preemption_with_shared_blocks_parity(model, params):
    """KV pressure over a pool holding shared blocks: victims free
    their REFERENCES (never a block another sequence still reads),
    preempted sequences re-hit their own registered prefix on resume,
    and every stream stays bit-exact."""
    rng = np.random.RandomState(5)
    prefix = rng.randint(0, VOCAB, size=BS).tolist()
    cases = [(prefix + rng.randint(0, VOCAB, size=1 + i).tolist(), 8)
             for i in range(4)]
    # pool: one full-context sequence + barely any slack — decode
    # growth must outrun it even WITH the shared prefix block
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, num_blocks=CTX // BS + 2,
                    prefill_chunk=8)
    eng.warmup()
    seqs = [Sequence(p, n) for p, n in cases]
    for s in seqs:
        eng.add(s)
    steps = 0
    preempted = 0
    while eng.has_work():
        events = eng.step()
        preempted += sum(1 for e, _ in events if e == "preempted")
        live = [s.block_ids for s in eng.scheduler.running()]
        eng.cache.check(live_block_ids=live)
        steps += 1
        assert steps < 2000
    assert preempted >= 1, "pool was too large to exercise preemption"
    for (p, n), s in zip(cases, seqs):
        assert s.output_tokens() == greedy_decode_reference(
            model, params, p, n)
    eng.cache.check(live_block_ids=[])


@pytest.mark.slow   # shares the small-pool program set above
def test_hit_admission_counts_its_own_cached_blocks(model, params):
    """Admission-gate regression: a cache-hit sequence's hit blocks
    sit in the cached LRU, where they count as free capacity — but
    the admission is about to consume them itself. The gate must
    charge need + cached-hit blocks, or a hit sequence admits into
    capacity it is consuming and then PREEMPTS a healthy running
    sequence to cover its growth."""
    rng = np.random.RandomState(17)
    prompt_a = rng.randint(0, VOCAB, size=2 * BS).tolist()
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, num_blocks=CTX // BS + 2,
                    prefill_chunk=8)                   # 5 usable
    eng.warmup()
    a = Sequence(prompt_a, 2)
    _run_all(eng, [a])          # registers 2 blocks -> cached, 3 free
    assert eng.cache.stats()["blocks_cached"] == 2
    c = Sequence(rng.randint(0, VOCAB, size=2 * BS + 1).tolist(), 8)
    eng.add(c)
    steps = 0
    while not c.generated:      # C running, holding the 3 free blocks
        eng.step()
        steps += 1
        assert steps < 50
    b = Sequence(prompt_a, 4)   # full hit on A's 2 cached blocks
    eng.add(b)
    for _ in range(3):
        events = eng.step()
        # B must WAIT (need + its own cached hits exceed capacity),
        # never admit-then-preempt the healthy C
        assert not any(e == "preempted" for e, _ in events)
    assert b.state == "waiting" and c.state == "running"
    while eng.has_work():
        events = eng.step()
        assert not any(e == "preempted" for e, _ in events)
    assert b.cache_hit_tokens == 2 * BS - 1     # admitted after C freed
    assert b.output_tokens() == greedy_decode_reference(
        model, params, prompt_a, 4)
    eng.cache.check(live_block_ids=[])


@pytest.mark.slow   # its own tiny-pool page shape (~10s compile)
def test_aligned_live_hit_reserves_cow_block(model, params):
    """Admission-gate regression (the LIVE-shared twin of the test
    above): a block-aligned full hit on blocks another RUNNING
    sequence still owns must reserve the copy-on-write block up
    front — with only one free block the hit request WAITS instead of
    admitting, COWing the last free block away and then preempting
    the healthy owner to cover its first decode page."""
    rng = np.random.RandomState(23)
    prompt = rng.randint(0, VOCAB, size=2 * BS).tolist()   # aligned
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, num_blocks=CTX // BS + 1,
                    prefill_chunk=8)                       # 4 usable
    eng.warmup()
    a = Sequence(prompt, 8)
    eng.add(a)
    steps = 0
    while len(a.block_ids) < 3:     # A running: 2 prompt + 1 decode
        eng.step()                  # block all allocated (1 free left)
        steps += 1
        assert steps < 50
    b = Sequence(prompt, 4)     # aligned full hit on A's LIVE blocks
    eng.add(b)
    while eng.has_work():
        events = eng.step()
        assert not any(e == "preempted" for e, _ in events), \
            "hit admission preempted the healthy block owner"
    assert a.output_tokens() == greedy_decode_reference(
        model, params, prompt, 8)
    assert b.output_tokens() == greedy_decode_reference(
        model, params, prompt, 4)
    eng.cache.check(live_block_ids=[])


@pytest.mark.slow   # the speculative engine compiles its own
# target-step + draft program set (~25s); tier-1 retains spec parity
# without the cache (test_llm_sampling) and the no-spec zero-recompile
# pin below — this test carries the full spec x cache cross product
def test_speculative_decode_with_prefix_cache_parity(model, params,
                                                     draft,
                                                     draft_params):
    """Greedy speculative decoding over cache-hit sequences: the
    draft's catch-up feeds rebuild its (missing) KV for hit tokens,
    rollback trims only private blocks, and spec+cache greedy equals
    target-only greedy bit-exactly."""
    dparams = draft_params
    rng = np.random.RandomState(9)
    prefix = rng.randint(0, VOCAB, size=2 * BS).tolist()
    cases = _shared_mix(rng, prefix, n_shared=3, n_unshared=1)
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefill_chunk=4,
                    draft_model=draft, draft_params=dparams,
                    spec_k=2)
    eng.warmup()
    seqs = [Sequence(p, n) for p, n in cases]
    with serving.CompileCounter() as cc:
        # first wave registers the shared prefix; the rest hit it
        _run_all(eng, seqs[:2])
        _run_all(eng, seqs[2:])
    assert cc.count == 0, \
        f"{cc.count} recompiles under speculative cache-hit traffic"
    assert eng.prefix_hits >= 2
    for (p, n), s in zip(cases, seqs):
        assert s.output_tokens() == greedy_decode_reference(
            model, params, p, n)
    assert eng.cache.allocator.num_used == 0
    eng.cache.check(live_block_ids=[])


@pytest.mark.slow   # shares the small-pool program set above
def test_lru_eviction_reclaims_cached_blocks(model, params):
    """Cached (zero-refcount) blocks are spare capacity: when the
    strict free list runs short, the allocator reclaims them LRU-first
    — dropping their index entries and counting
    mxtpu_llm_prefix_evict_total — instead of preempting or failing."""
    rng = np.random.RandomState(13)
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, num_blocks=CTX // BS + 2,
                    prefill_chunk=8)
    eng.warmup()
    # churn distinct prompts through the tiny pool: finished
    # sequences' registered blocks park in the LRU until the next
    # admissions need the capacity back
    for i in range(6):
        s = Sequence(rng.randint(0, VOCAB, size=2 * BS + i).tolist(), 2)
        _run_all(eng, [s])
    assert eng.cache.prefix_evictions > 0
    st = eng.cache.stats()
    assert st["blocks_used"] == 0
    assert st["blocks_cached"] + (st["blocks_free"]
                                  - st["blocks_cached"]) >= 0
    # reclaimable capacity is the whole pool again
    assert eng.cache.allocator.num_free == eng.cache.allocator.num_usable
    eng.cache.check(live_block_ids=[])


def test_zero_recompiles_mixed_hit_miss_sampled(model, params):
    """The zero-steady-state-recompile contract: cache hits, misses,
    COW and sampled rows — the backend_compile counter must not move
    after warmup() (the speculative variant of this pin rides the slow
    spec-parity test above; cache hit vs miss never changes a program
    shape either way)."""
    rng = np.random.RandomState(21)
    prefix = rng.randint(0, VOCAB, size=2 * BS).tolist()
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefill_chunk=8)
    eng.warmup()
    with serving.CompileCounter() as cc:
        # wave 1 registers the shared prefix; wave 2 hits it (incl.
        # an aligned full-prompt hit that COWs, and sampled rows)
        _run_all(eng, [Sequence(prefix + [0], 4)])
        seqs = [Sequence(prefix + [i], 4,
                         sampling=SamplingParams(temperature=0.8,
                                                 seed=i)
                         if i % 2 else None)
                for i in range(1, 3)]
        seqs.append(Sequence(prefix, 3))            # aligned full hit
        seqs.append(Sequence(rng.randint(0, VOCAB, size=5).tolist(), 3))
        _run_all(eng, seqs)
    assert cc.count == 0, f"{cc.count} recompiles in steady state"
    assert eng.prefix_hits >= 3
    assert eng.cache.allocator.num_used == 0
    eng.cache.check(live_block_ids=[])


def test_server_stats_and_exposition(model, params):
    """The server path: hit telemetry lands in stats() and every new
    mxtpu_llm_prefix_* / kv-blocks-breakdown series lands in one
    Prometheus exposition, with per-tenant saved-token attribution."""
    from mxnet_tpu.observability import get_registry
    srv = LLMServer(model, params, name="prefix_stats", max_seqs=4,
                    block_size=BS, max_context=CTX, prefill_chunk=8)
    srv.warmup()
    srv.start()
    prompt = list(range(BS)) + [1, 2]
    # first generation registers the prefix; the rest hit it
    srv.submit(prompt, 3, tenant="acme").result(timeout=60)
    futs = [srv.submit(prompt, 3, tenant="acme") for _ in range(2)]
    for f in futs:
        f.result(timeout=60)
    st = srv.stats()
    srv.shutdown()
    assert st["prefix_cache"] is True
    assert st["kv_dtype"] == "float32"
    assert st["prefix_lookups"] == 3
    assert st["prefix_hits"] >= 1
    assert st["prefill_tokens_saved"] >= BS
    assert 0 < st["prefix_hit_rate"] <= 1
    assert st["kv_cache"]["prefix_blocks"] >= 1
    text = get_registry().expose()
    for series in ("mxtpu_llm_prefix_lookup_total",
                   "mxtpu_llm_prefix_hit_total",
                   "mxtpu_llm_prefix_evict_total",
                   "mxtpu_llm_prefill_tokens_saved_total",
                   "mxtpu_llm_kv_blocks_cached",
                   "mxtpu_llm_kv_blocks_shared",
                   "mxtpu_llm_kv_blocks_free",
                   "mxtpu_llm_tenant_prefill_tokens_saved_total"):
        assert series in text, f"{series} missing from exposition"


def test_env_gate_disables_prefix_cache(model, params, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_LLM_PREFIX_CACHE", "0")
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefill_chunk=8)
    assert eng.prefix_enabled is False
    eng.warmup()
    s1 = Sequence(list(range(2 * BS)), 2)
    s2 = Sequence(list(range(2 * BS)), 2)
    _run_all(eng, [s1, s2])
    assert eng.prefix_lookups == 0 and eng.prefix_hits == 0
    assert s1.output_tokens() == s2.output_tokens()
    st = eng.cache.stats()
    assert st["prefix_blocks"] == 0 and st["blocks_cached"] == 0
