"""Flight recorder: incident black box + exemplars + statusz (ISSUE 18).

Tier-1 coverage of the always-on bounded black box:

- recorder OFF is the shared no-op singleton: real served traffic
  records ZERO events (counter-asserted — the same discipline as the
  tracer's zero-per-step-allocation pin);
- recorder ON: the ring is bounded at its configured capacity under a
  10k-event burst, with every eviction counted as a drop;
- SLO page transition -> one post-mortem bundle, edge-triggered (a
  breach that stays breached fires once), carrying the burn-window
  reports in ``slo.json``;
- worker death -> crash-triggered bundle, while every submitted
  Future still resolves typed (the dump must not eat the chaos
  contract);
- a torn dump (InjectedCrash at the ``flight.dump`` site) leaves data
  files with NO manifest, and ``flight_inspect.check`` says so;
- exemplars on the hot-path latency histograms join back to the
  offending request's event timeline inside the same bundle;
- two bundles diff (the metrics pair chains "then" <- previous dump).

The LLM-engine end-to-end (admit/prefill/step events, TTFT exemplar,
engine statusz) needs a warmed decoder and is slow-marked; everything
tier-1 here runs against pure-Python ``ModelServer`` backends — no XLA
compiles at all.
"""
import glob
import importlib.util
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.serving import ServerClosed  # noqa: E402
from mxnet_tpu.observability import (  # noqa: E402
    get_flightrecorder, get_registry)
from mxnet_tpu.observability.flightrecorder import (  # noqa: E402
    flight_ring_capacity)
from mxnet_tpu.observability.exemplars import collect  # noqa: E402
from mxnet_tpu.observability.registry import MetricsRegistry  # noqa: E402
from mxnet_tpu.observability.timeseries import TimeSeriesRing  # noqa: E402
from mxnet_tpu.observability.slo import (  # noqa: E402
    SLO, SLOEngine, STATUS_PAGE)
from mxnet_tpu.resilience import InjectedCrash, faults  # noqa: E402

ITEM = (2,)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _flight_state():
    """Every test leaves the process-wide singleton OFF, empty, and at
    the default ring capacity (tests share one interpreter)."""
    fl = get_flightrecorder()
    fl.disable()
    fl.clear()
    faults.reset()
    yield fl
    fl.enable(ring=flight_ring_capacity())
    fl.disable()
    fl.clear()
    faults.reset()


def _echo_server(name, **kw):
    kw.setdefault("buckets", [1, 2, 4])
    kw.setdefault("max_delay_ms", 5.0)
    return serving.ModelServer(lambda b: b * 2.0, item_shape=ITEM,
                               dtype="float32", name=name,
                               **kw).start()


def _serve_burst(srv, n=4):
    for f in [srv.submit(np.zeros(ITEM, np.float32))
              for _ in range(n)]:
        f.result(timeout=30)


def _bundles(tmp, trigger):
    return sorted(glob.glob(os.path.join(tmp, f"flight_*_{trigger}")))


def _read(bundle, fname):
    with open(os.path.join(bundle, fname)) as f:
        return json.load(f)


# ------------------------------------------------------ off = no-op --

def test_off_mode_records_nothing_counter_asserted(_flight_state):
    """The zero-overhead pin: with the recorder off, real served
    traffic moves NEITHER the ring nor the events counter — the same
    shared-no-op discipline as the tracer."""
    fl = _flight_state
    assert not fl.enabled
    before = fl.stats()
    srv = _echo_server("flight_off")
    _serve_burst(srv, n=6)
    srv.shutdown()
    after = fl.stats()
    assert after["recorded"] == before["recorded"]
    assert after["buffered"] == 0
    assert after["dropped"] == before["dropped"]
    # and event() itself is inert, not queueing anywhere
    fl.event("serving.submit", req="srv:ghost")
    assert fl.stats()["recorded"] == before["recorded"]


# --------------------------------------------------- bounded ring ----

def test_ring_bounded_with_counted_drops_at_10k_events(_flight_state):
    fl = _flight_state
    fl.enable(ring=128)
    base = fl.stats()
    for i in range(10_000):
        fl.event("llm.step", attrs={"i": i})
    st = fl.stats()
    assert st["capacity"] == 128
    assert st["buffered"] == 128                  # flat, not 10k
    assert st["recorded"] - base["recorded"] == 10_000
    assert st["dropped"] - base["dropped"] == 10_000 - (128 - base["buffered"])
    # the ring holds the NEWEST events (black-box semantics: the tail
    # before the incident, not the takeoff)
    snap = fl.snapshot()
    assert snap[-1]["attrs"]["i"] == 9_999
    assert snap[0]["attrs"]["i"] == 9_999 - 127


# ------------------------------------------------ SLO-page trigger ---

def _paging_fixture():
    """A local registry + ring whose last second burns hot enough that
    a (1.5s, 1s) window pair pages at threshold 1.0 (borrowed from
    test_slo_capacity's exact-burn fixtures)."""
    reg = MetricsRegistry()
    served = reg.counter("mxtpu_serving_requests_completed_total", "",
                         ("server",)).labels(server="u")
    shed = reg.counter("mxtpu_serving_shed_total", "",
                       ("server", "reason")).labels(server="u",
                                                    reason="queue_full")
    reg.counter("mxtpu_serving_deadline_expired_total", "",
                ("server",)).labels(server="u")
    ring = TimeSeriesRing(reg, capacity=32)
    t = 0.0
    ring.record(now=t)
    for _ in range(9):
        t += 1.0
        served.inc(100)
        ring.record(now=t)
    t += 1.0
    served.inc(100)
    shed.inc(10)
    ring.record(now=t)
    slo = SLO.serving_availability("avail_flight", "u", target=0.95)
    eng = SLOEngine([slo], ring, registry=reg,
                    windows=[(1.5, 1.0, 1.0, STATUS_PAGE)])
    return eng


def test_slo_page_transition_dumps_bundle_once(_flight_state,
                                               tmp_path):
    fl = _flight_state
    fl.enable(out_dir=str(tmp_path))
    eng = _paging_fixture()
    rep = eng.evaluate()["avail_flight"]
    assert rep["status"] == STATUS_PAGE
    bundles = _bundles(str(tmp_path), "slo")
    assert len(bundles) == 1, "page transition must cut one bundle"
    man = _read(bundles[0], "MANIFEST.json")
    assert man["trigger"] == "slo"
    assert "avail_flight" in man["reason"]
    # burn windows ride inside the bundle
    slo_blob = _read(bundles[0], "slo.json")
    assert slo_blob["avail_flight"]["status"] == STATUS_PAGE
    assert "burn_rates" in slo_blob["avail_flight"]
    # the trigger left its own decision event in the ring
    kinds = [e["kind"] for e in _read(bundles[0], "events.json")]
    assert "slo.trigger" in kinds
    # edge-triggered: still paging on the next pass -> NO second bundle
    eng.evaluate()
    assert len(_bundles(str(tmp_path), "slo")) == 1


def test_slo_trigger_gated_by_trigger_list(_flight_state, tmp_path,
                                           monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FLIGHT_TRIGGERS", "crash")
    fl = _flight_state
    fl.enable(out_dir=str(tmp_path))
    eng = _paging_fixture()
    assert eng.evaluate()["avail_flight"]["status"] == STATUS_PAGE
    assert _bundles(str(tmp_path), "slo") == []


# -------------------------------------------------- crash trigger ----

def test_worker_death_dumps_bundle_and_futures_resolve_typed(
        _flight_state, tmp_path):
    """The chaos invariant survives the black box: InjectedCrash at
    the serving.worker point cuts a crash bundle AND every Future
    still resolves typed — the dump must never add a hang."""
    fl = _flight_state
    fl.enable(out_dir=str(tmp_path))
    faults.crash_at_point("serving.worker", nth=1)
    srv = _echo_server("flight_crash", max_delay_ms=100.0)
    futs = [srv.submit(np.zeros(ITEM, np.float32)) for _ in range(5)]
    resolved, errors = 0, []
    for f in futs:
        try:
            f.result(timeout=30)
            resolved += 1
        except BaseException as exc:
            errors.append(exc)
    assert resolved + len(errors) == 5            # nothing hangs
    assert errors and all(isinstance(e, ServerClosed) for e in errors)
    faults.reset()
    srv.shutdown()
    bundles = _bundles(str(tmp_path), "crash")
    assert len(bundles) == 1
    man = _read(bundles[0], "MANIFEST.json")
    assert man["trigger"] == "crash"
    assert "InjectedCrash" in man["reason"]
    assert (man.get("extra") or {}).get("server") == "flight_crash"
    # the ring caught the submits that preceded the death, and the
    # statusz sweep caught the still-live server
    events = _read(bundles[0], "events.json")
    assert any(e["kind"] == "serving.submit"
               and e["req"].startswith("srv:") for e in events)
    assert "serving:flight_crash" in _read(bundles[0], "status.json")
    fi = _load_tool("flight_inspect")
    assert fi.check(bundles[0]) == []


def test_torn_dump_leaves_no_manifest_and_check_reports_it(
        _flight_state, tmp_path):
    fl = _flight_state
    fl.enable(out_dir=str(tmp_path))
    fl.event("serving.submit", req="srv:1")
    faults.crash_at_point("flight.dump", nth=1)
    with pytest.raises(InjectedCrash):
        fl.dump(trigger="manual", reason="torn")
    faults.reset()
    torn = _bundles(str(tmp_path), "manual")
    assert len(torn) == 1
    assert not os.path.exists(os.path.join(torn[0], "MANIFEST.json"))
    assert os.path.exists(os.path.join(torn[0], "events.json"))
    fi = _load_tool("flight_inspect")
    probs = fi.check(torn[0])
    assert probs and any("manifest" in p.lower() for p in probs)


# ------------------------------------------- exemplars + statusz -----

def test_exemplar_joins_back_to_request_timeline(_flight_state,
                                                 tmp_path):
    """The page-to-cause path: a latency exemplar captured on the hot
    path carries the SAME ``srv:<rid>`` key as the request's events,
    so a bundle resolves slow-bucket occupants to full timelines."""
    fl = _flight_state
    fl.enable(out_dir=str(tmp_path))
    srv = _echo_server("flight_exm")
    _serve_burst(srv, n=4)
    bundle = fl.dump(trigger="manual", reason="exemplar join")
    srv.shutdown()
    exm = _read(bundle, "exemplars.json")
    rows = [r for r in exm.get("mxtpu_serving_latency_seconds", [])
            if r["labels"].get("server") == "flight_exm"]
    assert rows, "served burst must have left latency exemplars"
    reqs = {e["req"] for bkt in rows[0]["buckets"].values()
            for e in bkt}
    assert reqs and all(r.startswith("srv:") for r in reqs)
    events = _read(bundle, "events.json")
    by_req = {e["req"] for e in events if e["req"]}
    assert reqs <= by_req, "every exemplar must join to ring events"
    # and the inspector renders that join (exemplar -> waterfall)
    fi = _load_tool("flight_inspect")
    out = fi.render_exemplars(bundle,
                              "mxtpu_serving_latency_seconds")
    assert any(r in out for r in reqs)


def test_model_server_statusz_shape(_flight_state):
    srv = _echo_server("flight_statusz", max_queue=7)
    _serve_burst(srv, n=2)
    st = srv.debug_status()
    srv.shutdown()
    assert st["kind"] == "serving"
    assert st["server"] == "flight_statusz"
    assert st["max_queue"] == 7
    assert st["queue_depth"] == 0 and st["inflight"] == []
    assert st["breaker_state"] in (0, 1, 2)
    json.dumps(st)                      # JSON-safe, whole surface


# ------------------------------------------------------ bundle diff --

def test_bundle_diff_pairs_consecutive_dumps(_flight_state, tmp_path):
    """metrics_then of bundle N+1 == metrics_now of bundle N (the
    baseline refresh chains bundles), and the inspector's diff
    renders what moved between them."""
    fl = _flight_state
    fl.enable(out_dir=str(tmp_path))
    srv = _echo_server("flight_diff")
    _serve_burst(srv, n=2)
    b1 = fl.dump(trigger="manual", reason="first")
    _serve_burst(srv, n=3)
    b2 = fl.dump(trigger="manual", reason="second")
    srv.shutdown()
    assert _read(b2, "metrics_then.json") == _read(b1,
                                                   "metrics_now.json")
    fi = _load_tool("flight_inspect")
    assert fi.check(b1) == [] and fi.check(b2) == []
    out = fi.diff(b1, b2)
    assert "recorded" in out
    assert os.path.basename(b1) in out and os.path.basename(b2) in out


# ------------------------------------------------- LLM e2e (slow) ----

@pytest.fixture(scope="module")
def llm_srv():
    """ONE warmed decoder server for every slow LLM test in this
    module (warmup is the expensive part on a 1-CPU box)."""
    from mxnet_tpu.serving.llm import TinyDecoder, DecoderConfig, LLMServer
    model = TinyDecoder(DecoderConfig(
        vocab_size=17, d_model=16, num_layers=2, num_heads=2,
        d_ff=32, max_context=64))
    srv = LLMServer(model, model.init_params(seed=0),
                    name="flight_llm", max_seqs=2, block_size=8,
                    max_context=64, max_queue=32)
    srv.warmup()
    srv.start()
    yield srv
    srv.shutdown()


@pytest.mark.slow
def test_llm_request_timeline_exemplar_and_statusz(
        _flight_state, tmp_path, llm_srv):
    """End to end on a real engine: one request's full event timeline
    (submit -> admit -> prefill -> step -> served) lands in the ring,
    its TTFT exemplar joins back to it, and the engine's statusz
    carries KV/program accounting — all with zero recompiles (the
    recorder is pure host code on warmed programs)."""
    fl = _flight_state
    fl.enable(out_dir=str(tmp_path))
    with serving.CompileCounter() as cc:
        futs = [llm_srv.submit([1 + i, 2, 3], max_new_tokens=3)
                for i in range(3)]
        for f in futs:
            f.result(timeout=60)
    assert cc.count == 0, "recording must not recompile warm programs"
    events = fl.snapshot()
    by_req = {}
    for e in events:
        if e["req"]:
            by_req.setdefault(e["req"], []).append(e["kind"])
    llm_reqs = {r for r in by_req if r.startswith("llm:")}
    assert len(llm_reqs) == 3
    for r in llm_reqs:
        assert {"llm.submit", "llm.admit", "llm.prefill",
                "llm.served"} <= set(by_req[r])
    assert any(e["kind"] == "llm.step" for e in events)
    # TTFT exemplars carry the same llm:<seq> keys
    exm = collect(get_registry(), ("mxtpu_llm_ttft_seconds",))
    ttft_reqs = {e["req"]
                 for row in exm.get("mxtpu_llm_ttft_seconds", [])
                 if row["labels"].get("server") == "flight_llm"
                 for bkt in row["buckets"].values() for e in bkt}
    assert ttft_reqs & llm_reqs
    # statusz: server -> engine sweep, JSON-safe
    st = llm_srv.debug_status()
    assert st["kind"] == "llm"
    eng = st["engine"]
    assert set(eng["kv_blocks"]) >= {"used", "usable", "free"}
    assert eng["programs"]["warmed"]
    json.dumps(st)
    # and the bundle round-trips through the inspector's request view
    bundle = fl.dump(trigger="manual", reason="llm e2e")
    fi = _load_tool("flight_inspect")
    assert fi.check(bundle) == []
    req = sorted(llm_reqs)[0]
    out = fi.render_request(bundle, req)
    assert "llm.admit" in out and "llm.served" in out
