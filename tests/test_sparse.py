"""Sparse storage types and the sparse compute paths.

Reference behaviours pinned here:
- python/mxnet/ndarray/sparse.py (row_sparse_array/csr_matrix/tostype)
- src/operator/tensor/dot.cc DotCsrDnsDns (csr @ dense, csr.T @ dense)
- src/operator/tensor/indexing_op.cc EmbeddingOpBackward row-sparse grad
- src/operator/optimizer_op.cc SGDUpdateRspImpl / AdamUpdateRspImpl
  (lazy updates touch only rows present in the gradient)
- kvstore.h PullRowSparse (row_sparse_pull gathers only requested rows)

The TPU-native property under test everywhere: nothing densifies unless
a dense op is explicitly applied (``.densified`` stays False).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.ndarray import sparse
import mxnet_tpu.autograd as ag


def test_row_sparse_lazy_storage():
    r = sparse.row_sparse_array((np.ones((2, 3), np.float32), [1, 4]),
                                shape=(6, 3))
    assert r.stype == "row_sparse"
    assert r.shape == (6, 3) and r.ndim == 2 and r.size == 18
    assert not r.densified          # no dense buffer yet
    np.testing.assert_allclose(r.indices.asnumpy(), [1, 4])
    np.testing.assert_allclose(r.data.asnumpy(), np.ones((2, 3)))
    dense = r.asnumpy()             # first dense touch materializes
    assert r.densified
    expect = np.zeros((6, 3), np.float32)
    expect[[1, 4]] = 1
    np.testing.assert_allclose(dense, expect)


def test_row_sparse_from_dense_and_tostype():
    d = np.zeros((5, 2), np.float32)
    d[0] = [1, 2]
    d[3] = [3, 4]
    r = sparse.row_sparse_array(d)
    np.testing.assert_allclose(r.indices.asnumpy(), [0, 3])
    np.testing.assert_allclose(r.tostype("default").asnumpy(), d)
    back = sparse.cast_storage(nd.array(d), "row_sparse")
    np.testing.assert_allclose(back.asnumpy(), d)


def test_csr_roundtrip_and_spmm():
    rng = np.random.RandomState(0)
    a = rng.randn(6, 8).astype(np.float32)
    a[a < 0.5] = 0                   # sparsify
    c = sparse.csr_matrix(a)
    np.testing.assert_allclose(c.asnumpy(), a)
    c2 = sparse.csr_matrix(a)        # fresh, undensified copy
    b = rng.randn(8, 4).astype(np.float32)
    out = sparse.dot(c2, nd.array(b))
    np.testing.assert_allclose(out.asnumpy(), a @ b, rtol=1e-5, atol=1e-5)
    assert not c2.densified          # SpMM ran on the structure
    bt = rng.randn(6, 4).astype(np.float32)
    out_t = sparse.dot(c2, nd.array(bt), transpose_a=True)
    np.testing.assert_allclose(out_t.asnumpy(), a.T @ bt, rtol=1e-5,
                               atol=1e-5)
    assert not c2.densified


@pytest.mark.slow   # ~7s on 1 CPU (tier-1 budget); csr dot
# coverage stays fast via csr_roundtrip_and_spmm + csr_dot_vector_rhs
def test_csr_dot_gradient_flows():
    """Autograd through sparse.dot: grad wrt the dense rhs must equal
    the dense-oracle csr.T @ dy (regression: the csr path used to build
    its output outside the tape, silently returning zero grads —
    surfaced by examples/sparse_linear_classification.py)."""
    import mxnet_tpu.autograd as ag
    rng = np.random.RandomState(0)
    dense_lhs = (rng.rand(6, 8) < 0.3).astype(np.float32) * \
        rng.randn(6, 8).astype(np.float32)
    csr = mx.nd.sparse.csr_matrix(dense_lhs)
    w = mx.nd.array(rng.randn(8, 3).astype(np.float32))
    w.attach_grad()
    dy = rng.randn(6, 3).astype(np.float32)
    with ag.record():
        out = mx.nd.sparse.dot(csr, w)
        loss = (out * mx.nd.array(dy)).sum()
    loss.backward()
    np.testing.assert_allclose(out.asnumpy(), dense_lhs @ w.asnumpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(w.grad.asnumpy(), dense_lhs.T @ dy,
                               rtol=1e-4, atol=1e-5)
    # transposed: csr.T @ w2
    w2 = mx.nd.array(rng.randn(6, 3).astype(np.float32))
    w2.attach_grad()
    dy2 = rng.randn(8, 3).astype(np.float32)
    with ag.record():
        out2 = mx.nd.sparse.dot(csr, w2, transpose_a=True)
        loss2 = (out2 * mx.nd.array(dy2)).sum()
    loss2.backward()
    np.testing.assert_allclose(w2.grad.asnumpy(), dense_lhs @ dy2,
                               rtol=1e-4, atol=1e-5)


def test_csr_dot_vector_rhs():
    """csr @ 1-D vector keeps shape (m,) (regression: the 2-D-only
    contraction silently produced (m, nnz))."""
    rng = np.random.RandomState(1)
    dense_lhs = (rng.rand(5, 7) < 0.4).astype(np.float32) * \
        rng.randn(5, 7).astype(np.float32)
    csr = mx.nd.sparse.csr_matrix(dense_lhs)
    v = mx.nd.array(rng.randn(7).astype(np.float32))
    out = mx.nd.sparse.dot(csr, v)
    assert out.shape == (5,)
    np.testing.assert_allclose(out.asnumpy(), dense_lhs @ v.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    vt = mx.nd.array(rng.randn(5).astype(np.float32))
    out_t = mx.nd.sparse.dot(csr, vt, transpose_a=True)
    assert out_t.shape == (7,)
    np.testing.assert_allclose(out_t.asnumpy(),
                               dense_lhs.T @ vt.asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_retain():
    r = sparse.row_sparse_array(
        (np.arange(6, dtype=np.float32).reshape(3, 2), [1, 4, 5]),
        shape=(7, 2))
    kept = sparse.retain(r, [4, 6])
    dense = kept.asnumpy()
    expect = np.zeros((7, 2), np.float32)
    expect[4] = [2, 3]
    np.testing.assert_allclose(dense, expect)


def test_sparse_add_stays_sparse():
    a = sparse.row_sparse_array((np.ones((1, 2), np.float32), [0]),
                                shape=(4, 2))
    b = sparse.row_sparse_array((np.ones((2, 2), np.float32), [0, 2]),
                                shape=(4, 2))
    s = sparse.add(a, b)
    assert s.stype == "row_sparse" and not s.densified
    expect = np.zeros((4, 2), np.float32)
    expect[0] = 2
    expect[2] = 1
    np.testing.assert_allclose(s.asnumpy(), expect)


def test_embedding_sparse_grad():
    """sparse_grad=True produces a RowSparseNDArray gradient holding the
    looked-up rows only — never a (vocab, dim) dense scatter."""
    emb = gluon.nn.Embedding(1000, 4, sparse_grad=True)
    emb.initialize()
    assert emb.weight.grad_stype == "row_sparse"
    x = nd.array(np.array([[1, 3], [3, 7]]))
    with ag.record():
        out = emb(x)
        loss = (out * out).sum()
    loss.backward()
    g = emb.weight.grad()
    assert isinstance(g, sparse.RowSparseNDArray)
    assert not g.densified
    assert set(np.asarray(g.indices.asnumpy()).tolist()) == {1, 3, 3, 7} \
        or sorted(np.asarray(g.indices.asnumpy()).tolist()) == [1, 3, 3, 7]
    # value check against the dense-grad oracle
    emb2 = gluon.nn.Embedding(1000, 4, sparse_grad=False)
    emb2.initialize()
    emb2.weight.set_data(emb.weight.data())
    with ag.record():
        loss2 = (emb2(x) ** 2).sum()
    loss2.backward()
    np.testing.assert_allclose(g.asnumpy(),
                               emb2.weight.grad().asnumpy(), rtol=1e-5)


@pytest.mark.parametrize("opt,kwargs", [
    ("sgd", {"learning_rate": 0.5}),
    ("sgd", {"learning_rate": 0.5, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.1}),
])
def test_lazy_update_touches_only_grad_rows(opt, kwargs):
    mx.random.seed(0)
    emb = gluon.nn.Embedding(64, 3, sparse_grad=True)
    emb.initialize()
    w0 = emb.weight.data().asnumpy().copy()
    tr = gluon.Trainer(emb.collect_params(), opt, kwargs)
    x = nd.array(np.array([2, 5, 5, 9]))
    with ag.record():
        loss = (emb(x) ** 2).sum()
    loss.backward()
    tr.step(1)
    w1 = emb.weight.data().asnumpy()
    changed = set(np.where(np.abs(w1 - w0).sum(axis=1) > 0)[0].tolist())
    assert changed <= {2, 5, 9}, changed
    assert changed, "no rows updated"


def test_lazy_sgd_matches_dense_on_touched_rows():
    """On the touched rows, the lazy update must equal the dense sgd
    update (reference: lazy_update only skips untouched rows)."""
    mx.random.seed(1)
    vals = np.array([[1.0, -2.0], [0.5, 0.25]], np.float32)
    g = sparse.row_sparse_array((vals, [1, 3]), shape=(5, 2))
    w = np.arange(10, dtype=np.float32).reshape(5, 2)
    from mxnet_tpu import optimizer as optmod
    opt = optmod.SGD(learning_rate=0.1, momentum=0.9, wd=0.01)
    weight = nd.array(w.copy())
    state = opt.create_state(0, weight)
    opt.update(0, weight, g, state)
    out = weight.asnumpy()
    # dense oracle
    expect = w.copy()
    mom = np.zeros_like(w)
    gd = np.zeros_like(w)
    gd[[1, 3]] = vals
    touched = [1, 3]
    mom_t = 0.9 * mom[touched] + gd[touched] + 0.01 * w[touched]
    expect[touched] = w[touched] - 0.1 * mom_t
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_kvstore_row_sparse_pull_and_sparse_push():
    kv = mx.kv.create("local")
    val = np.arange(12, dtype=np.float32).reshape(6, 2)
    kv.init(3, nd.array(val))
    out = sparse.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull(3, out=out, row_ids=nd.array(np.array([1, 4, 4])))
    assert not out.densified
    np.testing.assert_allclose(out.indices.asnumpy(), [1, 4])
    np.testing.assert_allclose(out.data.asnumpy(), val[[1, 4]])
    # push of row-sparse values reduces sparsely (no updater set)
    g1 = sparse.row_sparse_array((np.ones((1, 2), np.float32), [0]),
                                 shape=(6, 2))
    g2 = sparse.row_sparse_array((np.ones((1, 2), np.float32), [2]),
                                 shape=(6, 2))
    kv.init(4, sparse.zeros("row_sparse", (6, 2)))
    kv.push(4, [g1, g2])
    pulled = nd.zeros((6, 2))
    kv.pull(4, out=pulled)
    expect = np.zeros((6, 2), np.float32)
    expect[0] = 1
    expect[2] = 1
    np.testing.assert_allclose(pulled.asnumpy(), expect)


def test_parameter_row_sparse_data():
    p = gluon.Parameter("w", shape=(8, 3), stype="row_sparse")
    p.initialize()
    rows = p.row_sparse_data(nd.array(np.array([0, 6])))
    assert isinstance(rows, sparse.RowSparseNDArray)
    np.testing.assert_allclose(rows.indices.asnumpy(), [0, 6])
    np.testing.assert_allclose(rows.data.asnumpy(),
                               p.data().asnumpy()[[0, 6]])
