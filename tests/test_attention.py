"""Flash attention kernel + MultiHeadAttention + BERT tests.

The Pallas kernel runs in interpret mode on CPU (flash_attention picks
that automatically); ``attention_reference`` is the oracle, and gradients
are pinned against ``jax.vjp`` of the oracle — so these tests hold for
both the interpret path here and the compiled path on TPU.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.autograd as ag
from mxnet_tpu.ops.flash_attention import (attention_reference,
                                           flash_attention)


def _rand_qkv(rng, B, H, Tq, Tk, D, dtype=np.float32):
    q = rng.randn(B, H, Tq, D).astype(dtype)
    k = rng.randn(B, H, Tk, D).astype(dtype)
    v = rng.randn(B, H, Tk, D).astype(dtype)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("T", [16, 64, 80])   # 80: not a block multiple
def test_flash_forward_matches_reference(causal, T):
    rng = np.random.RandomState(0)
    q, k, v = _rand_qkv(rng, 2, 2, T, T, 16)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_forward_cross_attention():
    rng = np.random.RandomState(1)
    q, k, v = _rand_qkv(rng, 2, 3, 24, 56, 8)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_forward_with_bias_mask():
    rng = np.random.RandomState(2)
    B, H, T, D = 2, 2, 40, 16
    q, k, v = _rand_qkv(rng, B, H, T, T, D)
    lengths = np.array([33, 17])
    bias = np.where(np.arange(T)[None, :] < lengths[:, None],
                    0.0, -1e30).astype(np.float32)
    bias = jnp.asarray(bias)
    out = flash_attention(q, k, v, bias=bias, block_q=16, block_k=16)
    ref = attention_reference(q, k, v, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    rng = np.random.RandomState(3)
    q, k, v = _rand_qkv(rng, 1, 2, 48, 48, 16)
    g = jnp.asarray(rng.randn(1, 2, 48, 16).astype(np.float32))

    out, vjp = jax.vjp(
        lambda q, k, v: flash_attention(q, k, v, causal=causal,
                                        block_q=16, block_k=16), q, k, v)
    dq, dk, dv = vjp(g)
    ref_out, ref_vjp = jax.vjp(
        lambda q, k, v: attention_reference(q, k, v, causal=causal),
        q, k, v)
    rdq, rdk, rdv = ref_vjp(g)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=2e-5, atol=2e-5)
    for a, b, name in [(dq, rdq, "dq"), (dk, rdk, "dk"), (dv, rdv, "dv")]:
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_flash_grads_with_bias_and_ragged_shapes():
    rng = np.random.RandomState(4)
    B, H, Tq, Tk, D = 2, 2, 20, 36, 8   # neither a block multiple
    q, k, v = _rand_qkv(rng, B, H, Tq, Tk, D)
    lengths = np.array([36, 11])
    bias = jnp.asarray(np.where(np.arange(Tk)[None, :] < lengths[:, None],
                                0.0, -1e30).astype(np.float32))
    g = jnp.asarray(rng.randn(B, H, Tq, D).astype(np.float32))

    out, vjp = jax.vjp(
        lambda q, k, v, b: flash_attention(q, k, v, bias=b, block_q=16,
                                           block_k=16), q, k, v, bias)
    grads = vjp(g)
    ref_out, ref_vjp = jax.vjp(
        lambda q, k, v, b: attention_reference(q, k, v, b), q, k, v, bias)
    ref_grads = ref_vjp(g)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=2e-5, atol=2e-5)
    for a, b, name in zip(grads, ref_grads, ["dq", "dk", "dv", "dbias"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_flash_bf16_close_to_f32_reference():
    rng = np.random.RandomState(5)
    q, k, v = _rand_qkv(rng, 1, 2, 32, 32, 16)
    out16 = flash_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                            v.astype(jnp.bfloat16), block_q=16, block_k=16)
    ref = attention_reference(q, k, v)
    assert out16.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out16, dtype=np.float32),
                               np.asarray(ref), rtol=0.05, atol=0.05)


def test_memory_scales_linearly_in_seq_len():
    """The jitted flash fwd+bwd must not materialize the (T, T) score
    matrix: peak temp memory from XLA's own analysis should grow ~O(T),
    not O(T^2)."""
    def train_mem(T):
        rng = np.random.RandomState(0)
        q, k, v = _rand_qkv(rng, 1, 1, T, T, 16)

        def f(q, k, v):
            # default interpret selection: real kernel on TPU, interpret
            # lowering on CPU — both keep block-resident buffers only
            return flash_attention(q, k, v, causal=True).sum()
        c = jax.jit(jax.grad(f)).lower(q, k, v)
        try:
            mem = c.compile().memory_analysis()
            return float(mem.temp_size_in_bytes)
        except Exception:
            pytest.skip("memory analysis unavailable on this backend")

    m1, m2 = train_mem(512), train_mem(2048)
    # O(T^2) would give 16x; O(T) gives ~4x. Allow slack.
    assert m2 < 8 * m1, (m1, m2)


# --------------------------------------------------------------------------
# MultiHeadAttention layer


def test_multi_head_attention_forward_and_grads():
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    mha = nn.MultiHeadAttention(units=32, num_heads=4, flash=False)
    mha.initialize()
    x = nd.array(np.random.RandomState(0).randn(2, 10, 32))
    with ag.record():
        out = mha(x)
        loss = (out * out).sum()
    loss.backward()
    assert out.shape == (2, 10, 32)
    g = mha.query_proj.weight.grad().asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0

    # flash path (interpret mode on CPU) must agree with the XLA path
    mha2 = nn.MultiHeadAttention(units=32, num_heads=4, flash=True)
    mha2.initialize()
    for (ka, pa), (kb, pb) in zip(sorted(mha.collect_params().items()),
                                  sorted(mha2.collect_params().items())):
        pb.set_data(pa.data())
    with ag.pause():
        o1 = mha(x).asnumpy()
        o2 = mha2(x).asnumpy()
    np.testing.assert_allclose(o2, o1, rtol=2e-5, atol=2e-5)


def test_multi_head_attention_padding_mask():
    from mxnet_tpu.gluon import nn

    mx.random.seed(1)
    mha = nn.MultiHeadAttention(units=16, num_heads=2, flash=False)
    mha.initialize()
    rng = np.random.RandomState(1)
    x = rng.randn(2, 8, 16).astype(np.float32)
    mask = np.zeros((2, 8), np.float32)
    mask[:, 5:] = -1e30   # drop last 3 keys
    with ag.pause():
        out_masked = mha(nd.array(x), mask=nd.array(mask)).asnumpy()
        # changing the masked tail of the *keys/values* must not matter
        x2 = x.copy()
        x2[:, 5:, :] = rng.randn(2, 3, 16)
        out_masked2 = mha(nd.array(x2), mask=nd.array(mask)).asnumpy()
    np.testing.assert_allclose(out_masked[:, :5], out_masked2[:, :5],
                               rtol=1e-4, atol=1e-4)


# --------------------------------------------------------------------------
# BERT


def test_bert_small_forward_shapes():
    from mxnet_tpu.gluon.model_zoo.bert import bert_small

    mx.random.seed(0)
    net = bert_small(vocab_size=100)
    net.initialize()
    tokens = nd.array(np.random.RandomState(0).randint(0, 100, (2, 12)))
    valid_len = nd.array(np.array([12, 7]))
    with ag.pause():
        seq, pooled = net(tokens, valid_length=valid_len)
    assert seq.shape[0] == 2 and seq.shape[1] == 12
    assert pooled.shape[0] == 2
    assert np.isfinite(seq.asnumpy()).all()
    assert np.isfinite(pooled.asnumpy()).all()


@pytest.mark.slow   # ~60s convergence loop (tier-1 budget, ISSUE 12);
# attention-correctness coverage stays via the parity tests above
def test_bert_tiny_convergence():
    """A tiny BERT must be able to fit a toy sequence-classification task
    (grads flow through embeddings, attention, layernorm, pooler)."""
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    mx.random.seed(2)
    bert = BERTModel(vocab_size=16, units=16, hidden_size=32, num_heads=2,
                     num_layers=1, max_length=16, dropout=0.0)
    head = nn.Dense(2)
    bert.initialize()
    head.initialize()
    params = bert.collect_params()
    params.update(head.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 5e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(0)
    # task: class = whether token 0 is < 8
    tokens_np = rng.randint(0, 16, (16, 6))
    labels_np = (tokens_np[:, 0] < 8).astype(np.float32)
    tokens, labels = nd.array(tokens_np), nd.array(labels_np)
    losses = []
    for i in range(60):
        with ag.record():
            _, pooled = bert(tokens)
            out = head(pooled)
            loss = loss_fn(out, labels).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < 0.25 * losses[0], (losses[0], losses[-1])
