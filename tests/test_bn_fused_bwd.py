"""Pin the fused BatchNorm backward (MXNET_TPU_BN_FUSED_BWD=1) against
the autodiff path.

The fused path is an HBM-bandwidth lever for TPU training (two sibling
reductions + one elementwise pass instead of autodiff's reduction chain;
reference computes the same grouping in src/operator/nn/batch_norm.cu
DoBNBackward). It must be numerically indistinguishable from the default
path so it can be flipped on for benchmarking without a correctness
question. These tests run on CPU so the lever is verified before any
hardware window.
"""
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from mxnet_tpu.ops.registry import _REGISTRY

_BN = _REGISTRY["BatchNorm"].impl


def _flag(on):
    if on:
        os.environ["MXNET_TPU_BN_FUSED_BWD"] = "1"
    else:
        os.environ.pop("MXNET_TPU_BN_FUSED_BWD", None)


@pytest.fixture(autouse=True)
def _restore_flag():
    yield
    os.environ.pop("MXNET_TPU_BN_FUSED_BWD", None)


def _run(on, x, gamma, beta, axis, fix_gamma, dtype, jit):
    """loss-style scalar reduction through training-mode BN; returns
    (out, mean, var, dx, dgamma, dbeta) as float64 numpy."""
    _flag(on)
    c = x.shape[axis]
    mmean = jnp.zeros(c, dtype)
    mvar = jnp.ones(c, dtype)

    def fwd(x, gamma, beta):
        return _BN(x, gamma, beta, mmean, mvar, eps=1e-3,
                   fix_gamma=fix_gamma, output_mean_var=True, axis=axis,
                   _training=True)

    def loss(x, gamma, beta):
        out, mean, var = fwd(x, gamma, beta)
        # a weighting that makes every element's gradient distinct
        w = jnp.arange(out.size, dtype=jnp.float32).reshape(out.shape)
        return jnp.sum(out.astype(jnp.float32) * jnp.sin(w))

    gfn = jax.grad(loss, argnums=(0, 1, 2))
    if jit:
        fwd = jax.jit(fwd)
        gfn = jax.jit(gfn)
    out, mean, var = fwd(x, gamma, beta)
    dx, dg, db = gfn(x, gamma, beta)
    return [np.asarray(a, np.float64) for a in (out, mean, var, dx, dg, db)]


@pytest.mark.parametrize("axis", [1, 3])
@pytest.mark.parametrize("fix_gamma", [False, True])
@pytest.mark.parametrize("jit", [False, True])
def test_fused_matches_autodiff_f32(axis, fix_gamma, jit):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 3, 5, 6).astype(np.float32))
    c = x.shape[axis]
    gamma = jnp.asarray(rng.uniform(0.5, 1.5, c).astype(np.float32))
    beta = jnp.asarray(rng.randn(c).astype(np.float32))
    ref = _run(False, x, gamma, beta, axis, fix_gamma, jnp.float32, jit)
    got = _run(True, x, gamma, beta, axis, fix_gamma, jnp.float32, jit)
    names = ["out", "mean", "var", "dx", "dgamma", "dbeta"]
    for name, r, g in zip(names, ref, got):
        np.testing.assert_allclose(g, r, rtol=2e-4, atol=2e-4,
                                   err_msg=name)
    if fix_gamma:
        assert np.all(got[4] == 0), "fix_gamma must zero dgamma"


def test_fused_matches_autodiff_bf16():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 6, 4, 4)).astype(jnp.bfloat16)
    gamma = jnp.asarray(rng.uniform(0.5, 1.5, 6)).astype(jnp.bfloat16)
    beta = jnp.asarray(rng.randn(6)).astype(jnp.bfloat16)
    ref = _run(False, x, gamma, beta, 1, False, jnp.bfloat16, True)
    got = _run(True, x, gamma, beta, 1, False, jnp.bfloat16, True)
    for name, r, g in zip(["out", "mean", "var", "dx", "dgamma", "dbeta"],
                          ref, got):
        # both paths accumulate stats/grads in fp32; bf16 rounding of the
        # inputs/outputs is the only noise source
        np.testing.assert_allclose(g, r, rtol=2e-2, atol=2e-2, err_msg=name)


def test_fused_gluon_layer_end_to_end():
    """Flag on/off must give identical training-step grads through a
    conv+BN+relu Gluon block (the integration the bench exercises)."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import functional_call, extract_params

    def build():
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
                nn.Activation("relu"), nn.Dense(5))
        net.initialize()
        x = mx.nd.ones((2, 3, 8, 8))
        with mx.autograd.pause():
            net(x)
        return net

    rng = np.random.RandomState(2)
    xb = jnp.asarray(rng.randn(4, 3, 8, 8).astype(np.float32))

    def grads(on):
        _flag(on)
        net = build()
        params = extract_params(net)

        def loss(params, x):
            out, _aux = functional_call(net, params, x, training=True)
            return jnp.sum(out ** 2)

        g = jax.jit(jax.grad(loss))(params, xb)
        return {k: np.asarray(v, np.float64) for k, v in g.items()}

    ref, got = grads(False), grads(True)
    # global name counters differ between the two builds (dense0 vs
    # dense1); param ORDER is identical, so compare positionally
    assert len(ref) == len(got)
    for (rk, rv), (gk, gv) in zip(sorted(ref.items()), sorted(got.items())):
        np.testing.assert_allclose(gv, rv, rtol=1e-4, atol=1e-5,
                                   err_msg=f"{rk} vs {gk}")


def test_fused_second_order():
    """grad-of-grad through the fused path stays differentiable (the
    custom bwd is itself jax-traceable) and matches autodiff."""
    x = jnp.asarray(np.random.RandomState(3).randn(3, 4).astype(np.float32))
    gamma = jnp.ones(4)
    beta = jnp.zeros(4)
    mz, mv = jnp.zeros(4), jnp.ones(4)

    def scalar(x):
        out = _BN(x, gamma, beta, mz, mv, eps=1e-3, fix_gamma=False,
                  axis=1, _training=True)
        return jnp.sum(jnp.tanh(out))

    def gg(on):
        _flag(on)
        return np.asarray(jax.grad(lambda x: jnp.sum(
            jax.grad(scalar)(x) ** 2))(x), np.float64)

    np.testing.assert_allclose(gg(True), gg(False), rtol=5e-4, atol=5e-5)
