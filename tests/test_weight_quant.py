"""Quantized serving weights (ISSUE 20): the tolerance contract.

int8/fp8 weight-only serving is NOT bit-exact against fp32 — so, like
the int8 KV contract (test_kv_quant.py), this suite pins an EXPLICIT
contract instead of letting drift hide:

- mechanics are exact where they can be: ``quantized_matmul``'s jnp
  reference equals the dequantize-then-matmul oracle to float
  tolerance and the Pallas kernel (interpret mode off-TPU) tracks the
  reference within ``KERNEL_TOL``; calibration is deterministic,
  per-output-channel, and round-trip error is bounded by half a scale
  step;
- per-dispatch model tolerance: ``decode_flat`` logits on int8
  weights stay within ``LOGIT_TOL`` (fp8: ``FP8_LOGIT_TOL``) of the
  fp32 run on the same inputs, with identical argmax at the pinned
  seed;
- end-to-end: the int8 weight engine serves mixed traffic — greedy,
  sampled, speculative, LoRA, prefix cache — with ZERO steady-state
  recompiles, and its greedy streams agree top-1, token for token,
  with the fp32 eager oracle for the pinned seed/config;
- an int8 DRAFT under a fp32 target is bit-exact: the speculative
  accept rule guarantees greedy output equals target-only greedy
  regardless of draft quality;
- prefix-cache hit == miss on the quantized engine (weight
  quantization is static — the written KV bytes are a pure function
  of the tokens);
- artifacts round-trip: ``deploy.export_decoder``/``load_decoder``
  carry dtype + per-channel scales, and ``FleetRouter.publish`` can
  hot-swap an fp32 model to its quantized twin with zero compiles
  when the quantized program set is pre-warmed on the same model
  object.

Budget note (tier-1): every fast engine-level test shares the ONE
module-scoped warmed int8 engine (``qeng``); the tp=2 mesh, fp8
engine, fleet hot-swap and the dtype x spec x LoRA matrix are
``slow``-marked with the fast tests as their per-invariant gate.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import jax.numpy as jnp  # noqa: E402

from mxnet_tpu import deploy, serving  # noqa: E402
from mxnet_tpu.ops import registry  # noqa: E402
from mxnet_tpu.ops.quantization import (  # noqa: E402
    quantized_matmul, quantized_matmul_reference)
from mxnet_tpu.serving.llm import (  # noqa: E402
    TinyDecoder, LLMEngine, LLMServer, Sequence,
    greedy_decode_reference, QuantizedWeights, quantize_weights,
    fp8_supported, resolve_weight_dtype)
from mxnet_tpu.serving.llm.metrics import LLMStats  # noqa: E402
from mxnet_tpu.serving.llm.quant import (  # noqa: E402
    FP8_NAME, calibration_error, dequantize_leaf, quantize_leaf)
from mxnet_tpu.serving.llm.sampling import SamplingParams  # noqa: E402
from mxnet_tpu.serving.adapters.bank import AdapterBank  # noqa: E402

VOCAB, BS, CTX = 23, 8, 64

# per-dispatch contract: max |logits_q - logits_fp32| for one
# decode_flat dispatch of this reference config (int8 measured ~0.027,
# fp8-e4m3 ~0.13; both bounds leave ~2x headroom without letting real
# drift hide)
LOGIT_TOL = 0.05
FP8_LOGIT_TOL = 0.25
# kernel-vs-reference: same dequant, only blocked float accumulation
KERNEL_TOL = 2e-6


@pytest.fixture(scope="module")
def model():
    # 4 heads so the same model shards at tp=2 in the slow sweep
    return TinyDecoder(vocab_size=VOCAB, d_model=16, num_layers=2,
                       num_heads=4, d_ff=32, max_context=CTX)


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(0)


@pytest.fixture(scope="module")
def draft():
    return TinyDecoder(vocab_size=VOCAB, d_model=16, num_layers=1,
                       num_heads=4, d_ff=32, max_context=CTX)


@pytest.fixture(scope="module")
def dparams(draft):
    return draft.init_params(1)


@pytest.fixture(scope="module")
def bank():
    bank = AdapterBank(num_layers=2, d_model=16, max_adapters=4,
                       page_rank=2, max_pages_per_adapter=2)
    rs = np.random.RandomState(3)
    bank.publish("tiny",
                 (rs.randn(2, 4, 16, 2) * 0.1).astype(np.float32),
                 (rs.randn(2, 4, 2, 16) * 0.1).astype(np.float32))
    return bank


@pytest.fixture(scope="module")
def qweights(params):
    return quantize_weights(params, dtype="int8")


@pytest.fixture(scope="module")
def qeng(model, params, draft, dparams, bank):
    """The ONE warmed int8 engine every fast engine test shares:
    int8 target weights, int8 draft, adapter bank, prefix cache —
    the full unified step on quantized weights. Tests drain it
    completely before returning."""
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    num_blocks=41, max_context=CTX, prefill_chunk=8,
                    draft_model=draft, draft_params=dparams, spec_k=2,
                    adapter_bank=bank, prefix_cache=True,
                    weight_dtype="int8", draft_weight_dtype="int8",
                    stats=LLMStats(server="wq_shared"))
    eng.warmup()
    return eng


def _serve(engine, jobs, max_new=8):
    """Run jobs (prompt, sampling, adapter) to completion; returns
    generated streams in submit order. Asserts nothing died."""
    seqs = []
    for prompt, samp, ad in jobs:
        s = Sequence(list(prompt), max_new, sampling=samp, adapter=ad)
        engine.add(s)
        seqs.append(s)
    for _ in range(600):
        if not engine.has_work():
            break
        engine.step()
        engine.pop_finished()
    assert not engine.has_work(), "engine did not drain"
    dead = engine.pop_dead()
    assert not dead, f"sequences died: {dead}"
    return [list(s.generated) for s in seqs]


# ----------------------------------------------------- calibration --
def test_absmax_scale_and_halfstep_roundtrip():
    """Per-output-channel absmax: scale is exactly colmax/127 and the
    dequantized round trip errs by at most half a scale step."""
    rng = np.random.RandomState(0)
    w = rng.randn(16, 24).astype(np.float32)
    q, s = quantize_leaf(w, dtype="int8", method="absmax")
    assert q.dtype == np.int8 and s.shape == (24,)
    assert np.allclose(s, np.abs(w).max(axis=0) / 127.0)
    err = np.abs(dequantize_leaf(q, s) - w)
    assert (err <= s[None, :] * 0.5 + 1e-7).all()


def test_percentile_beats_absmax_on_outlier_channels():
    """The calibration choice is observable: a huge outlier row
    inflates the absmax scale — and the rounding step — for EVERY
    row, while percentile clips it and keeps the bulk fine-grained.
    Percentile wins exactly when the calibration batch shows the
    outlier channel is rarely activated (which is the point of
    calibrating against a batch instead of the weights alone)."""
    rng = np.random.RandomState(1)
    w = rng.randn(64, 8).astype(np.float32)
    w[7, :] = 40.0                          # one outlier row, all cols
    xs = rng.randn(16, 64).astype(np.float32)
    xs[:, 7] *= 0.01                        # ...that inputs rarely hit
    qa, sa = quantize_leaf(w, method="absmax")
    qp, sp = quantize_leaf(w, method="percentile", percentile=95.0)
    ea = calibration_error(w, qa, sa, xs)
    ep = calibration_error(w, qp, sp, xs)
    assert ep < ea, f"percentile {ep} should beat absmax {ea}"
    assert (sp < sa).all()                  # the outlier is clipped
    # per-element: the bulk rows round finer under percentile
    bulk = np.ones(64, bool)
    bulk[7] = False
    ba = np.abs(dequantize_leaf(qa, sa) - w)[bulk].mean()
    bp = np.abs(dequantize_leaf(qp, sp) - w)[bulk].mean()
    assert bp < ba


def test_per_channel_beats_per_tensor():
    """Per-channel scales price each output column by its own range;
    a per-tensor scale wastes resolution on quiet columns."""
    rng = np.random.RandomState(2)
    w = (rng.randn(32, 6) * np.array([0.01, 0.1, 1, 3, 10, 30],
                                     np.float32)).astype(np.float32)
    qc, sc = quantize_leaf(w, per_channel=True)
    qt, st = quantize_leaf(w, per_channel=False)
    assert sc.shape == (6,) and st.shape == (1,)
    ec = np.abs(dequantize_leaf(qc, sc) - w).mean()
    et = np.abs(dequantize_leaf(qt, st) - w).mean()
    assert ec < et


def test_quantize_weights_deterministic_and_selective(params,
                                                      qweights):
    """quantize_weights is bit-deterministic, quantizes exactly the
    2D float32 leaves (embed/pos/head + per-layer projections) and
    leaves biases/layernorms untouched."""
    again = quantize_weights(params, dtype="int8")
    f1 = deploy.flatten_params(qweights.params)
    f2 = deploy.flatten_params(again.params)
    assert set(f1) == set(f2)
    for k in f1:
        assert np.array_equal(np.asarray(f1[k]), np.asarray(f2[k])), k
    for k in qweights.scales:
        assert np.array_equal(np.asarray(qweights.scales[k]),
                              np.asarray(again.scales[k])), k
    flat = deploy.flatten_params(params)
    for k, v in f1.items():
        if k in qweights.scales:
            assert v.dtype == np.int8 and flat[k].ndim == 2, k
        else:
            assert v.dtype == flat[k].dtype, k
    assert {"embed", "pos", "head"} <= set(qweights.scales)
    assert "layers.0.wq" in qweights.scales
    assert "layers.0.b1" not in qweights.scales
    assert "layers.0.ln1_g" not in qweights.scales
    # the "auto" mode records a per-leaf method choice
    auto = quantize_weights(params, dtype="int8", method="auto",
                            calib_seed=0)
    assert auto.methods is not None
    assert set(auto.methods) == set(auto.scales)
    assert set(auto.methods.values()) <= {"absmax", "percentile"}


def test_resolve_weight_dtype_names():
    for name in ("", "float32", "fp32", "f32", "none", None):
        assert resolve_weight_dtype(name) == (None, False)
    assert resolve_weight_dtype("int8") == ("int8", False)
    got, fell = resolve_weight_dtype("fp8")
    if fp8_supported():
        assert got == FP8_NAME and not fell
    else:
        assert got == "int8" and fell
    with pytest.raises(ValueError, match="weight dtype"):
        resolve_weight_dtype("int4")


@pytest.mark.skipif(not fp8_supported(), reason="no fp8-e4m3 dtype")
def test_fp8_quantize_leaf_saturates_not_nan():
    """The float32->e4m3 cast NaNs out-of-range values instead of
    saturating; quantize_leaf must clip into the finite +-448 range
    first — no NaNs, ever, even for extreme weights."""
    rng = np.random.RandomState(3)
    w = (rng.randn(8, 4) * 1e4).astype(np.float32)
    q, s = quantize_leaf(w, dtype="fp8")
    assert q.dtype == np.dtype(FP8_NAME)
    deq = dequantize_leaf(q, s)
    assert np.isfinite(deq).all()
    assert np.abs(deq - w).max() / np.abs(w).max() < 0.1


# -------------------------------------------------------- the op --
def test_quantized_matmul_reference_matches_dequant_oracle():
    rng = np.random.RandomState(4)
    x = rng.randn(8, 16).astype(np.float32)
    w = rng.randn(16, 24).astype(np.float32)
    q, s = quantize_leaf(w)
    ref = quantized_matmul_reference(jnp.asarray(x), jnp.asarray(q),
                                     jnp.asarray(s))
    oracle = x @ dequantize_leaf(q, s)
    assert float(jnp.max(jnp.abs(ref - oracle))) < 1e-5


def test_quantized_matmul_pallas_matches_reference():
    """The Pallas weight-dequant matmul kernel (interpret mode
    off-TPU) tracks the jnp reference within float-accumulation
    tolerance, including ragged tile edges."""
    rng = np.random.RandomState(5)
    x = rng.randn(13, 16).astype(np.float32)     # ragged T vs block
    w = rng.randn(16, 19).astype(np.float32)     # ragged N vs block
    q, s = quantize_leaf(w)
    ref = quantized_matmul_reference(jnp.asarray(x), jnp.asarray(q),
                                     jnp.asarray(s))
    pal = quantized_matmul(x, q, s, use_pallas=True, interpret=True,
                           block_t=8, block_n=8)
    assert float(jnp.max(jnp.abs(pal - ref))) < KERNEL_TOL


def test_quantized_matmul_registered():
    op = registry.get("_contrib_quantized_matmul")
    assert registry.get("quantized_matmul") is op
    assert not op.differentiable
    rng = np.random.RandomState(6)
    x = rng.randn(4, 8).astype(np.float32)
    q, s = quantize_leaf(rng.randn(8, 8).astype(np.float32))
    out = op.impl(jnp.asarray(x), jnp.asarray(q), jnp.asarray(s))
    assert out.shape == (4, 8)


# -------------------------------------------- per-dispatch contract --
def _flat_inputs(model, seed=7, T=16):
    rng = np.random.RandomState(seed)
    L, H, D = model.num_layers, model.num_heads, model.head_dim
    N = 9
    kp = jnp.zeros((L, N, BS, H, D), jnp.float32)
    vp = jnp.zeros((L, N, BS, H, D), jnp.float32)
    toks = rng.randint(0, VOCAB, T).astype(np.int32)
    pos = np.arange(T, dtype=np.int32)
    sid = np.zeros(T, np.int32)
    valid = np.ones(T, np.int32)
    bt = np.zeros((4, 8), np.int32)
    bt[0, :2] = [3, 5]
    return toks, pos, sid, valid, kp, vp, bt


def test_decode_flat_int8_logit_tolerance(model, params, qweights):
    """The per-dispatch contract: one mixed flat dispatch, fp32 vs
    int8 weights, same tokens — logits within LOGIT_TOL and identical
    argmax at every position."""
    toks, pos, sid, valid, kp, vp, bt = _flat_inputs(model)
    lf = model.decode_flat(params, toks, pos, sid, valid, kp, vp, bt)[0]
    lq = model.decode_flat(qweights.params, toks, pos, sid, valid,
                           kp, vp, bt, w_scales=qweights.scales)[0]
    diff = float(jnp.max(jnp.abs(lf - lq)))
    assert diff < LOGIT_TOL, f"int8 logit drift {diff} > {LOGIT_TOL}"
    assert np.array_equal(np.asarray(jnp.argmax(lf, -1)),
                          np.asarray(jnp.argmax(lq, -1)))


@pytest.mark.skipif(not fp8_supported(), reason="no fp8-e4m3 dtype")
def test_decode_flat_fp8_logit_tolerance(model, params):
    qw = quantize_weights(params, dtype="fp8")
    assert qw.dtype == FP8_NAME
    toks, pos, sid, valid, kp, vp, bt = _flat_inputs(model)
    lf = model.decode_flat(params, toks, pos, sid, valid, kp, vp, bt)[0]
    lq = model.decode_flat(qw.params, toks, pos, sid, valid,
                           kp, vp, bt, w_scales=qw.scales)[0]
    diff = float(jnp.max(jnp.abs(lf - lq)))
    assert diff < FP8_LOGIT_TOL, \
        f"fp8 logit drift {diff} > {FP8_LOGIT_TOL}"
    # NO argmax pin for fp8: near-tie positions legitimately flip
    # within FP8_LOGIT_TOL — token parity is an int8-only contract


# ------------------------------------------- the int8 engine (fast) --
def test_int8_engine_mixed_traffic_zero_recompiles(qeng, model,
                                                   params, bank):
    """Acceptance gate: mixed greedy + sampled + LoRA + speculative
    traffic on the warmed int8 engine (int8 draft riding along) runs
    with ZERO steady-state recompiles — and greedy rows agree top-1,
    token for token, with the fp32 eager oracle (the speculative
    accept rule makes them exactly the int8-target-only streams)."""
    jobs = [
        (list(range(1, 15)), None, None),   # chunked prefill
        ([4, 5, 6], SamplingParams(temperature=0.8, top_k=5, seed=7),
         None),
        ([13, 2, 1], None, "tiny"),
        ([3, 3, 3, 3], SamplingParams(temperature=1.1, top_p=0.9,
                                      seed=11), "tiny"),
    ]
    with serving.CompileCounter() as cc:
        res = _serve(qeng, jobs)
    assert cc.count == 0, f"{cc.count} steady-state recompiles"
    assert res[0] == greedy_decode_reference(model, params,
                                             jobs[0][0], 8)
    assert res[2] == greedy_decode_reference(
        model, params, jobs[2][0], 8, lora=bank.adapter_arrays("tiny"))
    assert all(len(r) == 8 for r in res)
    qeng.cache.check([])


def test_int8_prefix_cache_hit_equals_miss(qeng):
    """Weight quantization is static, so a prefix-cache hit replays
    EXACTLY the stream a cache-miss recompute produces."""
    prompt = [19] * (2 * BS) + [3]
    first, = _serve(qeng, [(prompt, None, None)])
    hits0 = qeng.prefix_hits
    second_seq = Sequence(list(prompt), 8)
    qeng.add(second_seq)
    while qeng.has_work():
        qeng.step()
        qeng.pop_finished()
    assert qeng.prefix_hits > hits0
    assert second_seq.cache_hit_tokens >= 2 * BS
    assert list(second_seq.generated) == first
    qeng.cache.check([])


def test_int8_second_engine_shares_programs(qeng, model, params,
                                            draft, dparams, bank):
    """Satellite (tier-1 budget contract): a second int8 engine on the
    SAME model objects warms from the cached program set — zero
    compiles."""
    with serving.CompileCounter() as cc:
        eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                        num_blocks=41, max_context=CTX,
                        prefill_chunk=8, draft_model=draft,
                        draft_params=dparams, spec_k=2,
                        adapter_bank=bank, prefix_cache=True,
                        weight_dtype="int8", draft_weight_dtype="int8")
        eng.warmup()
        out, = _serve(eng, [([2, 4, 6], None, None)], max_new=4)
    assert cc.count == 0, f"{cc.count} compiles on the shared set"
    assert out == greedy_decode_reference(model, params, [2, 4, 6], 4)


def test_int8_engine_surfaces_and_bytes_ratio(qeng, params):
    """The capacity headline in miniature: the int8 weight tree holds
    >= 1.9x the params per byte of fp32, and dtype/bytes/params are
    surfaced on debug_status and the mxtpu_llm_weight_* series."""
    f32_bytes = sum(np.asarray(v).size * 4 for v in
                    deploy.flatten_params(params).values())
    ratio = f32_bytes / qeng.weight_bytes
    assert ratio >= 1.9, f"int8 bytes ratio {ratio:.2f} < 1.9"
    assert qeng.weight_dtype == "int8"
    assert qeng.draft_weight_dtype == "int8"
    assert qeng.weight_calib == "absmax"
    ds = qeng.debug_status()["weights"]
    assert ds["dtype"] == "int8" and ds["bytes"] == qeng.weight_bytes
    assert ds["params"] == qeng.weight_params > 0
    assert ds["params_per_chip"] == qeng.weight_params
    snap = qeng._stats.snapshot()
    assert snap["weight_dtype"] == {"int8": 1}
    assert snap["weight_bytes"] == qeng.weight_bytes
    assert snap["weight_params_per_chip"] == qeng.weight_params


def test_weight_dtype_env_knob(model, params, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_LLM_WEIGHT_DTYPE", "int8")
    monkeypatch.setenv("MXNET_TPU_LLM_WEIGHT_CALIB", "percentile")
    monkeypatch.setenv("MXNET_TPU_LLM_WEIGHT_PERCENTILE", "99.0")
    eng = LLMEngine(model, params, max_seqs=2, block_size=BS,
                    num_blocks=17, max_context=32, prefill_chunk=8)
    assert eng.weight_quantized and eng.weight_dtype == "int8"
    assert eng.weight_calib == "percentile"
    monkeypatch.setenv("MXNET_TPU_LLM_WEIGHT_DTYPE", "float32")
    eng2 = LLMEngine(model, params, max_seqs=2, block_size=BS,
                     num_blocks=17, max_context=32, prefill_chunk=8)
    assert not eng2.weight_quantized
    assert eng2.weight_dtype == "float32"


def test_fp8_fallback_guard_counts(model, params, monkeypatch):
    """With the fp8 dtype unavailable, fp8 weight AND KV requests
    serve as int8 — counted on mxtpu_llm_quant_fallback_total and
    warned, never silent."""
    from mxnet_tpu.serving.llm import engine as engine_mod
    from mxnet_tpu.serving.llm import quant as quant_mod
    # the KV guard reads the engine-module binding, the weight guard
    # resolves through quant.resolve_weight_dtype — patch both
    monkeypatch.setattr(engine_mod, "fp8_supported", lambda: False)
    monkeypatch.setattr(quant_mod, "fp8_supported", lambda: False)
    stats = LLMStats(server="wq_fallback")
    with pytest.warns(RuntimeWarning, match="int8"):
        eng = LLMEngine(model, params, max_seqs=2, block_size=BS,
                        num_blocks=17, max_context=32,
                        prefill_chunk=8, weight_dtype="fp8",
                        kv_dtype="fp8", stats=stats)
    assert eng.weight_dtype == "int8"
    assert eng.cache.dtype.name == "int8"
    assert eng.kv_dtype_fallbacks == 1
    assert stats.snapshot()["quant_fallbacks"] >= 2


# ------------------------------------------------------- artifacts --
def test_decoder_artifact_roundtrip_quantized(model, params, qweights):
    """export_decoder/load_decoder carry dtype + scales bit-exactly
    (int8 AND fp8 — npz reads fp8 back as raw bytes, the loader
    view-casts from the header dtype); fp32 artifacts are unchanged."""
    art = deploy.export_decoder(model, qweights)
    m2, p2 = deploy.load_decoder(art)
    assert isinstance(p2, QuantizedWeights)
    assert p2.dtype == "int8" and p2.method == "absmax"
    f1 = deploy.flatten_params(qweights.params)
    f2 = deploy.flatten_params(p2.params)
    for k in f1:
        assert np.array_equal(np.asarray(f1[k]), np.asarray(f2[k])), k
    for k in qweights.scales:
        assert np.array_equal(np.asarray(qweights.scales[k]),
                              np.asarray(p2.scales[k])), k
    if fp8_supported():
        qf = quantize_weights(params, dtype="fp8")
        _, pf = deploy.load_decoder(deploy.export_decoder(model, qf))
        assert pf.dtype == FP8_NAME
        a = deploy.flatten_params(qf.params)["head"]
        b = deploy.flatten_params(pf.params)["head"]
        assert b.dtype == np.dtype(FP8_NAME)
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    _, p3 = deploy.load_decoder(deploy.export_decoder(model, params))
    assert not isinstance(p3, QuantizedWeights)


def test_params_from_arrays_detects_quantized(params, qweights):
    """The fleet-builder helper: a flat checkpoint dict with scale.*
    entries rebuilds a QuantizedWeights; without them, a plain tree."""
    flat = deploy.flatten_params(qweights.params)
    flat.update({"scale." + k: np.asarray(v)
                 for k, v in qweights.scales.items()})
    got = deploy.params_from_arrays(flat)
    assert isinstance(got, QuantizedWeights) and got.dtype == "int8"
    assert set(got.scales) == set(qweights.scales)
    plain = deploy.params_from_arrays(deploy.flatten_params(params))
    assert not isinstance(plain, QuantizedWeights)
    assert "embed" in plain


# ------------------------------------------------ slow: the matrix --
@pytest.mark.slow   # compiles its own fp32-target spec program set
def test_int8_draft_spec_bitexact(model, params, draft, dparams):
    """An int8 DRAFT under a fp32 target is bit-exact end to end: the
    speculative accept rule guarantees greedy output == target-only
    greedy regardless of draft quality — quantizing the draft can only
    move the accept RATE, never the tokens."""
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    num_blocks=41, max_context=CTX, prefill_chunk=8,
                    draft_model=draft, draft_params=dparams, spec_k=2,
                    draft_weight_dtype="int8")
    eng.warmup()
    assert eng.draft_weight_quantized
    assert not eng.weight_quantized
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8, 9, 10, 11, 12, 13],
               [14, 15], list(range(1, 15))]
    outs = _serve(eng, [(p, None, None) for p in prompts])
    for p, out in zip(prompts, outs):
        assert out == greedy_decode_reference(model, params, p, 8), \
            f"int8 draft changed greedy tokens on {p}"
    eng.cache.check([])


@pytest.mark.slow   # compiles the sharded quantized program set
def test_tp2_int8_parity_and_zero_recompiles(model, params, qeng):
    """The tolerance contract holds under a tp=2 mesh: per-channel
    scales shard with their column/row-split weights, greedy streams
    match the UNSHARDED int8 engine token for token (host-side
    quantization with global scales — sharding only re-orders the
    psum), and mixed traffic stays zero-recompile."""
    et = LLMEngine(model, params, mesh="tp=2", max_seqs=4,
                   block_size=BS, num_blocks=41, max_context=CTX,
                   prefill_chunk=8, weight_dtype="int8",
                   prefix_cache=True)
    et.warmup()
    e1 = LLMEngine(model, params, max_seqs=4, block_size=BS,
                   num_blocks=41, max_context=CTX, prefill_chunk=8,
                   weight_dtype="int8", prefix_cache=True)
    e1.warmup()
    jobs = [([1, 2, 3], None, None),
            (list(range(1, 15)), None, None),
            ([14, 15], SamplingParams(temperature=0.9, top_k=5,
                                      seed=7), None)]
    base = _serve(e1, jobs)
    with serving.CompileCounter() as cc:
        sharded = _serve(et, jobs)
    assert cc.count == 0, f"{cc.count} recompiles on the tp=2 path"
    assert sharded == base
    assert base[0] == greedy_decode_reference(model, params,
                                              jobs[0][0], 8)
    assert et.debug_status()["weights"]["params_per_chip"] \
        == et.weight_params // 2
    et.cache.check([])
    e1.cache.check([])


@pytest.mark.slow   # compiles the fp8 program set
@pytest.mark.skipif(not fp8_supported(), reason="no fp8-e4m3 dtype")
def test_fp8_weight_engine_serves(model, params):
    """fp8-e4m3 weights: the engine serves greedy traffic with zero
    steady-state recompiles and >= 1.9x params-per-byte vs fp32; token
    parity is NOT part of the fp8 contract (FP8_LOGIT_TOL pins the
    per-dispatch drift instead)."""
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    num_blocks=41, max_context=CTX, prefill_chunk=8,
                    weight_dtype="fp8")
    eng.warmup()
    assert eng.weight_dtype == FP8_NAME
    f32_bytes = sum(np.asarray(v).size * 4 for v in
                    deploy.flatten_params(params).values())
    assert f32_bytes / eng.weight_bytes >= 1.9
    with serving.CompileCounter() as cc:
        outs = _serve(eng, [([1, 2, 3], None, None),
                            ([4, 5, 6, 7], None, None)], max_new=6)
    assert cc.count == 0
    assert all(len(o) == 6 for o in outs)
    eng.cache.check([])


@pytest.mark.slow   # builds two servers + a router
def test_fleet_hotswap_fp32_to_int8_zero_compiles(model, params,
                                                  qweights):
    """Satellite: FleetRouter.publish hot-swaps an fp32 model to its
    quantized twin with ZERO compiles once the quantized program set
    is warm on the shared model object — weights and scales enter the
    warmed step as traced arguments."""
    kw = dict(max_seqs=4, block_size=BS, num_blocks=41,
              max_context=CTX, prefill_chunk=8)

    def build(arrays, _n=[0]):
        _n[0] += 1
        return LLMServer(model, deploy.params_from_arrays(arrays),
                         name=f"wq_fleet_v{_n[0]}", **kw)

    # pre-warm the quantized program set off the serving path (the
    # one-time cost a real fleet pays at first quantized rollout) —
    # and take the int8 twin's own greedy stream as the post-swap
    # reference (this prompt sits inside the tolerance contract, not
    # inside fp32 token parity)
    pre = LLMEngine(model, qweights, **kw)
    pre.warmup()
    ref_int8 = _serve(pre, [([5, 6, 7], None, None)], max_new=6)[0]
    srv = build(deploy.flatten_params(params))
    srv.warmup()
    srv.start()
    router = serving.FleetRouter(name="wq_fleet")
    router.add_model("m", srv, version=1, builder=build)
    ref = greedy_decode_reference(model, params, [5, 6, 7], 6)
    assert router.generate("m", [5, 6, 7], 6, timeout=30).tokens == ref
    arrays = deploy.flatten_params(qweights.params)
    arrays.update({"scale." + k: np.asarray(v)
                   for k, v in qweights.scales.items()})
    with serving.CompileCounter() as cc:
        assert router.publish("m", 2, arrays=arrays) == 2
    assert cc.count == 0, \
        f"{cc.count} compiles publishing the quantized twin"
    eng = router.server("m").engine
    assert eng.weight_dtype == "int8" and eng.weight_quantized
    assert router.generate("m", [5, 6, 7], 6,
                           timeout=30).tokens == ref_int8
    router.shutdown()


@pytest.mark.slow   # the full dtype x spec x LoRA parity matrix
def test_dtype_spec_lora_matrix(model, params, draft, dparams, bank):
    """Every cell of the dtype x spec x LoRA matrix serves mixed
    traffic with zero steady-state recompiles and clean block
    accounting; int8 greedy cells agree with the fp32 oracle."""
    dtypes = ["int8"] + (["fp8"] if fp8_supported() else [])
    jobs = [([1, 2, 3], None, None),
            ([13, 2, 1], None, "tiny"),
            ([4, 5, 6], SamplingParams(temperature=0.8, top_k=5,
                                       seed=7), None)]
    for dtype in dtypes:
        for spec in (False, True):
            kw = dict(max_seqs=4, block_size=BS, num_blocks=41,
                      max_context=CTX, prefill_chunk=8,
                      adapter_bank=bank, prefix_cache=True,
                      weight_dtype=dtype)
            if spec:
                kw.update(draft_model=draft, draft_params=dparams,
                          spec_k=2, draft_weight_dtype=dtype)
            eng = LLMEngine(model, params, **kw)
            eng.warmup()
            with serving.CompileCounter() as cc:
                outs = _serve(eng, jobs)
            assert cc.count == 0, \
                f"recompiles at dtype={dtype} spec={spec}"
            if dtype == "int8":
                assert outs[0] == greedy_decode_reference(
                    model, params, jobs[0][0], 8)
                assert outs[1] == greedy_decode_reference(
                    model, params, jobs[1][0], 8,
                    lora=bank.adapter_arrays("tiny"))
            eng.cache.check([])
