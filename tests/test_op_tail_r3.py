"""Round-3 op tail: transformer interleaved matmuls, image ops, npx/npi
internals, packed-triangular linalg, scatter family, optimizer multi
variants, quantized op family, SyncBatchNorm, Correlation.

Each test pins a numpy/jax oracle for the reference semantics cited in
the op docstrings (src/operator/contrib/transformer.cc, image/,
optimizer_op.cc, quantization/, correlation.cc, ...).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops.registry import _REGISTRY


def _op(name, *args, **kw):
    import jax.numpy as jnp
    arrays = [jnp.asarray(a) for a in args]
    op = _REGISTRY[name]
    if op.variadic:
        return op.impl(arrays, **kw)
    return op.impl(*arrays, **kw)


def test_interleaved_matmul_selfatt_roundtrip():
    rng = np.random.RandomState(0)
    T, B, H, D = 5, 2, 3, 4
    qkv = rng.randn(T, B, 3 * H * D).astype(np.float32)
    att = _op("_contrib_interleaved_matmul_selfatt_qk", qkv, heads=H)
    assert att.shape == (B * H, T, T)
    # oracle straight from the reference docstring
    tmp = qkv.reshape(T, B, H, 3, D)
    q = tmp[:, :, :, 0, :].transpose(1, 2, 0, 3).reshape(B * H, T, D)
    k = tmp[:, :, :, 1, :].transpose(1, 2, 0, 3).reshape(B * H, T, D)
    want = (q / np.sqrt(D)) @ k.transpose(0, 2, 1)
    np.testing.assert_allclose(np.asarray(att), want, rtol=1e-5,
                               atol=1e-5)
    out = _op("_contrib_interleaved_matmul_selfatt_valatt", qkv,
              np.asarray(att), heads=H)
    v = tmp[:, :, :, 2, :].transpose(1, 2, 0, 3).reshape(B * H, T, D)
    want_out = (np.asarray(att) @ v).reshape(B, H, T, D)\
        .transpose(2, 0, 1, 3).reshape(T, B, H * D)
    np.testing.assert_allclose(np.asarray(out), want_out, rtol=1e-5,
                               atol=1e-5)


def test_interleaved_matmul_encdec():
    rng = np.random.RandomState(1)
    Tq, Tk, B, H, D = 4, 6, 2, 2, 3
    q = rng.randn(Tq, B, H * D).astype(np.float32)
    kv = rng.randn(Tk, B, 2 * H * D).astype(np.float32)
    att = _op("_contrib_interleaved_matmul_encdec_qk", q, kv, heads=H)
    assert att.shape == (B * H, Tq, Tk)
    out = _op("_contrib_interleaved_matmul_encdec_valatt", kv,
              np.asarray(att), heads=H)
    assert out.shape == (Tq, B, H * D)
    assert np.isfinite(np.asarray(out)).all()


def test_image_ops():
    rng = np.random.RandomState(2)
    img = (rng.rand(8, 10, 3) * 255).astype(np.uint8)
    crop = _op("_image_crop", img, x=2, y=1, width=5, height=4)
    np.testing.assert_array_equal(np.asarray(crop), img[1:5, 2:7])
    t = _op("_image_to_tensor", img)
    assert t.shape == (3, 8, 10)
    np.testing.assert_allclose(np.asarray(t)[0], img[:, :, 0] / 255.0,
                               rtol=1e-6)
    norm = _op("_image_normalize", np.asarray(t),
               mean=(0.5, 0.5, 0.5), std=(0.25, 0.25, 0.25))
    np.testing.assert_allclose(np.asarray(norm),
                               (np.asarray(t) - 0.5) / 0.25, rtol=1e-5)
    r = _op("_image_resize", img, size=(5, 4))
    assert r.shape == (4, 5, 3)


def test_npx_reshape_codes():
    x = np.zeros((2, 3, 4, 5), np.float32)
    assert _op("_npx_reshape", x, newshape=(0, -1)).shape == (2, 60)
    assert _op("_npx_reshape", x, newshape=(0, -2)).shape == \
        (2, 3, 4, 5)
    assert _op("_npx_reshape", x, newshape=(0, 0, -1)).shape == \
        (2, 3, 20)


def test_scatter_family():
    data = np.array([1.0, 0.0, -2.0, 0.0], np.float32)
    out = _op("_scatter_minus_scalar", data, scalar=1.0)
    np.testing.assert_allclose(np.asarray(out), [0, 0, -3, 0])
    lhs = np.zeros((3, 3), np.float32)
    idx = np.array([[0, 2], [1, 0]])
    out = _op("_scatter_set_nd", lhs, np.array([5.0, 7.0], np.float32),
              idx)
    assert np.asarray(out)[0, 1] == 5 and np.asarray(out)[2, 0] == 7


def test_preloaded_multi_sgd_matches_single():
    rng = np.random.RandomState(3)
    w1, g1 = rng.randn(4), rng.randn(4)
    w2, g2 = rng.randn(3), rng.randn(3)
    lrs = np.array([0.1, 0.2], np.float32)
    wds = np.array([0.0, 0.01], np.float32)
    outs = _op("preloaded_multi_sgd_update",
               w1.astype(np.float32), g1.astype(np.float32),
               w2.astype(np.float32), g2.astype(np.float32), lrs, wds)
    want1 = w1 - 0.1 * g1
    want2 = w2 - 0.2 * (g2 + 0.01 * w2)
    np.testing.assert_allclose(np.asarray(outs[0]), want1, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]), want2, rtol=1e-5)


def test_multi_adamw_update():
    rng = np.random.RandomState(4)
    w = rng.randn(5).astype(np.float32)
    g = rng.randn(5).astype(np.float32)
    m = np.zeros(5, np.float32)
    v = np.zeros(5, np.float32)
    rescale = np.asarray(1.0, np.float32)
    outs = _op("_multi_adamw_update", w, g, m, v, rescale,
               lrs=(0.01,), wds=(0.0,), etas=(1.0,))
    m_want = 0.1 * g
    v_want = 0.001 * g * g
    w_want = w - 1.0 * (0.01 * m_want / (np.sqrt(v_want) + 1e-8))
    np.testing.assert_allclose(np.asarray(outs[0]), w_want, rtol=1e-5)


def test_sparse_and_group_adagrad():
    w = np.ones((3, 2), np.float32)
    g = np.zeros((3, 2), np.float32)
    g[1] = [1.0, -2.0]                       # only row 1 has gradient
    h = np.zeros((3, 2), np.float32)
    new_w, new_h = _op("_sparse_adagrad_update", w, g, h, lr=0.1)
    assert np.allclose(np.asarray(new_w)[0], 1.0)    # untouched rows
    assert np.allclose(np.asarray(new_w)[2], 1.0)
    assert not np.allclose(np.asarray(new_w)[1], 1.0)
    hg = np.zeros((3,), np.float32)
    new_w2, new_hg = _op("_contrib_group_adagrad_update", w, g, hg,
                         lr=0.1)
    assert np.asarray(new_hg)[1] > 0 and np.asarray(new_hg)[0] == 0


def test_quantized_family():
    import jax.numpy as jnp
    q = np.array([-50, -1, 0, 30, 127], np.int8)
    out, mn, mx_ = _op("_contrib_quantized_act", q, -1.0, 1.0)
    np.testing.assert_array_equal(np.asarray(out), [0, 0, 0, 30, 127])
    assert float(mn) == 0.0
    # elemwise add requantizes: dequant oracle
    a = np.array([127, -127, 64], np.int8)
    b = np.array([127, 127, 0], np.int8)
    s, mn, mx_ = _op("_contrib_quantized_elemwise_add",
                     a, b, -1.0, 1.0, -2.0, 2.0)
    real = a / 127.0 * 1.0 + b / 127.0 * 2.0
    back = np.asarray(s, np.float32) * (float(mx_) / 127.0)
    np.testing.assert_allclose(back, real, atol=float(mx_) / 127.0)
    # concat to widest range
    c, mn, mx_ = _op("_contrib_quantized_concat",
                     np.array([[127]], np.int8),
                     np.array([[127]], np.int8),
                     np.asarray(-1.0), np.asarray(-4.0),
                     np.asarray(1.0), np.asarray(4.0),
                     num_args=2, dim=1)
    assert float(mx_) == 4.0
    np.testing.assert_array_equal(np.asarray(c), [[32, 127]])


def test_calibrate_entropy_op():
    rng = np.random.RandomState(5)
    data = np.concatenate([rng.randn(100000) * 0.5, [60.0]])
    hist, edges = np.histogram(data, bins=4001, range=(-64, 64))
    mn, mx_ = _op("_contrib_calibrate_entropy", hist.astype(np.float32),
                  edges.astype(np.float32))
    assert 0.5 < float(mx_) < 30.0
    assert float(mn) == -float(mx_)


def test_sync_batch_norm_pmean():
    """Stats must be identical to a BatchNorm over the CONCATENATED
    per-device batches (reference sync_batch_norm.cc contract)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    # jax 0.4.x has no top-level jax.shard_map; the parallel.compat shim
    # is the one import path that works on every supported jax
    from mxnet_tpu.parallel import shard_map

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("dp",))
    rng = np.random.RandomState(6)
    x = rng.randn(8, 4, 6).astype(np.float32)     # (N, C, W), dp over N
    gamma = np.ones(4, np.float32)
    beta = np.zeros(4, np.float32)
    mm = np.zeros(4, np.float32)
    mv = np.ones(4, np.float32)
    sync = _REGISTRY["_contrib_SyncBatchNorm"].impl

    def local(x):
        return sync(x, jnp.asarray(gamma), jnp.asarray(beta),
                    jnp.asarray(mm), jnp.asarray(mv), fix_gamma=False,
                    axis=1, axis_name="dp", _training=True)

    f = shard_map(local, mesh=mesh, in_specs=(P("dp"),),
                  out_specs=P("dp"))
    out = np.asarray(f(jnp.asarray(x)))
    # oracle: plain BatchNorm over the full batch
    ref = _REGISTRY["BatchNorm"].impl(
        jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta),
        jnp.asarray(mm), jnp.asarray(mv), fix_gamma=False, axis=1,
        _training=True)
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-4,
                               atol=1e-5)


def test_correlation_oracle():
    rng = np.random.RandomState(7)
    n, c, h, w = 1, 3, 6, 6
    x1 = rng.randn(n, c, h, w).astype(np.float32)
    x2 = rng.randn(n, c, h, w).astype(np.float32)
    out = np.asarray(_op("Correlation", x1, x2, kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=1))
    assert out.shape == (1, 9, 8, 8)
    # center displacement (0,0) at interior position equals channel-mean
    # of the product
    want = (x1[0, :, 2, 3] * x2[0, :, 2, 3]).mean()
    np.testing.assert_allclose(out[0, 4, 3, 4], want, rtol=1e-5)


def test_count_sketch():
    data = np.array([[1.0, 2.0, 3.0]], np.float32)
    h = np.array([0, 1, 0])
    s = np.array([1.0, -1.0, 1.0], np.float32)
    out = _op("_contrib_count_sketch", data, h, s, out_dim=2)
    np.testing.assert_allclose(np.asarray(out), [[4.0, -2.0]])


def test_bipartite_matching():
    scores = np.array([[0.9, 0.1], [0.8, 0.7]], np.float32)
    rows, cols = _op("_contrib_bipartite_matching", scores,
                     threshold=0.05)
    np.testing.assert_array_equal(np.asarray(rows), [0, 1])
    np.testing.assert_array_equal(np.asarray(cols), [0, 1])


def test_trian_roundtrip():
    rng = np.random.RandomState(8)
    A = rng.randn(4, 4).astype(np.float32)
    packed = _op("_linalg_extracttrian", A, offset=0, lower=True)
    assert packed.shape == (10,)
    back = _op("_linalg_maketrian", np.asarray(packed), offset=0,
               lower=True)
    np.testing.assert_allclose(np.asarray(back), np.tril(A), rtol=1e-6)


def test_boolean_mask_and_getnnz():
    x = np.array([[1.0, 0.0], [0.0, 0.0], [3.0, 4.0]], np.float32)
    sel = _op("_contrib_boolean_mask", x, np.array([1, 0, 1]))
    np.testing.assert_allclose(np.asarray(sel), x[[0, 2]])
    assert int(_op("_contrib_getnnz", x)) == 3


def test_sparse_embedding_op_grad():
    from mxnet_tpu import nd
    from mxnet_tpu.ndarray.sparse import RowSparseNDArray
    import mxnet_tpu.autograd as ag
    w = nd.array(np.random.RandomState(9).randn(20, 3)
                 .astype(np.float32))
    w.attach_grad()
    x = nd.array(np.array([1, 5]))
    with ag.record():
        out = nd._contrib_SparseEmbedding(x, w, input_dim=20,
                                          output_dim=3)
        loss = (out * out).sum()
    loss.backward()
    assert isinstance(w.grad, RowSparseNDArray)
