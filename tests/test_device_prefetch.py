"""DevicePrefetchIter + DataLoader/io/estimator wiring: ordering,
identity, overlap, error transparency, and the prefetch knobs."""
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.data import (DataLoader, DevicePrefetchIter,
                                  stage_batch)
from mxnet_tpu.gluon.data.dataset import ArrayDataset, Dataset


def _loader_batches(loader):
    return [(d.asnumpy().copy(), l.asnumpy().copy()) for d, l in loader]


class SlowDataset(Dataset):
    """Dataset whose __getitem__ stalls like a real decode/augment."""

    def __init__(self, n=48, dim=3, delay=0.002, seed=0):
        rng = np.random.RandomState(seed)
        self._x = rng.randn(n, dim).astype(np.float32)
        self._delay = delay

    def __len__(self):
        return len(self._x)

    def __getitem__(self, idx):
        time.sleep(self._delay)
        return self._x[idx], np.float32(idx)


def test_prefetch_yields_identical_batches_in_order():
    """Stress the satellite contract: under a slow dataset, every
    prefetch configuration yields exactly the batches the synchronous
    loader yields, in the same order."""
    ds = SlowDataset()
    want = _loader_batches(DataLoader(ds, batch_size=8))
    assert len(want) == 6
    for kwargs in ({"prefetch": 3},                      # host-side thread
                   {"device_prefetch": 2},               # device staging
                   {"prefetch": 2, "device_prefetch": 3}):
        got = _loader_batches(DataLoader(ds, batch_size=8, **kwargs))
        assert len(got) == len(want)
        for (a, b), (c, d) in zip(got, want):
            assert (a == c).all() and (b == d).all(), kwargs


def test_explicit_prefetch_honored_single_process():
    """num_workers=0 with an explicit prefetch= used to be silently
    zeroed (`prefetch or 2*num_workers`); the argument must win."""
    ds = ArrayDataset(np.zeros((8, 2), np.float32),
                      np.zeros(8, np.float32))
    assert DataLoader(ds, batch_size=4, prefetch=3)._prefetch == 3
    assert DataLoader(ds, batch_size=4)._prefetch == 0
    assert DataLoader(ds, batch_size=4, num_workers=2,
                      thread_pool=True)._prefetch == 4


def test_env_default_enables_device_prefetch(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_DATA_PREFETCH", "2")
    ds = ArrayDataset(np.arange(16, dtype=np.float32).reshape(8, 2),
                      np.arange(8, dtype=np.float32))
    loader = DataLoader(ds, batch_size=4)
    assert loader._device_prefetch == 2
    want = _loader_batches(DataLoader(ds, batch_size=4,
                                      device_prefetch=0))
    got = _loader_batches(loader)
    for (a, b), (c, d) in zip(got, want):
        assert (a == c).all() and (b == d).all()


def test_overlap_hides_data_latency():
    """Acceptance: with an artificially slow source and a compute-bound
    consumer, total epoch time must be well under the serial
    sum(data_time) + sum(compute_time)."""
    n, delay = 14, 0.02

    class SlowSource:
        def __iter__(self):
            for i in range(n):
                time.sleep(delay)
                yield mx.nd.NDArray(np.full((4, 4), i, np.float32))

    def epoch(source):
        t0 = time.monotonic()
        seen = []
        for batch in source:
            time.sleep(delay)           # the "compute" half
            seen.append(int(batch.asnumpy()[0, 0]))
        return time.monotonic() - t0, seen

    # timing comparisons on shared CI need a retry to shed scheduler noise
    for attempt in range(3):
        serial, order_a = epoch(SlowSource())
        overlapped, order_b = epoch(DevicePrefetchIter(SlowSource(),
                                                       depth=2))
        assert order_a == order_b == list(range(n))
        if overlapped < 0.85 * serial:
            break
    else:
        pytest.fail(f"no overlap: prefetch epoch {overlapped:.3f}s vs "
                    f"serial {serial:.3f}s")


def test_source_exception_surfaces_in_consumer():
    def bad():
        yield mx.nd.NDArray(np.zeros(3, np.float32))
        raise RuntimeError("decode failed")

    it = iter(DevicePrefetchIter(bad(), depth=2))
    next(it)
    with pytest.raises(RuntimeError, match="decode failed"):
        next(it)


def test_stage_batch_structures():
    """Staging preserves structure and values; non-array leaves pass
    through untouched."""
    nd = mx.nd.NDArray(np.arange(6, dtype=np.float32).reshape(2, 3))
    batch = {"x": nd, "meta": ("tag", 7), "ys": [nd, np.ones(2)]}
    staged = stage_batch(batch)
    assert (staged["x"].asnumpy() == nd.asnumpy()).all()
    assert staged["meta"] == ("tag", 7)
    assert isinstance(staged["ys"][1], np.ndarray)
    assert (staged["ys"][0].asnumpy() == nd.asnumpy()).all()


def test_stage_batch_databatch_label_none_and_tuple():
    """io.DataBatch with label=None (inference) or tuple payloads must
    still have its data staged."""
    from mxnet_tpu.io import DataBatch
    nd = mx.nd.NDArray(np.arange(4, dtype=np.float32))
    b1 = stage_batch(DataBatch(data=[nd], label=None))
    assert (b1.data[0].asnumpy() == nd.asnumpy()).all()
    assert b1.label is None
    b2 = stage_batch(DataBatch(data=(nd,), label=(nd,)))
    assert (b2.data[0].asnumpy() == nd.asnumpy()).all()
    assert (b2.label[0].asnumpy() == nd.asnumpy()).all()


def test_io_prefetching_iter_device_staging():
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter
    x = np.random.RandomState(0).randn(20, 3).astype(np.float32)
    y = np.arange(20, dtype=np.float32)
    want = []
    it = NDArrayIter(x, y, batch_size=5)
    for b in it:
        want.append((b.data[0].asnumpy().copy(),
                     b.label[0].asnumpy().copy()))
    src = NDArrayIter(x, y, batch_size=5)
    got = []
    for b in PrefetchingIter(src, device_prefetch=True):
        got.append((b.data[0].asnumpy().copy(),
                    b.label[0].asnumpy().copy()))
    assert len(got) == len(want)
    for (a, b_), (c, d) in zip(got, want):
        assert (a == c).all() and (b_ == d).all()


def test_io_prefetching_iter_forwards_worker_errors():
    """A staging/source failure in the PrefetchingIter worker must raise
    in the consumer, not strand it on an empty queue."""
    from mxnet_tpu.io import DataIter, PrefetchingIter

    class Bad(DataIter):
        provide_data = []
        provide_label = []
        batch_size = 1

        def next(self):
            raise ValueError("reader exploded")

    it = PrefetchingIter(Bad())
    with pytest.raises(ValueError, match="reader exploded"):
        it.next()


def test_estimator_no_double_wrap(monkeypatch):
    """MXNET_TPU_DATA_PREFETCH + a DataLoader (which self-wraps) must not
    stack a second estimator-level prefetcher."""
    monkeypatch.setenv("MXNET_TPU_DATA_PREFETCH", "2")
    from mxnet_tpu.gluon import nn, loss as gloss
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.observability import get_registry

    net = nn.Sequential()
    with net.name_scope():
        net.add(nn.Dense(2))
    net.initialize()
    x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
    y = (np.arange(8) % 2).astype(np.float32)
    loader = DataLoader(ArrayDataset(x, y), batch_size=4)
    counter = get_registry().counter("mxtpu_data_prefetch_batches_total")
    before = counter.value
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss())
    est.fit(loader, epochs=1)
    assert counter.value - before == 2  # staged once per batch, not twice


def test_estimator_fit_with_device_prefetch():
    """Smoke: Estimator.fit drives a full epoch through the prefetcher
    and the StepTimer data_fraction gauge is populated."""
    from mxnet_tpu.gluon import nn, loss as gloss
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.observability import get_registry

    net = nn.Sequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize()
    x = np.random.RandomState(0).randn(16, 3).astype(np.float32)
    y = (np.arange(16) % 4).astype(np.float32)
    ds = ArrayDataset(x, y)
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss())
    est.fit(DataLoader(ds, batch_size=4), epochs=1, device_prefetch=2)
    reg = get_registry()
    assert reg.counter("mxtpu_data_prefetch_batches_total").value >= 4
    assert reg.gauge("mxtpu_data_prefetch_depth").value == 2


def test_prefetch_metrics_registered():
    ds = ArrayDataset(np.zeros((8, 2), np.float32),
                      np.zeros(8, np.float32))
    list(DataLoader(ds, batch_size=4, device_prefetch=2))
    from mxnet_tpu.observability import get_registry
    text = get_registry().expose()
    for name in ("mxtpu_data_prefetch_batches_total",
                 "mxtpu_data_prefetch_depth",
                 "mxtpu_data_prefetch_queue_fill",
                 "mxtpu_data_prefetch_wait_seconds"):
        assert name in text
