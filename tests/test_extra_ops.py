"""Coverage-tail ops (ops/extra.py): legacy outputs, spatial transformer
family, im2col/col2im, samplers, multi-tensor optimizer kernels, small
contribs. Reference provenance in ops/extra.py docstrings."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.autograd as ag
from mxnet_tpu.test_utils import check_numeric_gradient


def test_internal_comparison_and_logical():
    a = nd.array(np.array([[1.0, 2.0], [3.0, 4.0]]))
    b = nd.array(np.array([[1.0, 1.0], [5.0, 4.0]]))
    np.testing.assert_array_equal(nd._equal(a, b).asnumpy(),
                                  [[1, 0], [0, 1]])
    np.testing.assert_array_equal(nd._greater(a, b).asnumpy(),
                                  [[0, 1], [0, 0]])
    np.testing.assert_array_equal(
        nd._logical_and(a, nd.array(np.array([[0.0, 1.0], [1.0, 0.0]])))
        .asnumpy(), [[0, 1], [1, 0]])
    np.testing.assert_allclose(nd.add_n(a, b, a).asnumpy(),
                               2 * a.asnumpy() + b.asnumpy())


def test_im2col_col2im_adjoint():
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(1, 2, 4, 4).astype(np.float32))
    cols = nd.im2col(x, kernel=(2, 2), stride=(1, 1))
    assert cols.shape == (1, 8, 9)
    # col2im(im2col(x)) multiplies each pixel by its patch count
    back = nd.col2im(cols, output_size=(4, 4), kernel=(2, 2),
                     stride=(1, 1))
    counts = np.zeros((4, 4), np.float32)
    for i in range(3):
        for j in range(3):
            counts[i:i + 2, j:j + 2] += 1
    np.testing.assert_allclose(back.asnumpy(),
                               x.asnumpy() * counts, rtol=1e-5)


def test_legacy_output_layers():
    d = nd.array(np.array([[2.0]], np.float32))
    lab = nd.array(np.array([[0.5]], np.float32))
    d.attach_grad()
    with ag.record():
        nd.LinearRegressionOutput(d, lab).backward()
    np.testing.assert_allclose(d.grad.asnumpy(), [[1.5]])

    d2 = nd.array(np.array([[0.0]], np.float32))
    d2.attach_grad()
    with ag.record():
        out = nd.LogisticRegressionOutput(d2, lab)
        out.backward()
    np.testing.assert_allclose(out.asnumpy(), [[0.5]])
    np.testing.assert_allclose(d2.grad.asnumpy(), [[0.0]], atol=1e-6)

    sm = nd.SoftmaxActivation(nd.array(np.zeros((2, 3), np.float32)))
    np.testing.assert_allclose(sm.asnumpy(), 1 / 3, rtol=1e-6)


def test_spatial_transformer_identity_and_shift():
    rng = np.random.RandomState(0)
    img = nd.array(rng.randn(1, 1, 5, 5).astype(np.float32))
    ident = nd.array(np.array([[1, 0, 0, 0, 1, 0]], np.float32))
    out = nd.SpatialTransformer(img, ident, target_shape=(5, 5))
    np.testing.assert_allclose(out.asnumpy(), img.asnumpy(), atol=1e-5)
    # grads flow to both data and the transform
    img.attach_grad()
    theta = nd.array(np.array([[1, 0, 0.1, 0, 1, -0.1]], np.float32))
    theta.attach_grad()
    with ag.record():
        o = nd.SpatialTransformer(img, theta, target_shape=(5, 5))
        o.sum().backward()
    assert np.abs(img.grad.asnumpy()).sum() > 0
    assert np.abs(theta.grad.asnumpy()).sum() > 0


def test_bilinear_sampler_zero_padding_outside():
    img = nd.array(np.ones((1, 1, 3, 3), np.float32))
    # grid entirely outside [-1,1] -> zeros
    grid = nd.array(np.full((1, 2, 2, 2), 3.0, np.float32))
    out = nd.BilinearSampler(img, grid)
    np.testing.assert_allclose(out.asnumpy(), 0.0)


def test_roi_pooling_max_semantics():
    img_np = np.zeros((1, 1, 8, 8), np.float32)
    img_np[0, 0, 1, 1] = 5.0
    img_np[0, 0, 6, 6] = 7.0
    out = nd.ROIPooling(nd.array(img_np),
                        nd.array(np.array([[0, 0, 0, 7, 7]], np.float32)),
                        pooled_size=(2, 2), spatial_scale=1.0)
    o = out.asnumpy()[0, 0]
    assert o[0, 0] == 5.0 and o[1, 1] == 7.0


def test_crop():
    x = nd.array(np.arange(16.0).reshape(1, 1, 4, 4))
    out = nd.Crop(x, offset=(1, 1), h_w=(2, 2))
    np.testing.assert_allclose(out.asnumpy()[0, 0], [[5, 6], [9, 10]])
    like = nd.array(np.zeros((1, 1, 2, 2), np.float32))
    out2 = nd.Crop(x, like, center_crop=True, num_args=2)
    np.testing.assert_allclose(out2.asnumpy()[0, 0], [[5, 6], [9, 10]])


def test_samplers_row_per_distribution():
    mx.random.seed(0)
    lam = nd.array(np.array([1.0, 20.0]))
    s = nd._sample_poisson(lam, shape=(800,))
    assert s.shape == (2, 800)
    means = s.asnumpy().mean(axis=1)
    assert abs(means[0] - 1.0) < 0.2 and abs(means[1] - 20.0) < 1.0
    e = nd._sample_exponential(lam, shape=(800,))
    em = e.asnumpy().mean(axis=1)
    assert abs(em[0] - 1.0) < 0.2 and abs(em[1] - 0.05) < 0.02
    k = nd.array(np.array([5.0]))
    p = nd.array(np.array([0.5]))
    nb = nd._sample_negative_binomial(k, p, shape=(2000,))
    assert abs(nb.asnumpy().mean() - 5.0) < 0.5   # mean k(1-p)/p = 5


def test_ftml_and_adamw_updates():
    w = nd.array(np.ones(4, np.float32))
    g = nd.array(np.full(4, 0.1, np.float32))
    d = nd.array(np.zeros(4, np.float32))
    v = nd.array(np.zeros(4, np.float32))
    z = nd.array(np.zeros(4, np.float32))
    nd.ftml_update(w, g, d, v, z, lr=0.1, t=1)
    # t=1: v=(1-b2)g^2; d_t=(1-b1)/lr*(sqrt(g^2)+eps); z=(1-b1)g-d_t*w
    assert np.all(w.asnumpy() < 1.0) and np.isfinite(w.asnumpy()).all()

    w2 = nd.array(np.ones(4, np.float32))
    m = nd.array(np.zeros(4, np.float32))
    vv = nd.array(np.zeros(4, np.float32))
    nd._adamw_update(w2, g, m, vv, rescale_grad=1.0, lr=0.1, wd=0.01,
                     eta=1.0)
    expect = 1.0 - (0.1 * (0.1 * 0.1) / (np.sqrt(0.001 * 0.01) + 1e-8)
                    + 0.01 * 1.0)
    np.testing.assert_allclose(w2.asnumpy(), expect, rtol=1e-4)


def test_multi_tensor_sgd():
    outs = nd.multi_sgd_update(
        nd.array(np.ones(2, np.float32)),
        nd.array(np.full(2, 0.5, np.float32)),
        nd.array(np.ones(3, np.float32)),
        nd.array(np.full(3, 0.1, np.float32)),
        num_weights=2, lrs=(0.1, 0.2), wds=(0.0, 0.0))
    np.testing.assert_allclose(outs[0].asnumpy(), 0.95, rtol=1e-6)
    np.testing.assert_allclose(outs[1].asnumpy(), 0.98, rtol=1e-6)
    # sum-sq + all-finite helpers
    ss = nd.multi_sum_sq(nd.array(np.ones(3)), nd.array(np.full(2, 2.0)),
                         num_arrays=2)
    np.testing.assert_allclose([float(s.asnumpy()[0]) for s in ss],
                               [3.0, 8.0])
    fin = nd.all_finite(nd.array(np.array([1.0, np.inf])))
    assert float(fin.asnumpy()[0]) == 0.0


def test_small_contribs():
    a = nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    assert float(nd._contrib_allclose(a, a).asnumpy()[0]) == 1.0
    np.testing.assert_allclose(
        nd._contrib_quadratic(a, a=1.0, b=2.0, c=3.0).asnumpy(),
        a.asnumpy() ** 2 + 2 * a.asnumpy() + 3)
    np.testing.assert_allclose(
        nd._contrib_div_sqrt_dim(a).asnumpy(),
        a.asnumpy() / np.sqrt(2), rtol=1e-6)
    # gradient multiplier scales only the backward
    x = nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with ag.record():
        nd._contrib_gradientmultiplier(x, scalar=3.0).backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [3.0])
    # straight-through round: grad passes unchanged
    x2 = nd.array(np.array([1.4], np.float32))
    x2.attach_grad()
    with ag.record():
        out = nd._contrib_round_ste(x2)
        out.backward()
    np.testing.assert_allclose(out.asnumpy(), [1.0])
    np.testing.assert_allclose(x2.grad.asnumpy(), [1.0])


def test_box_encode_decode_roundtrip():
    anchors = np.array([[[0.2, 0.2, 0.4, 0.4], [0.5, 0.5, 0.9, 0.8]]],
                       np.float32)
    refs = np.array([[[0.25, 0.25, 0.45, 0.5]]], np.float32)
    matches = np.array([[0, 0]], np.float32)
    samples = np.array([[1.0, 1.0]], np.float32)
    enc, mask = nd._contrib_box_encode(
        nd.array(samples), nd.array(matches), nd.array(anchors),
        nd.array(refs), means=(0, 0, 0, 0), stds=(0.1, 0.1, 0.2, 0.2))
    dec = nd._contrib_box_decode(enc, nd.array(anchors),
                                 std0=0.1, std1=0.1, std2=0.2, std3=0.2)
    np.testing.assert_allclose(dec.asnumpy()[0, 0], refs[0, 0], atol=1e-5)
    np.testing.assert_allclose(dec.asnumpy()[0, 1], refs[0, 0], atol=1e-5)


def test_fft_ifft_reference_convention():
    sig = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    f = nd._contrib_fft(nd.array(sig))
    assert f.shape == (2, 16)
    rt = nd._contrib_ifft(f)     # reference ifft is unnormalized (x n)
    np.testing.assert_allclose(rt.asnumpy() / 8, sig, rtol=1e-4,
                               atol=1e-5)


def test_numeric_gradients_extra():
    check_numeric_gradient("_contrib_quadratic",
                           [np.random.RandomState(0).randn(3, 3)],
                           {"a": 0.5, "b": -1.0, "c": 2.0})
    check_numeric_gradient("_contrib_div_sqrt_dim",
                           [np.random.RandomState(1).randn(2, 4)])
    check_numeric_gradient("im2col",
                           [np.random.RandomState(2).randn(1, 2, 4, 4)],
                           {"kernel": (2, 2), "stride": (1, 1)})
    check_numeric_gradient("_square_sum",
                           [np.random.RandomState(3).randn(3, 3)],
                           {"axis": 1})


def test_monitor_and_runtime():
    from mxnet_tpu import monitor, runtime
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    mon = monitor.Monitor(interval=1, pattern=".*").install(net)
    x = nd.array(np.ones((2, 3), np.float32))
    mon.tic()
    with ag.pause():
        net(x)
    rows = mon.toc()
    assert len(rows) >= 2           # one stat per hooked block forward
    assert all(np.isfinite(r[2]) for r in rows)

    feats = runtime.Features()
    assert feats.is_enabled("CPU")
    assert feats.is_enabled("DIST_KVSTORE")
    assert any(f.name == "PALLAS" for f in runtime.feature_list())


def test_arange_like_repeat():
    """repeat>1 emits each value `repeat` times (review finding r3)."""
    import numpy as np
    from mxnet_tpu import nd
    x = nd.array(np.zeros(5))
    out = nd._contrib_arange_like(x, repeat=2)
    np.testing.assert_allclose(out.asnumpy(), [0, 0, 1, 1, 2])
