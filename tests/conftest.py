"""Test fixtures for mxnet_tpu.

Mirrors the reference's test infra (reference: conftest.py:38+ seed
reporting, tests/python/unittest/common.py with_seed): every test runs with
a reproducible seed that is printed on failure.

Sharding/collective tests run on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``) — the TPU-build analogue of
the reference's `--launcher local` fake cluster (SURVEY.md §4).
"""
import os
import random

# Force a virtual 8-device CPU platform so multi-chip sharding paths are
# exercised without TPU hardware. NOTE: jax may already be imported (site
# hooks can register accelerator plugins at interpreter start and capture
# JAX_PLATFORMS), so the env var alone is not enough — update jax config
# directly before any backend initializes. Set MXNET_TEST_ON_TPU=1 to run
# the suite against the real chip instead.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
if not os.environ.get("MXNET_TEST_ON_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def seed_all(request):
    """Seed python/numpy/mxnet RNGs per test; report the seed on failure
    (reference: conftest.py seeding + common.py:155 with_seed)."""
    seed = int(os.environ.get("MXNET_TEST_SEED",
                              np.random.randint(0, 2**31)))
    random.seed(seed)
    np.random.seed(seed)
    import mxnet_tpu as mx
    mx.ndarray.random.seed(seed)
    yield
    if request.node.rep_call.failed if hasattr(request.node, "rep_call") else False:
        print(f"\nTest failed with MXNET_TEST_SEED={seed} — "
              f"set this env var to reproduce.")


@pytest.hookimpl(tryfirst=True, hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    rep = outcome.get_result()
    setattr(item, "rep_" + rep.when, rep)
