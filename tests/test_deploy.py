"""Self-contained inference artifacts (mx.deploy — the C predict API
analogue, reference include/mxnet/c_predict_api.h).
"""
import os
import struct
import subprocess
import sys

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
import mxnet_tpu.autograd as ag


def _net():
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    return net


def test_export_load_roundtrip(tmp_path):
    net = _net()
    x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
    with ag.pause():
        want = net(nd.array(x)).asnumpy()
    path = str(tmp_path / "model.mxtpu")
    mx.deploy.export_predictor(net, x, path)
    pred = mx.deploy.load_predictor(path)
    assert pred.input_shape == (3, 8)
    np.testing.assert_allclose(pred(x), want, rtol=1e-5, atol=1e-6)


def test_artifact_loads_with_only_jax(tmp_path):
    """The serving side needs ONLY jax — the defining property of the
    reference's dependency-free predictor."""
    net = _net()
    x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
    with ag.pause():
        want = net(nd.array(x)).asnumpy()
    path = str(tmp_path / "m.mxtpu")
    mx.deploy.export_predictor(net, x, path)
    xpath = str(tmp_path / "x.npy")
    np.save(xpath, x)
    script = f"""
import struct, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from jax import export
blob = open({path!r}, "rb").read()
assert blob.startswith(b"MXTPUPRED1")
off = len(b"MXTPUPRED1")
(hlen,) = struct.unpack_from("<I", blob, off)
exp = export.deserialize(blob[off + 4 + hlen:])
out = exp.call(np.load({xpath!r}))
np.save({str(tmp_path / 'out.npy')!r}, np.asarray(out))
"""
    env = {k: v for k, v in os.environ.items()}
    r = subprocess.run([sys.executable, "-c", script],
                       capture_output=True, text=True, env=env,
                       timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    got = np.load(tmp_path / "out.npy")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_rejects_garbage():
    import pytest
    with pytest.raises(ValueError):
        mx.deploy.Predictor(b"not an artifact")
