"""INT8 quantization: ops, calibration, and quantize_net.

Reference behaviours pinned here:
- src/operator/quantization/quantize.cc / dequantize.cc / requantize.cc
  (symmetric int8, affine uint8, range bookkeeping triples)
- python/mxnet/contrib/quantization.py quantize_net:806 (calibrated
  post-training quantization of a gluon net), _get_optimal_threshold:320
  (KL/entropy calibration)
- src/operator/quantization/quantized_fully_connected.cc, quantized_conv.cc
  (int8 x int8 -> int32 accumulation)
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon import nn
import mxnet_tpu.autograd as ag


def _op(name, *args, **kw):
    import jax.numpy as jnp
    from mxnet_tpu.ops.registry import _REGISTRY
    arrays = [jnp.asarray(a) for a in args]
    return _REGISTRY[name].impl(*arrays, **kw)


def test_quantize_dequantize_roundtrip_int8():
    rng = np.random.RandomState(0)
    x = (rng.randn(64) * 3).astype(np.float32)
    q, mn, mx_ = _op("_contrib_quantize_v2", x)
    assert np.asarray(q).dtype == np.int8
    back = _op("_contrib_dequantize", q, mn, mx_)
    # max error is half a quantization step
    step = float(np.asarray(mx_)) / 127.0
    np.testing.assert_allclose(np.asarray(back), x, atol=step / 2 + 1e-6)


def test_quantize_uint8_affine():
    x = np.array([0.0, 0.5, 1.0], np.float32)
    q, mn, mx_ = _op("_contrib_quantize", x, 0.0, 1.0, out_type="uint8")
    np.testing.assert_array_equal(np.asarray(q), [0, 128, 255])
    back = _op("_contrib_dequantize", q, mn, mx_)
    np.testing.assert_allclose(np.asarray(back), x, atol=1 / 255)


def test_requantize_int32_to_int8():
    # int32 accumulator of products of int8 values scaled by (t/127)^2
    acc = np.array([16129, -8000, 0, 4000], np.int32)   # 127*127 max
    q, mn, mx_ = _op("_contrib_requantize", acc, -1.0, 1.0)
    assert np.asarray(q).dtype == np.int8
    real = acc.astype(np.float32) / (127.0 * 127.0)
    back = _op("_contrib_dequantize", q, mn, mx_)
    np.testing.assert_allclose(np.asarray(back), real, atol=1e-2)


def test_quantized_fully_connected_matches_fp32():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 32).astype(np.float32)
    w = rng.randn(8, 32).astype(np.float32)
    qx, _, xmx = _op("_contrib_quantize_v2", x)
    qw, _, wmx = _op("_contrib_quantize_v2", w)
    xs = float(np.asarray(xmx)) / 127.0
    ws = float(np.asarray(wmx)) / 127.0
    out = _op("_contrib_quantized_fully_connected", qx, qw,
              x_scale=xs, w_scale=ws)
    np.testing.assert_allclose(np.asarray(out), x @ w.T, rtol=0.1,
                               atol=0.15)


def test_optimal_threshold_rejects_outliers():
    """Entropy calibration should pick a threshold well below a lone
    outlier when the mass is concentrated (the point of the KL search)."""
    from mxnet_tpu.contrib.quantization import optimal_threshold
    rng = np.random.RandomState(2)
    data = np.concatenate([rng.randn(100000) * 0.5, [50.0]])
    hist, edges = np.histogram(data, bins=4001, range=(-64, 64))
    t = optimal_threshold(hist, edges)
    assert t < 25.0, t                   # naive would say 50
    assert t > 0.5, t


def _calib_batches(rng, n, shape):
    return [nd.array(rng.randn(*shape).astype(np.float32))
            for _ in range(n)]


@pytest.mark.parametrize(
    "calib_mode",
    [pytest.param("naive", marks=pytest.mark.slow),  # ~8s (tier-1
     # budget); the entropy variant + exclude_and_accuracy keep the
     # quantize_net path fast
     "entropy"])
def test_quantize_net_dense_mlp(calib_mode):
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(10))
    # O(1) outputs: a near-zero-output net makes relative error
    # meaningless for PTQ comparison
    net.initialize(init=mx.initializer.Normal(0.5))
    rng = np.random.RandomState(3)
    x = nd.array(rng.randn(16, 20).astype(np.float32))
    with ag.pause():
        ref = net(x).asnumpy()
    mx.contrib.quantization.quantize_net(
        net, calib_data=_calib_batches(rng, 4, (16, 20)),
        calib_mode=calib_mode)
    from mxnet_tpu.contrib.quantization import QuantizedDense
    assert any(isinstance(c, QuantizedDense)
               for c in net._children.values())
    with ag.pause():
        out = net(x).asnumpy()
    # int8 PTQ keeps outputs close on a small calibrated net. naive
    # calibration bounds the worst case; entropy clips tails BY DESIGN
    # (it optimizes average information kept), so judge it on mean error.
    scale = np.abs(ref).max()
    if calib_mode == "naive":
        assert np.abs(out - ref).max() / scale < 0.06, \
            np.abs(out - ref).max() / scale
    else:
        assert np.abs(out - ref).mean() / scale < 0.05, \
            np.abs(out - ref).mean() / scale


@pytest.mark.slow   # ~7s on 1 CPU (tier-1 budget); conv
# quantization numerics stay fast via quantized_fully_connected +
# exclude_and_accuracy, NHWC conv via the layout op tests
def test_quantize_net_conv_nhwc():
    mx.random.seed(1)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1, layout="NHWC"),
                nn.Activation("relu"),
                nn.GlobalAvgPool2D(layout="NHWC"),
                nn.Dense(5))
    net.initialize()
    rng = np.random.RandomState(4)
    x = nd.array(rng.randn(4, 12, 12, 3).astype(np.float32))
    with ag.pause():
        ref = net(x).asnumpy()
    mx.contrib.quantization.quantize_net(
        net, calib_data=_calib_batches(rng, 4, (4, 12, 12, 3)))
    from mxnet_tpu.contrib.quantization import (QuantizedConv2D,
                                                QuantizedDense)
    kinds = {type(c) for c in net._children.values()}
    assert QuantizedConv2D in kinds and QuantizedDense in kinds
    with ag.pause():
        out = net(x).asnumpy()
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() / scale < 0.12


def test_quantize_net_exclude_and_accuracy():
    """Excluded layers stay fp32; quantized classifier keeps argmax
    agreement high on the calibration distribution (the reference's
    acceptance criterion for PTQ)."""
    mx.random.seed(2)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"),
                nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    rng = np.random.RandomState(5)
    xs = rng.randn(256, 16).astype(np.float32)
    with ag.pause():
        ref_cls = net(nd.array(xs)).asnumpy().argmax(1)
    mx.contrib.quantization.quantize_net(
        net, calib_data=_calib_batches(rng, 8, (32, 16)), exclude=["2"])
    from mxnet_tpu.gluon.nn import Dense
    assert isinstance(net._children["2"], Dense)   # excluded, still fp32
    with ag.pause():
        q_cls = net(nd.array(xs)).asnumpy().argmax(1)
    agreement = (ref_cls == q_cls).mean()
    assert agreement > 0.95, agreement
