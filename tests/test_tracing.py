"""End-to-end runtime tracing (mxnet_tpu.observability.tracing).

Pins the span-tracer contracts every perf PR's evidence rides on:

- span mechanics: contextvar nesting, attrs, hand-off spans, and the
  explicit cross-thread propagation primitives (``current``/``attach``/
  ``parent=``) across the two real thread hops — DevicePrefetchIter's
  staging worker and the serving MicroBatchQueue batch former — with
  parent linkage preserved and zero spans left open after drain;
- off = free: with tracing disabled the hot paths return the shared
  no-op singleton and the ``mxtpu_trace_spans_started_total`` counter
  stays exactly flat over real training steps (counter-asserted);
- bounded memory: a 10k-span burst leaves the ring at capacity with
  every eviction counted (the PR 3 memory-flat discipline);
- the acceptance criterion: one ``Estimator.fit`` epoch with tracing on
  exports valid Chrome-trace JSON whose step spans nest compile/
  dispatch children, serving request spans decompose into
  queue/pad/compute, and a ``perf_capture`` record from an unreachable
  backend emits ``"skipped"`` with ``"value": null``.
"""
import importlib.util
import json
import os
import sys
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn, Trainer
from mxnet_tpu.observability import MetricsRegistry, get_registry
from mxnet_tpu.observability.tracing import (Tracer, get_tracer,
                                             validate_chrome_trace)
from mxnet_tpu.observability import tracing as tracing_mod

LOSS = gluon.loss.SoftmaxCrossEntropyLoss()


@pytest.fixture
def tracer():
    """The process tracer, enabled and emptied for one test; always
    disabled + drained again afterwards so tracing never leaks into the
    rest of the tier-1 run."""
    tr = get_tracer()
    tr.clear()
    tr.enable()
    yield tr
    tr.disable()
    tr.clear()


def _spans_by_name(tr):
    out = {}
    for s in tr.snapshot():
        out.setdefault(s["name"], []).append(s)
    return out


def _build(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(init=mx.initializer.Xavier())
    return net


def _batches(n=4, batch=16):
    rng = np.random.RandomState(0)
    return [(nd.array(rng.randn(batch, 6).astype(np.float32)),
             nd.array((rng.permutation(batch) % 4).astype(np.float32)))
            for _ in range(n)]


# ---------------------------------------------------- span mechanics --

def test_span_nesting_attrs_and_linkage(tracer):
    with tracer.span("outer", "host", attrs={"k": 1}) as outer:
        with tracer.span("inner") as inner:
            inner.set("x", "y")
        assert tracer.current() is outer
    assert tracer.current() is None
    by = _spans_by_name(tracer)
    assert by["inner"][0]["parent_id"] == by["outer"][0]["span_id"]
    assert by["inner"][0]["attrs"] == {"x": "y"}
    assert by["outer"][0]["attrs"] == {"k": 1}
    # inner finished first, so the ring holds it first (oldest first)
    assert [s["name"] for s in tracer.snapshot()] == ["inner", "outer"]
    assert tracer.stats()["open"] == 0


def test_disabled_span_is_shared_noop_singleton():
    tr = get_tracer()
    assert not tr.enabled
    a, b = tr.span("hot"), tr.span("other", "step", step=3)
    assert a is b, "disabled tracing must not allocate per call"
    with a as sp:
        sp.set("k", "v")          # all no-ops, never raises
    assert a.finish() is None
    assert tr.begin("handoff") is a


def test_ring_bounded_under_10k_spans():
    """Memory stays flat under load: the ring never exceeds capacity
    and every eviction is counted (PR 3 histogram discipline)."""
    reg = MetricsRegistry()
    tr = Tracer(ring=256, registry=reg).enable()
    for i in range(10000):
        tr.span(f"s{i % 7}").finish()
    st = tr.stats()
    assert st["buffered"] == 256
    assert st["capacity"] == 256
    assert st["started"] == 10000
    assert st["dropped"] == 10000 - 256
    assert st["open"] == 0
    assert len(tr.snapshot()) == 256


def test_attach_propagates_context_to_plain_thread(tracer):
    recorded = []

    def worker(parent):
        with tracer.attach(parent):
            assert tracer.current() is parent
            with tracer.span("work") as sp:
                recorded.append(sp.span_id)
        assert tracer.current() is None

    with tracer.span("producer") as parent:
        t = threading.Thread(target=worker, args=(tracer.current(),))
        t.start()
        t.join()
    by = _spans_by_name(tracer)
    work = by["work"][0]
    assert work["span_id"] == recorded[0]
    assert work["parent_id"] == parent.span_id
    assert work["tid"] != by["producer"][0]["tid"]


def test_step_annotation_goes_to_innermost_step_span(tracer, monkeypatch):
    """XLA step markers do not nest: while a profiler capture runs, only
    the OUTERMOST-at-open step-category span becomes a
    jax.profiler.StepTraceAnnotation — an enclosing epoch span or a
    trainer.step wrapped by CompiledTrainStep's fallback must not garble
    per-step device attribution."""
    import jax
    monkeypatch.setattr(tracing_mod, "_profiler_running", lambda: True)
    Step = jax.profiler.StepTraceAnnotation
    with tracer.span("epoch", "epoch", attrs={"epoch": 0}) as ep:
        assert not isinstance(ep._ann, Step)
        with tracer.span("step", "step", step=3) as outer:
            assert isinstance(outer._ann, Step)
            with tracer.span("fallback.step", "step", step=3) as inner:
                assert not isinstance(inner._ann, Step), \
                    "nested step span must degrade to a plain annotation"
        with tracer.span("step2", "step", step=4) as nxt:
            assert isinstance(nxt._ann, Step), \
                "depth must unwind when the outer step span finishes"
    # the tracer-off bridge (_AnnSpan) obeys the same rule
    tracer.disable()
    outer = tracer.span("step", "step", step=5)
    with outer:
        assert isinstance(outer._ann, Step)
        inner = tracer.span("inner", "step", step=5)
        with inner:
            assert not isinstance(inner._ann, Step)
    tracer.enable()


def test_validator_rejects_malformed_documents():
    ok = {"traceEvents": [{"ph": "X", "name": "a", "pid": 1, "tid": 1,
                           "ts": 0, "dur": 5}]}
    assert validate_chrome_trace(ok) == 1
    assert validate_chrome_trace(json.dumps(ok)) == 1
    for bad in (
            [],                                              # not a dict
            {"traceEvents": {}},                             # not a list
            {"traceEvents": [{"ph": "X", "name": "a"}]},     # no pid/tid
            {"traceEvents": [{"ph": "X", "name": "a", "pid": 1,
                              "tid": 1, "ts": -1, "dur": 2}]},
            {"traceEvents": [{"ph": "s", "name": "f", "pid": 1,
                              "tid": 1}]},                   # flow w/o id
    ):
        with pytest.raises(ValueError):
            validate_chrome_trace(bad)


def test_export_cross_thread_parent_draws_flow_arrows(tracer, tmp_path):
    def worker(parent):
        with tracer.span("child", parent=parent):
            pass

    with tracer.span("parent") as p:
        t = threading.Thread(target=worker, args=(p,))
        t.start()
        t.join()
    path = tracer.export(str(tmp_path / "t.json"))
    n = validate_chrome_trace(path)
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    assert n == 2
    phases = {e["ph"] for e in events}
    assert {"s", "f"} <= phases, "cross-thread hand-off needs flow arrows"
    # export accounting on the registry
    reg = get_registry()
    assert reg.counter("mxtpu_trace_exports_total").value > 0
    assert reg.counter("mxtpu_trace_export_bytes_total").value >= \
        os.path.getsize(path)


# ----------------------------------------- thread-hop instrumentation --

def test_prefetch_worker_spans_parent_under_consumer(tracer):
    """DevicePrefetchIter's staging thread: every stage span links back
    to the consumer's span that started the iteration, and the drain
    leaves nothing open."""
    from mxnet_tpu.gluon.data.prefetch import DevicePrefetchIter
    src = _batches(3)
    with tracer.span("train_loop") as loop:
        out = list(DevicePrefetchIter(src, depth=2))
    assert len(out) == 3
    by = _spans_by_name(tracer)
    stages = by["mxtpu.data_prefetch.stage"]
    assert len(stages) == 3
    for s in stages:
        assert s["parent_id"] == loop.span_id
        assert s["tid"] != by["train_loop"][0]["tid"], \
            "stage spans must come from the worker thread"
        assert s["cat"] == "data"
    assert tracer.stats()["open"] == 0


def test_serving_request_spans_cross_batch_former(tracer):
    """One serving request reads end to end: the hand-off span opens
    under the caller's span, is finished by the MicroBatchQueue worker,
    and decomposes into queue/pad/compute with its request id."""
    from mxnet_tpu import serving
    srv = serving.ModelServer(lambda b: b * 2.0, buckets=[1, 2, 4],
                              max_delay_ms=2.0, item_shape=(3,),
                              name="tsrv").start()
    try:
        with tracer.span("client") as client:
            y = srv.predict(np.ones(3, np.float32))
        assert np.allclose(y, 2.0)
    finally:
        srv.shutdown(drain=True)
    by = _spans_by_name(tracer)
    req = by["mxtpu.serving.request"][0]
    assert req["parent_id"] == client.span_id
    for key in ("req_id", "queue_ms", "pad_ms", "compute_ms", "bucket"):
        assert key in req["attrs"], f"request span lacks {key}"
    assert req["attrs"]["queue_ms"] >= 0
    assert req["attrs"]["compute_ms"] >= 0
    # the worker's batch span nests the pad -> dispatch -> reply stages
    batch = by["mxtpu.serving.batch"][0]
    for stage in ("mxtpu.serving.pad", "mxtpu.serving.dispatch",
                  "mxtpu.serving.reply"):
        assert by[stage][0]["parent_id"] == batch["span_id"]
    assert batch["tid"] != by["client"][0]["tid"]
    assert tracer.stats()["open"] == 0, "drained server leaked spans"


def test_serving_closed_request_span_is_finished(tracer):
    from mxnet_tpu import serving
    from mxnet_tpu.serving import ServerClosed
    srv = serving.ModelServer(lambda b: b, buckets=[1],
                              item_shape=(2,)).start()
    srv.shutdown(drain=True)
    with pytest.raises((ServerClosed, RuntimeError)):
        srv.submit(np.ones(2, np.float32))
    assert tracer.stats()["open"] == 0


def test_checkpoint_write_restore_spans(tracer, tmp_path):
    from mxnet_tpu import resilience as rz
    run = str(tmp_path / "run")
    rz.write_checkpoint(run, {"w": nd.array([1.0, 2.0])}, step=3)
    ckpt, manifest = rz.latest_checkpoint(run)
    rz.read_arrays(ckpt, manifest)
    by = _spans_by_name(tracer)
    w = by["mxtpu.ckpt.write"][0]
    assert w["attrs"]["step"] == 3 and w["attrs"]["bytes"] > 0
    r = by["mxtpu.ckpt.restore"][0]
    assert r["attrs"]["bytes"] > 0
    assert tracer.stats()["open"] == 0


def test_host_scope_is_a_tracer_span_too(tracer):
    """profiler.host_scope: one API, two sinks — existing call sites
    appear in tracer exports without re-instrumentation."""
    from mxnet_tpu import profiler
    with profiler.host_scope("legacy/site"):
        pass
    assert "legacy/site" in _spans_by_name(tracer)


# ----------------------------------------------------- off = free --

def test_tracing_off_training_hot_path_allocates_no_spans():
    """Counter-asserted zero-overhead contract: real compiled training
    steps with tracing off start exactly zero spans."""
    tr = get_tracer()
    assert not tr.enabled
    started = get_registry().counter("mxtpu_trace_spans_started_total")
    net = _build(21)
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.05})
    step = trainer.compile_step(lambda x, y: LOSS(net(x), y))
    data = _batches(3)
    step(*data[0])                      # compile outside the meter
    c0 = started.value
    for b in data:
        step(*b)
    assert started.value - c0 == 0, \
        "disabled tracing must not start spans on the step hot path"


# ------------------------------------------------------- acceptance --

def test_estimator_fit_epoch_exports_attributable_trace(tracer, tmp_path):
    """One Estimator.fit epoch with tracing on -> a valid Chrome-trace
    export whose step spans nest compile/dispatch children under the
    epoch span."""
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    net = _build(7)
    est = Estimator(net, LOSS,
                    trainer=Trainer(net.collect_params(), "sgd",
                                    {"learning_rate": 0.05}))
    est.fit(_batches(4), epochs=1, compiled_step=True)

    by = _spans_by_name(tracer)
    epoch = by["mxtpu.estimator.epoch"][0]
    steps = by["mxtpu.train_step"]
    assert len(steps) == 4
    step_ids = {s["span_id"] for s in steps}
    for s in steps:
        assert s["parent_id"] == epoch["span_id"]
        assert s["cat"] == "step"
    # first step compiled; every step dispatched — as children
    assert len(by["mxtpu.train_step.compile"]) == 1
    assert by["mxtpu.train_step.compile"][0]["parent_id"] in step_ids
    dispatches = by["mxtpu.train_step.dispatch"]
    assert len(dispatches) == 4
    assert all(d["parent_id"] in step_ids for d in dispatches)

    path = tracer.export(str(tmp_path / "fit.json"))
    n_events = validate_chrome_trace(path)
    assert n_events >= 4 + 4 + 1 + 1
    with open(path) as f:
        names = {e["name"] for e in json.load(f)["traceEvents"]
                 if e["ph"] == "X"}
    assert {"mxtpu.estimator.epoch", "mxtpu.train_step",
            "mxtpu.train_step.compile",
            "mxtpu.train_step.dispatch"} <= names
    assert tracer.stats()["open"] == 0


def _load_perf_capture():
    spec = importlib.util.spec_from_file_location(
        "perf_capture_under_test",
        os.path.join(REPO, "tools", "perf_capture.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_capture_unreachable_backend_emits_skip_marker(
        tmp_path, monkeypatch):
    """The BENCH_r05 regression, closed: an unreachable backend yields
    an artifact with a hard top-level "skipped" marker and value=null —
    a stale in-session capture is surfaced for audit but NEVER promoted
    to the headline unless --allow-stale says so, and then only under an
    explicit "stale": true."""
    pc = _load_perf_capture()
    monkeypatch.setattr(pc, "REPO", str(tmp_path))
    stale = {"metric": "resnet50_v1_train_bs128_bfloat16_NHWC_mfu",
             "value": 30.47, "vs_baseline": 7.36,
             "_capture": {"captured_at": "2026-07-30T00:00:00Z"}}
    rec = {"metric": "resnet50_v1_train_bs128_bfloat16_NHWC_mfu",
           "value": None, "unit": "% of bf16 peak",
           "skipped": "tpu_unavailable",
           "detail": "backend probe timed out",
           "last_capture": stale, "_capture": {"tag": "bs128_bf16"}}

    path = pc.emit_bench_snapshot(rec)
    with open(path) as f:
        out = json.load(f)
    assert out["skipped"] == "tpu_unavailable"
    assert out["value"] is None
    assert "stale" not in out
    assert out["stale_capture_available"]["value"] == 30.47
    assert "NOT promoted" in out["detail"]

    path2 = pc.emit_bench_snapshot(rec, allow_stale=True)
    assert path2 != path, "each attempt gets its own round artifact"
    with open(path2) as f:
        out2 = json.load(f)
    assert out2["skipped"] == "tpu_unavailable"
    assert out2["stale"] is True
    assert out2["value"] == 30.47, \
        "--allow-stale promotes the value under the stale marker"


def test_bench_skip_record_refuses_stale_headline(tmp_path, monkeypatch):
    """bench.py's own skip record obeys the same discipline when the
    in-process backend probe fails."""
    import bench
    cap = {"metric": "resnet50_v1_train_bs128_bfloat16_NHWC_mfu",
           "value": 30.47, "vs_baseline": 7.36}
    cap_path = tmp_path / "cap.json"
    cap_path.write_text(json.dumps(cap))
    monkeypatch.setenv("BENCH_CAPTURE_PATH", str(cap_path))
    monkeypatch.delenv("BENCH_ALLOW_STALE", raising=False)
    rec = bench._skip_record(128, "bfloat16", "NHWC", "tpu_unavailable",
                             "probe timed out")
    assert rec["skipped"] == "tpu_unavailable"
    assert rec["value"] is None and "stale" not in rec
    assert rec["last_capture"]["value"] == 30.47

    monkeypatch.setenv("BENCH_ALLOW_STALE", "1")
    rec2 = bench._skip_record(128, "bfloat16", "NHWC", "tpu_unavailable",
                              "probe timed out")
    assert rec2["value"] == 30.47 and rec2["stale"] is True


def test_bench_trend_classifies_artifacts(tmp_path):
    """tools/bench_trend.py: rc!=0 / suspect / skipped / stale rounds
    are never rendered as evidence; only fresh rc=0 values are valid."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_trend as bt
    finally:
        sys.path.pop(0)
    rounds = {
        1: {"n": 1, "rc": 1, "parsed": None},
        2: {"n": 2, "rc": 0, "parsed": {"suspect": True, "value": 99.0}},
        3: {"n": 3, "rc": 0, "parsed": {"skipped": "tpu_unavailable",
                                        "value": None}},
        4: {"n": 4, "rc": 0,
            "parsed": {"value": 30.47, "unit": "% of bf16 peak",
                       "stale": True,
                       "extra": {"train_img_s": 2676.0}}},
        5: {"n": 5, "rc": 0,
            "parsed": {"value": 31.0, "unit": "% of bf16 peak",
                       "extra": {"train_img_s": 2722.0}}},
    }
    for n, rec in rounds.items():
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))
    rows = {r["round"]: r for r in bt.scan(str(tmp_path))}
    assert rows[1]["status"] == "invalid"
    assert rows[2]["status"] == "invalid" and rows[2]["mfu"] is None
    assert rows[3]["status"] == "skipped"
    assert rows[4]["status"] == "stale"
    assert rows[5]["status"] == "valid" and rows[5]["mfu"] == 31.0
    table = bt.render(sorted(rows.values(), key=lambda r: r["round"]))
    assert "Best verified MFU: **31.00%**" in table
    doc = tmp_path / "PERF.md"
    bt.splice(str(doc), table)
    text = doc.read_text()
    assert bt.BEGIN in text and bt.END in text
    # splice is idempotent: re-running replaces, not appends
    bt.splice(str(doc), table)
    assert doc.read_text().count(bt.BEGIN) == 1


def test_bench_trend_multichip_classification(tmp_path):
    """tools/bench_trend.py MULTICHIP trajectory: legacy replica-loop
    dryruns render as structure-only rows, failed/skipped rounds are
    never evidence, and the SPMD points table labels tolerance-gated
    parity honestly (never as bit-exact)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_trend as bt
    finally:
        sys.path.pop(0)
    rounds = {
        1: {"n_devices": 8, "rc": 1, "ok": False},          # failed dryrun
        2: {"n_devices": 8, "rc": 0, "ok": True},           # legacy dryrun
        3: {"round": 3, "ok": False, "skipped": False, "value": None,
            "points": [], "errors": ["devices=8: boom"]},   # failed SPMD
        4: {"round": 4, "ok": True, "skipped": False, "value": 1.0,
            "tag": "spmd", "timing_evidence": False,
            "points": [
                {"devices": 1, "mesh": {"dp": 1}, "step_ms": 2.0,
                 "dispatches_per_step": 1.0, "speedup_vs_1dev": 1.0,
                 "parity_ok": True, "parity_kind": "bitexact"},
                # legacy key (pre-rename artifacts): renders the same
                {"devices": 8, "mesh": {"dp": 4, "tp": 2}, "step_ms": 6.0,
                 "dispatches_per_step": 1.0, "scaling_efficiency": 0.33,
                 "parity_ok": True, "parity_kind": "tolerance"},
            ]},
    }
    for n, rec in rounds.items():
        (tmp_path / f"MULTICHIP_r{n:02d}.json").write_text(json.dumps(rec))
    rows = {r["round"]: r for r in bt.scan_multichip(str(tmp_path))}
    assert rows[1]["status"] == "invalid"
    assert rows[2]["status"] == "legacy" and not rows[2]["points"]
    assert rows[3]["status"] == "invalid"
    assert rows[4]["status"] == "valid" and len(rows[4]["points"]) == 2
    table = bt.render_multichip(
        sorted(rows.values(), key=lambda r: r["round"]))
    lines = table.splitlines()
    dp_row = next(l for l in lines if "1 (dp1)" in l)
    tp_row = next(l for l in lines if "8 (dp4×tp2)" in l)
    assert "bit-exact" in dp_row
    assert "tol" in tp_row and "bit-exact" not in tp_row
    assert "structure evidence only" in table
    assert "1.0 dispatch/step" in table
    doc = tmp_path / "PERF.md"
    bt.splice(str(doc), table, begin=bt.MC_BEGIN, end=bt.MC_END,
              heading=bt.MC_HEADING)
    bt.splice(str(doc), table, begin=bt.MC_BEGIN, end=bt.MC_END,
              heading=bt.MC_HEADING)
    assert doc.read_text().count(bt.MC_BEGIN) == 1


def test_rollup_library_diff_report(tmp_path):
    """observability.rollup: per-op-family attribution + the A/B diff
    report perf levers are judged on (device-lane only, scan wrapper
    excluded)."""
    import gzip
    from mxnet_tpu.observability import rollup as ru

    def capture(d, fusion_us, conv_us):
        events = [
            {"ph": "M", "name": "process_name", "pid": 7, "tid": 0,
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "name": "thread_name", "pid": 7, "tid": 1,
             "args": {"name": "XLA Ops"}},
            {"ph": "M", "name": "process_name", "pid": 9, "tid": 0,
             "args": {"name": "Host threads"}},
            # host lane noise that must NOT count
            {"ph": "X", "name": "fusion.999", "pid": 9, "tid": 1,
             "ts": 0, "dur": 10 ** 6},
            # scan wrapper double-counts its body: excluded
            {"ph": "X", "name": "while.3", "pid": 7, "tid": 1,
             "ts": 0, "dur": 10 ** 6},
            {"ph": "X", "name": "fusion.12", "pid": 7, "tid": 1,
             "ts": 0, "dur": fusion_us},
            {"ph": "X", "name": "fusion.7", "pid": 7, "tid": 1,
             "ts": 5, "dur": fusion_us},
            {"ph": "X", "name": "convolution.2", "pid": 7, "tid": 1,
             "ts": 9, "dur": conv_us},
        ]
        p = os.path.join(d, "x.trace.json.gz")
        os.makedirs(d, exist_ok=True)
        with gzip.open(p, "wt") as f:
            json.dump({"traceEvents": events}, f)
        return d

    a = capture(str(tmp_path / "a"), 1000, 4000)
    b = capture(str(tmp_path / "b"), 1000, 2000)
    fam, total = ru.rollup(a)
    assert fam["fusion"] == 2000 and fam["convolution"] == 4000
    assert total == 6000
    report = ru.diff(a, b, steps=50)
    assert report["families"][0]["family"] == "convolution"
    assert report["total_delta_ms_per_step"] == pytest.approx(-0.04)
    assert "convolution" in ru.format_diff(report)
    s = ru.summary(b, steps=50)
    assert s["device_ms_per_step"] == pytest.approx(4000 / 1e3 / 50)
    assert {f["family"] for f in s["families"]} == \
        {"fusion", "convolution"}
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    with pytest.raises(ru.RollupError):
        ru.rollup(empty)                # no trace file anywhere under it
    host_only = str(tmp_path / "h")
    os.makedirs(host_only)
    import gzip as _g
    with _g.open(os.path.join(host_only, "h.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
             "args": {"name": "Host threads"}}]}, f)
    with pytest.raises(ru.RollupError):
        ru.rollup(host_only)            # not a TPU device capture
