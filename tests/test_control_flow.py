"""Control-flow ops: foreach / while_loop / cond.

Reference: src/operator/control_flow.cc (:1089/:1150/:1211) +
python/mxnet/ndarray/contrib.py; grads are pinned against unrolled
eager loops, the reference's own test strategy
(tests/python/unittest/test_contrib_control_flow.py).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.autograd as ag


def test_foreach_matches_unrolled_forward_and_grad():
    rng = np.random.RandomState(0)
    data_np = rng.randn(5, 3).astype(np.float32)
    init_np = rng.randn(3).astype(np.float32)

    def body(x, state):
        s = state[0] * 0.9 + x * x
        return s * 2.0, [s]

    # scan path
    data, init = nd.array(data_np), nd.array(init_np)
    data.attach_grad()
    init.attach_grad()
    with ag.record():
        outs, final = nd.contrib.foreach(body, data, [init])
        loss = outs.sum() + final[0].sum()
    loss.backward()
    g_data, g_init = data.grad.asnumpy(), init.grad.asnumpy()

    # unrolled oracle
    data2, init2 = nd.array(data_np), nd.array(init_np)
    data2.attach_grad()
    init2.attach_grad()
    with ag.record():
        s = init2
        tot = None
        for t in range(5):
            o, (s,) = body(data2[t], [s])
            tot = o.sum() if tot is None else tot + o.sum()
        loss2 = tot + s.sum()
    loss2.backward()

    np.testing.assert_allclose(float(loss.asnumpy()),
                               float(loss2.asnumpy()), rtol=1e-5)
    np.testing.assert_allclose(g_data, data2.grad.asnumpy(), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(g_init, init2.grad.asnumpy(), rtol=1e-4,
                               atol=1e-5)
    assert outs.shape == (5, 3)


def test_foreach_multiple_data_and_outputs():
    rng = np.random.RandomState(1)
    a = nd.array(rng.randn(4, 2).astype(np.float32))
    b = nd.array(rng.randn(4, 2).astype(np.float32))
    s0 = nd.array(np.zeros(2, np.float32))

    def body(xs, states):
        x, y = xs
        s = states[0] + x * y
        return [x + y, s * 1.0], [s]

    (o1, o2), [fs] = nd.contrib.foreach(body, [a, b], [s0])
    an, bn = a.asnumpy(), b.asnumpy()
    np.testing.assert_allclose(o1.asnumpy(), an + bn, rtol=1e-6)
    np.testing.assert_allclose(o2.asnumpy(), np.cumsum(an * bn, axis=0),
                               rtol=1e-5)
    np.testing.assert_allclose(fs.asnumpy(), (an * bn).sum(0), rtol=1e-5)


def test_while_loop_matches_python_loop():
    def cond_fn(i, s):
        return i < 5

    def func(i, s):
        return (s + i), (i + 1, s + i)

    i0 = nd.array(np.array(0.0, np.float32))
    s0 = nd.array(np.array(1.0, np.float32))
    outs, (fi, fs) = nd.contrib.while_loop(cond_fn, func, [i0, s0],
                                           max_iterations=8)
    # python oracle
    i, s, ys = 0.0, 1.0, []
    while i < 5:
        ys.append(s + i)
        i, s = i + 1, s + i
    np.testing.assert_allclose(float(fi.asnumpy()), i)
    np.testing.assert_allclose(float(fs.asnumpy()), s)
    o = outs.asnumpy()
    np.testing.assert_allclose(o[:len(ys)], ys, rtol=1e-6)
    np.testing.assert_allclose(o[len(ys):], 0.0)   # zero-filled tail


def test_while_loop_grads():
    x0 = nd.array(np.array([2.0, 3.0], np.float32))
    x0.attach_grad()

    def cond_fn(x, t):
        return t < 3

    def func(x, t):
        return x * 0.0, (x * x * 0.1 + x, t + 1)

    with ag.record():
        _, (xf, _) = nd.contrib.while_loop(
            cond_fn, func, [x0, nd.array(np.array(0.0, np.float32))],
            max_iterations=5)
        loss = xf.sum()
    loss.backward()

    # numeric gradient oracle
    def f(v):
        x = v.copy()
        for _ in range(3):
            x = x * x * 0.1 + x
        return x.sum()
    eps = 1e-3
    num = np.zeros(2)
    base = np.array([2.0, 3.0])
    for j in range(2):
        p, m = base.copy(), base.copy()
        p[j] += eps
        m[j] -= eps
        num[j] = (f(p) - f(m)) / (2 * eps)
    np.testing.assert_allclose(x0.grad.asnumpy(), num, rtol=1e-3)


@pytest.mark.parametrize("branch", [True, False])
def test_cond_forward_and_grad(branch):
    x = nd.array(np.array([1.0, -2.0], np.float32))
    x.attach_grad()
    flag = nd.array(np.array(1.0 if branch else -1.0, np.float32))

    with ag.record():
        out = nd.contrib.cond(
            lambda a, f: (f > 0),
            lambda a, f: a * 3.0,
            lambda a, f: a * a,
            [x, flag])
        loss = out.sum()
    loss.backward()
    if branch:
        np.testing.assert_allclose(out.asnumpy(), [3.0, -6.0])
        np.testing.assert_allclose(x.grad.asnumpy(), [3.0, 3.0])
    else:
        np.testing.assert_allclose(out.asnumpy(), [1.0, 4.0])
        np.testing.assert_allclose(x.grad.asnumpy(), [2.0, -4.0])


def test_foreach_inside_hybridized_block():
    """Control flow must compile inside a jitted (hybridized) block —
    the scan stays a scan, not an unrolled trace."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class ScanNet(nn.HybridSequential):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.proj = nn.Dense(4, flatten=False)

        def forward(self, x):
            h = self.proj(x)                     # (B, T, 4)
            ht = h.transpose((1, 0, 2))          # (T, B, 4)

            def body(xt, states):
                s = states[0] + xt.tanh()
                return s, [s]

            outs, _ = nd.contrib.foreach(
                body, ht, [nd.zeros((h.shape[0], 4))])
            return outs[-1]

    mx.random.seed(0)
    net = ScanNet()
    net.initialize()
    x = nd.array(np.random.RandomState(0).randn(2, 6, 3))
    with ag.pause():
        eager = net(x).asnumpy()
    net.hybridize()
    with ag.pause():
        jitted = net(x).asnumpy()
        jitted2 = net(x).asnumpy()   # second call: cache hit
    np.testing.assert_allclose(jitted, eager, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(jitted2, eager, rtol=1e-5, atol=1e-6)


def test_foreach_stateless():
    """init_states=None runs a stateless loop (review finding r3)."""
    import numpy as np
    from mxnet_tpu import nd
    data = nd.array(np.arange(6.0).reshape(3, 2))
    outs, states = nd.contrib.foreach(lambda x, s: (x * 2, s), data, None)
    np.testing.assert_allclose(outs.asnumpy(),
                               np.arange(6.0).reshape(3, 2) * 2)
    assert states is None
