"""mx.rtc — runtime Pallas kernel registration (reference: rtc.py
CudaModule/CudaKernel; here Pallas is the runtime-compiled kernel
path).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
import mxnet_tpu.autograd as ag


def test_register_and_run_pallas_op():
    from jax.experimental import pallas as pl  # noqa: F401

    def scale_add(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0 + y_ref[...]

    mx.rtc.register_pallas_op("rtc_scale_add", scale_add)
    a = nd.array(np.arange(8, dtype=np.float32).reshape(2, 4))
    b = nd.array(np.ones((2, 4), np.float32))
    out = nd.rtc_scale_add(a, b)
    np.testing.assert_allclose(out.asnumpy(), a.asnumpy() * 2 + 1)


def test_registered_kernel_is_differentiable():
    def sq(x_ref, o_ref):
        o_ref[...] = x_ref[...] * x_ref[...]

    mx.rtc.register_pallas_op("rtc_square", sq,
                              reference_fn=lambda x: x * x)
    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    x.attach_grad()
    with ag.record():
        y = nd.rtc_square(x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 4.0, 6.0])


def test_custom_out_shape():
    import jax.numpy as jnp

    def rowsum(x_ref, o_ref):
        o_ref[...] = jnp.sum(x_ref[...], axis=1)

    mx.rtc.register_pallas_op(
        "rtc_rowsum", rowsum,
        out_shape=lambda shapes, dtypes: ((shapes[0][0],), dtypes[0]))
    x = nd.array(np.ones((3, 5), np.float32))
    np.testing.assert_allclose(nd.rtc_rowsum(x).asnumpy(), [5, 5, 5])


def test_cuda_module_points_to_pallas():
    with pytest.raises(NotImplementedError, match="[Pp]allas"):
        mx.rtc.CudaModule("__global__ void k() {}")
