"""Zero-downtime fleet (ISSUE 16): FleetRouter atomic weight hot-swap,
per-tenant quotas + priority lanes, quiesce()/resume(), the
fine-tune->publish loop, and the fleet chaos matrix.

The invariants pinned here:

- zero in-flight loss across a hot-swap under load: every submitted
  Future resolves served / shed / evicted-typed, the partition sums to
  the number submitted, the swap performs ZERO XLA compiles (the chat
  builder reuses the decoder model object, so published weights enter
  the cached programs as traced arguments), and post-swap responses
  are bit-exact vs the eager reference over the new weights;
- crash-anywhere consistency: an InjectedCrash at any publish phase
  before the handover commit rolls BACK (old version serving,
  admission resumed, half-published replica invisible); after it rolls
  FORWARD (new version serving, old replica retired typed);
- quota isolation: the greedy tenant alone degrades to typed
  ``Overloaded(reason="quota")``; the batch lane depth-caps without
  touching interactive traffic.

Budget discipline: ONE module-scoped kit owns the TinyDecoder and the
shared jitted matmul — the first server of each kind pays the compile
cost once, and every later server/router build in the module reuses
the cached programs compile-free. The fast gate keeps a single
kill-mid-swap row and the quota/lane tests; the full crash-at-every-
phase matrix, bounded-drain eviction, and the FleetRouter load replay
are ``@pytest.mark.slow``.
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mxnet_tpu import deploy, serving  # noqa: E402
from mxnet_tpu.serving import (  # noqa: E402
    DeadlineExceededError, Overloaded, SequenceEvictedError,
    ServerClosed)
from mxnet_tpu.serving.llm import (  # noqa: E402
    TinyDecoder, DecoderConfig, LLMServer, greedy_decode_reference)
from mxnet_tpu.resilience import faults  # noqa: E402
from mxnet_tpu.resilience.faults import InjectedCrash  # noqa: E402
from mxnet_tpu.observability import get_registry  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB, BS, CTX, DIM = 17, 8, 32, 4


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


def _expo():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from metrics_dump import parse_exposition
    finally:
        sys.path.pop(0)
    return parse_exposition(get_registry().expose())


class Kit:
    """Module-scoped warm kit: one decoder model object + one shared
    jitted matmul. Every server built through these factories hits the
    programs the first build compiled — hot-swap warmups and fresh
    per-test routers cost zero compiles."""

    def __init__(self):
        import jax
        import jax.numpy as jnp
        self.model = TinyDecoder(DecoderConfig(
            vocab_size=VOCAB, d_model=16, num_layers=1, num_heads=2,
            d_ff=32, max_context=CTX))
        self.params1 = self.model.init_params(0)
        self.params2 = self.model.init_params(1)
        self.rank_jit = jax.jit(lambda w, b: jnp.tanh(b @ w))
        self.dense_jit = jax.jit(lambda w, b, x: jnp.tanh(x @ w.T + b))
        self.w1 = np.random.RandomState(7).randn(DIM, DIM) \
            .astype(np.float32)

    def ref(self, params, prompt, n):
        return greedy_decode_reference(self.model, params, prompt, n)

    # publish() hands builders the FLAT checkpoint array dict; the
    # chat builder restores the decoder pytree from it
    def chat_builder(self, name):
        def build(arrays):
            return LLMServer(self.model,
                             deploy.unflatten_params(arrays),
                             name=name, max_seqs=2, block_size=BS,
                             max_context=CTX)
        return build

    def rank_builder(self, name):
        def build(arrays):
            w = np.asarray(arrays["w"], np.float32)
            return serving.ModelServer(
                lambda batch: np.asarray(self.rank_jit(w, batch)),
                buckets=[1, 2], max_delay_ms=1.0, item_shape=(DIM,),
                dtype="float32", name=name)
        return build

    def chat_router(self, tag, **router_kw):
        build = self.chat_builder(f"fc_{tag}")
        srv = build(deploy.flatten_params(self.params1))
        srv.warmup()
        srv.start()
        router = serving.FleetRouter(name=f"fleet_{tag}", **router_kw)
        router.add_model("chat", srv, version=1, builder=build)
        return router

    def rank_router(self, tag, **router_kw):
        build = self.rank_builder(f"fr_{tag}")
        srv = build({"w": self.w1})
        srv.warmup()
        srv.start()
        router = serving.FleetRouter(name=f"fleet_{tag}", **router_kw)
        router.add_model("rank", srv, version=1, builder=build)
        return router


@pytest.fixture(scope="module")
def kit():
    return Kit()


# ------------------------------------------------- quiesce / resume --
def test_quiesce_resume_model_server(kit):
    """quiesce() is distinct from close(): admission pauses TYPED,
    running work finishes, resume() reopens — nothing torn down."""
    srv = kit.rank_builder("fq1")({"w": kit.w1})
    srv.warmup()
    srv.start()
    x = np.ones(DIM, np.float32)
    gate = faults.block_at("serving.dispatch")
    f1 = srv.submit(x)
    assert gate.wait_reached(30)
    # in-flight work pending -> a bounded quiesce reports not-drained
    assert srv.quiesce(timeout=0.2) is False
    assert not srv.admitting
    with pytest.raises(ServerClosed, match="quiesced"):
        srv.submit(x)
    gate.release()
    assert srv.quiesce(timeout=30) is True
    np.testing.assert_allclose(f1.result(timeout=30),
                               np.tanh(x @ kit.w1), rtol=1e-5)
    srv.resume()
    assert srv.admitting
    np.testing.assert_allclose(srv.submit(x).result(timeout=30),
                               np.tanh(x @ kit.w1), rtol=1e-5)
    srv.shutdown()


@pytest.mark.slow   # ~12s on 1 CPU (tier-1 budget); the ModelServer
# quiesce test above and the hot-swap drain (publish runs quiesce/
# resume on the LLM path) keep fast coverage
def test_quiesce_resume_llm_server(kit):
    srv = kit.chat_builder("fq2")(deploy.flatten_params(kit.params1))
    srv.warmup()
    srv.start()
    gate = faults.block_at("llm.decode")
    f1 = srv.submit([1, 2, 3], 4)
    assert gate.wait_reached(30)
    assert srv.quiesce(timeout=0.2) is False
    with pytest.raises(ServerClosed, match="quiesced"):
        srv.submit([1], 1)
    gate.release()
    assert srv.quiesce(timeout=60) is True
    assert f1.result(timeout=30).tokens == kit.ref(kit.params1,
                                                   [1, 2, 3], 4)
    srv.resume()
    assert srv.admitting
    assert srv.submit([2, 3], 2).result(timeout=30).tokens \
        == kit.ref(kit.params1, [2, 3], 2)
    srv.shutdown()


# ------------------------------------------------ hot-swap fast gate --
def test_hot_swap_zero_loss_bitexact(kit):
    """The tentpole invariant: publish v2 while concurrent traffic
    streams in — zero compiles, zero unresolved Futures, the typed
    partition sums exactly, and post-swap tokens are bit-exact vs the
    eager reference over the NEW weights."""
    router = kit.chat_router("swap")
    prompts = [[(i % (VOCAB - 1)) + 1, ((i + 3) % (VOCAB - 1)) + 1]
               for i in range(24)]
    futs, errs = [], []
    outcomes = dict.fromkeys(("served", "shed", "evicted", "expired"),
                             0)
    olock = threading.Lock()

    def pump(k):
        for i in range(k, len(prompts), 2):
            try:
                fut = router.submit(
                    "chat", prompts[i], 4, tenant=f"t{i % 3}")
                with olock:
                    futs.append(fut)
            except Overloaded:              # typed shed at admission
                with olock:
                    outcomes["shed"] += 1
            except Exception as exc:        # pragma: no cover
                errs.append(exc)
            time.sleep(0.02)

    threads = [threading.Thread(target=pump, args=(k,))
               for k in range(2)]
    with serving.CompileCounter() as cc:
        for th in threads:
            th.start()
        time.sleep(0.05)
        assert router.publish(
            "chat", 2,
            arrays=deploy.flatten_params(kit.params2)) == 2
        for th in threads:
            th.join()
        for f in futs:
            try:
                f.result(timeout=60)
                outcomes["served"] += 1
            except SequenceEvictedError:
                outcomes["evicted"] += 1
            except Overloaded:
                outcomes["shed"] += 1
            except DeadlineExceededError:
                outcomes["expired"] += 1
    assert cc.count == 0, f"{cc.count} recompiles during hot-swap"
    assert not errs, errs                  # no untyped submit failure
    # every request resolved TYPED: the partition covers all 24 exactly
    assert sum(outcomes.values()) == len(prompts)
    assert outcomes["served"] >= 1
    assert router.active_version("chat") == 2
    for p in prompts[:2]:
        assert router.generate("chat", p, 5, timeout=60).tokens \
            == kit.ref(kit.params2, p, 5)
    assert router.server("chat").engine.cache.check(live_block_ids=[])
    router.shutdown()


def test_kill_mid_swap_rolls_back(kit):
    """Fast chaos row: the publisher dies at the drain phase (after
    the new replica warmed, before the commit) — the old version keeps
    serving, admission resumes, the half-published replica is
    invisible, and the rolled_back outcome lands on the registry."""
    router = kit.chat_router("kill")
    old_srv = router.server("chat")
    faults.crash_at_point("fleet.publish:drain")
    f = router.submit("chat", [1, 2], 3)
    with pytest.raises(InjectedCrash):
        router.publish("chat", 2,
                       arrays=deploy.flatten_params(kit.params2))
    assert router.active_version("chat") == 1
    assert router.server("chat") is old_srv
    assert old_srv.admitting
    assert f.result(timeout=30).tokens == kit.ref(kit.params1,
                                                  [1, 2], 3)
    assert router.generate("chat", [3], 2, timeout=30).tokens \
        == kit.ref(kit.params1, [3], 2)
    samples = _expo()
    key = ("mxtpu_fleet_swap_total",
           (("fleet", "fleet_kill"), ("model", "chat"),
            ("outcome", "rolled_back"), ("phase", "drain")))
    assert samples.get(key) == 1
    router.shutdown()


# -------------------------------------------------- quotas and lanes --
def test_quota_shed_isolation(kit):
    """The greedy tenant ALONE degrades to typed Overloaded(quota);
    the polite tenant and untagged traffic are untouched."""
    router = kit.rank_router("quota", quota_rps=0.001, quota_burst=2)
    x = np.ones(DIM, np.float32)
    greedy = [router.submit("rank", x, tenant="greedy")
              for _ in range(2)]
    with pytest.raises(Overloaded) as ei:
        router.submit("rank", x, tenant="greedy")
    assert ei.value.reason == "quota"
    ok = [router.submit("rank", x, tenant="polite"),
          router.submit("rank", x)]
    for f in greedy + ok:
        np.testing.assert_allclose(f.result(timeout=30),
                                   np.tanh(x @ kit.w1), rtol=1e-5)
    samples = _expo()
    key = ("mxtpu_fleet_quota_shed_total",
           (("fleet", "fleet_quota"), ("tenant", "greedy")))
    assert samples.get(key) == 1
    router.shutdown()


def test_batch_lane_depth_cap(kit):
    """The batch lane depth-caps with typed Overloaded(lane_full);
    interactive traffic is unaffected by a saturated batch lane."""
    router = kit.rank_router("lane", batch_lane_depth=1)
    x = np.ones(DIM, np.float32)
    gate = faults.block_at("serving.dispatch")
    f1 = router.submit("rank", x, lane="batch")
    assert gate.wait_reached(30)
    with pytest.raises(Overloaded) as ei:
        router.submit("rank", x, lane="batch")
    assert ei.value.reason == "lane_full"
    f2 = router.submit("rank", x)               # interactive lane
    with pytest.raises(ValueError, match="unknown lane"):
        router.submit("rank", x, lane="bulk")
    gate.release()
    for f in (f1, f2):
        np.testing.assert_allclose(f.result(timeout=30),
                                   np.tanh(x @ kit.w1), rtol=1e-5)
    router.shutdown()


def test_route_poison_surfaces_typed(kit):
    """The fleet.route chaos site: a scripted upstream shed surfaces
    AS the scripted typed error; the next request routes normally."""
    router = kit.rank_router("poison")
    x = np.ones(DIM, np.float32)
    faults.script("fleet.route",
                  [Overloaded("injected upstream shed",
                              reason="quota")])
    with pytest.raises(Overloaded):
        router.submit("rank", x)
    np.testing.assert_allclose(
        router.generate("rank", x, timeout=30),
        np.tanh(x @ kit.w1), rtol=1e-5)
    router.shutdown()


# ------------------------------------------- fine-tune -> publish ----
def test_finetune_publish_loop(kit, tmp_path):
    """The continuous loop: CompiledTrainStep job -> sharded-manifest
    checkpoint -> auto-publish into the live router, training and
    serving on ONE metrics registry; the served output is bit-exact vs
    the trained weights after every round."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon import nn, Trainer
    import mxnet_tpu.autograd as ag
    from mxnet_tpu.resilience.checkpoint import latest_checkpoint
    from mxnet_tpu.serving.fleet import FineTunePublisher

    mx.random.seed(3)
    net = nn.Dense(DIM)
    net.initialize()
    with ag.pause(train_mode=False):
        net(nd.array(np.zeros((1, DIM), np.float32)))
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})
    loss = gluon.loss.L2Loss()
    step = tr.compile_step(lambda a, b: loss(net(a), b))
    rng = np.random.RandomState(5)
    X = rng.randn(8, DIM).astype(np.float32)
    Y = rng.randn(8, DIM).astype(np.float32)

    def get_arrays():
        return {k: p.data().asnumpy()
                for k, p in net.collect_params().items()}

    def build(arrays):
        wk = next(k for k in arrays if k.endswith("weight"))
        bk = next(k for k in arrays if k.endswith("bias"))
        w = np.asarray(arrays[wk], np.float32)
        b = np.asarray(arrays[bk], np.float32)
        return serving.ModelServer(
            lambda batch: np.asarray(kit.dense_jit(w, b, batch)),
            buckets=[1, 2], max_delay_ms=1.0, item_shape=(DIM,),
            dtype="float32", name="fleet_ft_m")

    srv = build(get_arrays())
    srv.warmup()
    srv.start()
    router = serving.FleetRouter(name="fleet_ft")
    router.add_model("m", srv, version=0, builder=build)
    pub = FineTunePublisher(router, "m", lambda: step(nd.array(X),
                                                      nd.array(Y)),
                            get_arrays, str(tmp_path),
                            steps_per_publish=2, num_shards=2,
                            version_start=1)
    assert pub.run(rounds=2) == 2
    assert pub.step == 4
    assert router.active_version("m") == 2
    # the serving weights ARE the step-4 training weights, bit-exact
    arrays = get_arrays()
    wk = next(k for k in arrays if k.endswith("weight"))
    bk = next(k for k in arrays if k.endswith("bias"))
    x = np.ones(DIM, np.float32)
    np.testing.assert_allclose(
        router.generate("m", x, timeout=30),
        np.tanh(x @ arrays[wk].T + arrays[bk]), rtol=1e-5, atol=1e-6)
    # the loop went through a SHARDED manifest commit
    ckpt_dir, _manifest = latest_checkpoint(str(tmp_path))
    assert ckpt_dir is not None
    assert any(f.startswith("shard-") for f in os.listdir(ckpt_dir))
    # one registry carries the step that trained the weights AND the
    # swap that started serving them
    samples = _expo()
    names = {n for n, _ in samples}
    assert "mxtpu_train_step_dispatch_total" in names
    key = ("mxtpu_fleet_swap_total",
           (("fleet", "fleet_ft"), ("model", "m"),
            ("outcome", "ok"), ("phase", "handover")))
    assert samples.get(key) == 2
    router.shutdown()


# ------------------------------------------------ slow: chaos matrix --
@pytest.mark.slow
def test_publish_crash_matrix(kit):
    """Crash at EVERY publish phase boundary (plus the route-flip/
    quiesce gap), with requests in flight: before the handover commit
    the fleet rolls back — v1 serving, admission open, every Future
    served bit-exact; after it (prune) the fleet rolls forward — v2
    serving, the old replica retired typed, KV accounting clean on
    both replicas."""
    router = kit.chat_router("matrix")
    pre_commit = ("fleet.publish:load", "fleet.publish:warm",
                  "fleet.publish:drain", "fleet.drain",
                  "fleet.publish:handover")
    for site in pre_commit:
        faults.reset()
        faults.crash_at_point(site)
        futs = [router.submit("chat", [1, 2], 3),
                router.submit("chat", [4], 2)]
        with pytest.raises(InjectedCrash):
            router.publish("chat", 2,
                           arrays=deploy.flatten_params(kit.params2))
        assert router.active_version("chat") == 1, site
        srv = router.server("chat")
        assert srv.admitting, site
        assert futs[0].result(timeout=60).tokens \
            == kit.ref(kit.params1, [1, 2], 3), site
        assert futs[1].result(timeout=60).tokens \
            == kit.ref(kit.params1, [4], 2), site
        assert router.generate("chat", [5], 2, timeout=60).tokens \
            == kit.ref(kit.params1, [5], 2), site
        assert srv.engine.cache.check(live_block_ids=[]), site

    # prune: the crash lands AFTER the commit -> roll forward
    faults.reset()
    faults.crash_at_point("fleet.publish:prune")
    old_srv = router.server("chat")
    with pytest.raises(InjectedCrash):
        router.publish("chat", 2,
                       arrays=deploy.flatten_params(kit.params2))
    assert router.active_version("chat") == 2
    new_srv = router.server("chat")
    assert new_srv is not old_srv
    assert router.generate("chat", [1, 2], 3, timeout=60).tokens \
        == kit.ref(kit.params2, [1, 2], 3)
    # the failure handler finished retiring the old replica
    assert not old_srv.admitting
    with pytest.raises(ServerClosed):
        old_srv.submit([1], 1)
    assert old_srv.engine.cache.check(live_block_ids=[])
    assert new_srv.engine.cache.check(live_block_ids=[])
    samples = _expo()
    rolled = {phase for (n, lbls) in samples
              if n == "mxtpu_fleet_swap_total"
              and dict(lbls).get("fleet") == "fleet_matrix"
              and dict(lbls).get("outcome") == "rolled_back"
              for phase in [dict(lbls)["phase"]]}
    assert rolled == {"load", "warm", "drain", "handover"}
    key = ("mxtpu_fleet_swap_total",
           (("fleet", "fleet_matrix"), ("model", "chat"),
            ("outcome", "failed"), ("phase", "prune")))
    assert samples.get(key) == 1
    router.shutdown()


@pytest.mark.slow
def test_bounded_drain_evicts_typed(kit):
    """A straggler that outlives the drain deadline resolves TYPED at
    prune — SequenceEvictedError with its partial tokens — while the
    swap still commits and the new version serves bit-exact."""
    router = kit.chat_router("evict")
    old_srv = router.server("chat")
    # slow every decode step so the straggler cannot finish inside the
    # publish window; reset before measuring the new replica
    faults.delay_at("llm.decode", 0.1)
    straggler = router.submit("chat", [1, 2], 28)
    time.sleep(0.3)
    with serving.CompileCounter() as cc:
        assert router.publish(
            "chat", 2, arrays=deploy.flatten_params(kit.params2),
            drain_timeout=0.05) == 2
    faults.reset()
    assert cc.count == 0
    with pytest.raises(SequenceEvictedError) as ei:
        straggler.result(timeout=60)
    assert isinstance(ei.value.tokens, list)    # partial generation
    assert router.active_version("chat") == 2
    assert router.generate("chat", [3], 2, timeout=60).tokens \
        == kit.ref(kit.params2, [3], 2)
    assert old_srv.engine.cache.check(live_block_ids=[])
    router.shutdown()


# --------------------------------------------- slow: fleet replay ----
@pytest.mark.slow
def test_fleet_replay_capacity(tmp_path):
    """tools/load_replay.py --fleet end to end in a clean process:
    seeded Zipf-tenant trace through the router, hot-swap mid-replay
    from a sharded checkpoint, and a capacity report that does NOT
    refuse itself — zero compiles, exact per-model partition, swap
    committed, per-model + fleet-total chips-per-M-users present."""
    import json
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "load_replay.py"),
         "--fleet", "--duration", "4", "--base-rps", "12",
         "--out", str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=560)
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-4000:]
    cap = json.loads((tmp_path / "CAPACITY_r01.json").read_text())
    assert not cap.get("skipped")
    assert cap["value"] and cap["value"] > 0
    assert cap["compiles_during_replay"] == 0
    assert cap["detail"]["swap"]["final_active_version"] == 2
    assert cap["detail"]["swap"]["sharded_checkpoint"] is True
    for model, oc in cap["outcomes"].items():
        assert oc["failed"] == 0, (model, oc)
    assert {fe["model"] for fe in cap["frontends"]} == {"chat", "rank"}
    for fe in cap["frontends"]:
        assert fe["chips_per_m_users"] > 0
        assert fe["availability"] == 1.0
