"""mx.rnn legacy cell API: step/unroll numerics vs the fused RNN op.

Reference: tests/python/unittest/test_rnn.py (test_rnn, test_lstm,
test_bidirectional, test_stack, ...) — the reference pins cell graphs
by consistency with FusedRNNCell; same strategy here: the unrolled cell
chain must match the lax.scan fused op given the same packed weights.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx
from mxnet_tpu import rnn


def _pack_lstm_params(iW, iB, hW, hB):
    """Flat vector in the fused op's layout: Wx, Wh then bx, bh."""
    return np.concatenate([iW.reshape(-1), hW.reshape(-1),
                           iB.reshape(-1), hB.reshape(-1)])


def test_lstm_cell_unroll_matches_fused():
    B, T, I, H = 3, 5, 4, 6
    rng = np.random.RandomState(0)
    iW = rng.randn(4 * H, I).astype(np.float32) * 0.3
    iB = rng.randn(4 * H).astype(np.float32) * 0.1
    hW = rng.randn(4 * H, H).astype(np.float32) * 0.3
    hB = rng.randn(4 * H).astype(np.float32) * 0.1
    x = rng.randn(B, T, I).astype(np.float32)

    cell = rnn.LSTMCell(H, forget_bias=0.0, prefix="l0_")
    data = mx.sym.var("data")
    out, _ = cell.unroll(T, data, layout="NTC", merge_outputs=True)
    got = out.eval_dict({"data": x, "l0_i2h_weight": iW, "l0_i2h_bias": iB,
                         "l0_h2h_weight": hW, "l0_h2h_bias": hB})
    got = (got[0] if isinstance(got, list) else got).asnumpy()

    fused = rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="f_")
    fdata = mx.sym.var("data")
    fout, _ = fused.unroll(T, fdata, layout="NTC")
    params = _pack_lstm_params(iW, iB, hW, hB)
    want = fout.eval_dict({"data": x, "f_parameters": params})
    want = (want[0] if isinstance(want, list) else want).asnumpy()

    assert got.shape == (B, T, H)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_gru_cell_unroll_matches_fused():
    B, T, I, H = 2, 4, 3, 5
    rng = np.random.RandomState(1)
    iW = rng.randn(3 * H, I).astype(np.float32) * 0.3
    iB = rng.randn(3 * H).astype(np.float32) * 0.1
    hW = rng.randn(3 * H, H).astype(np.float32) * 0.3
    hB = rng.randn(3 * H).astype(np.float32) * 0.1
    x = rng.randn(B, T, I).astype(np.float32)

    cell = rnn.GRUCell(H, prefix="g0_")
    out, _ = cell.unroll(T, mx.sym.var("data"), layout="NTC",
                         merge_outputs=True)
    got = out.eval_dict({"data": x, "g0_i2h_weight": iW, "g0_i2h_bias": iB,
                         "g0_h2h_weight": hW, "g0_h2h_bias": hB})
    got = (got[0] if isinstance(got, list) else got).asnumpy()

    fused = rnn.FusedRNNCell(H, num_layers=1, mode="gru", prefix="f_")
    fout, _ = fused.unroll(T, mx.sym.var("data"), layout="NTC")
    params = _pack_lstm_params(iW, iB, hW, hB)
    want = fout.eval_dict({"data": x, "f_parameters": params})
    want = (want[0] if isinstance(want, list) else want).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_rnn_cell_step_and_shapes():
    cell = rnn.RNNCell(8, prefix="r_")
    x = mx.sym.var("x")
    states = cell.begin_state(batch_size=2)
    out, next_states = cell(x, states)
    assert len(next_states) == 1
    res = out.eval_dict({
        "x": np.ones((2, 4), np.float32),
        "r_i2h_weight": np.ones((8, 4), np.float32) * 0.1,
        "r_i2h_bias": np.zeros(8, np.float32),
        "r_h2h_weight": np.ones((8, 8), np.float32) * 0.1,
        "r_h2h_bias": np.zeros(8, np.float32)})
    res = (res[0] if isinstance(res, list) else res).asnumpy()
    np.testing.assert_allclose(res, np.tanh(np.full((2, 8), 0.4)),
                               rtol=1e-6)


def test_sequential_stack_unrolls():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(6, prefix="s0_"))
    stack.add(rnn.LSTMCell(4, prefix="s1_"))
    out, states = stack.unroll(3, mx.sym.var("data"), layout="NTC",
                               merge_outputs=True)
    assert len(states) == 4  # two (h, c) pairs
    args = {n: np.random.RandomState(2).randn(*s).astype(np.float32) * 0.2
            for n, s in [("s0_i2h_weight", (24, 5)), ("s0_i2h_bias", (24,)),
                         ("s0_h2h_weight", (24, 6)), ("s0_h2h_bias", (24,)),
                         ("s1_i2h_weight", (16, 6)), ("s1_i2h_bias", (16,)),
                         ("s1_h2h_weight", (16, 4)), ("s1_h2h_bias", (16,))]}
    args["data"] = np.random.RandomState(3).randn(2, 3, 5).astype(np.float32)
    res = out.eval_dict(args)
    res = (res[0] if isinstance(res, list) else res).asnumpy()
    assert res.shape == (2, 3, 4)
    assert np.isfinite(res).all()


def test_bidirectional_concat_width():
    bi = rnn.BidirectionalCell(rnn.LSTMCell(5, prefix="fl_"),
                               rnn.LSTMCell(5, prefix="fr_"))
    out, states = bi.unroll(4, mx.sym.var("data"), layout="NTC",
                            merge_outputs=True)
    rng = np.random.RandomState(4)
    args = {"data": rng.randn(2, 4, 3).astype(np.float32)}
    for p in ("fl", "fr"):
        args[f"{p}_i2h_weight"] = rng.randn(20, 3).astype(np.float32) * 0.2
        args[f"{p}_i2h_bias"] = np.zeros(20, np.float32)
        args[f"{p}_h2h_weight"] = rng.randn(20, 5).astype(np.float32) * 0.2
        args[f"{p}_h2h_bias"] = np.zeros(20, np.float32)
    res = out.eval_dict(args)
    res = (res[0] if isinstance(res, list) else res).asnumpy()
    assert res.shape == (2, 4, 10)
    # the backward half at t=0 must depend on the LAST input: flip the
    # last timestep and check t=0's backward features change
    args2 = dict(args)
    flipped = args["data"].copy()
    flipped[:, -1] += 1.0
    args2["data"] = flipped
    res2 = out.eval_dict(args2)
    res2 = (res2[0] if isinstance(res2, list) else res2).asnumpy()
    assert not np.allclose(res[:, 0, 5:], res2[:, 0, 5:])
    assert np.allclose(res[:, 0, :5], res2[:, 0, :5])


def test_residual_and_dropout_cells():
    base = rnn.RNNCell(4, prefix="rb_")
    res_cell = rnn.ResidualCell(base)
    out, _ = res_cell.unroll(2, mx.sym.var("data"), layout="NTC",
                             merge_outputs=True)
    rng = np.random.RandomState(5)
    args = {"data": rng.randn(1, 2, 4).astype(np.float32),
            "rb_i2h_weight": np.zeros((4, 4), np.float32),
            "rb_i2h_bias": np.zeros(4, np.float32),
            "rb_h2h_weight": np.zeros((4, 4), np.float32),
            "rb_h2h_bias": np.zeros(4, np.float32)}
    got = out.eval_dict(args)
    got = (got[0] if isinstance(got, list) else got).asnumpy()
    # zero weights -> cell output 0 -> residual returns the input
    np.testing.assert_allclose(got, args["data"], atol=1e-6)

    d = rnn.DropoutCell(0.0)
    o, s = d(mx.sym.var("x"), [])
    assert s == []


def test_unfuse_matches_fused():
    B, T, I, H = 2, 3, 4, 5
    rng = np.random.RandomState(6)
    x = rng.randn(B, T, I).astype(np.float32)
    iW = rng.randn(4 * H, I).astype(np.float32) * 0.3
    iB = rng.randn(4 * H).astype(np.float32) * 0.1
    hW = rng.randn(4 * H, H).astype(np.float32) * 0.3
    hB = rng.randn(4 * H).astype(np.float32) * 0.1

    fused = rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="u_")
    fout, _ = fused.unroll(T, mx.sym.var("data"), layout="NTC")
    want = fout.eval_dict({"data": x, "u_parameters":
                           _pack_lstm_params(iW, iB, hW, hB)})
    want = (want[0] if isinstance(want, list) else want).asnumpy()

    stack = fused.unfuse()
    sout, _ = stack.unroll(T, mx.sym.var("data"), layout="NTC",
                           merge_outputs=True)
    got = sout.eval_dict({"data": x, "u_l0_i2h_weight": iW,
                          "u_l0_i2h_bias": iB, "u_l0_h2h_weight": hW,
                          "u_l0_h2h_bias": hB})
    got = (got[0] if isinstance(got, list) else got).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fused_get_next_state():
    B, T, I, H = 2, 4, 3, 5
    rng = np.random.RandomState(8)
    x = rng.randn(B, T, I).astype(np.float32)
    params = rng.randn(4 * H * (I + H) + 8 * H).astype(np.float32) * 0.2

    cell = rnn.FusedRNNCell(H, mode="lstm", prefix="n_",
                            get_next_state=True)
    out, states = cell.unroll(T, mx.sym.var("data"), layout="NTC")
    assert len(states) == 2
    feeds = {"data": x, "n_parameters": params}
    seq = out.eval_dict(feeds)
    seq = (seq[0] if isinstance(seq, list) else seq).asnumpy()
    hT = states[0].eval_dict(feeds)
    hT = (hT[0] if isinstance(hT, list) else hT).asnumpy()
    # final hidden state == last output step (single layer, unidir)
    np.testing.assert_allclose(hT[0], seq[:, -1], rtol=1e-5, atol=1e-6)

    # default: no state outputs (reference returns [])
    cell2 = rnn.FusedRNNCell(H, mode="lstm", prefix="n2_")
    _, states2 = cell2.unroll(T, mx.sym.var("data"), layout="NTC")
    assert states2 == []


def test_fused_unpack_pack_roundtrip():
    I, H = 4, 5
    rng = np.random.RandomState(9)
    flat = rng.randn(4 * H * (I + H) + 8 * H).astype(np.float32)
    cell = rnn.FusedRNNCell(H, mode="lstm", prefix="p_")
    args = cell.unpack_weights({"p_parameters": mx.nd.array(flat)})
    assert "p_parameters" not in args
    assert f"p_l0_i2h_i_weight" in args and args[
        "p_l0_i2h_i_weight"].shape == (H, I)
    assert args["p_l0_h2h_o_bias"].shape == (H,)
    packed = cell.pack_weights(args)
    np.testing.assert_allclose(packed["p_parameters"].asnumpy(), flat,
                               rtol=0, atol=0)
    # the unpacked blocks drive the unfused stack to the same numbers
    B, T = 2, 3
    x = rng.randn(B, T, I).astype(np.float32)
    fout, _ = cell.unroll(T, mx.sym.var("data"), layout="NTC")
    want = fout.eval_dict({"data": x, "p_parameters": flat})
    want = (want[0] if isinstance(want, list) else want).asnumpy()
    stack = cell.unfuse()
    merged = stack.pack_weights(dict(args))  # gate names -> block names
    feeds = {k: v.asnumpy() if hasattr(v, "asnumpy") else v
             for k, v in stack.unpack_weights(merged).items()}
    # unfused cells bind whole blocks: re-merge per cell
    blocks = stack.pack_weights(feeds)
    blocks = {k: (v.asnumpy() if hasattr(v, "asnumpy") else v)
              for k, v in blocks.items()}
    sout, _ = stack.unroll(T, mx.sym.var("data"), layout="NTC",
                           merge_outputs=True)
    got = sout.eval_dict(dict(blocks, data=x))
    got = (got[0] if isinstance(got, list) else got).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_lstm_forget_bias_in_initializer_not_forward():
    """forget_bias lives in the default i2h_bias initializer
    (init.LSTMBias), never in the forward pass: with identical explicit
    weights, cells built with different forget_bias settings must
    compute identical outputs — otherwise checkpoint-trained biases get
    the offset double-applied."""
    B, T, I, H = 2, 3, 4, 5
    rng = np.random.RandomState(11)
    args = {"data": rng.randn(B, T, I).astype(np.float32),
            "fb_i2h_weight": rng.randn(4 * H, I).astype(np.float32) * .3,
            "fb_i2h_bias": rng.randn(4 * H).astype(np.float32) * .1,
            "fb_h2h_weight": rng.randn(4 * H, H).astype(np.float32) * .3,
            "fb_h2h_bias": rng.randn(4 * H).astype(np.float32) * .1}
    outs = []
    for fb in (0.0, 1.0, 5.0):
        cell = rnn.LSTMCell(H, forget_bias=fb, prefix="fb_")
        out, _ = cell.unroll(T, mx.sym.var("data"), layout="NTC",
                             merge_outputs=True)
        got = out.eval_dict(dict(args))
        outs.append((got[0] if isinstance(got, list) else got).asnumpy())
    np.testing.assert_array_equal(outs[0], outs[1])
    np.testing.assert_array_equal(outs[0], outs[2])


def test_lstm_cell_default_init_sets_forget_bias():
    """Bind + init through Module: the i2h_bias variable's __init__
    attr (init.LSTMBias) must produce [0, forget_bias, 0, 0] gate
    blocks while other params follow the global initializer."""
    B, T, I, H = 2, 2, 3, 4
    cell = rnn.LSTMCell(H, forget_bias=1.5, prefix="mb_")
    out, _ = cell.unroll(T, mx.sym.var("data"), layout="NTC",
                         merge_outputs=True)
    assert out.attr_dict()["mb_i2h_bias"]["__init__"] == \
        mx.initializer.LSTMBias(forget_bias=1.5).dumps()
    mod = mx.module.Module(out, data_names=("data",), label_names=None)
    mod.bind(data_shapes=[("data", (B, T, I))], for_training=False)
    mod.init_params(mx.initializer.Uniform(0.1))
    args, _ = mod.get_params()
    b = args["mb_i2h_bias"].asnumpy()
    np.testing.assert_array_equal(b[H:2 * H], 1.5)
    np.testing.assert_array_equal(b[:H], 0.0)
    np.testing.assert_array_equal(b[2 * H:], 0.0)
    assert np.abs(args["mb_i2h_weight"].asnumpy()).max() <= 0.1


def test_fused_cell_default_init_sets_forget_bias():
    """FusedRNNCell's packed vector gets init.FusedRNN: forget-gate
    bias slices = forget_bias, other biases zero, weight blocks from
    the global initializer — so forget_bias is honored instead of
    silently ignored."""
    B, T, I, H = 2, 2, 3, 4
    cell = rnn.FusedRNNCell(H, mode="lstm", prefix="mf_",
                            forget_bias=2.0)
    out, _ = cell.unroll(T, mx.sym.var("data"), layout="NTC")
    mod = mx.module.Module(out, data_names=("data",), label_names=None)
    mod.bind(data_shapes=[("data", (B, T, I))], for_training=False)
    mod.init_params(mx.initializer.Uniform(0.1))
    args, _ = mod.get_params()
    un = cell.unpack_weights({"mf_parameters": args["mf_parameters"]})
    np.testing.assert_array_equal(un["mf_l0_i2h_f_bias"].asnumpy(), 2.0)
    np.testing.assert_array_equal(un["mf_l0_h2h_f_bias"].asnumpy(), 2.0)
    np.testing.assert_array_equal(un["mf_l0_i2h_i_bias"].asnumpy(), 0.0)
    w = un["mf_l0_i2h_i_weight"].asnumpy()
    assert 0.0 < np.abs(w).max() <= 0.1


def test_encode_sentences_fixed_vocab_guard():
    _, vocab = rnn.encode_sentences([["a", "b"]], invalid_label=0,
                                    start_label=1)
    with pytest.raises(ValueError):
        rnn.encode_sentences([["zzz"]], vocab=vocab)
    with pytest.raises(ValueError):
        rnn.encode_sentences([["zzz"]], vocab=vocab, unknown_token="<unk>")
    vocab["<unk>"] = max(vocab.values()) + 1
    enc, _ = rnn.encode_sentences([["zzz"]], vocab=vocab,
                                  unknown_token="<unk>")
    assert enc == [[vocab["<unk>"]]]


def test_topk_both_symbol_outputs():
    # regression: dynamic-nout resolution must not break topk ret_typ=both
    s = mx.sym.topk(mx.sym.var("x"), k=2, ret_typ="both", axis=-1)
    vals, idx = s
    x = np.array([[3.0, 1.0, 2.0]], np.float32)
    v = vals.eval_dict({"x": x})
    v = (v[0] if isinstance(v, list) else v).asnumpy()
    np.testing.assert_allclose(v, [[3.0, 2.0]])


def test_bucket_sentence_iter():
    rng = np.random.RandomState(7)
    sentences = [list(rng.randint(1, 20, size=rng.randint(2, 11)))
                 for _ in range(100)]
    it = rnn.BucketSentenceIter(sentences, batch_size=4, buckets=[5, 10],
                                invalid_label=0)
    assert it.default_bucket_key == 10
    seen = set()
    n = 0
    for batch in it:
        key = batch.bucket_key
        seen.add(key)
        assert batch.data[0].shape == (4, key)
        assert batch.label[0].shape == (4, key)
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        # label is data shifted left, invalid-padded
        np.testing.assert_array_equal(l[:, :-1][d[:, 1:] != 0],
                                      d[:, 1:][d[:, 1:] != 0])
        n += 1
    assert n >= 2 and seen == {5, 10}


def test_rnn_checkpoint_roundtrip(tmp_path):
    """save unpacked / load re-packed (reference: rnn.py:32,62)."""
    I, H = 3, 4
    rng = np.random.RandomState(10)
    flat = rng.randn(4 * H * (I + H) + 8 * H).astype(np.float32)
    cell = rnn.FusedRNNCell(H, mode="lstm", prefix="c_")
    out, _ = cell.unroll(2, mx.sym.var("data"), layout="NTC")
    prefix = str(tmp_path / "model")
    arg = {"c_parameters": mx.nd.array(flat)}
    rnn.save_rnn_checkpoint(cell, prefix, 3, out, arg, {})
    sym2, arg2, aux2 = rnn.load_rnn_checkpoint(cell, prefix, 3)
    np.testing.assert_allclose(arg2["c_parameters"].asnumpy(), flat,
                               atol=0)
    # on disk the params are per-gate (interchangeable with unfused)
    _, raw_args, _ = mx.model.load_checkpoint(prefix, 3)
    assert "c_parameters" not in raw_args
    assert "c_l0_i2h_i_weight" in raw_args


def test_begin_state_guards():
    cell = rnn.LSTMCell(4, prefix="bs_")
    with pytest.raises(ValueError):
        cell.begin_state(func=mx.sym.zeros)   # batch unknown -> (0, H)
    states = cell.begin_state(func=mx.sym.zeros, batch_size=3)
    assert len(states) == 2


def test_bucket_iter_empty_raises():
    with pytest.raises(ValueError):
        rnn.BucketSentenceIter([[1, 2]] * 3, batch_size=32, buckets=[1])


def test_encode_sentences():
    enc, vocab = rnn.encode_sentences([["a", "b"], ["b", "c"]],
                                      invalid_label=0, start_label=1)
    assert len(enc) == 2 and vocab["\n"] == 0
    dec = [[k for v2 in s for k, v in vocab.items() if v == v2]
           for s in enc]
    assert dec == [["a", "b"], ["b", "c"]]
