"""End-to-end training convergence tests.

Modeled on the reference's tests/python/train/ (test_mlp.py, test_conv.py):
small models trained on MNIST reach high accuracy. Here the MNIST dataset
falls back to a deterministic class-separable surrogate (no network egress)
of identical shapes — the training loop, data pipeline, autograd, and
optimizer stack are exercised end to end either way.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data.vision import transforms


def _lenet():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(
            nn.Conv2D(channels=6, kernel_size=5, padding=2,
                      activation="relu"),
            nn.MaxPool2D(pool_size=2, strides=2),
            nn.Conv2D(channels=16, kernel_size=5, activation="relu"),
            nn.MaxPool2D(pool_size=2, strides=2),
            nn.Flatten(),
            nn.Dense(120, activation="relu"),
            nn.Dense(84, activation="relu"),
            nn.Dense(10))
    return net


def _evaluate(net, loader):
    metric = mx.metric.Accuracy()
    for data, label in loader:
        out = net(data)
        metric.update([label], [out])
    return metric.get()[1]


@pytest.mark.slow   # ~19s on 1 CPU (tier-1 budget); mlp_mnist
# convergence below keeps a fast training-convergence gate
@pytest.mark.parametrize("hybridize", [True])
def test_lenet_mnist_convergence(hybridize):
    mx.random.seed(0)
    np.random.seed(0)
    transform = transforms.Compose([transforms.ToTensor()])
    train_ds = gluon.data.vision.MNIST(train=True).take(2048)\
        .transform_first(transform)
    test_ds = gluon.data.vision.MNIST(train=False).take(512)\
        .transform_first(transform)
    train_loader = gluon.data.DataLoader(train_ds, batch_size=64,
                                         shuffle=True)
    test_loader = gluon.data.DataLoader(test_ds, batch_size=128)

    net = _lenet()
    net.initialize(init=mx.initializer.Xavier())
    if hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(3):
        for data, label in train_loader:
            with mx.autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])

    acc = _evaluate(net, test_loader)
    assert acc > 0.90, f"LeNet failed to converge: test accuracy {acc}"


def test_mlp_mnist_convergence():
    # reference: tests/python/train/test_mlp.py
    mx.random.seed(0)
    np.random.seed(0)
    train_ds = gluon.data.vision.MNIST(train=True).take(1024)
    loader = gluon.data.DataLoader(
        train_ds.transform_first(lambda x: x.astype("float32") / 255.0),
        batch_size=128, shuffle=True)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(128, activation="relu"),
                nn.Dense(64, activation="relu"),
                nn.Dense(10))
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    first = last = None
    for epoch in range(4):
        total, count = 0.0, 0
        for data, label in loader:
            data = data.reshape((data.shape[0], -1))
            with mx.autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(data.shape[0])
            total += float(loss.mean().asscalar())
            count += 1
        avg = total / count
        if first is None:
            first = avg
        last = avg
    assert last < first * 0.5, f"MLP loss did not drop: {first} -> {last}"


def test_dataloader_multiworker_matches_serial():
    ds = gluon.data.ArrayDataset(np.arange(64).reshape(32, 2),
                                 np.arange(32))
    serial = [b[0].asnumpy() for b in
              gluon.data.DataLoader(ds, batch_size=8)]
    par = [b[0].asnumpy() for b in
           gluon.data.DataLoader(ds, batch_size=8, num_workers=2)]
    for a, b in zip(serial, par):
        np.testing.assert_array_equal(a, b)
