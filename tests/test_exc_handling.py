"""Exception propagation semantics.

Reference analogue: tests/python/unittest/test_exc_handling.py. The
reference's async engine defers kernel errors until WaitToRead, so it
tests that exceptions surface on wait. This framework's contract is
STRONGER and pinned here: shape/validity errors raise synchronously at
the call site (imperative) or at trace/compile time (hybridized), never
silently poisoning later reads — immutability + tracing remove the
deferred-failure window the reference had to test around.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon import nn
import mxnet_tpu.autograd as ag


def test_imperative_shape_error_raises_at_callsite():
    a = nd.array(np.ones((2, 3)))
    b = nd.array(np.ones((4, 5)))
    with pytest.raises(Exception):
        (a + b).asnumpy()


def test_dot_shape_error_is_synchronous():
    a = nd.array(np.ones((2, 3)))
    b = nd.array(np.ones((4, 5)))
    raised = False
    try:
        nd.dot(a, b)
    except Exception:
        raised = True
    assert raised, "mismatched dot must raise at the call site"


def test_hybridized_error_raises_at_first_call():
    net = nn.Dense(4, in_units=7, flatten=False)
    net.initialize()
    net.hybridize()
    with pytest.raises(Exception):
        net(nd.array(np.ones((2, 5))))     # wrong in_units


def test_custom_op_exception_propagates():
    """Errors inside a Python CustomOp callback must reach the caller
    (reference: test_exc_handling.py test_custom_op_exc)."""
    class Bad(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            raise RuntimeError("boom in custom op")

        def backward(self, req, out_grad, in_data, out_data, in_grad,
                     aux):
            pass

    @mx.operator.register("bad_op_exc_test")
    class BadProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return Bad()

    x = nd.array(np.ones((2, 2)))
    with pytest.raises(Exception, match="boom"):
        out = nd.Custom(x, op_type="bad_op_exc_test")
        out.asnumpy()                      # force execution


def test_backward_without_record_raises():
    x = nd.array(np.ones(3))
    x.attach_grad()
    y = (x * 2).sum()                      # computed OUTSIDE record()
    with pytest.raises(Exception):
        y.backward()


def test_error_does_not_poison_subsequent_ops():
    """After a failed op, the imperative frontend keeps working — the
    reference had to re-create executors after engine errors."""
    a = nd.array(np.ones((2, 3)))
    try:
        nd.dot(a, nd.array(np.ones((4, 5))))
    except Exception:
        pass
    out = (a * 3).asnumpy()
    np.testing.assert_allclose(out, 3.0)
