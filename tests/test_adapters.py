"""mxnet_tpu.serving.adapters: multi-LoRA serving (ISSUE 17).

The multi-adapter contract pinned here:

- a MIXED-adapter continuous batch (different adapters per row,
  including adapter-less base-model rows) is BIT-IDENTICAL, token for
  token, to per-adapter eager decoding with the same factors — one
  fixed-shape program serves every combination;
- the prefix cache is adapter-NAMESPACED: the same prompt under the
  same adapter hits, under a different adapter (or the base model)
  never cross-hits, and hits stay bit-exact;
- adapter churn — publish, serve, evict, registry fault-in, republish
  — compiles NOTHING after warmup (the backend_compile counter must
  not move);
- the AdapterBank survives a 1k-step randomized publish/acquire/
  release/evict storm against a shadow refcount model with its
  ``check()`` partition invariant intact throughout;
- a worker death with live shared adapters resolves every Future,
  settles every refcount to zero users and leaks no pages or blocks;
- ``FleetRouter.submit(..., adapter=...)`` plumbs through to the
  backing ``LLMServer`` untouched.

Tier-1 budget: ONE module-scoped warmed engine carries the parity,
prefix and churn tests; the chaos/fleet servers reuse the same model
object + geometry, so their warmups hit the model's program cache and
compile nothing. The speculative-decode parity sweep compiles a fresh
lora+spec program set and is marked slow.
"""
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.serving import ServerClosed  # noqa: E402
from mxnet_tpu.serving.llm import (  # noqa: E402
    TinyDecoder, DecoderConfig, LLMEngine, LLMServer, Sequence,
    greedy_decode_reference)
from mxnet_tpu.serving.adapters import (  # noqa: E402
    AdapterBank, AdapterRegistry, UnknownAdapterError,
    NoFreeAdapterPagesError, AdapterAccountingError)
from mxnet_tpu.resilience import faults  # noqa: E402

VOCAB = 17
BS = 8          # KV block size
CTX = 32   # small shapes: the module's one lora program set compiles fast
L = 2           # num_layers
D = 16          # d_model


@pytest.fixture(scope="module")
def model():
    return TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=D, num_layers=L, num_heads=2,
        d_ff=32, max_context=CTX))


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(seed=0)


def _factors(seed, rank, layers=L, d_model=D, scale=0.05):
    rng = np.random.RandomState(seed)
    a = (rng.randn(layers, 4, d_model, rank) * scale).astype(np.float32)
    b = (rng.randn(layers, 4, rank, d_model) * scale).astype(np.float32)
    return a, b


@pytest.fixture(scope="module")
def bank(tmp_path_factory):
    """Module bank: 'ada' (rank 4, one page), 'bob' (rank 8, two
    pages, explicit alpha), backed by an on-disk registry so capacity
    evictions can always fault adapters back in."""
    reg = AdapterRegistry(tmp_path_factory.mktemp("adapter_reg"),
                          num_shards=2)
    bk = AdapterBank(L, D, max_adapters=4, page_rank=4, registry=reg)
    a, b = _factors(1, 4)
    bk.publish("ada", a, b)
    a, b = _factors(2, 8)
    bk.publish("bob", a, b, alpha=4.0)
    return bk


@pytest.fixture(scope="module")
def engine(model, params, bank):
    """THE warmed engine every in-process test shares (tier-1 budget:
    one lora program set for the module)."""
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefix_cache=True,
                    adapter_bank=bank)
    eng.warmup()
    return eng


def _run_all(eng, seqs, stagger_from=None):
    """Drive seqs to completion; optionally inject the tail mid-run
    (staggered admission churns mixed-adapter batch composition)."""
    cut = len(seqs) if stagger_from is None else stagger_from
    for s in seqs[:cut]:
        eng.add(s)
    injected = cut
    steps = 0
    while eng.has_work() or injected < len(seqs):
        if injected < len(seqs) and (steps % 2 == 0
                                     or not eng.has_work()):
            eng.add(seqs[injected])
            injected += 1
        eng.step()
        steps += 1
        assert steps < 2000
    return steps


def _oracle(model, params, bank, prompt, n, adapter):
    lora = None if adapter is None else bank.adapter_arrays(adapter)
    return greedy_decode_reference(model, params, prompt, n, lora=lora)


# --------------------------------------- mixed-adapter bit-exactness --
def test_mixed_adapter_batch_bit_identical(model, params, bank, engine):
    """>= 8 sequences under 3 different adapters AND base-model rows
    (null page), ragged prompts, staggered admission: every token
    stream equals per-adapter eager decoding exactly, and the bank's
    accounting drains to zero users."""
    before = bank.stats()
    rng = np.random.RandomState(11)
    adapters = [None, "ada", "bob", None, "ada", "bob", "ada", None]
    cases = []
    for i, ad in enumerate(adapters):
        plen = (BS - 1, BS, BS + 1)[i] if i < 3 else int(
            rng.randint(1, 21))
        prompt = rng.randint(0, VOCAB, size=plen).tolist()
        cases.append((prompt, int(rng.randint(2, 9)), ad))
    seqs = [Sequence(p, n, adapter=ad) for p, n, ad in cases]
    _run_all(engine, seqs, stagger_from=4)
    for (prompt, n, ad), s in zip(cases, seqs):
        assert s.state == "finished"
        ref = _oracle(model, params, bank, prompt, n, ad)
        assert s.output_tokens() == ref, \
            f"seq {s.seq_id} (adapter {ad!r}) diverged"
    after = bank.stats()
    assert after["in_use"] == 0
    # one acquire per adapter-carrying admission, all from residency
    n_ad = sum(1 for ad in adapters if ad is not None)
    assert after["acquires"] - before["acquires"] == n_ad
    assert after["registry_loads"] == before["registry_loads"]
    assert bank.check()
    assert engine.cache.allocator.num_used == 0


# ------------------------------------ adapter-namespaced prefix cache --
def test_prefix_cache_is_adapter_namespaced(model, params, bank,
                                            engine):
    """Same prompt, four namespaces: a repeat under the SAME adapter
    hits the cache (bit-exact), the same prompt under a DIFFERENT
    adapter or the base model never cross-hits — the pinned
    (name, version) salts the block hash chain."""
    rng = np.random.RandomState(23)
    prompt = rng.randint(0, VOCAB, size=2 * BS + 1).tolist()

    # wave 1 seeds two namespaces (cold: zero hits)
    lk0, h0 = engine.prefix_lookups, engine.prefix_hits
    w1 = [Sequence(prompt, 5, adapter="ada"), Sequence(prompt, 5)]
    _run_all(engine, w1)
    assert engine.prefix_lookups == lk0 + 2
    assert engine.prefix_hits == h0

    # wave 2: same-namespace repeats hit, "bob" must not cross-hit
    w2 = [Sequence(prompt, 5, adapter="ada"),
          Sequence(prompt, 5, adapter="bob"),
          Sequence(prompt, 5)]
    _run_all(engine, w2)
    assert engine.prefix_lookups == lk0 + 5
    assert engine.prefix_hits == h0 + 2
    assert w2[0].cache_hit_tokens == 2 * BS      # ada @ ada: hit
    assert w2[1].cache_hit_tokens == 0           # bob: own namespace
    assert w2[2].cache_hit_tokens == 2 * BS      # base @ base: hit

    for s, ad in zip(w1 + w2, ["ada", None, "ada", "bob", None]):
        assert s.output_tokens() == _oracle(model, params, bank,
                                            prompt, 5, ad), \
            f"adapter {ad!r} (hit={s.cache_hit_tokens}) diverged"
    assert bank.stats()["in_use"] == 0 and bank.check()


# ----------------------------------------- zero-recompile churn pin ---
def test_adapter_churn_never_recompiles(model, params, bank, engine):
    """Publish a NEW adapter, serve it, evict it cold, fault it back
    in from the registry, republish a live name mid-flight — the
    backend_compile counter must not move once."""
    rng = np.random.RandomState(31)
    prompt = rng.randint(0, VOCAB, size=9).tolist()
    with serving.CompileCounter() as cc:
        bank.publish("cal", *_factors(3, 2))        # rank 2: tail-pad
        s = Sequence(prompt, 4, adapter="cal")
        _run_all(engine, [s])
        assert s.output_tokens() == _oracle(model, params, bank,
                                            prompt, 4, "cal")
        bank.evict("cal")                           # cold: evictable
        assert "cal" not in bank.names()
        loads0 = bank.stats()["registry_loads"]
        s2 = Sequence(prompt, 4, adapter="cal")     # registry fault-in
        _run_all(engine, [s2])
        assert bank.stats()["registry_loads"] == loads0 + 1
        v2 = bank.publish("ada", *_factors(41, 4))  # republish live name
        assert bank.resident_version("ada") == v2
        s3 = Sequence(prompt, 4, adapter="ada")     # serves v2
        _run_all(engine, [s3])
        assert s3.output_tokens() == _oracle(model, params, bank,
                                             prompt, 4, "ada")
    assert cc.count == 0, \
        f"{cc.count} XLA recompiles during adapter churn"
    assert bank.check()


# ------------------------------------------- engine-level poison path --
def test_unknown_adapter_poisons_without_leaking(model, params, bank,
                                                engine):
    """Server.submit validates names up front; a sequence that still
    reaches admission with an unknown adapter is poison-isolated —
    released typed, no KV blocks or adapter pins left behind."""
    st0 = bank.stats()
    s = Sequence([1, 2, 3], 4, adapter="ghost")
    engine.add(s)
    steps = 0
    while engine.has_work():
        engine.step()
        steps += 1
        assert steps < 50
    assert s.state == "evicted" and s.finish_reason == "poison"
    seq, exc = engine._poison_pending.pop()
    assert seq is s and isinstance(exc, UnknownAdapterError)
    st1 = bank.stats()
    assert st1["in_use"] == 0
    assert st1["pages_used"] == st0["pages_used"]
    assert engine.cache.allocator.num_used == 0


def test_submit_adapter_requires_bank(model, params):
    """adapter= against a bank-less server is a caller-thread
    ValueError before any engine work (the idle worker compiles
    nothing)."""
    srv = LLMServer(model, params, name="adapters_nobank", max_seqs=4,
                    block_size=BS, max_context=CTX, prefix_cache=True)
    srv.start()
    try:
        with pytest.raises(ValueError, match="no AdapterBank"):
            srv.submit([1, 2], 2, adapter="ada")
    finally:
        srv.shutdown(drain=False)


# --------------------------------------------- 1k-step bank fuzzing ---
class _ShadowFull(Exception):
    pass


class _ShadowBank:
    """Host-side replica of the AdapterBank's accounting — refcounts,
    page ownership AND the cold-LRU order (a publish under pressure
    capacity-evicts oldest-idle residents, so predicting exactly which
    names survive requires mirroring the LRU, not just counting)."""

    def __init__(self, pages_total):
        import collections
        self.pages_total = pages_total
        self.resident = {}     # name -> current version
        self.users = {}        # (name, version) -> in-flight pins
        self.npages = {}       # (name, version) -> pages (held while
        #                        current, or detached with users > 0)
        self.cold = collections.OrderedDict()   # oldest-idle first

    def free_pages(self):
        return self.pages_total - sum(self.npages.values())

    def retire(self, name):
        v = self.resident.pop(name)
        self.cold.pop(name, None)
        if self.users.get((name, v), 0) == 0:   # fully idle: pages back
            self.users.pop((name, v), None)
            self.npages.pop((name, v), None)
        # else: detached — pages drain with its last release

    def publish(self, name, need, version):
        old = self.resident.get(name)
        if old is not None and self.users.get((name, old), 0) == 0:
            self.retire(name)       # cold old version retires up front
            old = None
        while self.free_pages() < need:
            victim = next(iter(self.cold), None)
            if victim is None:      # NB: the cold LRU is already
                raise _ShadowFull   # drained at this point
            self.retire(victim)
        if old is not None:
            self.retire(name)       # live old version: detach
        self.resident[name] = version
        self.npages[(name, version)] = need
        self.users.setdefault((name, version), 0)
        self.cold[name] = None

    def acquire(self, name):
        v = self.resident[name]
        self.users[(name, v)] += 1
        self.cold.pop(name, None)
        return v

    def release(self, name, v):
        self.users[(name, v)] -= 1
        if self.users[(name, v)] == 0:
            if self.resident.get(name) == v:
                self.cold[name] = None          # most-recently idle
            else:                               # detached: drained
                self.users.pop((name, v))
                self.npages.pop((name, v))


def test_adapter_bank_fuzz_shadow_refcounts():
    """1000 randomized publish/acquire/release/evict steps against the
    shadow model on a deliberately tiny pool (3 adapters x 2 pages of
    rank 2): every typed error fires exactly when the shadow says it
    must, capacity evictions hit exactly the adapters the shadow LRU
    predicts, ``check()`` holds throughout, and the final drain
    returns every page."""
    rng = np.random.RandomState(7)
    dL, dD = 2, 8
    bk = AdapterBank(dL, dD, max_adapters=3, page_rank=2,
                     max_pages_per_adapter=2)
    sh = _ShadowBank(bk.stats()["pages_total"])
    names = [f"f{i}" for i in range(6)]
    live = []                        # (name, version, handle)

    for step in range(1000):
        op = int(rng.randint(4))
        if op == 0:                  # publish / republish
            name = names[int(rng.randint(len(names)))]
            rank = int(rng.randint(1, 5))
            a = (rng.randn(dL, 4, dD, rank) * 0.01).astype(np.float32)
            b = (rng.randn(dL, 4, rank, dD) * 0.01).astype(np.float32)
            need = -(-rank // 2)
            try:
                v = bk.publish(name, a, b, persist=False)
            except NoFreeAdapterPagesError:
                v = None
            # replay on the shadow: same evictions, same outcome —
            # a FAILED publish still drains the whole cold LRU (and a
            # cold old version of the name itself), a successful one
            # evicts exactly the oldest-idle residents it needed
            try:
                sh.publish(name, need, v)
                assert v is not None, \
                    f"step {step}: bank pool-full, shadow fits {need}"
            except _ShadowFull:
                assert v is None, \
                    f"step {step}: shadow pool-full, bank fit {need}"
        elif op == 1:                # acquire
            res = bk.names()
            if res:
                name = res[int(rng.randint(len(res)))]
                h = bk.acquire(name)
                assert h.version == sh.acquire(name)
                live.append((name, h.version, h))
            elif step % 7 == 0:      # no registry: typed unknown
                with pytest.raises(UnknownAdapterError):
                    bk.acquire("nope")
        elif op == 2:                # release a random pin
            if live:
                name, v, h = live.pop(int(rng.randint(len(live))))
                bk.release(h)
                sh.release(name, v)
        else:                        # evict
            res = bk.names()
            if res:
                name = res[int(rng.randint(len(res)))]
                v = sh.resident[name]
                if sh.users.get((name, v), 0) > 0:
                    with pytest.raises(AdapterAccountingError):
                        bk.evict(name)
                else:
                    bk.evict(name)
                    sh.retire(name)
            else:
                with pytest.raises(UnknownAdapterError):
                    bk.evict("f0")
        assert sorted(sh.resident) == bk.names(), f"step {step}"
        if step % 50 == 0:
            assert bk.check()
            st = bk.stats()
            assert st["resident"] == len(sh.resident)
            assert st["cold"] == len(sh.cold)
            assert st["in_use"] == sum(
                1 for n, v in sh.resident.items()
                if sh.users[(n, v)] > 0)
            assert st["detached"] == sum(
                1 for (n, v), u in sh.users.items()
                if u > 0 and sh.resident.get(n) != v)
            assert st["pages_used"] == sum(sh.npages.values())

    for name, v, h in live:          # drain: every pin released
        bk.release(h)
        sh.release(name, v)
    for name in bk.names():
        bk.evict(name)
    st = bk.stats()
    assert st["pages_used"] == 0 and st["resident"] == 0 \
        and st["detached"] == 0
    assert bk.check()


# ------------------------------------------------ chaos: worker death --
def test_worker_death_with_live_adapters_settles_refcounts(
        model, params, bank):
    """InjectedCrash mid-loop while adapter-carrying requests are in
    flight: every Future resolves typed, the shared bank's refcounts
    settle to zero users (no leaked pins, partition invariant holds)
    and the KV pool is clean. Same model + geometry as the module
    engine, so warmup compiles nothing."""
    srv = LLMServer(model, params, name="adapters_chaos", max_seqs=4,
                    block_size=BS, max_context=CTX, prefix_cache=True,
                    adapter_bank=bank)
    srv.warmup()
    srv.start()
    try:
        faults.crash_at_point("llm.worker", nth=2)
        futs = [srv.submit([1 + i, 2, 3], 10, adapter=ad)
                for i, ad in enumerate(["ada", "bob", None, "ada"])]
        for f in futs:
            try:
                f.result(timeout=30)
            except BaseException:
                pass                     # typed resolution is enough
    finally:
        faults.reset()
    deadline = time.monotonic() + 10
    while srv.running and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(ServerClosed):
        srv.submit([1], 1, adapter="ada")
    st = bank.stats()
    assert st["in_use"] == 0, "crash leaked adapter pins"
    assert st["detached"] == 0
    assert bank.check()
    assert srv.engine.cache.allocator.num_used == 0
    assert srv.engine.cache.check(live_block_ids=[])


# ------------------------------------------------ fleet plumb-through --
def test_fleet_router_plumbs_adapter_through(model, params, bank):
    """FleetRouter.submit(..., adapter=...) reaches the backing
    LLMServer untouched: routed generation matches the per-adapter
    oracle; unknown names fail typed at the router's front door."""
    srv = LLMServer(model, params, name="adapters_fleet", max_seqs=4,
                    block_size=BS, max_context=CTX, prefix_cache=True,
                    adapter_bank=bank)
    srv.warmup()
    srv.start()
    router = serving.FleetRouter(name="fleet_adapters")
    router.add_model("chat", srv, version=1)
    try:
        prompt = [3, 1, 4, 1, 5]
        out = router.generate("chat", prompt, 6, adapter="bob",
                              timeout=60, tenant="acme")
        assert out.tokens == _oracle(model, params, bank, prompt, 6,
                                     "bob")
        base = router.generate("chat", prompt, 6, timeout=60)
        assert base.tokens == _oracle(model, params, bank, prompt, 6,
                                      None)
        with pytest.raises(UnknownAdapterError):
            router.submit("chat", prompt, 2, adapter="ghost")
    finally:
        router.shutdown()
    assert bank.stats()["in_use"] == 0 and bank.check()


# ------------------------------------- speculative decoding (slow) ----
@pytest.mark.slow
def test_spec_decode_mixed_adapter_parity(model, params, bank):
    """Speculative decoding with a layer-truncated draft under a
    MIXED-adapter batch: the base-model draft proposes, the
    adapter-bearing target verifies, greedy acceptance keeps every
    stream identical to target-only decoding — so the per-adapter
    oracle still holds bit-exactly. (Fresh lora+spec program set:
    the module's one heavyweight compile, hence slow.)"""
    draft = TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=D, num_layers=1, num_heads=2,
        d_ff=32, max_context=CTX))
    draft_params = {k: (v if k != "layers" else list(v[:1]))
                    for k, v in params.items()}
    eng = LLMEngine(model, params, max_seqs=4, block_size=BS,
                    max_context=CTX, prefix_cache=True,
                    adapter_bank=bank, draft_model=draft,
                    draft_params=draft_params, spec_k=2)
    eng.warmup()
    rng = np.random.RandomState(47)
    cases = []
    for ad in ["ada", "bob", None, "ada"]:
        prompt = rng.randint(0, VOCAB,
                             size=int(rng.randint(3, 20))).tolist()
        cases.append((prompt, int(rng.randint(3, 9)), ad))
    seqs = [Sequence(p, n, adapter=ad) for p, n, ad in cases]
    _run_all(eng, seqs, stagger_from=2)
    for (prompt, n, ad), s in zip(cases, seqs):
        assert s.state == "finished"
        assert s.output_tokens() == _oracle(model, params, bank,
                                            prompt, n, ad), \
            f"spec-decode diverged under adapter {ad!r}"
    assert bank.stats()["in_use"] == 0 and bank.check()
    assert eng.cache.allocator.num_used == 0
