"""Registry-wide numeric-gradient sweep.

Closes the gap between the 114-case hand list (test_numeric_gradient.py)
and the full differentiable registry: every registered op with
``differentiable=True`` must be either
  (a) covered by the hand list,
  (b) covered by a template here (checked against finite differences), or
  (c) listed in EXCLUDED with a stated reason.
``test_registry_grad_coverage_is_total`` enforces the trichotomy, so a
newly registered differentiable op fails the suite until it is swept or
justified. (Reference practice: tests/python/unittest/test_operator.py
calls check_numeric_gradient per op, with the same kinds of exclusions —
loss layers whose backward is the loss gradient, STE estimators, RNG
ops.)
"""
import importlib.util
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd
from mxnet_tpu.ops import registry
from mxnet_tpu.test_utils import check_numeric_gradient

_spec = importlib.util.spec_from_file_location(
    "_tng", os.path.join(os.path.dirname(__file__),
                         "test_numeric_gradient.py"))
_tng = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_tng)
HAND_COVERED = {c[0] for c in _tng.ALL_CASES}


def _r(*shape, seed=0, scale=1.0, shift=0.0):
    return np.random.RandomState(seed).randn(*shape) * scale + shift


def _pos(*shape, seed=0, shift=1.0):
    return np.abs(_r(*shape, seed=seed)) + shift


def _spd(n, seed=0):
    a = _r(n, n, seed=seed)
    return a @ a.T + n * np.eye(n)


def _first(name):
    """Wrap a multi-output op: project only output[0]."""
    def f(*xs, **kw):
        return getattr(nd, name)(*xs, **kw)[0]
    return f


def _sum_outs(name):
    def f(*xs, **kw):
        outs = getattr(nd, name)(*xs, **kw)
        return sum(o.sum() for o in outs)
    return f


# --------------------------------------------------------------------------
# Templates: op -> (callable-or-name, inputs, kwargs, grad_inputs or None)
# Inputs stay tiny: numeric diff costs O(size) forward evals per case.
# --------------------------------------------------------------------------
T = {}


def case(name, inputs, kwargs=None, grad_inputs=None, op=None,
         rtol=1e-2, atol=1e-3, eps=1e-3):
    T[name] = (op or name, inputs, kwargs or {}, grad_inputs, rtol, atol,
               eps)


# scalar-arithmetic family (kwarg name: scalar)
for opname in ["_plus_scalar", "_minus_scalar", "_rminus_scalar",
               "_mul_scalar", "_div_scalar", "_maximum_scalar",
               "_minimum_scalar", "_hypot_scalar",
               "_scatter_plus_scalar", "_scatter_minus_scalar"]:
    case(opname, [_r(3, 4, shift=0.3)], {"scalar": 2.5})
case("_rdiv_scalar", [_pos(3, 4)], {"scalar": 2.5})
case("_power_scalar", [_pos(3, 4)], {"scalar": 2.5})
case("_rpower_scalar", [_r(3, 4, scale=0.5)], {"scalar": 2.5})
case("_mod_scalar", [_pos(3, 4, shift=0.6)], {"scalar": 2.5})
case("_rmod_scalar", [_pos(3, 4, shift=3.0)], {"scalar": 2.0})
case("_power", [_pos(3, 4), _r(3, 4, seed=1, scale=0.5)])
case("_mod", [_pos(3, 4, shift=5.0), _pos(3, 4, seed=1, shift=2.0)])
case("broadcast_mod", [_pos(3, 4, shift=5.0), _pos(1, 4, seed=1,
                                                   shift=2.0)])
case("_grad_add", [_r(3, 4), _r(3, 4, seed=1)])
case("_scatter_elemwise_div", [_r(3, 4), _pos(3, 4, seed=1)])
case("_npi_powerd", [_pos(3, 4), _pos(3, 4, seed=1, shift=0.5)])
# LoRA delta (serving/adapters fine-tune path): grads flow into x AND
# both low-rank factors
case("lora_delta", [_r(3, 4), _r(4, 2, seed=1, scale=0.5),
                    _r(2, 4, seed=2, scale=0.5)], {"alpha": 2.0})

# zero-slope-almost-everywhere rounders: both sides are 0 away from the
# jumps, so the check is meaningful (inputs kept off half-integers)
for opname in ["ceil", "floor", "fix", "rint", "round", "sign"]:
    case(opname, [_r(3, 4, shift=0.29)])

case("degrees", [_r(3, 4)])
case("radians", [_r(3, 4)])
case("digamma", [_pos(3, 4, shift=1.5)])
case("hard_sigmoid", [_r(3, 4, shift=0.3)])
case("_npx_relu", [_r(3, 4, shift=0.4)])
case("_npx_sigmoid", [_r(3, 4)])
case("Cast", [_r(3, 4)], {"dtype": "float32"})
case("moments", [_r(3, 4)], {"axes": (1,)}, op=_sum_outs("moments"))
case("nanprod", [_pos(2, 3, shift=0.5)], {"axis": 1})
case("_square_sum", [_r(3, 4)], {"axis": 1})
case("softmax_cross_entropy",
     [_r(3, 5), np.array([0.0, 2.0, 4.0])], grad_inputs=[0])

# shape/layout movers (gradient = inverse rearrangement)
case("broadcast_to", [_r(3, 1)], {"shape": (3, 4)})
case("broadcast_axes", [_r(3, 1)], {"axis": 1, "size": 4})
case("broadcast_like", [_r(3, 1), _r(3, 4, seed=1)], grad_inputs=[0])
case("reshape_like", [_r(3, 4), _r(2, 6, seed=1)], grad_inputs=[0])
case("_npx_reshape", [_r(3, 4)], {"newshape": (4, 3)})
case("space_to_depth", [_r(1, 2, 4, 4)], {"block_size": 2})
case("depth_to_space", [_r(1, 8, 2, 2)], {"block_size": 2})
case("slice_like", [_r(4, 5), _r(2, 3, seed=1)], grad_inputs=[0])
case("stack", [_r(2, 3), _r(2, 3, seed=1)], {"axis": 1})
case("Concat", [_r(2, 3), _r(2, 4, seed=1)], {"dim": 1})
case("_rnn_param_concat", [_r(4), _r(6, seed=1)], {"dim": 0})
case("ElementWiseSum", [_r(3, 4), _r(3, 4, seed=1), _r(3, 4, seed=2)])
case("SliceChannel", [_r(2, 6)], {"num_outputs": 3, "axis": 1},
     op=_sum_outs("SliceChannel"))
case("_split_v2", [_r(2, 6)], {"indices": (2, 4), "axis": 1},
     op=_sum_outs("_split_v2"))
case("Crop", [_r(1, 3, 6, 6)], {"h_w": (4, 4), "center_crop": True})
case("batch_take", [_r(3, 4), np.array([0.0, 2.0, 1.0])],
     grad_inputs=[0])
case("gather_nd", [_r(3, 4), np.array([[0.0, 2.0], [1.0, 3.0]])],
     grad_inputs=[0])
case("scatter_nd", [_r(2), np.array([[0.0, 1.0], [1.0, 2.0]])],
     {"shape": (3, 4)}, grad_inputs=[0])
case("_scatter_set_nd",
     [_r(3, 4), _r(2, seed=1), np.array([[0.0, 1.0], [1.0, 2.0]])],
     {"shape": (3, 4)}, grad_inputs=[0, 1])
case("_slice_assign", [_r(3, 4), _r(2, 2, seed=1)],
     {"begin": (0, 1), "end": (2, 3)}, grad_inputs=[0, 1])
case("_slice_assign_scalar", [_r(3, 4)],
     {"scalar": 1.5, "begin": (0, 1), "end": (2, 3)})
case("_contrib_index_copy",
     [_r(4, 3), np.array([1.0, 3.0]), _r(2, 3, seed=1)],
     grad_inputs=[0, 2])
case("_npi_boolean_mask_assign_scalar",
     [_r(3, 4), (np.arange(12).reshape(3, 4) % 3 == 0).astype(np.float32)],
     {"value": 1.5}, grad_inputs=[0])
case("_npi_where_lscalar",
     [(np.arange(12).reshape(3, 4) % 2).astype(np.float32), _r(3, 4)],
     {"scalar": 1.5}, grad_inputs=[1])
case("_npi_where_rscalar",
     [(np.arange(12).reshape(3, 4) % 2).astype(np.float32), _r(3, 4)],
     {"scalar": 1.5}, grad_inputs=[1])
case("_npi_tensordot_int_axes", [_r(2, 3), _r(3, 4, seed=1)],
     {"axes": 1})
case("_npi_matmul", [_r(2, 3, 4, scale=0.5), _r(2, 4, 2, seed=1,
                                                scale=0.5)])

# sorting/selection (permutation gradients; ties measure zero)
case("sort", [_r(3, 4)], {"axis": 1})

# sequence family (length input is integral -> data grad only)
case("SequenceLast", [_r(4, 2, 3), np.array([2.0, 4.0])],
     {"use_sequence_length": True}, grad_inputs=[0])
case("SequenceMask", [_r(4, 2, 3), np.array([2.0, 4.0])],
     {"use_sequence_length": True, "value": 0.0}, grad_inputs=[0])
case("SequenceReverse", [_r(4, 2, 3), np.array([2.0, 4.0])],
     {"use_sequence_length": True}, grad_inputs=[0])

# normalization / nn tail
# use_global_stats pins BN to the moving-stats path in BOTH the eager
# probe (inference mode) and the recorded pass — without it the numeric
# side evaluates inference BN while autograd differentiates batch-stats
# BN and the comparison is between two different functions
case("BatchNorm",
     [_r(2, 3, 4, 4), _pos(3), _r(3, seed=1), _r(3, seed=2, scale=0.3),
      _pos(3, seed=3)],
     {"fix_gamma": False, "use_global_stats": True},
     grad_inputs=[0, 1, 2], rtol=3e-2, atol=3e-3)
case("_contrib_SyncBatchNorm",
     [_r(2, 3, 4, 4), _pos(3), _r(3, seed=1), _r(3, seed=2, scale=0.3),
      _pos(3, seed=3)],
     {"fix_gamma": False, "use_global_stats": True},
     grad_inputs=[0, 1, 2], rtol=3e-2, atol=3e-3)
case("LRN", [_r(1, 4, 3, 3)], {"nsize": 3})
case("SoftmaxActivation", [_r(2, 5)])
case("L2Normalization", [_r(2, 6)])
case("UpSampling", [_r(1, 2, 3, 3)], {"scale": 2,
                                      "sample_type": "nearest"})
case("_contrib_AdaptiveAvgPooling2D", [_r(1, 2, 6, 6)],
     {"output_size": (3, 3)})
case("_contrib_BilinearResize2D", [_r(1, 2, 4, 4)],
     {"height": 6, "width": 6})
case("_contrib_div_sqrt_dim", [_r(2, 8)])
case("_contrib_quadratic", [_r(3, 4)], {"a": 0.5, "b": -1.0, "c": 2.0})
case("_contrib_gradientmultiplier", [_r(3, 4)], {"scalar": 1.0})
case("scaled_dot_product_attention",
     [_r(1, 2, 4, 3, scale=0.5), _r(1, 2, 4, 3, seed=1, scale=0.5),
      _r(1, 2, 4, 3, seed=2, scale=0.5)])
case("_contrib_interleaved_matmul_encdec_qk",
     [_r(3, 1, 8, scale=0.5), _r(3, 1, 16, seed=1, scale=0.5)],
     {"heads": 2})
case("_contrib_interleaved_matmul_encdec_valatt",
     [_r(3, 1, 16, scale=0.5), _r(2, 3, 3, seed=1, scale=0.5)],
     {"heads": 2})
case("col2im",
     [_r(1, 8, 4)], {"output_size": (3, 3), "kernel": (2, 2),
                     "stride": (1, 1)})
case("khatri_rao", [_r(2, 3), _r(4, 3, seed=1)])
case("_contrib_hawkesll",
     [_pos(2, 3, shift=0.5),                      # lda (N,K)
      _pos(3, seed=1, shift=0.2),                 # alpha (K,)
      _pos(3, seed=2, shift=0.5),                 # beta (K,)
      np.abs(_r(2, 3, seed=3)),                   # state (N,K)
      _pos(2, 4, seed=4, shift=0.1),              # lags (N,T)
      np.array([[0.0, 1.0, 2.0, 0.0],
                [1.0, 0.0, 2.0, 1.0]]),           # marks (N,T) int
      np.array([3.0, 4.0]),                       # valid_length (N,)
      np.array([5.0, 5.0])],                      # max_time (N,)
     grad_inputs=[0, 1, 2, 3], op=_first("_contrib_hawkesll"),
     # f32 log-lik sums need a larger step: at eps=1e-3 the secant is
     # round-off (verified convergent at 1e-2/3e-2)
     eps=1e-2, rtol=2e-2, atol=2e-3)

# spatial / detection tail (integral or box inputs -> data grads only)
case("ROIPooling",
     [_r(1, 2, 8, 8), np.array([[0.0, 0.0, 0.0, 6.0, 6.0]])],
     {"pooled_size": (2, 2), "spatial_scale": 1.0}, grad_inputs=[0])
case("_contrib_ROIAlign",
     [_r(1, 2, 8, 8), np.array([[0.0, 0.5, 0.5, 6.0, 6.0]])],
     {"pooled_size": (2, 2), "spatial_scale": 1.0}, grad_inputs=[0])
case("_contrib_PSROIPooling",
     [_r(1, 8, 8, 8), np.array([[0.0, 0.5, 0.5, 6.0, 6.0]])],
     {"spatial_scale": 1.0, "output_dim": 2, "pooled_size": 2},
     grad_inputs=[0])
case("_contrib_box_decode",
     [_r(1, 3, 4, scale=0.1), np.array([[[2.0, 2.0, 6.0, 6.0],
                                         [1.0, 1.0, 4.0, 5.0],
                                         [0.0, 2.0, 3.0, 7.0]]])],
     grad_inputs=[0])
case("_contrib_box_iou",
     [np.array([[1.0, 1.0, 4.0, 4.0]]),
      np.array([[2.0, 2.0, 5.0, 5.0]])])
case("GridGenerator", [_r(1, 6, scale=0.2)],
     {"transform_type": "affine", "target_shape": (4, 4)})
case("BilinearSampler",
     [_r(1, 2, 5, 5), np.clip(_r(1, 2, 4, 4, seed=1, scale=0.3), -0.8,
                              0.8)])
case("SpatialTransformer",
     [_r(1, 2, 5, 5), _r(1, 6, seed=1, scale=0.1)],
     {"transform_type": "affine", "sampler_type": "bilinear",
      "target_shape": (4, 4)})
case("_image_crop", [_r(6, 6, 3)], {"x": 1, "y": 1, "width": 4,
                                    "height": 4})
case("_image_resize", [_r(5, 5, 3)], {"size": (4, 4)})
case("_image_to_tensor", [_pos(4, 4, 3, shift=0.0)])
case("_contrib_SparseEmbedding",
     [np.array([0.0, 2.0, 1.0]), _r(4, 3, seed=1)],
     {"input_dim": 4, "output_dim": 3}, grad_inputs=[1])
case("_contrib_ModulatedDeformableConvolution",
     [_r(1, 2, 5, 5), _r(1, 8, 4, 4, seed=1, scale=0.1),
      np.full((1, 4, 4, 4), 0.5, np.float32),
      _r(3, 2, 2, 2, seed=2, scale=0.3)],
     {"kernel": (2, 2), "num_filter": 3, "no_bias": True},
     grad_inputs=[0, 3], rtol=3e-2, atol=3e-3)
case("_contrib_DeformablePSROIPooling",
     [_r(1, 8, 8, 8), np.array([[0.0, 0.5, 0.5, 6.0, 6.0]]),
      np.zeros((1, 2, 2, 2), np.float32)],
     {"spatial_scale": 1.0, "output_dim": 2, "group_size": 2,
      "pooled_size": 2, "part_size": 2, "sample_per_part": 2,
      "trans_std": 0.1}, grad_inputs=[0], rtol=3e-2, atol=3e-3)

# linalg tail (well-conditioned inputs)
case("_linalg_gemm", [_r(2, 3), _r(3, 4, seed=1), _r(2, 4, seed=2)],
     {"alpha": 1.0, "beta": 1.0})
case("_linalg_gemm2", [_r(2, 3), _r(3, 4, seed=1)])
case("_linalg_det", [_spd(3)])
case("_linalg_slogdet", [_spd(3)], op=_first("_linalg_slogdet"))
case("_linalg_inverse", [_spd(3)])
case("_linalg_potrf", [_spd(3)])
case("_linalg_potri", [np.linalg.cholesky(_spd(3))])
case("_linalg_sumlogdiag", [_spd(3)])
case("_linalg_extractdiag", [_r(3, 3)])
case("_linalg_makediag", [_r(3)])
case("_linalg_extracttrian", [_r(3, 3)])
case("_linalg_maketrian", [_r(6)])
case("_linalg_syrk", [_r(2, 3)], {"alpha": 1.0})
case("_linalg_trmm", [np.tril(_pos(3, 3, shift=0.5)), _r(3, 2, seed=1)])
case("_linalg_trsm", [np.tril(_pos(3, 3, shift=1.5)), _r(3, 2, seed=1)])
case("_npi_pinv_scalar_rcond", [_r(3, 2)])

# fft pair (linear maps)
case("_contrib_fft", [_r(2, 4)])
case("_contrib_ifft", [_r(2, 8)])

case("CTCLoss",
     [_r(5, 2, 4, scale=0.5), np.array([[1.0, 2.0], [2.0, 1.0]])],
     grad_inputs=[0], rtol=3e-2, atol=3e-3)

# --------------------------------------------------------------------------
# Exclusions, each with its reason
# --------------------------------------------------------------------------
EXCLUDED = {
    # backward is a LOSS gradient by contract, not the forward Jacobian
    # (reference output-layer semantics: src/operator/softmax_output.cc)
    "SoftmaxOutput": "backward emits d(CE loss), not forward Jacobian",
    "LinearRegressionOutput": "backward emits d(L2 loss) by contract",
    "LogisticRegressionOutput": "backward emits d(logistic loss)",
    "MAERegressionOutput": "backward emits d(L1 loss) by contract",
    "SVMOutput": "backward emits d(hinge loss) by contract",
    "MakeLoss": "backward is grad_scale*1 (loss contract), not Jacobian",
    "BlockGrad": "gradient is defined to be zero (stop_gradient)",
    "IdentityAttachKLSparseReg":
        "backward adds KL penalty; forward is identity",
    "_contrib_round_ste": "straight-through estimator: grad != Jacobian",
    "_contrib_sign_ste": "straight-through estimator: grad != Jacobian",
    "_contrib_gradientmultiplier_doc_note":
        "covered with scalar=1.0 template above",
    # stochastic / stateful
    "Dropout": "stochastic mask (needs_rng); identity in eval mode",
    "Custom": "user-defined callback op; tests/test_custom_op.py",
    "RNN": "fused multi-gate kernel; dedicated oracle tests "
           "(tests/test_rnn.py pin fwd+bwd vs hand LSTM/GRU)",
    # optimizer update kernels (mutating; reference defines no gradient)
    "ftml_update": "optimizer update kernel (tests/test_optimizer.py)",
    "mp_lamb_update_phase1": "optimizer update kernel",
    "mp_lamb_update_phase2": "optimizer update kernel",
    "mp_nag_mom_update": "optimizer update kernel",
    "_mp_adamw_update": "optimizer update kernel",
    # piecewise-constant selection outputs (reference: no gradient)
    "_contrib_box_nms": "selection/suppression output is piecewise "
                        "constant in scores",
    "_npi_where_scalar2":
        "only input is the selector; output is piecewise constant and "
        "finite differences at the 0/nonzero boundary straddle branches",
    "_contrib_Proposal": "top-k anchor selection, piecewise constant",
    "_contrib_MultiProposal": "top-k anchor selection, piecewise constant",
    # factorization outputs with sign/basis ambiguity: finite
    # differences of a non-unique factor are ill-defined
    "_linalg_gelqf": "LQ factor sign ambiguity",
    "_linalg_syevd": "eigenvector sign/ordering ambiguity",
    "_contrib_BatchNormWithReLU":
        "ReLU kink sits exactly at the BN mean — a measure-zero kink "
        "for analytic grads but a dense failure set for finite "
        "differences; BN half is covered by the BatchNorm template",
}


def _unique_impl_groups():
    ops = {n: registry.get(n) for n in registry.list_ops()}
    groups = {}
    for n, o in ops.items():
        if o.differentiable:
            groups.setdefault(id(o.impl), []).append(n)
    return list(groups.values())


def test_registry_grad_coverage_is_total():
    """Every differentiable op impl is hand-covered, templated here, or
    excluded with a reason."""
    missing = []
    for names in _unique_impl_groups():
        ns = set(names)
        if ns & HAND_COVERED or ns & set(T) or ns & set(EXCLUDED):
            continue
        missing.append(sorted(names))
    assert not missing, (
        f"{len(missing)} differentiable op groups have no gradient "
        f"coverage and no stated exclusion: {missing}")


_IDS = sorted(T)

# the deformable/PSROI/attention templates cost 30-90s EACH of numeric
# differencing — together over 300s of tier-1 (ISSUE 12 budget fix).
# They still run under -m slow; the rest of the sweep keeps per-op
# gradient coverage in the fast gate.
_SLOW_IDS = {"CTCLoss",              # ~17s (tier-1 budget);
             # lstm_ocr example keeps CTC training fast
             "_contrib_ROIAlign",    # ~13s; roi_align grad test
             # in test_detection stays fast
             "_contrib_ModulatedDeformableConvolution",
             "_contrib_DeformablePSROIPooling",
             "scaled_dot_product_attention",
             "_contrib_PSROIPooling",
             "_contrib_hawkesll",
             "ROIPooling",           # ~9s; roi op forward tests
             # in test_detection2/test_extra_ops stay fast
             "BilinearSampler"}      # ~7s; GridGenerator/
             # SpatialTransformer sweep entries stay fast


@pytest.mark.parametrize(
    "name", [pytest.param(n, marks=pytest.mark.slow)
             if n in _SLOW_IDS else n for n in _IDS])
def test_numeric_gradient_tail(name):
    op, inputs, kwargs, grad_inputs, rtol, atol, eps = T[name]
    check_numeric_gradient(op, inputs, kwargs=kwargs,
                           grad_inputs=grad_inputs, rtol=rtol, atol=atol,
                           eps=eps)


# eager-vs-jit consistency over the same templates (the reference's
# check_consistency compared cpu-vs-gpu executors; here the two
# execution modes of one op). Wrapper-based and host-callback cases are
# skipped: the former aren't registry names, the latter don't jit.
_JIT_IDS = [n for n in _IDS
            if isinstance(T[n][0], str)
            and not registry.get(n).host_op
            and not registry.get(n).needs_rng]


@pytest.mark.parametrize("name", _JIT_IDS)
def test_eager_jit_consistency_tail(name):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.test_utils import assert_almost_equal
    op, inputs, kwargs, grad_inputs, rtol, atol, eps = T[name]
    o = registry.get(name)
    okwargs = dict(kwargs)
    if o.needs_train:
        okwargs["_training"] = True
    xs = [jnp.asarray(x) for x in inputs]
    if o.variadic:
        fn = lambda *a: o.impl(list(a), **okwargs)  # noqa: E731
    else:
        fn = lambda *a: o.impl(*a, **okwargs)       # noqa: E731
    eager = fn(*xs)
    jitted = jax.jit(fn)(*xs)
    pairs = [(eager, jitted)] if not isinstance(eager, (tuple, list)) \
        else list(zip(eager, jitted))
    for e, j in pairs:
        assert_almost_equal(np.asarray(j), np.asarray(e), rtol=1e-5,
                            atol=1e-6, names=("jit", "eager"))
