"""mx.np / mx.npx frontend tests.

Models the reference's numpy-frontend suites
(tests/python/unittest/test_numpy_ndarray.py, test_numpy_op.py,
test_numpy_interoperability.py — dispatch-protocol coverage).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.autograd as ag

np = mx.np
npx = mx.npx


class TestCreation:
    def test_array_default_dtype(self):
        assert np.array([1, 2, 3]).dtype == onp.float32
        # TPU-first policy: 64-bit dtypes narrow to 32-bit (x64 disabled;
        # matches XLA/TPU-native widths, unlike the reference's int64)
        assert np.array(onp.arange(3, dtype=onp.int64)).dtype == onp.int32
        assert np.array(onp.arange(3, dtype=onp.int32)).dtype == onp.int32

    def test_creation_ops(self):
        assert np.zeros((2, 3)).dtype == onp.float32
        assert np.ones((2, 3)).shape == (2, 3)
        assert np.arange(5).dtype == onp.float32
        assert np.full((2,), 7.0).asnumpy().tolist() == [7.0, 7.0]
        assert np.eye(3).asnumpy()[1, 1] == 1.0
        onp.testing.assert_allclose(
            np.linspace(0, 1, 5).asnumpy(), onp.linspace(0, 1, 5),
            rtol=1e-6)

    def test_zero_dim_and_zero_size(self):
        # numpy shape semantics: 0-dim and 0-size arrays are first-class
        s = np.array(3.0)
        assert s.shape == () and float(s) == 3.0
        z = np.zeros((0, 4))
        assert z.shape == (0, 4) and z.size == 0

    def test_empty_like_and_full_like(self):
        a = np.ones((2, 2))
        assert np.empty_like(a).shape == (2, 2)
        assert np.full_like(a, 5).asnumpy()[0, 0] == 5


class TestSemantics:
    def test_comparison_returns_bool(self):
        a = np.array([1, 2, 3])
        assert (a > 1).dtype == onp.bool_
        assert (a == 2).asnumpy().tolist() == [False, True, False]

    def test_true_divide_promotes(self):
        a = np.array([1, 2], dtype=np.int32)
        assert (a / 2).dtype.kind == "f"

    def test_matmul_operator(self):
        a = np.arange(6).reshape(2, 3)
        b = np.arange(6).reshape(3, 2)
        onp.testing.assert_allclose((a @ b).asnumpy(),
                                    a.asnumpy() @ b.asnumpy(), rtol=1e-5)

    def test_indexing_numpy_style(self):
        x = np.arange(12).reshape(3, 4)
        assert x[1].shape == (4,)          # integer index drops the dim
        assert x[:, 1:3].shape == (3, 2)
        assert x[x > 5].shape == (6,)      # boolean mask
        assert float(x[2, 3]) == 11.0

    def test_scalar_mixing(self):
        a = np.array([1.0, 2.0])
        onp.testing.assert_allclose((3 - a).asnumpy(), [2.0, 1.0])
        onp.testing.assert_allclose((2 ** a).asnumpy(), [2.0, 4.0])


class TestOps:
    def test_reductions(self):
        x = np.array([[1.0, 2.0], [3.0, 4.0]])
        assert float(x.sum()) == 10.0
        assert float(x.mean(axis=0)[1]) == 3.0
        onp.testing.assert_allclose(np.std(x).asnumpy(),
                                    onp.std(x.asnumpy()), rtol=1e-6)

    def test_manipulation(self):
        x = np.arange(12).reshape(3, 4)
        assert np.concatenate([x, x], axis=0).shape == (6, 4)
        assert np.stack([x, x], axis=1).shape == (3, 2, 4)
        assert [s.shape for s in np.split(x, 2, axis=1)] == \
            [(3, 2), (3, 2)]
        assert np.swapaxes(x, 0, 1).shape == (4, 3)
        assert np.expand_dims(x, -1).shape == (3, 4, 1)
        assert np.tile(x, (2, 1)).shape == (6, 4)

    def test_einsum(self):
        a = np.arange(6).reshape(2, 3)
        onp.testing.assert_allclose(
            np.einsum("ij,kj->ik", a, a).asnumpy(),
            onp.einsum("ij,kj->ik", a.asnumpy(), a.asnumpy()), rtol=1e-5)

    def test_where_unique_nonzero(self):
        x = np.array([0.0, 1.0, 0.0, 2.0, 1.0])
        assert np.where(x > 0, x, np.zeros_like(x)).asnumpy().sum() == 4.0
        assert np.unique(x).shape == (3,)
        assert np.nonzero(x)[0].shape == (3,)

    def test_linalg(self):
        a = onp.array([[2.0, 0.0], [1.0, 3.0]], dtype=onp.float32)
        x = np.array(a)
        onp.testing.assert_allclose(np.linalg.inv(x).asnumpy(),
                                    onp.linalg.inv(a), rtol=1e-5)
        onp.testing.assert_allclose(
            float(np.linalg.norm(x)), onp.linalg.norm(a), rtol=1e-5)
        b = onp.array([1.0, 2.0], dtype=onp.float32)
        onp.testing.assert_allclose(
            np.linalg.solve(x, np.array(b)).asnumpy(),
            onp.linalg.solve(a, b), rtol=1e-5)


class TestAutograd:
    def test_backward_through_np_ops(self):
        x = np.array([1.0, 2.0, 3.0])
        x.attach_grad()
        with ag.record():
            y = np.sum(x ** 2)
        y.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), [2.0, 4.0, 6.0],
                                    rtol=1e-6)

    def test_backward_mixed_nd_np(self):
        # slots survive as_np/as_nd conversion
        x = mx.nd.array([2.0])
        x.attach_grad()
        with ag.record():
            y = (x.as_np_ndarray() * 3).as_nd_ndarray()
        y.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), [3.0])

    def test_grad_through_linalg(self):
        x = np.array([[3.0]])
        x.attach_grad()
        with ag.record():
            y = np.linalg.det(x)
        y.backward()
        onp.testing.assert_allclose(x.grad.asnumpy(), [[1.0]], rtol=1e-6)


class TestInterop:
    def test_ufunc_protocol(self):
        a = np.array([0.0, 1.0])
        out = onp.sin(a)
        assert isinstance(out, np.ndarray)
        onp.testing.assert_allclose(out.asnumpy(), onp.sin([0.0, 1.0]),
                                    rtol=1e-6)

    def test_array_function_protocol(self):
        a = np.array([1.0, 2.0])
        out = onp.concatenate([a, a])
        assert isinstance(out, np.ndarray) and out.shape == (4,)
        out2 = onp.stack([a, a])
        assert isinstance(out2, np.ndarray) and out2.shape == (2, 2)

    def test_conversion_roundtrip(self):
        a = mx.nd.array([1.0, 2.0])
        b = a.as_np_ndarray()
        assert isinstance(b, np.ndarray)
        c = b.as_nd_ndarray()
        assert type(c) is mx.nd.NDArray
        onp.testing.assert_allclose(c.asnumpy(), a.asnumpy())


class TestRandom:
    def test_determinism(self):
        np.random.seed(42)
        a = np.random.uniform(size=(4,)).asnumpy()
        np.random.seed(42)
        b = np.random.uniform(size=(4,)).asnumpy()
        onp.testing.assert_array_equal(a, b)

    def test_shapes_and_ranges(self):
        u = np.random.uniform(low=2.0, high=3.0, size=(100,))
        assert u.shape == (100,)
        assert float(u.min()) >= 2.0 and float(u.max()) <= 3.0
        n = np.random.normal(loc=0.0, scale=1.0, size=(50, 2))
        assert n.shape == (50, 2)
        r = np.random.randint(0, 10, size=(20,))
        assert r.dtype.kind == "i"
        assert int(r.max()) < 10

    def test_choice_permutation(self):
        p = np.random.permutation(5)
        assert sorted(p.asnumpy().tolist()) == [0, 1, 2, 3, 4]
        c = np.random.choice(np.arange(5), size=(3,))
        assert c.shape == (3,)


class TestNpx:
    def test_scoping(self):
        assert not npx.is_np_array()
        npx.set_np()
        assert npx.is_np_array() and npx.is_np_shape()
        npx.reset_np()
        assert not npx.is_np_array()

    def test_scope_managers(self):
        with mx.util.np_array(True):
            assert npx.is_np_array()
        assert not npx.is_np_array()

    def test_use_np_decorator(self):
        @npx.use_np
        def f():
            return npx.is_np_array(), npx.is_np_shape()

        assert f() == (True, True)
        assert not npx.is_np_array()

    def test_npx_ops_return_np(self):
        out = npx.softmax(np.array([1.0, 2.0, 3.0]))
        assert isinstance(out, np.ndarray)
        onp.testing.assert_allclose(float(out.sum()), 1.0, rtol=1e-6)
        oh = npx.one_hot(np.array([0, 2]), 3)
        assert oh.shape == (2, 3)

    def test_npx_save_load(self, tmp_path):
        f = str(tmp_path / "arrs.npz.mx")
        npx.save(f, {"w": np.arange(4)})
        out = npx.load(f)
        assert isinstance(out["w"], np.ndarray)
        onp.testing.assert_allclose(out["w"].asnumpy(),
                                    onp.arange(4, dtype=onp.float32))


class TestJitTransparency:
    def test_np_ops_inside_jit(self):
        import jax
        import jax.numpy as jnp

        def f(a):
            return np.mean(np.tanh(a) ** 2)._data

        out = jax.jit(f)(jnp.ones((4,)))
        onp.testing.assert_allclose(
            float(out), float(onp.mean(onp.tanh(onp.ones(4)) ** 2)),
            rtol=1e-6)


def test_round4_widened_surface():
    """Round-4 np-namespace widening: spot-pin representative new
    functions (array-output jnp bridges) and their NONDIFF taping."""
    import mxnet_tpu.autograd as ag
    np, mnp = onp, mx.np
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    ma = mnp.array(a)
    np.testing.assert_allclose(mnp.cov(ma).asnumpy(), np.cov(a))
    np.testing.assert_allclose(mnp.gradient(mnp.array([1.0, 2.0, 4.0]))
                               .asnumpy(), np.gradient([1.0, 2.0, 4.0]))
    np.testing.assert_allclose(
        mnp.heaviside(mnp.array([-1.0, 0.0, 2.0]),
                      mnp.array(0.5)).asnumpy(), [0.0, 0.5, 1.0])
    np.testing.assert_allclose(mnp.vander(mnp.array([2.0, 3.0]), 3)
                               .asnumpy(), np.vander([2.0, 3.0], 3))
    np.testing.assert_allclose(
        mnp.unwrap(mnp.array([0.0, 3.0, 6.0, 9.0])).asnumpy(),
        np.unwrap([0.0, 3.0, 6.0, 9.0]))
    assert bool(mnp.allclose(ma, ma))
    assert mnp.isin(ma, mnp.array([2.0])).asnumpy().tolist() == \
        [[False, True], [False, False]]
    # sized set ops stay jit-compatible
    np.testing.assert_array_equal(
        mnp.setdiff1d(mnp.array([1.0, 2.0, 3.0]), mnp.array([2.0]),
                      size=2).asnumpy(), [1.0, 3.0])
    # new smooth fns differentiate; predicates don't tape
    x = mnp.array([0.3, 0.7])
    x.attach_grad()
    with ag.record():
        y = (mnp.sinc(x) + mnp.exp2(x)).sum()
    y.backward()
    assert np.isfinite(x.grad.asnumpy()).all()
    x2 = mnp.array([1.0, 2.0])
    x2.attach_grad()
    with ag.record():
        p = mnp.signbit(x2)
    assert p.asnumpy().tolist() == [False, False]
