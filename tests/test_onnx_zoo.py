"""Model-zoo ONNX round-trips with numeric equality.

The reference's onnx backend tests export whole zoo models and compare
outputs (python/mxnet/contrib/onnx/ + tests/python-pytest/onnx/); these
three cover the op families the translation tables must handle:
ResNet-50 (conv/BN/pool/residual-add/gemm), MobileNet (depthwise conv,
width multipliers), and a BERT encoder layer (per-token gemm, matmul
attention with transposes/reshapes, softmax, layernorm, erf-gelu).
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _init_params(sym, **shapes):
    arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
    rng = np.random.RandomState(0)
    params = {}
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        if name in shapes or name.endswith("_label"):
            continue
        params[name] = mx.nd.array(
            rng.randn(*shape).astype(np.float32) * 0.2)
    for name, shape in zip(sym.list_auxiliary_states(), aux_shapes):
        params[name] = mx.nd.array(
            np.abs(rng.randn(*shape).astype(np.float32)) + 0.5)
    return params


def _run(sym, params, x):
    feed = {"data": mx.nd.array(x)}
    feed.update(params)
    out = sym.eval_dict(feed)
    if isinstance(out, list):
        out = out[0]
    return out.asnumpy()


def _roundtrip(sym, data_shape, rtol=2e-4, atol=2e-5):
    params = _init_params(sym, data=data_shape)
    x = np.random.RandomState(1).randn(*data_shape).astype(np.float32)
    want = _run(sym, params, x)
    blob = mx.onnx.export_model(sym, params, {"data": data_shape})
    sym2, args2, aux2 = mx.onnx.import_model(blob)
    got = _run(sym2, {**args2, **aux2}, x)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)
    return blob


@pytest.mark.slow   # ~13s on 1 CPU (tier-1 budget); mobilenet +
# bert-layer roundtrips keep fast zoo coverage
def test_roundtrip_resnet50():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "examples"))
    import train_imagenet
    sym = train_imagenet.get_resnet_symbol(10, 50, (3, 32, 32))
    # strip the training head: export the logits like the reference's
    # inference exports
    logits = sym.get_internals()["fc1_output"] \
        if hasattr(sym, "get_internals") else sym
    _roundtrip(logits, (2, 3, 32, 32), rtol=1e-3, atol=1e-4)


def _mobilenet_symbol(num_classes=10, alpha=0.5):
    """MobileNet v1 essence: depthwise-separable conv stacks
    (reference network: example/image-classification/symbols/
    mobilenet.py — conv_dw = 3x3 depthwise + 1x1 pointwise)."""
    def conv_block(x, nf, name, stride=(1, 1), kernel=(3, 3), pad=(1, 1),
                   group=1):
        c = mx.sym.Convolution(x, num_filter=nf, kernel=kernel,
                               stride=stride, pad=pad, num_group=group,
                               no_bias=True, name=name + "_conv")
        b = mx.sym.BatchNorm(c, fix_gamma=False, name=name + "_bn")
        return mx.sym.Activation(b, act_type="relu", name=name + "_act")

    def dw_sep(x, in_ch, out_ch, name, stride=(1, 1)):
        dw = conv_block(x, in_ch, name + "_dw", stride=stride,
                        group=in_ch)
        return conv_block(dw, out_ch, name + "_pw", kernel=(1, 1),
                          pad=(0, 0))

    ch = [int(alpha * c) for c in (32, 64, 128, 256)]
    x = mx.sym.var("data")
    x = conv_block(x, ch[0], "stem", stride=(2, 2))
    x = dw_sep(x, ch[0], ch[1], "b1")
    x = dw_sep(x, ch[1], ch[2], "b2", stride=(2, 2))
    x = dw_sep(x, ch[2], ch[3], "b3", stride=(2, 2))
    x = mx.sym.Pooling(x, global_pool=True, pool_type="avg",
                       kernel=(1, 1), name="gap")
    x = mx.sym.Flatten(x)
    return mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc")


def test_roundtrip_mobilenet():
    sym = _mobilenet_symbol()
    _roundtrip(sym, (2, 3, 32, 32), rtol=1e-3, atol=1e-4)


def _bert_layer_symbol(units=32, heads=4, hidden=64):
    """One BERT encoder layer, spelled symbolically: per-token q/k/v
    projections, batched attention matmuls, softmax, residual +
    LayerNorm, erf-GELU FFN (reference layer: the gluon BERTEncoderLayer
    in mxnet_tpu/gluon/model_zoo/bert.py; ONNX surface: MatMul/
    Transpose/Reshape/Softmax/LayerNormalization/Erf)."""
    d = units // heads
    x = mx.sym.var("data")  # (B, T, U)

    def proj(inp, name):
        return mx.sym.FullyConnected(inp, num_hidden=units,
                                     flatten=False, name=name)

    def split_heads(t, name):
        r = mx.sym.Reshape(t, shape=(0, -1, heads, d),
                           name=name + "_r")
        tr = mx.sym.transpose(r, axes=(0, 2, 1, 3), name=name + "_t")
        # -3 merges (B, H) -> B*H; the 0s then copy T and D
        return mx.sym.Reshape(tr, shape=(-3, 0, 0), name=name + "_m")

    q = split_heads(proj(x, "query"), "qh")   # (B*H, T, D)
    k = split_heads(proj(x, "key"), "kh")
    v = split_heads(proj(x, "value"), "vh")
    scores = mx.sym.batch_dot(q, k, transpose_b=True, name="scores")
    scores = mx.sym._div_scalar(scores, scalar=float(np.sqrt(d)))
    attn = mx.sym.softmax(scores, axis=-1, name="attn")
    ctxv = mx.sym.batch_dot(attn, v, name="ctx")   # (B*H, T, D)
    # -4(-1, heads) splits B*H back into (B, H)
    ctxv = mx.sym.Reshape(ctxv, shape=(-4, -1, heads, 0, 0),
                          name="ctx_r")
    ctxv = mx.sym.transpose(ctxv, axes=(0, 2, 1, 3), name="ctx_t")
    ctxv = mx.sym.Reshape(ctxv, shape=(0, 0, -1), name="ctx_m")
    out = proj(ctxv, "attnout")
    h = mx.sym.LayerNorm(mx.sym.elemwise_add(x, out, name="res1"),
                         name="ln1")

    f1 = mx.sym.FullyConnected(h, num_hidden=hidden, flatten=False,
                               name="ffn1")
    # erf-form GELU: 0.5 * x * (1 + erf(x / sqrt(2)))
    g = mx.sym._mul_scalar(
        mx.sym.elemwise_mul(
            f1, mx.sym._plus_scalar(
                mx.sym.erf(mx.sym._div_scalar(f1,
                                              scalar=float(np.sqrt(2)))),
                scalar=1.0)),
        scalar=0.5)
    f2 = mx.sym.FullyConnected(g, num_hidden=units, flatten=False,
                               name="ffn2")
    return mx.sym.LayerNorm(mx.sym.elemwise_add(h, f2, name="res2"),
                            name="ln2")


def test_roundtrip_bert_layer():
    sym = _bert_layer_symbol()
    _roundtrip(sym, (2, 6, 32), rtol=5e-4, atol=5e-5)


def test_roundtrip_deconv_resize_slice():
    """The remaining families VERDICT round 3 called out: ConvTranspose,
    Resize, Slice, reductions, clip."""
    x = mx.sym.var("data")
    up = mx.sym.Deconvolution(x, num_filter=4, kernel=(2, 2),
                              stride=(2, 2), no_bias=True, name="dc")
    up = mx.sym.Activation(up, act_type="relu")
    s = mx.sym.slice_axis(up, axis=2, begin=1, end=7, name="sl")
    c = mx.sym.clip(s, a_min=-1.0, a_max=1.0, name="cl")
    m = mx.sym.mean(c, axis=(2, 3), keepdims=False, name="mn")
    _roundtrip(m, (2, 3, 4, 4))


def test_roundtrip_pad():
    x = mx.sym.var("data")
    p = mx.sym.Pad(x, mode="constant",
                   pad_width=(0, 0, 0, 0, 1, 2, 2, 1),
                   constant_value=0.5, name="pd")
    out = mx.sym.relu(p, name="r")
    _roundtrip(out, (2, 3, 4, 4))


def test_import_general_gemm_and_constant():
    """External-exporter patterns: Gemm with transA/alpha/beta and a
    Constant node (built by hand through the export encoder)."""
    import numpy as np
    from mxnet_tpu.onnx import _proto as P
    from mxnet_tpu.onnx.export import (_attr, _node, _tensor,
                                       _value_info, AT_FLOAT, AT_INT)
    rng = np.random.RandomState(0)
    A = rng.randn(3, 2).astype(np.float32)   # transA -> (2,3)@(3,4)
    B = rng.randn(3, 4).astype(np.float32)
    C = rng.randn(1, 4).astype(np.float32)
    nodes = [
        _node("Constant", [], ["cst"], "cst",
              [(5, P.LEN, P.encode([(1, P.LEN, "value"),
                                    (20, P.VARINT, 4),
                                    (5, P.LEN, _tensor("", C))]))]),
        _node("Gemm", ["a", "b", "cst"], ["y"], "gemm",
              [_attr("alpha", AT_FLOAT, 0.5),
               _attr("beta", AT_FLOAT, 2.0),
               _attr("transA", AT_INT, 1)]),
    ]
    graph = P.encode(
        nodes
        + [(2, P.LEN, "g")]
        + [(5, P.LEN, _tensor("b", B))]
        + [(11, P.LEN, _value_info("a", (3, 2)))]
        + [(12, P.LEN, _value_info("y", (2, 4)))])
    model = P.encode([(1, P.VARINT, 8), (2, P.LEN, "t"),
                      (7, P.LEN, graph),
                      (8, P.LEN, P.encode([(1, P.LEN, ""),
                                           (2, P.VARINT, 17)]))])
    sym, args, aux = mx.onnx.import_model(model)
    feed = {"a": mx.nd.array(A)}
    feed.update(args)
    got = sym.eval_dict(feed)
    got = (got[0] if isinstance(got, list) else got).asnumpy()
    want = 0.5 * (A.T @ B) + 2.0 * C
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_roundtrip_reversed_open_slice():
    """An open-ended reversed slice (step=-1, end=None) must export an
    INT_MIN end sentinel — a positive sentinel is clamped to dim-1 by
    ONNX for negative steps, yielding an empty result (advisor r4)."""
    x = mx.sym.var("data")
    out = mx.sym.slice(x, begin=(None, 1), end=(None, None),
                       step=(-1, 2), name="rev")
    out = mx.sym.relu(out, name="r")
    _roundtrip(out, (4, 5))


def test_import_resize_sizes_input_rejected():
    """Resize with the opset-13 'sizes' input must refuse rather than
    silently import a wrong graph (advisor r4)."""
    from mxnet_tpu.onnx import _proto as P
    from mxnet_tpu.onnx.export import _node, _tensor, _value_info
    sizes = np.asarray([1, 3, 8, 8], np.int64)
    nodes = [_node("Resize", ["x", "", "", "szs"], ["y"], "rs", [])]
    graph = P.encode(
        nodes
        + [(2, P.LEN, "g")]
        + [(5, P.LEN, _tensor("szs", sizes))]
        + [(11, P.LEN, _value_info("x", (1, 3, 4, 4)))]
        + [(12, P.LEN, _value_info("y", (1, 3, 8, 8)))])
    model = P.encode([(1, P.VARINT, 8), (2, P.LEN, "t"),
                      (7, P.LEN, graph),
                      (8, P.LEN, P.encode([(1, P.LEN, ""),
                                           (2, P.VARINT, 17)]))])
    with pytest.raises(NotImplementedError, match="sizes"):
        mx.onnx.import_model(model)


def test_import_resize_nonuniform_bilinear():
    """Non-uniform H/W scales must not collapse to the height scale
    (advisor r4): 4x3 -> 8x9 via scales (2, 3) bilinear."""
    from mxnet_tpu.onnx import _proto as P
    from mxnet_tpu.onnx.export import (_attr, _node, _tensor,
                                       _value_info)
    scales = np.asarray([1.0, 1.0, 2.0, 3.0], np.float32)
    nodes = [_node("Resize", ["x", "", "scl"], ["y"], "rs",
                   [_attr("mode", 3, b"linear")])]
    graph = P.encode(
        nodes
        + [(2, P.LEN, "g")]
        + [(5, P.LEN, _tensor("scl", scales))]
        + [(11, P.LEN, _value_info("x", (1, 2, 4, 3)))]
        + [(12, P.LEN, _value_info("y", (1, 2, 8, 9)))])
    model = P.encode([(1, P.VARINT, 8), (2, P.LEN, "t"),
                      (7, P.LEN, graph),
                      (8, P.LEN, P.encode([(1, P.LEN, ""),
                                           (2, P.VARINT, 17)]))])
    sym, args, aux = mx.onnx.import_model(model)
    x = np.random.RandomState(0).randn(1, 2, 4, 3).astype(np.float32)
    out = sym.eval_dict({"x": mx.nd.array(x), **args})
    out = (out[0] if isinstance(out, list) else out).asnumpy()
    assert out.shape == (1, 2, 8, 9)
