"""Whole-step compilation (jit.CompiledTrainStep): bit-exact parity with
the eager record/backward path, single-dispatch steady state, zero
recompiles across lr changes and bucketed batch tails, AMP overflow
skip, checkpoint resume mid-run, and the guarded fallback reasons."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn, Trainer
import mxnet_tpu.autograd as ag
from mxnet_tpu.observability import get_registry, \
    install_jax_monitoring_bridge

LOSS = gluon.loss.SoftmaxCrossEntropyLoss()


def _build(seed=0, ctx=None, hybrid=False, bn=False):
    """Fresh MLP with deferred init RESOLVED (so two same-seed builds
    draw identical host-rng streams regardless of later forward
    order)."""
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        if bn:
            net.add(nn.Dense(16), nn.BatchNorm(), nn.Activation("relu"),
                    nn.Dense(4))
        else:
            net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(init=mx.initializer.Xavier(), ctx=ctx)
    with ag.pause(train_mode=False):
        net(nd.array(np.zeros((1, 6), np.float32)))
    if hybrid:
        net.hybridize()
    return net


def _data(steps=5, n=32):
    rng = np.random.RandomState(7)
    X = rng.randn(steps, n, 6).astype(np.float32)
    Y = (np.arange(steps * n).reshape(steps, n) % 4).astype(np.float32)
    return X, Y


def _eager_run(net, opt, opt_args, sizes, lrs=None, kvstore="device"):
    tr = Trainer(net.collect_params(), opt, dict(opt_args),
                 kvstore=kvstore)
    X, Y = _data(len(sizes))
    losses = []
    for s, n in enumerate(sizes):
        if lrs:
            tr.set_learning_rate(lrs[s % len(lrs)])
        with ag.record():
            l = LOSS(net(nd.array(X[s][:n])), nd.array(Y[s][:n]))
        l.backward()
        tr.step(n)
        losses.append(l.asnumpy())
    return tr, losses


def _compiled_run(net, opt, opt_args, sizes, lrs=None, kvstore="device",
                  **step_kw):
    tr = Trainer(net.collect_params(), opt, dict(opt_args),
                 kvstore=kvstore)
    step = tr.compile_step(lambda x, y: LOSS(net(x), y), **step_kw)
    X, Y = _data(len(sizes))
    losses = []
    for s, n in enumerate(sizes):
        if lrs:
            tr.set_learning_rate(lrs[s % len(lrs)])
        losses.append(step(nd.array(X[s][:n]), nd.array(Y[s][:n]))
                      .asnumpy())
    return tr, step, losses


def _params_of(net):
    return {k: p.data().asnumpy().copy()
            for k, p in net.collect_params().items()}


def _assert_params_bitexact(net_a, net_b):
    for (ka, pa), (kb, pb) in zip(sorted(net_a.collect_params().items()),
                                  sorted(net_b.collect_params().items())):
        assert (pa.data().asnumpy() == pb.data().asnumpy()).all(), \
            f"parameter {ka} differs (not bit-exact)"


@pytest.mark.parametrize("opt,args", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 1e-3, "wd": 1e-3}),
])
def test_parity_bitexact(opt, args):
    """Acceptance: ≥5 steps, losses AND weights AND optimizer slots
    bit-exact with the eager record/backward path, across lr changes and
    batch-size changes (Adam bias correction included: the same host
    phase-A pass that makes the fused update exact drives this)."""
    sizes = [32, 16, 32, 16, 32]          # pow2 sizes: full buckets
    lrs = [0.05, 0.02, 0.05, 0.01]
    net_e = _build()
    tr_e, el = _eager_run(net_e, opt, args, sizes, lrs)
    net_c = _build()
    tr_c, step, cl = _compiled_run(net_c, opt, args, sizes, lrs)
    assert step.last_reason is None, step.last_reason
    for s in range(len(sizes)):
        assert (el[s] == cl[s]).all(), f"step {s} loss not bit-exact"
    _assert_params_bitexact(net_e, net_c)
    assert tr_e._optimizer._index_update_count == \
        tr_c._optimizer._index_update_count
    assert tr_e._optimizer.num_update == tr_c._optimizer.num_update
    import jax
    sa, sb = tr_e._updaters[0].states, tr_c._updaters[0].states
    assert sorted(sa) == sorted(sb)
    for k in sa:
        for la, lb in zip(jax.tree_util.tree_leaves(sa[k]),
                          jax.tree_util.tree_leaves(sb[k])):
            assert (la.asnumpy() == lb.asnumpy()).all(), \
                f"optimizer slot {k} differs"


def test_parity_hybridized():
    """A hybridized block traces into the whole-step program through its
    eager forward (the CachedOp is bypassed under the trace) and stays
    bit-exact with hybridized eager training."""
    sizes = [32, 32, 32, 32, 32]
    net_e = _build(hybrid=True)
    _, el = _eager_run(net_e, "sgd", {"learning_rate": 0.05}, sizes)
    net_c = _build(hybrid=True)
    _, step, cl = _compiled_run(net_c, "sgd", {"learning_rate": 0.05},
                                sizes)
    assert step.last_reason is None
    for s in range(5):
        assert (el[s] == cl[s]).all()
    _assert_params_bitexact(net_e, net_c)


def test_parity_multictx():
    """Per-context replicated parameters: the compiled step runs the
    batch on the primary context and broadcasts the updated weights —
    every replica ends identical, bit-exact with the eager path (whose
    tree-sum reduce over one real + N zero gradients is the identity)."""
    ctxs = [mx.cpu(0), mx.cpu(1)]
    sizes = [32, 32, 32, 32, 32]
    net_e = _build(ctx=ctxs)
    _, el = _eager_run(net_e, "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9}, sizes,
                       kvstore=None)
    net_c = _build(ctx=ctxs)
    _, step, cl = _compiled_run(
        net_c, "sgd", {"learning_rate": 0.05, "momentum": 0.9}, sizes,
        kvstore=None)
    assert step.last_reason is None
    for s in range(5):
        assert (el[s] == cl[s]).all()
    for k, p in net_c.collect_params().items():
        reps = [d.asnumpy() for d in p.list_data()]
        assert (reps[0] == reps[1]).all(), f"{k} replicas diverged"
    _assert_params_bitexact(net_e, net_c)


def test_single_dispatch_steady_state():
    """CI smoke (acceptance criterion): a 2-step train through the
    compiled path — after warmup, ONE device dispatch and ZERO XLA
    compiles per step, loss parity with eager."""
    install_jax_monitoring_bridge()
    reg = get_registry()
    dispatch = reg.counter("mxtpu_train_step_dispatch_total")
    compiles = reg.counter("mxtpu_xla_compile_total")

    net_e = _build()
    _, el = _eager_run(net_e, "sgd", {"learning_rate": 0.05}, [32, 32])
    net_c = _build()
    tr_c = Trainer(net_c.collect_params(), "sgd", {"learning_rate": 0.05})
    step = tr_c.compile_step(lambda x, y: LOSS(net_c(x), y))
    X, Y = _data(2)
    l0 = step(nd.array(X[0]), nd.array(Y[0]))      # warmup: compiles
    d0, c0 = dispatch.value, compiles.value
    l1 = step(nd.array(X[1]), nd.array(Y[1]))
    assert dispatch.value - d0 == 1, \
        f"steady-state step took {dispatch.value - d0} dispatches, not 1"
    assert compiles.value - c0 == 0, "steady-state step recompiled"
    assert (l0.asnumpy() == el[0]).all()
    assert (l1.asnumpy() == el[1]).all()


def test_zero_recompile_lr_and_tails():
    """After one warmup per bucket, lr/batch-size changes and ragged
    tails mapped to warm buckets must be recompile-free (asserted via
    the jax.monitoring backend_compile counter)."""
    install_jax_monitoring_bridge()
    reg = get_registry()
    compiles = reg.counter("mxtpu_xla_compile_total")
    net = _build()
    tr = Trainer(net.collect_params(), "adam", {"learning_rate": 1e-3})
    step = tr.compile_step(lambda x, y: LOSS(net(x), y))
    X, Y = _data(10)
    step(nd.array(X[0]), nd.array(Y[0]))            # bucket 32
    step(nd.array(X[1][:20]), nd.array(Y[1][:20]))  # tail->32 (pad ops)
    step(nd.array(X[2][:7]), nd.array(Y[2][:7]))    # bucket 8
    c0 = compiles.value
    for s, n in enumerate([32, 20, 32, 7, 20, 32], start=3):
        tr.set_learning_rate(1e-3 * (s + 1))
        step(nd.array(X[s][:n]), nd.array(Y[s][:n]))
    assert compiles.value - c0 == 0, \
        "lr change or warmed batch tail recompiled the step"
    assert step.cache_size() == 2       # one program per bucket

    # an UNSEEN tail size pays only O(ms) pad/slice glue compiles —
    # never a step-program rebuild (the expensive compile)
    bucket_compiles = reg.counter("mxtpu_train_step_bucket_compiles_total",
                                  labelnames=("bucket",))
    b0 = sum(c.value for c in bucket_compiles.children())
    step(nd.array(X[9][:19]), nd.array(Y[9][:19]))  # 19 -> warm bucket 32
    assert step.cache_size() == 2
    assert sum(c.value for c in bucket_compiles.children()) == b0, \
        "an unseen tail size rebuilt a whole-step program"


def test_bucket_tail_semantics():
    """A padded tail's per-sample losses equal the unpadded eager step's
    bitwise (pad rows cannot touch real rows' forward); the update
    matches to reduction-reassociation tolerance (batch-summed grads
    see the +0 pad rows)."""
    net_e = _build()
    _, el = _eager_run(net_e, "sgd", {"learning_rate": 0.05}, [32, 20])
    net_c = _build()
    _, step, cl = _compiled_run(net_c, "sgd", {"learning_rate": 0.05},
                                [32, 20])
    assert cl[1].shape == (20,)
    assert (el[1] == cl[1]).all(), "tail losses not bit-exact"
    for (ka, pa), (kb, pb) in zip(sorted(net_e.collect_params().items()),
                                  sorted(net_c.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(),
                                   rtol=1e-6, atol=1e-7, err_msg=ka)
    reg = get_registry()
    assert reg.counter("mxtpu_train_step_padded_rows_total").value >= 12


def test_amp_scaled_parity_and_overflow_skip():
    """LossScaler rescale rides as a traced scalar (scaled runs stay
    bit-exact with eager AMP); a forced overflow skips the update
    IN-PROGRAM: weights/slots unchanged, scale halves, no step tick —
    exactly the eager amp_step contract."""
    from mxnet_tpu import amp
    sizes = [16, 16, 16, 16]
    X, Y = _data(len(sizes), 16)

    def amp_eager(net):
        tr = Trainer(net.collect_params(), "sgd", {"learning_rate": .05})
        amp.init_trainer(tr, loss_scaler=amp.LossScaler(
            init_scale=64.0, target_dtype="float16"))
        for s, n in enumerate(sizes):
            with ag.record():
                l = LOSS(net(nd.array(X[s][:n])), nd.array(Y[s][:n]))
                with amp.scale_loss(l, tr) as scaled:
                    pass
            scaled.backward()
            tr.step(n)
        return tr

    net_e = _build(3)
    amp_eager(net_e)
    net_c = _build(3)
    tr_c = Trainer(net_c.collect_params(), "sgd", {"learning_rate": .05})
    amp.init_trainer(tr_c, loss_scaler=amp.LossScaler(
        init_scale=64.0, target_dtype="float16"))
    step = tr_c.compile_step(lambda x, y: LOSS(net_c(x), y))
    for s, n in enumerate(sizes):
        step(nd.array(X[s][:n]), nd.array(Y[s][:n]))
    assert step.last_reason is None
    _assert_params_bitexact(net_e, net_c)
    assert tr_c._amp_loss_scaler.loss_scale == 64.0

    # overflow: a loss scale beyond float32 range makes every gradient
    # non-finite; the in-program where() must keep the weights
    net_o = _build(4)
    tr_o = Trainer(net_o.collect_params(), "sgd", {"learning_rate": .05})
    amp.init_trainer(tr_o, loss_scaler=amp.LossScaler(
        init_scale=1e39, target_dtype="float16"))
    stepo = tr_o.compile_step(lambda x, y: LOSS(net_o(x), y))
    before = _params_of(net_o)
    with pytest.warns(UserWarning, match="overflow"):
        stepo(nd.array(X[0]), nd.array(Y[0]))
    assert tr_o._amp_loss_scaler.loss_scale == 5e38
    assert tr_o._step_count == 0
    for k, v in before.items():
        assert (net_o.collect_params()[k].data().asnumpy() == v).all(), \
            f"{k} changed despite overflow skip"


def test_bn_aux_states_update_in_program():
    """BatchNorm running stats are captured as program outputs and
    written back; values track eager training to fusion tolerance (XLA
    reassociates the batch-stat reductions inside the whole program —
    exact bitwise parity is a no-reduction-fusion property)."""
    sizes = [32] * 4
    net_e = _build(bn=True)
    _, el = _eager_run(net_e, "sgd", {"learning_rate": 0.05}, sizes)
    net_c = _build(bn=True)
    _, step, cl = _compiled_run(net_c, "sgd", {"learning_rate": 0.05},
                                sizes)
    assert step.last_reason is None
    for s in range(4):
        np.testing.assert_allclose(el[s], cl[s], rtol=1e-5, atol=1e-6)
    moved = False
    for (ka, pa), (kb, pb) in zip(sorted(net_e.collect_params().items()),
                                  sorted(net_c.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(),
                                   rtol=1e-4, atol=1e-6, err_msg=ka)
        if "running" in ka and pb.data().asnumpy().any():
            moved = True
    assert moved, "BN running stats never updated under the compiled step"


def test_remat_stays_correct():
    """remat='dots'/'full' (the memory-headroom lever) recomputes the
    forward in the backward without changing the trained result."""
    sizes = [32] * 3
    net_e = _build(5)
    _, el = _eager_run(net_e, "sgd", {"learning_rate": 0.05}, sizes)
    for remat in ("dots", "full"):
        net_c = _build(5)
        _, step, cl = _compiled_run(net_c, "sgd", {"learning_rate": 0.05},
                                    sizes, remat=remat)
        assert step.last_reason is None
        for s in range(3):
            np.testing.assert_allclose(el[s], cl[s], rtol=1e-6,
                                       atol=1e-7)
        for (ka, pa), (kb, pb) in zip(
                sorted(net_e.collect_params().items()),
                sorted(net_c.collect_params().items())):
            np.testing.assert_allclose(pa.data().asnumpy(),
                                       pb.data().asnumpy(),
                                       rtol=1e-6, atol=1e-7, err_msg=ka)


def test_checkpoint_resume_midrun():
    """save_state after 3 compiled steps + restore into a fresh process
    image resumes bit-exactly (optimizer slots, Adam counters, and the
    RNG draw position all ride the resilience checkpoint)."""
    import tempfile
    sizes = [32] * 5
    X, Y = _data(5)
    with tempfile.TemporaryDirectory() as run_dir:
        net_a = _build(6)
        tr_a = Trainer(net_a.collect_params(), "adam",
                       {"learning_rate": 1e-3})
        step_a = tr_a.compile_step(lambda x, y: LOSS(net_a(x), y))
        for s in range(3):
            step_a(nd.array(X[s]), nd.array(Y[s]))
        tr_a.save_state(run_dir)
        for s in range(3, 5):
            step_a(nd.array(X[s]), nd.array(Y[s]))
        final_a = _params_of(net_a)

        net_b = _build(7)      # different init: restore must overwrite
        tr_b = Trainer(net_b.collect_params(), "adam",
                       {"learning_rate": 1e-3})
        tr_b.restore_state(run_dir)
        step_b = tr_b.compile_step(lambda x, y: LOSS(net_b(x), y))
        for s in range(3, 5):
            step_b(nd.array(X[s]), nd.array(Y[s]))
        assert tr_b._step_count == 5
        # name prefixes differ between builds; compare by position
        pa = [p.data().asnumpy() for _, p in
              sorted(net_a.collect_params().items())]
        pb = [p.data().asnumpy() for _, p in
              sorted(net_b.collect_params().items())]
        for i, (a, b) in enumerate(zip(pa, pb)):
            assert (a == b).all(), \
                f"param #{i} diverged after mid-run resume"


def test_fallback_reasons_and_parity():
    """Ineligible configurations run the eager path (same numbers),
    counted by reason; data-dependent Python control flow is detected at
    trace time and sticks to eager."""
    reg = get_registry()
    fallback = reg.counter("mxtpu_train_step_fallback_total",
                           labelnames=("reason",))
    X, Y = _data(2)

    # host-state optimizer -> 'optimizer'
    net = _build(8)
    tr = Trainer(net.collect_params(), "nadam", {"learning_rate": 1e-3})
    step = tr.compile_step(lambda x, y: LOSS(net(x), y))
    before = fallback.labels(reason="optimizer").value
    step(nd.array(X[0]), nd.array(Y[0]))
    assert fallback.labels(reason="optimizer").value == before + 1
    assert step.last_reason == "optimizer"

    # env kill-switch -> 'env_disabled', numbers identical to eager
    os.environ["MXNET_TPU_COMPILED_STEP"] = "0"
    try:
        net_e = _build(9)
        _, el = _eager_run(net_e, "sgd", {"learning_rate": .05}, [32, 32])
        net_c = _build(9)
        _, stepc, cl = _compiled_run(net_c, "sgd",
                                     {"learning_rate": .05}, [32, 32])
        assert stepc.last_reason == "env_disabled"
        for s in range(2):
            assert (el[s] == cl[s]).all()
        _assert_params_bitexact(net_e, net_c)
    finally:
        del os.environ["MXNET_TPU_COMPILED_STEP"]

    # data-dependent Python control flow -> trace_failed, sticky, but
    # training continues (eager) and still learns
    net_d = _build(10)
    tr_d = Trainer(net_d.collect_params(), "sgd", {"learning_rate": .05})

    def branchy_loss(x, y):
        out = net_d(x)
        if float(out.asnumpy().sum()) > 1e9:   # host sync on a tracer
            out = out * 2
        return LOSS(out, y)

    step_d = tr_d.compile_step(branchy_loss)
    w0 = _params_of(net_d)
    with pytest.warns(UserWarning, match="trace failed"):
        step_d(nd.array(X[0]), nd.array(Y[0]))
    assert step_d.last_reason == "trace_failed"
    step_d(nd.array(X[1]), nd.array(Y[1]))     # sticky: no retrace
    assert step_d.last_reason == "trace_failed"
    assert any((net_d.collect_params()[k].data().asnumpy() != v).any()
               for k, v in w0.items()), "fallback did not train"


def test_frozen_subset_trainer_promotes_untracked_params():
    """Fine-tuning: only HALF the parameters are in the Trainer. The
    frozen parameters the loss reads are promoted to program inputs (not
    baked constants), so mutating one later is picked up without a stale
    result; the trained half stays bit-exact with eager."""
    X, Y = _data(3)
    net_e = _build(11)
    head = {k: p for k, p in net_e.collect_params().items()
            if "dense1" in k}
    tr_e = Trainer(head, "sgd", {"learning_rate": 0.05})
    el = []
    for s in range(3):
        with ag.record():
            l = LOSS(net_e(nd.array(X[s])), nd.array(Y[s]))
        l.backward()
        tr_e.step(32)
        el.append(l.asnumpy())

    net_c = _build(11)
    head_c = {k: p for k, p in net_c.collect_params().items()
              if "dense1" in k}
    tr_c = Trainer(head_c, "sgd", {"learning_rate": 0.05})
    step = tr_c.compile_step(lambda x, y: LOSS(net_c(x), y))
    for s in range(3):
        lc = step(nd.array(X[s]), nd.array(Y[s]))
        assert (el[s] == lc.asnumpy()).all()
    assert step.last_reason is None
    _assert_params_bitexact(net_e, net_c)

    # mutate a frozen param: the next compiled step must see it
    for k, p in net_c.collect_params().items():
        if "dense0_weight" in k:
            p.set_data(p.data() * 0.0)
    lc = step(nd.array(X[0]), nd.array(Y[0])).asnumpy()
    for k, p in net_e.collect_params().items():
        if "dense0_weight" in k:
            p.set_data(p.data() * 0.0)
    with ag.record():
        le = LOSS(net_e(nd.array(X[0])), nd.array(Y[0]))
    le.backward()
    tr_e.step(32)
    assert (le.asnumpy() == lc).all(), \
        "compiled step served a stale frozen parameter"


def test_estimator_compiled_step():
    """Estimator.fit(compiled_step=True): the batch loop runs through
    ONE dispatch per batch (GradientUpdateHandler skips its step), and
    the trained parameters match a plain eager Estimator fit."""
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.metric import Accuracy
    X, Y = _data(4)
    batches = [(nd.array(X[s]), nd.array(Y[s])) for s in range(4)]

    def fit(compiled):
        net = _build(12)
        est = Estimator(net, LOSS, train_metrics=[Accuracy()],
                        trainer=Trainer(net.collect_params(), "sgd",
                                        {"learning_rate": 0.05}))
        est.fit(batches, epochs=1, compiled_step=compiled)
        return net, est

    reg = get_registry()
    compiled_ctr = reg.counter("mxtpu_train_step_compiled_total")
    net_e, _ = fit(False)
    c0 = compiled_ctr.value
    net_c, est_c = fit(True)
    assert compiled_ctr.value - c0 == 4, \
        "estimator batches did not run through the compiled step"
    _assert_params_bitexact(net_e, net_c)
    # the update happened exactly once per batch (a double step would
    # change num_update)
    assert est_c.trainer._optimizer.num_update == 4


def test_device_prefetch_feeds_compiled_step():
    """DevicePrefetchIter -> CompiledTrainStep: staged batches keep
    order and the steady state stays one dispatch per step."""
    from mxnet_tpu.gluon.data.prefetch import DevicePrefetchIter
    reg = get_registry()
    dispatch = reg.counter("mxtpu_train_step_dispatch_total")
    X, Y = _data(4)
    net = _build(13)
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.05})
    step = tr.compile_step(lambda x, y: LOSS(net(x), y))
    src = [(nd.array(X[s]), nd.array(Y[s])) for s in range(4)]
    it = iter(DevicePrefetchIter(src, depth=2))
    step(*next(it))                     # warmup compile
    d0 = dispatch.value
    losses = [step(*b).asnumpy() for b in it]
    assert dispatch.value - d0 == 3
    assert all(np.isfinite(l).all() for l in losses)


def test_sharded_trainer_tail_bucket_no_retrace():
    """parallel.ShardedTrainer: a ragged tail pads to the warm bucket
    instead of retracing the SPMD step program."""
    install_jax_monitoring_bridge()
    from mxnet_tpu import parallel
    reg = get_registry()
    compiles = reg.counter("mxtpu_xla_compile_total")
    net = _build(14)
    tr = parallel.ShardedTrainer(
        net, LOSS, "sgd", {"learning_rate": 0.05})
    rng = np.random.RandomState(3)
    x32 = rng.randn(32, 6).astype(np.float32)
    y32 = (np.arange(32) % 4).astype(np.float32)
    tr.step(x32, y32)                   # trace @ bucket 32
    tr.step(x32, y32)
    c0 = compiles.value
    l = tr.step(x32[:20], y32[:20])     # tail -> padded to 32
    assert compiles.value - c0 == 0, "batch tail retraced the SPMD step"
    assert np.isfinite(float(l.asscalar()))
