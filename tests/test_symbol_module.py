"""Symbol / Executor / Module tests.

Modeled on the reference's tests/python/unittest/test_symbol.py,
test_module.py, test_executor.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym_mod


def _mlp_symbol():
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=16)
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, name="fc2", num_hidden=4)
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def test_symbol_compose_and_arguments():
    net = _mlp_symbol()
    args = net.list_arguments()
    assert "data" in args
    assert "fc1_weight" in args and "fc2_bias" in args
    assert "softmax_label" in args
    assert net.list_outputs() == ["softmax_output"]


def test_symbol_infer_shape():
    net = _mlp_symbol()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(
        data=(8, 10), softmax_label=(8,), fc1_weight=(16, 10),
        fc1_bias=(16,), fc2_weight=(4, 16), fc2_bias=(4,))
    assert out_shapes == [(8, 4)]
    assert aux_shapes == []


def test_symbol_eval():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = (a + b) * 2
    out = c.eval_dict({"a": mx.nd.ones((2, 2)),
                       "b": mx.nd.ones((2, 2)) * 3})
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 2), 8.0))


def test_symbol_json_roundtrip(tmp_path):
    net = _mlp_symbol()
    f = str(tmp_path / "net-symbol.json")
    net.save(f)
    net2 = sym_mod.load(f)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()


def test_symbol_getitem_multi_output():
    x = mx.sym.var("x")
    g = sym_mod.Group([x * 2, x + 1])
    assert g.num_outputs == 2
    outs = g.eval_dict({"x": mx.nd.ones((2,))})
    np.testing.assert_allclose(outs[0].asnumpy(), [2, 2])
    np.testing.assert_allclose(outs[1].asnumpy(), [2, 2])


def test_executor_forward_backward():
    x = mx.sym.var("x")
    w = mx.sym.var("w")
    y = mx.sym.sum(x * w)
    ex = y.bind(args={"x": mx.nd.array([1.0, 2.0, 3.0]),
                      "w": mx.nd.array([4.0, 5.0, 6.0])},
                args_grad={"x": mx.nd.zeros((3,)),
                           "w": mx.nd.zeros((3,))})
    out = ex.forward(is_train=True)[0]
    assert float(out.asscalar()) == 32.0
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["x"].asnumpy(), [4, 5, 6])
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(), [1, 2, 3])


def test_executor_simple_bind():
    net = _mlp_symbol()
    ex = net.simple_bind(data=(8, 10), softmax_label=(8,))
    assert ex.arg_dict["fc1_weight"].shape == (16, 10)
    out = ex.forward(is_train=False, data=mx.nd.ones((8, 10)))[0]
    assert out.shape == (8, 4)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1),
                               np.ones(8), rtol=1e-5)


def test_module_fit_convergence():
    np.random.seed(0)
    mx.random.seed(0)
    n = 512
    x = np.random.randn(n, 16).astype(np.float32)
    y = (x[:, :8].sum(axis=1) > x[:, 8:].sum(axis=1)).astype(np.float32)
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True)
    val = mx.io.NDArrayIter(x, y, batch_size=32)

    net = _mlp_symbol()
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(train, eval_data=val, num_epoch=6,
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.initializer.Xavier())
    score = mod.score(val, "acc")
    assert score[0][1] > 0.9, score


def test_module_save_load_checkpoint(tmp_path):
    np.random.seed(0)
    net = _mlp_symbol()
    mod = mx.mod.Module(net)
    mod.bind(data_shapes=[("data", (4, 10))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    prefix = str(tmp_path / "chk")
    mod.save_checkpoint(prefix, 3)

    mod2 = mx.mod.Module.load(prefix, 3)
    mod2.bind(data_shapes=[("data", (4, 10))],
              label_shapes=[("softmax_label", (4,))])
    batch = mx.io.DataBatch(data=[mx.nd.ones((4, 10))],
                            label=[mx.nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               mod2.get_outputs()[0].asnumpy(), rtol=1e-5)


def test_ndarray_iter_pad_and_shuffle():
    x = np.arange(20).reshape(10, 2).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=3, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 4

    it2 = mx.io.NDArrayIter(x, y, batch_size=5, shuffle=True)
    seen = np.sort(np.concatenate(
        [b.label[0].asnumpy() for b in it2]))
    np.testing.assert_array_equal(seen, np.arange(10))


def test_bucketing_module():
    np.random.seed(0)

    def sym_gen(seq_len):
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        fc = mx.sym.FullyConnected(data, name="fc_shared", num_hidden=4)
        out = mx.sym.SoftmaxOutput(fc, label, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8)
    mod.bind(data_shapes=[("data", (2, 8))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
    for key, shape in ((8, (2, 8)), (8, (2, 8))):
        batch = mx.io.DataBatch(
            data=[mx.nd.ones(shape)], label=[mx.nd.zeros((2,))],
            bucket_key=key,
            provide_data=[mx.io.DataDesc("data", shape)],
            provide_label=[mx.io.DataDesc("softmax_label", (2,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    assert mod.get_outputs()[0].shape == (2, 4)


def test_sequential_module_train():
    """SequentialModule chains two Modules with auto-wiring and trains
    (reference: module/sequential_module.py:28, tests test_module.py
    test_module_layout/test_sequential)."""
    from mxnet_tpu.module import SequentialModule, Module
    from mxnet_tpu.io.io import DataBatch

    d = mx.sym.var("data")
    net1 = mx.sym.FullyConnected(d, name="fc1", num_hidden=16)
    net1 = mx.sym.Activation(net1, act_type="relu", name="a1")
    d2 = mx.sym.var("data")
    net2 = mx.sym.FullyConnected(d2, name="fc2", num_hidden=4)
    net2 = mx.sym.SoftmaxOutput(net2, name="softmax")

    seq = SequentialModule()
    seq.add(Module(net1, label_names=None)) \
       .add(Module(net2), take_labels=True, auto_wiring=True)

    seq.bind(data_shapes=[("data", (8, 10))],
             label_shapes=[("softmax_label", (8,))])
    mx.random.seed(0)
    seq.init_params(initializer=mx.initializer.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.5),
                                         ("momentum", 0.9)))

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 10).astype(np.float32))
    y = mx.nd.array((np.arange(8) % 4).astype(np.float32))

    def step():
        batch = DataBatch(data=[x], label=[y])
        seq.forward(batch, is_train=True)
        out = seq.get_outputs()[0].asnumpy()
        seq.backward()
        seq.update()
        probs = out[np.arange(8), (np.arange(8) % 4)]
        return -np.log(np.maximum(probs, 1e-9)).mean()

    losses = [step() for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    arg_params, _ = seq.get_params()
    assert "fc1_weight" in arg_params and "fc2_weight" in arg_params
    assert seq.output_shapes == [(8, 4)]


def test_python_loss_module_chain():
    """PythonLossModule provides the loss gradient for the module below
    it (reference: module/python_module.py:243)."""
    from mxnet_tpu.module import SequentialModule, Module, PythonLossModule
    from mxnet_tpu.io.io import DataBatch

    d = mx.sym.var("data")
    net = mx.sym.FullyConnected(d, name="fc", num_hidden=4)

    def ce_grad(scores, labels):
        s = scores.asnumpy()
        e = np.exp(s - s.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        lab = labels.asnumpy().astype(int)
        p[np.arange(len(lab)), lab] -= 1.0
        return mx.nd.array(p / len(lab))

    seq = SequentialModule()
    seq.add(Module(net, label_names=None)) \
       .add(PythonLossModule(grad_func=ce_grad), take_labels=True,
            auto_wiring=True)
    seq.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mx.random.seed(1)
    seq.init_params(initializer=mx.initializer.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 1.0),))

    rng = np.random.RandomState(1)
    x = mx.nd.array(rng.randn(8, 6).astype(np.float32))
    y = mx.nd.array((np.arange(8) % 4).astype(np.float32))

    def loss_now():
        seq.forward(DataBatch(data=[x], label=[y]), is_train=True)
        s = seq.get_outputs()[0].asnumpy()
        e = np.exp(s - s.max(axis=1, keepdims=True))
        p = e / e.sum(axis=1, keepdims=True)
        return -np.log(p[np.arange(8), (np.arange(8) % 4)]).mean()

    l0 = loss_now()
    for _ in range(20):
        seq.forward(DataBatch(data=[x], label=[y]), is_train=True)
        seq.backward()
        seq.update()
    l1 = loss_now()
    assert l1 < l0 * 0.5, (l0, l1)


def test_context_memory_info_surface():
    """memory_info degrades gracefully where PJRT exposes no stats and
    returns (free, total) ints where it does (SURVEY §7 memory-stats)."""
    free, total = mx.context.current_context().memory_info()
    assert free is None or isinstance(free, int)
    assert total is None or isinstance(total, int)
    f2, t2 = mx.context.gpu_memory_info(0)
    assert f2 is None or isinstance(f2, int)
