"""Custom Python operators (reference: python/mxnet/operator.py,
src/operator/custom/custom.cc; test strategy from
tests/python/unittest/test_operator.py test_custom_op)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, operator
import mxnet_tpu.autograd as ag


class _Sigmoid(operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        g = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], g * y * (1.0 - y))


@operator.register("test_sigmoid")
class _SigmoidProp(operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _Sigmoid()


class _ScaledAdd(operator.CustomOp):
    def __init__(self, scale):
        self.scale = scale

    def forward(self, is_train, req, in_data, out_data, aux):
        a, b = in_data[0].asnumpy(), in_data[1].asnumpy()
        self.assign(out_data[0], req[0], a + self.scale * b)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        g = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], g)
        self.assign(in_grad[1], req[1], self.scale * g)


@operator.register("test_scaled_add")
class _ScaledAddProp(operator.CustomOpProp):
    def __init__(self, scale="2.0"):
        super().__init__(need_top_grad=True)
        self.scale = float(scale)

    def list_arguments(self):
        return ["a", "b"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _ScaledAdd(self.scale)


def test_custom_forward_and_grad_match_builtin():
    x_np = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    x = nd.array(x_np)
    x.attach_grad()
    with ag.record():
        y = nd.Custom(x, op_type="test_sigmoid")
        y.sum().backward()
    gx = x.grad.asnumpy()

    x2 = nd.array(x_np)
    x2.attach_grad()
    with ag.record():
        y2 = nd.sigmoid(x2)
        y2.sum().backward()
    np.testing.assert_allclose(y.asnumpy(), y2.asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(gx, x2.grad.asnumpy(), rtol=1e-5)


def test_custom_multi_input_with_param():
    a = nd.array(np.ones((2, 2), np.float32))
    b = nd.array(np.full((2, 2), 3.0, np.float32))
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        out = nd.Custom(a, b, op_type="test_scaled_add", scale="4.0")
        out.sum().backward()
    np.testing.assert_allclose(out.asnumpy(), 1.0 + 4.0 * 3.0)
    np.testing.assert_allclose(a.grad.asnumpy(), 1.0)
    np.testing.assert_allclose(b.grad.asnumpy(), 4.0)


def test_unregistered_op_type_raises():
    with pytest.raises(ValueError, match="not registered"):
        nd.Custom(nd.array(np.ones(2)), op_type="nope_never_registered")


def test_custom_op_trains_inside_gluon_net():
    """The reference's headline custom-op scenario: a Python op embedded
    in a net, trained end to end — including under hybridize (the
    callback becomes a host call inside the jitted program)."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    class CustomActNet(nn.HybridSequential):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.fc1 = nn.Dense(16)
                self.fc2 = nn.Dense(2)

        def forward(self, x):
            h = nd.Custom(self.fc1(x), op_type="test_sigmoid")
            return self.fc2(h)

    mx.random.seed(0)
    net = CustomActNet()
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    x_np = rng.randn(32, 8).astype(np.float32)
    y_np = (x_np.sum(axis=1) > 0).astype(np.float32)
    x, y = nd.array(x_np), nd.array(y_np)
    losses = []
    for _ in range(60):
        with ag.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])

    # hybridized: pure_callback inside jit
    net.hybridize()
    with ag.pause():
        out_j = net(x).asnumpy()
    assert np.isfinite(out_j).all()
