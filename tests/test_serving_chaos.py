"""Serving chaos matrix: overload-proof request semantics (ISSUE 10).

The invariant pinned here, under EVERY injected fault: **every
submitted Future resolves** — with a result or a typed error — no
hangs, no silent drops, no KV-block leaks, and the zero-steady-state-
recompile contract intact. The fault switchboard is the same
``resilience.faults`` harness the training side uses, extended into
the serving hot paths (``serving.dispatch`` / ``serving.worker`` /
``llm.prefill`` / ``llm.decode`` / ``llm.worker``):

- dispatch raise (transient → bisect-retry recovers; persistent →
  poison row isolated, ONLY its Future fails, with the original
  exception);
- slow compute (injected latency / Gate-parked dispatch → queued
  deadlines expire typed BEFORE wasting a dispatch);
- worker death mid-batch (InjectedCrash → every queued + in-flight
  Future resolves typed; later submits raise ServerClosed);
- preemption mid-drain and drain-under-load (shed vs evict vs served
  deterministic, each counted once);
- queue overflow (bounded queue sheds with typed Overloaded at
  submit);
- circuit breaker (persistent failures → CircuitOpenError fail-fast,
  half-open probe heals).

Also pinned: the unified typed exception hierarchy
(`serving.ServingError` satellite) and `PagedKVCache.check()` block
accounting after every LLM scenario.
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.serving import (  # noqa: E402
    CircuitOpenError, DeadlineExceededError, Overloaded,
    SequenceEvictedError, ServerClosed, ServingError)
from mxnet_tpu.serving.llm import (  # noqa: E402
    TinyDecoder, DecoderConfig, LLMServer, greedy_decode_reference)
from mxnet_tpu.resilience import faults  # noqa: E402

ITEM = (2,)


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


def _echo_server(name, fn=None, **kw):
    kw.setdefault("buckets", [1, 2, 4])
    kw.setdefault("max_delay_ms", 20.0)
    return serving.ModelServer(fn or (lambda b: b * 2.0),
                               item_shape=ITEM, dtype="float32",
                               name=name, **kw).start()


def _resolve_all(futs, timeout=30):
    """The chaos invariant: every Future resolves (result or typed
    error) — no hangs. Returns (results, errors)."""
    results, errors = [], []
    for f in futs:
        try:
            results.append(f.result(timeout=timeout))
        except BaseException as exc:
            errors.append(exc)
    return results, errors


# ---------------------------------------------------- error hierarchy --
def test_typed_error_hierarchy_unified():
    """Satellite: one exported base class covers every serving-side
    typed error, and the legacy RuntimeError contract still holds."""
    for exc_type in (ServerClosed, Overloaded, CircuitOpenError,
                     DeadlineExceededError, SequenceEvictedError):
        assert issubclass(exc_type, ServingError)
        assert issubclass(exc_type, RuntimeError)
    assert issubclass(CircuitOpenError, Overloaded)
    # the hierarchy is importable from the package root AND the llm
    # subpackage re-exports the decode-side members
    from mxnet_tpu.serving import llm as llm_mod
    assert llm_mod.SequenceEvictedError is SequenceEvictedError
    assert llm_mod.DeadlineExceededError is DeadlineExceededError
    # submit-after-close raises through the hierarchy
    q = serving.MicroBatchQueue()
    q.close()
    with pytest.raises(ServingError):
        q.submit(1)
    err = DeadlineExceededError("x", tokens=[1, 2], seq_id=7)
    assert err.tokens == [1, 2] and err.seq_id == 7


# ------------------------------------------- ModelServer chaos matrix --
def test_transient_dispatch_raise_recovers_all_rows():
    """One injected dispatch raise: the bisect retry re-runs the rows
    and every request is still served — zero failed Futures."""
    srv = _echo_server("chaos_transient")
    faults.script("serving.dispatch", [RuntimeError("transient blip")])
    futs = [srv.submit(np.full(ITEM, i, np.float32)) for i in range(4)]
    results, errors = _resolve_all(futs)
    srv.shutdown()
    assert not errors
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r, np.full(ITEM, 2.0 * i))
    st = srv.stats()
    assert st["requests_failed"] == 0
    assert st["requests_completed"] == 4
    assert st["breaker_state"] == 0     # one blip does not trip


def test_poison_row_isolated_rest_served():
    """A row the model cannot process fails ONLY its own Future, with
    the ORIGINAL exception; every other row in its micro-batch is
    served, and each request is counted exactly once."""
    def fn(batch):
        if (batch == 99.0).any():
            raise ValueError("poison row")
        return batch * 2.0

    srv = _echo_server("chaos_poison", fn=fn, buckets=[1, 2, 4, 8],
                       max_delay_ms=50.0)
    vals = [1, 2, 99, 4, 5, 6, 7, 8]
    futs = [srv.submit(np.full(ITEM, v, np.float32)) for v in vals]
    results, errors = _resolve_all(futs)
    srv.shutdown()
    assert len(results) == 7 and len(errors) == 1
    assert isinstance(errors[0], ValueError)       # original, unmasked
    assert "poison row" in str(errors[0])
    st = srv.stats()
    assert st["poison_isolated"] == 1
    assert st["requests_completed"] == 7
    assert st["requests_failed"] == 1
    assert st["requests_completed"] + st["requests_failed"] \
        == st["requests_submitted"]


def test_slow_compute_expires_queued_deadlines():
    """Gate-parked dispatch (injected slow compute): a request whose
    deadline expires while queued fails typed BEFORE any dispatch is
    spent on it; requests without deadlines are unaffected."""
    gate = faults.block_at("serving.dispatch")
    srv = _echo_server("chaos_slow", buckets=[1], max_delay_ms=0.1)
    f_slow = srv.submit(np.zeros(ITEM, np.float32))     # parks in gate
    assert gate.wait_reached(10)
    f_dead = srv.submit(np.zeros(ITEM, np.float32), deadline_ms=5)
    f_live = srv.submit(np.zeros(ITEM, np.float32))     # no deadline
    time.sleep(0.03)                                    # deadline passes
    gate.release()
    np.testing.assert_array_equal(f_slow.result(timeout=30), 0.0)
    np.testing.assert_array_equal(f_live.result(timeout=30), 0.0)
    with pytest.raises(DeadlineExceededError):
        f_dead.result(timeout=30)
    srv.shutdown()
    st = srv.stats()
    assert st["deadline_expired"] == 1
    assert st["requests_failed"] == 1


def test_deadline_already_expired_fails_at_submit():
    srv = _echo_server("chaos_dl0")
    with pytest.raises(DeadlineExceededError):
        srv.submit(np.zeros(ITEM, np.float32), deadline_ms=0)
    srv.shutdown()
    assert srv.stats()["deadline_expired"] == 1


def test_estimated_wait_sheds_unmeetable_deadline():
    """Once the service histogram knows dispatches are slow, a request
    whose deadline cannot possibly be met is shed AT SUBMIT."""
    faults.delay_at("serving.dispatch", 0.06)
    srv = _echo_server("chaos_est", buckets=[1], max_delay_ms=0.1)
    # teach the histogram: a few slow dispatches
    for _ in range(3):
        srv.predict(np.zeros(ITEM, np.float32), timeout=30)
    gate = faults.block_at("serving.dispatch")
    f_busy = srv.submit(np.zeros(ITEM, np.float32))
    assert gate.wait_reached(10)
    # queue up work so the estimator sees a backlog
    f_q = srv.submit(np.zeros(ITEM, np.float32))
    with pytest.raises(Overloaded) as ei:
        srv.submit(np.zeros(ITEM, np.float32), deadline_ms=1.0)
    assert ei.value.reason == "deadline_unmeetable"
    gate.release()
    _resolve_all([f_busy, f_q])
    srv.shutdown()
    assert srv.stats()["shed"].get("deadline_unmeetable") == 1


def test_queue_overflow_sheds_typed():
    """Bounded queue: past MXNET_TPU_SERVE_MAX_QUEUE, submit fails
    fast with Overloaded(queue_full) instead of growing the queue;
    every ADMITTED request still resolves."""
    gate = faults.block_at("serving.dispatch")
    srv = _echo_server("chaos_full", buckets=[1], max_delay_ms=0.1,
                       max_queue=2)
    f_busy = srv.submit(np.zeros(ITEM, np.float32))
    assert gate.wait_reached(10)
    admitted = [srv.submit(np.zeros(ITEM, np.float32))
                for _ in range(2)]
    shed = 0
    for _ in range(3):
        with pytest.raises(Overloaded) as ei:
            srv.submit(np.zeros(ITEM, np.float32))
        assert ei.value.reason == "queue_full"
        shed += 1
    gate.release()
    results, errors = _resolve_all([f_busy] + admitted)
    srv.shutdown()
    assert len(results) == 3 and not errors
    st = srv.stats()
    assert st["shed"]["queue_full"] == shed == 3
    assert st["requests_submitted"] == 3        # shed never admitted


def test_worker_death_mid_batch_resolves_everything():
    """InjectedCrash at the serving.worker point: the worker thread
    dies mid-batch, yet every queued and in-flight Future resolves
    typed, and later submits raise ServerClosed."""
    faults.crash_at_point("serving.worker", nth=1)
    srv = _echo_server("chaos_death", buckets=[1, 2, 4],
                       max_delay_ms=100.0)
    futs = [srv.submit(np.zeros(ITEM, np.float32)) for _ in range(5)]
    results, errors = _resolve_all(futs, timeout=30)
    assert len(results) + len(errors) == 5      # nothing hangs
    assert all(isinstance(e, ServerClosed) for e in errors)
    assert errors, "the crash must have failed at least the batch"
    faults.reset()
    with pytest.raises(ServerClosed):
        srv.submit(np.zeros(ITEM, np.float32))
    srv.shutdown()                               # must not hang


def test_breaker_trips_then_half_open_probe_heals():
    """Persistent dispatch failures trip the breaker (typed fail-fast
    at submit AND for queued work); after the cooldown a half-open
    probe succeeds and the breaker closes."""
    state = {"broken": True}

    def fn(batch):
        if state["broken"]:
            raise RuntimeError("backend down")
        return batch + 1.0

    srv = _echo_server("chaos_breaker", fn=fn, buckets=[1],
                       max_delay_ms=0.1, breaker_threshold=2,
                       breaker_cooldown_ms=50)
    # two consecutive failing batch dispatches trip it
    for _ in range(2):
        f = srv.submit(np.zeros(ITEM, np.float32))
        with pytest.raises(RuntimeError):
            f.result(timeout=30)
    deadline = time.monotonic() + 10
    while (srv.stats()["breaker_state"] != 1
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert srv.stats()["breaker_state"] == 1     # OPEN
    with pytest.raises(CircuitOpenError) as ei:
        srv.submit(np.zeros(ITEM, np.float32))
    assert ei.value.reason == "breaker_open"
    assert srv.stats()["shed"]["breaker_open"] == 1
    # heal the backend; past the cooldown the probe closes the breaker
    state["broken"] = False
    time.sleep(0.12)
    out = srv.predict(np.zeros(ITEM, np.float32), timeout=30)
    np.testing.assert_array_equal(out, 1.0)
    srv.shutdown()
    assert srv.stats()["breaker_state"] == 0     # CLOSED again


def test_recurring_poison_rows_do_not_trip_breaker():
    """Regression: isolation sub-dispatches that SUCCEED prove the
    backend is healthy — a misbehaving client interleaving poison rows
    into traffic must not accumulate consecutive breaker failures into
    a self-inflicted outage."""
    def fn(batch):
        if (batch == 99.0).any():
            raise ValueError("poison row")
        return batch

    srv = _echo_server("chaos_poisbrk", fn=fn, buckets=[1, 2],
                       max_delay_ms=30.0, breaker_threshold=2)
    for _ in range(4):      # 4 poison-containing rounds > threshold
        f_bad = srv.submit(np.full(ITEM, 99.0, np.float32))
        f_ok = srv.submit(np.full(ITEM, 1.0, np.float32))
        with pytest.raises(ValueError):
            f_bad.result(timeout=30)
        np.testing.assert_array_equal(f_ok.result(timeout=30), 1.0)
    assert srv.stats()["breaker_state"] == 0     # never tripped
    srv.shutdown()
    assert srv.stats()["poison_isolated"] == 4
    assert srv.stats()["requests_completed"] == 4


def test_drain_under_load_shed_evict_served_counted_once():
    """Satellite: shutdown drain deadline x per-request deadlines x a
    full bounded queue — the outcome of every request is deterministic
    (served / shed / deadline-expired / drain-rejected) and each is
    counted exactly once in the metrics."""
    gate = faults.block_at("serving.dispatch")
    srv = _echo_server("chaos_drain", buckets=[1], max_delay_ms=0.1,
                       max_queue=3)
    f_busy = srv.submit(np.zeros(ITEM, np.float32))   # served (parked)
    assert gate.wait_reached(10)
    f_ok = srv.submit(np.zeros(ITEM, np.float32))     # served on drain
    f_dead = srv.submit(np.zeros(ITEM, np.float32),
                        deadline_ms=5)                # expires queued
    f_q = srv.submit(np.zeros(ITEM, np.float32))      # queue now full
    with pytest.raises(Overloaded):                   # shed
        srv.submit(np.zeros(ITEM, np.float32))
    time.sleep(0.03)                                  # f_dead expires

    done = threading.Event()

    def _shutdown():
        srv.shutdown(drain=True)                      # unbounded drain
        done.set()

    t = threading.Thread(target=_shutdown, daemon=True)
    t.start()
    gate.release()
    assert done.wait(30)
    served, errors = _resolve_all([f_busy, f_ok, f_dead, f_q])
    assert len(served) == 3                           # busy, ok, q
    assert len(errors) == 1
    assert isinstance(errors[0], DeadlineExceededError)
    st = srv.stats()
    assert st["requests_submitted"] == 4
    assert st["requests_completed"] == 3
    assert st["requests_failed"] == 1
    assert st["deadline_expired"] == 1
    assert st["shed"] == {"queue_full": 1}
    # exactly-once: admitted outcomes partition the submitted set
    assert (st["requests_completed"] + st["requests_failed"]
            == st["requests_submitted"])


# -------------------------------------------------- LLM chaos matrix --
VOCAB, BS, CTX = 17, 8, 32


@pytest.fixture(scope="module")
def model():
    return TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=16, num_layers=1, num_heads=2,
        d_ff=32, max_context=CTX))


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(seed=0)


def _llm(model, params, name, **kw):
    srv = LLMServer(model, params, name=name, max_seqs=2,
                    block_size=BS, max_context=CTX, **kw)
    srv.warmup()
    srv.start()
    return srv


def _assert_kv_clean(srv):
    eng = srv.engine
    assert eng.cache.allocator.num_used == 0
    assert eng.cache.check(live_block_ids=[])


@pytest.mark.slow   # ~13s on 1 CPU (tier-1 budget); poison
# isolation stays fast via test_llm_decode_poison_isolated +
# test_llm_poison_with_shared_prefix_isolated
def test_llm_prefill_poison_isolated(model, params):
    """A poison prompt (prefill raises) fails only ITS Future with the
    original exception; other sequences decode normally; no KV leak."""
    srv = _llm(model, params, "llmc_pois")
    faults.script("llm.prefill", [ValueError("poison prompt")])
    f_bad = srv.submit([1, 2, 3], 4)
    f_ok = srv.submit([2, 3], 4)
    with pytest.raises(ValueError, match="poison prompt"):
        f_bad.result(timeout=30)
    ref = greedy_decode_reference(model, params, [2, 3], 4)
    assert f_ok.result(timeout=30).tokens == ref
    srv.shutdown()
    assert srv.stats()["poison_isolated"] == 1
    _assert_kv_clean(srv)


def test_llm_decode_transient_bitexact_zero_recompiles(model, params):
    """One injected decode raise: the bisect retry re-dispatches the
    SAME fixed shape — token streams stay bit-exact vs the eager
    reference and the compile counter does not move."""
    srv = _llm(model, params, "llmc_trans")
    prompts = [[1, 2], [3, 4, 5]]
    with serving.CompileCounter() as cc:
        faults.script("llm.decode", [RuntimeError("transient")])
        futs = [srv.submit(p, 6) for p in prompts]
        res = [f.result(timeout=60) for f in futs]
    srv.shutdown()
    assert cc.count == 0, f"{cc.count} recompiles during chaos"
    for p, r in zip(prompts, res):
        assert r.tokens == greedy_decode_reference(model, params, p, 6)
    _assert_kv_clean(srv)


def test_llm_decode_poison_isolated(model, params):
    """Persistent per-row decode failure: top-level dispatch, the
    half, and the leaf retry all raise (3 scripted faults) — the
    poisoned sequence fails with the original exception, the other
    sequence keeps decoding to completion."""
    srv = _llm(model, params, "llmc_dpois")
    # deterministic: park the first decode launch on a Gate so BOTH
    # sequences are in the batch, arm the script while parked, then
    # release — the very next decode consumes the fault schedule
    gate = faults.block_at("llm.decode")
    f1 = srv.submit([1, 2, 3], 12)
    f2 = srv.submit([4, 5], 12)
    assert gate.wait_reached(30)
    faults.script("llm.decode", [RuntimeError("poison-decode")] * 3)
    gate.release()
    r1 = r2 = None
    try:
        r1 = f1.result(timeout=60)
    except RuntimeError as e:
        r1 = e
    try:
        r2 = f2.result(timeout=60)
    except RuntimeError as e:
        r2 = e
    srv.shutdown()
    outcomes = [r1, r2]
    poisoned = [r for r in outcomes if isinstance(r, RuntimeError)]
    finished = [r for r in outcomes if not isinstance(r, RuntimeError)]
    assert len(poisoned) == 1 and len(finished) == 1
    assert "poison-decode" in str(poisoned[0])
    assert len(finished[0].tokens) == 12
    assert srv.stats()["poison_isolated"] == 1
    _assert_kv_clean(srv)


def test_llm_worker_death_resolves_everything(model, params):
    """InjectedCrash in the engine loop: every Future resolves, the
    pool has zero leaked blocks, later submits raise ServerClosed."""
    srv = _llm(model, params, "llmc_death")
    faults.crash_at_point("llm.worker", nth=2)
    futs = [srv.submit([1 + i, 2], 10) for i in range(3)]
    resolved = 0
    for f in futs:
        try:
            f.result(timeout=30)
        except BaseException:
            pass
        resolved += 1
    assert resolved == 3
    faults.reset()
    deadline = time.monotonic() + 10
    while srv.running and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(ServerClosed):
        srv.submit([1], 1)
    _assert_kv_clean(srv)


def test_llm_queue_overflow_and_drain_under_load(model, params):
    """Satellite (LLM side): bounded admission + drain deadline under
    load — shed vs evicted vs served is deterministic and each request
    is counted once; KV accounting stays clean."""
    srv = LLMServer(model, params, name="llmc_full", max_seqs=1,
                    block_size=BS, max_context=CTX, max_queue=2)
    srv.warmup()
    srv.start()
    gate = faults.block_at("llm.decode")
    f_run = srv.submit([1, 2, 3], 20)       # running, parked at gate
    assert gate.wait_reached(30)
    w1 = srv.submit([2, 3], 5)              # waiting
    w2 = srv.submit([3, 4], 5)              # waiting (queue now full)
    with pytest.raises(Overloaded) as ei:
        srv.submit([4, 5], 5)
    assert ei.value.reason == "queue_full"
    with pytest.raises(DeadlineExceededError):
        srv.submit([4, 5], 5, deadline_ms=0)

    done = threading.Event()

    def _shutdown():
        srv.shutdown(drain=True, deadline_ms=0.0)   # evict now, typed
        done.set()

    t = threading.Thread(target=_shutdown, daemon=True)
    t.start()
    gate.release()
    assert done.wait(60)
    outcomes = {"evicted": 0, "served": 0}
    for f in (f_run, w1, w2):
        try:
            f.result(timeout=10)
            outcomes["served"] += 1
        except SequenceEvictedError as e:
            assert e.reason == "drain_deadline"
            outcomes["evicted"] += 1
    assert outcomes["evicted"] + outcomes["served"] == 3
    assert outcomes["evicted"] >= 2          # deadline_ms=0 binds
    st = srv.stats()
    assert st["shed"] == {"queue_full": 1}
    assert st["deadline_expired"] == 1       # the deadline_ms=0 submit
    assert (st["requests_completed"] + st["requests_evicted"]
            + st["requests_failed"] == st["requests_submitted"])
    _assert_kv_clean(srv)


def test_llm_deadline_expires_waiting_and_running(model, params):
    """End-to-end deadlines on the decode path: a WAITING sequence
    whose deadline expires dies before costing a prefill; a RUNNING
    one is evicted typed WITH its partial tokens."""
    srv = LLMServer(model, params, name="llmc_dl", max_seqs=1,
                    block_size=BS, max_context=CTX)
    srv.warmup()
    srv.start()
    gate = faults.block_at("llm.decode")
    f_run = srv.submit([1, 2, 3], 20, deadline_ms=150.0)
    assert gate.wait_reached(30)            # running, >=1 token out
    f_wait = srv.submit([2, 3], 5, deadline_ms=50.0)   # never admitted
    time.sleep(0.2)                         # both deadlines pass
    gate.release()
    faults.reset()
    with pytest.raises(DeadlineExceededError) as e_run:
        f_run.result(timeout=60)
    with pytest.raises(DeadlineExceededError) as e_wait:
        f_wait.result(timeout=60)
    assert len(e_run.value.tokens) >= 1     # partial tokens carried
    assert e_wait.value.tokens == []
    srv.shutdown()
    assert srv.stats()["deadline_expired"] == 2
    _assert_kv_clean(srv)


def test_llm_generate_timeout_cancels_sequence(model, params):
    """Satellite: generate(timeout=) cancels the underlying sequence —
    KV blocks and the decode slot are released, the Future resolves
    typed with partial tokens, and the server keeps serving."""
    srv = _llm(model, params, "llmc_cancel")
    # injected slow decode: ~40ms/step makes a 20-token generation far
    # outlive the 0.1s timeout while the engine keeps iterating (so it
    # can observe the cancel), with no wall-clock race on the outcome
    faults.delay_at("llm.decode", 0.04)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceededError) as ei:
        srv.generate([1, 2, 3], 20, timeout=0.1)
    assert time.monotonic() - t0 >= 0.1
    assert ei.value.reason == "timeout"
    assert len(ei.value.tokens) < 20        # partial generation carried
    faults.reset()
    # blocks released, slot free: the server still serves new work
    ref = greedy_decode_reference(model, params, [4, 5], 4)
    assert srv.generate([4, 5], 4, timeout=60).tokens == ref
    srv.shutdown()
    assert srv.stats()["requests_evicted"] >= 1
    _assert_kv_clean(srv)


def test_llm_breaker_trips_on_persistent_prefill_failure(model, params):
    """A hard-down backend (every prefill raises) trips the breaker:
    submits fail fast with CircuitOpenError; after the cooldown a
    healthy probe closes it and serving resumes."""
    srv = _llm(model, params, "llmc_brk", breaker_threshold=2,
               breaker_cooldown_ms=50)
    faults.script("llm.prefill", [RuntimeError("backend down")] * 2)
    for i in range(2):
        with pytest.raises(RuntimeError, match="backend down"):
            srv.submit([1 + i, 2], 4).result(timeout=30)
    deadline = time.monotonic() + 10
    while (srv.stats()["breaker_state"] != 1
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert srv.stats()["breaker_state"] == 1
    with pytest.raises(CircuitOpenError):
        srv.submit([1], 2)
    assert srv.stats()["shed"]["breaker_open"] == 1
    faults.reset()
    time.sleep(0.12)                        # cooldown passes
    ref = greedy_decode_reference(model, params, [3, 4], 3)
    assert srv.generate([3, 4], 3, timeout=60).tokens == ref
    srv.shutdown()
    assert srv.stats()["breaker_state"] == 0
    _assert_kv_clean(srv)


def test_llm_breaker_stays_open_while_decode_succeeds(model, params):
    """An OPEN breaker must not be closed by decode launches of
    already-admitted sequences: only a post-cooldown probe may heal
    it. (Regression: a prefill-down backend with live decodes used to
    flap the breaker shut on every decode success.)"""
    srv = _llm(model, params, "llmc_brk2", breaker_threshold=2,
               breaker_cooldown_ms=60000)      # cooldown >> test
    # a long-running healthy sequence keeps the decode path busy
    gate = faults.block_at("llm.decode")
    f_live = srv.submit([1, 2, 3], CTX - 8)
    assert gate.wait_reached(30)
    gate.release()                              # decode now free-runs
    faults.script("llm.prefill", [RuntimeError("backend down")] * 2)
    for i in range(2):
        with pytest.raises(RuntimeError, match="backend down"):
            srv.submit([2 + i, 3], 4).result(timeout=30)
    deadline = time.monotonic() + 10
    while (srv.stats()["breaker_state"] != 1
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert srv.stats()["breaker_state"] == 1
    # decode keeps succeeding for f_live, yet admission STAYS rejected
    tok0 = srv.stats()["tokens_generated"]
    deadline = time.monotonic() + 10
    while (srv.stats()["tokens_generated"] < tok0 + 3
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert srv.stats()["tokens_generated"] >= tok0 + 3
    with pytest.raises(CircuitOpenError):
        srv.submit([5, 6], 2)
    assert srv.stats()["breaker_state"] == 1    # still open
    srv.shutdown(drain=True, deadline_ms=0.0)
    try:
        f_live.result(timeout=10)   # finished before the shutdown, or
    except ServingError:
        pass                        # evicted typed by it — both fine
    _assert_kv_clean(srv)


def test_llm_preemption_mid_drain_under_injected_latency(model, params):
    """Preemption (guard-style drain) while dispatches are slow: the
    deadline-bounded drain evicts what cannot finish — typed, partial
    tokens carried — and block accounting survives the churn."""
    import signal
    from mxnet_tpu.resilience import PreemptionGuard
    guard = PreemptionGuard(signals=(signal.SIGUSR1,)).install()
    try:
        srv = _llm(model, params, "llmc_preempt")
        srv.attach_preemption_guard(guard, poll_s=0.01,
                                    deadline_ms=0.0)   # evict now
        faults.delay_at("llm.decode", 0.02)
        futs = [srv.submit([1 + i, 2], CTX - 8) for i in range(4)]
        deadline = time.monotonic() + 30
        while (srv.stats()["tokens_generated"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.005)
        os.kill(os.getpid(), signal.SIGUSR1)
        results, errors = _resolve_all(futs, timeout=60)
        assert len(results) + len(errors) == 4
        assert all(isinstance(e, SequenceEvictedError) for e in errors)
        assert errors                        # deadline 0 must evict
        assert any(e.tokens for e in errors)  # partials carried
        deadline = time.monotonic() + 10
        while srv.running and time.monotonic() < deadline:
            time.sleep(0.01)
        _assert_kv_clean(srv)
    finally:
        guard.uninstall()


def _spec_server(model, params, name):
    """A speculative-decoding server for the mid-verify chaos cases:
    small draft model sharing the target's vocab/context."""
    draft = TinyDecoder(DecoderConfig(
        vocab_size=VOCAB, d_model=8, num_layers=1, num_heads=1,
        d_ff=16, max_context=CTX))
    srv = LLMServer(model, params, name=name, max_seqs=2,
                    block_size=BS, max_context=CTX, draft_model=draft,
                    draft_params=draft.init_params(seed=5), spec_k=2)
    srv.warmup()
    srv.start()
    return srv


@pytest.mark.slow   # ~23s on 1 CPU (tier-1 budget); the
# drain-mid-verify case below keeps the typed-partial-tokens
# contract in the fast gate
def test_llm_mid_verify_death_resolves_typed_partial_tokens(model,
                                                            params):
    """Chaos matrix (ISSUE 12): the engine thread dies MID-VERIFY —
    between draft proposals and the commit, while sequences hold
    speculative KV blocks. Every Future must resolve typed with its
    partial tokens, the speculative blocks must come back (the draft
    cache shares the target's block accounting — one free covers
    both), and ``PagedKVCache.check()`` must be clean."""
    srv = _spec_server(model, params, "llmc_midverify")
    futs = [srv.submit([1 + i, 2, 3], 20) for i in range(3)]
    # let real decode progress accumulate partial tokens first, then
    # crash the 3rd draft dispatch: the worker dies holding proposals
    # that were never verified or committed
    deadline = time.monotonic() + 30
    while (srv.stats()["tokens_generated"] < 3
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert srv.stats()["tokens_generated"] >= 3
    faults.crash_at_point("llm.draft", nth=3)
    typed = served = 0
    for f in futs:
        try:
            f.result(timeout=30)
            served += 1                  # finished before the crash
        except ServingError:
            typed += 1                   # typed worker-death ServerClosed
    assert typed + served == 3           # nothing hangs, nothing raw
    assert typed >= 1                    # the crash really landed
    faults.reset()
    deadline = time.monotonic() + 10
    while srv.running and time.monotonic() < deadline:
        time.sleep(0.01)
    with pytest.raises(ServerClosed):
        srv.submit([1], 1)
    _assert_kv_clean(srv)


@pytest.mark.slow   # ~30s on 1 CPU (tier-1 budget); drain-with-
# partial-tokens stays fast via test_llm_queue_overflow_and_drain +
# test_llm_drain_with_shared_blocks_refcounts_settle, and the
# mid-verify death variant below it is already slow-tiered
def test_llm_drain_mid_verify_evicts_with_partial_tokens(model,
                                                         params):
    """Drain/evict while a verify round is parked mid-flight: the
    deadline-bounded shutdown resolves every speculative sequence
    with a typed SequenceEvictedError CARRYING the tokens committed
    so far; draft-speculation blocks are freed and accounting is
    exact."""
    srv = _spec_server(model, params, "llmc_specdrain")
    futs = [srv.submit([1 + i, 2, 3], CTX - 8) for i in range(3)]
    deadline = time.monotonic() + 30
    while (srv.stats()["tokens_generated"] < 3
           and time.monotonic() < deadline):
        time.sleep(0.005)
    assert srv.stats()["tokens_generated"] >= 3
    gate = faults.block_at("llm.draft")      # park the next verify
    assert gate.wait_reached(30)
    done = threading.Event()

    def _shutdown():
        srv.shutdown(drain=True, deadline_ms=0.0)   # evict now, typed
        done.set()

    t = threading.Thread(target=_shutdown, daemon=True)
    t.start()
    gate.release()
    assert done.wait(60)
    faults.reset()
    evicted = partial = served = 0
    for f in futs:
        try:
            f.result(timeout=10)
            served += 1
        except SequenceEvictedError as e:
            assert e.reason == "drain_deadline"
            evicted += 1
            partial += bool(e.tokens)
    assert evicted + served == 3         # nothing silently dropped
    assert evicted >= 1 and partial >= 1  # partials really carried
    _assert_kv_clean(srv)


def test_chaos_metrics_land_in_one_exposition(model, params):
    """The degradation is observable: the new overload series are
    present (and parseable) in one Prometheus exposition alongside the
    pre-existing serving series."""
    from mxnet_tpu.observability import get_registry
    # self-contained: exercise one instance of each outcome so the
    # series exist even when this test runs alone
    srv = _echo_server("chaos_expo", buckets=[1], max_queue=1,
                       max_delay_ms=0.1)
    gate = faults.block_at("serving.dispatch")
    f1 = srv.submit(np.zeros(ITEM, np.float32))
    assert gate.wait_reached(10)
    f2 = srv.submit(np.zeros(ITEM, np.float32))
    with pytest.raises(Overloaded):
        srv.submit(np.zeros(ITEM, np.float32))          # shed
    with pytest.raises(DeadlineExceededError):
        srv.submit(np.zeros(ITEM, np.float32),
                   deadline_ms=0)                       # deadline
    gate.release()
    _resolve_all([f1, f2])
    srv.shutdown()
    text = get_registry().expose()
    for needed in ("mxtpu_serving_shed_total",
                   "mxtpu_serving_deadline_expired_total",
                   "mxtpu_serving_poison_isolated_total",
                   "mxtpu_serving_breaker_state"):
        assert needed in text, f"{needed} missing from exposition"
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    try:
        from metrics_dump import parse_exposition
    finally:
        sys.path.pop(0)
    parse_exposition(text)      # raises on malformed exposition


# ---------------------------------------- shared-block chaos (ISSUE 13)

def test_llm_worker_death_with_live_shared_blocks(model, params):
    """Chaos satellite: the engine thread dies while sequences SHARE
    prefix-cache blocks mid-flight. Every Future resolves typed,
    refcounts settle to zero, and the pool partition (free + cached)
    is exact — a shared block is decref'd once per owner, never
    double-freed, never leaked."""
    srv = _llm(model, params, "llmc_share_death")
    prefix = list(range(BS))                # one full shared block
    # wave 1 registers the prefix, then the crash lands mid-decode of
    # a wave of cache-hit sequences
    srv.submit(prefix + [1], 2).result(timeout=30)
    faults.crash_at_point("llm.worker", nth=2)
    futs = [srv.submit(prefix + [2 + i], 8) for i in range(3)]
    for f in futs:
        try:
            f.result(timeout=30)
        except BaseException:
            pass                            # typed resolution is the pin
    faults.reset()
    deadline = time.monotonic() + 10
    while srv.running and time.monotonic() < deadline:
        time.sleep(0.01)
    eng = srv.engine
    assert eng.prefix_hits >= 1             # sharing really happened
    _assert_kv_clean(srv)                   # refcounts settled to zero
    # cached blocks survive the crash as reclaimable capacity
    assert (eng.cache.allocator.num_free
            == eng.cache.allocator.num_usable)


def test_llm_drain_with_shared_blocks_refcounts_settle(model, params):
    """Immediate drain (evict now, typed) over live cache-hit
    sequences: evicting one owner of a shared block must not free it
    from under the other — check(live) stays clean at every point and
    both evictions carry their partial tokens."""
    srv = _llm(model, params, "llmc_share_drain")
    prefix = list(range(BS))
    srv.submit(prefix + [1], 2).result(timeout=30)     # register
    gate = faults.block_at("llm.decode")
    f1 = srv.submit(prefix + [2], 20)
    assert gate.wait_reached(30)
    f2 = srv.submit(prefix + [3], 20)
    eng = srv.engine
    done = threading.Event()

    def _shutdown():
        srv.shutdown(drain=True, deadline_ms=0.0)
        done.set()

    t = threading.Thread(target=_shutdown, daemon=True)
    t.start()
    gate.release()
    assert done.wait(60)
    outcomes = 0
    for f in (f1, f2):
        try:
            f.result(timeout=10)
            outcomes += 1
        except SequenceEvictedError:
            outcomes += 1
    assert outcomes == 2
    assert eng.prefix_hits >= 1
    _assert_kv_clean(srv)


def test_llm_poison_with_shared_prefix_isolated(model, params):
    """A poison prompt that HITS the prefix cache: its isolation frees
    only its own references — the healthy sequence sharing the same
    blocks keeps decoding bit-exact, and the shared blocks stay
    readable (cached) afterwards."""
    srv = _llm(model, params, "llmc_share_pois")
    prefix = list(range(BS))
    first = srv.submit(prefix + [1], 2).result(timeout=30)
    faults.script("llm.prefill", [ValueError("poison shared prompt")])
    f_bad = srv.submit(prefix + [2], 4)     # poisoned, shares blocks
    f_ok = srv.submit(prefix + [3], 4)      # healthy, shares blocks
    with pytest.raises(ValueError, match="poison shared prompt"):
        f_bad.result(timeout=30)
    ref = greedy_decode_reference(model, params, prefix + [3], 4)
    assert f_ok.result(timeout=30).tokens == ref
    srv.shutdown()
    st = srv.stats()
    assert st["poison_isolated"] == 1
    assert st["prefix_hits"] >= 1
    _assert_kv_clean(srv)
