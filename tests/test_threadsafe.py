"""Thread-safe concurrent inference.

The reference ships a dedicated thread-safe cached op for multi-threaded
serving (reference: src/imperative/cached_op_threadsafe.h:82, exercised
by tests/cpp/thread_safety/thread_safety_test.cc): N C++ threads drive
one CachedOp concurrently and outputs must match single-threaded runs.

Here the compiled (post-trace) CachedOp path is lock-free — jax compiled
executables are thread-safe — and only the first-call trace serializes
(gluon/block.py CachedOp._trace_lock). These tests pin that contract:
outputs from N Python threads hammering one hybridized net are
bit-identical to serial execution, including when the very first call
(the trace) races, and when two jit signatures race.
"""
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon.model_zoo import vision


N_THREADS = 4
N_ITERS = 3


def _make_net(seed=0):
    mx.random.seed(seed)
    net = vision.resnet18_v1(classes=10)
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    return net


def _inputs(n, batch=2, size=16, seed=123):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((batch, 3, size, size)).astype("float32")
            for _ in range(n)]


def _run_threads(n_threads, worker):
    barrier = threading.Barrier(n_threads)
    errors = []
    threads = []

    def wrapped(tid):
        try:
            barrier.wait()
            worker(tid)
        except BaseException:  # pragma: no cover - failure path
            import traceback
            errors.append((tid, traceback.format_exc()))

    for t in range(n_threads):
        th = threading.Thread(target=wrapped, args=(t,))
        th.start()
        threads.append(th)
    for th in threads:
        th.join()
    assert not errors, f"worker exceptions: {errors}"


def test_concurrent_inference_matches_serial():
    net = _make_net()
    xs = _inputs(N_THREADS)
    serial = [net(nd.array(x)).asnumpy() for x in xs]  # also warms the jit

    results = [[None] * N_ITERS for _ in range(N_THREADS)]

    def worker(tid):
        for i in range(N_ITERS):
            results[tid][i] = net(nd.array(xs[tid])).asnumpy()

    _run_threads(N_THREADS, worker)
    for tid in range(N_THREADS):
        for i in range(N_ITERS):
            np.testing.assert_array_equal(results[tid][i], serial[tid])


@pytest.mark.slow   # ~11s on 1 CPU (tier-1 budget); the other
# five concurrency tests here keep thread-safety in the fast gate
def test_concurrent_first_call_trace_races():
    """The FIRST call from every thread simultaneously: the trace itself
    races. All outputs must still be bit-identical to a serial run."""
    ref_net = _make_net(seed=1)
    xs = _inputs(N_THREADS, seed=7)
    expected = [ref_net(nd.array(x)).asnumpy() for x in xs]

    net = _make_net(seed=1)  # same seed -> identical params, cold jit
    results = [None] * N_THREADS

    def worker(tid):
        results[tid] = net(nd.array(xs[tid])).asnumpy()

    _run_threads(N_THREADS, worker)
    for tid in range(N_THREADS):
        np.testing.assert_array_equal(results[tid], expected[tid])


@pytest.mark.slow   # ~13s on 1 CPU (tier-1 budget); concurrency
# coverage stays fast via concurrent_inference_matches_serial,
# trace_state_is_thread_local and the recording/backward-thread tests
def test_concurrent_mixed_signatures():
    """Different batch shapes concurrently -> distinct jit signatures
    being traced/executed at once."""
    net = _make_net(seed=2)
    shapes = [(1, 3, 16, 16), (2, 3, 16, 16), (3, 3, 16, 16),
              (1, 3, 16, 16)]
    rng = np.random.default_rng(5)
    xs = [rng.standard_normal(s).astype("float32") for s in shapes]

    results = [None] * len(shapes)

    def worker(tid):
        results[tid] = net(nd.array(xs[tid])).asnumpy()

    _run_threads(len(shapes), worker)
    serial = [net(nd.array(x)).asnumpy() for x in xs]
    for tid in range(len(shapes)):
        np.testing.assert_array_equal(results[tid], serial[tid])


def test_trace_state_is_thread_local():
    """An eager forward in one thread while another thread traces must
    not observe tracer-backed parameter data."""
    from mxnet_tpu.gluon import nn

    class Slow(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.dense = nn.Dense(8)

        def hybrid_forward(self, F, x, **params):
            y = self.dense(x)
            for _ in range(30):  # fat trace: widen the race window
                y = y * 1.0 + 0.0
            return y

    mx.random.seed(3)
    net = Slow()
    net.initialize()
    x = np.ones((2, 8), "float32")
    eager_net_ok = []

    def tracer(tid):
        if tid == 0:
            net.hybridize()
            net(nd.array(x))
        else:
            for _ in range(20):
                # plain (non-hybridized second net) eager math sharing
                # the global rng/trace machinery
                v = (nd.array(x) * 2.0).asnumpy()
                eager_net_ok.append(bool(np.all(v == 2.0)))

    _run_threads(2, tracer)
    assert all(eager_net_ok)


def test_recording_thread_unaffected_by_concurrent_trace():
    """Thread A records a training step while thread B triggers a
    first-call trace (whose pure() runs under autograd.pause): A's tape
    must still capture gradients — autograd mode is thread-local
    (reference: src/imperative/imperative.h thread_local is_recording_)."""
    from mxnet_tpu.gluon import nn
    import mxnet_tpu.autograd as ag

    mx.random.seed(4)
    traced = nn.HybridSequential()
    with traced.name_scope():
        traced.add(nn.Dense(64, activation="relu"), nn.Dense(64))
    traced.initialize()
    traced.hybridize()

    grads = []
    start = threading.Barrier(2)

    def train_worker():
        x = nd.array(np.ones((4, 8), "float32"))
        x.attach_grad()
        start.wait()
        for _ in range(20):
            with ag.record():
                y = (x * x).sum()
            y.backward()
            grads.append(x.grad.asnumpy().copy())

    def trace_worker():
        start.wait()
        for i in range(1, 5):
            # each batch size is a fresh jit signature -> fresh trace,
            # each trace wraps pure() in autograd.pause()
            traced(nd.array(np.ones((i, 8), "float32")))

    ths = [threading.Thread(target=train_worker),
           threading.Thread(target=trace_worker)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert len(grads) == 20
    for g in grads:
        np.testing.assert_array_equal(
            g, np.full((4, 8), 2.0, "float32"))


def test_attach_grad_main_thread_backward_worker_thread():
    """Leaves are process-global even though the autograd graph is
    per-thread: params attached on the main thread get gradients from a
    backward() run in a worker thread (the reference's AGInfo lives on
    the NDArray itself, not in thread state)."""
    import mxnet_tpu.autograd as ag

    x = nd.array(np.ones(3, "float32"))
    x.attach_grad()
    done = []

    def worker():
        with ag.record():
            y = (x * 2).sum()
        y.backward()
        done.append(True)

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert done
    np.testing.assert_array_equal(x.grad.asnumpy(),
                                  np.full(3, 2.0, "float32"))
