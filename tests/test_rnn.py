"""RNN tests: fused op oracle checks, gluon.rnn layers/cells, LSTM and
CTC convergence (the BASELINE.md LSTM/CTC north-star config), bucketing.

Models the reference's tests/python/unittest/test_gluon_rnn.py and
tests/python/train/test_bucketing.py.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
import mxnet_tpu.autograd as ag
from mxnet_tpu import gluon
from mxnet_tpu.gluon import rnn, nn


def _np_lstm_ref(x, h0, c0, wx, wh, bx, bh):
    """Plain-numpy single-layer LSTM oracle, gate order [i, f, g, o]."""
    def sig(v):
        return 1.0 / (1.0 + onp.exp(-v))

    T, N, _ = x.shape
    H = h0.shape[-1]
    h, c = h0.copy(), c0.copy()
    outs = []
    for t in range(T):
        gates = x[t] @ wx.T + bx + h @ wh.T + bh
        i, f, g, o = onp.split(gates, 4, axis=-1)
        c = sig(f) * c + sig(i) * onp.tanh(g)
        h = sig(o) * onp.tanh(c)
        outs.append(h)
    return onp.stack(outs), h, c


class TestFusedRNNOracle:
    def test_lstm_matches_numpy(self):
        T, N, I, H = 4, 3, 5, 6
        rng = onp.random.RandomState(0)
        x = rng.randn(T, N, I).astype("f")
        wx = rng.randn(4 * H, I).astype("f") * 0.3
        wh = rng.randn(4 * H, H).astype("f") * 0.3
        bx = rng.randn(4 * H).astype("f") * 0.1
        bh = rng.randn(4 * H).astype("f") * 0.1
        h0 = onp.zeros((1, N, H), "f")
        c0 = onp.zeros((1, N, H), "f")
        flat = onp.concatenate([wx.ravel(), wh.ravel(), bx, bh])

        out, hT, cT = mx.nd.RNN(
            mx.nd.array(x), mx.nd.array(flat), mx.nd.array(h0),
            mx.nd.array(c0), state_size=H, num_layers=1, mode="lstm")
        ref_out, ref_h, ref_c = _np_lstm_ref(x, h0[0], c0[0], wx, wh, bx, bh)
        onp.testing.assert_allclose(out.asnumpy(), ref_out, rtol=1e-4,
                                    atol=1e-5)
        onp.testing.assert_allclose(hT.asnumpy()[0], ref_h, rtol=1e-4,
                                    atol=1e-5)
        onp.testing.assert_allclose(cT.asnumpy()[0], ref_c, rtol=1e-4,
                                    atol=1e-5)

    def test_layer_matches_cell_unroll(self):
        """Fused LSTM layer == LSTMCell.unroll with identical params —
        validates gate order and flat packing consistency."""
        T, N, I, H = 5, 2, 4, 8
        layer = rnn.LSTM(H, input_size=I)
        layer.initialize()
        cell = rnn.LSTMCell(H, input_size=I)
        cell.initialize()
        cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
        cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
        cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
        cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
        x = mx.nd.array(onp.random.randn(T, N, I).astype("f"))
        out_layer = layer(x)
        out_cell, _ = cell.unroll(T, x, layout="TNC", merge_outputs=True)
        onp.testing.assert_allclose(out_layer.asnumpy(),
                                    out_cell.asnumpy(), rtol=1e-4,
                                    atol=1e-5)

    def test_gru_layer_matches_cell_unroll(self):
        T, N, I, H = 5, 2, 4, 8
        layer = rnn.GRU(H, input_size=I)
        layer.initialize()
        cell = rnn.GRUCell(H, input_size=I)
        cell.initialize()
        cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
        cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
        cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
        cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
        x = mx.nd.array(onp.random.randn(T, N, I).astype("f"))
        onp.testing.assert_allclose(
            layer(x).asnumpy(),
            cell.unroll(T, x, layout="TNC", merge_outputs=True)[0].asnumpy(),
            rtol=1e-4, atol=1e-5)


class TestRNNLayers:
    @pytest.mark.slow   # ~14s on 1 CPU (tier-1 budget); per-mode
    # numerics stay fast via the lstm/gru numpy + unroll parity tests
    def test_shapes_all_modes(self):
        x = mx.nd.array(onp.random.randn(6, 2, 3).astype("f"))
        for cls, h in [(rnn.LSTM, 5), (rnn.GRU, 5), (rnn.RNN, 5)]:
            net = cls(h, num_layers=2, bidirectional=True)
            net.initialize()
            assert net(x).shape == (6, 2, 2 * h)

    def test_ntc_layout(self):
        net = rnn.LSTM(4, layout="NTC")
        net.initialize()
        x = mx.nd.array(onp.random.randn(2, 7, 3).astype("f"))
        assert net(x).shape == (2, 7, 4)

    def test_explicit_states(self):
        net = rnn.LSTM(4, num_layers=2)
        net.initialize()
        x = mx.nd.array(onp.random.randn(3, 2, 5).astype("f"))
        states = net.begin_state(2)
        out, new_states = net(x, states)
        assert out.shape == (3, 2, 4)
        assert [s.shape for s in new_states] == [(2, 2, 4), (2, 2, 4)]

    @pytest.mark.slow   # ~11s on 1 CPU (tier-1 budget); RNN
    # backward stays fast via the bucketing_lm/bi_lstm_sort
    # example runs and the fused-oracle tests
    def test_gradients_flow(self):
        net = rnn.GRU(4, num_layers=2, bidirectional=True)
        net.initialize()
        x = mx.nd.array(onp.random.randn(3, 2, 5).astype("f"))
        net(x)  # resolve shapes
        params = net.collect_params()
        with ag.record():
            loss = net(x).sum()
        loss.backward()
        for name, p in params.items():
            g = p.grad()
            assert onp.abs(g.asnumpy()).sum() > 0, f"zero grad for {name}"

    def test_hybridize(self):
        net = rnn.LSTM(4)
        net.initialize()
        x = mx.nd.array(onp.random.randn(3, 2, 5).astype("f"))
        eager = net(x).asnumpy()
        net.hybridize()
        onp.testing.assert_allclose(net(x).asnumpy(), eager, rtol=1e-5,
                                    atol=1e-6)
        onp.testing.assert_allclose(net(x).asnumpy(), eager, rtol=1e-5,
                                    atol=1e-6)  # second call: cache hit


class TestCells:
    def test_residual_and_dropout_cells(self):
        base = rnn.GRUCell(6, input_size=6)
        cell = rnn.ResidualCell(base)
        cell.initialize()
        x = mx.nd.array(onp.random.randn(2, 4, 6).astype("f"))
        out, _ = cell.unroll(4, x, layout="NTC")
        assert out.shape == (2, 4, 6)
        d = rnn.DropoutCell(0.5)
        out, _ = d.unroll(4, x, layout="NTC")
        assert out.shape == (2, 4, 6)

    def test_unroll_valid_length_states(self):
        """States returned from unroll(valid_length=...) come from each
        sample's last VALID step, not the padded tail."""
        cell = rnn.LSTMCell(6, input_size=3)
        cell.initialize()
        T = 5
        x = onp.random.randn(2, T, 3).astype("f")
        vl = onp.array([3.0, 5.0], "f")
        out, states = cell.unroll(T, mx.nd.array(x), layout="NTC",
                                  valid_length=mx.nd.array(vl))
        # sample 0: states must equal an unroll truncated at t=3
        out3, states3 = cell.unroll(3, mx.nd.array(x[:, :3]), layout="NTC")
        onp.testing.assert_allclose(states[0].asnumpy()[0],
                                    states3[0].asnumpy()[0], rtol=1e-5,
                                    atol=1e-6)
        # masked outputs beyond valid_length are zero
        assert onp.abs(out.asnumpy()[0, 3:]).sum() == 0

    def test_bidirectional_valid_length(self):
        """Reverse direction must consume real tokens first under
        valid_length (SequenceReverse semantics)."""
        l, r = rnn.LSTMCell(4, input_size=3), rnn.LSTMCell(4, input_size=3)
        bi = rnn.BidirectionalCell(l, r)
        bi.initialize()
        T = 4
        x = onp.random.randn(2, T, 3).astype("f")
        vl = onp.array([2.0, 4.0], "f")
        out, _ = bi.unroll(T, mx.nd.array(x), layout="NTC",
                           valid_length=mx.nd.array(vl))
        # sample 0 truncated to its valid length must reproduce the
        # variable-length result on the valid prefix
        out_trunc, _ = bi.unroll(2, mx.nd.array(x[:1, :2]), layout="NTC")
        onp.testing.assert_allclose(out.asnumpy()[0, :2],
                                    out_trunc.asnumpy()[0], rtol=1e-5,
                                    atol=1e-6)
        assert onp.abs(out.asnumpy()[0, 2:]).sum() == 0

    def test_zoneout_cell_train_mode(self):
        cell = rnn.ZoneoutCell(rnn.LSTMCell(5), zoneout_outputs=0.3,
                               zoneout_states=0.3)
        cell.initialize()
        x = mx.nd.array(onp.random.randn(2, 4, 3).astype("f"))
        with ag.record():
            out, _ = cell.unroll(4, x, layout="NTC")
        assert out.shape == (2, 4, 5)


class TestConvergence:
    @pytest.mark.slow   # ~37s convergence loop (tier-1 budget)
    def test_char_lstm_learns_pattern(self):
        """Char-level LSTM on a deterministic cyclic sequence — the
        LSTM/CTC north-star config's recurrent half."""
        vocab, T, B, H = 7, 12, 8, 32
        seq = onp.arange(vocab * 6) % vocab  # cyclic pattern
        rng = onp.random.RandomState(0)
        starts = rng.randint(0, len(seq) - T - 1, size=(64,))
        xs = onp.stack([seq[s:s + T] for s in starts])
        ys = onp.stack([seq[s + 1:s + T + 1] for s in starts])

        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Embedding(vocab, 16))
        lstm = rnn.LSTM(H, layout="NTC")
        dense = nn.Dense(vocab, flatten=False)
        mx.random.seed(0)
        net.initialize()
        lstm.initialize()
        dense.initialize()
        params = {}
        for blk in (net, lstm, dense):
            params.update(blk.collect_params())
        trainer = gluon.Trainer(params, "adam", {"learning_rate": 0.01})
        L = gluon.loss.SoftmaxCrossEntropyLoss()

        first = last = None
        for step in range(60):
            bi = rng.randint(0, 64, size=(B,))
            x = mx.nd.array(xs[bi].astype("f"))
            y = mx.nd.array(ys[bi].astype("f"))
            with ag.record():
                out = dense(lstm(net(x)))
                loss = L(out.reshape((-1, vocab)), y.reshape((-1,))).mean()
            loss.backward()
            trainer.step(1)
            if first is None:
                first = float(loss)
            last = float(loss)
        assert last < 0.5 * first, (first, last)

    @pytest.mark.slow   # ~57s convergence loop (tier-1 budget);
    # CTC correctness stays via test_ctc_torch_oracle.py
    def test_ctc_head_converges(self):
        """LSTM + CTC head trained to decreasing loss (north-star
        LSTM/CTC config; reference: example OCR pipelines)."""
        T, B, A, H = 16, 4, 6, 24  # A includes blank=0
        rng = onp.random.RandomState(1)
        x_np = rng.randn(T, B, 8).astype("f")
        labels = onp.tile(onp.array([[1, 2, 3, 4]], "f"), (B, 1))

        lstm = rnn.LSTM(H)
        head = nn.Dense(A, flatten=False)
        mx.random.seed(1)
        lstm.initialize()
        head.initialize()
        params = dict(lstm.collect_params())
        params.update(head.collect_params())
        trainer = gluon.Trainer(params, "adam", {"learning_rate": 0.02})
        L = gluon.loss.CTCLoss(layout="TNC")

        x = mx.nd.array(x_np)
        y = mx.nd.array(labels)
        first = last = None
        for step in range(40):
            with ag.record():
                out = head(lstm(x))  # (T, B, A)
                loss = L(out, y).mean()
            loss.backward()
            trainer.step(1)
            if first is None:
                first = float(loss)
            last = float(loss)
        assert onp.isfinite(last)
        assert last < 0.5 * first, (first, last)


class TestBucketing:
    def test_bucketing_module_shares_params(self):
        """BucketingModule trains across variable-length buckets with
        shared parameters (reference: tests/python/train/test_bucketing.py)."""
        import logging

        def sym_gen(seq_len):
            data = mx.sym.var("data")
            label = mx.sym.var("softmax_label")
            pooled = mx.sym.mean(data, axis=1)  # (N, C): length-invariant
            fc = mx.sym.FullyConnected(pooled, name="fc", num_hidden=4)
            out = mx.sym.SoftmaxOutput(fc, label, name="softmax")
            return out, ("data",), ("softmax_label",)

        mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10)
        from mxnet_tpu.io import DataBatch
        rng = onp.random.RandomState(0)

        def batch(T):
            x = rng.randn(8, T, 6).astype("f")
            y = (rng.rand(8) * 4).astype("f")
            return DataBatch(data=[mx.nd.array(x)],
                             label=[mx.nd.array(y)],
                             bucket_key=T,
                             provide_data=[("data", (8, T, 6))],
                             provide_label=[("softmax_label", (8,))])

        mod.bind(data_shapes=[("data", (8, 10, 6))],
                 label_shapes=[("softmax_label", (8,))])
        mod.init_params()
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        losses = []
        for i in range(12):
            b = batch([6, 10, 14][i % 3])
            mod.forward(b)
            mod.backward()
            mod.update()
            out = mod.get_outputs()[0].asnumpy()
        # parameters are shared: all buckets see the same fc weight
        assert len(mod._buckets) == 3
        w0 = mod._buckets[6].get_params()[0]["fc_weight"].asnumpy()
        w1 = mod._buckets[14].get_params()[0]["fc_weight"].asnumpy()
        onp.testing.assert_allclose(w0, w1)
