"""SPMD LLM serving gates (ISSUE 19): the unified decode step —
chunked prefill + decode + speculative verify in ONE donated program —
sharded tensor-parallel over a ``tp`` mesh axis, with dp replica
groups of engines behind one server.

What this module pins:

- **bit-exactness at tp=1** — wrapping the step in shard_map over a
  one-device mesh changes NOTHING: greedy AND sampled token streams
  match the unsharded engine token-for-token (same programs modulo
  the wrapper, same float order);
- **greedy parity at tp>1** — per-shard ragged attention + psum'd
  o/MLP projections reproduce the eager single-device reference on
  virtual CPU devices (structure evidence; ICI collectives on real
  hardware run the same program);
- **zero steady-state recompiles, ONE dispatch per step** — mixed
  chunked-prefill + sampled + speculative + adapter traffic on a
  warmed tp=2 engine never re-enters XLA, and every ``step()`` lands
  exactly one launch of the sharded unified program;
- **ONE strict BlockAllocator** — the draft pool rides the target
  allocator's block ids under sharding too; block accounting stays
  exact under randomized admission/completion traffic;
- **prefix-cache elastic resume** — block hashes are pure token
  chains (no mesh salt), so a cache warmed at one mesh size hits at
  another after restart;
- **COW under sharding** — copy-on-write flows through a
  shard_map'd program, so the donated pools come back with their
  sharding intact (the latent single-device assumption fixed in the
  engine: an unconstrained jit would have resharded the pools on the
  first shared-prefix rewrite);
- **kill-one-shard chaos** — a tp engine's worker dying resolves
  every in-flight Future typed, settles KV blocks and adapter-page
  refcounts clean, and a fresh engine at a DIFFERENT mesh size
  resumes the prefix-hash namespace;
- **dp replica groups** — ``mesh="dp=2"`` runs two engines behind
  one scheduler thread with least-loaded routing, one warmup, one
  drain contract.

Budget note (tier-1): every fast tp=2 test shares the ONE
module-scoped warmed ``world`` engine; the tp=4 and dp×tp sweep is
``slow``-marked with the tp=2 tests as its fast gate.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import jax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.serving import ServerClosed  # noqa: E402
from mxnet_tpu.serving.llm import (  # noqa: E402
    TinyDecoder, LLMServer, greedy_decode_reference,
    prefix_block_hashes)
from mxnet_tpu.serving.llm.engine import LLMEngine  # noqa: E402
from mxnet_tpu.serving.llm.metrics import LLMStats  # noqa: E402
from mxnet_tpu.serving.llm.scheduler import Sequence  # noqa: E402
from mxnet_tpu.serving.llm.sampling import SamplingParams  # noqa: E402
from mxnet_tpu.serving.adapters.bank import AdapterBank  # noqa: E402
from mxnet_tpu.parallel.mesh import llm_mesh  # noqa: E402
from mxnet_tpu.resilience import faults  # noqa: E402

VOCAB, BS, CTX = 23, 8, 64


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def model():
    # 4 heads so the same model shards at tp=1/2/4
    return TinyDecoder(vocab_size=VOCAB, d_model=16, num_layers=2,
                       num_heads=4, d_ff=32, max_context=CTX)


@pytest.fixture(scope="module")
def params(model):
    return model.init_params(0)


@pytest.fixture(scope="module")
def draft():
    return TinyDecoder(vocab_size=VOCAB, d_model=16, num_layers=1,
                       num_heads=4, d_ff=32, max_context=CTX)


@pytest.fixture(scope="module")
def dparams(draft):
    return draft.init_params(1)


def _tiny_bank():
    bank = AdapterBank(num_layers=2, d_model=16, max_adapters=4,
                       page_rank=2, max_pages_per_adapter=2)
    rs = np.random.RandomState(3)
    bank.publish("tiny",
                 (rs.randn(2, 4, 16, 2) * 0.1).astype(np.float32),
                 (rs.randn(2, 4, 2, 16) * 0.1).astype(np.float32))
    return bank


@pytest.fixture(scope="module")
def bank():
    return _tiny_bank()


@pytest.fixture(scope="module")
def world(model, params, draft, dparams, bank):
    """The ONE warmed tp=2 engine every fast SPMD test shares:
    speculative draft, adapter bank, prefix cache — the full unified
    step, sharded. Tests drain it completely before returning."""
    eng = LLMEngine(model, params, mesh="tp=2", max_seqs=4,
                    block_size=BS, num_blocks=41, max_context=CTX,
                    prefill_chunk=8, draft_model=draft,
                    draft_params=dparams, spec_k=2,
                    adapter_bank=bank, prefix_cache=True,
                    stats=LLMStats(server="spmd_world"))
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def tp1(model, params):
    """Small tp=1 engine (shared): the shard_map-over-one-device
    wrapper whose streams must be bit-exact vs unsharded."""
    eng = LLMEngine(model, params, mesh="tp=1", max_seqs=2,
                    block_size=BS, num_blocks=17, max_context=32,
                    prefill_chunk=8, prefix_cache=True)
    eng.warmup()
    return eng


def _serve(engine, jobs, max_new=8):
    """Run jobs (prompt, sampling, adapter) to completion; returns
    generated streams in submit order. Asserts nothing died."""
    seqs = []
    for prompt, samp, ad in jobs:
        s = Sequence(list(prompt), max_new, sampling=samp, adapter=ad)
        engine.add(s)
        seqs.append(s)
    outs = {}
    for _ in range(600):
        if not engine.has_work():
            break
        engine.step()
        for s in engine.pop_finished():
            outs[s.seq_id] = list(s.generated)
    assert not engine.has_work(), "engine did not drain"
    dead = engine.pop_dead()
    assert not dead, f"sequences died: {dead}"
    return [outs[s.seq_id] for s in seqs]


# ------------------------------------------------------- mesh parsing --
def test_llm_mesh_spec_parsing():
    """llm_mesh: bare int = tp, dp defaults to 1 (never silently
    absorbs spare devices), dp=-1 absorbs explicitly."""
    m = llm_mesh("2")
    assert dict(zip(m.axis_names, m.devices.shape)) == {"dp": 1,
                                                        "tp": 2}
    m = llm_mesh("dp=2,tp=2")
    assert dict(zip(m.axis_names, m.devices.shape)) == {"dp": 2,
                                                        "tp": 2}
    n = len(jax.devices())
    m = llm_mesh("dp=-1,tp=2")
    assert dict(zip(m.axis_names, m.devices.shape)) == {"dp": n // 2,
                                                        "tp": 2}
    with pytest.raises(ValueError):
        llm_mesh("pp=2")
    with pytest.raises(ValueError):
        llm_mesh(f"tp={2 * n}")


def test_engine_rejects_dp_mesh(model, params):
    """The ENGINE owns only tp; a dp>1 mesh is a config error
    pointing at LLMServer, not a silent absorb."""
    with pytest.raises(ValueError, match="LLMServer"):
        LLMEngine(model, params, mesh="dp=2,tp=2", max_seqs=2,
                  block_size=BS, num_blocks=17, max_context=32)
    with pytest.raises(ValueError, match="not divisible by tp"):
        LLMEngine(model, params, mesh="tp=8", max_seqs=2,
                  block_size=BS, num_blocks=17, max_context=32)


# ------------------------------------------------- tp=1 bit-exactness --
def test_tp1_bitexact_greedy_and_sampled(model, params, tp1):
    """Acceptance gate: tp=1 is BIT-EXACT vs the unsharded engine —
    greedy AND sampled streams, token for token."""
    e0 = LLMEngine(model, params, max_seqs=2, block_size=BS,
                   num_blocks=17, max_context=32, prefill_chunk=8,
                   prefix_cache=True)
    e0.warmup()
    jobs = [
        ([1, 2, 3], None, None),
        ([4, 5, 6, 7, 8, 9, 10, 11, 12, 13], None, None),
        ([14, 15], SamplingParams(temperature=0.9, top_k=5, seed=7),
         None),
        ([3, 3, 3], SamplingParams(temperature=1.2, top_p=0.9,
                                   seed=11), None),
    ]
    base = _serve(e0, jobs)
    sharded = _serve(tp1, jobs)
    assert sharded == base
    for (prompt, samp, _), toks in zip(jobs, base):
        if samp is None:
            assert toks == greedy_decode_reference(model, params,
                                                   prompt, 8)


# ------------------------------------- tp=2: the mixed-traffic gate --
def test_tp2_mixed_traffic_zero_recompiles_one_dispatch(world, model,
                                                        params, bank):
    """Acceptance gate: mixed chunked-prefill + sampled + speculative
    + adapter traffic on the warmed tp=2 engine runs with ZERO
    recompiles and exactly ONE launch of the sharded unified step per
    ``engine.step()`` — and greedy rows match the eager reference."""
    jobs = [
        # 14-token prompt: two chunked-prefill steps through the
        # unified program before its first token
        (list(range(1, 15)), None, None),
        ([4, 5, 6], SamplingParams(temperature=0.8, top_k=5, seed=7),
         None),
        ([13, 2, 1], None, "tiny"),
        ([3, 3, 3, 3], SamplingParams(temperature=1.1, top_p=0.9,
                                      seed=11), "tiny"),
    ]
    seqs = []
    for prompt, samp, ad in jobs:
        s = Sequence(list(prompt), 8, sampling=samp, adapter=ad)
        world.add(s)
        seqs.append(s)
    outs = {}
    steps = 0
    with serving.CompileCounter() as cc:
        while world.has_work():
            before = world.spmd_dispatches
            world.step()
            steps += 1
            assert world.spmd_dispatches == before + 1, \
                "unified step must be ONE device dispatch"
            for s in world.pop_finished():
                outs[s.seq_id] = list(s.generated)
            assert steps < 600
    assert cc.count == 0, f"{cc.count} steady-state recompiles"
    assert not world.pop_dead()
    res = [outs[s.seq_id] for s in seqs]
    assert res[0] == greedy_decode_reference(model, params,
                                             jobs[0][0], 8)
    assert res[2] == greedy_decode_reference(
        model, params, jobs[2][0], 8,
        lora=bank.adapter_arrays("tiny"))
    world.cache.check([])


def test_tp2_replicated_lora_pools_cached(world):
    """Regression (latent single-device assumption): the bank's A/B
    factor pools are replicated onto the mesh ONCE per publish, not
    re-placed per step — the memoized placement survives across
    steps while the pool identity is unchanged."""
    _serve(world, [([5, 6, 7], None, "tiny")], max_new=4)
    first = world._lora_placed
    assert first is not None
    _serve(world, [([6, 7, 8], None, "tiny")], max_new=4)
    assert world._lora_placed is first


def test_tp2_statusz_and_metrics_mesh_block(world):
    """Satellite: the flight-recorder statusz surface and the
    ``mxtpu_llm_spmd_*`` series expose mesh shape and per-shard KV
    placement."""
    ds = world.debug_status()
    mesh = ds["mesh"]
    assert mesh["devices"] == 2 and mesh["tp"] == 2
    kv = mesh["kv"]
    assert kv["axis"] == "tp" and kv["shards"] == 2
    assert kv["heads_per_shard"] == 2
    heads = sorted(tuple(p["heads"]) for p in kv["placement"])
    assert heads == [(0, 2), (2, 4)]        # every head exactly once
    assert all(len(p["devices"]) == 1 for p in kv["placement"])
    snap = world._stats.snapshot()
    assert snap["spmd_mesh_devices"] == 2
    assert snap["spmd_mesh_axes"] == {"tp": 2}
    assert snap["spmd_kv_heads_per_shard"] == 2
    assert snap["spmd_step_dispatches"] == world.spmd_dispatches > 0


def test_tp2_cow_preserves_sharding_one_allocator(world, model, params):
    """Regression (the COW single-device fix): a shared-prefix
    rewrite flows through the shard_map'd copy program, so the
    donated pools come back with their sharding INTACT — and the
    draft pool still rides the target allocator (ONE strict
    accounting)."""
    from jax.sharding import NamedSharding
    expected = NamedSharding(world.mesh, world.cache.pool_spec())

    def _sharded(pool):
        return pool.sharding.is_equivalent_to(expected, pool.ndim)

    assert world.cache.pool_spec() != P()
    assert _sharded(world.cache.k_pages)
    cow0 = world.cache.cow_count
    prompt = [17] * (2 * BS)                # two full blocks, aligned
    a = Sequence(prompt, 8)                 # long-lived first owner
    world.add(a)
    guard = 0
    while not a.generated:                  # A's blocks registered
        world.step()
        guard += 1
        assert guard < 50
    b = Sequence(prompt, 3)                 # hits all but last token
    world.add(b)
    while world.has_work():
        world.step()
    assert b.cache_hit_tokens == 2 * BS - 1
    assert world.cache.cow_count > cow0, \
        "block-aligned prefix hit must copy-on-write the last block"
    ref = greedy_decode_reference(model, params, prompt, 8)
    assert a.output_tokens() == ref
    assert b.output_tokens() == ref[:3]
    for pool in (world.cache.k_pages, world.cache.v_pages,
                 world.draft_cache.k_pages, world.draft_cache.v_pages):
        assert _sharded(pool), \
            "COW must hand the pools back with their sharding intact"
    # ONE allocator: the draft cache's own allocator is never touched
    assert world.draft_cache.allocator.num_used == 0
    world.cache.check([])


def test_tp2_allocator_fuzz_under_churn(world):
    """ONE-BlockAllocator invariant under randomized admission /
    completion churn on the sharded engine: exact per-block owner
    counts at EVERY step (leaks, double-owns and refcount drift all
    raise)."""
    rng = np.random.default_rng(0)
    live = []
    steps = 0
    while steps < 120:
        if len(live) < 4 and rng.random() < 0.5:
            prompt = list(rng.integers(1, VOCAB,
                                       size=int(rng.integers(1, 20))))
            s = Sequence(prompt, int(rng.integers(1, 8)),
                         adapter="tiny" if rng.random() < 0.3
                         else None)
            world.add(s)
            live.append(s)
        if not world.has_work():
            break
        world.step()
        steps += 1
        done = world.pop_finished()
        assert not world.pop_dead()
        live = [s for s in live if s not in done]
        world.cache.check([s.block_ids for s in live])
    while world.has_work():                 # drain the tail
        world.step()
        world.pop_finished()
    world.cache.check([])


# -------------------------------------- prefix cache: elastic resume --
def test_prefix_hashes_elastic_across_mesh_sizes(tp1, world):
    """Satellite invariant: prefix-cache hashes are pure token
    chains — NO mesh salt — so the hash a tp=1 engine registered is
    the hash a restarted tp=2 engine computes for the same prompt.
    Restart at a different mesh size resumes the namespace."""
    prefix = [19] * BS                      # one full block
    hashes = prefix_block_hashes(prefix, BS)
    _serve(tp1, [(prefix + [1], None, None)], max_new=2)
    assert tp1.cache.prefix_get(hashes[0]) is not None
    hits0 = tp1.prefix_hits
    _serve(tp1, [(prefix + [2], None, None)], max_new=2)
    assert tp1.prefix_hits > hits0
    # "restart" at tp=2: same tokens -> same hash -> a hit, and the
    # shared stream still matches the eager reference
    _serve(world, [(prefix + [1], None, None)], max_new=2)
    assert world.cache.prefix_get(hashes[0]) is not None
    hits0 = world.prefix_hits
    _serve(world, [(prefix + [2], None, None)], max_new=2)
    assert world.prefix_hits > hits0


# --------------------------------------------- kill-one-shard chaos --
def test_kill_one_shard_resolves_and_resumes(model, params):
    """Chaos satellite: a tp=2 server's worker dying mid-loop
    resolves EVERY in-flight Future typed, settles KV blocks and
    adapter-page refcounts clean, and a fresh engine at a DIFFERENT
    mesh size (tp=1) resumes the prefix-hash namespace."""
    bank2 = _tiny_bank()
    srv = LLMServer(model, params, name="spmd_chaos", mesh="tp=2",
                    max_seqs=2, block_size=BS, num_blocks=17,
                    max_context=32, prefill_chunk=8,
                    adapter_bank=bank2, prefix_cache=True)
    srv.warmup()
    srv.start()
    prefix = [21] * BS
    srv.submit(prefix + [1], 2).result(timeout=30)   # register prefix
    faults.crash_at_point("llm.worker", nth=2)
    futs = [srv.submit(prefix + [2 + i], 8,
                       adapter="tiny" if i == 0 else None)
            for i in range(3)]
    for f in futs:
        try:
            f.result(timeout=30)
        except BaseException:
            pass                            # typed outcome either way
    assert all(f.done() for f in futs)
    faults.reset()
    deadline_ok = False
    import time
    t0 = time.monotonic()
    while time.monotonic() - t0 < 10:
        if not srv.running:
            deadline_ok = True
            break
        time.sleep(0.01)
    assert deadline_ok
    with pytest.raises(ServerClosed):
        srv.submit([1], 1)
    eng = srv.engine
    assert eng.cache.allocator.num_used == 0
    eng.cache.check([])
    bank2.check()                           # adapter refcounts settled
    # elastic resume: a FRESH engine at tp=1 recomputes the same
    # hashes for the same tokens and rebuilds the shared namespace
    e1 = LLMEngine(model, params, mesh="tp=1", max_seqs=2,
                   block_size=BS, num_blocks=17, max_context=32,
                   prefill_chunk=8, prefix_cache=True)
    e1.warmup()
    _serve(e1, [(prefix + [1], None, None)], max_new=2)
    assert e1.cache.prefix_get(
        prefix_block_hashes(prefix, BS)[0]) is not None
    hits0 = e1.prefix_hits
    out = _serve(e1, [(prefix + [2], None, None)], max_new=4)
    assert e1.prefix_hits > hits0
    assert out[0] == greedy_decode_reference(model, params,
                                             prefix + [2], 4)


# --------------------------------------------------- dp replica groups --
def test_dp_replicas_behind_one_scheduler(model, params):
    """dp=2 replica groups: one server front end, two engines, ONE
    worker thread — least-loaded routing spreads sequences over both
    replicas and every generation matches the eager reference."""
    srv = LLMServer(model, params, name="spmd_dp", mesh="dp=2",
                    max_seqs=2, block_size=BS, num_blocks=17,
                    max_context=32, prefill_chunk=8)
    assert srv.dp == 2
    timings = srv.warmup()
    assert any(k.startswith("dp1.") for k in timings)
    srv.start()
    prompts = [[1 + i, 2, 3] for i in range(6)]
    futs = [srv.submit(p, 5) for p in prompts]
    for p, f in zip(prompts, futs):
        assert f.result(timeout=60).tokens == \
            greedy_decode_reference(model, params, p, 5)
    assert all(e.spmd_dispatches > 0 for e in srv._engines), \
        "least-loaded routing must feed BOTH replicas"
    st = srv.stats()
    assert st["dp"] == 2 and st["mesh"]["devices"] == 2
    ds = srv.debug_status()
    assert ds["dp"] == 2 and len(ds["engines"]) == 1
    srv.shutdown()
    for e in srv._engines:
        assert e.cache.allocator.num_used == 0
        e.cache.check([])


# ------------------------------------------------ slow: bigger meshes --
@pytest.mark.slow
def test_tp4_and_dp2tp2_sweep(model, params):
    """Structural sweep past the fast gate: tp=4 sharding and the
    dp=2 x tp=2 product mesh both reproduce the eager reference."""
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8, 9], [10, 11]]
    refs = [greedy_decode_reference(model, params, p, 6)
            for p in prompts]
    e4 = LLMEngine(model, params, mesh="tp=4", max_seqs=2,
                   block_size=BS, num_blocks=17, max_context=32,
                   prefill_chunk=8)
    e4.warmup()
    out = _serve(e4, [(p, None, None) for p in prompts], max_new=6)
    assert out == refs
    srv = LLMServer(model, params, name="spmd_dp2tp2",
                    mesh="dp=2,tp=2", max_seqs=2, block_size=BS,
                    num_blocks=17, max_context=32, prefill_chunk=8)
    srv.warmup()
    srv.start()
    futs = [srv.submit(p, 6) for p in prompts]
    assert [f.result(timeout=60).tokens for f in futs] == refs
    srv.shutdown()
