"""Fault-tolerance tests: crash-safe checkpoints, preemption resume,
retry/backoff — every recovery path proven by injected faults
(mxnet_tpu.resilience.faults). All tier-1: fast, CPU-only, in-process.
"""
import os
import signal

import numpy as np
import pytest

import mxnet_tpu as mx
import mxnet_tpu.autograd as ag
from mxnet_tpu import error, nd, resilience as rz
from mxnet_tpu.gluon import nn
from mxnet_tpu.resilience import checkpoint as ckpt_mod
from mxnet_tpu.resilience import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _mlp(seed=7):
    mx.nd.random.seed(seed)
    net = nn.Dense(2, in_units=4)
    net.initialize()
    return net


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(8, 4).astype(np.float32),
            rs.randn(8, 2).astype(np.float32))


def _train(net, trainer, n, data=None):
    x, y = data or _batch()
    for _ in range(n):
        with ag.record():
            loss = ((net(nd.array(x)) - nd.array(y)) ** 2).sum()
        loss.backward()
        trainer.step(x.shape[0])


# ------------------------------------------------------------- atomic ----

def test_atomic_write_publishes_on_success(tmp_path):
    p = tmp_path / "f.bin"
    with rz.atomic_write(str(p)) as f:
        f.write(b"hello")
    assert p.read_bytes() == b"hello"
    assert f.crc32 != 0 and f.nbytes == 5
    # no temp strays after a clean write
    assert not [n for n in os.listdir(tmp_path) if rz.is_temp_path(n)]


def test_atomic_write_crash_leaves_previous_contents(tmp_path):
    p = tmp_path / "f.bin"
    with rz.atomic_write(str(p)) as f:
        f.write(b"version-one")
    faults.kill_write_at("f.bin", 4)
    with pytest.raises(rz.InjectedCrash):
        with rz.atomic_write(str(p)) as f:
            f.write(b"version-two-longer")
    # the reader still sees the old version; the stray temp is marked
    assert p.read_bytes() == b"version-one"
    strays = [n for n in os.listdir(tmp_path) if rz.is_temp_path(n)]
    assert strays, "crash should leave the partial temp file behind"


def test_nd_save_killed_at_any_byte_never_corrupts(tmp_path):
    """Golden crash sweep: kill the container write at many byte
    offsets; a reader must ALWAYS see the previous intact file."""
    path = str(tmp_path / "w.params")
    old = {"w": nd.array([1.0, 2.0, 3.0]), "b": nd.array([[9.0]])}
    meta = nd.save(path, old)
    new = {"w": nd.array([4.0, 5.0, 6.0]), "b": nd.array([[-1.0]])}
    for cut in range(0, meta["nbytes"] + 1, 13):
        faults.kill_write_at("w.params", cut)
        with pytest.raises(rz.InjectedCrash):
            nd.save(path, new)
        faults.reset()
        back = nd.load(path, manifest=meta["arrays"])
        assert np.array_equal(back["w"].asnumpy(), [1.0, 2.0, 3.0])
    nd.save(path, new)   # clean write finally goes through
    assert np.array_equal(nd.load(path)["w"].asnumpy(), [4.0, 5.0, 6.0])


def test_block_save_parameters_is_atomic(tmp_path):
    net = _mlp()
    p = str(tmp_path / "net.params")
    net.save_parameters(p)
    before = net.weight.data().asnumpy().copy()
    net.weight.set_data(nd.array(before + 1))
    faults.kill_write_at("net.params", 10)
    with pytest.raises(rz.InjectedCrash):
        net.save_parameters(p)
    faults.reset()
    net2 = _mlp(seed=8)
    net2.load_parameters(p)   # previous file must still be loadable
    assert np.array_equal(net2.weight.data().asnumpy(), before)


# ------------------------------------------------------- typed errors ----

def test_load_rejects_truncated_file(tmp_path):
    p = str(tmp_path / "t.params")
    nd.save(p, {"w": nd.array([1.0, 2.0])})
    raw = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(raw[:len(raw) - 3])
    with pytest.raises(error.CheckpointCorruptError):
        nd.load(p)


def test_load_rejects_bad_magic(tmp_path):
    p = str(tmp_path / "junk.params")
    with open(p, "wb") as f:
        f.write(b"\x00" * 64)
    with pytest.raises(error.CheckpointCorruptError):
        nd.load(p)


def test_load_crc_mismatch_with_manifest(tmp_path):
    p = str(tmp_path / "c.params")
    meta = nd.save(p, {"w": nd.array([1.0, 2.0, 3.0])})
    raw = bytearray(open(p, "rb").read())
    raw[-2] ^= 0xFF   # flip a payload bit, sizes stay right
    with open(p, "wb") as f:
        f.write(bytes(raw))
    with pytest.raises(error.CheckpointCorruptError):
        nd.load(p, manifest=meta["arrays"])


def test_model_load_params_typed_errors(tmp_path):
    prefix = str(tmp_path / "m")
    nd.save(f"{prefix}-0003.params", {"bogus_key": nd.array([1.0])})
    with pytest.raises(error.InternalError, match="bogus_key"):
        mx.model.load_params(prefix, 3)
    # CheckpointCorruptError (a subclass of InternalError) on malformed
    with open(f"{prefix}-0004.params", "wb") as f:
        f.write(b"not a container")
    with pytest.raises(error.CheckpointCorruptError):
        mx.model.load_params(prefix, 4)


# --------------------------------------------------- checkpoint dirs  ----

def test_manager_skips_corrupt_and_falls_back(tmp_path):
    run = str(tmp_path / "run")
    mgr = rz.CheckpointManager(run, keep=10)
    for s in (1, 2, 3):
        mgr.save({"w": nd.array([float(s)])}, step=s)
    # corrupt the newest checkpoint's payload after commit
    newest = os.path.join(run, ckpt_mod.checkpoint_dirname(3),
                          ckpt_mod.DATA_FILE)
    with open(newest, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(size - 1)
        f.write(b"\xff")
    path, manifest = mgr.latest()
    assert manifest["step"] == 2
    assert np.array_equal(mgr.load_arrays(path, manifest)["w"].asnumpy(),
                          [2.0])


def test_crashed_save_ignored_previous_restorable(tmp_path):
    run = str(tmp_path / "run")
    mgr = rz.CheckpointManager(run)
    mgr.save({"w": nd.array([1.0])}, step=1)
    faults.kill_write_at(ckpt_mod.DATA_FILE, 25)
    with pytest.raises(rz.InjectedCrash):
        mgr.save({"w": nd.array([2.0])}, step=2)
    faults.reset()
    path, manifest = mgr.latest()
    assert manifest["step"] == 1   # partial ckpt-…2 dir is invisible
    # the partial directory exists on disk but never validates
    partial = os.path.join(run, ckpt_mod.checkpoint_dirname(2))
    assert os.path.isdir(partial)
    with pytest.raises(error.CheckpointCorruptError):
        rz.validate_checkpoint(partial)
    # pruning clears the unreadable partial
    rz.prune_checkpoints(run, keep=5)
    assert not os.path.isdir(partial)


def test_checkpoint_write_retries_transient_oserrors(tmp_path):
    faults.script("checkpoint.write",
                  [OSError("flaky-1"), OSError("flaky-2")])
    run = str(tmp_path / "run")
    path = rz.write_checkpoint(run, {"w": nd.array([5.0])}, step=7)
    assert path is not None
    _, manifest = rz.latest_checkpoint(run)
    assert manifest["step"] == 7   # succeeded on the 3rd attempt


def test_latest_pointer_stale_falls_back_to_scan(tmp_path):
    run = str(tmp_path / "run")
    mgr = rz.CheckpointManager(run)
    mgr.save({"w": nd.array([1.0])}, step=1)
    with open(os.path.join(run, ckpt_mod.LATEST_NAME), "w") as f:
        f.write("ckpt-0000009999")   # points at nothing
    path, manifest = rz.latest_checkpoint(run)
    assert manifest is not None and manifest["step"] == 1


def test_latest_pointer_behind_does_not_hide_newer(tmp_path):
    """Writer killed between manifest commit and LATEST update: the
    newer committed checkpoint must win over the stale pointer."""
    run = str(tmp_path / "run")
    mgr = rz.CheckpointManager(run)
    mgr.save({"w": nd.array([1.0])}, step=1)
    mgr.save({"w": nd.array([2.0])}, step=2)
    with open(os.path.join(run, ckpt_mod.LATEST_NAME), "w") as f:
        f.write(ckpt_mod.checkpoint_dirname(1))   # one save stale
    path, manifest = rz.latest_checkpoint(run)
    assert manifest["step"] == 2


def test_verify_checkpoint_cli(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import verify_checkpoint
    finally:
        sys.path.pop(0)
    run = str(tmp_path / "run")
    mgr = rz.CheckpointManager(run)
    mgr.save({"w": nd.array([1.0])}, step=1)
    mgr.save({"w": nd.array([2.0])}, step=2)
    assert verify_checkpoint.main([run, "--quiet"]) == 0
    # corrupt everything → gate fails
    for _, path in ckpt_mod.list_checkpoints(run):
        os.remove(os.path.join(path, ckpt_mod.MANIFEST_NAME))
    assert verify_checkpoint.main([run, "--quiet"]) == 1
    assert verify_checkpoint.main([str(tmp_path / "nope"),
                                   "--quiet"]) == 1


# ----------------------------------------------------- retry/backoff  ----

def test_backoff_schedule_deterministic_and_bounded():
    a = rz.backoff_schedule(max_attempts=6, base_delay=0.1, max_delay=1.0,
                            jitter=0.5, seed=3)
    b = rz.backoff_schedule(max_attempts=6, base_delay=0.1, max_delay=1.0,
                            jitter=0.5, seed=3)
    c = rz.backoff_schedule(max_attempts=6, base_delay=0.1, max_delay=1.0,
                            jitter=0.5, seed=4)
    assert a == b            # same seed → identical schedule
    assert a != c            # rank-seeded jitter decorrelates workers
    assert len(a) == 5
    for k, d in enumerate(a):
        lo = min(0.1 * (2.0 ** k), 1.0)
        assert lo <= d <= lo * 1.5   # jitter only ever lengthens, ≤50%


def test_call_with_retry_schedule_and_exhaustion():
    slept = []
    calls = []

    def flaky():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(rz.RetryError) as ei:
        rz.call_with_retry(flaky, max_attempts=4, base_delay=0.1,
                           seed=11, sleep=slept.append)
    assert len(calls) == 4
    assert slept == rz.backoff_schedule(max_attempts=4, base_delay=0.1,
                                        seed=11)
    assert isinstance(ei.value.last, OSError)
    # non-matching exceptions are not retried
    def bad():
        calls.append(1)
        raise KeyError("no")
    calls.clear()
    with pytest.raises(KeyError):
        rz.call_with_retry(bad, max_attempts=4, sleep=slept.append)
    assert len(calls) == 1


def test_init_process_group_retries_transient_failures(monkeypatch):
    from mxnet_tpu.kvstore import tpu as kvtpu
    import jax

    attempts = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: attempts.append(kw))
    monkeypatch.setattr(kvtpu, "_INITIALIZED", False)
    # retry sleeps must not slow the suite down
    from mxnet_tpu.resilience import retry as retry_mod
    monkeypatch.setattr(retry_mod.time, "sleep", lambda s: None)
    faults.script("kvstore.init",
                  [ConnectionRefusedError("coordinator not up"),
                   OSError("still booting"),
                   RuntimeError("barrier timeout")])
    kvtpu.init_process_group(coordinator_address="127.0.0.1:9",
                             num_processes=2, process_id=0)
    assert len(attempts) == 1          # connected on the 4th attempt
    assert kvtpu._INITIALIZED
    monkeypatch.setattr(kvtpu, "_INITIALIZED", False)


# ------------------------------------------------ trainer round-trips ----

def test_gluon_trainer_restore_bit_exact(tmp_path):
    run = str(tmp_path / "run")
    netA = _mlp()
    trA = mx.gluon.Trainer(netA.collect_params(), "adam",
                           {"learning_rate": 0.05})
    _train(netA, trA, 3)
    assert trA.save_state(run) is not None
    _train(netA, trA, 4)
    wA = [p._get_primary().asnumpy() for p in trA._params]

    netB = _mlp(seed=123)   # different init — restore must overwrite
    trB = mx.gluon.Trainer(netB.collect_params(), "adam",
                           {"learning_rate": 0.05})
    manifest = trB.restore_state(run)
    assert manifest["step"] == 3 and trB._step_count == 3
    _train(netB, trB, 4)
    wB = [p._get_primary().asnumpy() for p in trB._params]
    for a, b in zip(wA, wB):
        assert np.array_equal(a, b)   # bit-exact continuation


def test_sharded_trainer_restore_bit_exact(tmp_path):
    from mxnet_tpu.parallel import ShardedTrainer
    run = str(tmp_path / "run")
    x, y = _batch()

    def make(seed):
        mx.nd.random.seed(seed)
        net = nn.Dense(2, in_units=4)
        net.initialize()
        return ShardedTrainer(net, lambda p, l: (p - l) ** 2, "adam",
                              {"learning_rate": 0.05})

    stA = make(9)
    for _ in range(3):
        stA.step(x, y)
    assert stA.save_state(run) is not None
    for _ in range(4):
        stA.step(x, y)
    pA = [np.asarray(stA.params[k]) for k in sorted(stA.params)]

    stB = make(31)   # different init seed — restore must overwrite
    manifest = stB.restore_state(run)   # deferred until first step
    assert manifest["step"] == 3
    for _ in range(4):
        stB.step(x, y)
    assert stB._step_count == 7
    pB = [np.asarray(stB.params[k]) for k in sorted(stB.params)]
    for a, b in zip(pA, pB):
        assert np.array_equal(a, b)


def test_rng_state_roundtrip():
    from mxnet_tpu import _rng
    _rng.seed(42)
    _rng.next_key()
    _rng.next_key()
    st = _rng.get_state()
    a = np.asarray(mx.ndarray.random.uniform(shape=(4,)).asnumpy())
    _rng.seed(999)      # trash the stream
    _rng.set_state(st)  # … and restore it
    b = np.asarray(mx.ndarray.random.uniform(shape=(4,)).asnumpy())
    assert np.array_equal(a, b)


# --------------------------------------------------------- preemption ----

def test_preemption_guard_flags_and_restores_handler():
    prev = signal.getsignal(signal.SIGTERM)
    with rz.PreemptionGuard() as guard:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert guard.requested
        assert guard.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is prev


def test_sigterm_at_step_k_checkpoint_and_resume(tmp_path):
    """The full preemption drill: SIGTERM lands mid-run at step K, the
    loop checkpoints and exits cleanly; a restarted process restores and
    finishes with params identical to an uninterrupted run."""
    run = str(tmp_path / "run")
    total, k = 7, 3

    def preemptible_run():
        net = _mlp()
        tr = mx.gluon.Trainer(net.collect_params(), "adam",
                              {"learning_rate": 0.05})
        with rz.PreemptionGuard() as guard:
            done = 0
            for _ in range(total):
                _train(net, tr, 1)
                done += 1
                if guard.requested:   # poll at the step boundary
                    tr.save_state(run)
                    break
        return net, tr, done

    faults.sigterm_at_step(k)
    net1, tr1, done1 = preemptible_run()
    faults.reset()
    assert done1 == k    # stopped right at the injected preemption
    _, manifest = rz.latest_checkpoint(run)
    assert manifest["step"] == k

    # "restarted process": fresh net+trainer, restore, finish the run
    net2 = _mlp(seed=55)
    tr2 = mx.gluon.Trainer(net2.collect_params(), "adam",
                           {"learning_rate": 0.05})
    tr2.restore_state(run)
    _train(net2, tr2, total - k)

    # uninterrupted reference run
    net3 = _mlp()
    tr3 = mx.gluon.Trainer(net3.collect_params(), "adam",
                           {"learning_rate": 0.05})
    _train(net3, tr3, total)

    for a, b in zip(tr2._params, tr3._params):
        assert np.array_equal(a._get_primary().asnumpy(),
                              b._get_primary().asnumpy())


def test_estimator_checkpoint_on_preemption(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import Estimator
    from mxnet_tpu.gluon.contrib.estimator.event_handler import \
        CheckpointOnPreemption
    from mxnet_tpu.gluon import loss as gloss

    run = str(tmp_path / "run")
    mx.nd.random.seed(3)
    net = nn.Dense(3, in_units=5)
    net.initialize()
    est = Estimator(net, gloss.SoftmaxCrossEntropyLoss(),
                    trainer=mx.gluon.Trainer(net.collect_params(), "sgd",
                                             {"learning_rate": 0.1}))
    rs = np.random.RandomState(1)
    data = [(rs.randn(4, 5).astype(np.float32),
             rs.randint(0, 3, (4,)).astype(np.float32))
            for _ in range(6)]
    handler = CheckpointOnPreemption(run)
    faults.sigterm_at_step(2)
    est.fit(train_data=data, epochs=3, event_handlers=[handler])
    faults.reset()
    assert handler.stop_training          # loop stopped early, cleanly
    assert handler.current_batch < 18     # did not run all 3 epochs
    path, manifest = rz.latest_checkpoint(run)
    assert manifest is not None and manifest["step"] == 2
    # and the checkpoint restores into a fresh trainer
    net2 = _mlp(seed=77)
    mx.nd.random.seed(4)
    net2 = nn.Dense(3, in_units=5)
    net2.initialize()
    net2(nd.array(data[0][0]))   # materialize params
    tr2 = mx.gluon.Trainer(net2.collect_params(), "sgd",
                           {"learning_rate": 0.1})
    tr2.restore_state(run)
    assert tr2._step_count == 2
