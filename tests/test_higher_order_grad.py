"""Higher-order gradients via autograd.grad(create_graph=True).

Cases ported from the reference's
tests/python/unittest/test_higher_order_grad.py (sin/log/sigmoid +
composite polynomials), checked against closed-form derivatives.
"""
import numpy as np
import pytest

from mxnet_tpu import nd
import mxnet_tpu.autograd as ag


def _second_order(fn, x_np, d2_expected, rtol=1e-4):
    x = nd.array(x_np)
    x.attach_grad()
    with ag.record():
        y = fn(x)
        dydx = ag.grad(y, x, create_graph=True, retain_graph=True)
        dydx.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), d2_expected(x_np),
                               rtol=rtol, atol=1e-5)


def test_sin_second_order():
    x_np = np.random.RandomState(0).rand(3, 4).astype(np.float32) * 2
    # d2/dx2 sum(sin x) = -sin x
    _second_order(lambda x: nd.sin(x), x_np, lambda v: -np.sin(v))


def test_log_second_order():
    x_np = (np.random.RandomState(1).rand(3, 4).astype(np.float32) + 0.5)
    _second_order(lambda x: nd.log(x), x_np, lambda v: -1.0 / v ** 2)


def test_sigmoid_second_order():
    x_np = np.random.RandomState(2).randn(3, 4).astype(np.float32)

    def d2(v):
        s = 1 / (1 + np.exp(-v))
        return s * (1 - s) * (1 - 2 * s)
    _second_order(lambda x: nd.sigmoid(x), x_np, d2)


def test_polynomial_second_order():
    x_np = np.random.RandomState(3).randn(3).astype(np.float32)
    # y = x^3 + 2x^2 -> y'' = 6x + 4
    _second_order(lambda x: x * x * x + 2.0 * (x * x), x_np,
                  lambda v: 6 * v + 4)


def test_third_order():
    x_np = np.array([0.7, -0.3, 1.2], np.float32)
    x = nd.array(x_np)
    x.attach_grad()
    with ag.record():
        y = x * x * x * x            # y'''' well defined; check y'''
        g1 = ag.grad(y, x, create_graph=True, retain_graph=True)
        g2 = ag.grad(g1, x, create_graph=True, retain_graph=True)
        g2.backward()
    # y''' = 24x
    np.testing.assert_allclose(x.grad.asnumpy(), 24 * x_np, rtol=1e-4)


def test_first_order_grad_unchanged():
    """grad() without create_graph still returns plain first-order."""
    x = nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with ag.record():
        y = x * x
        g = ag.grad(y, x)
    np.testing.assert_allclose(g.asnumpy(), [4.0])


def test_grad_of_product_of_grads():
    """Hessian-vector-ish pattern: loss built FROM a gradient trains."""
    x_np = np.array([1.0, 2.0], np.float32)
    x = nd.array(x_np)
    x.attach_grad()
    with ag.record():
        y = (x * x * x).sum()
        (gx,) = ag.grad(y, [x], create_graph=True, retain_graph=True)
        penalty = (gx * gx).sum()    # sum (3x^2)^2 = 9x^4
        penalty.backward()
    # d/dx 9x^4 = 36 x^3
    np.testing.assert_allclose(x.grad.asnumpy(), 36 * x_np ** 3,
                               rtol=1e-4)
