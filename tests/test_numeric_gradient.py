"""Numeric-gradient sweep over the op registry.

The reference sweeps its hand-written backward kernels with
check_numeric_gradient (tests/python/unittest/test_operator.py uses
python/mxnet/test_utils.py:987 pervasively); here the same harness pins
the framework path (op -> invoke -> tape -> jax.vjp) against central
finite differences, op family by op family, plus eager-vs-jit
consistency (the TPU analogue of check_consistency).
"""
import numpy as np
import pytest

from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_eager_jit_consistency,
                                  check_numeric_gradient)


def _r(*shape, seed=0, scale=1.0, shift=0.0):
    return np.random.RandomState(seed).randn(*shape) * scale + shift


# (op, inputs, kwargs) — inputs kept tiny: numeric diff is O(size) evals.
UNARY_SMOOTH = [
    ("exp", [_r(3, 4, scale=0.5)], {}),
    ("log", [np.abs(_r(3, 4)) + 0.5], {}),
    ("log10", [np.abs(_r(3, 4)) + 0.5], {}),
    ("log2", [np.abs(_r(3, 4)) + 0.5], {}),
    ("log1p", [np.abs(_r(3, 4))], {}),
    ("expm1", [_r(3, 4, scale=0.5)], {}),
    ("sqrt", [np.abs(_r(3, 4)) + 0.5], {}),
    ("rsqrt", [np.abs(_r(3, 4)) + 0.5], {}),
    ("cbrt", [np.abs(_r(3, 4)) + 0.5], {}),
    ("rcbrt", [np.abs(_r(3, 4)) + 0.5], {}),
    ("square", [_r(3, 4)], {}),
    ("reciprocal", [np.abs(_r(3, 4)) + 0.5], {}),
    ("sin", [_r(3, 4)], {}),
    ("cos", [_r(3, 4)], {}),
    ("tan", [_r(3, 4, scale=0.3)], {}),
    ("sinh", [_r(3, 4, scale=0.5)], {}),
    ("cosh", [_r(3, 4, scale=0.5)], {}),
    ("tanh", [_r(3, 4)], {}),
    ("arcsin", [_r(3, 4, scale=0.3)], {}),
    ("arccos", [_r(3, 4, scale=0.3)], {}),
    ("arctan", [_r(3, 4)], {}),
    ("arcsinh", [_r(3, 4)], {}),
    ("arccosh", [np.abs(_r(3, 4)) + 1.5], {}),
    ("arctanh", [_r(3, 4, scale=0.3)], {}),
    ("sigmoid", [_r(3, 4)], {}),
    ("log_sigmoid", [_r(3, 4)], {}),
    ("softsign", [_r(3, 4)], {}),
    ("softrelu", [_r(3, 4)], {}),
    ("erf", [_r(3, 4, scale=0.5)], {}),
    ("erfinv", [_r(3, 4, scale=0.2)], {}),
    ("gamma", [np.abs(_r(3, 4)) + 1.0], {}),
    ("gammaln", [np.abs(_r(3, 4)) + 1.0], {}),
    ("gelu", [_r(3, 4)], {}),
    ("silu", [_r(3, 4)], {}),
    ("mish", [_r(3, 4)], {}),
    ("negative", [_r(3, 4)], {}),
    ("relu", [_r(3, 4, shift=0.3)], {}),   # keep away from the kink
    ("abs", [_r(3, 4, shift=0.3)], {}),
    ("smooth_l1", [_r(3, 4, shift=3.0)], {}),
    ("logsumexp", [_r(3, 4)], {"axis": 1}),
]

BINARY = [
    ("elemwise_add", [_r(3, 4), _r(3, 4, seed=1)], {}),
    ("elemwise_sub", [_r(3, 4), _r(3, 4, seed=1)], {}),
    ("elemwise_mul", [_r(3, 4), _r(3, 4, seed=1)], {}),
    ("elemwise_div", [_r(3, 4), np.abs(_r(3, 4, seed=1)) + 0.5], {}),
    ("broadcast_add", [_r(3, 4), _r(1, 4, seed=1)], {}),
    ("broadcast_mul", [_r(3, 4), _r(3, 1, seed=1)], {}),
    ("broadcast_sub", [_r(3, 4), _r(1, 4, seed=1)], {}),
    ("broadcast_div", [_r(3, 4), np.abs(_r(1, 4, seed=1)) + 0.5], {}),
    ("broadcast_power", [np.abs(_r(3, 4)) + 0.5,
                         _r(1, 4, seed=1, scale=0.5)], {}),
    ("broadcast_hypot", [_r(3, 4, shift=2), _r(1, 4, seed=1, shift=2)], {}),
    ("broadcast_maximum", [_r(3, 4), _r(3, 4, seed=1) + 0.05], {}),
    ("broadcast_minimum", [_r(3, 4), _r(3, 4, seed=1) + 0.05], {}),
    ("arctan2", [_r(3, 4, shift=1.5), _r(3, 4, seed=1, shift=1.5)], {}),
    ("hypot", [_r(3, 4, shift=2), _r(3, 4, seed=1, shift=2)], {}),
    ("maximum", [_r(3, 4), _r(3, 4, seed=1) + 0.05], {}),
    ("minimum", [_r(3, 4), _r(3, 4, seed=1) + 0.05], {}),
]

REDUCE_SHAPE = [
    ("sum", [_r(3, 4)], {"axis": 1}),
    ("mean", [_r(3, 4)], {"axis": 0}),
    ("prod", [np.abs(_r(3, 3)) + 0.5], {"axis": 1}),
    ("nansum", [_r(3, 4)], {"axis": 1}),
    ("max", [_r(3, 4)], {"axis": 1}),
    ("min", [_r(3, 4)], {"axis": 1}),
    ("norm", [_r(3, 4, shift=1)], {"ord": 2, "axis": 1}),
    ("transpose", [_r(3, 4)], {}),
    ("reshape", [_r(3, 4)], {"shape": (4, 3)}),
    ("flatten", [_r(2, 3, 4)], {}),
    ("expand_dims", [_r(3, 4)], {"axis": 1}),
    ("squeeze", [_r(3, 1, 4)], {}),
    ("flip", [_r(3, 4)], {"axis": 1}),
    ("reverse", [_r(3, 4)], {"axis": 1}),
    ("tile", [_r(2, 3)], {"reps": (2, 2)}),
    ("repeat", [_r(2, 3)], {"repeats": 2, "axis": 1}),
    ("pad", [_r(1, 1, 3, 4)], {"mode": "constant",
                               "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}),
    ("slice", [_r(4, 5)], {"begin": (1, 0), "end": (3, 4)}),
    ("slice_axis", [_r(4, 5)], {"axis": 1, "begin": 1, "end": 4}),
    ("clip", [_r(3, 4, scale=2)], {"a_min": -1.0, "a_max": 1.0}),
    ("swapaxes", [_r(2, 3, 4)], {"dim1": 0, "dim2": 2}),
    ("cumsum", [_r(3, 4)], {"axis": 1}),
    ("diag", [_r(4, 4)], {}),
    ("where", [np.array([[1.0, 0.0], [0.0, 1.0]]), _r(2, 2),
               _r(2, 2, seed=1)], {"_numeric_grad_inputs": (1, 2)}),
]

NN_OPS = [
    ("softmax", [_r(3, 5)], {}),
    ("log_softmax", [_r(3, 5)], {}),
    ("softmin", [_r(3, 5)], {}),
    ("FullyConnected", [_r(3, 4), _r(5, 4, seed=1), _r(5, seed=2)],
     {"num_hidden": 5}),
    ("dot", [_r(3, 4), _r(4, 5, seed=1)], {}),
    ("batch_dot", [_r(2, 3, 4), _r(2, 4, 5, seed=1)], {}),
    ("Convolution", [_r(1, 2, 5, 5), _r(3, 2, 3, 3, seed=1, scale=0.5),
                     _r(3, seed=2)],
     {"kernel": (3, 3), "num_filter": 3, "pad": (1, 1)}),
    ("Deconvolution", [_r(1, 2, 4, 4), _r(2, 3, 2, 2, seed=1, scale=0.5)],
     {"kernel": (2, 2), "stride": (2, 2), "num_filter": 3,
      "no_bias": True}),
    ("Pooling", [_r(1, 2, 6, 6)],
     {"kernel": (2, 2), "stride": (2, 2), "pool_type": "avg"}),
    ("LayerNorm", [_r(3, 6), np.ones(6, np.float32),
                   np.zeros(6, np.float32)], {}),
    ("L2Normalization", [_r(2, 3, 4, shift=1)], {}),
    ("Activation", [_r(3, 4, shift=0.3)], {"act_type": "tanh"}),
    ("LeakyReLU", [_r(3, 4, shift=0.3)], {"act_type": "leaky",
                                          "slope": 0.1}),
    ("pick", [_r(3, 4), np.array([0.0, 2.0, 1.0])],
     {"_numeric_grad_inputs": (0,)}),
    ("take", [_r(4, 3), np.array([0.0, 2.0])],
     {"_numeric_grad_inputs": (0,)}),
    ("Embedding", [np.array([0.0, 2.0, 1.0]), _r(4, 3)],
     {"input_dim": 4, "output_dim": 3, "_numeric_grad_inputs": (1,)}),
    ("one_hot", [np.array([0.0, 2.0])], {"depth": 4,
                                         "_numeric_grad_inputs": ()}),
]

R3_OPS = [
    # round-3 additions: differentiable tail ops get the same oracle
    ("_s2d_stem_conv", [_r(1, 8, 8, 3), _r(4, 7, 7, 3, seed=1,
                                           scale=0.3)], {}),
    ("_contrib_interleaved_matmul_selfatt_qk",
     [_r(4, 2, 3 * 2 * 3, scale=0.5)], {"heads": 2}),
    ("_contrib_interleaved_matmul_selfatt_valatt",
     [_r(4, 2, 3 * 2 * 3, scale=0.5), _r(4, 4, 4, seed=1, scale=0.3)],
     {"heads": 2}),
    ("Correlation", [_r(1, 2, 4, 4), _r(1, 2, 4, 4, seed=1)],
     {"kernel_size": 1, "max_displacement": 1, "pad_size": 1}),
    # numeric diff is O(size) forwards and the deformable forward is a
    # python tap loop: keep it tiny and check only the 12-element weight
    ("_contrib_DeformableConvolution",
     [_r(1, 1, 3, 3), _r(1, 8, 2, 2, seed=1, scale=0.2),
      _r(3, 1, 2, 2, seed=2, scale=0.5)],
     {"kernel": (2, 2), "pad": (0, 0), "num_filter": 3, "no_bias": True,
      "_numeric_grad_inputs": (2,)}),
    ("_contrib_RROIAlign",
     [_r(1, 2, 8, 8), np.array([[0, 4.0, 4.0, 4.0, 4.0, 20.0]],
                               np.float32)],
     {"pooled_size": (2, 2), "_numeric_grad_inputs": (0,)}),
    ("GroupNorm", [_r(2, 4, 3), np.ones(4, np.float32),
                   np.zeros(4, np.float32)], {"num_groups": 2}),
    ("InstanceNorm", [_r(2, 3, 5), np.ones(3, np.float32),
                      np.zeros(3, np.float32)], {}),
    # exact index-copy op: the autograd side is exact, but f32
    # central differences on unit-scale inputs carry ~5e-3 noise on
    # near-zero elements — give the NUMERIC side the atol it needs
    ("im2col", [_r(1, 2, 5, 5)], {"kernel": (3, 3), "stride": (1, 1),
                                  "pad": (1, 1),
                                  "_numeric_tol": (2e-2, 8e-3)}),
    ("_image_normalize", [_r(3, 4, 4)], {"mean": 0.2, "std": 0.7}),
    ("_contrib_count_sketch",
     [_r(2, 4), np.array([0.0, 1, 0, 2]),
      np.array([1.0, -1, 1, -1], np.float32)],
     {"out_dim": 3, "_numeric_grad_inputs": (0,)}),
]

ALL_CASES = UNARY_SMOOTH + BINARY + REDUCE_SHAPE + NN_OPS + R3_OPS

# the python-tap-loop deformable/rotated-ROI forwards cost 13-18s each
# under numeric differencing (tier-1 budget, ISSUE 12); they still run
# under -m slow
_SLOW_GRAD_OPS = {"_contrib_DeformableConvolution", "_contrib_RROIAlign"}


@pytest.mark.parametrize(
    "op,inputs,kwargs",
    [pytest.param(*c, marks=pytest.mark.slow)
     if c[0] in _SLOW_GRAD_OPS else c for c in ALL_CASES],
    ids=[f"{c[0]}-{i}" for i, c in enumerate(ALL_CASES)])
def test_numeric_gradient(op, inputs, kwargs):
    kwargs = dict(kwargs)
    grad_inputs = kwargs.pop("_numeric_grad_inputs", None)
    rtol, atol = kwargs.pop("_numeric_tol", (2e-2, 2e-3))
    if grad_inputs == ():
        pytest.skip("no differentiable inputs")
    check_numeric_gradient(op, inputs, kwargs, rtol=rtol, atol=atol,
                           grad_inputs=grad_inputs)


@pytest.mark.parametrize(
    "op,inputs,kwargs", ALL_CASES,
    ids=[f"{c[0]}-{i}" for i, c in enumerate(ALL_CASES)])
def test_eager_jit_consistency(op, inputs, kwargs):
    kwargs = {k: v for k, v in kwargs.items()
              if not k.startswith("_numeric_")}
    check_eager_jit_consistency(
        op, [np.asarray(x, np.float32) for x in inputs], kwargs)


def test_assert_almost_equal_reports_location():
    a = np.zeros((2, 2))
    b = np.zeros((2, 2))
    b[1, 0] = 1.0
    with pytest.raises(AssertionError, match=r"\(1, 0\)"):
        assert_almost_equal(a, b)


@pytest.mark.parametrize("op,inputs,kwargs", [
    ("FullyConnected", [np.random.RandomState(0).randn(3, 4)
                        .astype(np.float32),
                        np.random.RandomState(1).randn(5, 4)
                        .astype(np.float32),
                        np.random.RandomState(2).randn(5)
                        .astype(np.float32)], {"num_hidden": 5}),
    ("softmax", [np.random.RandomState(0).randn(3, 5)
                 .astype(np.float32)], {}),
    ("Convolution", [np.random.RandomState(0).randn(1, 2, 5, 5)
                     .astype(np.float32),
                     np.random.RandomState(1).randn(3, 2, 3, 3)
                     .astype(np.float32) * 0.5,
                     np.zeros(3, np.float32)],
     {"kernel": (3, 3), "num_filter": 3, "pad": (1, 1)}),
])
def test_check_consistency_across_contexts_dtypes(op, inputs, kwargs):
    """test_utils.check_consistency (reference: test_utils.py:1460):
    results agree across every available context and the fp64/fp32
    dtype ladder."""
    from mxnet_tpu.test_utils import check_consistency
    results = check_consistency(op, inputs, kwargs)
    assert len(results) >= 2
