#!/usr/bin/env python
"""Continuous-batching greedy decoding with a paged KV cache.

The full LLM serving path in one file:

1. build a tiny decoder-only transformer and export it in DECODE shape
   (``mx.deploy.export_decoder``: config + params, loadable on a
   serving host);
2. load it back and wrap it in ``mx.serving.llm.LLMServer``: a fixed
   pool of KV blocks, per-sequence block tables, ragged attention over
   the paged cache, and token-level continuous batching — sequences
   are admitted (chunked prefill) and retired every engine step;
3. ``warmup()`` pre-compiles the ONE fixed chunked-step shape (prompts
   prefill in chunks THROUGH the decode program), so the ragged load
   phase below runs with ZERO XLA recompiles (the script asserts this);
4. verify a sample of generations token-for-token against eager
   per-sequence greedy decoding, then print tokens/sec, TTFT and
   KV-cache occupancy.

  python examples/llm_serve_decode.py --threads 4 --requests 8
"""
import argparse
import os
import sys
import tempfile
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import serving  # noqa: E402
from mxnet_tpu.serving.llm import (TinyDecoder, DecoderConfig,  # noqa: E402
                                   LLMServer, greedy_decode_reference)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--requests", type=int, default=8,
                    help="generations per thread")
    ap.add_argument("--max-seqs", type=int, default=4,
                    help="decode batch slots")
    ap.add_argument("--block-size", type=int, default=8,
                    help="KV block size (tokens)")
    ap.add_argument("--max-context", type=int, default=64)
    ap.add_argument("--max-new-tokens", type=int, default=12)
    ap.add_argument("--vocab", type=int, default=32)
    args = ap.parse_args()

    # ---- 1. build + export in decode shape ------------------------
    model = TinyDecoder(DecoderConfig(
        vocab_size=args.vocab, d_model=32, num_layers=2, num_heads=2,
        d_ff=64, max_context=args.max_context))
    params = model.init_params(seed=0)
    path = os.path.join(tempfile.mkdtemp(), "decoder.mxtpu")
    mx.deploy.export_decoder(model, params, path)
    print(f"exported decode-shaped artifact -> {path}")

    # ---- 2. load + serve ------------------------------------------
    model, params = mx.deploy.load_decoder(path)
    srv = LLMServer(model, params, name="example",
                    max_seqs=args.max_seqs, block_size=args.block_size,
                    max_context=args.max_context)

    # ---- 3. warmup, then a recompile-free ragged load -------------
    warm = srv.warmup()
    print("warmup compiled programs:",
          {k: f"{s:.2f}s" for k, s in sorted(warm.items())})
    srv.start()

    rng = np.random.RandomState(1)
    lock = threading.Lock()
    sample = []          # (prompt, n, result) for the oracle check
    errors = []

    def client(tid):
        try:
            trng = np.random.RandomState(100 + tid)
            for i in range(args.requests):
                plen = int(trng.randint(1, args.max_context // 2))
                prompt = trng.randint(0, args.vocab,
                                      size=plen).tolist()
                n = 1 + int(trng.randint(0, args.max_new_tokens))
                res = srv.generate(prompt, n, timeout=300)
                # the context cap may legally end a generation early
                assert len(res.tokens) == min(
                    n, args.max_context - len(prompt))
                with lock:
                    if len(sample) < 6:
                        sample.append((prompt, n, res))
        except Exception as exc:        # surface, don't swallow
            errors.append(f"thread {tid}: {exc!r}")

    with serving.CompileCounter() as cc:
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(args.threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    # ---- 4. drain + verify + report -------------------------------
    stats = srv.stats()
    srv.shutdown()
    if errors:
        print("\n".join(errors))
        sys.exit(1)
    if cc.count != 0:
        print(f"FAIL: {cc.count} XLA recompiles during load")
        sys.exit(1)
    for prompt, n, res in sample:
        ref = greedy_decode_reference(model, params, prompt, n)
        if res.tokens != ref:
            print(f"FAIL: batched decode diverged from eager oracle "
                  f"for prompt len {len(prompt)}")
            sys.exit(1)
    total = args.threads * args.requests
    print(f"served {stats['requests_completed']}/{total} generations, "
          f"0 recompiles, {len(sample)} oracle-checked")
    print(f"decode rate {stats['tokens_per_sec']:.0f} tok/s (EMA) | "
          f"ttft p50 {stats['ttft_ms']['p50']:.2f} ms, "
          f"p99 {stats['ttft_ms']['p99']:.2f} ms | "
          f"kv blocks {stats['kv_blocks_total']} "
          f"({stats['preemptions']} preemptions)")
    assert stats["requests_completed"] == total


if __name__ == "__main__":
    main()
