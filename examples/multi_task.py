#!/usr/bin/env python
"""Multi-task learning: one trunk, two heads, jointly-weighted losses.

Reference example: example/multi-task/multi-task-learning.ipynb (one
conv trunk on MNIST with a digit head and an odd/even head trained
jointly). Same structure here on a synthetic digit-bitmap dataset:
task 1 classifies the digit (10-way), task 2 predicts its parity
(binary) — the trunk must serve both gradients at once.

TPU-first notes: both heads and both losses live inside one recorded
graph, so the whole joint step compiles to a single XLA program; the
per-task loss weights are static constants folded into the program.

  python examples/multi_task.py --epochs 8
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402

from lstm_ocr import _GLYPHS, GLYPH_H, GLYPH_W  # noqa: E402  (7x5 bitmaps)


def make_digits(n, seed):
    """(n, 1, 12, 12) noisy single-digit images + labels."""
    rng = np.random.default_rng(seed)
    imgs = rng.uniform(0, 0.2, size=(n, 1, 12, 12)).astype(np.float32)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    for i, d in enumerate(labels):
        y = rng.integers(0, 12 - GLYPH_H + 1)
        x = rng.integers(0, 12 - GLYPH_W + 1)
        g = np.array([[float(c) for c in row] for row in _GLYPHS[d]],
                     np.float32)
        imgs[i, 0, y:y + GLYPH_H, x:x + GLYPH_W] += g * rng.uniform(0.7, 1.0)
    return np.clip(imgs, 0, 1), labels


class MultiTaskNet(gluon.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.trunk = nn.HybridSequential()
            self.trunk.add(nn.Conv2D(16, 3, activation="relu"),
                           nn.MaxPool2D(2),
                           nn.Flatten(),
                           nn.Dense(64, activation="relu"))
            self.digit_head = nn.Dense(10)
            self.parity_head = nn.Dense(2)

    def hybrid_forward(self, F, x):
        z = self.trunk(x)
        return self.digit_head(z), self.parity_head(z)


def evaluate(net, imgs, labels, batch):
    dig_m = mx.metric.Accuracy(name="digit-acc")
    par_m = mx.metric.Accuracy(name="parity-acc")
    for i in range(0, len(imgs), batch):
        d, p = net(nd.array(imgs[i:i + batch]))
        lab = labels[i:i + batch]
        dig_m.update([nd.array(lab)], [d])
        par_m.update([nd.array(lab % 2)], [p])
    return dig_m.get()[1], par_m.get()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-samples", type=int, default=1024)
    ap.add_argument("--parity-weight", type=float, default=0.3)
    ap.add_argument("--min-acc", type=float, default=0.0)
    args = ap.parse_args()

    imgs, labels = make_digits(args.num_samples, seed=5)
    ev_imgs, ev_labels = make_digits(max(args.batch_size,
                                         args.num_samples // 8), seed=77)

    mx.random.seed(0)
    net = MultiTaskNet()
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()

    B = args.batch_size
    n = (len(imgs) // B) * B
    for epoch in range(args.epochs):
        perm = np.random.default_rng(epoch).permutation(n)
        total = 0.0
        for i in range(0, n, B):
            idx = perm[i:i + B]
            x = nd.array(imgs[idx])
            y_digit = nd.array(labels[idx])
            y_parity = nd.array(labels[idx] % 2)
            with ag.record():
                digit_logits, parity_logits = net(x)
                loss = (sce(digit_logits, y_digit).mean()
                        + args.parity_weight
                        * sce(parity_logits, y_parity).mean())
            loss.backward()
            trainer.step(B)
            total += float(loss.asnumpy())
        dig, par = evaluate(net, ev_imgs, ev_labels, B)
        print(f"epoch {epoch}: loss {total / (n // B):.4f} "
              f"digit-acc {dig:.3f} parity-acc {par:.3f}")

    if min(dig, par) < args.min_acc:
        print(f"FAIL: accuracy {min(dig, par):.3f} < {args.min_acc}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
