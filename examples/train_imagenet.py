"""Train an ImageNet classifier through the user-facing legacy path:
symbolic network -> Module.fit + MXDataIter("ImageRecordIter") over the
native RecordIO reader + kvstore.

This is the parity driver for the reference's north-star protocol
(reference: example/image-classification/train_imagenet.py:1 and
common/fit.py:150 — Module.fit fed by ImageRecordIter with kvstore), the
same script whose throughput is the BASELINE.md headline number.

Usage (real data):
    python examples/train_imagenet.py --data-train train.rec \
        --data-val val.rec --network resnet --num-layers 50 \
        --batch-size 128 --num-epochs 90 --lr 0.1 --lr-step-epochs 30,60

Synthetic-data mode (no rec files; reference fit.py:236 does the same
for its --benchmark flag):
    python examples/train_imagenet.py --benchmark 1 --num-examples 1024

The smoke test in tests/test_train_imagenet.py drives main() end-to-end
on generated .rec files at a reduced image shape.
"""
import argparse
import logging
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402


# --------------------------------------------------------------- network --
def _conv_bn_relu(data, num_filter, kernel, stride, pad, name, relu=True):
    body = mx.sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                              stride=stride, pad=pad, no_bias=True,
                              name=name + "_conv")
    body = mx.sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=0.9,
                            name=name + "_bn")
    if relu:
        body = mx.sym.Activation(body, act_type="relu",
                                 name=name + "_relu")
    return body


def _residual_unit(data, num_filter, stride, dim_match, name, bottle_neck):
    """One ResNet v1.5 unit (stride lives in the 3x3 conv)."""
    if bottle_neck:
        body = _conv_bn_relu(data, num_filter // 4, (1, 1), (1, 1), (0, 0),
                             name + "_a")
        body = _conv_bn_relu(body, num_filter // 4, (3, 3), stride, (1, 1),
                             name + "_b")
        body = _conv_bn_relu(body, num_filter, (1, 1), (1, 1), (0, 0),
                             name + "_c", relu=False)
    else:
        body = _conv_bn_relu(data, num_filter, (3, 3), stride, (1, 1),
                             name + "_a")
        body = _conv_bn_relu(body, num_filter, (3, 3), (1, 1), (1, 1),
                             name + "_b", relu=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn_relu(data, num_filter, (1, 1), stride, (0, 0),
                                 name + "_ds", relu=False)
    out = mx.sym.elemwise_add(body, shortcut, name=name + "_add")
    return mx.sym.Activation(out, act_type="relu", name=name + "_out")


_RESNET_UNITS = {18: ([2, 2, 2, 2], False), 34: ([3, 4, 6, 3], False),
                 50: ([3, 4, 6, 3], True), 101: ([3, 4, 23, 3], True),
                 152: ([3, 8, 36, 3], True)}


def get_resnet_symbol(num_classes, num_layers, image_shape):
    """Symbolic ResNet (reference network builder:
    example/image-classification/symbols/resnet.py:1)."""
    if num_layers not in _RESNET_UNITS:
        raise ValueError(f"resnet num_layers must be one of "
                         f"{sorted(_RESNET_UNITS)}, got {num_layers}")
    units, bottle_neck = _RESNET_UNITS[num_layers]
    filters = [256, 512, 1024, 2048] if bottle_neck else [64, 128, 256, 512]
    height = image_shape[1]

    data = mx.sym.Variable("data")
    if height <= 32:  # CIFAR-style stem
        body = _conv_bn_relu(data, 64, (3, 3), (1, 1), (1, 1), "stem")
    else:
        body = _conv_bn_relu(data, 64, (7, 7), (2, 2), (3, 3), "stem")
        body = mx.sym.Pooling(body, kernel=(3, 3), stride=(2, 2),
                              pad=(1, 1), pool_type="max", name="stem_pool")
    for stage, (n_units, nf) in enumerate(zip(units, filters)):
        for unit in range(n_units):
            stride = (1, 1) if stage == 0 or unit > 0 else (2, 2)
            body = _residual_unit(body, nf, stride, dim_match=unit > 0,
                                  name=f"stage{stage + 1}_unit{unit + 1}",
                                  bottle_neck=bottle_neck)
    body = mx.sym.Pooling(body, global_pool=True, pool_type="avg",
                          kernel=(1, 1), name="gap")
    body = mx.sym.Flatten(body, name="flat")
    body = mx.sym.FullyConnected(body, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(body, name="softmax")


def get_network(args):
    if args.network == "resnet":
        return get_resnet_symbol(args.num_classes, args.num_layers,
                                 args.image_shape_t)
    raise ValueError(f"unknown --network {args.network!r} "
                     "(this driver ships resnet; other families live in "
                     "mxnet_tpu.gluon.model_zoo)")


# ------------------------------------------------------------------ data --
def get_rec_iter(args, kv):
    """ImageRecordIter pair through the MXDataIter dispatch (reference:
    common/data.py get_rec_iter — ImageRecordIter with sharding by
    kv.rank/kv.num_workers)."""
    image_shape = args.image_shape_t
    train = mx.io.MXDataIter(
        "ImageRecordIter",
        path_imgrec=args.data_train,
        data_shape=image_shape,
        batch_size=args.batch_size,
        shuffle=True,
        rand_crop=True,
        rand_mirror=True,
        resize=args.resize,
        num_parts=kv.num_workers,
        part_index=kv.rank,
    )
    if not args.data_val:
        return train, None
    val = mx.io.MXDataIter(
        "ImageRecordIter",
        path_imgrec=args.data_val,
        data_shape=image_shape,
        batch_size=args.batch_size,
        shuffle=False,
        resize=args.resize,
        num_parts=kv.num_workers,
        part_index=kv.rank,
    )
    return train, val


def get_synthetic_iter(args):
    """Random-data iterator for --benchmark runs (reference:
    common/fit.py:236 SyntheticDataIter usage)."""
    image_shape = args.image_shape_t
    n = max(args.batch_size * 4, 64)
    rng = np.random.RandomState(0)
    data = rng.uniform(-1, 1, (n,) + image_shape).astype(np.float32)
    label = rng.randint(0, args.num_classes, (n,)).astype(np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=args.batch_size,
                           shuffle=False)
    epoch_size = math.ceil(args.num_examples / args.batch_size)
    return mx.io.ResizeIter(it, epoch_size), None


# ------------------------------------------------------------------- fit --
def _lr_scheduler(args, epoch_size, begin_epoch):
    """(lr, scheduler) with resume handling: decays already passed by
    begin_epoch are applied to the base lr, remaining steps are offset
    (reference: common/fit.py _get_lr_scheduler:29)."""
    lr = args.lr
    tokens = [t.strip() for t in (args.lr_step_epochs or "").split(",")]
    step_epochs = [int(t) for t in tokens if t]
    for e in step_epochs:
        if begin_epoch >= e:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjusted learning rate to %e for epoch %d",
                     lr, begin_epoch)
    steps = [epoch_size * (e - begin_epoch) for e in step_epochs
             if e > begin_epoch]
    if not steps:
        return lr, None
    return lr, mx.lr_scheduler.MultiFactorScheduler(
        step=steps, factor=args.lr_factor, base_lr=lr)


def fit(args, network, train, val=None, kv=None):
    """Module.fit wiring (reference: common/fit.py:150)."""
    if kv is None:
        kv = mx.kv.create(args.kv_store)
    begin_epoch = (args.load_epoch
                   if args.model_prefix and args.load_epoch is not None
                   else 0)
    epoch_size = math.ceil(args.num_examples / kv.num_workers
                           / args.batch_size)
    lr, sched = _lr_scheduler(args, epoch_size, begin_epoch)

    mod = mx.mod.Module(symbol=network, context=mx.context.current_context())
    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "rescale_grad": 1.0 / args.batch_size,
    }
    if sched is not None:
        optimizer_params["lr_scheduler"] = sched
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.mom

    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(
            mx.metric.create("top_k_accuracy", top_k=args.top_k))

    batch_cb = mx.callback.Speedometer(args.batch_size, args.disp_batches)
    epoch_cb = (mx.callback.do_checkpoint(args.model_prefix,
                                          period=args.save_period)
                if args.model_prefix else None)

    initializer = mx.initializer.Xavier(rnd_type="gaussian",
                                        factor_type="in", magnitude=2)
    arg_params = aux_params = None
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)

    mod.fit(train,
            eval_data=val,
            eval_metric=eval_metrics,
            kvstore=kv,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=initializer,
            arg_params=arg_params,
            aux_params=aux_params,
            batch_end_callback=batch_cb,
            epoch_end_callback=epoch_cb,
            begin_epoch=begin_epoch,
            num_epoch=args.num_epochs,
            allow_missing=True)
    return mod


def add_args(parser):
    parser.add_argument("--network", type=str, default="resnet")
    parser.add_argument("--num-layers", type=int, default=50)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--num-examples", type=int, default=1281167)
    parser.add_argument("--image-shape", type=str, default="3,224,224")
    parser.add_argument("--resize", type=int, default=0,
                        help="resize shorter edge before augmentation")
    parser.add_argument("--data-train", type=str,
                        help="training .rec file")
    parser.add_argument("--data-val", type=str, help="validation .rec")
    parser.add_argument("--kv-store", type=str, default="device")
    parser.add_argument("--num-epochs", type=int, default=90)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--lr-factor", type=float, default=0.1)
    parser.add_argument("--lr-step-epochs", type=str, default="30,60,80")
    parser.add_argument("--optimizer", type=str, default="sgd")
    parser.add_argument("--mom", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=1e-4)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--disp-batches", type=int, default=20)
    parser.add_argument("--model-prefix", type=str)
    parser.add_argument("--save-period", type=int, default=1)
    parser.add_argument("--load-epoch", type=int)
    parser.add_argument("--top-k", type=int, default=0)
    parser.add_argument("--benchmark", type=int, default=0,
                        help="1 = train on synthetic random data")
    parser.add_argument("--dtype", type=str, default="float32",
                        choices=["float32", "bfloat16", "float16"],
                        help="mixed precision via mx.amp (float16 maps "
                             "to bfloat16 — the TPU-native half type)")
    return parser


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    parser = add_args(argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter))
    args = parser.parse_args(argv)
    args.image_shape_t = tuple(int(x) for x in args.image_shape.split(","))
    if args.dtype != "float32":
        # reference --dtype float16 == AMP; bf16 is the TPU half type
        from mxnet_tpu import amp
        amp.init(target_dtype="bfloat16")
    network = get_network(args)
    kv = mx.kv.create(args.kv_store)
    if args.benchmark:
        train, val = get_synthetic_iter(args)
    else:
        if not args.data_train:
            parser.error("--data-train is required (or pass --benchmark 1)")
        train, val = get_rec_iter(args, kv)
    return fit(args, network, train, val, kv=kv)


if __name__ == "__main__":
    main()
