#!/usr/bin/env python
"""Serve an exported ``.mxtpu`` artifact with dynamic batching.

The full serving path in one file:

1. train-ish: build a tiny MLP and export it batch-polymorphically
   (``poly_batch=True`` — one artifact, any batch size);
2. load it back with ``mx.deploy.load_predictor`` (only jax needed on
   a real serving host) and wrap it in a
   ``mx.serving.ModelServer``: concurrent single-sample requests are
   coalesced into micro-batches and padded to power-of-two buckets;
3. ``warmup()`` pre-compiles every bucket, so the load phase below
   runs with ZERO XLA recompiles (the script asserts this);
4. drain gracefully and print the latency/throughput/waste stats.

  python examples/serve_predictor.py --threads 8 --requests 64
"""
import argparse
import os
import sys
import tempfile
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd, serving  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--requests", type=int, default=64,
                    help="requests per thread")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-delay-ms", type=float, default=2.0)
    ap.add_argument("--feature-dim", type=int, default=32)
    args = ap.parse_args()

    # ---- 1. export ------------------------------------------------
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()
    example = np.zeros((1, args.feature_dim), np.float32)
    with ag.pause():
        net(nd.array(example))
    path = os.path.join(tempfile.mkdtemp(), "model.mxtpu")
    mx.deploy.export_predictor(net, example, path, poly_batch=True)
    print(f"exported batch-polymorphic artifact -> {path}")

    # ---- 2. load + serve ------------------------------------------
    pred = mx.deploy.load_predictor(path)
    srv = serving.ModelServer(pred, max_batch_size=args.max_batch,
                              max_delay_ms=args.max_delay_ms,
                              name="example")
    srv.start()

    # ---- 3. warmup, then a recompile-free load --------------------
    warm = srv.warmup()
    print("warmup compiled buckets:",
          {b: f"{s:.2f}s" for b, s in sorted(warm.items())})

    rng = np.random.RandomState(1)
    errors = []

    def client(tid):
        try:
            for i in range(args.requests):
                x = rng.randn(args.feature_dim).astype(np.float32)
                y = srv.predict(x, timeout=120)
                assert y.shape == (10,)
        except Exception as exc:        # surface, don't swallow
            errors.append(f"thread {tid}: {exc!r}")

    with serving.CompileCounter() as cc:
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(args.threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    # ---- 4. drain + report ----------------------------------------
    srv.shutdown()     # joins the worker: stats below are final
    stats = srv.stats()
    if errors:
        print("\n".join(errors))
        sys.exit(1)
    if cc.count != 0:
        print(f"FAIL: {cc.count} XLA recompiles during load")
        sys.exit(1)
    total = args.threads * args.requests
    print(f"served {stats['requests_completed']}/{total} requests, "
          f"0 recompiles")
    print(f"throughput {stats['throughput_rps']:.0f} req/s | "
          f"p50 {stats['latency_ms']['p50']:.2f} ms, "
          f"p99 {stats['latency_ms']['p99']:.2f} ms | "
          f"avg batch {stats['avg_batch_size']:.1f}, "
          f"padded waste {stats['padded_waste']:.0%}")
    assert stats["requests_completed"] == total


if __name__ == "__main__":
    main()
