#!/usr/bin/env python
"""LSTM + CTC OCR on synthetic digit captchas — the fifth north-star config.

Reference example: example/ctc/lstm_ocr_train.py (LSTM over CAPTCHA image
columns, WarpCTC loss, greedy decode). That example renders digits with
TTF fonts through a multiprocess generator; this one renders them from
embedded 7x5 glyph bitmaps (zero egress, deterministic) and keeps the
same learning problem: an image containing 3-4 digits at jittered
positions, read column-by-column by an LSTM, trained with CTC.

TPU-first notes: the whole dataset is a single device array and every
training step is one jitted program (fused lax.scan LSTM from ops/rnn.py
plus the log-domain CTC forward from ops/nn.py — CTC gradient comes from
JAX AD, no hand-written backward). Greedy decode is argmax + collapse,
done once per eval on host.

  python examples/lstm_ocr.py --epochs 20 --min-acc 0.9
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn, rnn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402

# 7x5 dot-matrix digit glyphs (classic layout), rendered into the image.
_GLYPHS = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11111", "00010", "00100", "00010", "00001", "10001", "01110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}
GLYPH_H, GLYPH_W = 7, 5
IMG_H = 12


def render_captcha(digits, width, rng):
    """Render a digit sequence into an (IMG_H, width) float image.

    Positions are laid out up front so glyphs never overlap — an
    overlapped glyph would make the image illegible while the label
    still claims the digit is there, poisoning CTC training.
    """
    k = len(digits)
    need = k * GLYPH_W + (k - 1)  # glyphs + 1px minimum gaps
    if need > width:
        raise ValueError(f"width {width} cannot fit {k} digits")
    slack = width - need
    cuts = np.sort(rng.integers(0, slack + 1, size=k + 1)) if slack else \
        np.zeros(k + 1, np.int64)
    img = rng.uniform(0.0, 0.15, size=(IMG_H, width)).astype(np.float32)
    x = int(cuts[0])
    for i, d in enumerate(digits):
        y = rng.integers(0, IMG_H - GLYPH_H + 1)
        g = np.array([[float(c) for c in row] for row in _GLYPHS[d]],
                     np.float32)
        img[y:y + GLYPH_H, x:x + GLYPH_W] += g * rng.uniform(0.7, 1.0)
        x += GLYPH_W + 1 + int(cuts[i + 1] - cuts[i])
    return np.clip(img, 0.0, 1.0)


def make_dataset(n, width, min_len, max_len, seed):
    rng = np.random.default_rng(seed)
    imgs = np.zeros((n, IMG_H, width), np.float32)
    max_l = max_len
    labels = np.full((n, max_l), 10, np.int32)  # pad = blank (= last class)
    lengths = np.zeros((n,), np.int32)
    for i in range(n):
        k = int(rng.integers(min_len, max_len + 1))
        digits = rng.integers(0, 10, size=k)
        imgs[i] = render_captcha(digits, width, rng)
        labels[i, :k] = digits
        lengths[i] = k
    return imgs, labels, lengths


class OCRNet(gluon.HybridBlock):
    """Columns of the image are the LSTM's time steps (reference:
    example/ctc/lstm.py builds the same unrolled-over-width topology).
    Bidirectional context makes CTC alignment much easier to learn —
    the emission column sees the whole glyph from both sides. Hybrid so
    the whole forward (and its vjp) is ONE compiled XLA program — the
    eager tape re-dispatching 4 × T scan steps per call is ~100x
    slower on CPU."""

    def __init__(self, num_hidden=64, num_classes=11, bidirectional=True,
                 **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.lstm = rnn.LSTM(num_hidden, num_layers=2, layout="NTC",
                                 bidirectional=bidirectional)
            self.out = nn.Dense(num_classes, flatten=False)

    def hybrid_forward(self, F, x):      # x: (B, H, W)
        seq = F.transpose(x, axes=(0, 2, 1))   # (B, T=W, C=H)
        return self.out(self.lstm(seq))        # (B, T, num_classes)


def greedy_decode(logits, blank=10):
    """argmax per step, collapse repeats, strip blanks. (B,T,C) -> lists."""
    ids = logits.argmax(axis=-1)
    out = []
    for row in ids:
        seq, prev = [], -1
        for t in row:
            if t != prev and t != blank:
                seq.append(int(t))
            prev = t
        out.append(seq)
    return out


def seq_accuracy(net, imgs, labels, lengths, batch):
    hits = 0
    for i in range(0, len(imgs), batch):
        logits = net(nd.array(imgs[i:i + batch])).asnumpy()
        for pred, lab, ln in zip(greedy_decode(logits),
                                 labels[i:i + batch],
                                 lengths[i:i + batch]):
            hits += pred == list(lab[:ln])
    return hits / len(imgs)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-samples", type=int, default=512)
    ap.add_argument("--width", type=int, default=40)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--min-acc", type=float, default=0.0,
                    help="exit nonzero unless eval seq-accuracy >= this")
    args = ap.parse_args()

    imgs, labels, lengths = make_dataset(
        args.num_samples, args.width, min_len=3, max_len=4, seed=7)
    n_eval = max(args.batch_size, args.num_samples // 8)
    ev_imgs, ev_labels, ev_lengths = make_dataset(
        n_eval, args.width, min_len=3, max_len=4, seed=99)

    mx.random.seed(0)
    net = OCRNet(num_hidden=args.hidden)
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    # blank is the last class (index 10), matching blank_label='last'.
    # hybridized: the CTC forward scan + its vjp compile once instead of
    # re-dispatching T scan steps eagerly every batch (~100x on CPU)
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    ctc.hybridize()

    B = args.batch_size
    n = (len(imgs) // B) * B
    for epoch in range(args.epochs):
        perm = np.random.default_rng(epoch).permutation(n)
        total, count = 0.0, 0
        for i in range(0, n, B):
            idx = perm[i:i + B]
            x = nd.array(imgs[idx])
            y = nd.array(labels[idx])
            ylen = nd.array(lengths[idx])
            with ag.record():
                logits = net(x)
                loss = ctc(logits, y, None, ylen).mean()
            loss.backward()
            trainer.step(B)
            total += float(loss.asnumpy())
            count += 1
        acc = seq_accuracy(net, ev_imgs, ev_labels, ev_lengths, B)
        print(f"epoch {epoch}: ctc-loss {total / count:.4f} "
              f"eval-seq-acc {acc:.3f}")

    if acc < args.min_acc:
        print(f"FAIL: seq-accuracy {acc:.3f} < required {args.min_acc}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
