"""Pipeline-parallel ResNet-50 training over 4 stages.

Demonstrates parallel.gluon_pipeline_stages + HeteroPipeline: a real
model (changing activation shapes, per-stage param pytrees) trained
under the differentiable GPipe schedule — each mesh rank holds exactly
one stage's weights; activations hop ranks over ICI via ppermute inside
one jitted scan.

Runs anywhere: on a machine without 4 accelerators, start with
    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
    JAX_PLATFORMS=cpu python examples/pipeline_parallel_resnet.py
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402
from mxnet_tpu import parallel  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--microbatch", type=int, default=2)
    ap.add_argument("--n-microbatches", type=int, default=4)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--classes", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.005)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 4:
        raise SystemExit(
            "need 4 devices; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=4 "
            "JAX_PLATFORMS=cpu for a virtual mesh")
    mesh = Mesh(np.asarray(devs[:4]).reshape(4), ("pp",))

    mx.random.seed(0)
    net = vision.resnet50_v1(classes=args.classes, thumbnail=True)
    net.initialize(init=mx.initializer.Xavier())
    s = args.image_size
    with ag.pause():
        net(mx.nd.NDArray(jnp.ones((1, 3, s, s), jnp.float32)))

    # stage boundaries: [stem+layer1 | layer2 | layer3 | layer4+head]
    fns, params, shapes = parallel.gluon_pipeline_stages(
        net, [2, 3, 4], (args.microbatch, 3, s, s))
    print("stage activation shapes:", shapes)
    pipe = parallel.hetero_pipeline(fns, params, shapes,
                                    args.microbatch,
                                    args.n_microbatches, mesh)
    packed = jax.device_put(pipe.packed, NamedSharding(mesh, P("pp")))
    print(f"packed per-rank params: {pipe.packed.shape} "
          f"({pipe.packed.nbytes / 1e6:.1f} MB total, each rank holds "
          f"1/{mesh.shape['pp']})")

    def loss_fn(logits, lab):
        lp = jax.nn.log_softmax(logits, -1)
        return -jnp.take_along_axis(lp, lab[:, None], 1).mean()

    step = jax.jit(pipe.value_and_grad(loss_fn))
    rng = np.random.RandomState(0)
    xs = jnp.asarray(rng.randn(args.n_microbatches, args.microbatch,
                               3, s, s), jnp.float32)
    ys = jnp.asarray(rng.randint(0, args.classes,
                                 (args.n_microbatches, args.microbatch)),
                     jnp.int32)
    for i in range(args.steps):
        loss, grads = step(packed, xs, ys)
        packed = packed - args.lr * grads
        print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
