#!/usr/bin/env python
"""Variational autoencoder on synthetic digit bitmaps.

Reference example: example/autoencoder + the VAE notebook under
example/ (encoder -> (mu, log_var) -> reparameterized z -> decoder,
loss = reconstruction + KL). The MNIST download is replaced by the
embedded 7x5 digit glyphs (zero egress); the learning task is the same:
compress images through a low-dimensional stochastic bottleneck.

TPU-first notes: the reparameterization draw uses mx.nd.random inside
``autograd.record`` — the sampler is a registered RNG op, so the whole
ELBO step (encoder, sample, decoder, both loss terms) records as one
graph and the gradient flows through mu/sigma by the standard
z = mu + sigma*eps trick.

  python examples/vae_mnist.py --epochs 10
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402

from multi_task import make_digits  # noqa: E402  (shared renderer)


class VAE(gluon.HybridBlock):
    def __init__(self, n_latent=8, hidden=128, out_dim=144, **kw):
        super().__init__(**kw)
        self._n_latent = n_latent
        with self.name_scope():
            self.enc = nn.HybridSequential()
            self.enc.add(nn.Dense(hidden, activation="relu"),
                         nn.Dense(2 * n_latent))
            self.dec = nn.HybridSequential()
            self.dec.add(nn.Dense(hidden, activation="relu"),
                         nn.Dense(out_dim, activation="sigmoid"))

    def hybrid_forward(self, F, x):
        h = self.enc(x)
        mu = F.slice_axis(h, axis=-1, begin=0, end=self._n_latent)
        log_var = F.slice_axis(h, axis=-1, begin=self._n_latent, end=None)
        sigma = F.exp(0.5 * log_var)
        eps = F.random.normal(shape=(x.shape[0], self._n_latent))
        z = mu + sigma * eps
        y = self.dec(z)
        return y, mu, log_var


def elbo_loss(y, x, mu, log_var):
    # bernoulli reconstruction + analytic KL(q||N(0,1))
    rec = -nd.sum(x * nd.log(y + 1e-7)
                  + (1 - x) * nd.log(1 - y + 1e-7), axis=1)
    kl = -0.5 * nd.sum(1 + log_var - mu * mu - nd.exp(log_var), axis=1)
    return (rec + kl).mean()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-samples", type=int, default=1024)
    ap.add_argument("--n-latent", type=int, default=8)
    ap.add_argument("--max-loss", type=float, default=float("inf"),
                    help="exit nonzero unless final ELBO <= this")
    args = ap.parse_args()

    imgs, _ = make_digits(args.num_samples, seed=21)
    flat = imgs.reshape(len(imgs), -1)          # (N, 144)

    mx.random.seed(0)
    net = VAE(n_latent=args.n_latent, out_dim=flat.shape[1])
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})

    B = args.batch_size
    n = (len(flat) // B) * B
    for epoch in range(args.epochs):
        perm = np.random.default_rng(epoch).permutation(n)
        total, count = 0.0, 0
        for i in range(0, n, B):
            x = nd.array(flat[perm[i:i + B]])
            with ag.record():
                y, mu, log_var = net(x)
                loss = elbo_loss(y, x, mu, log_var)
            loss.backward()
            trainer.step(B)
            total += float(loss.asnumpy())
            count += 1
        elbo = total / count
        print(f"epoch {epoch}: neg-ELBO {elbo:.2f}")

    if elbo > args.max_loss:
        print(f"FAIL: neg-ELBO {elbo:.2f} > {args.max_loss}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
