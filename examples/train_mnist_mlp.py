#!/usr/bin/env python
"""Train an MLP classifier with the Module API.

Reference example: example/image-classification/train_mnist.py. This
environment has no network egress, so data is a synthetic MNIST-shaped
problem (random images, learnable structure via a fixed teacher); swap
`synthetic_mnist` for mx.io.NDArrayIter over real MNIST arrays to train
the real thing — the Module flow is identical.

  python examples/train_mnist_mlp.py [--epochs 3] [--batch-size 64]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402


def synthetic_mnist(n=2048, seed=0):
    """Random 28x28 images whose label is decided by a FIXED random
    teacher projection — the same teacher for every split, so train and
    validation measure the same learnable rule."""
    teacher = np.random.RandomState(42).randn(784, 10).astype(np.float32)
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 784).astype(np.float32)
    y = (x @ teacher).argmax(axis=1).astype(np.float32)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=64)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc3", num_hidden=10)
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    x, y = synthetic_mnist()
    xv, yv = synthetic_mnist(512, seed=1)
    train = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(xv, yv, args.batch_size)

    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, num_epoch=args.epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.initializer.Xavier(),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 20))
    score = mod.score(val, mx.metric.Accuracy())
    print("validation:", dict(score) if not isinstance(score, dict)
          else score)


if __name__ == "__main__":
    main()
