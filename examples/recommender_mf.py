"""Matrix-factorization recommender with sparse embedding gradients.

The reference ships MF/recommender examples (example/recommenders/)
built on sparse row_sparse embeddings + lazy optimizer updates; this is
the TPU-build counterpart: two Embedding tables trained on synthetic
ratings with a planted low-rank structure. Per step only the touched
rows carry gradient — the sparse Embedding grad + lazy SGD path
(mxnet_tpu/ndarray/sparse.py) keeps updates O(batch) instead of
O(vocab).

  JAX_PLATFORMS=cpu python examples/recommender_mf.py --steps 60
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


class MFNet(gluon.HybridBlock):
    def __init__(self, n_users, n_items, rank):
        super().__init__()
        with self.name_scope():
            self.user = nn.Embedding(n_users, rank, prefix="user_")
            self.item = nn.Embedding(n_items, rank, prefix="item_")

    def forward(self, users, items):
        u = self.user(users)
        v = self.item(items)
        return (u * v).sum(axis=-1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--users", type=int, default=200)
    ap.add_argument("--items", type=int, default=150)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.2)
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    # planted low-rank ratings
    U = rng.randn(args.users, args.rank) * 0.5
    V = rng.randn(args.items, args.rank) * 0.5

    mx.random.seed(0)
    net = MFNet(args.users, args.items, args.rank)
    net.initialize(init=mx.initializer.Normal(0.1))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.L2Loss()

    for step in range(args.steps):
        users = rng.randint(0, args.users, args.batch_size)
        items = rng.randint(0, args.items, args.batch_size)
        ratings = (U[users] * V[items]).sum(-1)
        x_u = nd.array(users.astype(np.float32))
        x_i = nd.array(items.astype(np.float32))
        y = nd.array(ratings.astype(np.float32))
        with ag.record():
            pred = net(x_u, x_i)
            loss = loss_fn(pred, y).mean()
        loss.backward()
        trainer.step(args.batch_size)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: mse {2 * float(loss.asnumpy()):.4f}")

    # held-out check
    users = rng.randint(0, args.users, 512)
    items = rng.randint(0, args.items, 512)
    truth = (U[users] * V[items]).sum(-1)
    with ag.pause():
        pred = net(nd.array(users.astype(np.float32)),
                   nd.array(items.astype(np.float32))).asnumpy()
    corr = np.corrcoef(pred, truth)[0, 1]
    print(f"held-out correlation with planted ratings: {corr:.3f}")


if __name__ == "__main__":
    main()
