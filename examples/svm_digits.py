#!/usr/bin/env python
"""Multiclass SVM on digit bitmaps — the SVMOutput head.

Reference example: example/svm_mnist/svm_mnist.py (an MLP whose output
layer is ``SVMOutput`` — hinge loss with margin instead of softmax
cross-entropy — trained with Module). Same structure on the synthetic
digit bitmaps; exercises the symbolic SVMOutput op end to end, both
L1 and squared (L2) hinge variants.

  python examples/svm_digits.py --epochs 8 --min-acc 0.8
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402

from multi_task import make_digits  # noqa: E402


def build_sym(use_linear):
    data = mx.sym.var("data")
    net = mx.sym.Flatten(data)
    net = mx.sym.FullyConnected(net, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    label = mx.sym.var("svm_label")
    # regularization_coefficient scales the hinge subgradient itself
    # (reference: src/operator/svm_output-inl.h) — 1.0 like the
    # reference example, NOT a small weight-decay-style value
    return mx.sym.SVMOutput(net, label, margin=1.0,
                            regularization_coefficient=1.0,
                            use_linear=use_linear, name="svm")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-samples", type=int, default=1024)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--hinge", choices=["l1", "l2"], default="l2")
    ap.add_argument("--min-acc", type=float, default=0.0)
    args = ap.parse_args()

    imgs, labels = make_digits(args.num_samples, seed=17)
    ev_imgs, ev_labels = make_digits(256, seed=171)

    sym = build_sym(use_linear=args.hinge == "l1")
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("svm_label",))
    B = args.batch_size
    mod.bind(data_shapes=[("data", (B, 1, 12, 12))],
             label_shapes=[("svm_label", (B,))])
    mx.random.seed(0)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": args.lr,
                                         "momentum": 0.9})

    metric = mx.metric.Accuracy()
    n = (len(imgs) // B) * B
    if n == 0 or (len(ev_imgs) // B) * B == 0:
        ap.error(f"--batch-size {B} exceeds the train or eval set size")
    acc = 0.0
    for epoch in range(args.epochs):
        # permute the FULL set then truncate, so the dropped tail
        # rotates across epochs instead of excluding fixed samples
        perm = np.random.default_rng(epoch).permutation(len(imgs))[:n]
        for i in range(0, n, B):
            idx = perm[i:i + B]
            batch = mx.io.DataBatch(
                data=[mx.nd.array(imgs[idx])],
                label=[mx.nd.array(labels[idx].astype(np.float32))])
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        metric.reset()
        for i in range(0, (len(ev_imgs) // B) * B, B):
            batch = mx.io.DataBatch(
                data=[mx.nd.array(ev_imgs[i:i + B])],
                label=[mx.nd.array(
                    ev_labels[i:i + B].astype(np.float32))])
            mod.forward(batch, is_train=False)
            metric.update([mx.nd.array(ev_labels[i:i + B])],
                          mod.get_outputs())
        acc = metric.get()[1]
        print(f"epoch {epoch}: eval acc {acc:.3f} ({args.hinge} hinge)")

    if acc < args.min_acc:
        print(f"FAIL: accuracy {acc:.3f} < {args.min_acc}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
