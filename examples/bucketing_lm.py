#!/usr/bin/env python
"""Variable-length word-level LM with BucketingModule — PTB-style.

Reference example: example/rnn/bucketing/lstm_bucketing.py
(BucketSentenceIter + stacked LSTM symbol per bucket, shared params,
Perplexity with ignore_label). Same shape here, zero egress: sentences
come from an embedded corpus, each batch is assigned to the smallest
bucket that fits, and `BucketingModule` generates/bind-shares one
symbolic LSTM program per bucket length.

TPU-first notes: each bucket key is one static-shape jitted program
(bucketing exists precisely because XLA wants static shapes); params
are shared across buckets by the module, so switching buckets never
re-initializes. The LSTM is the fused lax.scan `sym.RNN` op.

  python examples/bucketing_lm.py --epochs 5
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402

CORPUS = """
the cat sat on the mat
a quick brown fox jumps over the lazy dog near the river bank
we hold these truths to be self evident
the rain in spain stays mainly in the plain
to be or not to be that is the question asked by the prince
all that glitters is not gold and all who wander are not lost
the early bird catches the worm but the second mouse gets the cheese
a journey of a thousand miles begins with a single step forward
ask not what your country can do for you
time flies like an arrow and fruit flies like a banana
the pen is mightier than the sword in the long run
actions speak louder than words ever could
practice makes perfect when patience guides the hand
knowledge speaks but wisdom listens to the quiet voice within
""".strip().splitlines() * 6

PAD = 0  # reserved id; SoftmaxOutput ignores it via use_ignore


def build_vocab(lines):
    words = sorted({w for ln in lines for w in ln.split()})
    return {w: i + 1 for i, w in enumerate(words)}  # 0 is PAD


def bucketize(lines, vocab, buckets, batch_size, seed):
    """Return {bucket: (data (N,T) int32, label (N,T) int32)} batches."""
    per_bucket = {b: [] for b in buckets}
    for ln in lines:
        ids = [vocab[w] for w in ln.split()]
        if len(ids) < 2:
            continue
        b = next((b for b in buckets if len(ids) <= b + 1), None)
        if b is None:
            ids = ids[:buckets[-1] + 1]
            b = buckets[-1]
        x = ids[:-1] + [PAD] * (b - len(ids) + 1)
        y = ids[1:] + [PAD] * (b - len(ids) + 1)
        per_bucket[b].append((x, y))
    rng = np.random.default_rng(seed)
    batches = []
    for b, rows in per_bucket.items():
        rng.shuffle(rows)
        for i in range(0, len(rows) - batch_size + 1, batch_size):
            chunk = rows[i:i + batch_size]
            data = np.array([r[0] for r in chunk], np.int32)
            label = np.array([r[1] for r in chunk], np.int32)
            batches.append((b, data, label))
    rng.shuffle(batches)
    return batches


def make_sym_gen(vocab_size, num_embed, num_hidden, num_layers):
    """Per-bucket symbol. The LSTM carry (`lstm_state`/`lstm_state_cell`)
    comes in as *data*, zeroed every batch — the reference bucketing
    example feeds init states the same way (init_states as input data),
    which keeps them out of the parameter set."""
    def sym_gen(seq_len):
        data = mx.sym.var("data")            # (B, T) int ids
        label = mx.sym.var("softmax_label")  # (B, T)
        emb = mx.sym.Embedding(data, input_dim=vocab_size,
                               output_dim=num_embed, name="embed")
        tnc = mx.sym.swapaxes(emb, 0, 1)     # fused RNN is TNC
        params = mx.sym.var("lstm_parameters")
        init_h = mx.sym.var("lstm_state")
        init_c = mx.sym.var("lstm_state_cell")
        out = mx.sym.RNN(tnc, params, init_h, init_c, state_size=num_hidden,
                         num_layers=num_layers, mode="lstm",
                         state_outputs=False, name="lstm")
        out = mx.sym.swapaxes(out, 0, 1)                 # (B, T, H)
        out = mx.sym.Reshape(out, shape=(-1, num_hidden))
        fc = mx.sym.FullyConnected(out, num_hidden=vocab_size, name="pred")
        flat_label = mx.sym.Reshape(label, shape=(-1,))
        sm = mx.sym.SoftmaxOutput(fc, flat_label, use_ignore=True,
                                  ignore_label=PAD, name="softmax")
        return sm, ("data", "lstm_state", "lstm_state_cell"), \
            ("softmax_label",)
    return sym_gen


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-layers", type=int, default=1)
    ap.add_argument("--buckets", type=str, default="6,9,12,16")
    args = ap.parse_args()

    buckets = sorted(int(b) for b in args.buckets.split(","))
    vocab = build_vocab(CORPUS)
    vocab_size = len(vocab) + 1
    B = args.batch_size

    mod = mx.mod.BucketingModule(
        make_sym_gen(vocab_size, args.num_embed, args.num_hidden,
                     args.num_layers),
        default_bucket_key=buckets[-1])
    state_shape = (args.num_layers, B, args.num_hidden)
    mod.bind(data_shapes=[("data", (B, buckets[-1])),
                          ("lstm_state", state_shape),
                          ("lstm_state_cell", state_shape)],
             label_shapes=[("softmax_label", (B, buckets[-1]))])
    mx.random.seed(0)
    # the fused-RNN parameter vector is 1D, so route it to Uniform and
    # everything else to Xavier (reference uses init.FusedRNN / Mixed)
    mod.init_params(initializer=mx.initializer.Mixed(
        [".*lstm_parameters", ".*"],
        [mx.initializer.Uniform(0.08), mx.initializer.Xavier()]))
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 3e-3})

    zero_state = mx.nd.zeros(state_shape)
    metric = mx.metric.Perplexity(ignore_label=PAD)
    for epoch in range(args.epochs):
        batches = bucketize(CORPUS, vocab, buckets, B, seed=epoch)
        metric.reset()
        for bkey, data, label in batches:
            batch = mx.io.DataBatch(
                data=[mx.nd.array(data), zero_state, zero_state],
                label=[mx.nd.array(label)],
                bucket_key=bkey,
                provide_data=[mx.io.DataDesc("data", (B, bkey)),
                              mx.io.DataDesc("lstm_state", state_shape),
                              mx.io.DataDesc("lstm_state_cell",
                                             state_shape)],
                provide_label=[mx.io.DataDesc("softmax_label", (B, bkey))])
            mod.forward(batch, is_train=True)
            out = mod.get_outputs()[0]
            flat = mx.nd.array(np.asarray(label).reshape(-1))
            metric.update([flat], [out])
            mod.backward()
            mod.update()
        name, ppl = metric.get()
        print(f"epoch {epoch}: buckets={sorted({b for b, _, _ in batches})} "
              f"{name} {ppl:.2f}")

    assert np.isfinite(ppl) and ppl < vocab_size, "LM did not learn"
    print("final perplexity:", round(float(ppl), 2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
