#!/usr/bin/env python
"""SSD-300 detection: train a few steps on synthetic boxes, then run
full inference (forward + decode + NMS).

Reference example: example/ssd/ (train.py + demo.py). Data is synthetic
(colored rectangles on noise with their boxes as labels), so the script
runs with zero egress; swap in a .rec dataset packed by
tools/im2rec.py + image.ImageDetIter for real training.

  python examples/ssd_detect.py --steps 10
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402
from mxnet_tpu.gluon.model_zoo.ssd import (  # noqa: E402
    ssd_300_vgg16_reduced, MultiBoxLoss)


def synthetic_batch(rng, n, size=300):
    """Images with one bright rectangle each; label rows
    [cls, x1, y1, x2, y2, difficult] normalized to [0, 1]."""
    x = rng.rand(n, 3, size, size).astype(np.float32) * 0.1
    labels = np.zeros((n, 1, 6), np.float32)
    for i in range(n):
        w, h = rng.randint(60, 150, 2)
        x1, y1 = rng.randint(0, size - w), rng.randint(0, size - h)
        cls = rng.randint(0, 2)
        x[i, cls, y1:y1 + h, x1:x1 + w] += 0.8
        labels[i, 0] = [cls, x1 / size, y1 / size, (x1 + w) / size,
                        (y1 + h) / size, 0]
    return x, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=2)
    args = ap.parse_args()

    mx.random.seed(0)
    net = ssd_300_vgg16_reduced(classes=2)
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01, "momentum": 0.9})
    loss_fn = MultiBoxLoss()

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        xb, yb = synthetic_batch(rng, args.batch_size)
        with ag.record():
            cls_preds, loc_preds, anchors = net(nd.array(xb))
            loss = loss_fn(cls_preds, loc_preds, nd.array(yb),
                           anchors).mean()
        loss.backward()
        trainer.step(args.batch_size)
        if step % 2 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss.asnumpy()):.4f}")

    # inference: top detections on a fresh image
    xb, yb = synthetic_batch(rng, 1)
    with ag.pause(train_mode=False):
        dets = net.detect(nd.array(xb), threshold=0.05).asnumpy()[0]
    kept = dets[dets[:, 0] >= 0][:5]
    print("top detections [cls, score, x1, y1, x2, y2]:")
    for row in kept:
        print("  ", np.round(row, 3))
    print("ground truth:", np.round(yb[0, 0], 3))


if __name__ == "__main__":
    main()
