#!/usr/bin/env python
"""Language model trained with noise-contrastive estimation.

Reference example: example/nce-loss (word LM whose softmax is replaced
by NCE — score the true next word against k noise samples, so the
update cost is O(k) instead of O(vocab)). The binary-logistic NCE
objective (Gutmann & Hyvarinen) with a unigram noise distribution;
evaluation computes true perplexity with the full softmax, so the gate
checks that the O(k) training objective actually learned the O(V)
distribution.

TPU-first notes: negative sample ids are drawn on host per batch and
enter the jitted step as data — the scoring gathers
(embedding rows of k+1 candidates) are O(B*(k+1)*H), MXU-friendly, and
no (B, V) logits matrix is ever materialized during training.

  python examples/nce_lm.py --epochs 10
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn, rnn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402

from bucketing_lm import CORPUS, build_vocab  # noqa: E402


class NCELM(gluon.Block):
    """LSTM encoder + tied output embedding scored against sampled
    candidates (train) or the full vocab (eval)."""

    def __init__(self, vocab, embed=48, hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.emb = nn.Embedding(vocab, embed)
            self.lstm = rnn.LSTM(hidden, num_layers=1, layout="NTC")
            self.proj = nn.Dense(embed, flatten=False)
            self.out_w = self.params.get("out_weight",
                                         shape=(vocab, embed))
            self.out_b = self.params.get("out_bias", shape=(vocab,))

    def encode(self, x):                      # (B, T) -> (B, T, E)
        return self.proj(self.lstm(self.emb(x)))

    def score_candidates(self, h, cand):
        """h: (B,T,E); cand: (B,T,K) ids -> (B,T,K) logits."""
        w = nd.Embedding(cand, self.out_w.data(),
                         input_dim=self.out_w.shape[0],
                         output_dim=self.out_w.shape[1])  # (B,T,K,E)
        b = nd.Embedding(cand, self.out_b.data().reshape((-1, 1)),
                         input_dim=self.out_b.shape[0], output_dim=1)
        return (w * h.expand_dims(2)).sum(axis=-1) + b.squeeze(-1)

    def full_logits(self, h):                 # eval only: (B,T,V)
        return nd.dot(h, self.out_w.data().T) + self.out_b.data()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=8)
    ap.add_argument("--num-negatives", type=int, default=8)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--max-ppl", type=float, default=float("inf"))
    args = ap.parse_args()

    vocab = build_vocab(CORPUS)
    V = len(vocab) + 1
    ids = np.array([vocab[w] for ln in CORPUS for w in ln.split()],
                   np.int32)
    T, B, K = args.seq_len, args.batch_size, args.num_negatives
    nseq = (len(ids) - 1) // T
    xs = ids[:nseq * T].reshape(nseq, T)
    ys = ids[1:nseq * T + 1].reshape(nseq, T)
    # unigram noise distribution
    counts = np.bincount(ids, minlength=V).astype(np.float64)
    q = counts / counts.sum()
    log_kq = np.log(np.maximum(K * q, 1e-12)).astype(np.float32)

    mx.random.seed(0)
    net = NCELM(V)
    net.initialize(init=mx.initializer.Xavier())
    # standard NCE trick: start the output bias at -log V so initial
    # scores are roughly normalized and sigma(s - log kq) is calibrated
    net.out_b.set_data(nd.full((V,), -float(np.log(V))))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    rng = np.random.default_rng(0)
    nb = (nseq // B) * B
    ppl = float("inf")
    for epoch in range(args.epochs):
        # full permutation then truncate: the partial-batch tail
        # rotates across epochs instead of excluding fixed sequences
        perm = rng.permutation(nseq)[:nb]
        total, count = 0.0, 0
        for i in range(0, nb, B):
            idx = perm[i:i + B]
            x = nd.array(xs[idx])
            y = ys[idx]                                   # (B,T)
            neg = rng.choice(V, size=(B, T, K), p=q)      # noise ids
            cand = np.concatenate([y[..., None], neg], -1)  # (B,T,1+K)
            lkq = log_kq[cand]                            # (B,T,1+K)
            with ag.record():
                h = net.encode(x)
                s = net.score_candidates(h, nd.array(cand))
                delta = s - nd.array(lkq)
                pos = delta[:, :, 0]
                negd = delta[:, :, 1:]
                # NCE: true sample classified as data, noise as noise;
                # softrelu == stable softplus, log(1+exp(x)) sans overflow
                loss = (nd.Activation(-pos, act_type="softrelu").sum()
                        + nd.Activation(negd,
                                        act_type="softrelu").sum()) \
                    / (B * T)
            loss.backward()
            # loss is already a per-token mean; step(1) keeps the
            # effective lr independent of --batch-size (Trainer
            # rescales grads by 1/batch_size)
            trainer.step(1)
            total += float(loss.asnumpy())
            count += 1

        # true perplexity with the full softmax (eval-only O(V))
        h = net.encode(nd.array(xs[:nb]))
        logits = net.full_logits(h).asnumpy()
        logp = logits - np.log(
            np.exp(logits - logits.max(-1, keepdims=True)).sum(
                -1, keepdims=True)) - logits.max(-1, keepdims=True)
        ppl = float(np.exp(-np.mean(
            np.take_along_axis(logp, ys[:nb][..., None], -1))))
        print(f"epoch {epoch}: nce-loss {total / count:.4f} "
              f"full-softmax ppl {ppl:.1f}")

    if ppl > args.max_ppl:
        print(f"FAIL: perplexity {ppl:.1f} > {args.max_ppl}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
