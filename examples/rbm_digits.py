#!/usr/bin/env python
"""Bernoulli RBM trained with contrastive divergence (CD-k).

Reference example: example/restricted-boltzmann-machine (binarized
MNIST RBM). A deliberately non-backprop workflow: no autograd, no
Trainer — parameter updates are the CD-k estimator
(<v h>_data - <v h>_model) computed from Gibbs samples, applied with
plain NDArray arithmetic. Exercises seeded samplers
(nd.random.uniform), matmuls, and in-place-style parameter updates
outside the tape.

The gate is mean-squared reconstruction error of held-out digits
through one Gibbs round-trip (v -> h sample -> v probabilities); for
these ~13%-on binary images a structure-blind reconstructor sits near
p(1-p)*2 ~ 0.2, so the 0.12 CI gate requires learned structure.

  python examples/rbm_digits.py --epochs 15
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402

from multi_task import make_digits  # noqa: E402


def bernoulli(p):
    return (nd.random.uniform(shape=p.shape) < p) * 1.0


class RBM:
    def __init__(self, n_vis, n_hid, seed=0):
        rng = np.random.RandomState(seed)
        self.W = nd.array(rng.randn(n_vis, n_hid).astype(np.float32)
                          * 0.05)
        self.vb = nd.zeros((n_vis,))
        self.hb = nd.zeros((n_hid,))

    def h_given_v(self, v):
        return nd.sigmoid(nd.dot(v, self.W) + self.hb)

    def v_given_h(self, h):
        return nd.sigmoid(nd.dot(h, self.W.T) + self.vb)

    def cd_step(self, v0, lr, k=1):
        """One CD-k update; returns reconstruction error."""
        ph0 = self.h_given_v(v0)
        h = bernoulli(ph0)
        for _ in range(k):
            pv = self.v_given_h(h)
            v = bernoulli(pv)
            ph = self.h_given_v(v)
            h = bernoulli(ph)
        B = v0.shape[0]
        pos = nd.dot(v0.T, ph0)
        neg = nd.dot(v.T, ph)
        self.W += lr * (pos - neg) / B
        self.vb += lr * (v0 - v).mean(axis=0)
        self.hb += lr * (ph0 - ph).mean(axis=0)
        return float(((v0 - pv) ** 2).mean().asnumpy())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-samples", type=int, default=1024)
    ap.add_argument("--n-hidden", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--cd-k", type=int, default=1)
    ap.add_argument("--max-recon-err", type=float, default=float("inf"))
    args = ap.parse_args()
    if args.cd_k < 1:
        ap.error("--cd-k must be >= 1")
    if args.num_samples < args.batch_size:
        ap.error(f"--num-samples {args.num_samples} must be >= "
                 f"--batch-size {args.batch_size}")

    imgs, _ = make_digits(args.num_samples, seed=29)
    data = (imgs.reshape(len(imgs), -1) > 0.5).astype(np.float32)
    ev = (make_digits(256, seed=291)[0].reshape(256, -1) > 0.5) * 1.0
    ev = ev.astype(np.float32)

    mx.random.seed(0)
    rbm = RBM(n_vis=data.shape[1], n_hid=args.n_hidden)

    B = args.batch_size
    n = (len(data) // B) * B
    err = float("inf")
    for epoch in range(args.epochs):
        perm = np.random.default_rng(epoch).permutation(len(data))[:n]
        errs = []
        for i in range(0, n, B):
            v0 = nd.array(data[perm[i:i + B]])
            errs.append(rbm.cd_step(v0, args.lr, args.cd_k))
        # held-out reconstruction through one Gibbs half-step
        v = nd.array(ev)
        recon = rbm.v_given_h(bernoulli(rbm.h_given_v(v)))
        err = float(((v - recon) ** 2).mean().asnumpy())
        print(f"epoch {epoch}: train-recon {np.mean(errs):.4f} "
              f"eval-recon {err:.4f}")

    if err > args.max_recon_err:
        print(f"FAIL: eval reconstruction error {err:.4f} > "
              f"{args.max_recon_err}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
