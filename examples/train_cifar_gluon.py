#!/usr/bin/env python
"""Train a model-zoo ResNet with Gluon, TPU-first knobs included.

Reference example: example/image-classification/train_cifar10.py. Data
is synthetic CIFAR-shaped (no egress); the flags show the TPU path:
--layout NHWC --dtype bfloat16 --stem-s2d run the same configuration
bench.py measures.

  python examples/train_cifar_gluon.py --steps 20 --layout NHWC
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--layout", default="NHWC", choices=["NCHW", "NHWC"])
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--stem-s2d", action="store_true",
                    help="MLPerf space-to-depth stem (NHWC only)")
    args = ap.parse_args()

    mx.random.seed(0)
    kwargs = {"layout": args.layout}
    if args.stem_s2d:
        kwargs["stem_s2d"] = True
    net = vision.get_model(args.model, classes=10, **kwargs) \
        if hasattr(vision, "get_model") else \
        getattr(vision, args.model)(classes=10, **kwargs)
    net.initialize(init=mx.initializer.Xavier())
    if args.dtype != "float32":
        net.cast(args.dtype)
    net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    rng = np.random.RandomState(0)
    shape = (args.batch_size, 3, 32, 32) if args.layout == "NCHW" \
        else (args.batch_size, 32, 32, 3)
    x = nd.array(rng.randn(*shape).astype(args.dtype))
    y = nd.array((np.arange(args.batch_size) % 10).astype(np.float32))

    for step in range(args.steps):
        with ag.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(args.batch_size)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: loss {float(loss.asnumpy()):.4f}")


if __name__ == "__main__":
    main()
