"""DCGAN on synthetic data: adversarial training with Deconvolution.

Reference analogue: example/gluon/dcgan (generator of ConvTranspose +
BN + ReLU stacks vs a conv discriminator, alternating updates). The
"dataset" is procedurally generated blobs so the demo runs with zero
egress; success criterion is the adversarial dynamic itself — both
losses stay finite and the discriminator cannot collapse to 100%
accuracy on generator samples.

  JAX_PLATFORMS=cpu python examples/dcgan.py --steps 20
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402


def make_generator(ngf=16, nz=16):
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # z (B, nz, 1, 1) -> (B, 1, 16, 16)
        net.add(nn.Conv2DTranspose(ngf * 2, 4, 1, 0, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.Conv2DTranspose(1, 4, 2, 1, use_bias=False),
                nn.Activation("tanh"))
    return net


def make_discriminator(ndf=16):
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, 2, 1, use_bias=False),
                nn.LeakyReLU(0.2),
                nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False),
                nn.BatchNorm(), nn.LeakyReLU(0.2),
                nn.Conv2D(1, 4, 1, 0, use_bias=False),
                nn.Flatten())
    return net


def real_batch(rng, batch):
    """Blobs: gaussian bumps at random positions — a simple, learnable
    'image' distribution in [-1, 1]."""
    yy, xx = np.mgrid[0:16, 0:16]
    imgs = []
    for _ in range(batch):
        cy, cx = rng.uniform(4, 12, 2)
        s = rng.uniform(1.5, 3.0)
        g = np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * s * s))
        imgs.append(2.0 * g - 1.0)
    return np.asarray(imgs, np.float32)[:, None]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--nz", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    gen, disc = make_generator(nz=args.nz), make_discriminator()
    gen.initialize(init=mx.initializer.Normal(0.02))
    disc.initialize(init=mx.initializer.Normal(0.02))
    gt = gluon.Trainer(gen.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})
    dt = gluon.Trainer(disc.collect_params(), "adam",
                       {"learning_rate": args.lr, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    ones = nd.array(np.ones((args.batch_size,), np.float32))
    zeros = nd.array(np.zeros((args.batch_size,), np.float32))
    for step in range(args.steps):
        z = nd.array(rng.randn(args.batch_size, args.nz, 1, 1)
                     .astype(np.float32))
        real = nd.array(real_batch(rng, args.batch_size))
        # --- discriminator step
        with ag.record():
            with ag.pause():
                fake = gen(z)
            d_loss = (loss_fn(disc(real), ones).mean()
                      + loss_fn(disc(fake), zeros).mean())
        d_loss.backward()
        dt.step(args.batch_size)
        # --- generator step
        with ag.record():
            g_loss = loss_fn(disc(gen(z)), ones).mean()
        g_loss.backward()
        gt.step(args.batch_size)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: d_loss {float(d_loss.asnumpy()):.3f} "
                  f"g_loss {float(g_loss.asnumpy()):.3f}")
    assert np.isfinite(float(d_loss.asnumpy()))
    assert np.isfinite(float(g_loss.asnumpy()))
    print("adversarial loop stable")


if __name__ == "__main__":
    main()
