#!/usr/bin/env python
"""Character-level LSTM language model with gluon.rnn.

Reference example: example/gluon/char_lstm via example/rnn. Trains on
an embedded corpus (no egress); the fused lax.scan LSTM (ops/rnn.py)
is the compute path.

  python examples/char_lstm.py --epochs 3
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn, rnn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
) * 12


class CharLM(gluon.HybridBlock):
    """Hybrid so the scan-based LSTM compiles once per shape instead of
    re-dispatching T steps eagerly every batch (see lstm_ocr.py)."""

    def __init__(self, vocab, hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.emb = nn.Embedding(vocab, 32)
            self.lstm = rnn.LSTM(hidden, num_layers=1, layout="NTC")
            self.out = nn.Dense(vocab, flatten=False)

    def hybrid_forward(self, F, x):
        return self.out(self.lstm(self.emb(x)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=8)
    args = ap.parse_args()

    chars = sorted(set(CORPUS))
    c2i = {c: i for i, c in enumerate(chars)}
    ids = np.array([c2i[c] for c in CORPUS], np.int32)

    T, B = args.seq_len, args.batch_size
    n = (len(ids) - 1) // T
    xs = ids[:n * T].reshape(n, T)
    ys = ids[1:n * T + 1].reshape(n, T)

    mx.random.seed(0)
    net = CharLM(len(chars))
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        total, count = 0.0, 0
        for i in range(0, n - B + 1, B):
            x = nd.array(xs[i:i + B])
            y = nd.array(ys[i:i + B])
            with ag.record():
                logits = net(x)
                loss = loss_fn(logits.reshape((-1, len(chars))),
                               y.reshape((-1,))).mean()
            loss.backward()
            trainer.step(B)
            total += float(loss.asnumpy())
            count += 1
        ppl = float(np.exp(total / max(count, 1)))
        print(f"epoch {epoch}: perplexity {ppl:.2f}")


if __name__ == "__main__":
    main()
