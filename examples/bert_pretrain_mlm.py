"""Masked-LM pretraining loop on BERT (synthetic corpus, no egress).

Exercises the language-model path end to end: BERTModel (embeddings +
flash-attention encoder + pooler) with a tied-embedding MLM head,
gluon Trainer, optional bf16 AMP, optional dp sharding via
ShardedTrainer-style mesh. The synthetic "language" has learnable
bigram structure, so MLM loss dropping well below uniform (-log 1/V)
demonstrates real learning, not memorized noise.

Reference analogue: the reference ships BERT under its model zoo /
gluon-nlp examples (SURVEY.md L7); this is the TPU-build counterpart.

  JAX_PLATFORMS=cpu python examples/bert_pretrain_mlm.py --steps 30
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.gluon.model_zoo.bert import BERTModel  # noqa: E402

MASK = 1  # token id reserved for [MASK]


class BertForMLM(gluon.HybridBlock):
    """BERT + tied-embedding masked-LM head (decoder weight = word
    embedding, the standard BERT tying)."""

    def __init__(self, vocab, units=64, hidden=128, layers=2, heads=4):
        super().__init__()
        with self.name_scope():
            self.bert = BERTModel(vocab_size=vocab, units=units,
                                  hidden_size=hidden, num_layers=layers,
                                  num_heads=heads, max_length=64,
                                  dropout=0.0)
            self.transform = nn.Dense(units, activation="relu",
                                      flatten=False)
            self.ln = nn.LayerNorm()

    def forward(self, tokens):
        seq, _ = self.bert(tokens)
        h = self.ln(self.transform(seq))
        # tied decoder: logits = h @ word_embedding^T
        w = self.bert.word_embed.weight.data()
        return nd.dot(h.reshape((-1, h.shape[-1])), w,
                      transpose_b=True).reshape(
                          (h.shape[0], h.shape[1], -1))


def make_batch(rng, batch, seqlen, vocab, trans):
    """Bigram-chain sentences + 15% masking."""
    toks = np.zeros((batch, seqlen), np.int32)
    toks[:, 0] = rng.randint(2, vocab, batch)
    for t in range(1, seqlen):
        toks[:, t] = trans[toks[:, t - 1]]
    masked = toks.copy()
    mask_pos = rng.rand(batch, seqlen) < 0.15
    mask_pos[:, 0] = False
    masked[mask_pos] = MASK
    return masked, toks, mask_pos


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=24)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    args = ap.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    # deterministic bigram successor table = the structure to learn
    trans = rng.randint(2, args.vocab, args.vocab)

    net = BertForMLM(args.vocab)
    net.initialize(init=mx.initializer.Xavier())
    if args.dtype == "bfloat16":
        from mxnet_tpu import amp
        amp.init()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    uniform = float(np.log(args.vocab))
    print(f"uniform-guess MLM loss: {uniform:.3f}")
    for step in range(args.steps):
        masked, target, pos = make_batch(rng, args.batch_size,
                                         args.seq_len, args.vocab, trans)
        x = nd.array(masked.astype(np.float32))
        y = nd.array(target.astype(np.float32))
        w = nd.array(pos.astype(np.float32))
        with ag.record():
            logits = net(x)
            per_tok = loss_fn(logits.reshape((-1, args.vocab)),
                              y.reshape((-1,)))
            # loss only on masked positions
            wf = w.reshape((-1,))
            loss = (per_tok * wf).sum() / (wf.sum() + 1e-6)
        loss.backward()
        trainer.step(args.batch_size)
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}: masked-LM loss {float(loss.asnumpy()):.4f}")
    final = float(loss.asnumpy())
    if final < 0.7 * uniform:
        print(f"learned bigram structure (loss {final:.3f} << uniform "
              f"{uniform:.3f})")


if __name__ == "__main__":
    main()
