#!/usr/bin/env python
"""Bayesian regression with SGLD posterior sampling.

Reference example: example/bayesian-methods (sgld.ipynb — stochastic
gradient Langevin dynamics: SGD whose injected Gaussian noise makes the
iterates samples from the posterior). A tiny MLP regresses a noisy
sinusoid; after burn-in, parameter snapshots along the SGLD trajectory
form a posterior ensemble whose predictive spread widens off the data
support — the classic picture epistemic-uncertainty methods are judged
by.

Gates: (1) ensemble-mean RMSE on held-out in-support points beats a
threshold; (2) with --check-uncertainty, predictive std is strictly
larger outside the data support than inside it. The two pull against
each other through the step size: smaller --lr gives a crisper
uncertainty contrast (verified: 0.22 in- vs 0.47 off-support at
--lr 1e-4 --epochs 60), larger --lr mixes faster and fits tighter
(RMSE 0.51 at --lr 2e-4 --epochs 100).

  python examples/bayesian_sgld.py --epochs 60 --check-uncertainty
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402


def make_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="tanh"),
            nn.Dense(32, activation="tanh"),
            nn.Dense(1))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=60)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-samples", type=int, default=256)
    ap.add_argument("--lr", type=float, default=1e-4)
    ap.add_argument("--burn-in", type=int, default=30,
                    help="epochs before posterior snapshots start")
    ap.add_argument("--max-rmse", type=float, default=float("inf"))
    ap.add_argument("--check-uncertainty", action="store_true",
                    help="also gate on off-support std > in-support std")
    args = ap.parse_args()
    if args.burn_in >= args.epochs:
        ap.error("--burn-in must be < --epochs")
    if args.num_samples < args.batch_size:
        ap.error(f"--num-samples {args.num_samples} must be >= "
                 f"--batch-size {args.batch_size}")

    rng = np.random.default_rng(3)
    # data lives on [-2, 2]; we probe uncertainty at |x| in [3, 4]
    x = rng.uniform(-2, 2, size=(args.num_samples, 1)).astype(np.float32)
    y = (np.sin(2 * x) + 0.1 * rng.standard_normal(x.shape)
         ).astype(np.float32)
    xt = rng.uniform(-2, 2, size=(128, 1)).astype(np.float32)
    yt = np.sin(2 * xt).astype(np.float32)
    x_far = np.concatenate([rng.uniform(-4, -3, size=(64, 1)),
                            rng.uniform(3, 4, size=(64, 1))]
                           ).astype(np.float32)

    mx.random.seed(0)
    net = make_net()
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize()
    # SGLD: the update is -lr/2 * grad + N(0, lr) noise; the iterates
    # (post burn-in) are posterior samples under the implied prior
    trainer = gluon.Trainer(net.collect_params(), "sgld",
                            {"learning_rate": args.lr, "wd": 1e-4})
    loss_fn = gluon.loss.L2Loss()

    B = args.batch_size
    n = (len(x) // B) * B
    snapshots = []
    for epoch in range(args.epochs):
        perm = np.random.default_rng(epoch).permutation(len(x))[:n]
        total = 0.0
        for i in range(0, n, B):
            idx = perm[i:i + B]
            with ag.record():
                # SGLD samples the posterior of the DATASET-sum loss:
                # scale the minibatch mean by N so the drift term is the
                # standard (N/B)·Σ_minibatch ∇ℓ = N·∇mean unbiased
                # estimator of the full-data gradient sum. step(1) keeps
                # rescale_grad at 1 — a step(B) here would divide the
                # likelihood term by B, sampling a 32x-hotter posterior
                # whose ensemble mean wanders off the data
                loss = loss_fn(net(nd.array(x[idx])),
                               nd.array(y[idx])).mean() * len(x)
            loss.backward()
            trainer.step(1)
            total += float(loss.asnumpy()) / len(x)
        if epoch >= args.burn_in:
            snapshots.append([p.data().asnumpy().copy()
                              for p in net.collect_params().values()])
        if (epoch + 1) % 10 == 0:
            print(f"epoch {epoch + 1}: loss {total / (n // B):.4f} "
                  f"({len(snapshots)} posterior samples)")

    def predict_with(params, xs):
        for p, arr in zip(net.collect_params().values(), params):
            p.set_data(nd.array(arr))
        return net(nd.array(xs)).asnumpy()

    preds_in = np.stack([predict_with(s, xt) for s in snapshots])
    preds_far = np.stack([predict_with(s, x_far) for s in snapshots])
    rmse = float(np.sqrt(((preds_in.mean(0) - yt) ** 2).mean()))
    std_in = float(preds_in.std(0).mean())
    std_far = float(preds_far.std(0).mean())
    print(f"posterior ensemble ({len(snapshots)} samples): "
          f"in-support RMSE {rmse:.3f}, predictive std "
          f"in-support {std_in:.3f} vs off-support {std_far:.3f}")

    if rmse > args.max_rmse:
        print(f"FAIL: RMSE {rmse:.3f} > {args.max_rmse}")
        return 1
    if args.check_uncertainty and not std_far > std_in:
        print("FAIL: no epistemic-uncertainty growth off-support")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
