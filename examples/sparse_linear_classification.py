#!/usr/bin/env python
"""Sparse logistic regression over libsvm data with row_sparse weights.

Reference example: example/sparse/linear_classification/ (LibSVMIter +
sparse dot). Same shape here: features arrive as CSR batches from
``mx.io.LibSVMIter`` and ``sparse.dot(csr, dense)`` is the compute;
the weight itself is a small dense vector updated with plain SGD (the
row_sparse lazy-update path is exercised separately by the gluon
Trainer sparse tests, tests/test_sparse.py).

TPU-first notes: XLA has no sparse buffers, so `sparse.dot` lowers to
gather + segment-sum on the CSR coordinates — one FLOP per stored
nonzero, still one jitted program per batch shape.

  python examples/sparse_linear_classification.py --epochs 5
"""
import argparse
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402


def write_synthetic_libsvm(path, n, num_features, nnz, seed):
    """Linearly-separable sparse data in libsvm text format."""
    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=num_features)
    with open(path, "w") as f:
        for _ in range(n):
            idx = np.sort(rng.choice(num_features, size=nnz,
                                     replace=False))
            val = rng.normal(size=nnz)
            y = int(val @ true_w[idx] > 0)
            feats = " ".join(f"{i}:{v:.4f}" for i, v in zip(idx, val))
            f.write(f"{y} {feats}\n")
    return true_w


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-samples", type=int, default=2048)
    ap.add_argument("--num-features", type=int, default=1000)
    ap.add_argument("--nnz", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--min-acc", type=float, default=0.0)
    args = ap.parse_args()

    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "train.libsvm")
    write_synthetic_libsvm(path, args.num_samples, args.num_features,
                           args.nnz, seed=3)

    it = mx.io.LibSVMIter(data_libsvm=path,
                          data_shape=(args.num_features,),
                          batch_size=args.batch_size)

    mx.random.seed(0)
    weight = nd.zeros((args.num_features, 1))
    weight.attach_grad()
    bias = nd.zeros((1,))
    bias.attach_grad()
    loss_fn = gluon.loss.LogisticLoss(label_format="binary")

    for epoch in range(args.epochs):
        it.reset()
        total, count, correct, seen = 0.0, 0, 0, 0
        for batch in it:
            x = batch.data[0]          # CSRNDArray
            y = batch.label[0]
            with ag.record():
                logits = nd.sparse.dot(x, weight) + bias
                loss = loss_fn(logits.reshape((-1,)), y).mean()
            loss.backward()
            # plain SGD on the touched rows (grad of a csr-dot is dense
            # here; the row_sparse path is exercised in gluon Trainer)
            weight -= args.lr * weight.grad
            bias -= args.lr * bias.grad
            weight.grad[:] = 0
            bias.grad[:] = 0
            total += float(loss.asnumpy())
            count += 1
            pred = (logits.asnumpy().reshape(-1) > 0).astype(np.int64)
            correct += int((pred == y.asnumpy().astype(np.int64)).sum())
            seen += len(pred)
        acc = correct / seen
        print(f"epoch {epoch}: logistic-loss {total / count:.4f} "
              f"train-acc {acc:.3f}")

    if acc < args.min_acc:
        print(f"FAIL: accuracy {acc:.3f} < {args.min_acc}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
