#!/usr/bin/env python
"""Stochastic-depth residual net: drop whole residual branches while
training, keep them all (rescaled) at inference.

Reference example: example/stochastic-depth (Huang et al. 2016 on a
CIFAR ResNet). Each residual block's branch is gated by a Bernoulli
survival draw during training — a linearly-decaying survival schedule
from input to output — and scaled by its survival probability at eval.

TPU-first notes: the gate multiplies the branch output by a per-batch
scalar sample instead of branching with Python `if` — data-dependent
control flow would force retraces, a multiply keeps one static XLA
graph for every survival outcome.

  python examples/stochastic_depth.py --epochs 6
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402

from multi_task import make_digits  # noqa: E402


class StochasticBlock(gluon.Block):
    """Residual block whose branch survives with probability p_l."""

    def __init__(self, channels, survival_p, **kw):
        super().__init__(**kw)
        self.survival_p = survival_p
        with self.name_scope():
            self.body = nn.Sequential()
            self.body.add(nn.Conv2D(channels, 3, padding=1),
                          nn.BatchNorm(),
                          nn.Activation("relu"),
                          nn.Conv2D(channels, 3, padding=1),
                          nn.BatchNorm())

    def forward(self, x):
        branch = self.body(x)
        if ag.is_training():
            gate = float(np.random.random() < self.survival_p)
            return nd.relu(x + gate * branch)
        # eval: expected value of the gated branch
        return nd.relu(x + self.survival_p * branch)


class StochasticDepthNet(gluon.Block):
    def __init__(self, num_blocks=6, channels=16, classes=10,
                 final_survival=0.5, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.stem = nn.Conv2D(channels, 3, padding=1)
            self.blocks = nn.Sequential()
            for i in range(num_blocks):
                # linear decay: early blocks almost always survive,
                # deep blocks drop half the time (reference schedule)
                p = 1.0 - (i + 1) / num_blocks * (1.0 - final_survival)
                self.blocks.add(StochasticBlock(channels, p))
            self.head = nn.Sequential()
            self.head.add(nn.GlobalAvgPool2D(), nn.Flatten(),
                          nn.Dense(classes))

    def forward(self, x):
        return self.head(self.blocks(self.stem(x)))


def evaluate(net, imgs, labels, batch):
    metric = mx.metric.Accuracy()
    for i in range(0, len(imgs), batch):
        out = net(nd.array(imgs[i:i + batch]))
        metric.update([nd.array(labels[i:i + batch])], [out])
    return metric.get()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-samples", type=int, default=1024)
    ap.add_argument("--num-blocks", type=int, default=6)
    ap.add_argument("--min-acc", type=float, default=0.0)
    args = ap.parse_args()
    if args.num_samples < args.batch_size:
        ap.error("--num-samples must be >= --batch-size")

    imgs, labels = make_digits(args.num_samples, seed=41)
    ev_imgs, ev_labels = make_digits(256, seed=411)

    mx.random.seed(0)
    np.random.seed(0)
    net = StochasticDepthNet(num_blocks=args.num_blocks)
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    B = args.batch_size
    n = (len(imgs) // B) * B
    acc = 0.0
    for epoch in range(args.epochs):
        perm = np.random.default_rng(epoch).permutation(len(imgs))[:n]
        total = 0.0
        for i in range(0, n, B):
            idx = perm[i:i + B]
            with ag.record():
                loss = loss_fn(net(nd.array(imgs[idx])),
                               nd.array(labels[idx])).mean()
            loss.backward()
            trainer.step(B)
            total += float(loss.asnumpy())
        acc = evaluate(net, ev_imgs, ev_labels, B)
        print(f"epoch {epoch}: loss {total / (n // B):.4f} "
              f"eval-acc {acc:.3f}")

    if acc < args.min_acc:
        print(f"FAIL: accuracy {acc:.3f} < {args.min_acc}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
