#!/usr/bin/env python
"""FGSM adversarial examples — gradients with respect to the INPUT.

Reference example: example/adversary/adversary_generation.ipynb (train
a small net on MNIST, then perturb inputs along the sign of the input
gradient and watch accuracy collapse). Uses the synthetic digit
bitmaps; the interesting framework path is ``x.attach_grad()`` +
``loss.backward()`` producing d(loss)/d(input) — most training code
only ever pulls parameter gradients.

  python examples/adversary_fgsm.py --epochs 6
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402

from multi_task import make_digits  # noqa: E402


def build_net():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(16, 3, activation="relu"),
            nn.MaxPool2D(2),
            nn.Flatten(),
            nn.Dense(64, activation="relu"),
            nn.Dense(10))
    return net


def accuracy(net, imgs, labels, batch):
    hits = 0
    for i in range(0, len(imgs), batch):
        pred = net(nd.array(imgs[i:i + batch])).asnumpy().argmax(-1)
        hits += int((pred == labels[i:i + batch]).sum())
    return hits / len(imgs)


def fgsm_perturb(net, loss_fn, imgs, labels, eps, batch):
    """x_adv = clip(x + eps * sign(dL/dx))."""
    out = np.empty_like(imgs)
    for i in range(0, len(imgs), batch):
        x = nd.array(imgs[i:i + batch])
        x.attach_grad()
        with ag.record():
            loss = loss_fn(net(x), nd.array(labels[i:i + batch])).mean()
        loss.backward()
        step = np.sign(x.grad.asnumpy())
        out[i:i + batch] = np.clip(imgs[i:i + batch] + eps * step, 0, 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-samples", type=int, default=1024)
    ap.add_argument("--eps", type=float, default=0.15)
    ap.add_argument("--min-drop", type=float, default=0.0,
                    help="exit nonzero unless adversarial accuracy drops "
                    "at least this much below clean accuracy")
    args = ap.parse_args()

    imgs, labels = make_digits(args.num_samples, seed=13)
    ev_imgs, ev_labels = make_digits(256, seed=131)

    mx.random.seed(0)
    net = build_net()
    net.initialize(init=mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    B = args.batch_size
    n = (len(imgs) // B) * B
    for epoch in range(args.epochs):
        perm = np.random.default_rng(epoch).permutation(n)
        for i in range(0, n, B):
            idx = perm[i:i + B]
            with ag.record():
                loss = loss_fn(net(nd.array(imgs[idx])),
                               nd.array(labels[idx])).mean()
            loss.backward()
            trainer.step(B)
        print(f"epoch {epoch}: clean eval acc "
              f"{accuracy(net, ev_imgs, ev_labels, B):.3f}")

    clean = accuracy(net, ev_imgs, ev_labels, B)
    adv_imgs = fgsm_perturb(net, loss_fn, ev_imgs, ev_labels, args.eps, B)
    adv = accuracy(net, adv_imgs, ev_labels, B)
    print(f"clean acc {clean:.3f} -> adversarial acc {adv:.3f} "
          f"(eps={args.eps})")

    if clean - adv < args.min_drop:
        print(f"FAIL: accuracy drop {clean - adv:.3f} < {args.min_drop}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
