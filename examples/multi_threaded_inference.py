#!/usr/bin/env python
"""Serve one hybridized model from many Python threads concurrently.

Reference example: example/multi_threaded_inference (C++ threads over
CachedOpThreadSafe — src/imperative/cached_op_threadsafe.h). The
TPU-native CachedOp is thread-safe by construction (jit programs are
pure; first-trace warm-up is lock-serialized, see gluon/block.py), so
the Python threading story is the same: hybridize once, call from N
threads, and every thread's outputs must be bit-identical to a serial
run of the same inputs.

  python examples/multi_threaded_inference.py --threads 8
"""
import argparse
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import nd  # noqa: E402
from mxnet_tpu.gluon.model_zoo import vision  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--requests", type=int, default=4,
                    help="batches served per thread")
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--image-size", type=int, default=64)
    args = ap.parse_args()

    mx.random.seed(0)
    net = vision.get_model(args.model, classes=10)
    net.initialize(init=mx.initializer.Xavier())
    net.hybridize(static_alloc=True)

    rng = np.random.RandomState(0)
    shape = (args.batch_size, 3, args.image_size, args.image_size)
    batches = [rng.randn(*shape).astype(np.float32)
               for _ in range(args.threads * args.requests)]

    # warm-up + serial reference outputs
    serial = [net(nd.array(b)).asnumpy() for b in batches]

    results = [None] * len(batches)
    errors = []

    def worker(tid):
        try:
            for r in range(args.requests):
                i = tid * args.requests + r
                results[i] = net(nd.array(batches[i])).asnumpy()
        except Exception as exc:   # surface, don't swallow
            errors.append((tid, exc))

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(args.threads)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.time() - t0

    if errors:
        print(f"FAIL: {len(errors)} worker(s) raised: {errors[0]}")
        return 1
    for i, (got, want) in enumerate(zip(results, serial)):
        if not np.array_equal(got, want):
            print(f"FAIL: request {i} diverged from the serial run "
                  f"(max diff {np.abs(got - want).max()})")
            return 1

    n_img = len(batches) * args.batch_size
    print(f"{args.threads} threads x {args.requests} requests "
          f"({n_img} images) in {dt:.2f}s -> {n_img / dt:.1f} img/s; "
          "all outputs bit-identical to serial")
    return 0


if __name__ == "__main__":
    sys.exit(main())
