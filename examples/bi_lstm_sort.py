#!/usr/bin/env python
"""Sort a token sequence with a bidirectional LSTM (seq2seq-as-tagging).

Reference example: example/bi-lstm-sort (notebook): feed N random
tokens, train the net to emit them in sorted order — each output
position needs global context, which is exactly what the backward
direction of a BidirectionalCell provides (a unidirectional model
cannot know position t's sorted token without seeing the whole
sequence).

Uses the legacy ``mx.rnn`` cell API end to end: BidirectionalCell over
two LSTMCells, unrolled to one symbol graph, trained with Module.

  python examples/bi_lstm_sort.py --epochs 10 --min-acc 0.8
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import rnn  # noqa: E402


def make_data(n, seq_len, vocab, seed):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, vocab, size=(n, seq_len)).astype(np.int32)
    y = np.sort(x, axis=1)
    return x, y


def build_sym(seq_len, vocab, num_hidden, num_embed):
    data = mx.sym.var("data")                  # (B, T) ids
    label = mx.sym.var("softmax_label")        # (B, T) sorted ids
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_embed,
                           name="embed")
    bi = rnn.BidirectionalCell(rnn.LSTMCell(num_hidden, prefix="fw_"),
                               rnn.LSTMCell(num_hidden, prefix="bw_"))
    out, _ = bi.unroll(seq_len, emb, layout="NTC", merge_outputs=True)
    out = mx.sym.Reshape(out, shape=(-1, 2 * num_hidden))
    fc = mx.sym.FullyConnected(out, num_hidden=vocab, name="pred")
    flat = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(fc, flat, name="softmax"), \
        ("data",), ("softmax_label",)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=6)
    ap.add_argument("--vocab", type=int, default=20)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=16)
    ap.add_argument("--num-samples", type=int, default=2048)
    ap.add_argument("--min-acc", type=float, default=0.0,
                    help="exit nonzero unless eval token accuracy >= this")
    args = ap.parse_args()

    x, y = make_data(args.num_samples, args.seq_len, args.vocab, seed=11)
    ex, ey = make_data(max(args.batch_size, args.num_samples // 8),
                       args.seq_len, args.vocab, seed=97)

    sym, data_names, label_names = build_sym(
        args.seq_len, args.vocab, args.num_hidden, args.num_embed)
    mod = mx.mod.Module(sym, data_names=data_names,
                        label_names=label_names)
    B = args.batch_size
    mod.bind(data_shapes=[("data", (B, args.seq_len))],
             label_shapes=[("softmax_label", (B, args.seq_len))])
    mx.random.seed(0)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 3e-3})

    n = (len(x) // B) * B
    acc = 0.0
    for epoch in range(args.epochs):
        perm = np.random.default_rng(epoch).permutation(n)
        for i in range(0, n, B):
            idx = perm[i:i + B]
            batch = mx.io.DataBatch(
                data=[mx.nd.array(x[idx])], label=[mx.nd.array(y[idx])])
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        # eval token accuracy
        correct = total = 0
        for i in range(0, (len(ex) // B) * B, B):
            batch = mx.io.DataBatch(
                data=[mx.nd.array(ex[i:i + B])],
                label=[mx.nd.array(ey[i:i + B])])
            mod.forward(batch, is_train=False)
            pred = mod.get_outputs()[0].asnumpy().argmax(axis=-1)
            pred = pred.reshape(B, args.seq_len)
            correct += (pred == ey[i:i + B]).sum()
            total += pred.size
        acc = correct / total
        print(f"epoch {epoch}: eval token-acc {acc:.3f}")

    if acc < args.min_acc:
        print(f"FAIL: token-acc {acc:.3f} < {args.min_acc}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
