#!/usr/bin/env python
"""Deep Q-Network on a built-in CartPole — reinforcement learning.

Reference example: example/reinforcement-learning/dqn (replay memory,
target network, epsilon-greedy exploration). The gym dependency is
replaced by a 40-line numpy CartPole with the standard dynamics
(Barto-Sutton-Anderson '83 equations, the same ones gym implements),
so the example is hermetic.

TPU-first notes: the Q-network forward and the TD update are each one
jitted program (hybridized net + gluon Trainer); the replay buffer is a
preallocated numpy ring on host — RL's per-step env interaction is
inherently host-side, the device sees only fixed-shape minibatches.

  python examples/dqn_cartpole.py --episodes 60
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
import mxnet_tpu.autograd as ag  # noqa: E402


class CartPole:
    """Classic cart-pole balancing, episode ends on |x|>2.4, |th|>12deg
    or 200 steps. reward +1 per step survived."""

    GRAV, MCART, MPOLE, LEN, FORCE, TAU = 9.8, 1.0, 0.1, 0.5, 10.0, 0.02
    X_LIM, TH_LIM, MAX_STEPS = 2.4, 12 * np.pi / 180, 200

    def __init__(self, seed):
        self.rng = np.random.default_rng(seed)
        self.reset()

    def reset(self):
        self.s = self.rng.uniform(-0.05, 0.05, size=4).astype(np.float32)
        self.t = 0
        return self.s.copy()

    def step(self, action):
        x, xd, th, thd = self.s
        force = self.FORCE if action == 1 else -self.FORCE
        mtot = self.MCART + self.MPOLE
        pml = self.MPOLE * self.LEN
        cos, sin = np.cos(th), np.sin(th)
        tmp = (force + pml * thd ** 2 * sin) / mtot
        thacc = (self.GRAV * sin - cos * tmp) / \
            (self.LEN * (4.0 / 3.0 - self.MPOLE * cos ** 2 / mtot))
        xacc = tmp - pml * thacc * cos / mtot
        self.s = np.array([x + self.TAU * xd, xd + self.TAU * xacc,
                           th + self.TAU * thd, thd + self.TAU * thacc],
                          np.float32)
        self.t += 1
        done = (abs(self.s[0]) > self.X_LIM
                or abs(self.s[2]) > self.TH_LIM
                or self.t >= self.MAX_STEPS)
        return self.s.copy(), 1.0, done


class QNet(gluon.HybridBlock):
    def __init__(self, n_actions=2, hidden=64, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.h1 = nn.Dense(hidden, activation="relu")
            self.h2 = nn.Dense(hidden, activation="relu")
            self.out = nn.Dense(n_actions)

    def hybrid_forward(self, F, x):
        return self.out(self.h2(self.h1(x)))


def copy_params(src, dst):
    """Hard target-network update (reference dqn: copyto between the
    policy and target executors)."""
    sp, dp = src.collect_params(), dst.collect_params()
    for ks, kd in zip(sorted(sp.keys()), sorted(dp.keys())):
        dp[kd].set_data(sp[ks].data())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=60)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--buffer", type=int, default=10000)
    ap.add_argument("--gamma", type=float, default=0.99)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--target-sync", type=int, default=200)
    ap.add_argument("--min-mean-reward", type=float, default=0.0,
                    help="exit nonzero unless trailing-10 mean >= this")
    args = ap.parse_args()

    mx.random.seed(0)
    env = CartPole(seed=1)
    qnet, tnet = QNet(), QNet()
    for net in (qnet, tnet):
        net.initialize(init=mx.initializer.Xavier())
        net.hybridize()
        net(nd.zeros((1, 4)))
    copy_params(qnet, tnet)
    trainer = gluon.Trainer(qnet.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.HuberLoss()

    N, B = args.buffer, args.batch_size
    buf_s = np.zeros((N, 4), np.float32)
    buf_a = np.zeros((N,), np.int32)
    buf_r = np.zeros((N,), np.float32)
    buf_s2 = np.zeros((N, 4), np.float32)
    buf_d = np.zeros((N,), np.float32)
    size, head, steps = 0, 0, 0

    rng = np.random.default_rng(0)
    rewards = []
    for ep in range(args.episodes):
        s = env.reset()
        total = 0.0
        eps = max(0.05, 1.0 - ep / (0.6 * args.episodes))
        while True:
            if rng.random() < eps:
                a = int(rng.integers(2))
            else:
                q = qnet(nd.array(s[None])).asnumpy()
                a = int(q.argmax())
            s2, r, done = env.step(a)
            buf_s[head], buf_a[head] = s, a
            buf_r[head], buf_s2[head] = r, s2
            buf_d[head] = float(done and env.t < env.MAX_STEPS)
            head = (head + 1) % N
            size = min(size + 1, N)
            s = s2
            total += r
            steps += 1

            if size >= B:
                idx = rng.integers(0, size, size=B)
                st = nd.array(buf_s[idx])
                a_t = buf_a[idx]
                # TD target from the frozen network
                q2 = tnet(nd.array(buf_s2[idx])).asnumpy().max(axis=1)
                tgt = buf_r[idx] + args.gamma * q2 * (1.0 - buf_d[idx])
                with ag.record():
                    qall = qnet(st)
                    onehot = nd.array(
                        np.eye(2, dtype=np.float32)[a_t])
                    qsel = (qall * onehot).sum(axis=1)
                    loss = loss_fn(qsel, nd.array(tgt)).mean()
                loss.backward()
                trainer.step(B)
            if steps % args.target_sync == 0:
                copy_params(qnet, tnet)
            if done:
                break
        rewards.append(total)
        if (ep + 1) % 10 == 0:
            print(f"episode {ep + 1}: reward {total:.0f} "
                  f"mean10 {np.mean(rewards[-10:]):.1f} eps {eps:.2f}")

    mean10 = float(np.mean(rewards[-10:]))
    print(f"final mean10 reward: {mean10:.1f}")
    if mean10 < args.min_mean_reward:
        print(f"FAIL: {mean10:.1f} < {args.min_mean_reward}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
