"""Benchmark: ResNet-50 inference throughput on one chip.

Mirrors the reference's benchmark_score.py protocol
(example/image-classification/benchmark_score.py: symbol bind, dry runs,
then timed forward passes). Baseline (BASELINE.md / perf.md:185-198):
ResNet-50 inference, batch 128, fp32 on V100 = 1233.15 img/s.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_IMG_S = 1233.15  # ResNet-50 bs=128 fp32 V100 (perf.md:185-198)


def main():
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import functional_call, extract_params

    batch = int(os.environ.get("BENCH_BATCH", 128))
    dtype = os.environ.get("BENCH_DTYPE", "float32")

    mx.random.seed(0)
    net = vision.resnet50_v1()
    net.initialize(init=mx.initializer.Xavier())
    import mxnet_tpu.autograd as ag
    with ag.pause():
        net(mx.nd.NDArray(jnp.ones((1, 3, 224, 224), jnp.float32)))
    if dtype != "float32":
        net.cast(dtype)
    params = extract_params(net)

    def fwd(params, x):
        out, _ = functional_call(net, params, x, training=False)
        return out

    jfwd = jax.jit(fwd)
    x = jnp.ones((batch, 3, 224, 224), jnp.dtype(dtype))

    # dry runs: compile + warm caches (reference: benchmark_score.py
    # dry_run iterations)
    for _ in range(3):
        jfwd(params, x).block_until_ready()

    iters = int(os.environ.get("BENCH_ITERS", 20))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfwd(params, x)
    out.block_until_ready()
    dt = time.perf_counter() - t0

    img_s = batch * iters / dt
    print(json.dumps({
        "metric": f"resnet50_v1_infer_bs{batch}_{dtype}",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
