"""Benchmark: ResNet-50 training + inference throughput on one chip.

Mirrors the reference's two benchmark protocols:
  - training:  example/image-classification/train_imagenet.py
               (baseline 363.69 img/s, ResNet-50 bs=128 fp32 V100,
               perf.md:243-256) — the headline metric here, since the
               north star (BASELINE.md) is a *training* number.
  - inference: example/image-classification/benchmark_score.py
               (baseline 1233.15 img/s, bs=128 fp32 V100, perf.md:185-198)
               — reported in "extra".

All model build / parameter init / deferred-shape warmup happens on the
HOST (CPU backend) so the accelerator sees no eager op storm — params are
transferred once with a single device_put, then only compiled programs
run on the chip. The training step donates param/momentum buffers.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N, ...}
"""
import json
import os
import time

import numpy as np

TRAIN_BASELINE_IMG_S = 363.69   # ResNet-50 train bs=128 fp32 V100
INFER_BASELINE_IMG_S = 1233.15  # ResNet-50 infer bs=128 fp32 V100

# Peak bf16 matmul FLOP/s per chip, by device_kind substring (public
# spec-sheet numbers); MFU is reported against the bf16 peak regardless
# of benchmark dtype so the denominator is well-defined.
_PEAK_BF16 = [
    ("v6", 918e12), ("v5p", 459e12), ("v5", 197e12), ("v4", 275e12),
    ("v3", 123e12), ("v2", 45e12),
]


def _peak_flops(device):
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak
    return None


def main():
    import jax
    # A site hook can register accelerator plugins that ignore the
    # JAX_PLATFORMS env var; sync it into the config so explicit
    # platform selection (e.g. CPU-only test runs) actually sticks.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import functional_call, extract_params
    import mxnet_tpu.autograd as ag

    batch = int(os.environ.get("BENCH_BATCH", 128))
    dtype = os.environ.get("BENCH_DTYPE", "float32")
    infer_iters = int(os.environ.get("BENCH_ITERS", 100))
    train_iters = int(os.environ.get("BENCH_TRAIN_ITERS", 50))

    dev = jax.devices()[0]
    try:
        host = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        host = dev  # no separate CPU backend; stay on the default device

    # ---- build + init + shape warmup, all on host -----------------------
    with jax.default_device(host):
        mx.random.seed(0)
        net = vision.resnet50_v1()
        net.initialize(init=mx.initializer.Xavier())
        with ag.pause():
            net(mx.nd.NDArray(jnp.ones((1, 3, 224, 224), jnp.float32)))
        if dtype != "float32":
            net.cast(dtype)
        params_host = extract_params(net)

    # single transfer to the accelerator
    params = jax.device_put(params_host, dev)

    def fwd(params, x):
        out, _ = functional_call(net, params, x, training=False)
        return out

    x = jax.device_put(
        np.random.RandomState(0).randn(batch, 3, 224, 224)
        .astype(jnp.dtype(dtype)), dev)
    y = jax.device_put(
        (np.arange(batch) % 1000).astype(np.int32), dev)

    # ---- inference ------------------------------------------------------
    jfwd = jax.jit(fwd)
    for _ in range(3):
        jfwd(params, x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(infer_iters):
        out = jfwd(params, x)
    out.block_until_ready()
    infer_img_s = batch * infer_iters / (time.perf_counter() - t0)

    # ---- training step (fwd+bwd+SGD-momentum, donated buffers) ----------
    def loss_fn(params, x, y):
        logits, aux = functional_call(net, params, x, training=True)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
        return loss, aux

    def train_step(params, mom, x, y):
        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, x, y)
        mom = jax.tree.map(lambda m, g: 0.9 * m + g, mom, grads)
        # lr kept small: the bench runs ~50 steps on random labels and the
        # final-loss finiteness assert must not trip on a divergence
        params = jax.tree.map(lambda p, m: p - 1e-3 * m.astype(p.dtype),
                              params, mom)
        for k, v in aux.items():  # BatchNorm running stats thread through
            if k in params:
                params[k] = v.astype(params[k].dtype)
        return params, mom, loss

    mom = jax.tree.map(jnp.zeros_like, params)
    jstep = jax.jit(train_step, donate_argnums=(0, 1))

    # AOT-compile once; reuse the same executable for the timed loop (the
    # jit dispatch cache does not share Lowered.compile()'s output, so
    # falling back to jstep would compile the whole step a second time).
    flops_per_step = None
    try:
        jstep = jstep.lower(params, mom, x, y).compile()
        cost = jstep.cost_analysis()
        if cost:
            c = cost[0] if isinstance(cost, (list, tuple)) else cost
            flops_per_step = float(c.get("flops", 0)) or None
    except Exception:
        pass

    for _ in range(3):
        params, mom, loss = jstep(params, mom, x, y)
    loss.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(train_iters):
        params, mom, loss = jstep(params, mom, x, y)
    loss.block_until_ready()
    dt = time.perf_counter() - t0
    train_img_s = batch * train_iters / dt
    assert np.isfinite(float(loss)), "training diverged"

    mfu = None
    peak = _peak_flops(dev)
    if flops_per_step and peak:
        mfu = round(flops_per_step * (train_iters / dt) / peak, 4)

    print(json.dumps({
        "metric": f"resnet50_v1_train_bs{batch}_{dtype}",
        "value": round(train_img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(train_img_s / TRAIN_BASELINE_IMG_S, 3),
        "extra": {
            "infer_img_s": round(infer_img_s, 2),
            "infer_vs_baseline": round(
                infer_img_s / INFER_BASELINE_IMG_S, 3),
            "mfu_vs_bf16_peak": mfu,
            "device_kind": getattr(dev, "device_kind", str(dev)),
            "final_loss": round(float(loss), 4),
        },
    }))


if __name__ == "__main__":
    main()
